"""Input pipeline: native C++ gather engine, sampler sharding semantics,
loader determinism, and native/Python-fallback equivalence.

The reference delegates data loading to torch DataLoader +
DistributedSampler (reference examples/pytorch_mnist.py:160-170); this
build's own pipeline must reproduce that sampler's contract (disjoint
shards, pad-by-wrapping, epoch reshuffle) plus the prefetch behavior."""

import numpy as np
import pytest

from bluefog_tpu import native
from bluefog_tpu.data import DataLoader, DistributedSampler, device_prefetch


def _dataset(n=97, img_shape=(4, 5), seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, *img_shape).astype(np.float32)
    y = rng.randint(0, 10, size=(n,)).astype(np.int32)
    return x, y


# ---------------------------------------------------------------- sampler


def test_sampler_shards_disjoint_and_cover():
    n, world = 103, 4
    samplers = [DistributedSampler(n, rank=r, world=world, seed=7)
                for r in range(world)]
    all_idx = np.concatenate([s.indices(epoch=3) for s in samplers])
    # pad-by-wrapping: ceil(103/4)*4 = 104 indices, 103 distinct
    assert len(all_idx) == 104
    assert len(np.unique(all_idx)) == n
    counts = [len(s.indices(epoch=3)) for s in samplers]
    assert len(set(counts)) == 1  # equal shards


def test_sampler_drop_last_and_epochs():
    n, world = 103, 4
    s = DistributedSampler(n, rank=1, world=world, drop_last=True, seed=1)
    idx = s.indices(epoch=0)
    assert len(idx) == n // world
    assert not np.array_equal(s.indices(epoch=0), s.indices(epoch=1))
    np.testing.assert_array_equal(s.indices(epoch=0), s.indices(epoch=0))


def test_sampler_no_shuffle_is_interleaved():
    s = DistributedSampler(8, rank=1, world=2, shuffle=False)
    np.testing.assert_array_equal(s.indices(), [1, 3, 5, 7])


# ---------------------------------------------------------- native engine


def test_native_available():
    # g++ is part of the toolchain contract; the engine must build here
    assert native.available()


def test_native_pipeline_gathers_exactly():
    x, y = _dataset(50)
    pipe = native.NativeBatchPipeline([x, y], batch_size=8, depth=3,
                                      workers=3)
    order = np.random.RandomState(3).permutation(50)
    n_batches = pipe.start_epoch(order)
    assert n_batches == 7  # ceil(50/8), last batch partial (2)
    got_x, got_y = [], []
    sizes = []
    while True:
        item = pipe.next()
        if item is None:
            break
        slot, (bx, by) = item
        sizes.append(len(bx))
        got_x.append(bx.copy())
        got_y.append(by.copy())
        pipe.release(slot)
    assert sizes == [8] * 6 + [2]
    np.testing.assert_array_equal(np.concatenate(got_x), x[order])
    np.testing.assert_array_equal(np.concatenate(got_y), y[order])
    pipe.close()


def test_native_pipeline_multi_epoch_and_abandon():
    x, y = _dataset(64)
    pipe = native.NativeBatchPipeline([x, y], batch_size=16, depth=2,
                                      workers=2)
    # abandon an epoch mid-way, then run two clean epochs
    pipe.start_epoch(np.arange(64))
    item = pipe.next()
    assert item is not None
    pipe.release(item[0])
    for seed in (1, 2):
        order = np.random.RandomState(seed).permutation(64)
        pipe.start_epoch(order)
        outs = []
        while (item := pipe.next()) is not None:
            slot, (bx, _) = item
            outs.append(bx.copy())
            pipe.release(slot)
        np.testing.assert_array_equal(np.concatenate(outs), x[order])
    pipe.close()


def test_python_pipeline_shutdown_leak_warns_and_close_idempotent():
    """A producer thread that survives cancel + drain + join is a leak:
    close() must say so (naming the thread) instead of silently
    ignoring it, and a second close() is a no-op — no double shutdown,
    no duplicate warning (the __del__-after-close path)."""
    import logging
    import threading
    import time

    from bluefog_tpu.data import _PythonPipeline
    from bluefog_tpu.logging_util import get_logger

    class Capture(logging.Handler):
        def __init__(self):
            super().__init__(level=logging.WARNING)
            self.messages = []

        def emit(self, record):
            self.messages.append(record.getMessage())

    handler = Capture()
    logger = get_logger()
    logger.addHandler(handler)
    try:
        x, y = _dataset(32)
        pipe = _PythonPipeline([x, y], batch_size=8, depth=2)
        # simulate a producer wedged outside the queue protocol (e.g. a
        # transform stuck on a dead filesystem): a thread that ignores
        # the cancel event entirely
        stuck = threading.Thread(target=time.sleep, args=(30,),
                                 daemon=True, name="bf-data-producer")
        stuck.start()
        pipe._thread = stuck
        pipe._join_timeout = 0.05
        pipe.close()
        leaks = [m for m in handler.messages
                 if "still alive" in m and "bf-data-producer" in m]
        assert len(leaks) == 1, handler.messages
        pipe.close()  # idempotent: no second warning, no error
        assert len([m for m in handler.messages
                    if "still alive" in m]) == 1
    finally:
        logger.removeHandler(handler)


def test_python_pipeline_clean_shutdown_does_not_warn():
    import logging

    from bluefog_tpu.data import _PythonPipeline
    from bluefog_tpu.logging_util import get_logger

    class Capture(logging.Handler):
        def __init__(self):
            super().__init__(level=logging.WARNING)
            self.messages = []

        def emit(self, record):
            self.messages.append(record.getMessage())

    handler = Capture()
    logger = get_logger()
    logger.addHandler(handler)
    try:
        x, y = _dataset(32)
        pipe = _PythonPipeline([x, y], batch_size=8, depth=2)
        pipe.start_epoch(np.arange(32))  # abandon mid-epoch: the
        pipe.close()                     # cancel protocol must suffice
        # reuse after close re-arms the latch: the SECOND close must
        # still drain the fresh producer (not be a latched no-op)
        pipe.start_epoch(np.arange(32))
        thread = pipe._thread
        pipe.close()
        assert thread is not None and not thread.is_alive()
        assert not any("still alive" in m for m in handler.messages)
    finally:
        logger.removeHandler(handler)


# ------------------------------------------------------------- DataLoader


@pytest.mark.parametrize("use_native", [True, False])
def test_loader_epoch_content(use_native):
    x, y = _dataset(60)
    loader = DataLoader([x, y], batch_size=16, seed=5, rank=0, world=1,
                        use_native=use_native)
    batches = list(loader)
    assert [len(b[0]) for b in batches] == [16, 16, 16, 12]
    order = loader.sampler.indices(epoch=0)
    np.testing.assert_array_equal(
        np.concatenate([b[0] for b in batches]), x[order])
    np.testing.assert_array_equal(
        np.concatenate([b[1] for b in batches]), y[order])
    loader.close()


def test_loader_native_matches_python_fallback():
    x, y = _dataset(41)
    a = DataLoader([x, y], batch_size=8, seed=2, world=1, use_native=True)
    b = DataLoader([x, y], batch_size=8, seed=2, world=1, use_native=False)
    for (ax, ay), (bx, by) in zip(a, b, strict=True):
        np.testing.assert_array_equal(ax, bx)
        np.testing.assert_array_equal(ay, by)
    a.close()
    b.close()


def test_loader_reshuffles_across_epochs():
    x, y = _dataset(32)
    loader = DataLoader([x, y], batch_size=32, seed=0, world=1)
    first = next(iter(loader))[0]
    second = next(iter(loader))[0]
    assert not np.array_equal(first, second)
    np.testing.assert_array_equal(np.sort(first, axis=0),
                                  np.sort(second, axis=0))
    loader.close()


def test_loader_drop_last():
    x, y = _dataset(60)
    loader = DataLoader([x, y], batch_size=16, drop_last=True, world=1)
    assert len(loader) == 3
    assert [len(b[0]) for b in loader] == [16, 16, 16]
    loader.close()


def test_loader_sharded_ranks_disjoint():
    x, y = _dataset(64)
    seen = []
    for r in range(4):
        loader = DataLoader([x, y], batch_size=8, rank=r, world=4, seed=9)
        seen.append(np.concatenate([b[1] for b in loader]))
        loader.close()
    # every label observed exactly as often as it appears in the data
    all_labels = np.sort(np.concatenate(seen))
    np.testing.assert_array_equal(all_labels, np.sort(y))


def test_loader_rank_major_layout():
    x, y = _dataset(64, img_shape=(3,))
    world = 4
    loader = DataLoader([x, y], batch_size=16, world=world, rank_major=True,
                        seed=4)
    batches = list(loader)
    for bx, by in batches:
        assert bx.shape == (world, 4, 3)
        assert by.shape == (world, 4)
    # flattening recovers the global stream
    flat = np.concatenate([b[0].reshape(-1, 3) for b in batches])
    order = loader.sampler.indices(epoch=0)
    np.testing.assert_array_equal(flat, x[order])
    loader.close()


def test_loader_transform_hook():
    x, y = _dataset(20, img_shape=(2,))
    loader = DataLoader([x, y], batch_size=10, shuffle=False, world=1,
                        transform=lambda bx, by: (bx * 2.0, by))
    bx, by = next(iter(loader))
    np.testing.assert_allclose(bx, x[loader.sampler.indices(0)][:10] * 2.0)
    loader.close()


def test_device_prefetch_roundtrip():
    x, y = _dataset(24, img_shape=(2,))
    loader = DataLoader([x, y], batch_size=8, shuffle=False, world=1)
    out = list(device_prefetch(loader, depth=2))
    assert len(out) == 3
    np.testing.assert_array_equal(np.asarray(out[0][0]), x[:8])
    loader.close()


def test_loader_stress_random_shapes():
    rng = np.random.RandomState(0)
    for _ in range(5):
        n = int(rng.randint(5, 200))
        bs = int(rng.randint(1, 32))
        x = rng.randn(n, 7).astype(np.float32)
        loader = DataLoader([x], batch_size=bs, seed=int(rng.randint(99)),
                            world=1, num_workers=4, prefetch_depth=2)
        for epoch in range(2):
            got = np.concatenate([b[0] for b in loader])
            np.testing.assert_array_equal(
                got, x[loader.sampler.indices(epoch)])
        loader.close()


def test_loader_rank_major_partial_tail_padded():
    """rank_major + not drop_last: the trailing partial batch is padded by
    wrapping into equal per-rank rows — never an empty (world, 0, ...) or
    silently dropped samples (review finding)."""
    x = np.arange(10, dtype=np.float32).reshape(10, 1)
    y = np.arange(10, dtype=np.int32)
    loader = DataLoader([x, y], batch_size=8, world=4, rank_major=True,
                        seed=0, shuffle=False)
    batches = list(loader)
    assert [b[0].shape for b in batches] == [(4, 2, 1), (4, 1, 1)]
    delivered = np.concatenate([b[1].reshape(-1) for b in batches])
    assert set(delivered) == set(range(10))  # every sample delivered
    loader.close()


def test_sampler_pad_exceeding_dataset_tiles():
    """Padding larger than the dataset tiles it (review finding): every
    rank gets exactly num_samples indices even when world >> n_items."""
    samplers = [DistributedSampler(2, rank=r, world=8) for r in range(8)]
    for s in samplers:
        assert len(s.indices(epoch=0)) == s.num_samples == 1


def test_loader_world_defaults_to_bluefog_size(bf_ctx):
    import bluefog_tpu as bf

    x = np.zeros((64, 2), np.float32)
    loader = DataLoader([x], batch_size=16, rank_major=True)
    assert loader.world == bf.size()
    bx, = next(iter(loader))
    assert bx.shape == (bf.size(), 16 // bf.size(), 2)
    loader.close()


def test_loader_state_dict_mid_epoch_resume():
    """state_dict mid-epoch + load_state_dict on a fresh loader resumes the
    exact batch stream (review finding: epoch-granular state silently
    dropped the in-progress epoch's remainder)."""
    x = np.arange(40, dtype=np.float32).reshape(40, 1)
    ref = DataLoader([x], batch_size=8, seed=3, world=1)
    it = iter(ref)
    consumed = [next(it), next(it)]  # 2 of 5 batches of epoch 0
    state = ref.state_dict()
    assert state == {"epoch": 0, "batch": 2}
    rest_ref = list(it) + list(ref)  # remainder of epoch 0 + all of epoch 1

    fresh = DataLoader([x], batch_size=8, seed=3, world=1)
    fresh.load_state_dict(state)
    rest = list(fresh) + list(fresh)
    assert len(rest) == len(rest_ref)
    for (a,), (b,) in zip(rest, rest_ref):
        np.testing.assert_array_equal(a, b)
    ref.close()
    fresh.close()


def test_loader_state_dict_epoch_boundary():
    x = np.zeros((16, 1), np.float32)
    loader = DataLoader([x], batch_size=8, world=1)
    assert loader.state_dict() == {"epoch": 0, "batch": 0}
    list(loader)
    assert loader.state_dict() == {"epoch": 1, "batch": 0}
    loader.close()


def test_loader_state_dict_roundtrips_after_restore():
    """Saving right after load_state_dict (before any batch) must not
    rewind the position (review finding)."""
    x = np.zeros((40, 1), np.float32)
    loader = DataLoader([x], batch_size=8, seed=3, world=1)
    loader.load_state_dict({"epoch": 1, "batch": 3})
    assert loader.state_dict() == {"epoch": 1, "batch": 3}
    loader.close()


def test_rank_major_rejects_nonzero_rank():
    """rank_major loading serves every rank from one loader; a nonzero
    rank would silently duplicate shards (moved here from the deleted
    fused-combine test file, where it was misfiled)."""
    from bluefog_tpu.data import DataLoader

    x = np.zeros((16, 2), np.float32)
    with pytest.raises(ValueError, match="rank_major"):
        DataLoader([x], batch_size=8, world=4, rank=1, rank_major=True)


# ------------------------------------------------- on-disk dataset loaders


def _write_idx(path, arr):
    """Write a uint8 IDX file (the MNIST wire format), gzipped iff the
    path ends in .gz — the fixture IS the format the loader claims to
    read, so the day a real download exists it loads unchanged."""
    import gzip
    import struct

    arr = np.asarray(arr, np.uint8)
    header = struct.pack(">HBB", 0, 0x08, arr.ndim) + struct.pack(
        ">" + "I" * arr.ndim, *arr.shape)
    opener = gzip.open if str(path).endswith(".gz") else open
    with opener(str(path), "wb") as fh:
        fh.write(header + arr.tobytes())


def _mnist_fixture(root, n_train=32, n_test=8, gz=True):
    rng = np.random.RandomState(0)
    ext = ".gz" if gz else ""
    sets = {}
    for prefix, n in (("train", n_train), ("t10k", n_test)):
        imgs = rng.randint(0, 256, (n, 28, 28), np.uint8)
        labels = rng.randint(0, 10, (n,), np.uint8)
        _write_idx(root / f"{prefix}-images-idx3-ubyte{ext}", imgs)
        _write_idx(root / f"{prefix}-labels-idx1-ubyte{ext}", labels)
        sets[prefix] = (imgs, labels)
    return sets


def test_load_mnist_idx_roundtrip(tmp_path):
    from bluefog_tpu.data import load_mnist

    sets = _mnist_fixture(tmp_path, gz=True)
    for split, prefix in (("train", "train"), ("test", "t10k")):
        imgs, labels = load_mnist(str(tmp_path), split=split)
        raw_imgs, raw_labels = sets[prefix]
        assert imgs.shape == raw_imgs.shape + (1,)
        assert imgs.dtype == np.float32 and labels.dtype == np.int32
        assert imgs.min() >= 0.0 and imgs.max() <= 1.0
        np.testing.assert_allclose(imgs[..., 0] * 255.0, raw_imgs,
                                   atol=1e-4)
        np.testing.assert_array_equal(labels, raw_labels)


def test_load_mnist_raw_and_torchvision_layout(tmp_path):
    """Uncompressed files under the torchvision MNIST/raw subtree load
    identically (reference examples consume exactly this layout)."""
    from bluefog_tpu.data import load_mnist

    sub = tmp_path / "MNIST" / "raw"
    sub.mkdir(parents=True)
    sets = _mnist_fixture(sub, gz=False)
    imgs, labels = load_mnist(str(tmp_path), split="train")
    np.testing.assert_array_equal(labels, sets["train"][1])
    assert imgs.shape == (32, 28, 28, 1)


def test_load_mnist_missing_raises(tmp_path):
    from bluefog_tpu.data import load_mnist

    with pytest.raises(FileNotFoundError):
        load_mnist(str(tmp_path))
    with pytest.raises(ValueError):
        load_mnist(str(tmp_path), split="validation")


def test_load_cifar10_pickle_batches(tmp_path):
    import pickle

    from bluefog_tpu.data import load_cifar10

    rng = np.random.RandomState(1)
    root = tmp_path / "cifar-10-batches-py"
    root.mkdir()
    all_imgs, all_labels = [], []
    for i in range(1, 6):
        data = rng.randint(0, 256, (20, 3072), np.uint8)
        labels = rng.randint(0, 10, (20,)).tolist()
        with open(root / f"data_batch_{i}", "wb") as fh:
            pickle.dump({b"data": data, b"labels": labels}, fh)
        all_imgs.append(data)
        all_labels.extend(labels)
    test_data = rng.randint(0, 256, (10, 3072), np.uint8)
    with open(root / "test_batch", "wb") as fh:
        pickle.dump({b"data": test_data,
                     b"labels": list(range(10))}, fh)

    imgs, labels = load_cifar10(str(tmp_path), split="train")
    assert imgs.shape == (100, 32, 32, 3)
    assert imgs.dtype == np.float32
    np.testing.assert_array_equal(labels, np.asarray(all_labels))
    # channel-major rows [3, 32, 32] become HWC: red plane first
    raw0 = np.concatenate(all_imgs)[0].reshape(3, 32, 32)
    np.testing.assert_allclose(imgs[0, ..., 0] * 255.0, raw0[0], atol=1e-4)
    np.testing.assert_allclose(imgs[0, ..., 2] * 255.0, raw0[2], atol=1e-4)

    timgs, tlabels = load_cifar10(str(tmp_path), split="test")
    assert timgs.shape == (10, 32, 32, 3)
    np.testing.assert_array_equal(tlabels, np.arange(10))


def test_loaded_dataset_feeds_dataloader(tmp_path):
    """End-to-end: the on-disk loader's output drops straight into the
    rank-major DataLoader the examples/benchmarks iterate."""
    from bluefog_tpu.data import load_mnist

    _mnist_fixture(tmp_path, n_train=64)
    imgs, labels = load_mnist(str(tmp_path), split="train")
    loader = DataLoader((imgs, labels), batch_size=16, world=8,
                        rank_major=True, use_native=False)
    batch = next(iter(loader))
    assert batch[0].shape == (8, 2, 28, 28, 1)
    assert batch[1].shape == (8, 2)
    loader.close()
