"""Elastic membership (bluefog_tpu/elastic/): ranks that join, not
just die.

The acceptance properties of the elastic subsystem:

(a) growth is the EXACT inverse of healing: ``heal_weights`` ->
    ``grow_weights`` round-trips byte-equal to the pristine tables
    (and any partial growth equals a fresh heal of the remaining dead
    set, bitwise), row-stochastic at every intermediate step — a
    property test over random weighted schedules in rank and torus
    spaces (the PR-7 style);
(b) a joiner bootstraps by pulled neighbor averaging ONLY (self-weight
    annealed 0 -> pristine, live receivers keep zero weight on it), so
    a preempted rank re-enters the n=32 consensus floor (<= 1e-12)
    without a broadcast;
(c) the MembershipController's lifecycle (LIVE -> DEAD -> JOINING ->
    LIVE) renders as pure weight DATA in the unchanged comm-weight
    shapes, the FailureDetector readmits without latched suspicion,
    and the FleetAggregator heals AND re-grows from the controller;
(d) the full preempt -> heal -> rollback -> admit -> anneal -> promote
    cycle runs through ``run_resilient(elastic=...)`` with ZERO
    recompiles (asserted via the jitted cache size, the PR-3
    methodology).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh

from bluefog_tpu import resilience as R
from bluefog_tpu.checkpoint import Checkpointer
from bluefog_tpu.elastic import (
    DEAD,
    JOINING,
    LIVE,
    ElasticConfig,
    MembershipController,
    anneal_fraction,
    bootstrap_comm_weights,
    bootstrap_weights,
    disagreement,
    grow_spec,
    grow_weights,
    grown_comm_weights,
    sanitize_rank_rows,
    zero_rank_rows,
)
from bluefog_tpu.observe.fleet import FleetAggregator
from bluefog_tpu.optim import functional as F
from bluefog_tpu.topology import one_peer_dynamic_schedule
from bluefog_tpu.topology.compiler import (Candidate, CandidateRound,
                                           materialize)
from bluefog_tpu.topology.spec import self_weights_of

pytestmark = pytest.mark.elastic

N = 8


# ------------------------------------------------------------------ #
# acceptance (a): heal -> grow round-trips byte-equal (property test)
# ------------------------------------------------------------------ #
def test_heal_grow_round_trip_byte_equal_property():
    """Property: on random weighted circulant schedules (rank-space
    n=16 and (4, 4) torus-space, random shifts/self-weights), healing
    a random dead set and growing ANY subset back is byte-equal to a
    fresh heal of the remaining dead set — and growing everyone back
    is byte-equal to the pristine tables — with every intermediate
    mixing matrix row-stochastic.  Growth re-plans from the pristine
    spec instead of subtracting, which is the only way ``(a + w) - w``
    rounding residue never appears."""
    rng = np.random.default_rng(11)
    cases = []
    for _ in range(10):  # rank space, n = 16
        period = int(rng.integers(2, 5))
        rounds = tuple(
            CandidateRound(((None, int(rng.integers(1, 16))),),
                           float(rng.uniform(0.05, 0.9)))
            for _ in range(period))
        cases.append((Candidate("rnd", "rank", rounds), (2, 8)))
    for _ in range(6):  # torus space, (4, 4)
        period = int(rng.integers(2, 5))
        rounds = tuple(
            CandidateRound(((int(rng.integers(0, 2)),
                             int(rng.integers(1, 4))),),
                           float(rng.uniform(0.05, 0.9)))
            for _ in range(period))
        cases.append((Candidate("rnd", "torus", rounds), (4, 4)))
    checked = 0
    for cand, axes in cases:
        for spec in materialize(cand, axes):
            n = spec.size
            cw0, sw0 = R.heal_weights(spec, np.zeros(n, bool))
            # the no-dead heal IS the pristine plan
            np.testing.assert_array_equal(
                sw0, np.asarray(self_weights_of(spec), np.float64))
            n_dead = int(rng.integers(1, 4))
            dead_ranks = rng.choice(n, size=n_dead, replace=False)
            dead = np.zeros(n, bool)
            dead[dead_ranks] = True
            cwh, swh = R.heal_weights(spec, dead)
            M = R.mixing_matrix_from_weights(spec, cwh, swh)
            np.testing.assert_allclose(M.sum(axis=1), 1.0, atol=1e-12)
            # partial growth == fresh heal of the survivors' dead set
            k = int(rng.integers(1, n_dead + 1))
            back = [int(r) for r in
                    rng.choice(dead_ranks, size=k, replace=False)]
            gcw, gsw = grow_weights(spec, dead, back)
            rem = dead.copy()
            rem[back] = False
            fcw, fsw = R.heal_weights(spec, rem)
            assert gcw.tobytes() == fcw.tobytes()
            assert gsw.tobytes() == fsw.tobytes()
            Mg = R.mixing_matrix_from_weights(spec, gcw, gsw)
            np.testing.assert_allclose(Mg.sum(axis=1), 1.0, atol=1e-12)
            # full round trip: everyone back == pristine, bitwise
            acw, asw = grow_weights(spec, dead,
                                    [int(r) for r in dead_ranks])
            assert acw.tobytes() == cw0.tobytes()
            assert asw.tobytes() == sw0.tobytes()
            checked += 1
    assert checked >= 30  # the property was actually exercised


def test_grow_weights_validation():
    sched = one_peer_dynamic_schedule(N)
    dead = np.zeros(N, bool)
    dead[2] = True
    with pytest.raises(ValueError, match="dead mask"):
        grow_weights(sched[0], np.zeros(3, bool), [0])
    with pytest.raises(ValueError, match="outside topology"):
        grow_weights(sched[0], dead, [N])
    with pytest.raises(ValueError, match="not dead"):
        grow_weights(sched[0], dead, [3])
    with pytest.raises(ValueError, match="not dead"):
        grow_spec(sched[0], dead, 3)


def test_grown_comm_weights_keeps_traced_shapes():
    """Growth is deliverable to the compiled program: the re-grown
    weight DATA has exactly the unchanged ``comm_weight_inputs``
    structure (same shapes/dtypes), and growing everyone back equals
    the program's own default weights."""
    sched = one_peer_dynamic_schedule(N)
    dead = np.zeros(N, bool)
    dead[[1, 4]] = True
    base = F.comm_weight_inputs(sched)
    grown = grown_comm_weights(sched, dead, [1])
    assert len(grown) == len(base)
    for (cw0, sw0), (cw1, sw1) in zip(base, grown):
        assert cw0.shape == cw1.shape and sw0.shape == sw1.shape
        assert cw0.dtype == cw1.dtype and sw0.dtype == sw1.dtype
    full = grown_comm_weights(sched, dead, [1, 4])
    for (cw0, sw0), (cw1, sw1) in zip(base, full):
        np.testing.assert_array_equal(np.asarray(cw0), np.asarray(cw1))
        np.testing.assert_array_equal(np.asarray(sw0), np.asarray(sw1))
    g = grow_spec(sched[0], dead, [1, 4])
    assert R.is_row_stochastic(g)


# ------------------------------------------------------------------ #
# acceptance (b): the bootstrap pull
# ------------------------------------------------------------------ #
def test_anneal_fraction():
    assert anneal_fraction(0, 8) == 0.0
    assert anneal_fraction(4, 8) == 0.5
    assert anneal_fraction(8, 8) == 1.0
    assert anneal_fraction(100, 8) == 1.0  # clamped
    with pytest.raises(ValueError, match="rounds"):
        anneal_fraction(0, 0)
    with pytest.raises(ValueError, match="progress"):
        anneal_fraction(-1, 8)


def test_bootstrap_weights_anneal_semantics():
    """At fraction 0 the joiner's row is a pure pull (self-weight 0);
    at fraction 1 with live in-neighbors it is the pristine row
    EXACTLY; a round with no live in-neighbor freezes the joiner; live
    receivers keep zero weight on the joiner throughout; every row
    stays row-stochastic."""
    sched = one_peer_dynamic_schedule(N)
    j = 2
    live = np.ones(N, bool)
    live[j] = False
    for spec in sched:
        cw0, sw0 = R.heal_weights(spec, np.zeros(N, bool))
        src = [(j - cls.shift) % N for cls in spec.shift_classes
               if cls.recv_weights[j] != 0.0]
        # fraction 0: pure pull
        cw, sw = bootstrap_weights(spec, live, {j: 0.0})
        assert sw[j] == 0.0
        M = R.mixing_matrix_from_weights(spec, cw, sw)
        np.testing.assert_allclose(M.sum(axis=1), 1.0, atol=1e-12)
        assert abs(M[j, src].sum() - 1.0) < 1e-12
        # quarantine: no live receiver reads the joiner
        for i in range(N):
            if i != j:
                assert M[i, j] == 0.0
        # fraction 1, every in-neighbor live: the pristine row, exactly
        cw, sw = bootstrap_weights(spec, live, {j: 1.0})
        assert sw[j] == sw0[j]
        np.testing.assert_array_equal(cw[:, j], cw0[:, j])
        # no live in-neighbor this round: freeze (self-weight 1.0)
        live2 = live.copy()
        for s in src:
            live2[s] = False
        cw, sw = bootstrap_weights(spec, live2, {j: 0.5})
        assert sw[j] == 1.0 and (cw[:, j] == 0.0).all()
    # empty anneal IS the plain heal — the controller's single render
    dead = ~live
    for spec in sched:
        bcw, bsw = bootstrap_weights(spec, live, {})
        hcw, hsw = R.heal_weights(spec, dead)
        assert bcw.tobytes() == hcw.tobytes()
        assert bsw.tobytes() == hsw.tobytes()
    # jnp wrapper keeps the traced shapes
    base = F.comm_weight_inputs(sched)
    boot = bootstrap_comm_weights(sched, live, {j: 0.25})
    for (cw0_, sw0_), (cw1, sw1) in zip(base, boot):
        assert cw0_.shape == cw1.shape and sw0_.shape == sw1.shape


def test_bootstrap_weights_validation():
    spec = one_peer_dynamic_schedule(N)[0]
    live = np.ones(N, bool)
    live[2] = False
    with pytest.raises(ValueError, match="live mask"):
        bootstrap_weights(spec, np.ones(3, bool), {})
    with pytest.raises(ValueError, match="is live"):
        bootstrap_weights(spec, live, {0: 0.5})
    with pytest.raises(ValueError, match="outside topology"):
        bootstrap_weights(spec, live, {N: 0.5})
    with pytest.raises(ValueError, match="anneal fraction"):
        bootstrap_weights(spec, live, {2: 1.5})


def test_disagreement_metric():
    """The promotion gate is NORMALIZED: the joiner's L2 distance from
    the live mean in units of the live ranks' own max deviation —
    decentralized replicas intentionally differ by the consensus
    distance, so <= 1.0 means "inside the live consensus cloud"."""
    # live ranks at +1/-1 around mean 0 (max deviation exactly 1):
    # a joiner at 0.5 scores 0.5, a joiner at 3 scores ~3
    arr = np.array([[1.0], [-1.0], [0.5]])
    live = np.array([True, True, False])
    assert abs(disagreement({"w": arr}, 2, live) - 0.5) < 1e-6
    arr2 = arr.copy()
    arr2[2] = 3.0
    assert disagreement({"w": arr2}, 2, live) > 2.5
    # non-finite joiner state: infinite disagreement, never promoted
    arr3 = arr.copy()
    arr3[2] = np.nan
    assert disagreement({"w": arr3}, 2, live) == float("inf")
    with pytest.raises(ValueError, match="no live ranks"):
        disagreement({"w": arr}, 2, np.zeros(3, bool))
    with pytest.raises(ValueError, match="rank-major"):
        disagreement({"w": np.zeros((5, 2))}, 0, live)
    with pytest.raises(ValueError, match="inexact"):
        disagreement({"w": np.zeros((3, 2), np.int32)}, 0, live)


def test_sanitize_rank_rows():
    tree = {"a": np.arange(8.0).reshape(4, 2), "b": np.arange(4)}
    tree["a"][1, 0] = np.nan
    tree["a"][2, 1] = np.inf
    mask = np.array([False, True, False, False])
    out = sanitize_rank_rows(tree, mask)
    assert out["a"][1, 0] == 0.0 and out["a"][1, 1] == 3.0
    assert np.isinf(out["a"][2, 1])        # unmasked rows untouched
    assert out["b"] is tree["b"]           # int leaves pass through
    # finite masked rows: identity, no copy
    clean = {"a": np.ones((4, 2))}
    assert sanitize_rank_rows(clean, mask)["a"] is clean["a"]
    assert sanitize_rank_rows(tree, np.zeros(4, bool)) is tree
    with pytest.raises(ValueError, match="rank-major"):
        sanitize_rank_rows({"a": np.full((3, 2), np.nan)}, mask)


def test_zero_rank_rows():
    """Admission hygiene for optimizer state: the masked ranks' rows
    are zeroed (stale-but-finite moments must not ride through the
    params-only promotion gate), everything else is untouched, and
    already-zero rows / empty masks are identity."""
    tree = {"m": np.arange(1.0, 9.0).reshape(4, 2), "c": np.arange(4)}
    mask = np.array([False, True, False, False])
    out = zero_rank_rows(tree, mask)
    assert (out["m"][1] == 0.0).all()
    np.testing.assert_array_equal(out["m"][[0, 2, 3]],
                                  tree["m"][[0, 2, 3]])
    assert out["c"] is tree["c"]  # int passthrough
    assert zero_rank_rows(tree, np.zeros(4, bool)) is tree
    zeroed = {"m": np.zeros((4, 2))}
    assert zero_rank_rows(zeroed, mask)["m"] is zeroed["m"]
    with pytest.raises(ValueError, match="rank-major"):
        zero_rank_rows({"m": np.ones((3, 2))}, mask)


# ------------------------------------------------------------------ #
# acceptance (c): controller lifecycle + detector readmission
# ------------------------------------------------------------------ #
def test_membership_controller_lifecycle():
    det = R.FailureDetector(N)
    mc = MembershipController(one_peer_dynamic_schedule(N),
                              bootstrap_rounds=4, detector=det)
    assert mc.states() == [LIVE] * N
    assert not mc.effective_dead_mask().any()
    mc.mark_dead(3)
    assert mc.state(3) == DEAD and det.dead_mask()[3]
    assert mc.dead_ranks() == [3] and mc.live_ranks() == [
        r for r in range(N) if r != 3]
    # streak keeps counting while dead (observe has no special-case)
    for _ in range(5):
        det.observe(np.eye(N, dtype=bool)[3])
    mc.admit(3)
    assert mc.state(3) == JOINING and mc.joining_ranks() == [3]
    # still excised from receivers AND still dead to the detector:
    # bootstrap-window skips must not trigger fleet rollbacks
    assert mc.effective_dead_mask()[3] and det.dead_mask()[3]
    assert not mc.live_mask()[3]
    mc.tick()
    mc.tick()
    assert mc.progress(3) == 2 and mc.anneal() == {3: 0.5}
    assert mc.counts() == {LIVE: 7, DEAD: 0, JOINING: 1}
    mc.promote(3)
    assert mc.states() == [LIVE] * N
    # readmitted: dead flag AND latched streak cleared
    assert not det.dead_mask()[3]
    assert det.consecutive_bad()[3] == 0
    assert mc.progress(3) == 0
    assert "live=8" in repr(mc)


def test_membership_controller_transition_validation():
    mc = MembershipController(one_peer_dynamic_schedule(N),
                              bootstrap_rounds=4)
    with pytest.raises(ValueError, match="not dead"):
        mc.admit(0)
    with pytest.raises(ValueError, match="not joining"):
        mc.promote(0)
    with pytest.raises(ValueError, match="not joining"):
        mc.kick(0)
    with pytest.raises(ValueError, match="outside world"):
        mc.state(N)
    mc.mark_dead([2, 5])
    mc.admit(2)
    mc.kick(2)  # bootstrap failed: back to DEAD
    assert mc.state(2) == DEAD
    mc.seed_dead(np.eye(N, dtype=bool)[7])
    assert mc.state(7) == DEAD and mc.state(5) == DEAD
    with pytest.raises(ValueError, match="dead mask"):
        mc.seed_dead(np.zeros(3, bool))
    with pytest.raises(ValueError, match="non-empty"):
        MembershipController([])
    with pytest.raises(ValueError, match="bootstrap_rounds"):
        MembershipController(one_peer_dynamic_schedule(N),
                             bootstrap_rounds=0)


def test_detector_readmit():
    det = R.FailureDetector(4)
    for _ in range(3):
        det.observe([0, 1, 0, 0])
    det.suspect([1], source="straggler")
    det.declare_dead([1])
    with pytest.raises(ValueError, match="nothing to readmit"):
        det.readmit([0])
    det.readmit([1])
    assert not det.dead_mask()[1]
    assert det.consecutive_bad()[1] == 0     # streak cleared
    assert det.total_skips()[1] == 3          # history kept
    assert det.external_suspects() == []      # suspicion dropped
    assert det.suspects(1) == []              # nothing re-excises it


def test_controller_weights_cache_and_matrices():
    """Steady (no-joiner) weight tables are cached per membership
    pattern — bounded, so churn never grows host memory — and the
    per-round mixing matrices quarantine the joiner correctly."""
    sched = one_peer_dynamic_schedule(N)
    mc = MembershipController(sched, bootstrap_rounds=4)
    out1 = mc.comm_weight_arrays()
    out2 = mc.comm_weight_arrays()
    assert out1[0][0] is out2[0][0]  # cache hit: same arrays
    # cached tables are frozen: a caller mutating a returned array
    # must get a loud error, not silently corrupt later renders
    assert not out1[0][0].flags.writeable
    assert not out1[0][1].flags.writeable
    with pytest.raises(ValueError, match="read-only"):
        out1[0][0][0, 0] = 7.0
    mc.mark_dead(5)
    out3 = mc.comm_weight_arrays()
    assert out3[0][0] is not out1[0][0]
    mc.admit(5)
    mc.tick()
    mc.tick()  # anneal fraction 0.5
    for spec, M in zip(sched, mc.mixing_matrices()):
        np.testing.assert_allclose(M.sum(axis=1), 1.0, atol=1e-12)
        for i in range(N):
            if i != 5:
                assert M[i, 5] == 0.0  # quarantined: nobody reads it
        src = [(5 - cls.shift) % N for cls in spec.shift_classes
               if cls.recv_weights[5] != 0.0]
        if src:  # the joiner's own row pulls from its live neighbors
            assert M[5, src].sum() > 0.0
    # bounded steady cache: one entry per distinct pattern, LRU-capped
    mc2 = MembershipController(sched, bootstrap_rounds=4)
    for r in range(N):
        mc2.mark_dead(r)
        mc2.comm_weight_arrays()
        mc2.mark_dead((r + 1) % N)
        mc2.comm_weight_arrays()
        mc2._code[:] = 0  # reset pattern for the next pair
    assert len(mc2._steady) <= 16
    # the traced render matches comm_weight_inputs structurally
    base = F.comm_weight_inputs(sched)
    cur = mc.comm_weights()
    for (cw0, sw0), (cw1, sw1) in zip(base, cur):
        assert cw0.shape == cw1.shape and sw0.shape == sw1.shape


def test_bootstrap_consensus_recovery_n32():
    """Acceptance (b), simulation half: at n=32, kill ranks {3, 17},
    heal, converge the survivors, then admit both back through the
    annealed bootstrap — the joiners re-enter the consensus cloud (the
    normalized disagreement clears 1.0), growth restores the pristine
    tables byte-equal, and the FULL 32-rank fleet re-converges to a
    <= 1e-12 floor.  Pure numpy: the controller's mixing_matrices()
    drive the same seeded simulation the chaos bench uses."""
    n = 32
    sched = one_peer_dynamic_schedule(n)
    mc = MembershipController(sched, bootstrap_rounds=8)
    rng = np.random.default_rng(5)
    x = rng.standard_normal((n, 16))
    d0 = float(np.linalg.norm(x - x.mean(axis=0)))
    t = 0

    def mix(rounds, tick=False):
        nonlocal x, t
        for _ in range(rounds):
            M = mc.mixing_matrices()[t % len(sched)]
            x = M @ x
            t += 1
            if tick:
                mc.tick()

    def floor(mask):
        sub = x[mask]
        return float(np.linalg.norm(sub - sub.mean(axis=0))) / d0

    live = np.ones(n, bool)
    mix(120)
    assert floor(live) < 1e-12
    # preempt: two ranks die, survivors re-converge among themselves
    mc.mark_dead([3, 17])
    x[[3, 17]] += rng.standard_normal((2, 16))  # stale + drifted state
    live[[3, 17]] = False
    mix(120)
    assert floor(live) < 1e-12
    # rejoin: quarantined annealed bootstrap, then the promotion gate
    mc.admit([3, 17])
    mix(60, tick=True)
    for r in (3, 17):
        assert disagreement({"w": x}, r, mc.live_mask()) <= 1.0
    mc.promote([3, 17])
    # grown == pristine, byte-equal (the round-trip, via the controller)
    for spec, (cw, sw) in zip(sched, mc.comm_weight_arrays()):
        pcw, psw = R.heal_weights(spec, np.zeros(n, bool))
        assert cw.tobytes() == pcw.tobytes()
        assert sw.tobytes() == psw.tobytes()
    live[[3, 17]] = True
    mix(120)
    assert floor(live) < 1e-12  # the WHOLE fleet, rejoined ranks in


def test_fault_plan_preempt_queries():
    plan = R.FaultPlan.preempt(N, rank=3, step=5, duration=4)
    assert R.PREEMPT == "preempt"
    np.testing.assert_array_equal(plan.corrupt_codes(4), np.zeros(N))
    np.testing.assert_array_equal(plan.corrupt_codes(5),
                                  np.eye(N, dtype=np.int8)[3])
    np.testing.assert_array_equal(plan.corrupt_codes(8),
                                  np.eye(N, dtype=np.int8)[3])
    np.testing.assert_array_equal(plan.corrupt_codes(9), np.zeros(N))
    assert plan.preempted_ranks(6) == [3] and plan.preempted_ranks(9) == []
    # rejoinable only once the window has ENDED
    assert plan.rejoinable_ranks(8) == []
    assert plan.rejoinable_ranks(9) == [3]
    # a later re-preempt holds the rank again until ITS window passes
    plan2 = plan.merged(R.FaultPlan.preempt(N, rank=3, step=12,
                                            duration=2))
    assert plan2.rejoinable_ranks(9) == [3]
    assert plan2.rejoinable_ranks(12) == []
    assert plan2.rejoinable_ranks(14) == [3]


def test_fleet_aggregator_grows_with_membership():
    """The gossip layer heals AND re-grows from the controller: the
    duck-typed ``effective_dead_mask()`` is read live, so the same
    aggregator excises a dead rank's row and folds it back in after
    promotion — both to the exact live mean.  The matrices cache stays
    bounded under membership churn."""
    sched = one_peer_dynamic_schedule(N)
    agg = FleetAggregator(sched, record_traffic=False)
    mc = MembershipController(sched, bootstrap_rounds=4)
    rng = np.random.default_rng(3)
    vals = rng.standard_normal((N, 2))
    mc.mark_dead(2)
    res = agg.aggregate(vals, dead_mask=mc)
    live = [r for r in range(N) if r != 2]
    assert np.isnan(res.per_rank[2]).all()
    np.testing.assert_allclose(
        res.per_rank[live],
        np.broadcast_to(vals[live].mean(axis=0), (len(live), 2)),
        atol=1e-12)
    # JOINING is still excised: quarantine means nobody reads it
    mc.admit(2)
    res = agg.aggregate(vals, dead_mask=mc)
    assert np.isnan(res.per_rank[2]).all()
    # promotion re-grows the gossip to the full-fleet mean
    mc.promote(2)
    res = agg.aggregate(vals, dead_mask=mc)
    np.testing.assert_allclose(
        res.per_rank, np.broadcast_to(vals.mean(axis=0), (N, 2)),
        atol=1e-12)
    # churn through > _MATS_CACHE_MAX membership patterns: bounded
    import itertools
    for combo in itertools.islice(
            itertools.combinations(range(N), 2), 36):
        mask = np.zeros(N, bool)
        mask[list(combo)] = True
        agg.aggregate(vals, dead_mask=mask)
    assert len(agg._mats) <= 32


# ------------------------------------------------------------------ #
# acceptance (d): the end-to-end cycle through run_resilient
# ------------------------------------------------------------------ #
def _mesh():
    return Mesh(np.array(jax.devices()[:N]), ("bf",))


def _loss_fn(params, batch):
    x, y = batch
    return jnp.mean((x @ params["w"] - y) ** 2)


_OPT = optax.sgd(0.05, momentum=0.9)


def _state(mesh):
    params = F.rank_major({"w": jnp.zeros((6, 2))}, mesh)
    opt_state = F.rank_major(_OPT.init({"w": jnp.zeros((6, 2))}), mesh)
    return params, opt_state


_DATA = None


def _batch_fn(step):
    global _DATA
    if _DATA is None:
        rng = np.random.RandomState(7)
        _DATA = (rng.randn(32, N, 4, 6), rng.randn(32, N, 4, 2))
    return (_DATA[0][step % 32], _DATA[1][step % 32])


_GSTEP = {}


def _guarded_step():
    """One guarded atc + one-peer-schedule step shared by the elastic
    e2e tests — compile once, reuse everywhere (what lets the
    zero-recompile assertion span admission/anneal/promotion too)."""
    if "step" not in _GSTEP:
        mesh = _mesh()
        sched = one_peer_dynamic_schedule(N)
        _GSTEP["mesh"] = mesh
        _GSTEP["sched"] = sched
        _GSTEP["step"] = F.build_train_step(
            _loss_fn, _OPT, mesh, comm_mode="atc", schedule=sched,
            guard=F.GuardConfig())
    return _GSTEP["step"], _GSTEP["sched"], _GSTEP["mesh"]


def test_elastic_validation():
    step_g, sched, mesh = _guarded_step()
    params, opt_state = _state(mesh)
    with pytest.raises(ValueError, match="schedule"):
        R.run_resilient(step_g, params, opt_state, _batch_fn, steps=1,
                        checkpointer=None, mesh=mesh,
                        elastic=ElasticConfig())
    with pytest.raises(ValueError, match="max_quarantine_steps"):
        R.run_resilient(step_g, params, opt_state, _batch_fn, steps=1,
                        checkpointer=None, mesh=mesh, schedule=sched,
                        elastic=ElasticConfig(bootstrap_rounds=8,
                                              max_quarantine_steps=4))


def test_preempt_rejoin_cycle_zero_recompiles(tmp_path):
    """Acceptance (d): preempt a rank past the death window — the
    fleet declares it dead, heals, rolls back; the window ends, the
    rank is admitted (rank_joining), bootstraps under quarantine, and
    is PROMOTED back to a fully-live fleet — all through the ONE
    compiled program (join/leave/rejoin are pure weight data)."""
    step_g, sched, mesh = _guarded_step()
    params, opt_state = _state(mesh)
    step_g(params, opt_state, _batch_fn(0), jnp.int32(0),
           step_g.default_comm_weights)
    baseline = step_g.jitted._cache_size()
    params, opt_state = _state(mesh)  # the warm-up donated the buffers
    plan = R.FaultPlan.preempt(N, rank=2, step=6, duration=6)
    ck = Checkpointer(str(tmp_path / "ck"))
    res = R.run_resilient(
        step_g, params, opt_state, _batch_fn, steps=30,
        checkpointer=ck, mesh=mesh, schedule=sched,
        guard=F.GuardConfig(max_consecutive_bad=3, backoff_base=0.0),
        fault_plan=plan, checkpoint_every=4, sleep=lambda s: None,
        elastic=ElasticConfig(bootstrap_rounds=4,
                              max_quarantine_steps=16))
    ck.close()
    # zero recompiles across the whole death + rejoin cycle
    assert step_g.jitted._cache_size() == baseline
    kinds = [e.kind for e in res.events if e.kind != "skip"]
    assert kinds.count("rank_dead") == 1
    assert kinds.count("rollback") == 1
    assert kinds.count("rank_joining") == 1
    assert kinds.count("rank_promoted") == 1
    by_kind = {e.kind: e for e in res.events}
    assert by_kind["rank_dead"].detail["rank"] == 2
    assert by_kind["rank_joining"].step > by_kind["rollback"].step
    promo = by_kind["rank_promoted"]
    assert promo.detail["rank"] == 2
    assert promo.detail["rounds"] >= 4
    assert promo.detail["disagreement"] <= 1.0
    # the fleet ends FULLY live: the death verdict was reversed
    assert res.membership == [LIVE] * N
    assert not res.dead_mask.any()
    assert res.n_rollbacks == 1 and res.step == 30
    assert R.update_health(res.params).all()
    # only the preempted rank ever skipped
    assert res.total_skips[2] > 0
    assert res.total_skips[[r for r in range(N) if r != 2]].sum() == 0


def test_rollback_kicks_inflight_joiners(tmp_path):
    """A rollback invalidates in-flight joiners (the restored
    checkpoint predates their bootstrap): the stranded joiner is
    kicked (rank_join_failed, reason=rollback), then re-admitted on a
    later step and promoted — while the newly dead rank stays out."""
    step_g, sched, mesh = _guarded_step()
    params, opt_state = _state(mesh)
    plan = R.FaultPlan.preempt(N, rank=2, step=4, duration=4).merged(
        R.FaultPlan(N, [R.Fault(12, 5, "dead")]))
    ck = Checkpointer(str(tmp_path / "ck"))
    res = R.run_resilient(
        step_g, params, opt_state, _batch_fn, steps=36,
        checkpointer=ck, mesh=mesh, schedule=sched,
        guard=F.GuardConfig(max_consecutive_bad=3, backoff_base=0.0),
        fault_plan=plan, checkpoint_every=4, sleep=lambda s: None,
        elastic=ElasticConfig(bootstrap_rounds=10,
                              max_quarantine_steps=24))
    ck.close()
    joins = [e for e in res.events if e.kind == "rank_joining"]
    fails = [e for e in res.events if e.kind == "rank_join_failed"]
    assert [e.detail["rank"] for e in joins] == [2, 2]
    assert len(fails) == 1 and fails[0].detail["rank"] == 2
    assert fails[0].detail["reason"] == "rollback"
    promos = [e for e in res.events if e.kind == "rank_promoted"]
    assert [e.detail["rank"] for e in promos] == [2]
    assert res.n_rollbacks == 2
    assert res.membership[5] == DEAD
    assert [res.membership[r] for r in range(N) if r != 5] == [LIVE] * 7


def test_quarantine_expiry_kicks(tmp_path):
    """A joiner that can never clear the gate (threshold forced below
    any possible disagreement) is kicked back to DEAD after
    max_quarantine_steps — a half-synced rank never leaks in."""
    step_g, sched, mesh = _guarded_step()
    params, opt_state = _state(mesh)
    plan = R.FaultPlan.preempt(N, rank=2, step=4, duration=4)
    ck = Checkpointer(str(tmp_path / "ck"))
    res = R.run_resilient(
        step_g, params, opt_state, _batch_fn, steps=20,
        checkpointer=ck, mesh=mesh, schedule=sched,
        guard=F.GuardConfig(max_consecutive_bad=3, backoff_base=0.0),
        fault_plan=plan, checkpoint_every=4, sleep=lambda s: None,
        elastic=ElasticConfig(bootstrap_rounds=4,
                              max_quarantine_steps=6,
                              quarantine_threshold=-1.0))
    ck.close()
    fails = [e for e in res.events if e.kind == "rank_join_failed"]
    assert fails and all(e.detail["rank"] == 2 for e in fails)
    assert all(e.detail["reason"] == "quarantine_expired" for e in fails)
    assert not any(e.kind == "rank_promoted" for e in res.events)
    assert res.dead_mask[2]  # the detector verdict was never reversed
    assert res.membership[2] in (DEAD, JOINING)


def test_quarantine_expiry_enforced_between_checks(tmp_path):
    """With ``check_every > 1`` the expiry deadline must not wait for
    the next scheduled measurement: the joiner is kicked the tick its
    quarantine budget runs out, without a disagreement reading."""
    step_g, sched, mesh = _guarded_step()
    params, opt_state = _state(mesh)
    plan = R.FaultPlan.preempt(N, rank=2, step=4, duration=4)
    ck = Checkpointer(str(tmp_path / "ck"))
    res = R.run_resilient(
        step_g, params, opt_state, _batch_fn, steps=20,
        checkpointer=ck, mesh=mesh, schedule=sched,
        guard=F.GuardConfig(max_consecutive_bad=3, backoff_base=0.0),
        fault_plan=plan, checkpoint_every=4, sleep=lambda s: None,
        elastic=ElasticConfig(bootstrap_rounds=4,
                              max_quarantine_steps=6,
                              check_every=4,
                              quarantine_threshold=-1.0))
    ck.close()
    joins = [e for e in res.events if e.kind == "rank_joining"]
    fails = [e for e in res.events if e.kind == "rank_join_failed"]
    assert joins and fails
    # measurements land at progress 4, 8, ...; the deadline (6) falls
    # between them — the kick fires there anyway, measurement-free
    # (progress p is reached at the joining step + p - 1)
    assert fails[0].step - joins[0].step == 5
    assert "disagreement" not in fails[0].detail
    assert all(e.detail["reason"] == "quarantine_expired" for e in fails)


def test_rollback_demotes_promotion_past_restored_checkpoint(tmp_path):
    """A rank PROMOTED inside a bad window (where checkpoints are
    refused) must not stay LIVE through the rollback: the restore
    rewinds its rows to mid-bootstrap state the disagreement gate never
    certified, so the runner demotes it back to DEAD
    (``reason="promotion_rolled_back"``), the admission poll re-offers
    it, and it re-bootstraps cleanly.  On a clean step, promotion
    instead FORCES a checkpoint so the certified state is durable."""
    step_g, sched, mesh = _guarded_step()
    params, opt_state = _state(mesh)
    # rank 2: preempt -> rejoin; rank 5 dies RIGHT as rank 2 rejoins,
    # so rank 2's promotion lands inside rank 5's bad window
    plan = R.FaultPlan.preempt(N, rank=2, step=4, duration=4).merged(
        R.FaultPlan(N, [R.Fault(8, 5, "dead")]))
    ck = Checkpointer(str(tmp_path / "ck"))
    res = R.run_resilient(
        step_g, params, opt_state, _batch_fn, steps=24,
        checkpointer=ck, mesh=mesh, schedule=sched,
        guard=F.GuardConfig(max_consecutive_bad=3, backoff_base=0.0),
        fault_plan=plan, checkpoint_every=4, sleep=lambda s: None,
        elastic=ElasticConfig(bootstrap_rounds=2,
                              max_quarantine_steps=16,
                              quarantine_threshold=1e9))
    ck.close()
    joins = [e for e in res.events if e.kind == "rank_joining"]
    promos = [e for e in res.events if e.kind == "rank_promoted"]
    fails = [e for e in res.events if e.kind == "rank_join_failed"]
    rollbacks = [e for e in res.events if e.kind == "rollback"]
    assert [e.detail["rank"] for e in joins] == [2, 2]
    assert [e.detail["rank"] for e in promos] == [2, 2]
    assert len(fails) == 1 and fails[0].detail["rank"] == 2
    assert fails[0].detail["reason"] == "promotion_rolled_back"
    # the demotion was justified: the restore predates the promotion
    assert rollbacks[1].detail["restored_step"] <= promos[0].step
    # the re-promotion happened on a clean step and was made durable
    # by a forced checkpoint right after it (step not on the cadence)
    ckpt_steps = [e.step for e in res.events if e.kind == "checkpoint"]
    assert promos[1].step + 1 in ckpt_steps
    assert (promos[1].step + 1) % 4 != 0
    assert res.n_rollbacks == 2
    assert res.membership[5] == DEAD
    assert [res.membership[r] for r in range(N) if r != 5] == [LIVE] * 7
    assert not res.dead_mask[2] and res.dead_mask[5]


@pytest.mark.slow
def test_chaos_rejoin_benchmark_smoke(tmp_path):
    """The chaos bench's rejoin part (part 4) runs end to end on tiny
    settings and its self-checks pass (slow: it measures wall time)."""
    import json
    import os
    import subprocess
    import sys

    out = str(tmp_path / "chaos.json")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=repo)
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "benchmarks",
                                      "chaos_resilience.py"),
         "--steps", "24", "--dim", "6", "--sim-rounds", "80",
         "--out", out, "--compare", ""],
        capture_output=True, text=True, timeout=600, env=env, cwd=repo)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    rec = json.load(open(out))
    assert all(rec["checks"].values()), rec["checks"]
    assert rec["rejoin"]["recompiles"] == 0
    assert rec["rejoin"]["final_membership_all_live"]
    assert rec["rejoin"]["sim"]["post_rejoin_floor"] <= 1e-12
