"""Fleet serving (ISSUE 9): prefix/KV reuse, speculative decoding, and
the gossip-fed multi-replica router.

Contracts under test:

* **Prefix cache exactness** — for ANY mix of shared-prefix prompts
  (random prefix lengths, chunk-misaligned boundaries, int8 K/V, slot
  reuse between the insert and the restore), a prefix-cached engine's
  outputs are bit-identical to the one-shot path.  A restored chunk is
  the same bytes the prefill wrote, so reuse must be invisible.
* **Router determinism + backpressure** — routing is a pure function
  of the replicas' gauges (same state -> same decision), spreads load
  away from busy replicas, and surfaces whole-fleet saturation as
  :class:`FleetSaturated` carrying every replica's queue depth.
* **Speculative decoding** — the draft/verify resident pair is
  token-exact with the plain engine at temperature 0 (self-draft AND an
  independently-initialized draft), and the resident-program set is
  fixed at build time.
* **Zero-on-free** — both free modes (index-reset default, full zero
  via ``BLUEFOG_KV_ZERO_ON_FREE``/``zero_on_free=``) keep slot reuse
  exact; only the default retains bytes a prefix cache can reuse.
"""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bluefog_tpu import models
from bluefog_tpu.models import llama_generate
from bluefog_tpu.observe.registry import MetricsRegistry
from bluefog_tpu.serving import (FleetRouter, FleetSaturated, PrefixCache,
                                 Request, RequestRejected, ServingEngine,
                                 SlotPool, SpeculativeConfig,
                                 collect_serving_signals)

pytestmark = pytest.mark.fleet_serving

MAX_LEN = 48


def _setup(**cfg_overrides):
    cfg = models.LlamaConfig.tiny(dtype=jnp.float32, **cfg_overrides)
    variables = models.Llama(cfg).init(jax.random.PRNGKey(1),
                                       jnp.zeros((2, 4), jnp.int32))
    return cfg, variables


def _one_shot(variables, cfg, prompt, n, **kw):
    out = llama_generate(variables, cfg, jnp.asarray(prompt[None]), n,
                         max_len=MAX_LEN, **kw)
    return np.asarray(out)[0]


# --------------------------------------------------------------------- #
# prefix cache: hashing + store semantics
# --------------------------------------------------------------------- #
def test_chunk_keys_are_chained():
    """Keys commit to the WHOLE prefix: equal prefixes share keys, a
    single differing token kills every key from its chunk on, and only
    full chunks of prompt[:-1] are keyed (the last token rides decode)."""
    pc = PrefixCache(chunk=4, capacity_bytes=1 << 20)
    a = np.arange(13, dtype=np.int32)            # 12 prefill tokens
    assert len(pc.chunk_keys(a)) == 3
    assert len(pc.chunk_keys(a[:12])) == 2       # 11 prefill -> 2 full
    assert len(pc.chunk_keys(a[:4])) == 0        # 3 prefill tokens
    b = a.copy()
    b[5] = 99                                    # differ inside chunk 1
    ka, kb = pc.chunk_keys(a), pc.chunk_keys(b)
    assert ka[0] == kb[0]
    assert ka[1] != kb[1] and ka[2] != kb[2]     # chain severed
    # same tokens, different chunk size -> different key space
    assert PrefixCache(chunk=8).chunk_keys(a)[0] != ka[0]


def test_prefix_cache_lru_bound():
    """Insertion respects the byte budget: least-recently-USED entries
    evict first, an over-budget chunk is refused outright, and match()
    walks the chain (a miss at chunk i forecloses chunk i+1)."""
    leaf = np.zeros(100, np.float32)             # 400 bytes/entry
    pc = PrefixCache(chunk=4, capacity_bytes=1000)
    pc.insert("k0", [leaf])
    pc.insert("k1", [leaf])
    assert pc.match(["k0", "k1", "k2"]) == 2     # touches k0 then k1
    pc.insert("k2", [leaf])                      # evicts the LRU...
    assert len(pc) == 2 and pc.nbytes == 800
    assert pc.match(["k0"]) == 0                 # ...which was k0
    pc.insert("huge", [np.zeros(1001, np.uint8)])
    assert len(pc) == 2                          # refused, not thrashed
    assert pc.match(["k0", "k1"]) == 0           # chain: dead at k0
    s = pc.stats()
    assert s["evictions"] == 1 and s["hit_rate"] < 1.0


def test_seq_axes_structural_detection():
    """The per-leaf sequence axis comes from shape-evaluating the cache
    at two lengths — index leaves (no scaling axis) come back None, and
    both K/V layouts resolve without a registry."""
    from bluefog_tpu.serving.prefix_cache import seq_axes

    cfg, _ = _setup()
    for kv_quant in ("none", "int8"):
        axes = seq_axes(cfg, 16, kv_quant)
        assert None in axes                      # cache_index leaves
        assert any(a is not None for a in axes)  # K/V leaves


# --------------------------------------------------------------------- #
# prefix cache: the admission-exactness property
# --------------------------------------------------------------------- #
@pytest.mark.parametrize("kv_quant", ["none", "int8"])
def test_prefix_admission_bitwise_exact_property(kv_quant):
    """The acceptance property: random shared-prefix prompt families —
    prefix lengths off the chunk grid, novel tails, slot reuse and
    capacity-1 recycling between insert and restore — every output is
    bit-identical to COLD prefill (a cacheless engine running the same
    compiled programs; engine==one-shot is test_serving's anchor)."""
    cfg, variables = _setup()
    params = variables
    kw = {}
    if kv_quant == "int8":
        from bluefog_tpu.models.quant import quantize_llama_params

        params = quantize_llama_params(variables)
        kw = dict(kv_quant="int8", weight_quant="int8")
    rs = np.random.RandomState(42)
    eng = ServingEngine(params, cfg, capacity=1, max_len=MAX_LEN,
                        prefill_chunk=4, prefix_cache=True,
                        max_queue=64, registry=MetricsRegistry(), **kw)
    cold = ServingEngine(params, cfg, capacity=1, max_len=MAX_LEN,
                         prefill_chunk=4, prefix_cache=False,
                         max_queue=64, registry=MetricsRegistry(), **kw)
    prompts = []
    for _ in range(3):
        # a family: one prefix, several continuations of random length
        prefix = rs.randint(0, 256,
                            (rs.randint(3, 20),)).astype(np.int32)
        prompts.append(prefix)
        for _ in range(2):
            tail = rs.randint(0, 256,
                              (rs.randint(1, 8),)).astype(np.int32)
            prompts.append(np.concatenate([prefix, tail]))
    order = rs.permutation(len(prompts))
    reqs = {}
    for i in order:
        reqs[i] = eng.submit(Request(prompts[i], 5))
        eng.run()  # capacity 1: each admission reuses THE slot
    for i, r in reqs.items():
        ref = cold.submit(Request(prompts[i], 5))
        cold.run()
        np.testing.assert_array_equal(r.output(), ref.output())
    # the families actually exercised the cache
    assert eng.metrics.summary()["prefix_chunks_restored"] > 0
    assert eng.pool.prefix.stats()["hits"] > 0
    assert cold.metrics.summary()["prefix_chunks_restored"] == 0


def test_prefix_restore_skips_prefill_work():
    """A warm admission computes only its novel tail: the engine's
    prefill-chunk counter advances by the tail chunks alone, and the
    restored token count lands in the summary."""
    cfg, variables = _setup()
    eng = ServingEngine(variables, cfg, capacity=1, max_len=MAX_LEN,
                        prefill_chunk=4, prefix_cache=True,
                        registry=MetricsRegistry())
    rs = np.random.RandomState(7)
    prefix = rs.randint(0, 256, (16,)).astype(np.int32)
    a = np.concatenate([prefix, rs.randint(0, 256, (2,)).astype(np.int32)])
    b = np.concatenate([prefix, rs.randint(0, 256, (2,)).astype(np.int32)])
    eng.submit(Request(a, 4))
    eng.run()
    cold_chunks = eng.metrics.summary()["prefill_chunks"]
    eng.submit(Request(b, 4))
    eng.run()
    m = eng.metrics.summary()
    # b's 17 prefill tokens = 4 cached chunks restored + 1 tail chunk
    assert m["prefix_chunks_restored"] == 4
    assert m["prefix_tokens_restored"] == 16
    assert m["prefill_chunks"] == cold_chunks + 1
    assert 0 < m["prefix_hit_rate"] < 1


def test_prefix_chunk_must_match_engine_chunk():
    cfg, variables = _setup()
    with pytest.raises(ValueError, match="chunk"):
        ServingEngine(variables, cfg, capacity=1, max_len=MAX_LEN,
                      prefill_chunk=4,
                      prefix_cache=PrefixCache(chunk=8))


# --------------------------------------------------------------------- #
# zero-on-free: both modes exact, retention only in the default
# --------------------------------------------------------------------- #
@pytest.mark.parametrize("zero_on_free", [False, True])
def test_slot_reuse_exact_both_free_modes(zero_on_free):
    """Index-reset (default) and full-zero free both keep slot reuse
    bit-exact — the zero mode buys nothing for correctness."""
    cfg, variables = _setup()
    # lengths/budget shared with the speculative tests so the one-shot
    # reference programs compile once for the whole file
    prompts = [p.astype(np.int32) for p in
               (np.arange(5) + 3, np.arange(9) * 2 + 1)]
    eng = ServingEngine(variables, cfg, capacity=1, max_len=MAX_LEN,
                        prefill_chunk=4, zero_on_free=zero_on_free)
    assert eng.pool.zero_on_free is zero_on_free
    for p in prompts:
        r = eng.submit(Request(p, 6))
        eng.run()
        np.testing.assert_array_equal(
            r.output(), _one_shot(variables, cfg, p, 6))


def test_free_modes_differ_only_in_retention():
    """After free: the default leaves K/V bytes in place (what the
    prefix cache feeds on) and only resets ``cache_index``; zero-on-free
    wipes the whole slot.  Env var ``BLUEFOG_KV_ZERO_ON_FREE`` selects
    the mode when the ctor argument is left None."""
    cfg, variables = _setup()

    def run_one(zero):
        eng = ServingEngine(variables, cfg, capacity=1, max_len=MAX_LEN,
                            prefill_chunk=4, zero_on_free=zero)
        eng.submit(Request(np.arange(9, dtype=np.int32), 4))
        eng.run()
        total = 0.0
        for path, leaf in jax.tree_util.tree_flatten_with_path(
                eng.pool.cache)[0]:
            if getattr(path[-1], "key", None) == "cache_index":
                assert not np.asarray(leaf).any()  # always reset
            else:
                total += float(np.abs(np.asarray(
                    leaf, np.float32)).sum())
        return total

    assert run_one(zero=False) > 0.0   # bytes retained
    assert run_one(zero=True) == 0.0   # slot wiped
    import os

    from bluefog_tpu import config as bfconfig

    old = os.environ.get("BLUEFOG_KV_ZERO_ON_FREE")
    try:
        os.environ["BLUEFOG_KV_ZERO_ON_FREE"] = "1"
        assert bfconfig.kv_zero_on_free() is True
        assert SlotPool(cfg, capacity=1, max_len=16).zero_on_free
        os.environ["BLUEFOG_KV_ZERO_ON_FREE"] = "0"
        assert not SlotPool(cfg, capacity=1, max_len=16).zero_on_free
    finally:
        if old is None:
            os.environ.pop("BLUEFOG_KV_ZERO_ON_FREE", None)
        else:
            os.environ["BLUEFOG_KV_ZERO_ON_FREE"] = old


# --------------------------------------------------------------------- #
# speculative decoding
# --------------------------------------------------------------------- #
def _spec_engine(variables, cfg, draft_vars, draft_cfg=None, **kw):
    spec = SpeculativeConfig(variables=draft_vars,
                             cfg=draft_cfg or cfg, lookahead=3)
    return ServingEngine(variables, cfg, capacity=2, max_len=MAX_LEN,
                         prefill_chunk=4, speculative=spec,
                         registry=MetricsRegistry(), **kw)


def test_speculative_self_draft_exact_and_fast():
    """Target-as-its-own-draft at temp 0: every window verifies, so
    each step emits lookahead+1 tokens AND the stream is bit-exact with
    the plain engine / one-shot path."""
    cfg, variables = _setup()
    rs = np.random.RandomState(0)
    prompts = [rs.randint(0, 256, (n,)).astype(np.int32)
               for n in (5, 9, 3)]
    eng = _spec_engine(variables, cfg, variables)
    reqs = [eng.submit(Request(p, 6)) for p in prompts]
    eng.run()
    for r, p in zip(reqs, prompts):
        np.testing.assert_array_equal(
            r.output(), _one_shot(variables, cfg, p, 6))
    m = eng.metrics.summary()
    assert m["accepted_per_step"] > 1.0
    assert m["spec_steps"] > 0


def test_speculative_independent_draft_exact():
    """An independently-initialized draft disagrees with the target
    almost everywhere — the rejection path dominates — and the output
    is STILL bit-exact at temp 0 (speculation changes cost, never
    content)."""
    cfg, variables = _setup()
    draft = models.Llama(cfg).init(jax.random.PRNGKey(7),
                                   jnp.zeros((2, 4), jnp.int32))
    rs = np.random.RandomState(1)
    prompts = [rs.randint(0, 256, (n,)).astype(np.int32)
               for n in (9, 3)]
    eng = _spec_engine(variables, cfg, draft)
    reqs = [eng.submit(Request(p, 6)) for p in prompts]
    eng.run()
    for r, p in zip(reqs, prompts):
        np.testing.assert_array_equal(
            r.output(), _one_shot(variables, cfg, p, 6))


def test_speculative_sampled_path_completes():
    """temperature > 0 goes through rejection sampling + residual
    resample; streams complete within budget (distribution equality is
    the algorithm's guarantee; bit-equality is only promised at 0)."""
    cfg, variables = _setup()
    draft = models.Llama(cfg).init(jax.random.PRNGKey(7),
                                   jnp.zeros((2, 4), jnp.int32))
    rs = np.random.RandomState(2)
    prompts = [rs.randint(0, 256, (5,)).astype(np.int32)
               for _ in range(2)]
    eng = _spec_engine(variables, cfg, draft)
    reqs = [eng.submit(Request(p, 6, temperature=0.8, seed=3 + i))
            for i, p in enumerate(prompts)]
    eng.run()
    for r, p in zip(reqs, prompts):
        assert r.state == "completed"
        assert r.output().size == p.size + 6
        assert (r.output() >= 0).all()


def test_speculative_headroom_reservation():
    """submit() reserves lookahead positions past the budget: a prompt
    that fits the plain engine is refused by the speculative one when
    the draft window could overrun the slot (dynamic_update_slice would
    CLAMP and corrupt K/V silently)."""
    cfg, variables = _setup()
    prompt = np.arange(MAX_LEN - 8, dtype=np.int32)
    plain = ServingEngine(variables, cfg, capacity=1, max_len=MAX_LEN,
                          prefill_chunk=4)
    plain.submit(Request(prompt, 8))  # exactly fits
    eng = _spec_engine(variables, cfg, variables)
    with pytest.raises(ValueError, match="headroom"):
        eng.submit(Request(prompt, 8))


def test_resident_program_set_fixed_at_build():
    """The resident registry is a build-time constant: 2 programs
    plain, 3 speculative, unchanged by serving load, and profile()
    enumerates exactly that set."""
    cfg, variables = _setup()
    plain = ServingEngine(variables, cfg, capacity=2, max_len=MAX_LEN,
                          prefill_chunk=4)
    eng = _spec_engine(variables, cfg, variables)
    assert sorted(plain._resident) == ["decode_step", "prefill_chunk"]
    assert sorted(eng._resident) == ["draft_prefill_chunk",
                                     "prefill_chunk", "spec_step"]
    rs = np.random.RandomState(3)
    for e in (plain, eng):
        before = set(e._resident)
        for n in (3, 6):
            e.submit(Request(rs.randint(0, 256, (n,)).astype(np.int32),
                             4))
        e.run()
        assert set(e._resident) == before
    # generic profile() enumeration over the draft/verify pair (the
    # plain 2-program enumeration is test_observe's profile test)
    profs = eng.profile(publish=False)
    assert set(profs) == {"draft_prefill_chunk", "prefill_chunk",
                          "spec_step"}
    assert all(p.flops > 0 for p in profs.values())


def test_speculative_no_recompiles_across_arrivals():
    """One compiled speculative step serves every arrival pattern —
    same zero-recompile contract the plain decode step carries."""
    from bluefog_tpu.serving.engine import _spec_step_prog

    cfg, variables = _setup()
    eng = _spec_engine(variables, cfg, variables)
    rs = np.random.RandomState(4)
    eng.submit(Request(rs.randint(0, 256, (5,)).astype(np.int32), 4))
    eng.run()
    n0 = _spec_step_prog._cache_size()
    for n, b in ((3, 6), (9, 3), (1, 5)):
        eng.submit(Request(rs.randint(0, 256, (n,)).astype(np.int32), b))
        eng.step()
    eng.run()
    assert _spec_step_prog._cache_size() == n0


# --------------------------------------------------------------------- #
# fleet router
# --------------------------------------------------------------------- #
def _fleet(variables, cfg, n, capacity=2, max_queue=2, **kw):
    regs = [MetricsRegistry() for _ in range(n)]
    engines = [ServingEngine(variables, cfg, capacity=capacity,
                             max_len=MAX_LEN, prefill_chunk=4,
                             max_queue=max_queue, registry=r)
               for r in regs]
    return engines, regs, FleetRouter(engines, registries=regs, **kw)


def test_collect_serving_signals():
    cfg, variables = _setup()
    reg = MetricsRegistry()
    eng = ServingEngine(variables, cfg, capacity=2, max_len=MAX_LEN,
                        prefill_chunk=4, registry=reg)
    sig = collect_serving_signals(reg)
    assert sig == {"occupancy": 0.0, "queue_depth": 0.0, "ttft_p50": 0.0,
                   "last_step_ts": -1.0}  # -1: never stepped (the
    # staleness guard exempts cold replicas)
    eng.submit(Request(np.arange(5, dtype=np.int32), 3))
    eng.run()
    sig = collect_serving_signals(reg)
    assert sig["ttft_p50"] >= 0.0  # histogram scraped without error
    assert sig["last_step_ts"] >= 0.0  # heartbeat advanced by stepping


def test_router_is_deterministic_and_prefers_idle():
    """Same replica state -> identical snapshot, scores, and order; a
    loaded replica ranks behind an idle one; per-rank converged views
    agree (push-sum exactness over the serving gauges)."""
    cfg, variables = _setup()
    engines, regs, router = _fleet(variables, cfg, 3)
    rs = np.random.RandomState(5)
    engines[0].submit(Request(rs.randint(0, 256, (5,)).astype(np.int32),
                              6))
    engines[0].step()
    s1, s2 = router.poll(), router.poll()
    assert s1.order == s2.order
    np.testing.assert_allclose(s1.scores, s2.scores, rtol=0, atol=0)
    np.testing.assert_array_equal(s1.signals, s2.signals)
    assert s1.order[-1] == 0            # the busy replica ranks last
    assert s1.rounds > 0 and s1.spread <= 1e-10
    # another rank's router sees the same fleet (decentralized: no
    # rank is special)
    other = FleetRouter(engines, registries=regs, rank=2)
    np.testing.assert_allclose(other.poll().signals, s1.signals,
                               rtol=1e-9, atol=1e-12)
    # single replica bypasses gossip
    engines1, _, router1 = _fleet(variables, cfg, 1)
    snap = router1.poll()
    assert snap.rounds == 0 and snap.order == (0,)


def test_router_spreads_and_saturates():
    """Requests spread across replicas; when every queue is full the
    router raises FleetSaturated with all per-replica depths (a
    RequestRejected subclass — client backoff code keeps working)."""
    cfg, variables = _setup()
    engines, regs, router = _fleet(variables, cfg, 2, capacity=1,
                                   max_queue=1)
    rs = np.random.RandomState(6)

    def req():
        return Request(rs.randint(0, 256, (4,)).astype(np.int32), 3)

    picks = [router.submit(req())[0] for _ in range(2)]
    assert sorted(picks) == [0, 1]      # second submit avoids the first
    for e in engines:
        e.step()                        # queued -> slots (queues empty)
    for _ in range(2):                  # re-fill both 1-deep queues
        router.submit(req())
    with pytest.raises(FleetSaturated) as ei:
        router.submit(req())
    assert isinstance(ei.value, RequestRejected)
    assert ei.value.queue_depths == [1, 1]
    assert router.summary()["n_saturated"] == 1
    for e in engines:
        e.run()                         # fleet drains fine afterwards
    assert all(e.pool.n_active == 0 for e in engines)


def test_router_dead_replica_excised():
    """A dead replica's signals drop out of the gossip and its score is
    +inf: it is never routed to — same excision semantics as the
    training-side dead-rank handling."""
    cfg, variables = _setup()
    engines, regs, router = _fleet(variables, cfg, 2)
    snap = router.poll(dead_mask=[False, True])
    assert snap.order[0] == 0
    assert not np.isfinite(snap.scores[1])
    idx, _ = router.submit(Request(np.arange(4, dtype=np.int32), 3),
                           snapshot=snap)
    assert idx == 0
    engines[0].run()


def test_router_publish_lands_fleet_gauges():
    cfg, variables = _setup()
    pub = MetricsRegistry()
    engines, regs, router = _fleet(variables, cfg, 2, registry=pub)
    router.submit(Request(np.arange(5, dtype=np.int32), 3))
    for e in engines:
        e.run()
    router.publish()
    names = {n for n, *_ in pub.collect()}
    assert "bf_fleet_serving_occupancy" in names
    assert "bf_fleet_serving_queue_depth" in names
    assert "bf_fleet_serving_best_replica" in names


# --------------------------------------------------------------------- #
# the bench artifact (slow: subprocess + wall-clock measurement)
# --------------------------------------------------------------------- #
@pytest.mark.slow
def test_fleet_serving_bench_smoke(tmp_path):
    """benchmarks/fleet_serving.py end to end at a tiny scale: all
    machine-checked claims hold and the record carries every section."""
    import os
    import subprocess
    import sys

    out = str(tmp_path / "fleet.json")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=repo)
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "benchmarks",
                                      "fleet_serving.py"),
         "--num-requests", "8", "--capacity", "2", "--max-len", "48",
         "--prompt-len", "3", "8", "--new-tokens", "3", "6",
         "--prefix-pairs", "2", "--prefix-len", "24",
         "--prefill-chunk", "4", "--lookahead", "2",
         "--dim", "64", "--layers", "2",
         "--out", out, "--compare", ""],
        capture_output=True, text=True, timeout=600, env=env, cwd=repo)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    rec = json.load(open(out))
    assert all(rec["machine_checked"].values()), rec["machine_checked"]
    assert rec["fleet_two"]["fleet_speedup"] > 1.0
    assert (rec["prefix"]["warm_admit_ttft_p50"]
            < rec["prefix"]["cold_admit_ttft_p50"])
    assert rec["speculative"]["accepted_per_step"] > 1.0
    assert rec["resident"]["plain_count"] == 2
    assert rec["resident"]["speculative_count"] == 3
