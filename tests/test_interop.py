"""Torch framework adapter (reference second-framework binding parity)."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

from bluefog_tpu import interop  # noqa: E402


def test_allreduce(bf_ctx):
    n = bf_ctx.size()
    x = torch.arange(n * 3, dtype=torch.float32).reshape(n, 3)
    out = interop.allreduce(x, average=True)
    assert isinstance(out, torch.Tensor)
    expected = x.numpy().mean(axis=0)
    for r in range(n):
        np.testing.assert_allclose(out[r].numpy(), expected, rtol=1e-6)


def test_broadcast(bf_ctx):
    n = bf_ctx.size()
    x = torch.arange(n * 2, dtype=torch.float64).reshape(n, 2)
    out = interop.broadcast(x, root_rank=2)
    for r in range(n):
        np.testing.assert_array_equal(out[r].numpy(), x[2].numpy())


def test_allgather(bf_ctx):
    n = bf_ctx.size()
    x = torch.arange(n * 2, dtype=torch.float32).reshape(n, 1, 2)
    out = interop.allgather(x)
    # every rank holds the concatenation of all ranks' slices
    assert out.shape == (n, n, 2)


def test_neighbor_allreduce_consensus(bf_ctx):
    n = bf_ctx.size()
    x = torch.tensor([[float(r)] * 4 for r in range(n)])
    for _ in range(30):
        x = interop.neighbor_allreduce(x)
    np.testing.assert_allclose(x.numpy(), (n - 1) / 2, atol=1e-6)


def test_type_error(bf_ctx):
    with pytest.raises(TypeError):
        interop.allreduce(np.zeros((8, 2)))
