"""Torch framework adapter (reference second-framework binding parity)."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

from bluefog_tpu import interop  # noqa: E402


def test_allreduce(bf_ctx):
    n = bf_ctx.size()
    x = torch.arange(n * 3, dtype=torch.float32).reshape(n, 3)
    out = interop.allreduce(x, average=True)
    assert isinstance(out, torch.Tensor)
    expected = x.numpy().mean(axis=0)
    for r in range(n):
        np.testing.assert_allclose(out[r].numpy(), expected, rtol=1e-6)


def test_broadcast(bf_ctx):
    n = bf_ctx.size()
    x = torch.arange(n * 2, dtype=torch.float64).reshape(n, 2)
    out = interop.broadcast(x, root_rank=2)
    for r in range(n):
        np.testing.assert_array_equal(out[r].numpy(), x[2].numpy())


def test_allgather(bf_ctx):
    n = bf_ctx.size()
    x = torch.arange(n * 2, dtype=torch.float32).reshape(n, 1, 2)
    out = interop.allgather(x)
    # every rank holds the concatenation of all ranks' slices
    assert out.shape == (n, n, 2)


def test_neighbor_allreduce_consensus(bf_ctx):
    n = bf_ctx.size()
    x = torch.tensor([[float(r)] * 4 for r in range(n)])
    for _ in range(30):
        x = interop.neighbor_allreduce(x)
    np.testing.assert_allclose(x.numpy(), (n - 1) / 2, atol=1e-6)


def test_type_error(bf_ctx):
    with pytest.raises(TypeError):
        interop.allreduce(np.zeros((8, 2)))


def test_broadcast_parameters_in_place(bf_ctx):
    n = bf_ctx.size()
    p = torch.arange(n * 2, dtype=torch.float32).reshape(n, 2)
    q = torch.ones(n, 3) * torch.arange(n, dtype=torch.float32)[:, None]
    interop.broadcast_parameters([p, q], root_rank=1)
    for r in range(n):
        np.testing.assert_array_equal(p[r].numpy(), [2.0, 3.0])
        np.testing.assert_array_equal(q[r].numpy(), [1.0, 1.0, 1.0])


@pytest.mark.parametrize("communication",
                         ["allreduce", "neighbor_allreduce"])
def test_distributed_optimizer_trains_torch_model(bf_ctx, communication):
    """A real torch training loop: rank-major replica stacks, per-rank
    losses, communication over the JAX data plane (reference
    tensorflow/optimizers.py DistributedOptimizer parity)."""
    n = bf_ctx.size()
    torch.manual_seed(0)
    w = torch.zeros(n, 4, requires_grad=True)
    rng = np.random.RandomState(0)
    target = rng.randn(4).astype(np.float32)
    A = torch.tensor(rng.randn(n, 16, 4).astype(np.float32))
    b = torch.einsum("rsd,d->rs", A, torch.tensor(target))

    opt = interop.DistributedOptimizer(
        torch.optim.SGD([w], lr=0.05), communication=communication)
    for _ in range(150):
        opt.zero_grad()
        pred = torch.einsum("rsd,rd->rs", A, w)
        # Each rank's loss is the mean over ITS OWN 16 samples (a global
        # mean would shrink per-rank grads by 1/n — the reference's
        # DistributedOptimizer averages per-rank gradients, it does not
        # rescale them).  Summing the per-rank means keeps each rank's
        # gradient flowing only into its own replica slice.
        loss = ((pred - b) ** 2).mean(dim=1).sum()
        loss.backward()
        opt.step()
    final = w.detach().numpy()
    assert np.abs(final - target).max() < 0.1
    # ranks agree (consensus through the communication path)
    assert np.abs(final - final.mean(axis=0)).max() < 1e-2


def test_distributed_optimizer_rejects_unknown_mode(bf_ctx):
    with pytest.raises(ValueError, match="communication"):
        interop.DistributedOptimizer(
            torch.optim.SGD([torch.zeros(2, 2, requires_grad=True)], lr=0.1),
            communication="gossip")
