"""Pipeline parallelism (capability beyond the reference — SURVEY.md
§2.3 lists PP as absent there).

The contract under test: GPipe over a ``pp`` mesh axis
(``parallel.pipeline.gpipe`` + ``models.llama_pp_loss_fn``) is a LAYOUT,
not a different model — losses and one-step parameter updates must match
the unsharded scanned Llama exactly (up to f32 roundoff), including the
pp-replicated leaves (embedding, final norm, head) whose gradients ride
the train step's pipeline psum.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bluefog_tpu import models
from bluefog_tpu.models.llama import llama_param_specs, llama_pp_loss_fn
from bluefog_tpu.optim import functional as F
from bluefog_tpu.topology import RingGraph, uniform_topology_spec

B, T, L = 4, 16, 4


def _cfg():
    return models.LlamaConfig.tiny(dtype=jnp.float32, n_layers=L,
                                   scan_layers=True)


def _data(n_bf, seed=0):
    rng = np.random.RandomState(seed)
    raw = rng.randint(0, 256, size=(n_bf, B, T + 1)).astype(np.int32)
    return raw[:, :, :-1], raw[:, :, 1:]


def _plain_loss(model, variables, inp, tgt):
    logits = model.apply(variables, inp)
    return jnp.mean(
        optax.softmax_cross_entropy_with_integer_labels(logits, tgt))


def _build(mesh, n_bf, n_pp, n_micro, comm_mode="none", **kw):
    cfg = _cfg()
    model = models.Llama(cfg)
    variables = model.init(jax.random.PRNGKey(1),
                           jnp.zeros((B, 8), jnp.int32))
    specs = llama_param_specs(variables, tp_axis=None, ep_axis=None,
                              pp_axis="pp")
    opt = optax.sgd(0.1)
    loss_fn = llama_pp_loss_fn(cfg, pp_axis="pp", n_stages=n_pp,
                               n_micro=n_micro)
    step = F.build_train_step(
        loss_fn, opt, mesh, comm_mode=comm_mode, pp_axis="pp",
        batch_specs=P("bf"), param_specs=specs,
        opt_state_specs=F.optax_state_specs(opt, variables, specs), **kw)
    params = F.rank_major(variables, mesh, specs=specs)
    opt_state = F.rank_major(
        opt.init(variables), mesh,
        specs=F.optax_state_specs(opt, variables, specs))
    return cfg, model, variables, opt, step, params, opt_state


@pytest.mark.parametrize("n_micro", [1, 2, 4])
def test_pp_loss_matches_unsharded(n_micro):
    n_bf, n_pp = 2, 4
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(n_bf, n_pp),
                ("bf", "pp"))
    cfg, model, variables, opt, step, params, opt_state = _build(
        mesh, n_bf, n_pp, n_micro)
    inp, tgt = _data(n_bf)
    batch = (jax.device_put(inp, NamedSharding(mesh, P("bf"))),
             jax.device_put(tgt, NamedSharding(mesh, P("bf"))))
    _, _, loss = step(params, opt_state, batch, jnp.int32(0))
    loss = np.asarray(loss)
    for r in range(n_bf):
        ref = float(_plain_loss(model, variables, inp[r], tgt[r]))
        np.testing.assert_allclose(loss[r], ref, rtol=1e-5, atol=1e-5)


def test_pp_one_step_update_matches_unsharded():
    """One SGD step under pp == one SGD step of the plain scanned model,
    leaf by leaf — layer stacks (pp-sharded) AND embeddings/head
    (pp-replicated, exercised by the pipeline-axis psum)."""
    n_bf, n_pp = 2, 4
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(n_bf, n_pp),
                ("bf", "pp"))
    cfg, model, variables, opt, step, params, opt_state = _build(
        mesh, n_bf, n_pp, n_micro=2)
    inp, tgt = _data(n_bf)
    batch = (jax.device_put(inp, NamedSharding(mesh, P("bf"))),
             jax.device_put(tgt, NamedSharding(mesh, P("bf"))))
    new_params, _, _ = step(params, opt_state, batch, jnp.int32(0))

    for r in range(n_bf):
        grads = jax.grad(
            lambda v: _plain_loss(model, v, inp[r], tgt[r]))(variables)
        expect = jax.tree.map(lambda p, g: p - 0.1 * g, variables, grads)
        got_r = jax.tree.map(lambda l: np.asarray(l[r]), new_params)
        flat_e, _ = jax.tree_util.tree_flatten_with_path(expect)
        flat_g = jax.tree.leaves(got_r)
        for (path, e), g in zip(flat_e, flat_g):
            np.testing.assert_allclose(
                g, np.asarray(e), rtol=2e-5, atol=2e-5,
                err_msg=jax.tree_util.keystr(path))


def test_pp_composes_with_decentralized_combine():
    """dp x pp ATC run == dp-only ATC run: the pipeline changes the
    layout of the model, not the decentralized algorithm."""
    n_bf, n_pp = 4, 2
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(n_bf, n_pp),
                ("bf", "pp"))
    topo = uniform_topology_spec(RingGraph(n_bf))
    cfg, model, variables, opt, step, params, opt_state = _build(
        mesh, n_bf, n_pp, n_micro=2, comm_mode="atc", topology=topo)
    inp, tgt = _data(n_bf)
    batch = (jax.device_put(inp, NamedSharding(mesh, P("bf"))),
             jax.device_put(tgt, NamedSharding(mesh, P("bf"))))
    for s in range(2):
        params, opt_state, _ = step(params, opt_state, batch, jnp.int32(s))

    # dp-only reference on a flat 4-device mesh
    mesh_dp = Mesh(np.array(jax.devices()[:n_bf]), ("bf",))
    step_dp = F.build_train_step(
        lambda v, b: _plain_loss(model, v, b[0], b[1]), opt, mesh_dp,
        comm_mode="atc", topology=topo)
    params_dp = F.rank_major(variables, mesh_dp)
    opt_dp = F.rank_major(opt.init(variables), mesh_dp)
    batch_dp = (jax.device_put(inp, NamedSharding(mesh_dp, P("bf"))),
                jax.device_put(tgt, NamedSharding(mesh_dp, P("bf"))))
    for s in range(2):
        params_dp, opt_dp, _ = step_dp(params_dp, opt_dp, batch_dp,
                                       jnp.int32(s))

    flat_a, _ = jax.tree_util.tree_flatten_with_path(params)
    flat_b = jax.tree.leaves(params_dp)
    for (path, a), b in zip(flat_a, flat_b):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=3e-5, atol=3e-5,
            err_msg=jax.tree_util.keystr(path))


def test_circular_pp_loss_and_update_match_unsharded():
    """Circular (interleaved) schedule with n_loops=2: same exactness
    contract as GPipe — losses and one-step updates equal the unsharded
    model's, with the layer axis permuted into (and the update compared
    back out of) the circular storage order."""
    from bluefog_tpu.models.llama import (llama_circular_layout,
                                          llama_pp_loss_fn)

    n_bf, n_pp, n_loops, n_micro = 2, 2, 2, 4
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(n_bf, n_pp),
                ("bf", "pp"))
    cfg = _cfg()  # L=4 layers: 2 stages x 2 loops x 1 layer/chunk
    model = models.Llama(cfg)
    variables = model.init(jax.random.PRNGKey(1),
                           jnp.zeros((B, 8), jnp.int32))
    circ = llama_circular_layout(variables, n_pp, n_loops)
    # round-trip sanity
    back = llama_circular_layout(circ, n_pp, n_loops, inverse=True)
    for (pa, a), b in zip(
            jax.tree_util.tree_flatten_with_path(variables)[0],
            jax.tree.leaves(back)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=jax.tree_util.keystr(pa))

    specs = llama_param_specs(circ, tp_axis=None, ep_axis=None,
                              pp_axis="pp")
    opt = optax.sgd(0.1)
    opt_specs = F.optax_state_specs(opt, circ, specs)
    step = F.build_train_step(
        llama_pp_loss_fn(cfg, pp_axis="pp", n_stages=n_pp,
                         n_micro=n_micro, n_loops=n_loops),
        opt, mesh, comm_mode="none", pp_axis="pp", batch_specs=P("bf"),
        param_specs=specs, opt_state_specs=opt_specs, donate=False)
    params = F.rank_major(circ, mesh, specs=specs)
    opt_state = F.rank_major(opt.init(circ), mesh, specs=opt_specs)
    inp, tgt = _data(n_bf)
    batch = (jax.device_put(inp, NamedSharding(mesh, P("bf"))),
             jax.device_put(tgt, NamedSharding(mesh, P("bf"))))
    new_params, _, loss = step(params, opt_state, batch, jnp.int32(0))
    loss = np.asarray(loss)

    for r in range(n_bf):
        ref = float(_plain_loss(model, variables, inp[r], tgt[r]))
        np.testing.assert_allclose(loss[r], ref, rtol=1e-5, atol=1e-5)
        grads = jax.grad(
            lambda v: _plain_loss(model, v, inp[r], tgt[r]))(variables)
        expect = jax.tree.map(lambda p, g: p - 0.1 * g, variables, grads)
        got_r = llama_circular_layout(
            jax.tree.map(lambda l: l[r], new_params), n_pp, n_loops,
            inverse=True)
        flat_e, _ = jax.tree_util.tree_flatten_with_path(expect)
        flat_g = jax.tree.leaves(got_r)
        for (path, e), g in zip(flat_e, flat_g):
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(e), rtol=2e-5, atol=2e-5,
                err_msg=jax.tree_util.keystr(path))


def test_pp_composes_with_ring_sequence_parallelism():
    """dp x pp x sp in ONE program: pipelined stages whose blocks run
    ring attention over the sp axis — per-rank losses equal the
    unsharded full-attention model's."""
    from bluefog_tpu.models.llama import llama_pp_loss_fn

    n_bf, n_pp, n_sp = 2, 2, 2
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(n_bf, n_pp, n_sp),
                ("bf", "pp", "sp"))
    cfg = models.LlamaConfig.tiny(dtype=jnp.float32, n_layers=L,
                                  scan_layers=True, attn_mode="ring",
                                  sp_axis="sp")
    plain = models.LlamaConfig.tiny(dtype=jnp.float32, n_layers=L,
                                    scan_layers=True)
    ref_model = models.Llama(plain)
    variables = ref_model.init(jax.random.PRNGKey(1),
                               jnp.zeros((B, 8), jnp.int32))
    specs = llama_param_specs(variables, tp_axis=None, ep_axis=None,
                              pp_axis="pp")
    opt = optax.sgd(0.1)
    opt_specs = F.optax_state_specs(opt, variables, specs)
    step = F.build_train_step(
        llama_pp_loss_fn(cfg, pp_axis="pp", n_stages=n_pp, n_micro=2),
        opt, mesh, comm_mode="none", pp_axis="pp", sp_axis="sp",
        batch_specs=P("bf", None, "sp"), param_specs=specs,
        opt_state_specs=opt_specs, donate=False)
    params = F.rank_major(variables, mesh, specs=specs)
    opt_state = F.rank_major(opt.init(variables), mesh, specs=opt_specs)
    inp, tgt = _data(n_bf)
    sharding = NamedSharding(mesh, P("bf", None, "sp"))
    batch = (jax.device_put(inp, sharding), jax.device_put(tgt, sharding))
    _, _, loss = step(params, opt_state, batch, jnp.int32(0))
    loss = np.asarray(loss)
    for r in range(n_bf):
        ref = float(_plain_loss(ref_model, variables, inp[r], tgt[r]))
        np.testing.assert_allclose(loss[r], ref, rtol=1e-5, atol=1e-5)


def test_circular_pp_requires_enough_microbatches():
    from bluefog_tpu.parallel.pipeline import gpipe_circular

    from jax.sharding import Mesh
    mesh = Mesh(np.array(jax.devices()[:4]), ("pp",))

    def run(x):
        return gpipe_circular(lambda p, v: v, {"w": jnp.zeros((2, 1))},
                              x, "pp", 4, 2)

    with pytest.raises(ValueError, match="n_micro"):
        jax.shard_map(run, mesh=mesh, in_specs=P(), out_specs=P(),
                      check_vma=False)(jnp.zeros((2, 3)))


def test_pp_requires_scan_layers_and_divisibility():
    cfg = models.LlamaConfig.tiny(dtype=jnp.float32, n_layers=L)
    with pytest.raises(ValueError, match="scan_layers"):
        llama_pp_loss_fn(cfg, pp_axis="pp", n_stages=2, n_micro=2)
    cfg = models.LlamaConfig.tiny(dtype=jnp.float32, n_layers=3,
                                  scan_layers=True)
    with pytest.raises(ValueError, match="divide"):
        llama_pp_loss_fn(cfg, pp_axis="pp", n_stages=2, n_micro=2)
