"""Mixture-of-Experts FFN with expert parallelism (capability beyond the
reference, like TP/SP — SURVEY.md §2.3 lists EP as absent there).

The EP contract mirrors TP's: sharding is a LAYOUT, not a different
model — forward and gradients under ep=2 equal the unsharded model's for
the same global expert params, and the param tree is identical across EP
layouts (checkpoints portable).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bluefog_tpu import models
from bluefog_tpu.models.llama import llama_param_specs
from bluefog_tpu.optim import functional as F
from bluefog_tpu.topology import RingGraph, uniform_topology_spec

N_BF, N_EP = 4, 2
B, T = 2, 16


def _cfg(**kw):
    base = dict(dtype=jnp.float32, n_experts=4, moe_top_k=2,
                capacity_factor=2.0)
    base.update(kw)
    if base.get("moe_router") == "expert_choice":
        base.setdefault("allow_noncausal_router", True)
    return models.LlamaConfig.tiny(**base)


def test_expert_choice_requires_acknowledgement():
    """EC routing is non-causal; on this causal decoder it must be an
    explicit opt-in (ADVICE r2 medium)."""
    with pytest.raises(ValueError, match="non-causal"):
        models.LlamaConfig.tiny(n_experts=4, moe_router="expert_choice")


@pytest.fixture
def mesh():
    return Mesh(np.array(jax.devices()[:8]).reshape(N_BF, N_EP),
                ("bf", "ep"))


@pytest.mark.parametrize("router", ["topk", "expert_choice"])
def test_moe_forward_and_grads_match_single_shard(mesh, router):
    """ep=2 forward AND gradients equal ep=1 for the same global params,
    for BOTH routers (guards the f/g conjugate pair on the expert psum
    and the dynamic expert-slice dispatch; expert_choice additionally
    exercises the top_k gate gradients)."""
    m1 = models.Llama(_cfg(moe_router=router))
    m2 = models.Llama(_cfg(moe_router=router, ep_axis="ep",
                           ep_size=N_EP))
    tokens = jax.random.randint(jax.random.PRNGKey(0), (N_BF, B, T), 0, 256)
    targets = jax.random.randint(jax.random.PRNGKey(2), (N_BF, B, T), 0, 256)
    variables = m1.init(jax.random.PRNGKey(1), tokens[0])
    specs = llama_param_specs(variables, tp_axis=None, ep_axis="ep")
    params = F.rank_major(variables, mesh, specs=specs)

    def loss_of(model):
        def loss_fn(p, toks, tgt):
            logits = model.apply(p, toks)
            return jnp.mean(
                optax.softmax_cross_entropy_with_integer_labels(logits, tgt))
        return loss_fn

    def fwd_and_grad(p, toks, tgt):
        local = jax.tree.map(lambda l: l[0], p)
        loss, g = jax.value_and_grad(loss_of(m2))(local, toks[0], tgt[0])
        return loss[None], jax.tree.map(lambda l: l[None], g)

    sm = jax.shard_map(fwd_and_grad, mesh=mesh,
                       in_specs=(specs, P("bf"), P("bf")),
                       out_specs=(P("bf"), specs), check_vma=False)
    sharding = NamedSharding(mesh, P("bf"))
    loss_tp, g_tp = jax.jit(sm)(params,
                                jax.device_put(tokens, sharding),
                                jax.device_put(targets, sharding))

    for r in range(N_BF):
        ref_loss, g_ref = jax.value_and_grad(loss_of(m1))(
            variables, tokens[r], targets[r])
        np.testing.assert_allclose(np.asarray(loss_tp)[r],
                                   float(ref_loss), rtol=1e-5)
        flat_tp = jax.tree_util.tree_flatten_with_path(
            jax.tree.map(lambda l: np.asarray(l)[r], g_tp))[0]
        flat_ref = dict(jax.tree_util.tree_flatten_with_path(g_ref)[0])
        for path, got in flat_tp:
            want = np.asarray(flat_ref[path])
            scale = max(np.abs(want).max(), 1e-6)
            np.testing.assert_allclose(
                got / scale, want / scale, atol=5e-5,
                err_msg="/".join(str(getattr(k, "key", k)) for k in path))


def test_moe_param_tree_matches_dense_shapes():
    """Expert tensors carry a leading [n_experts] dim; the router is a
    plain Dense; the rest of the model is unchanged."""
    cfg = _cfg()
    m = models.Llama(cfg)
    v = m.init(jax.random.PRNGKey(0), jnp.zeros((B, T), jnp.int32))
    layer = v["params"]["layer_0"]["moe_ffn"]
    assert layer["w1"].shape == (4, cfg.dim, cfg.ffn_dim)
    assert layer["w2"].shape == (4, cfg.ffn_dim, cfg.dim)
    assert layer["router"]["kernel"].shape == (cfg.dim, 4)
    specs = llama_param_specs(v, tp_axis=None, ep_axis="ep")
    sl = specs["params"]["layer_0"]["moe_ffn"]
    assert sl["w1"] == P("bf", "ep")  # canonical: trailing Nones stripped
    assert sl["router"]["kernel"] == P("bf")


def test_moe_ep_train_step_converges(mesh):
    """dp x ep decentralized training: loss falls through the routed
    experts with ring neighbor averaging over 'bf'."""
    cfg = _cfg(ep_axis="ep", ep_size=N_EP)
    m2 = models.Llama(cfg)

    def loss_fn(params, batch):
        inp, tgt = batch
        logits = m2.apply(params, inp)
        return jnp.mean(
            optax.softmax_cross_entropy_with_integer_labels(logits, tgt))

    opt = optax.sgd(0.3)
    m1 = models.Llama(_cfg())
    variables = m1.init(jax.random.PRNGKey(1), jnp.zeros((B, T), jnp.int32))
    specs = llama_param_specs(variables, tp_axis=None, ep_axis="ep")
    params = F.rank_major(variables, mesh, specs=specs)
    opt_specs = F.optax_state_specs(opt, variables, specs)
    opt_state = F.rank_major(opt.init(variables), mesh, specs=opt_specs)

    step_fn = F.build_train_step(
        loss_fn, opt, mesh, comm_mode="cta",
        topology=uniform_topology_spec(RingGraph(N_BF)),
        param_specs=specs, opt_state_specs=opt_specs, donate=False)

    rng = np.random.RandomState(0)
    raw = rng.randint(0, 256, (N_BF, B, T + 1)).astype(np.int32)
    sharding = NamedSharding(mesh, P("bf"))
    batch = (jax.device_put(raw[:, :, :-1], sharding),
             jax.device_put(raw[:, :, 1:], sharding))

    losses = []
    for i in range(24):
        params, opt_state, loss = step_fn(params, opt_state, batch,
                                          jnp.asarray(i))
        if i % 8 == 0 or i == 23:
            losses.append(float(np.asarray(loss).mean()))
    assert losses[-1] < losses[0] * 0.9, losses


def test_moe_capacity_drops_are_deterministic():
    """With a tight capacity the same inputs produce the same outputs
    (static shapes, deterministic argmax routing — no data-dependent
    control flow)."""
    cfg = _cfg(capacity_factor=0.5)
    m = models.Llama(cfg)
    toks = jax.random.randint(jax.random.PRNGKey(0), (B, T), 0, 256)
    v = m.init(jax.random.PRNGKey(1), toks)
    a = np.asarray(m.apply(v, toks))
    b = np.asarray(m.apply(v, toks))
    np.testing.assert_array_equal(a, b)
    assert np.all(np.isfinite(a))


def test_moe_aux_loss_exposed():
    cfg = _cfg()
    m = models.Llama(cfg)
    toks = jax.random.randint(jax.random.PRNGKey(0), (B, T), 0, 256)
    v = m.init(jax.random.PRNGKey(1), toks)
    _, inter = m.apply(v, toks, mutable=["intermediates"])
    leaves = jax.tree.leaves(inter)
    # one scalar per MoE layer, >= 1 (perfect balance == 1)
    assert len(leaves) == cfg.n_layers
    assert all(float(l) >= 0.99 for l in leaves)


def test_moe_aux_loss_exposed_under_scan():
    """The scanned stack declares an intermediates axis, so the aux loss
    is retrievable under scan_layers too (it used to be silently absent
    — exactly the layout --pp forces)."""
    cfg = _cfg(scan_layers=True)
    m = models.Llama(cfg)
    toks = jax.random.randint(jax.random.PRNGKey(0), (B, T), 0, 256)
    v = m.init(jax.random.PRNGKey(1), toks)
    _, inter = m.apply(v, toks, mutable=["intermediates"])
    leaves = jax.tree.leaves(inter)
    total = sum(float(np.sum(np.asarray(l))) for l in leaves)
    n_vals = sum(np.asarray(l).size for l in leaves)
    assert n_vals == cfg.n_layers  # stacked [n_layers] instead of n leaves
    assert total / cfg.n_layers >= 0.99


def test_moe_grouped_routing_matches_ungrouped_with_ample_capacity():
    """With capacity large enough that no token is ever dropped, grouped
    routing (the O(s)-memory path) computes the SAME mixture as one
    global group: every token reaches its top-k experts with the same
    gates regardless of which slot it lands in."""
    # worst case: all G tokens of a group pick the same expert =>
    # cap >= G*top_k requires capacity_factor >= n_experts
    amp = dict(capacity_factor=4.0)  # == n_experts
    m_one = models.Llama(_cfg(moe_group_size=0, **amp))
    m_grp = models.Llama(_cfg(moe_group_size=8, **amp))
    toks = jax.random.randint(jax.random.PRNGKey(0), (B, T), 0, 256)
    v = m_one.init(jax.random.PRNGKey(1), toks)
    a = np.asarray(m_one.apply(v, toks))
    b = np.asarray(m_grp.apply(v, toks))
    np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5)


def test_routing_occupancy_contracts():
    """The routing contracts, asserted on the pure combine function:
    expert-choice fills EVERY slot of EVERY expert (dropless, perfectly
    balanced by construction); token-choice top-k assigns every token at
    most top_k slots and never exceeds any expert's capacity."""
    from bluefog_tpu.models.llama import moe_combine_weights

    g, G, E, cap, k = 3, 16, 4, 5, 2
    probs = jax.nn.softmax(
        jax.random.normal(jax.random.PRNGKey(0), (g, G, E)) * 3.0, -1)

    ec = np.asarray(moe_combine_weights(probs, k, cap, "expert_choice"))
    assert ec.shape == (g, G, E, cap)
    # every (group, expert, slot) is occupied by exactly one token
    per_slot = (ec > 0).sum(axis=1)          # [g, E, cap]
    np.testing.assert_array_equal(per_slot, 1)

    tk = np.asarray(moe_combine_weights(probs, k, cap, "topk"))
    per_token = (tk > 0).sum(axis=(2, 3))    # [g, G]
    assert per_token.max() <= k
    per_expert = (tk > 0).sum(axis=(1, 3))   # [g, E]
    assert per_expert.max() <= cap
    # ample capacity: nothing dropped, every token got all k experts
    roomy = np.asarray(moe_combine_weights(probs, k, G * k, "topk"))
    np.testing.assert_array_equal(roomy.sum(axis=(2, 3)) > 0, True)
    np.testing.assert_array_equal((roomy > 0).sum(axis=(2, 3)), k)


def test_expert_choice_deterministic():
    cfg = _cfg(moe_router="expert_choice", capacity_factor=1.0)
    m = models.Llama(cfg)
    toks = jax.random.randint(jax.random.PRNGKey(0), (B, T), 0, 256)
    v = m.init(jax.random.PRNGKey(1), toks)
    a = np.asarray(m.apply(v, toks))
    b = np.asarray(m.apply(v, toks))
    np.testing.assert_array_equal(a, b)
    assert np.all(np.isfinite(a))


def test_expert_choice_trains():
    cfg = _cfg(moe_router="expert_choice")
    m = models.Llama(cfg)
    toks = jax.random.randint(jax.random.PRNGKey(0), (B, T), 0, 256)
    tgt = jax.random.randint(jax.random.PRNGKey(2), (B, T), 0, 256)
    v = m.init(jax.random.PRNGKey(1), toks)

    def loss_fn(p):
        logits = m.apply(p, toks)
        return jnp.mean(
            optax.softmax_cross_entropy_with_integer_labels(logits, tgt))

    opt = optax.sgd(0.3)
    state = opt.init(v)
    losses = []
    for _ in range(20):
        loss, g = jax.value_and_grad(loss_fn)(v)
        updates, state = opt.update(g, state, v)
        v = optax.apply_updates(v, updates)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])


def test_moe_router_validation():
    with pytest.raises(ValueError, match="moe_router"):
        _cfg(moe_router="nope")


def test_moe_pp_loss_includes_aux():
    """Pipeline-parallel MoE training carries the load-balance signal:
    with n_micro=1 the psum'd pp loss equals plain CE + w * total aux
    exactly (each stage contributes its own layers' aux)."""
    from bluefog_tpu.models.llama import llama_pp_loss_fn

    n_bf, n_pp = 2, 2
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(n_bf, n_pp),
                ("bf", "pp"))
    w = 0.5
    cfg = _cfg(scan_layers=True, moe_aux_weight=w)
    m = models.Llama(cfg)
    variables = m.init(jax.random.PRNGKey(1), jnp.zeros((B, 8), jnp.int32))
    specs = llama_param_specs(variables, tp_axis=None, ep_axis=None,
                              pp_axis="pp")
    opt = optax.sgd(0.1)
    opt_specs = F.optax_state_specs(opt, variables, specs)
    step = F.build_train_step(
        llama_pp_loss_fn(cfg, pp_axis="pp", n_stages=n_pp, n_micro=1),
        opt, mesh, comm_mode="none", pp_axis="pp", batch_specs=P("bf"),
        param_specs=specs, opt_state_specs=opt_specs, donate=False)
    params = F.rank_major(variables, mesh, specs=specs)
    opt_state = F.rank_major(opt.init(variables), mesh, specs=opt_specs)
    rng = np.random.RandomState(0)
    raw = rng.randint(0, 256, (n_bf, B, T + 1)).astype(np.int32)
    sharding = NamedSharding(mesh, P("bf"))
    batch = (jax.device_put(raw[:, :, :-1], sharding),
             jax.device_put(raw[:, :, 1:], sharding))
    _, _, loss = step(params, opt_state, batch, jnp.int32(0))
    loss = np.asarray(loss)

    for r in range(n_bf):
        logits, inter = m.apply(variables, raw[r, :, :-1],
                                mutable=["intermediates"])
        ce = float(jnp.mean(optax.softmax_cross_entropy_with_integer_labels(
            logits, raw[r, :, 1:])))
        aux = sum(float(np.sum(np.asarray(l)))
                  for l in jax.tree.leaves(inter))
        np.testing.assert_allclose(loss[r], ce + w * aux, rtol=1e-5,
                                   atol=1e-5)
