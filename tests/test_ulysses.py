"""Ulysses (all-to-all) sequence parallelism vs dense reference — the
second SP flavor beside ring attention (parallel/ulysses.py).
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bluefog_tpu import models
from bluefog_tpu.optim import functional as F
from bluefog_tpu.parallel.ring_attention import full_attention
from bluefog_tpu.parallel.ulysses import ulysses_attention

N = 2  # sp ways (tiny config has 2 KV heads — the ulysses ceiling)


def _qkv(key, b, t, h, hkv, d, dtype=jnp.float32):
    k1, k2, k3 = jax.random.split(key, 3)
    return (jax.random.normal(k1, (b, t, h, d), dtype),
            jax.random.normal(k2, (b, t, hkv, d), dtype),
            jax.random.normal(k3, (b, t, hkv, d), dtype))


def _sharded(causal=True, n=N, impl="xla"):
    mesh = Mesh(np.array(jax.devices()[:n]), ("sp",))
    return jax.jit(jax.shard_map(
        lambda q, k, v: ulysses_attention(q, k, v, "sp", causal=causal,
                                          impl=impl),
        mesh=mesh,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"),
        check_vma=False,
    ))


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("hkv", [4, 2])
def test_ulysses_matches_full(causal, hkv):
    b, t, h, d = 2, 16 * N, 4, 16
    q, k, v = _qkv(jax.random.PRNGKey(1), b, t, h, hkv, d)
    ref = full_attention(q, k, v, causal=causal)
    out = _sharded(causal)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_ulysses_gradients_match_full():
    b, t, h, d = 1, 8 * N, 4, 8
    q, k, v = _qkv(jax.random.PRNGKey(2), b, t, h, 2, d)
    mesh = Mesh(np.array(jax.devices()[:N]), ("sp",))
    sm = jax.shard_map(
        lambda q, k, v: ulysses_attention(q, k, v, "sp", causal=True),
        mesh=mesh, in_specs=(P(None, "sp"),) * 3, out_specs=P(None, "sp"),
        check_vma=False)
    g_uly = jax.grad(lambda q: jnp.sum(sm(q, k, v) ** 2))(q)
    g_ref = jax.grad(lambda q: jnp.sum(
        full_attention(q, k, v, causal=True) ** 2))(q)
    np.testing.assert_allclose(np.asarray(g_uly), np.asarray(g_ref),
                               rtol=1e-4, atol=1e-4)


def test_ulysses_flash_impl_matches_full():
    """impl='flash' runs the Pallas kernel over the full sequence per
    head shard (interpret mode on CPU) — same numbers."""
    b, t, h, d = 1, 16 * N, 4, 16
    q, k, v = _qkv(jax.random.PRNGKey(5), b, t, h, 2, d)
    ref = full_attention(q, k, v, causal=True)
    out = _sharded(impl="flash")(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_ulysses_rejects_indivisible_heads():
    q, k, v = _qkv(jax.random.PRNGKey(3), 1, 8 * N, 3, 3, 8)
    with pytest.raises(ValueError, match="divide"):
        _sharded()(q, k, v)


def test_ulysses_hlo_two_all_to_alls_no_permute():
    """The wire pattern is the point: all-to-alls only (q/k/v in, out
    back), zero collective-permutes — genuinely different from ring
    attention's n-1 nearest-neighbor hops."""
    b, t, h, d = 1, 8 * N, 4, 8
    q, k, v = _qkv(jax.random.PRNGKey(4), b, t, h, 2, d)
    hlo = _sharded().lower(q, k, v).compile().as_text()
    n_a2a = len(re.findall(r"all-to-all(?:-start)?\(", hlo))
    n_perm = len(re.findall(r"collective-permute(?:-start)?\(", hlo))
    assert n_a2a >= 2, hlo.count("all-to-all")
    assert n_perm == 0


def test_llama_ulysses_trains_dp_x_sp():
    """dp x sp train step with attn_mode='ulysses': same wiring as ring
    (build_train_step(sp_axis=...)), loss matches the unsharded model."""
    n_bf, n_sp = 4, 2
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(n_bf, n_sp),
                ("bf", "sp"))
    B, T = 2, 32
    t_local = T // n_sp
    cfg = models.LlamaConfig.tiny(dtype=jnp.float32, attn_mode="ulysses",
                                  sp_axis="sp")
    plain = models.LlamaConfig.tiny(dtype=jnp.float32)
    model, ref_model = models.Llama(cfg), models.Llama(plain)
    variables = ref_model.init(jax.random.PRNGKey(1),
                               jnp.zeros((B, 8), jnp.int32))

    def loss_fn(params, batch):
        inp, tgt = batch
        offset = jax.lax.axis_index("sp") * t_local
        logits = model.apply(params, inp, pos_offset=offset)
        return jnp.mean(
            optax.softmax_cross_entropy_with_integer_labels(logits, tgt))

    opt = optax.sgd(0.1)
    step = F.build_train_step(loss_fn, opt, mesh, comm_mode="none",
                              sp_axis="sp",
                              batch_specs=P("bf", None, "sp"),
                              donate=False)
    params = F.rank_major(variables, mesh)
    opt_state = F.rank_major(opt.init(variables), mesh)
    raw = np.random.RandomState(0).randint(
        0, 256, (n_bf, B, T + 1)).astype(np.int32)
    sharding = NamedSharding(mesh, P("bf", None, "sp"))
    batch = (jax.device_put(raw[:, :, :-1], sharding),
             jax.device_put(raw[:, :, 1:], sharding))
    _, _, loss = step(params, opt_state, batch, jnp.int32(0))
    loss = np.asarray(loss)
    for r in range(n_bf):
        logits = ref_model.apply(variables, raw[r, :, :-1])
        ref = float(jnp.mean(optax.softmax_cross_entropy_with_integer_labels(
            logits, raw[r, :, 1:])))
        np.testing.assert_allclose(loss[r], ref, rtol=1e-5, atol=1e-5)
