"""Ring/blockwise attention vs dense reference (sequence parallelism)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bluefog_tpu.parallel.ring_attention import (
    blockwise_attention,
    full_attention,
    ring_attention,
)


def _qkv(key, b, t, h, hkv, d, dtype=jnp.float32):
    k1, k2, k3 = jax.random.split(key, 3)
    q = jax.random.normal(k1, (b, t, h, d), dtype)
    k = jax.random.normal(k2, (b, t, hkv, d), dtype)
    v = jax.random.normal(k3, (b, t, hkv, d), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("hkv", [4, 2])
def test_blockwise_matches_full(causal, hkv):
    q, k, v = _qkv(jax.random.PRNGKey(0), 2, 64, 4, hkv, 16)
    ref = full_attention(q, k, v, causal=causal)
    out = blockwise_attention(q, k, v, block_size=16, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("hkv", [4, 2])
def test_ring_matches_full(causal, hkv):
    n = 8
    b, t, h, d = 2, 8 * n, 4, 16
    q, k, v = _qkv(jax.random.PRNGKey(1), b, t, h, hkv, d)
    ref = full_attention(q, k, v, causal=causal)

    mesh = Mesh(np.array(jax.devices()[:n]), ("sp",))
    ring = jax.jit(jax.shard_map(
        lambda q, k, v: ring_attention(q, k, v, "sp", causal=causal),
        mesh=mesh,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"),
        check_vma=False,
    ))
    out = ring(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_blockwise_gradients_match_full():
    """Causal blockwise must stay reverse-mode differentiable (static
    per-q-block loop bounds) and agree with dense grads."""
    q, k, v = _qkv(jax.random.PRNGKey(3), 1, 64, 4, 2, 8)
    g_blk = jax.grad(lambda q: jnp.sum(
        blockwise_attention(q, k, v, 16, causal=True) ** 2))(q)
    g_full = jax.grad(lambda q: jnp.sum(
        full_attention(q, k, v, causal=True) ** 2))(q)
    np.testing.assert_allclose(np.asarray(g_blk), np.asarray(g_full),
                               rtol=1e-4, atol=1e-4)


def test_ring_flash_matches_full():
    """Ring attention over the Pallas kernel (lse-merged partials) agrees
    with dense attention."""
    n = 4
    b, t, h, d = 1, 16 * n, 4, 16
    q, k, v = _qkv(jax.random.PRNGKey(5), b, t, h, 2, d)
    ref = full_attention(q, k, v, causal=True)
    mesh = Mesh(np.array(jax.devices()[:n]), ("sp",))
    out = jax.jit(jax.shard_map(
        lambda q, k, v: ring_attention(q, k, v, "sp", causal=True,
                                       impl="flash"),
        mesh=mesh,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"),
        check_vma=False,
    ))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_llama_flash_impl_matches_xla():
    from bluefog_tpu import models

    cfg_x = models.LlamaConfig.tiny(dtype=jnp.float32)
    # attn_flash_block_size=16 over t=32: exercises MULTI-BLOCK flash
    # (online-softmax accumulation across k blocks), which the 1024
    # default would clamp away at test sizes
    cfg_f = models.LlamaConfig.tiny(dtype=jnp.float32, attn_impl="flash",
                                    attn_flash_block_size=16)
    toks = jax.random.randint(jax.random.PRNGKey(0), (2, 32), 0,
                              cfg_x.vocab_size)
    m_x, m_f = models.Llama(cfg_x), models.Llama(cfg_f)
    params = m_x.init(jax.random.PRNGKey(1), toks)
    np.testing.assert_allclose(
        np.asarray(m_f.apply(params, toks)),
        np.asarray(m_x.apply(params, toks)), rtol=2e-4, atol=2e-4)


def test_ring_gradients_match_full():
    """d(sum(attn))/dq must agree between ring and dense paths."""
    n = 4
    b, t, h, d = 1, 4 * n, 2, 8
    q, k, v = _qkv(jax.random.PRNGKey(2), b, t, h, h, d)
    mesh = Mesh(np.array(jax.devices()[:n]), ("sp",))

    def loss_ring(q, k, v):
        sm = jax.shard_map(
            lambda q, k, v: ring_attention(q, k, v, "sp"),
            mesh=mesh,
            in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
            out_specs=P(None, "sp"),
            check_vma=False,
        )
        return jnp.sum(sm(q, k, v) ** 2)

    def loss_full(q, k, v):
        return jnp.sum(full_attention(q, k, v) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_full = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    for gr, gf in zip(g_ring, g_full):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gf),
                                   rtol=1e-4, atol=1e-4)


def test_ring_flash_backward_matches_full():
    """The ring-level Pallas VJP: gradients of sequence-sharded
    ring+flash attention == gradients of dense single-device attention
    (dQ, dK, dV, all GQA-narrow)."""
    n = 4
    b, t, h, h_kv, d = 2, 32, 4, 2, 16
    t_local = t // n
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(b, t, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, t, h_kv, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, t, h_kv, d), jnp.float32)
    g = jnp.asarray(rng.randn(b, t, h, d), jnp.float32)

    def dense_loss(q, k, v):
        return jnp.sum(full_attention(q, k, v, causal=True) * g)

    ref_grads = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)

    mesh = Mesh(np.array(jax.devices()[:n]), ("sp",))

    def ring_loss(q, k, v, g):
        # local per-shard loss: the global loss is the implicit sum over
        # shards, and reverse-mode routes cross-shard dK/dV cotangents
        # through the ppermute VJPs (psum-ing here would double-count —
        # psum's VJP is psum, scaling every cotangent by n)
        out = ring_attention(q, k, v, "sp", causal=True, impl="flash")
        return jnp.sum(out * g)

    def shard_grads(q, k, v, g):
        return jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v, g)

    spec = P(None, "sp")
    out_grads = jax.jit(jax.shard_map(
        shard_grads, mesh=mesh, in_specs=(spec,) * 4,
        out_specs=(spec,) * 3, check_vma=False))(q, k, v, g)
    for got, ref in zip(out_grads, ref_grads):
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)


def test_ring_flash_trains_in_llama():
    """End-to-end: a tiny ring+flash Llama takes a training step under
    dp x sp without error and the loss decreases."""
    import optax
    from bluefog_tpu.optim import functional as F
    from bluefog_tpu.context import _uniform_topology_spec
    from bluefog_tpu.topology.graphs import RingGraph
    from bluefog_tpu import models

    n_dp, n_sp = 2, 4
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(n_dp, n_sp),
                ("bf", "sp"))
    cfg = models.LlamaConfig.tiny(dtype=jnp.float32, attn_mode="ring",
                                  sp_axis="sp", attn_impl="flash")
    model = models.Llama(cfg)
    t_total = 32
    t_local = t_total // n_sp
    raw = np.random.RandomState(0).randint(
        0, cfg.vocab_size, (n_dp, 2, t_total + 1)).astype(np.int32)
    inputs, targets = raw[:, :, :-1], raw[:, :, 1:]

    def loss_fn(params, batch):
        inp, tgt = batch
        logits = model.apply(params, inp,
                             pos_offset=jax.lax.axis_index("sp") * t_local)
        return jnp.mean(
            optax.softmax_cross_entropy_with_integer_labels(logits, tgt))

    spec = _uniform_topology_spec(RingGraph(n_dp))
    step_fn = F.build_train_step(
        loss_fn, optax.adam(1e-3), mesh, comm_mode="atc", topology=spec,
        sp_axis="sp", batch_specs=P("bf", None, "sp"))
    base = models.Llama(models.LlamaConfig.tiny(dtype=jnp.float32)).init(
        jax.random.PRNGKey(0), inputs[0, :, :8])
    params = F.rank_major(base, mesh)
    opt_state = F.rank_major(optax.adam(1e-3).init(base), mesh)
    sharding = NamedSharding(mesh, P("bf", None, "sp"))
    batch = (jax.device_put(inputs, sharding),
             jax.device_put(targets, sharding))
    losses = []
    for i in range(6):
        params, opt_state, loss = step_fn(params, opt_state, batch,
                                          jnp.int32(i))
        losses.append(float(np.asarray(loss).mean()))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
