"""Ring/blockwise attention vs dense reference (sequence parallelism)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from bluefog_tpu.parallel.ring_attention import (
    blockwise_attention,
    full_attention,
    ring_attention,
)


def _qkv(key, b, t, h, hkv, d, dtype=jnp.float32):
    k1, k2, k3 = jax.random.split(key, 3)
    q = jax.random.normal(k1, (b, t, h, d), dtype)
    k = jax.random.normal(k2, (b, t, hkv, d), dtype)
    v = jax.random.normal(k3, (b, t, hkv, d), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("hkv", [4, 2])
def test_blockwise_matches_full(causal, hkv):
    q, k, v = _qkv(jax.random.PRNGKey(0), 2, 64, 4, hkv, 16)
    ref = full_attention(q, k, v, causal=causal)
    out = blockwise_attention(q, k, v, block_size=16, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("hkv", [4, 2])
def test_ring_matches_full(causal, hkv):
    n = 8
    b, t, h, d = 2, 8 * n, 4, 16
    q, k, v = _qkv(jax.random.PRNGKey(1), b, t, h, hkv, d)
    ref = full_attention(q, k, v, causal=causal)

    mesh = Mesh(np.array(jax.devices()[:n]), ("sp",))
    ring = jax.jit(jax.shard_map(
        lambda q, k, v: ring_attention(q, k, v, "sp", causal=causal),
        mesh=mesh,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"),
        check_vma=False,
    ))
    out = ring(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_blockwise_gradients_match_full():
    """Causal blockwise must stay reverse-mode differentiable (static
    per-q-block loop bounds) and agree with dense grads."""
    q, k, v = _qkv(jax.random.PRNGKey(3), 1, 64, 4, 2, 8)
    g_blk = jax.grad(lambda q: jnp.sum(
        blockwise_attention(q, k, v, 16, causal=True) ** 2))(q)
    g_full = jax.grad(lambda q: jnp.sum(
        full_attention(q, k, v, causal=True) ** 2))(q)
    np.testing.assert_allclose(np.asarray(g_blk), np.asarray(g_full),
                               rtol=1e-4, atol=1e-4)


def test_ring_flash_matches_full():
    """Ring attention over the Pallas kernel (lse-merged partials) agrees
    with dense attention."""
    n = 4
    b, t, h, d = 1, 16 * n, 4, 16
    q, k, v = _qkv(jax.random.PRNGKey(5), b, t, h, 2, d)
    ref = full_attention(q, k, v, causal=True)
    mesh = Mesh(np.array(jax.devices()[:n]), ("sp",))
    out = jax.jit(jax.shard_map(
        lambda q, k, v: ring_attention(q, k, v, "sp", causal=True,
                                       impl="flash"),
        mesh=mesh,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"),
        check_vma=False,
    ))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_llama_flash_impl_matches_xla():
    from bluefog_tpu import models

    cfg_x = models.LlamaConfig.tiny(dtype=jnp.float32)
    cfg_f = models.LlamaConfig.tiny(dtype=jnp.float32, attn_impl="flash",
                                    attn_block_size=16)
    toks = jax.random.randint(jax.random.PRNGKey(0), (2, 32), 0,
                              cfg_x.vocab_size)
    m_x, m_f = models.Llama(cfg_x), models.Llama(cfg_f)
    params = m_x.init(jax.random.PRNGKey(1), toks)
    np.testing.assert_allclose(
        np.asarray(m_f.apply(params, toks)),
        np.asarray(m_x.apply(params, toks)), rtol=2e-4, atol=2e-4)


def test_ring_gradients_match_full():
    """d(sum(attn))/dq must agree between ring and dense paths."""
    n = 4
    b, t, h, d = 1, 4 * n, 2, 8
    q, k, v = _qkv(jax.random.PRNGKey(2), b, t, h, h, d)
    mesh = Mesh(np.array(jax.devices()[:n]), ("sp",))

    def loss_ring(q, k, v):
        sm = jax.shard_map(
            lambda q, k, v: ring_attention(q, k, v, "sp"),
            mesh=mesh,
            in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
            out_specs=P(None, "sp"),
            check_vma=False,
        )
        return jnp.sum(sm(q, k, v) ** 2)

    def loss_full(q, k, v):
        return jnp.sum(full_attention(q, k, v) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_full = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    for gr, gf in zip(g_ring, g_full):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gf),
                                   rtol=1e-4, atol=1e-4)
