"""Ring/blockwise attention vs dense reference (sequence parallelism)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from bluefog_tpu.parallel.ring_attention import (
    blockwise_attention,
    full_attention,
    ring_attention,
)


def _qkv(key, b, t, h, hkv, d, dtype=jnp.float32):
    k1, k2, k3 = jax.random.split(key, 3)
    q = jax.random.normal(k1, (b, t, h, d), dtype)
    k = jax.random.normal(k2, (b, t, hkv, d), dtype)
    v = jax.random.normal(k3, (b, t, hkv, d), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("hkv", [4, 2])
def test_blockwise_matches_full(causal, hkv):
    q, k, v = _qkv(jax.random.PRNGKey(0), 2, 64, 4, hkv, 16)
    ref = full_attention(q, k, v, causal=causal)
    out = blockwise_attention(q, k, v, block_size=16, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("hkv", [4, 2])
def test_ring_matches_full(causal, hkv):
    n = 8
    b, t, h, d = 2, 8 * n, 4, 16
    q, k, v = _qkv(jax.random.PRNGKey(1), b, t, h, hkv, d)
    ref = full_attention(q, k, v, causal=causal)

    mesh = Mesh(np.array(jax.devices()[:n]), ("sp",))
    ring = jax.jit(jax.shard_map(
        lambda q, k, v: ring_attention(q, k, v, "sp", causal=causal),
        mesh=mesh,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"),
        check_vma=False,
    ))
    out = ring(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_blockwise_gradients_match_full():
    """Causal blockwise must stay reverse-mode differentiable (static
    per-q-block loop bounds) and agree with dense grads."""
    q, k, v = _qkv(jax.random.PRNGKey(3), 1, 64, 4, 2, 8)
    g_blk = jax.grad(lambda q: jnp.sum(
        blockwise_attention(q, k, v, 16, causal=True) ** 2))(q)
    g_full = jax.grad(lambda q: jnp.sum(
        full_attention(q, k, v, causal=True) ** 2))(q)
    np.testing.assert_allclose(np.asarray(g_blk), np.asarray(g_full),
                               rtol=1e-4, atol=1e-4)


def test_ring_gradients_match_full():
    """d(sum(attn))/dq must agree between ring and dense paths."""
    n = 4
    b, t, h, d = 1, 4 * n, 2, 8
    q, k, v = _qkv(jax.random.PRNGKey(2), b, t, h, h, d)
    mesh = Mesh(np.array(jax.devices()[:n]), ("sp",))

    def loss_ring(q, k, v):
        sm = jax.shard_map(
            lambda q, k, v: ring_attention(q, k, v, "sp"),
            mesh=mesh,
            in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
            out_specs=P(None, "sp"),
            check_vma=False,
        )
        return jnp.sum(sm(q, k, v) ** 2)

    def loss_full(q, k, v):
        return jnp.sum(full_attention(q, k, v) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_full = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    for gr, gf in zip(g_ring, g_full):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gf),
                                   rtol=1e-4, atol=1e-4)
