"""The example notebooks must EXECUTE, not just render (reference
parity: examples/*.ipynb are the interactive on-ramp; round-2 verdict
'missing' item 3).  Each runs in its own kernel from a scratch cwd."""

import os
import shutil

import pytest

nbclient = pytest.importorskip("nbclient")
nbformat = pytest.importorskip("nbformat")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(name, tmp_path, extra_env=None):
    src = os.path.join(REPO, "examples", name)
    dst = tmp_path / name
    shutil.copy(src, dst)
    nb = nbformat.read(str(dst), as_version=4)
    env = dict(os.environ, PYTHONPATH=REPO)
    env.pop("JAX_PLATFORMS", None)
    env.update(extra_env or {})
    old = dict(os.environ)
    os.environ.clear()
    os.environ.update(env)
    try:
        client = nbclient.NotebookClient(
            nb, timeout=600, kernel_name="python3",
            resources={"metadata": {"path": str(tmp_path)}})
        client.execute()
    finally:
        os.environ.clear()
        os.environ.update(old)
    return nb


def test_decentralized_consensus_notebook(tmp_path):
    nb = _run("decentralized_consensus.ipynb", tmp_path,
              extra_env={"JAX_PLATFORMS": "cpu"})
    outputs = "\n".join(
        "".join(o.get("text", "") for o in c.get("outputs", []))
        for c in nb.cells if c.cell_type == "code")
    assert "8 ranks" in outputs
    assert "done" in outputs


def test_interactive_helloworld_notebook(tmp_path):
    nb = _run("interactive_helloworld.ipynb", tmp_path,
              extra_env={"JAX_PLATFORMS": "cpu"})
    outputs = "\n".join(
        "".join(o.get("text", "") for o in c.get("outputs", []))
        for c in nb.cells if c.cell_type == "code")
    assert outputs.count("Hello, I am process") == 2
    assert "all ranks agree" in outputs
    assert "cluster stopped" in outputs
