"""Inference quantization: int8 K/V cache + weight-only int8 decode.

Contract under test (models/quant.py, models/llama.py QuantDense /
kv_quant): quantized decode must track the full-precision decode — same
greedy tokens on well-separated logits, logits within a small tolerance —
while the cache/param trees actually carry int8 (the whole point is HBM
bytes).  The reference framework is training-only, so this surface has
no reference counterpart; the contract is internal consistency with our
own full-precision path.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bluefog_tpu import models
from bluefog_tpu.models import (LlamaConfig, llama_generate,
                                quantize_llama_params)
from bluefog_tpu.models.generate import init_cache
from bluefog_tpu.models.quant import QUANT_KERNELS, is_quantized_params


@pytest.fixture(scope="module")
def trained():
    cfg = LlamaConfig.tiny(max_seq_len=96)
    model = models.Llama(cfg)
    variables = model.init(jax.random.PRNGKey(7),
                           jnp.zeros((2, 8), jnp.int32))
    prompt = jnp.asarray(
        np.random.RandomState(3).randint(0, cfg.vocab_size, (2, 12)),
        jnp.int32)
    return cfg, variables, prompt


def _logits_one_step(variables, cfg, prompt, **quant):
    """Prefill the prompt and return the next-token logits by running
    generate with max_new_tokens=1 through the model's decode apply."""
    kv = quant.get("kv_quant", "none")
    wq = quant.get("weight_quant", "none")
    from bluefog_tpu.models.generate import _decode_cfg

    dcfg = _decode_cfg(cfg, prompt.shape[1] + 1, kv_quant=kv,
                       weight_quant=wq)
    model = models.Llama(dcfg)
    cache = init_cache(dcfg, prompt.shape[0], prompt.shape[1] + 1,
                       kv_quant=kv)
    logits, _ = model.apply({**variables, "cache": cache}, prompt,
                            mutable=["cache"])
    return logits[:, -1]


def test_quantize_params_structure(trained):
    cfg, variables, _ = trained
    qvars = quantize_llama_params(variables)
    assert is_quantized_params(qvars)
    assert not is_quantized_params(variables)
    wq = qvars["params"]["layer_0"]["attention"]["wq"]
    assert wq["kernel"].dtype == jnp.int8
    assert wq["scale"].dtype == jnp.float32
    assert wq["scale"].shape == (wq["kernel"].shape[-1],)
    # embeddings stay full precision
    emb = qvars["params"]["tok_embeddings"]["embedding"]
    assert emb.dtype == jnp.float32
    # dequantized kernel reproduces the original within one int8 step
    orig = variables["params"]["layer_0"]["attention"]["wq"]["kernel"]
    deq = wq["kernel"].astype(jnp.float32) * wq["scale"][None, :]
    assert float(jnp.max(jnp.abs(deq - orig))) <= \
        float(jnp.max(wq["scale"])) * 0.5 + 1e-8


def test_quantize_params_scanned_layout():
    cfg = LlamaConfig.tiny(scan_layers=True)
    variables = models.Llama(cfg).init(jax.random.PRNGKey(0),
                                       jnp.zeros((1, 8), jnp.int32))
    qvars = quantize_llama_params(variables)
    wq = qvars["params"]["layers"]["block"]["attention"]["wq"]
    assert wq["kernel"].dtype == jnp.int8
    # per-layer scales: leading layer axis preserved
    assert wq["scale"].shape == (cfg.n_layers, wq["kernel"].shape[-1])


def test_kv_int8_cache_is_int8(trained):
    cfg, _, _ = trained
    cache = init_cache(cfg, 2, 32, kv_quant="int8")
    leaves = jax.tree_util.tree_leaves_with_path(cache)
    kinds = {str(p[-1].key): l.dtype for p, l in leaves}
    assert kinds["cached_key"] == jnp.int8
    assert kinds["cached_value"] == jnp.int8
    assert kinds["cached_key_scale"] == jnp.float32


def test_kv_int8_logits_close(trained):
    cfg, variables, prompt = trained
    ref = _logits_one_step(variables, cfg, prompt)
    got = _logits_one_step(variables, cfg, prompt, kv_quant="int8")
    # int8 per-vector K/V: logits drift bounded by the quant noise
    assert float(jnp.max(jnp.abs(got - ref))) < 0.15 * (
        1.0 + float(jnp.max(jnp.abs(ref))))


@pytest.mark.parametrize("mode", ["int8", "w8a8"])
def test_weight_quant_logits_close(trained, mode):
    cfg, variables, prompt = trained
    qvars = quantize_llama_params(variables)
    ref = _logits_one_step(variables, cfg, prompt)
    got = _logits_one_step(qvars, cfg, prompt, weight_quant=mode)
    assert float(jnp.max(jnp.abs(got - ref))) < 0.15 * (
        1.0 + float(jnp.max(jnp.abs(ref))))


@pytest.mark.parametrize("mode", ["int8", "w8a8"])
def test_quant_generate_matches_full_precision_tokens(trained, mode):
    """Covers the full quantized decode per mode — for w8a8 that
    includes QuantDense's dynamic activation quant AND the
    fully-integer attention (_cached_attention_int8, both s8xs8
    contractions with the scale transposes)."""
    cfg, variables, prompt = trained
    full = llama_generate(variables, cfg, prompt, 16)
    qvars = quantize_llama_params(variables)
    both = llama_generate(qvars, cfg, prompt, 16, kv_quant="int8",
                          weight_quant=mode)
    full, both = np.asarray(full), np.asarray(both)
    assert full.shape == both.shape
    # prompts echo exactly; greedy tokens track closely (quant noise can
    # flip near-ties, so require agreement on the first steps and a high
    # overall match instead of exact equality)
    np.testing.assert_array_equal(full[:, :prompt.shape[1]],
                                  both[:, :prompt.shape[1]])
    gen_f = full[:, prompt.shape[1]:]
    gen_q = both[:, prompt.shape[1]:]
    assert (gen_f[:, 0] == gen_q[:, 0]).all()
    # beyond the first step the rollout is chaotic on this random-init
    # model (one near-tie flip changes all later context), so the
    # agreement fraction mostly measures WHEN the first flip lands;
    # logits closeness per mode is asserted separately above
    assert (gen_f == gen_q).mean() > 0.5


def test_weight_quant_tree_mismatch_raises(trained):
    cfg, variables, prompt = trained
    with pytest.raises(ValueError, match="quantize_llama_params"):
        llama_generate(variables, cfg, prompt, 2, weight_quant="int8")
    qvars = quantize_llama_params(variables)
    with pytest.raises(ValueError, match="mismatched"):
        llama_generate(qvars, cfg, prompt, 2)


def test_quant_config_guards():
    with pytest.raises(ValueError, match="decode"):
        LlamaConfig.tiny(kv_quant="int8")
    with pytest.raises(ValueError, match="inference-only"):
        LlamaConfig.tiny(param_quant="int8")
    with pytest.raises(ValueError, match="kv_quant"):
        LlamaConfig.tiny(kv_quant="fp4", decode=True)


def test_tp_sharded_quant_decode(trained):
    """weight_quant + kv_quant compose with the tp-sharded decode path:
    per-output-channel scales shard with their kernel's output dim
    (llama_param_specs), and the sharded program reproduces the
    replicated one's tokens."""
    cfg0, _, _ = trained
    cfg = dataclasses.replace(cfg0, tp_axis="tp", tp_size=2)
    model = models.Llama(dataclasses.replace(cfg0))
    variables = model.init(jax.random.PRNGKey(7),
                           jnp.zeros((2, 8), jnp.int32))
    prompt = jnp.asarray(
        np.random.RandomState(3).randint(0, cfg.vocab_size, (2, 12)),
        jnp.int32)
    qvars = quantize_llama_params(variables)
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))
    ref = llama_generate(qvars, cfg0, prompt, 8, kv_quant="int8",
                         weight_quant="int8")
    got = llama_generate(qvars, cfg, prompt, 8, mesh=mesh,
                         kv_quant="int8", weight_quant="int8")
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))


def test_w8a8_attention_int8_logits_close(trained):
    """_cached_attention_int8 in isolation (multi-token prefill + one
    step): w8a8 + int8 kv logits track the fully-unquantized path."""
    cfg, variables, prompt = trained
    qvars = quantize_llama_params(variables)
    ref = _logits_one_step(variables, cfg, prompt)
    got = _logits_one_step(qvars, cfg, prompt, kv_quant="int8",
                           weight_quant="w8a8")
    err = float(jnp.max(jnp.abs(got - ref)))
    assert err < 0.2 * (1.0 + float(jnp.max(jnp.abs(ref)))), err
    # argmax (the sampled token) must agree
    assert (jnp.argmax(got, -1) == jnp.argmax(ref, -1)).all()
