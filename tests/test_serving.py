"""Continuous-batching serving engine (bluefog_tpu/serving/).

Contract under test: the engine is a pure SCHEDULING layer over the
one-shot decode substrate — for any arrival pattern, every request's
output is token-exact with its own one-shot
``llama_generate(prompt[None], n, max_len=pool_max_len)`` call.  Plus
the serving behaviors that make it an engine rather than a loop: slot
reuse, EOS retirement, deadline cancellation, pool-full backpressure,
metrics, and timeline spans.
"""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bluefog_tpu import models
from bluefog_tpu.models import llama_generate
from bluefog_tpu.serving import (FifoScheduler, Request, RequestRejected,
                                 ServingEngine, SlotPool)

pytestmark = pytest.mark.serving

MAX_LEN = 48


class VirtualClock:
    """Deterministic engine clock: tests advance time explicitly, so
    deadline behavior and latency percentiles are reproducible."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _setup(**cfg_overrides):
    cfg = models.LlamaConfig.tiny(dtype=jnp.float32, **cfg_overrides)
    variables = models.Llama(cfg).init(jax.random.PRNGKey(1),
                                       jnp.zeros((2, 4), jnp.int32))
    return cfg, variables


def _one_shot(variables, cfg, prompt, n, **kw):
    out = llama_generate(variables, cfg, jnp.asarray(prompt[None]), n,
                         max_len=MAX_LEN, **kw)
    return np.asarray(out)[0]


def _prompts(sizes, seed=0):
    rs = np.random.RandomState(seed)
    return [rs.randint(0, 256, (n,)).astype(np.int32) for n in sizes]


def test_staggered_arrivals_match_one_shot():
    """The acceptance property: requests arriving at different engine
    steps, with different prompt lengths and budgets, sharing 2 slots —
    each output equals its per-request one-shot generation exactly."""
    cfg, variables = _setup()
    prompts = _prompts((5, 9, 3, 1))
    budgets = [6, 4, 8, 5]
    eng = ServingEngine(variables, cfg, capacity=2, max_len=MAX_LEN,
                        prefill_chunk=4)
    reqs = [Request(p, b) for p, b in zip(prompts, budgets)]
    eng.submit(reqs[0])
    eng.step()
    eng.step()
    eng.submit(reqs[1])
    eng.step()
    eng.submit(reqs[2])
    eng.submit(reqs[3])
    eng.run()
    for r, p, b in zip(reqs, prompts, budgets):
        assert r.state == "completed"
        np.testing.assert_array_equal(
            r.output(), _one_shot(variables, cfg, p, b))


def test_scan_layers_layout_served():
    """Both layer layouts decode through the engine (the scanned stack
    carries a [n_layers] cache axis — slots stack outside it)."""
    cfg, variables = _setup(scan_layers=True)
    prompts = _prompts((4, 6))
    eng = ServingEngine(variables, cfg, capacity=2, max_len=MAX_LEN,
                        prefill_chunk=3)
    reqs = [eng.submit(Request(p, 5)) for p in prompts]
    eng.run()
    for r, p in zip(reqs, prompts):
        np.testing.assert_array_equal(
            r.output(), _one_shot(variables, cfg, p, 5))


def test_slot_reuse_is_invisible():
    """capacity=1: the second request reuses the first's slot and still
    matches one-shot exactly (freed slots are zeroed — reuse leaves no
    trace)."""
    cfg, variables = _setup()
    prompts = _prompts((7, 5), seed=3)
    eng = ServingEngine(variables, cfg, capacity=1, max_len=MAX_LEN,
                        prefill_chunk=4)
    r0 = eng.submit(Request(prompts[0], 6))
    eng.step()  # r0 admitted into slot 0, mid-flight
    r1 = eng.submit(Request(prompts[1], 6))
    eng.run()
    assert r0.slot is None and r1.slot is None
    assert eng.pool.n_free == 1
    for r, p in zip((r0, r1), prompts):
        np.testing.assert_array_equal(
            r.output(), _one_shot(variables, cfg, p, 6))


def test_eos_retires_slot_and_truncates():
    """A request whose stream hits its eos_id retires early: its output
    is the one-shot prefix through the first EOS, and the freed slot
    admits the next queued request."""
    cfg, variables = _setup()
    (prompt,) = _prompts((5,), seed=1)
    full = _one_shot(variables, cfg, prompt, 10)
    eos = int(full[prompt.size + 3])  # forces a stop after 4 tokens
    assert eos not in full[prompt.size:prompt.size + 3]
    eng = ServingEngine(variables, cfg, capacity=1, max_len=MAX_LEN,
                        prefill_chunk=4)
    r0 = eng.submit(Request(prompt, 10, eos_id=eos))
    r1 = eng.submit(Request(prompt, 2))  # waits for r0's slot
    eng.run()
    assert r0.state == "completed"
    assert len(r0.tokens) == 4 and r0.tokens[-1] == eos
    np.testing.assert_array_equal(r0.output(), full[:prompt.size + 4])
    assert r1.state == "completed" and len(r1.tokens) == 2


def test_decode_horizon_invariant():
    """decode_horizon is pure host-overhead amortization: the emitted
    streams (including EOS truncation mid-horizon) are identical for
    every horizon, and still one-shot-exact."""
    cfg, variables = _setup()
    prompts = _prompts((5, 9, 3), seed=11)
    budgets = [7, 4, 6]
    full = _one_shot(variables, cfg, prompts[0], 10)
    eos = int(full[prompts[0].size + 2])

    def serve(horizon):
        eng = ServingEngine(variables, cfg, capacity=2, max_len=MAX_LEN,
                            prefill_chunk=4, decode_horizon=horizon)
        reqs = [Request(prompts[0], 10, eos_id=eos)] + \
            [Request(p, b) for p, b in zip(prompts[1:], budgets[1:])]
        eng.submit(reqs[0])
        eng.step()
        for r in reqs[1:]:
            eng.submit(r)
        eng.run()
        return [r.output() for r in reqs]

    a, b = serve(1), serve(4)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)
    for y, p, n in zip(b[1:], prompts[1:], budgets[1:]):
        np.testing.assert_array_equal(y, _one_shot(variables, cfg, p, n))


def test_temperature_sampling_deterministic_and_in_range():
    """Per-request sampling is a function of (seed, token index) only —
    re-serving the same request reproduces the stream, independent of
    co-batching."""
    cfg, variables = _setup()
    prompts = _prompts((5, 6), seed=7)

    def serve(reqs, capacity):
        eng = ServingEngine(variables, cfg, capacity=capacity,
                            max_len=MAX_LEN, prefill_chunk=4)
        for r in reqs:
            eng.submit(r)
        eng.run()
        return [r.output() for r in reqs]

    a = serve([Request(prompts[0], 6, temperature=0.8, seed=5),
               Request(prompts[1], 6, temperature=1.2, seed=9)], 2)
    b = serve([Request(prompts[0], 6, temperature=0.8, seed=5)], 1)
    np.testing.assert_array_equal(a[0], b[0])
    assert np.all((a[1] >= 0) & (a[1] < 256))


def test_deadline_cancels_running_and_queued():
    cfg, variables = _setup()
    clock = VirtualClock()
    prompts = _prompts((4, 4), seed=2)
    eng = ServingEngine(variables, cfg, capacity=1, max_len=MAX_LEN,
                        prefill_chunk=4, clock=clock)
    # r0 runs but can never finish 20 tokens by t=1.0 (1s per step)
    r0 = eng.submit(Request(prompts[0], 20, deadline=1.0))
    # r1 is stuck behind r0 and expires in the queue
    r1 = eng.submit(Request(prompts[1], 2, deadline=0.5))
    steps = 0
    while eng.step():
        clock.advance(1.0)
        steps += 1
        assert steps < 50
    assert r0.state == "cancelled"
    assert 0 < len(r0.tokens) < 20  # partial stream delivered
    assert r1.state == "cancelled" and r1.tokens == []
    assert eng.pool.n_free == 1  # cancelled slots come back


def test_deadline_cancels_mid_prefill():
    """Deadline contract, prefill phase: a request whose deadline
    expires while its prompt is still being chunk-prefilled (state
    'prefill', not yet decoding) is cancelled at the next step
    boundary with ZERO tokens delivered, its slot comes back, and the
    request behind it serves to one-shot exactness through the
    reclaimed slot."""
    cfg, variables = _setup()
    clock = VirtualClock()
    long_prompt, short_prompt = _prompts((17, 4), seed=5)
    # chunk=2 -> prompt[:-1] needs 8 chunks at 1 chunk/step: the
    # deadline at t=2.5 lands mid-prefill (1 s per step)
    eng = ServingEngine(variables, cfg, capacity=1, max_len=MAX_LEN,
                        prefill_chunk=2, clock=clock)
    r0 = eng.submit(Request(long_prompt, 8, deadline=2.5))
    r1 = eng.submit(Request(short_prompt, 3))
    saw_prefill = False
    steps = 0
    while eng.step():
        saw_prefill = saw_prefill or r0.state == "prefill"
        clock.advance(1.0)
        steps += 1
        assert steps < 50
    assert saw_prefill                      # it WAS mid-prefill
    assert r0.state == "cancelled"
    assert r0.tokens == [] and r0.slot is None   # never reached decode
    assert r1.state == "completed"
    assert eng.pool.n_free == 1             # the slot came back
    np.testing.assert_array_equal(
        r1.output(), _one_shot(variables, cfg, short_prompt, 3))
    m = eng.metrics.summary()
    assert m["outcomes"].get("cancelled") == 1


def test_explicit_cancellation():
    cfg, variables = _setup()
    prompts = _prompts((4, 4), seed=4)
    eng = ServingEngine(variables, cfg, capacity=1, max_len=MAX_LEN,
                        prefill_chunk=8)
    r0 = eng.submit(Request(prompts[0], 20))
    r1 = eng.submit(Request(prompts[1], 3))
    eng.step()
    assert eng.cancel(r0)   # running: retired at the next step boundary
    eng.run()
    assert r0.state == "cancelled"
    assert r1.state == "completed"
    assert not eng.cancel(r0)  # already retired


def test_pool_full_rejects_with_queue_depth():
    """Backpressure, not stalls: pool full -> queue; queue full ->
    immediate RequestRejected carrying the queue depth."""
    cfg, variables = _setup()
    (prompt,) = _prompts((4,))
    eng = ServingEngine(variables, cfg, capacity=1, max_len=MAX_LEN,
                        prefill_chunk=8, max_queue=2)
    eng.submit(Request(prompt, 4))
    eng.step()  # occupy the slot
    eng.submit(Request(prompt, 4))
    eng.submit(Request(prompt, 4))  # queue now at max_queue=2
    with pytest.raises(RequestRejected) as ei:
        eng.submit(Request(prompt, 4))
    assert ei.value.queue_depth == 2
    assert ei.value.max_queue == 2
    assert "queue depth 2/2" in str(ei.value)
    assert eng.metrics.summary()["n_rejected"] == 1
    eng.run()


def test_submit_validates_slot_capacity():
    cfg, variables = _setup()
    (prompt,) = _prompts((40,))
    eng = ServingEngine(variables, cfg, capacity=1, max_len=MAX_LEN,
                        prefill_chunk=8)
    big = Request(prompt, MAX_LEN)
    with pytest.raises(ValueError, match="cache positions"):
        eng.submit(big)
    # refusal paths agree: a request the engine will never run is
    # terminal AND counted, same as the RequestRejected backpressure
    # path — a caller polling req.done must not wait on a phantom, and
    # a dashboard must see every refusal
    assert big.state == "rejected" and big.done
    assert eng.metrics.summary()["n_rejected"] == 1
    with pytest.raises(ValueError, match="max_new_tokens"):
        Request(prompt, 0)
    # a chunk window that could cross the cache end is refused up front
    # (an overrunning dynamic_update_slice start would CLAMP, silently
    # corrupting near-max_len prompts)
    with pytest.raises(ValueError, match="divide max_len"):
        ServingEngine(variables, cfg, capacity=1, max_len=MAX_LEN,
                      prefill_chunk=32)


def test_prompt_filling_the_slot_is_exact():
    """Boundary regression: a prompt whose final prefill chunk ends
    exactly at the cache end (prompt + budget == max_len) stays
    token-exact — no chunk window crosses max_len."""
    cfg, variables = _setup()
    (prompt,) = _prompts((MAX_LEN - 6,), seed=12)  # 42 tokens, 6 budget
    eng = ServingEngine(variables, cfg, capacity=1, max_len=MAX_LEN,
                        prefill_chunk=8)
    r = eng.submit(Request(prompt, 6))
    eng.run()
    np.testing.assert_array_equal(
        r.output(), _one_shot(variables, cfg, prompt, 6))


def test_quantized_interop_matches_one_shot():
    """int8 weights + int8 K/V slots serve through the engine and match
    the equally-quantized one-shot path (models/quant.py interop)."""
    from bluefog_tpu.models.quant import quantize_llama_params

    cfg, variables = _setup()
    qvars = quantize_llama_params(variables)
    prompts = _prompts((5, 7), seed=6)
    eng = ServingEngine(qvars, cfg, capacity=2, max_len=MAX_LEN,
                        prefill_chunk=4, kv_quant="int8",
                        weight_quant="int8")
    reqs = [eng.submit(Request(p, 5)) for p in prompts]
    eng.run()
    for r, p in zip(reqs, prompts):
        want = _one_shot(qvars, cfg, p, 5, kv_quant="int8",
                         weight_quant="int8")
        np.testing.assert_array_equal(r.output(), want)
    with pytest.raises(ValueError, match="quantize_llama_params"):
        ServingEngine(variables, cfg, capacity=1, max_len=MAX_LEN,
                      weight_quant="int8")


def test_kv_pool_alloc_free():
    cfg, _ = _setup()
    pool = SlotPool(cfg, capacity=3, max_len=16)
    slots = [pool.alloc() for _ in range(3)]
    assert sorted(slots) == [0, 1, 2]
    assert pool.alloc() is None and pool.n_free == 0
    assert pool.occupancy() == 1.0
    pool.free(slots[1])
    assert pool.n_free == 1
    assert pool.alloc() == slots[1]  # freed slot comes back
    pool.free(slots[0])
    with pytest.raises(ValueError, match="not allocated"):
        pool.free(slots[0])  # double free


def test_scheduler_fifo_and_expiry():
    class R:
        def __init__(self, deadline=None):
            self.deadline = deadline

    s = FifoScheduler(max_queue=3)
    a, b, c = R(), R(deadline=1.0), R()
    for r in (a, b, c):
        s.submit(r)
    with pytest.raises(RequestRejected):
        s.submit(R())
    assert s.admit(now=2.0) is a      # FIFO
    assert s.admit(now=2.0) is c      # b expired (deadline 1.0 < 2.0)
    assert s.admit(now=2.0) is None


def test_metrics_and_timeline_spans(tmp_path):
    """TTFT/latency/occupancy land in the summary, and request
    lifecycle spans (admission -> prefill -> decode -> retire) reach the
    chrome://tracing file through the existing timeline writer."""
    from bluefog_tpu import timeline

    cfg, variables = _setup()
    clock = VirtualClock()
    path = str(tmp_path / "serve_tl")
    timeline.start_timeline(path)
    try:
        eng = ServingEngine(variables, cfg, capacity=2, max_len=MAX_LEN,
                            prefill_chunk=4, clock=clock)
        reqs = [eng.submit(Request(p, 4))
                for p in _prompts((5, 6), seed=8)]
        while eng.step():
            clock.advance(0.25)
    finally:
        timeline.stop_timeline()
    m = eng.metrics.summary()
    assert m["n_finished"] == 2
    assert m["tokens_generated"] == 8
    assert m["tokens_per_sec"] > 0
    assert 0 < m["ttft_p50"] <= m["ttft_p99"]
    assert 0 < m["latency_p50"] <= m["latency_p99"]
    assert 0 < m["mean_slot_occupancy"] <= 1.0
    events = json.load(open(path + "0.json"))
    names = {e.get("name") for e in events}
    for phase in ("admission", "prefill", "decode", "retire"):
        assert phase in names, (phase, names)
    tracks = {e.get("tid") for e in events}
    for r in reqs:
        assert f"request.{r.rid}" in tracks


def test_no_recompiles_across_arrival_patterns():
    """The continuous-batching invariant: serving different prompts,
    lengths, budgets, and arrival orders reuses the SAME compiled
    programs — shapes depend only on (capacity, max_len, chunk)."""
    from bluefog_tpu.serving.engine import (_decode_step_prog,
                                            _prefill_chunk_prog)

    cfg, variables = _setup()
    eng = ServingEngine(variables, cfg, capacity=2, max_len=MAX_LEN,
                        prefill_chunk=4)
    reqs = [eng.submit(Request(p, 3)) for p in _prompts((5, 9), seed=9)]
    eng.run()
    pre = _prefill_chunk_prog._cache_size()
    dec = _decode_step_prog._cache_size()
    reqs = [Request(p, b) for p, b in
            zip(_prompts((11, 2, 7), seed=10), (4, 6, 2))]
    eng.submit(reqs[0])
    eng.step()
    for r in reqs[1:]:
        eng.submit(r)
    eng.run()
    assert _prefill_chunk_prog._cache_size() == pre
    assert _decode_step_prog._cache_size() == dec
    assert all(r.state == "completed" for r in reqs)


def test_poisson_arrival_trace_is_deterministic():
    from bluefog_tpu.benchutil import poisson_arrivals

    a = poisson_arrivals(2.0, 16, seed=3)
    b = poisson_arrivals(2.0, 16, seed=3)
    np.testing.assert_array_equal(a, b)
    assert a.shape == (16,) and a[0] == 0.0
    assert np.all(np.diff(a) >= 0)
    assert not np.array_equal(a, poisson_arrivals(2.0, 16, seed=4))
    with pytest.raises(ValueError, match="rate"):
        poisson_arrivals(0.0, 4)


@pytest.mark.slow
def test_serving_bench_smoke(tmp_path):
    """The Poisson-load bench runs end to end and reports both engines
    (slow: out of tier-1 — the bench measures wall time)."""
    import subprocess
    import sys
    import os

    out = str(tmp_path / "bench.json")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=repo)
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "benchmarks",
                                      "serving_bench.py"),
         "--num-requests", "6", "--rate", "4", "--capacity", "2",
         "--max-len", "48", "--prompt-len", "3", "8",
         "--new-tokens", "2", "6", "--dim", "64", "--layers", "2",
         "--prefill-chunk", "4", "--out", out, "--compare", ""],
        capture_output=True, text=True, timeout=600, env=env, cwd=repo)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    rec = json.load(open(out))
    for side in ("continuous", "static"):
        assert rec[side]["tokens_per_sec"] > 0
        assert rec[side]["ttft_p99"] >= rec[side]["ttft_p50"] >= 0
