"""Fully-jitted decentralized train step (optim/functional.py).

Convergence checks mirror the reference's synthetic linear problem design
(reference test/torch_optimizer_test.py:100 LinearProblemBuilder).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bluefog_tpu.optim import functional as F
from bluefog_tpu.topology.graphs import ExponentialTwoGraph, RingGraph
from bluefog_tpu.topology.dynamic import one_peer_dynamic_schedule

N = 8
DIM = 4


def _mesh(n=N):
    return Mesh(np.array(jax.devices()[:n]), ("bf",))


def _linear_problem(seed=0):
    """Per-rank (A_r, b_r) with a common true x; global least squares."""
    rng = np.random.RandomState(seed)
    x_true = rng.randn(DIM)
    As, bs = [], []
    for r in range(N):
        A = rng.randn(16, DIM)
        b = A @ x_true + 0.01 * rng.randn(16)
        As.append(A)
        bs.append(b)
    return np.stack(As), np.stack(bs), x_true


def _topology_spec():
    from bluefog_tpu.context import _uniform_topology_spec
    return _uniform_topology_spec(ExponentialTwoGraph(N))


def loss_fn(params, batch):
    A, b = batch
    pred = A @ params["x"]
    return jnp.mean((pred - b) ** 2)


@pytest.mark.parametrize("comm_mode", ["cta", "atc", "gradient_allreduce"])
def test_linear_convergence(comm_mode):
    mesh = _mesh()
    As, bs, x_true = _linear_problem()
    spec = _topology_spec() if comm_mode in ("cta", "atc") else None
    step_fn = F.build_train_step(
        loss_fn, optax.sgd(0.05), mesh, comm_mode=comm_mode,
        topology=spec)
    params = F.rank_major({"x": jnp.zeros(DIM)}, mesh)
    opt_state = F.rank_major(optax.sgd(0.05).init({"x": jnp.zeros(DIM)}), mesh)
    batch = (jax.device_put(As, NamedSharding(mesh, P("bf"))),
             jax.device_put(bs, NamedSharding(mesh, P("bf"))))
    for i in range(300):
        params, opt_state, loss = step_fn(params, opt_state, batch,
                                          jnp.int32(i))
    xs = np.asarray(params["x"])
    # every rank near the truth, and ranks agree
    assert np.abs(xs - x_true).max() < 0.15, np.abs(xs - x_true).max()
    assert float(F.consensus_distance(params)) < 1e-2


def test_dynamic_schedule_consensus():
    """One-peer dynamic exp2 schedule via lax.switch: pure averaging (lr=0)
    must drive ranks to consensus."""
    mesh = _mesh()
    rounds = int(np.log2(N))
    schedule = one_peer_dynamic_schedule(N)
    assert len(schedule) == rounds

    step_fn = F.build_train_step(
        loss_fn, optax.sgd(0.0), mesh, comm_mode="cta", schedule=schedule)
    As, bs, _ = _linear_problem()
    params = {"x": jax.device_put(
        np.arange(N * DIM, dtype=np.float64).reshape(N, DIM),
        NamedSharding(mesh, P("bf")))}
    opt_state = F.rank_major(optax.sgd(0.0).init({"x": jnp.zeros(DIM)}), mesh)
    batch = (jax.device_put(As, NamedSharding(mesh, P("bf"))),
             jax.device_put(bs, NamedSharding(mesh, P("bf"))))
    for i in range(6 * rounds):
        params, opt_state, _ = step_fn(params, opt_state, batch, jnp.int32(i))
    assert float(F.consensus_distance(params)) < 1e-10


def test_periodic_communication():
    """num_steps_per_communication=2: combine fires only on even steps."""
    mesh = _mesh()
    spec = _topology_spec()
    step_fn = F.build_train_step(
        loss_fn, optax.sgd(0.0), mesh, comm_mode="cta", topology=spec,
        num_steps_per_communication=2)
    x0 = np.arange(N * DIM, dtype=np.float64).reshape(N, DIM)
    params = {"x": jax.device_put(x0, NamedSharding(mesh, P("bf")))}
    opt_state = F.rank_major(optax.sgd(0.0).init({"x": jnp.zeros(DIM)}), mesh)
    As, bs, _ = _linear_problem()
    batch = (jax.device_put(As, NamedSharding(mesh, P("bf"))),
             jax.device_put(bs, NamedSharding(mesh, P("bf"))))
    # step index 1: no communication -> params unchanged (lr=0)
    p1, opt_state, _ = step_fn(params, opt_state, batch, jnp.int32(1))
    np.testing.assert_array_equal(np.asarray(p1["x"]), x0)
    # step index 2: communication -> consensus distance strictly drops
    p2, _, _ = step_fn(p1, opt_state, batch, jnp.int32(2))
    assert float(F.consensus_distance(p2)) < float(
        F.consensus_distance({"x": jnp.asarray(x0)}))


def test_dp_sp_composition():
    """2D mesh: 4-rank decentralized DP x 2-way sequence parallelism with
    ring attention inside the jitted step."""
    from bluefog_tpu import models
    from bluefog_tpu.context import _uniform_topology_spec

    n_dp, n_sp = 4, 2
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(n_dp, n_sp), ("bf", "sp"))
    cfg = models.LlamaConfig.tiny(dtype=jnp.float32, attn_mode="ring",
                                  sp_axis="sp")
    model = models.Llama(cfg)
    t_total, t_local = 32, 16
    raw = np.asarray(jax.random.randint(
        jax.random.PRNGKey(0), (n_dp, 2, t_total + 1), 0, cfg.vocab_size))
    inputs, targets = raw[:, :, :-1], raw[:, :, 1:]

    def llm_loss(params, batch):
        inp, tgt = batch
        offset = jax.lax.axis_index("sp") * t_local
        logits = model.apply(params, inp, pos_offset=offset)
        return jnp.mean(
            optax.softmax_cross_entropy_with_integer_labels(logits, tgt))

    spec = _uniform_topology_spec(RingGraph(n_dp))
    step_fn = F.build_train_step(
        llm_loss, optax.adam(1e-3), mesh, comm_mode="atc", topology=spec,
        sp_axis="sp", batch_specs=P("bf", None, "sp"))

    base = models.Llama(models.LlamaConfig.tiny(dtype=jnp.float32)).init(
        jax.random.PRNGKey(1), jnp.asarray(inputs[0, :, :8]))
    params = F.rank_major(base, mesh)
    opt_state = F.rank_major(optax.adam(1e-3).init(base), mesh)
    sharding = NamedSharding(mesh, P("bf", None, "sp"))
    batch = (jax.device_put(inputs, sharding),
             jax.device_put(targets, sharding))

    losses = []
    for i in range(10):
        params, opt_state, loss = step_fn(params, opt_state, batch,
                                          jnp.int32(i))
        losses.append(float(np.asarray(loss).mean()))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]  # training moves


def test_has_aux_state():
    """Mutable aux (batch-norm-style counter) threads through the step."""
    mesh = _mesh()

    def aux_loss(params, aux, batch):
        A, b = batch
        pred = A @ params["x"]
        return jnp.mean((pred - b) ** 2), {"count": aux["count"] + 1}

    step_fn = F.build_train_step(
        aux_loss, optax.sgd(0.01), mesh, comm_mode="cta",
        topology=_topology_spec(), has_aux=True)
    As, bs, _ = _linear_problem()
    params = F.rank_major({"x": jnp.zeros(DIM)}, mesh)
    aux = F.rank_major({"count": jnp.zeros((), jnp.int32)}, mesh)
    opt_state = F.rank_major(optax.sgd(0.01).init({"x": jnp.zeros(DIM)}), mesh)
    batch = (jax.device_put(As, NamedSharding(mesh, P("bf"))),
             jax.device_put(bs, NamedSharding(mesh, P("bf"))))
    for i in range(3):
        params, aux, opt_state, loss = step_fn(params, aux, opt_state, batch,
                                               jnp.int32(i))
    assert (np.asarray(aux["count"]) == 3).all()


def test_push_sum_invariant_and_convergence():
    """comm_mode='push_sum' on a DIRECTED ring (non-doubly-stochastic —
    plain neighbor averaging would bias toward some ranks): sum of
    ps weights stays == N every step (the reference's associated-P
    invariant, torch_win_ops_test.py:780-863), ranks reach consensus near
    the global least-squares solution."""
    from bluefog_tpu.topology.spec import Topology

    mesh = _mesh()
    # directed ring r -> r+1 (out-degree 1 everywhere)
    w = np.zeros((N, N))
    for r in range(N):
        w[r, (r + 1) % N] = 1.0
        w[r, r] = 1.0
    spec = Topology.from_weight_matrix(w)
    opt = optax.sgd(0.05)
    step_fn = F.build_train_step(
        loss_fn, opt, mesh, comm_mode="push_sum", topology=spec)
    As, bs, x_true = _linear_problem()
    params = F.rank_major({"x": jnp.zeros(DIM)}, mesh)
    base_state = F.rank_major(opt.init({"x": jnp.zeros(DIM)}), mesh)
    opt_state = (base_state, F.push_sum_weights(mesh))
    batch = (jax.device_put(As, NamedSharding(mesh, P("bf"))),
             jax.device_put(bs, NamedSharding(mesh, P("bf"))))
    for i in range(400):
        params, opt_state, loss = step_fn(params, opt_state, batch,
                                          jnp.int32(i))
        if i % 97 == 0:
            ps_sum = float(np.sum(np.asarray(opt_state[1])))
            np.testing.assert_allclose(ps_sum, N, rtol=1e-5)
    ps_sum = float(np.sum(np.asarray(opt_state[1])))
    np.testing.assert_allclose(ps_sum, N, rtol=1e-5)
    xs = np.asarray(params["x"])
    assert np.abs(xs - x_true).max() < 0.15, np.abs(xs - x_true).max()
    assert float(F.consensus_distance(params)) < 1e-2


def test_push_sum_pure_mix_reaches_uniform_average():
    """lr=0 push-sum mixing on a directed exp2 graph converges every rank's
    de-biased value to the uniform initial average (the bias-correction
    property plain averaging lacks on directed graphs)."""
    mesh = _mesh()
    spec = _topology_spec()
    opt = optax.sgd(0.0)
    step_fn = F.build_train_step(
        loss_fn, opt, mesh, comm_mode="push_sum", topology=spec)
    init = np.arange(N, dtype=np.float64)[:, None] * np.ones((N, DIM))
    params = {"x": jax.device_put(init, NamedSharding(mesh, P("bf")))}
    base_state = F.rank_major(opt.init({"x": jnp.zeros(DIM)}), mesh)
    opt_state = (base_state, F.push_sum_weights(mesh))
    As, bs, _ = _linear_problem()
    batch = (jax.device_put(As, NamedSharding(mesh, P("bf"))),
             jax.device_put(bs, NamedSharding(mesh, P("bf"))))
    for i in range(60):
        params, opt_state, _ = step_fn(params, opt_state, batch, jnp.int32(i))
    xs = np.asarray(params["x"], np.float64)
    np.testing.assert_allclose(xs, np.mean(np.arange(N)), rtol=1e-5,
                               atol=1e-5)


def _multi_leaf_problem(seed=0):
    """Several leaves of mixed sizes so bucketing has real work."""
    rng = np.random.RandomState(seed)
    base = {"w1": jnp.asarray(rng.randn(DIM, 8) * 0.3),
            "b1": jnp.zeros((8,)),
            "w2": jnp.asarray(rng.randn(8, 1) * 0.3),
            "b2": jnp.zeros((1,))}

    def loss_fn(params, batch):
        A, b = batch
        h = jnp.tanh(A @ params["w1"] + params["b1"])
        pred = (h @ params["w2"] + params["b2"])[..., 0]
        return jnp.mean((pred - b) ** 2)

    return base, loss_fn


@pytest.mark.parametrize("comm_mode", ["cta", "atc"])
def test_bucketed_overlap_numerical_parity(comm_mode):
    """overlap='bucketed' computes the SAME training trajectory as the
    non-overlapped step (acceptance: same params/loss to f32
    tolerance) — the weighted combine distributes over concatenation,
    so bucketing is a schedule change, not a math change."""
    mesh = _mesh()
    base, loss_fn = _multi_leaf_problem()
    opt = optax.sgd(0.05)
    spec = _topology_spec()
    plain = F.build_train_step(
        loss_fn, opt, mesh, comm_mode=comm_mode, topology=spec,
        donate=False)
    bucketed = F.build_train_step(
        loss_fn, opt, mesh, comm_mode=comm_mode, topology=spec,
        donate=False, overlap="bucketed", overlap_buckets=3)
    As, bs, _ = _linear_problem()
    bs = bs[..., 0] * 0 + bs.mean(-1)
    batch = (jax.device_put(As, NamedSharding(mesh, P("bf"))),
             jax.device_put(bs, NamedSharding(mesh, P("bf"))))
    pA = pB = F.rank_major(base, mesh)
    oA = oB = F.rank_major(opt.init(base), mesh)
    for i in range(8):
        pA, oA, lA = plain(pA, oA, batch, jnp.int32(i))
        pB, oB, lB = bucketed(pB, oB, batch, jnp.int32(i))
    np.testing.assert_allclose(np.asarray(lA, np.float32),
                               np.asarray(lB, np.float32), rtol=1e-6)
    for k in base:
        np.testing.assert_allclose(
            np.asarray(pA[k], np.float32), np.asarray(pB[k], np.float32),
            rtol=1e-6, atol=1e-7, err_msg=f"leaf {k}")


def test_bucketed_dynamic_schedule_consensus():
    """Bucketed combine through the lax.switch dynamic schedule: lr=0
    one-peer averaging still reaches exact consensus (the plumbing the
    overlap engine must not disturb)."""
    mesh = _mesh()
    rounds = int(np.log2(N))
    schedule = one_peer_dynamic_schedule(N)
    step_fn = F.build_train_step(
        loss_fn, optax.sgd(0.0), mesh, comm_mode="cta",
        schedule=schedule, overlap="bucketed", overlap_buckets=2)
    As, bs, _ = _linear_problem()
    params = {"x": jax.device_put(
        np.arange(N * DIM, dtype=np.float64).reshape(N, DIM),
        NamedSharding(mesh, P("bf")))}
    opt_state = F.rank_major(optax.sgd(0.0).init({"x": jnp.zeros(DIM)}),
                             mesh)
    batch = (jax.device_put(As, NamedSharding(mesh, P("bf"))),
             jax.device_put(bs, NamedSharding(mesh, P("bf"))))
    for i in range(6 * rounds):
        params, opt_state, _ = step_fn(params, opt_state, batch,
                                       jnp.int32(i))
    assert float(F.consensus_distance(params)) < 1e-10


def test_bucketed_periodic_communication_still_applies_updates():
    """ATC bucketed + num_steps_per_communication=2: off-cycle steps
    skip the collectives but MUST still apply the optax update."""
    mesh = _mesh()
    base, loss_fn_ml = _multi_leaf_problem()
    opt = optax.sgd(0.05)
    step_fn = F.build_train_step(
        loss_fn_ml, opt, mesh, comm_mode="atc",
        topology=_topology_spec(), num_steps_per_communication=2,
        overlap="bucketed", overlap_buckets=2)
    As, bs, _ = _linear_problem()
    bs = bs.mean(-1)
    params = F.rank_major(base, mesh)
    opt_state = F.rank_major(opt.init(base), mesh)
    batch = (jax.device_put(As, NamedSharding(mesh, P("bf"))),
             jax.device_put(bs, NamedSharding(mesh, P("bf"))))
    before = np.asarray(params["w1"])
    # odd step: no communication, but the update must land
    params, opt_state, _ = step_fn(params, opt_state, batch, jnp.int32(1))
    assert np.abs(np.asarray(params["w1"]) - before).max() > 0


def test_bucketed_overlap_mode_validation():
    """Unsupported overlap combos are rejected up front."""
    mesh = _mesh()
    spec = _topology_spec()
    with pytest.raises(ValueError, match="overlap"):
        F.build_train_step(loss_fn, optax.sgd(0.1), mesh,
                           comm_mode="cta", topology=spec,
                           overlap="bogus")
    with pytest.raises(ValueError, match="bucketed"):
        F.build_train_step(loss_fn, optax.sgd(0.1), mesh,
                           comm_mode="gradient_allreduce",
                           overlap="bucketed")
    # push_sum + bucketed is supported by the fused epilogue pipeline
    # (ISSUE 6); only the unfused escape-hatch builder rejects it
    import os

    os.environ["BLUEFOG_FUSE_EPILOGUES"] = "0"
    try:
        with pytest.raises(ValueError, match="bucketed"):
            F.build_train_step(loss_fn, optax.sgd(0.1), mesh,
                               comm_mode="push_sum", topology=spec,
                               overlap="bucketed")
    finally:
        os.environ.pop("BLUEFOG_FUSE_EPILOGUES", None)
    step = F.build_train_step(loss_fn, optax.sgd(0.1), mesh,
                              comm_mode="push_sum", topology=spec,
                              overlap="bucketed")
    assert "exchange" in step.epilogue_stages
    with pytest.raises(ValueError, match="overlap_buckets"):
        F.build_train_step(loss_fn, optax.sgd(0.1), mesh,
                           comm_mode="cta", topology=spec,
                           overlap="bucketed", overlap_buckets=0)


def test_push_sum_non_doubly_stochastic_graph():
    """Regression: a directed ring PLUS one extra edge (out-degrees 2,1,...)
    is strongly connected but NOT doubly stochastic — mixing the de-biased
    params directly diverges here; only proper (x, w) biased-pair mixing
    converges to the shared optimum."""
    from bluefog_tpu.topology.spec import Topology

    mesh = _mesh()
    w = np.zeros((N, N))
    for r in range(N):
        w[r, (r + 1) % N] = 1.0
        w[r, r] = 1.0
    w[0, 4] = 1.0  # rank 0 out-degree 2; breaks double stochasticity
    spec = Topology.from_weight_matrix(w)
    opt = optax.sgd(0.1)

    def fit_loss(params, batch):
        return jnp.mean((params["x"] - batch) ** 2)

    step_fn = F.build_train_step(
        fit_loss, opt, mesh, comm_mode="push_sum", topology=spec)
    params = F.rank_major({"x": jnp.zeros(3)}, mesh)
    opt_state = (F.rank_major(opt.init({"x": jnp.zeros(3)}), mesh),
                 F.push_sum_weights(mesh))
    target = np.tile(np.array([1.0, 2.0, 3.0]), (N, 1))
    batch = jax.device_put(target, NamedSharding(mesh, P("bf")))
    for i in range(200):
        params, opt_state, loss = step_fn(params, opt_state, batch,
                                          jnp.int32(i))
    np.testing.assert_allclose(np.sum(np.asarray(opt_state[1])), N,
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(params["x"]), target, atol=1e-3)
