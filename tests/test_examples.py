"""End-to-end example runs — the reference drives every example under
``bfrun -np 4`` with a timeout (reference test/test_all_example.sh:31-118);
here each example runs as a subprocess on the 8-virtual-device CPU mesh.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_example(script, *argv, timeout=240):
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", script), *argv],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO)
    assert proc.returncode == 0, (
        f"{script} {argv} failed:\n{proc.stdout}\n{proc.stderr}")
    return proc.stdout


def test_average_consensus():
    out = run_example("average_consensus.py", "--data-size", "1000")
    assert "consensus" in out


def test_average_consensus_async():
    out = run_example("average_consensus.py", "--data-size", "1000",
                      "--asynchronous-mode", "--max-iters", "100")
    assert "async-win" in out


@pytest.mark.parametrize("method", ["diffusion", "exact_diffusion",
                                    "gradient_tracking", "push_diging"])
def test_decentralized_optimization(method):
    iters = "60" if method == "push_diging" else "200"
    out = run_example("decentralized_optimization.py", "--method", method,
                      "--max-iters", iters, "--samples-per-rank", "20",
                      "--dim", "5")
    # every method must drive the global gradient near zero and ranks
    # to (near-)agreement
    import re
    m = re.search(r"global grad norm=([0-9.e+-]+) rank spread=([0-9.e+-]+)",
                  out)
    assert m, out
    gnorm, spread = float(m.group(1)), float(m.group(2))
    assert gnorm < 0.3, (method, out)
    assert spread < 0.5, (method, out)


@pytest.mark.parametrize("dist_opt", ["neighbor_allreduce",
                                      "gradient_allreduce", "push_sum"])
def test_mnist(dist_opt):
    out = run_example("mnist.py", "--dist-optimizer", dist_opt, "--epochs",
                      "2", "--samples-per-rank", "64", "--batch-size", "32",
                      timeout=360)
    assert "train_acc" in out


def test_scaling_benchmark_mlp():
    out = run_example(
        "scaling_benchmark.py", "--model", "mlp", "--batch-size", "16",
        "--optimizers", "dynamic", "--num-warmup", "1", "--num-steps", "2",
        timeout=360)
    assert "efficiency" in out


def test_llama_benchmark_tiny():
    out = run_example(
        "llama_benchmark.py", "--model", "tiny", "--batch-size", "2",
        "--seq-len", "64", "--sp", "2", "--dist-optimizer", "dynamic",
        "--num-warmup", "1", "--num-steps", "2", timeout=360)
    assert "tokens_per_sec" in out


def test_generate_text():
    out = run_example("generate_text.py", "--max-new-tokens", "6")
    assert "generated ids:" in out


def test_llama_benchmark_pp_ulysses():
    out = run_example(
        "llama_benchmark.py", "--model", "tiny", "--layers", "4",
        "--batch-size", "4", "--seq-len", "32", "--pp", "2", "--pp-loops",
        "2", "--microbatches", "4", "--sp", "2", "--sp-mode", "ulysses",
        "--num-warmup", "1", "--num-steps", "2", timeout=360)
    assert "tokens_per_sec" in out


def test_resnet_benchmark_tiny():
    out = run_example(
        "resnet_benchmark.py", "--model", "resnet18", "--batch-size", "4",
        "--image-size", "32", "--dist-optimizer", "dynamic",
        "--num-warmup-batches", "1", "--num-batches-per-iter", "2",
        "--num-iters", "1", timeout=360)
    assert "img/sec" in out


def test_serve_llama():
    out = run_example("serve_llama.py", "--num-requests", "6", "--rate",
                      "30", "--capacity", "2", "--max-len", "64")
    assert "serving metrics:" in out
    assert "completed" in out


def test_decode_benchmark_tiny():
    out = run_example("decode_benchmark.py", "--model", "tiny",
                      "--batch-size", "2", "--prompt-len", "8",
                      "--new-tokens", "8", "--dtype", "f32",
                      "--repeats", "1")
    import json as _json

    rec = _json.loads(out.strip().splitlines()[-1])
    assert rec["decode_tokens_per_sec"] > 0
    assert rec["new_tokens"] == 8
