"""Fleet telemetry (ISSUE 5): in-graph health signals + decentralized
cross-rank aggregation.

Contracts under test:

* **HealthVector** — ``build_train_step(health=HealthConfig(...))``
  emits shape-stable per-rank health scalars; with ``health=None`` the
  step is bit-identical to a pre-feature build and jit cache sizes are
  unchanged; with health enabled there are ZERO recompiles across fault
  patterns (the GuardConfig methodology); the consensus distance term
  matches a by-hand recomputation from the combine's own inputs/outputs.
* **FleetAggregator** — push-sum gossip over the training topology
  reproduces the centralized mean to <= 1e-12 relative error at n=32
  (the acceptance bar), including after a ``healing.py`` weight re-plan
  excises a dead rank; the host matrices are EXACTLY one round of
  ``collectives.push_sum_mix`` (device parity test); hierarchical
  intra-host/inter-host aggregation is an exact weighted mean with
  uneven live machines.
* **StragglerDetector** — a slow rank's robust step-time z-score flags
  it within ``patience`` observations, recovery clears the flag, and
  ``run_resilient`` wires flags into ``FailureDetector.suspect`` +
  ``straggler`` events.
* **Traffic accounting** — ``bf_edge_bytes_total{src,dst}`` families
  appear for every declared edge, from both the train-step wrapper and
  the gossip itself, and fleet gauges export through Prometheus text
  unchanged.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bluefog_tpu import observe
from bluefog_tpu.observe import fleet as FL
from bluefog_tpu.observe.registry import MetricsRegistry
from bluefog_tpu.optim import functional as F
from bluefog_tpu.parallel import collectives as C
from bluefog_tpu.resilience.healing import heal_spec
from bluefog_tpu.topology import (ExponentialTwoGraph,
                                  one_peer_dynamic_schedule,
                                  uniform_topology_spec)

pytestmark = pytest.mark.fleet

N = 8


# --------------------------------------------------------------------- #
# push-sum gossip core
# --------------------------------------------------------------------- #
def test_push_sum_matrix_column_stochastic():
    for spec in ([uniform_topology_spec(ExponentialTwoGraph(N))]
                 + one_peer_dynamic_schedule(N)):
        A = FL.push_sum_matrix(spec)
        np.testing.assert_allclose(A.sum(axis=0), 1.0, atol=1e-15)
    dead = np.zeros(N, bool)
    dead[2] = True
    A = FL.push_sum_matrix(one_peer_dynamic_schedule(N)[0], dead)
    np.testing.assert_allclose(A.sum(axis=0), 1.0, atol=1e-15)
    assert A[2, 2] == 1.0 and A[2].sum() == 1.0  # dead rank is inert


def test_push_sum_matrix_matches_device_push_sum_mix():
    """The host gossip matrix IS one round of the device push-sum mix:
    same column-stochastic structure, same numbers — the 'reuse the
    push-sum machinery' claim, measured."""
    spec = uniform_topology_spec(ExponentialTwoGraph(N))
    mesh = Mesh(np.array(jax.devices()[:N]), ("bf",))
    x = np.arange(N, dtype=np.float64) + 1.0
    w = np.ones(N)

    def one_round(xs, ws):
        mixed, mps = C.push_sum_mix({"v": xs}, ws, spec, "bf")
        return mixed["v"], mps

    sm = jax.jit(jax.shard_map(one_round, mesh=mesh,
                               in_specs=(P("bf"), P("bf")),
                               out_specs=(P("bf"), P("bf")),
                               check_vma=False))
    dx, dw = sm(jnp.asarray(x), jnp.asarray(w))
    A = FL.push_sum_matrix(spec)
    np.testing.assert_allclose(np.asarray(dx), A @ x, rtol=1e-12)
    np.testing.assert_allclose(np.asarray(dw), A @ w, rtol=1e-12)


def test_aggregator_matches_centralized_mean_32_ranks():
    """Acceptance: n=32 digraph, per-rank estimates vs the centralized
    mean to <= 1e-12 relative error."""
    n = 32
    sched = one_peer_dynamic_schedule(n)
    vals = np.random.default_rng(0).standard_normal((n, 3)) * 10
    agg = FL.FleetAggregator(sched, registry=MetricsRegistry())
    res = agg.aggregate(vals, names=("a", "b", "c"))
    true = vals.mean(axis=0)
    err = np.abs(res.per_rank - true).max() / np.abs(true).max()
    assert err <= 1e-12, (err, res.rounds)
    assert res.names == ("a", "b", "c")
    np.testing.assert_allclose(res.mean, true, rtol=1e-12)


def test_aggregator_healed_dead_rank_excision():
    """Acceptance: after a healing.py weight re-plan excises dead
    ranks, gossip over the HEALED schedule converges to the live mean
    to <= 1e-12 — and the internally-excised matrices are byte-equal to
    the healed-spec matrices (the two paths cannot drift)."""
    n = 32
    sched = one_peer_dynamic_schedule(n)
    dead = np.zeros(n, bool)
    dead[[3, 17]] = True
    vals = np.random.default_rng(1).standard_normal((n, 2))
    vals[dead] = 1e6  # a dead rank's garbage must not leak into means

    healed = [heal_spec(s, dead) for s in sched]
    for s, h in zip(sched, healed):
        np.testing.assert_array_equal(FL.push_sum_matrix(s, dead),
                                      FL.push_sum_matrix(h))

    agg = FL.FleetAggregator(healed, registry=MetricsRegistry())
    res = agg.aggregate(vals, dead_mask=dead)
    true_live = vals[~dead].mean(axis=0)
    err = np.nanmax(np.abs(res.per_rank - true_live)) / \
        max(np.abs(true_live).max(), 1e-12)
    assert err <= 1e-12, err
    assert np.isnan(res.per_rank[3]).all()  # dead ranks have no view


def test_aggregator_healed_schedule_without_dead_mask():
    """A healed schedule passed WITHOUT a dead mask must behave like
    one passed with it: the re-plan's fully-excised ranks (no edges in
    any round) are detected as isolated and folded into the effective
    dead mask, instead of blocking convergence forever with their stale
    values counted live."""
    n = 32
    sched = one_peer_dynamic_schedule(n)
    dead = np.zeros(n, bool)
    dead[[3, 17]] = True
    vals = np.random.default_rng(2).standard_normal((n, 2))
    vals[dead] = 1e6

    healed = [heal_spec(s, dead) for s in sched]
    agg = FL.FleetAggregator(healed, registry=MetricsRegistry())
    res = agg.aggregate(vals)  # no dead_mask: excision inferred
    true_live = vals[~dead].mean(axis=0)
    err = np.nanmax(np.abs(res.per_rank - true_live)) / \
        max(np.abs(true_live).max(), 1e-12)
    assert err <= 1e-12, (err, res.rounds, res.spread)
    assert res.rounds < agg.max_rounds
    assert np.isnan(res.per_rank[list(np.nonzero(dead)[0])]).all()
    np.testing.assert_allclose(res.mean, true_live, rtol=1e-12)


def test_gossip_traffic_skips_zero_weight_edges():
    """The gossip's wire account bills the weight-FILTERED push-sum
    structure: a healed spec's zeroed edges (declared but pushing
    nothing, exactly like a 0.0-weight DynamicTopology edge) must not
    accrue bf_edge_bytes_total."""
    sched = one_peer_dynamic_schedule(N)
    dead = np.zeros(N, bool)
    dead[2] = True
    healed = [heal_spec(s, dead) for s in sched]
    dropped = [e for s, h in zip(sched, healed)
               for e in set(FL.edge_list(s)) - set(FL.gossip_edge_list(h))]
    assert dropped  # healing actually zeroed some edges
    assert all(2 in e for e in dropped)

    reg = MetricsRegistry()
    agg = FL.FleetAggregator(healed, registry=reg)
    vals = np.random.default_rng(3).standard_normal(N)
    agg.aggregate(vals, dead_mask=dead)
    billed = {(lbl["src"], lbl["dst"])
              for name, kind, _h, lbl, m in reg.collect()
              if name == "bf_edge_bytes_total" and m.value > 0}
    assert billed  # live edges are billed
    assert not ({e for e in billed if 2 in e})


def test_aggregator_hierarchical_weighted_mean():
    """HiCCL-style two-level aggregation: exact intra-machine reduce,
    inter-machine push-sum with live-COUNT weights — the global live
    mean exactly, uneven machines included."""
    n, local = 32, 4
    dead = np.zeros(n, bool)
    dead[[0, 1, 2, 5]] = True  # machine 0 keeps ONE live rank
    vals = np.random.default_rng(2).standard_normal((n, 2))
    reg = MetricsRegistry()
    agg = FL.FleetAggregator(one_peer_dynamic_schedule(n), registry=reg)
    res = agg.aggregate_hierarchical(
        vals, local, one_peer_dynamic_schedule(n // local),
        dead_mask=dead)
    true_live = vals[~dead].mean(axis=0)
    err = np.nanmax(np.abs(res.per_rank - true_live)) / \
        max(np.abs(true_live).max(), 1e-12)
    assert err <= 1e-12, err
    # inter-host gossip wire cost is accounted on the machine LEADER
    # ranks' edges (multiples of local_size)
    snap = reg.snapshot()
    assert "bf_edge_bytes_total" in snap
    for r in snap["bf_edge_bytes_total"]:
        assert int(r["labels"]["src"]) % local == 0
        assert int(r["labels"]["dst"]) % local == 0
    # repeated publishes hit the matrix cache
    n_cached = len(agg._mats)
    agg.aggregate_hierarchical(vals, local,
                               one_peer_dynamic_schedule(n // local),
                               dead_mask=dead)
    assert len(agg._mats) == n_cached


def test_aggregator_publish_lands_bf_fleet_metrics():
    reg = MetricsRegistry()
    sched = one_peer_dynamic_schedule(N)
    agg = FL.FleetAggregator(sched, registry=reg, rank=0)
    vals = np.tile(np.arange(N, dtype=float)[:, None], (1, 2))
    agg.publish(("step_time_p50", "skips_total"), vals)
    snap = reg.snapshot()
    expect = float(np.arange(N).mean())
    assert abs(snap["bf_fleet_step_time_p50"][0]["value"] - expect) < 1e-9
    assert abs(snap["bf_fleet_skips_total"][0]["value"] - expect) < 1e-9
    assert snap["bf_fleet_gossip_rounds"][0]["value"] >= 1
    # the gossip's own wire cost is accounted per edge
    assert "bf_edge_bytes_total" in snap
    assert all(set(r["labels"]) == {"src", "dst"}
               for r in snap["bf_edge_bytes_total"])
    # and the exporters serve fleet metrics with no changes
    text = observe.prometheus_text(reg)
    assert "bf_fleet_step_time_p50" in text
    assert 'bf_edge_bytes_total{dst="' in text


def test_collect_local_reads_registry():
    reg = MetricsRegistry()
    reg.histogram("bf_step_wall_seconds", loop="train").observe(0.25)
    reg.counter("bf_resilience_skips_total", rank=1).inc(3)
    reg.counter("bf_resilience_skips_total", rank=2).inc(4)
    reg.gauge("bf_serving_queue_depth").set(5)
    local = FL.collect_local(reg)
    assert local == {"step_time_p50": 0.25, "skips_total": 7.0,
                     "queue_depth": 5.0}


# --------------------------------------------------------------------- #
# straggler detection
# --------------------------------------------------------------------- #
def test_straggler_detector_flags_within_patience_and_clears():
    det = FL.StragglerDetector(N, z_threshold=4.0, patience=3,
                               registry=MetricsRegistry())
    base = np.full(N, 0.01)
    rng = np.random.default_rng(0)
    for _ in range(5):  # healthy jitter never flags
        assert det.observe(base + rng.normal(0, 1e-4, N)) == []
    assert det.flagged() == []
    slow = base.copy()
    slow[5] += 0.2
    newly = []
    for i in range(3):
        newly += det.observe(slow + rng.normal(0, 1e-4, N))
        if i < 2:
            assert det.flagged() == []  # not yet: patience=3
    assert newly == [5] and det.flagged() == [5]
    z = det.z_scores()
    assert set(z) == set(range(N)) and z[5] > 4.0
    # sub-threshold drift is readable without any event having fired
    assert all(abs(z[r]) < 4.0 for r in range(N) if r != 5)
    # recovery clears the flag (and the streak) — and the z snapshot
    # tracks the LATEST observation, so the recovered rank reads sane
    assert det.observe(base + rng.normal(0, 1e-4, N)) == []
    assert det.flagged() == []
    assert det.z_scores()[5] < 4.0


def test_straggler_detector_robust_to_its_own_outlier():
    """A plain std would be inflated by the straggler itself; the
    median/MAD score must still separate one 25x outlier at n=8."""
    det = FL.StragglerDetector(N, z_threshold=4.0, patience=1)
    times = np.full(N, 0.02)
    times[3] = 0.5
    assert det.observe(times) == [3]


def test_run_resilient_wires_straggler_to_suspects(tmp_path):
    """The control loop names the slow rank: a straggler event is
    emitted, FailureDetector.suspect is fed (and suspects() includes
    it), and recovery withdraws the suspicion."""
    from bluefog_tpu import resilience as R
    from bluefog_tpu.checkpoint import Checkpointer

    mesh = Mesh(np.array(jax.devices()[:N]), ("bf",))
    sched = one_peer_dynamic_schedule(N)
    base = {"w": jnp.eye(4)}

    def loss_fn(params, batch):
        return jnp.mean((batch @ params["w"]) ** 2)

    opt = optax.sgd(0.05)
    step = F.build_train_step(loss_fn, opt, mesh, comm_mode="cta",
                              schedule=sched, donate=False,
                              guard=F.GuardConfig())
    params = F.rank_major(base, mesh)
    ostate = F.rank_major(opt.init(base), mesh)

    def batch_fn(step_i):
        return jax.device_put(np.ones((N, 2, 4), np.float32),
                              NamedSharding(mesh, P("bf")))

    # rank 6 is slow for steps 2..7 then recovers
    stalls = {s: 0.3 for s in range(2, 8)}

    def step_times_fn(step_i, wall):
        t = np.full(N, 0.01)
        t[6] += stalls.get(step_i, 0.0)
        return t

    det = FL.StragglerDetector(N, z_threshold=4.0, patience=2)
    fdet = R.FailureDetector(N)
    ck = Checkpointer(str(tmp_path / "ck"))
    res = R.run_resilient(step, params, ostate, batch_fn, steps=12,
                          checkpointer=ck, mesh=mesh, schedule=sched,
                          detector=fdet, checkpoint_every=0,
                          sleep=lambda s: None, straggler=det,
                          step_times_fn=step_times_fn)
    ck.close()
    strag_events = [e for e in res.events if e.kind == "straggler"]
    assert len(strag_events) == 1
    assert strag_events[0].detail["ranks"] == [6]
    assert strag_events[0].step == 3  # onset 2 + patience 2 - 1
    # recovered by the end -> suspicion withdrawn, nobody died
    assert fdet.external_suspects() == []
    assert not res.dead_mask.any() and res.n_rollbacks == 0


def test_failure_detector_external_suspects():
    from bluefog_tpu.resilience import FailureDetector

    det = FailureDetector(4)
    det.suspect([2])
    assert det.suspects(3) == [2]
    assert det.streak_suspects(3) == []  # numeric evidence only
    assert det.external_suspects() == [2]
    det.declare_dead([2])
    assert det.suspects(3) == []  # dead ranks are not suspects
    det.suspect([1, 3])
    det.clear_suspicion([1])
    assert det.external_suspects() == [3]
    det.clear_suspicion()
    assert det.suspects(3) == []
    with pytest.raises(ValueError):
        det.suspect([9])
    # per-SOURCE suspicion: one monitor clearing its claim must not
    # erase another's standing claim on the same rank
    det.suspect([1], source="operator")
    det.suspect([1], source="straggler")
    det.clear_suspicion([1], source="straggler")
    assert det.external_suspects() == [1]  # operator's claim stands
    det.clear_suspicion([1], source="operator")
    assert det.external_suspects() == []


def test_straggler_suspicion_never_attributes_a_nan_window(tmp_path):
    """A flagged straggler must NOT be declared dead by an
    unattributable NaN window: death attribution is numeric
    (streak_suspects), so rotating transients across OTHER ranks
    produce a bad_window_unattributed event and training continues —
    the healthy-but-slow rank survives."""
    from bluefog_tpu import resilience as R
    from bluefog_tpu.checkpoint import Checkpointer

    mesh = Mesh(np.array(jax.devices()[:N]), ("bf",))
    sched = one_peer_dynamic_schedule(N)
    base = {"w": jnp.eye(4)}

    def loss_fn(params, batch):
        return jnp.mean((batch @ params["w"]) ** 2)

    opt = optax.sgd(0.05)
    step = F.build_train_step(
        loss_fn, opt, mesh, comm_mode="cta", schedule=sched,
        donate=False, guard=F.GuardConfig(max_consecutive_bad=3))
    params = F.rank_major(base, mesh)
    ostate = F.rank_major(opt.init(base), mesh)

    def batch_fn(step_i):
        return jax.device_put(np.ones((N, 2, 4), np.float32),
                              NamedSharding(mesh, P("bf")))

    # transients ROTATE across ranks 0/1/2 (no rank holds a 3-streak)
    # while rank 6 is persistently slow and flagged
    plan = R.FaultPlan(N, [R.Fault(2, 0, "nan"), R.Fault(3, 1, "nan"),
                           R.Fault(4, 2, "nan")])
    det = FL.StragglerDetector(N, z_threshold=4.0, patience=2)
    fdet = R.FailureDetector(N)

    def step_times_fn(step_i, wall):
        t = np.full(N, 0.01)
        t[6] += 0.3
        return t

    ck = Checkpointer(str(tmp_path / "ck"))
    res = R.run_resilient(step, params, ostate, batch_fn, steps=8,
                          checkpointer=ck, mesh=mesh, schedule=sched,
                          detector=fdet, fault_plan=plan,
                          checkpoint_every=0, sleep=lambda s: None,
                          straggler=det, step_times_fn=step_times_fn)
    ck.close()
    kinds = [e.kind for e in res.events]
    assert "bad_window_unattributed" in kinds
    assert "rank_dead" not in kinds  # nobody executed
    assert not res.dead_mask.any() and res.n_rollbacks == 0
    assert fdet.external_suspects() == [6]  # still NAMED, not shot


# --------------------------------------------------------------------- #
# in-graph health vector
# --------------------------------------------------------------------- #
def _toy(mesh, **kwargs):
    base = {"w": jnp.eye(4), "b": jnp.zeros((4,))}

    def loss_fn(params, batch):
        return jnp.mean((batch @ params["w"] + params["b"]) ** 2)

    opt = optax.sgd(0.05, momentum=0.9)
    step = F.build_train_step(loss_fn, opt, mesh, donate=False, **kwargs)
    params = F.rank_major(base, mesh)
    ostate = F.rank_major(opt.init(base), mesh)
    batch = jax.device_put(
        np.random.RandomState(0).randn(N, 2, 4).astype(np.float32),
        NamedSharding(mesh, P("bf")))
    return step, params, ostate, batch


@pytest.mark.parametrize("kwargs", [
    dict(comm_mode="cta"),
    dict(comm_mode="atc"),
    dict(comm_mode="atc", overlap="bucketed", overlap_buckets=2),
], ids=["cta", "atc", "atc-bucketed"])
def test_health_disabled_is_bit_identical(kwargs):
    """Acceptance: with health=None the outputs are bit-identical to
    the health-enabled build's (params/opt_state/loss), and each build
    compiles exactly one executable."""
    mesh = Mesh(np.array(jax.devices()[:N]), ("bf",))
    sched = one_peer_dynamic_schedule(N)
    s0, params, ostate, batch = _toy(mesh, schedule=sched, **kwargs)
    s1, *_ = _toy(mesh, schedule=sched, health=F.HealthConfig(), **kwargs)
    p0, o0 = params, ostate
    p1, o1 = params, ostate
    for i in range(3):
        p0, o0, l0 = s0(p0, o0, batch, jnp.int32(i))
        p1, o1, l1, hv = s1(p1, o1, batch, jnp.int32(i))
    np.testing.assert_array_equal(np.asarray(l0), np.asarray(l1))
    for a, b in zip(jax.tree.leaves((p0, o0)), jax.tree.leaves((p1, o1))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert s0.jitted._cache_size() == 1
    assert s1.jitted._cache_size() == 1
    assert s0.health_config is None
    assert isinstance(hv, F.HealthVector)


def test_health_vector_semantics():
    """Field-level checks: shapes [n]; loss mirrors the loss output;
    consensus is ~0 when every rank holds identical params (a
    row-stochastic combine is then the identity) and > 0 once ranks
    disagree; the consensus term equals a by-hand recomputation from
    the combine's inputs/outputs."""
    mesh = Mesh(np.array(jax.devices()[:N]), ("bf",))
    spec = one_peer_dynamic_schedule(N)[0]
    step, params, ostate, batch = _toy(
        mesh, comm_mode="atc", topology=spec, health=F.HealthConfig())
    p, o, loss, hv = step(params, ostate, batch, jnp.int32(0))
    for field in hv:
        assert np.asarray(field).shape == (N,)
        assert np.asarray(field).dtype == np.float32
    np.testing.assert_array_equal(np.asarray(hv.loss),
                                  np.asarray(loss, np.float32))
    assert (np.asarray(hv.grad_norm) > 0).all()
    assert (np.asarray(hv.update_norm) > 0).all()
    assert np.asarray(hv.skipped).max() == 0.0

    # step 0 starts from identical ranks: the ATC combine mixes
    # already-applied (divergent) updates -> consensus > 0
    assert (np.asarray(hv.consensus) > 0).all()

    # by-hand: ATC consensus = || applied - combine(applied) || per rank
    applied = {k: np.asarray(v) for k, v in p.items()}  # post-combine
    # recompute the combine input: apply the same sgd update eagerly
    lr_params = jax.tree.map(lambda x: np.asarray(x), params)
    grads = jax.vmap(jax.grad(
        lambda pp, bb: jnp.mean((bb @ pp["w"] + pp["b"]) ** 2)))(
            lr_params, np.asarray(batch))
    pre = jax.tree.map(lambda x, g: np.asarray(x) - 0.05 * np.asarray(g),
                       lr_params, grads)
    M = np.zeros((N, N))
    from bluefog_tpu.resilience.healing import mixing_matrix

    M = mixing_matrix(spec)
    expect = np.zeros(N)
    for k in ("w", "b"):
        flat = pre[k].reshape(N, -1)
        expect += ((flat - M @ flat) ** 2).sum(axis=1)
    np.testing.assert_allclose(np.asarray(hv.consensus),
                               np.sqrt(expect), rtol=1e-4)


def test_health_zero_recompiles_across_fault_patterns():
    """Acceptance: health enabled (guard too) — zero recompiles across
    fault patterns, asserted via jit cache sizes (the GuardConfig
    methodology from tests/test_resilience.py)."""
    from bluefog_tpu.resilience import FaultPlan

    mesh = Mesh(np.array(jax.devices()[:N]), ("bf",))
    sched = one_peer_dynamic_schedule(N)
    step, params, ostate, _ = _toy(
        mesh, comm_mode="atc", schedule=sched,
        guard=F.GuardConfig(), health=F.HealthConfig())
    plans = [FaultPlan.healthy(N),
             FaultPlan.nan_burst(N, rank=1, step=0, duration=1),
             FaultPlan.nan_burst(N, rank=5, step=1, duration=2),
             FaultPlan.rank_death(N, rank=2, step=0)]
    sharding = NamedSharding(mesh, P("bf"))
    baseline = None
    for i, plan in enumerate(plans):
        raw = np.random.RandomState(i).randn(N, 2, 4).astype(np.float32)
        batch = jax.device_put(plan.corrupt_batch(raw, i), sharding)
        p, o, loss, sk, hv = step(params, ostate, batch, jnp.int32(i),
                                  step.default_comm_weights)
        if baseline is None:
            baseline = step.jitted._cache_size()
        assert step.jitted._cache_size() == baseline, plan
        # the guard's actual skip flags ride the health vector
        np.testing.assert_array_equal(
            np.asarray(hv.skipped),
            np.asarray(sk).astype(np.float32))
        codes = plan.corrupt_codes(i)
        np.testing.assert_array_equal(np.asarray(sk) != 0, codes != 0)
    assert baseline == 1


def test_train_step_records_edge_traffic():
    """Each on-cycle dispatch adds the per-rank payload to every
    declared edge of the round's topology."""
    mesh = Mesh(np.array(jax.devices()[:N]), ("bf",))
    sched = one_peer_dynamic_schedule(N)
    step, params, ostate, batch = _toy(mesh, comm_mode="cta",
                                       schedule=sched)
    reg = observe.get_registry()
    edges0 = FL.edge_list(sched[0])
    before = reg.counter("bf_edge_bytes_total", src=edges0[0][0],
                         dst=edges0[0][1]).value
    step(params, ostate, batch, jnp.int32(0))
    payload = sum(l.nbytes for l in jax.tree.leaves(params)) // N
    for (src, dst) in edges0:
        assert reg.counter("bf_edge_bytes_total", src=src,
                           dst=dst).value >= payload
    after = reg.counter("bf_edge_bytes_total", src=edges0[0][0],
                        dst=edges0[0][1]).value
    assert after == before + payload

    # a topology passed alongside a NON-neighbor comm mode runs no
    # exchange — it must not count phantom edge bytes either
    step2, params2, ostate2, batch2 = _toy(
        mesh, comm_mode="gradient_allreduce", topology=sched[0])
    mid = reg.counter("bf_edge_bytes_total", src=edges0[0][0],
                      dst=edges0[0][1]).value
    step2(params2, ostate2, batch2, jnp.int32(0))
    assert reg.counter("bf_edge_bytes_total", src=edges0[0][0],
                       dst=edges0[0][1]).value == mid


# --------------------------------------------------------------------- #
# windowed traffic deltas + timing twin (ISSUE 15: the control plane's
# telemetry feed)
# --------------------------------------------------------------------- #
def test_record_edge_timing_bills_seconds_family():
    reg = MetricsRegistry()
    FL.record_edge_timing(None, 0.25, registry=reg, pairs=[(0, 1)])
    FL.record_edge_timing(None, 0.75, registry=reg, pairs=[(0, 1), (2, 3)])
    snap = FL.traffic_snapshot(reg, metric="bf_edge_seconds_total")
    assert snap[(0, 1)] == pytest.approx(1.0)
    assert snap[(2, 3)] == pytest.approx(0.75)
    # the per-leg label keeps hierarchical legs separable, same as bytes
    FL.record_edge_timing(None, 0.5, registry=reg, pairs=[(0, 2)],
                          link="dcn")
    assert FL.traffic_snapshot(
        reg, link="dcn", metric="bf_edge_seconds_total") == {(0, 2): 0.5}
    # and seconds never leak into the BYTES family the compiler reads
    assert FL.traffic_snapshot(reg) == {}


def test_traffic_deltas_window_semantics():
    """take() returns what moved SINCE the previous take — never
    lifetime totals — and construction snapshots the registry, so
    pre-history is excluded from the first window.  peek() reads the
    window without advancing it."""
    reg = MetricsRegistry()
    FL.record_edge_timing(None, 10.0, registry=reg, pairs=[(0, 1)])
    deltas = FL.TrafficDeltas(reg, metric="bf_edge_seconds_total")
    assert deltas.take() == {}  # the 10s of pre-history is not a delta
    FL.record_edge_timing(None, 2.0, registry=reg, pairs=[(0, 1)])
    FL.record_edge_timing(None, 3.0, registry=reg, pairs=[(4, 5)])
    assert deltas.peek() == {(0, 1): 2.0, (4, 5): 3.0}
    assert deltas.peek() == {(0, 1): 2.0, (4, 5): 3.0}  # no advance
    assert deltas.take() == {(0, 1): 2.0, (4, 5): 3.0}
    assert deltas.take() == {}  # quiet window: quiet edges omitted
    FL.record_edge_timing(None, 1.5, registry=reg, pairs=[(0, 1)])
    assert deltas.take() == {(0, 1): 1.5}


def test_traffic_snapshot_since_subtracts_marker():
    reg = MetricsRegistry()
    FL.record_edge_traffic(None, registry=reg, pairs=[(0, 1)],
                           payload_bytes=100)
    mark = FL.traffic_snapshot(reg)
    FL.record_edge_traffic(None, registry=reg, pairs=[(0, 1)],
                           payload_bytes=40)
    FL.record_edge_traffic(None, registry=reg, pairs=[(2, 3)],
                           payload_bytes=7)
    assert FL.traffic_snapshot(reg, since=mark) == {(0, 1): 40.0,
                                                    (2, 3): 7.0}
    # an edge with no NEW traffic is omitted, not reported as zero
    assert (0, 1) not in FL.traffic_snapshot(
        reg, since=FL.traffic_snapshot(reg))
