"""Decision flight recorder (bluefog_tpu/observe/blackbox.py).

Contracts under test:

* ring bound — O(1) memory: overflow evicts oldest-first, every
  eviction is counted, and the streaming chain digest is unaffected;
* byte-stable chain digest — same decision stream ⇒ identical
  SHA-256, with wall time and the free-form ``detail`` dict excluded
  from the digested line (a real run and its simulated twin agree);
* causal chaining — ``(parent_event_id, step)`` links render as the
  trigger→synthesize→swap→outcome chain through ``chain()`` /
  ``explain()``, and a terminal kind resolves its ancestors' outcome
  (rendering only — the digest never rewrites history);
* ``record_decision`` routing — ``False`` is a hard off, ``None``
  rides the ``BLUEFOG_BLACKBOX``-gated process-global ring, an
  explicit box records unconditionally;
* config knobs — ``BLUEFOG_BLACKBOX_CAPACITY`` sizes the ring,
  ``BLUEFOG_BLACKBOX_DUMP`` receives one JSONL dump per anomaly kind;
* export — JSONL round-trips through ``DecisionEvent.from_json`` and
  the ``python -m bluefog_tpu.observe.blackbox`` CLI renders chains
  from a dump;
* metrics — ``bf_decisions_total{plane,kind,outcome}`` and the
  ``bf_blackbox_dropped_events`` gauge publish to an injected
  registry.
"""

import json

import pytest

from bluefog_tpu import config
from bluefog_tpu.observe import MetricsRegistry
from bluefog_tpu.observe import blackbox as BB
from bluefog_tpu.observe.blackbox import (ANOMALY_KINDS, BlackBox,
                                          DecisionEvent, record_decision)

pytestmark = pytest.mark.observe


def _chain(bb, step=0):
    """One trigger→synthesize→swap→commit chain; returns the events."""
    trig = bb.record("topology", "trigger", step=step,
                     telemetry={"reason": "degraded", "secs": {"0-1": 0.5}})
    synth = bb.record("topology", "synthesize", step=step, parent=trig,
                      telemetry={"reason": "degraded"},
                      candidates={"incumbent": 2.0, "ring": 1.0},
                      winner="ring", winner_cost=1.0, margin=0.5)
    swap = bb.record("topology", "swap", step=step + 1, parent=synth,
                     winner="ring")
    commit = bb.record("topology", "commit", step=step + 7, parent=swap,
                       winner="ring")
    return trig, synth, swap, commit


# --------------------------------------------------------------------- #
# ring bound
# --------------------------------------------------------------------- #
def test_ring_bound_evicts_and_counts():
    bb = BlackBox(capacity=8)
    for i in range(20):
        bb.record("p", "k", step=i)
    assert len(bb) == 8
    assert bb.dropped == 12
    assert bb.n_recorded == 20
    # oldest fell off; the newest 8 remain, in order
    assert [ev.step for ev in bb.events()] == list(range(12, 20))
    assert bb.get(0) is None and bb.get(19) is not None


def test_eviction_leaves_chain_digest_streaming():
    a, b = BlackBox(capacity=4), BlackBox(capacity=1000)
    for i in range(16):
        a.record("p", "k", step=i)
        b.record("p", "k", step=i)
    assert a.dropped == 12 and b.dropped == 0
    assert a.chain_digest() == b.chain_digest()


def test_capacity_validation():
    with pytest.raises(ValueError):
        BlackBox(capacity=0)


# --------------------------------------------------------------------- #
# chain digest
# --------------------------------------------------------------------- #
def test_chain_digest_deterministic_and_ignores_wall_time():
    a, b = BlackBox(capacity=64), BlackBox(capacity=64)
    _chain(a)
    _chain(b)
    assert a.chain_digest() == b.chain_digest()
    # detail and t are rendering-only: they differ freely between a
    # real run and its simulated twin without breaking chain equality
    c = BlackBox(capacity=64)
    trig = c.record("topology", "trigger", step=0,
                    telemetry={"reason": "degraded",
                               "secs": {"0-1": 0.5}},
                    detail={"note": "totally different"})
    c.record("topology", "synthesize", step=0, parent=trig,
             telemetry={"reason": "degraded"},
             candidates={"incumbent": 2.0, "ring": 1.0},
             winner="ring", winner_cost=1.0, margin=0.5,
             detail={"other": 42})
    c.record("topology", "swap", step=1, parent=c.events()[-1])
    partial = BlackBox(capacity=64)
    t2, s2, _, _ = _chain(partial)
    # identical first three structural records -> same digest prefix
    # behavior: re-record the same three into a fresh box and compare
    d = BlackBox(capacity=64)
    trig_d = d.record("topology", "trigger", step=0,
                      telemetry={"reason": "degraded",
                                 "secs": {"0-1": 0.5}})
    d.record("topology", "synthesize", step=0, parent=trig_d,
             telemetry={"reason": "degraded"},
             candidates={"incumbent": 2.0, "ring": 1.0},
             winner="ring", winner_cost=1.0, margin=0.5)
    d.record("topology", "swap", step=1, parent=d.events()[-1])
    assert c.chain_digest() == d.chain_digest()


def test_digest_sensitive_to_structural_fields():
    base = BlackBox(capacity=8)
    base.record("p", "k", step=0, winner="a", winner_cost=1.0)
    for kw in ({"winner": "b", "winner_cost": 1.0},
               {"winner": "a", "winner_cost": 2.0},
               {"winner": "a", "winner_cost": 1.0, "margin": 0.1}):
        other = BlackBox(capacity=8)
        other.record("p", "k", step=0, **kw)
        assert other.chain_digest() != base.chain_digest()


def test_telemetry_digest_is_canonical():
    bb = BlackBox(capacity=8)
    e1 = bb.record("p", "k", step=0, telemetry={"a": 1.0, "b": 2.0})
    e2 = bb.record("p", "k", step=1, telemetry={"b": 2.0, "a": 1.0})
    assert e1.telemetry_digest == e2.telemetry_digest
    e3 = bb.record("p", "k", step=2, telemetry={"a": 1.0, "b": 2.5})
    assert e3.telemetry_digest != e1.telemetry_digest
    assert bb.record("p", "k", step=3).telemetry_digest == ""


# --------------------------------------------------------------------- #
# causal chaining + outcome resolution
# --------------------------------------------------------------------- #
def test_chain_links_and_explain():
    bb = BlackBox(capacity=64)
    trig, synth, swap, commit = _chain(bb)
    assert [ev.event_id for ev in bb.chain(commit)] == [
        trig.event_id, synth.event_id, swap.event_id, commit.event_id]
    # chain() through the ROOT walks the subtree below it too
    assert [ev.event_id for ev in bb.chain(trig)] == [
        trig.event_id, synth.event_id, swap.event_id, commit.event_id]
    assert [ev.event_id for ev in bb.children(trig.event_id)] == [
        synth.event_id]
    text = bb.explain(commit)
    for needle in ("trigger", "synthesize", "swap", "commit",
                   "winner=ring", "outcome=committed"):
        assert needle in text
    assert bb.explain(10_000) == "(no such decision in the ring)"


def test_terminal_kind_resolves_ancestors_not_digest():
    bb = BlackBox(capacity=64)
    trig = bb.record("topology", "trigger", step=0)
    synth = bb.record("topology", "synthesize", step=0, parent=trig,
                      winner="ring", winner_cost=1.0)
    pre = bb.chain_digest()
    assert trig.outcome == "pending" and synth.outcome == "pending"
    bb.record("topology", "rollback", step=5, parent=synth)
    assert trig.outcome == "rolled_back"
    assert synth.outcome == "rolled_back"
    # resolution is rendering-only: it appended exactly one line
    # (the rollback's own), never rewrote the ancestors' lines
    twin = BlackBox(capacity=64)
    t2 = twin.record("topology", "trigger", step=0)
    twin.record("topology", "synthesize", step=0, parent=t2,
                winner="ring", winner_cost=1.0)
    assert twin.chain_digest() == pre


def test_outcome_does_not_cross_chains():
    bb = BlackBox(capacity=64)
    other = bb.record("mix", "swap", step=0)
    trig = bb.record("topology", "trigger", step=1)
    bb.record("topology", "commit", step=2, parent=trig)
    assert trig.outcome == "committed"
    assert other.outcome == "pending"


# --------------------------------------------------------------------- #
# record_decision routing + config knobs
# --------------------------------------------------------------------- #
def test_record_decision_false_is_hard_off(monkeypatch):
    monkeypatch.setenv("BLUEFOG_BLACKBOX", "1")
    assert record_decision("p", "k", step=0, blackbox=False) is None


def test_record_decision_explicit_box_is_unconditional(monkeypatch):
    monkeypatch.setenv("BLUEFOG_BLACKBOX", "0")
    bb = BlackBox(capacity=8)
    ev = record_decision("p", "k", step=0, blackbox=bb)
    assert ev is not None and len(bb) == 1


def test_record_decision_global_gated_by_env(monkeypatch):
    monkeypatch.setattr(BB, "_global_blackbox", None)
    monkeypatch.setenv("BLUEFOG_BLACKBOX", "0")
    assert not config.blackbox_enabled()
    assert record_decision("p", "k", step=0) is None
    assert BB._global_blackbox is None  # off never materializes a ring
    monkeypatch.setenv("BLUEFOG_BLACKBOX", "1")
    ev = record_decision("p", "k", step=0)
    assert ev is not None
    assert BB.get_blackbox().get(ev.event_id) is ev


def test_capacity_env_knob(monkeypatch):
    monkeypatch.setenv("BLUEFOG_BLACKBOX_CAPACITY", "17")
    assert BlackBox().capacity == 17
    monkeypatch.setenv("BLUEFOG_BLACKBOX_CAPACITY", "not-a-number")
    assert BlackBox().capacity == 4096


# --------------------------------------------------------------------- #
# anomaly dump
# --------------------------------------------------------------------- #
def test_anomaly_dumps_once_per_kind(tmp_path, monkeypatch):
    monkeypatch.setenv("BLUEFOG_BLACKBOX_DUMP", str(tmp_path))
    bb = BlackBox(capacity=64)
    trig = bb.record("topology", "trigger", step=0)
    bb.record("topology", "rollback", step=5, parent=trig)
    path = tmp_path / "blackbox_rollback.jsonl"
    assert path.exists()
    first = path.read_text()
    # second rollback: evidence already preserved, no rewrite
    bb.record("topology", "rollback", step=9)
    assert path.read_text() == first
    # a different anomaly kind gets its own file, with the full ring
    bb.record("serving", "lost", step=10, detail={"rid": 3})
    lost = (tmp_path / "blackbox_lost.jsonl").read_text()
    meta = json.loads(lost.splitlines()[0])["blackbox"]
    assert meta["n_recorded"] == 4
    assert meta["chain_digest"] == bb.chain_digest()
    assert "rank_join_failed" in ANOMALY_KINDS  # the contract set
    # non-anomaly kinds never dump
    bb.record("topology", "commit", step=11)
    assert sorted(p.name for p in tmp_path.iterdir()) == [
        "blackbox_lost.jsonl", "blackbox_rollback.jsonl"]


# --------------------------------------------------------------------- #
# export: JSONL round trip + CLI
# --------------------------------------------------------------------- #
def test_jsonl_round_trips():
    bb = BlackBox(capacity=64)
    _, synth, _, commit = _chain(bb)
    lines = bb.jsonl().strip().splitlines()
    meta = json.loads(lines[0])["blackbox"]
    assert meta == {"n_recorded": 4, "retained": 4, "dropped": 0,
                    "capacity": 64,
                    "chain_digest": bb.chain_digest()}
    evs = [DecisionEvent.from_json(json.loads(ln)) for ln in lines[1:]]
    assert [e.canonical_line() for e in evs] == [
        e.canonical_line() for e in bb.events()]
    assert evs[1].candidates == {"incumbent": 2.0, "ring": 1.0}
    assert evs[3].outcome == "committed"


def test_cli_renders_chains_from_dump(tmp_path, capsys):
    bb = BlackBox(capacity=64)
    _, _, _, commit = _chain(bb)
    dump = tmp_path / "ring.jsonl"
    bb.dump(str(dump))
    assert BB.main([str(dump)]) == 0
    out = capsys.readouterr().out
    assert "trigger" in out and "commit" in out
    assert BB.main([str(dump), "--explain", str(commit.event_id)]) == 0
    out = capsys.readouterr().out
    assert out.count("\n") == 5  # header + 4 events
    assert "outcome=committed" in out
    assert BB.main([str(dump), "--explain", "9999"]) == 1


def test_cli_empty_ring(tmp_path, capsys):
    dump = tmp_path / "empty.jsonl"
    dump.write_text("")
    assert BB.main([str(dump)]) == 0
    assert "(empty ring)" in capsys.readouterr().out


# --------------------------------------------------------------------- #
# metrics
# --------------------------------------------------------------------- #
def test_metrics_publish_to_injected_registry():
    reg = MetricsRegistry()
    bb = BlackBox(capacity=4, registry=reg)
    _chain(bb)
    assert reg.counter("bf_decisions_total", plane="topology",
                       kind="trigger", outcome="pending").value == 1
    assert reg.counter("bf_decisions_total", plane="topology",
                       kind="commit", outcome="committed").value == 1
    # overflow moves the dropped gauge
    for i in range(6):
        bb.record("p", "k", step=i)
    assert reg.gauge("bf_blackbox_dropped_events").value == 6.0


def test_metrics_handles_are_cached():
    reg = MetricsRegistry()
    bb = BlackBox(capacity=64, registry=reg)
    for i in range(5):
        bb.record("p", "k", step=i)
    assert len(bb._counter_cache) == 1
    assert reg.counter("bf_decisions_total", plane="p", kind="k",
                       outcome="pending").value == 5
