"""Window (one-sided gossip) op tests.

Mirrors reference test/torch_win_ops_test.py: lifecycle (:64-140),
win_update default/weighted/collect (:141-244), win_put/accumulate/get incl.
partial destinations (:245-704), versions, and the associated-P push-sum
invariant sum(p) == size (:780-863).
"""

import numpy as np
import pytest

import bluefog_tpu as bf
from bluefog_tpu.topology import ExponentialTwoGraph, RingGraph

SIZE = 8


def rank_tensor(shape, dtype=np.float64):
    return bf.from_rank_values(lambda r: np.full(shape, r, dtype=dtype))


# ------------------------------------------------------------------ #
# lifecycle
# ------------------------------------------------------------------ #
def test_win_create_free(bf_ctx):
    x = rank_tensor((4,))
    assert bf.win_create(x, "w_life")
    assert not bf.win_create(x, "w_life")  # duplicate
    assert bf.get_current_created_window_names() == ["w_life"]
    assert bf.win_free("w_life")
    assert not bf.win_free("w_life")
    assert bf.get_current_created_window_names() == []


def test_win_free_all(bf_ctx):
    x = rank_tensor((2,))
    bf.win_create(x, "w_a")
    bf.win_create(x, "w_b")
    assert bf.win_free()
    assert bf.get_current_created_window_names() == []


# ------------------------------------------------------------------ #
# win_update semantics
# ------------------------------------------------------------------ #
def test_win_update_initial_is_neighbor_avg(bf_ctx):
    """Buffers init to the creator's value (not zero), so the first update
    without puts averages self with the *initial* neighbor values
    (reference torch_win_ops_test.py:141-170)."""
    bf.set_topology(RingGraph(SIZE))
    x = rank_tensor((3,))
    bf.win_create(x, "w_upd")
    out = np.asarray(bf.win_update("w_upd"))
    for r in range(SIZE):
        nbrs = [(r - 1) % SIZE, (r + 1) % SIZE]
        expected = (r + sum(nbrs)) / 3
        np.testing.assert_allclose(out[r], expected, atol=1e-12)
    bf.win_free("w_upd")


def test_win_update_zero_init(bf_ctx):
    bf.set_topology(RingGraph(SIZE))
    x = rank_tensor((3,))
    bf.win_create(x, "w_zero", zero_init=True)
    out = np.asarray(bf.win_update("w_zero"))
    for r in range(SIZE):
        np.testing.assert_allclose(out[r], r / 3, atol=1e-12)
    bf.win_free("w_zero")


def test_win_put_then_update(bf_ctx):
    """win_put then win_update: average of self + put values
    (reference :245-330)."""
    bf.set_topology(RingGraph(SIZE))
    x = rank_tensor((4,))
    bf.win_create(x, "w_put", zero_init=True)
    assert bf.win_put(x, "w_put")
    out = np.asarray(bf.win_update("w_put"))
    for r in range(SIZE):
        nbrs = [(r - 1) % SIZE, (r + 1) % SIZE]
        expected = (r + sum(nbrs)) / 3
        np.testing.assert_allclose(out[r], expected, atol=1e-12)
    bf.win_free("w_put")


def test_win_put_partial_destinations(bf_ctx):
    """dst_weights with a subset of out-neighbors (reference :331-420)."""
    bf.set_topology(RingGraph(SIZE))
    x = rank_tensor((2,))
    bf.win_create(x, "w_part", zero_init=True)
    # only push rightward: r -> r+1, with weight 2.0
    dst = [{(r + 1) % SIZE: 2.0} for r in range(SIZE)]
    assert bf.win_put(x, "w_part", dst_weights=dst)
    # update with explicit weights reading only the left neighbor
    nbr_w = [{(r - 1) % SIZE: 0.5} for r in range(SIZE)]
    out = np.asarray(bf.win_update("w_part", self_weight=0.5,
                                   neighbor_weights=nbr_w))
    for r in range(SIZE):
        expected = 0.5 * r + 0.5 * 2.0 * ((r - 1) % SIZE)
        np.testing.assert_allclose(out[r], expected, atol=1e-12)
    bf.win_free("w_part")


def test_win_fence_observes_scaled_self_value(bf_ctx):
    """round-5 verdict item 7: win_fence blocks on the window VALUE as
    well as the mailbox — after a fence, the self_weight rescale a
    win_put applied to the local window tensor is observable."""
    bf.set_topology(RingGraph(SIZE))
    x = rank_tensor((2,))
    bf.win_create(x, "w_fence", zero_init=True)
    bf.win_put(x, "w_fence", self_weight=0.5)
    bf.win_fence("w_fence")
    win_value = np.asarray(bf_win_value("w_fence"))
    for r in range(SIZE):
        np.testing.assert_allclose(win_value[r], 0.5 * r)
    bf.win_free("w_fence")


def test_win_put_self_weight_scales_local(bf_ctx):
    """win_put's self_weight multiplies the local window tensor in place
    (reference mpi_ops.py:1161-1175 'In-place multiply')."""
    bf.set_topology(RingGraph(SIZE))
    x = rank_tensor((2,))
    bf.win_create(x, "w_selfw", zero_init=True)
    bf.win_put(x, "w_selfw", self_weight=0.5)
    win_value = np.asarray(bf_win_value("w_selfw"))
    for r in range(SIZE):
        np.testing.assert_allclose(win_value[r], 0.5 * r)
    bf.win_free("w_selfw")


def bf_win_value(name):
    from bluefog_tpu import api
    return api._wm().window(name).value


def test_win_accumulate(bf_ctx):
    """Accumulate adds into the buffer (reference :420-520)."""
    bf.set_topology(RingGraph(SIZE))
    x = rank_tensor((2,))
    bf.win_create(x, "w_acc", zero_init=True)
    assert bf.win_accumulate(x, "w_acc")
    assert bf.win_accumulate(x, "w_acc")  # twice -> buffers hold 2*src
    nbr_w = [
        {(r - 1) % SIZE: 1.0, (r + 1) % SIZE: 1.0} for r in range(SIZE)
    ]
    out = np.asarray(bf.win_update("w_acc", self_weight=1.0,
                                   neighbor_weights=nbr_w))
    for r in range(SIZE):
        expected = r + 2 * ((r - 1) % SIZE) + 2 * ((r + 1) % SIZE)
        np.testing.assert_allclose(out[r], expected, atol=1e-12)
    bf.win_free("w_acc")


def test_win_get(bf_ctx):
    """win_get pulls the source's window value (reference :520-610)."""
    bf.set_topology(RingGraph(SIZE))
    x = rank_tensor((2,))
    bf.win_create(x, "w_get", zero_init=True)
    assert bf.win_get("w_get")
    out = np.asarray(bf.win_update("w_get"))
    for r in range(SIZE):
        nbrs = [(r - 1) % SIZE, (r + 1) % SIZE]
        expected = (r + sum(nbrs)) / 3
        np.testing.assert_allclose(out[r], expected, atol=1e-12)
    bf.win_free("w_get")


def test_win_update_then_collect(bf_ctx):
    """Collect: sum self + all buffers, then reset buffers
    (reference :200-244)."""
    bf.set_topology(RingGraph(SIZE))
    x = rank_tensor((2,))
    bf.win_create(x, "w_col", zero_init=True)
    bf.win_put(x, "w_col")
    out = np.asarray(bf.win_update_then_collect("w_col"))
    for r in range(SIZE):
        expected = r + ((r - 1) % SIZE) + ((r + 1) % SIZE)
        np.testing.assert_allclose(out[r], expected, atol=1e-12)
    # buffers were reset: a second collect only returns the (new) self value
    out2 = np.asarray(bf.win_update_then_collect("w_col"))
    np.testing.assert_allclose(out2, out, atol=1e-12)
    bf.win_free("w_col")


def test_win_versions(bf_ctx):
    """Versions bump on put and clear on update (reference
    get_win_version, mpi_ops.py:1397-1416)."""
    bf.set_topology(RingGraph(SIZE))
    x = rank_tensor((2,))
    bf.win_create(x, "w_ver", zero_init=True)
    v0 = bf.get_win_version("w_ver", rank=0)
    assert v0 == {1: 0, 7: 0}
    bf.win_put(x, "w_ver")
    v1 = bf.get_win_version("w_ver", rank=0)
    assert v1 == {1: 1, 7: 1}
    bf.win_put(x, "w_ver")
    assert bf.get_win_version("w_ver", rank=0) == {1: 2, 7: 2}
    bf.win_update("w_ver")
    assert bf.get_win_version("w_ver", rank=0) == {1: 0, 7: 0}
    bf.win_free("w_ver")


def test_win_mutex_and_lock_contexts(bf_ctx):
    x = rank_tensor((2,))
    bf.win_create(x, "w_mutex")
    with bf.win_mutex("w_mutex"):
        bf.win_update("w_mutex")
    with bf.win_lock("w_mutex"):
        pass
    bf.win_fence("w_mutex")
    bf.win_free("w_mutex")


def test_win_nonblocking_handles(bf_ctx):
    x = rank_tensor((2,))
    bf.win_create(x, "w_nb", zero_init=True)
    h = bf.win_put_nonblocking(x, "w_nb")
    assert bf.win_poll(h) in (True, False)
    assert bf.win_wait(h)
    assert not bf.win_wait(h)  # already cleared
    bf.win_free("w_nb")


# ------------------------------------------------------------------ #
# associated-P (push-sum) invariant — reference :780-863
# ------------------------------------------------------------------ #
def test_associated_p_sum_invariant(bf_ctx):
    """Random async accumulate/update rounds preserve sum(p) == size when
    weights are column-stochastic."""
    bf.set_topology(ExponentialTwoGraph(SIZE))
    bf.turn_on_win_ops_with_associated_p()
    try:
        x = rank_tensor((4,))
        bf.win_create(x, "w_ps", zero_init=True)
        rng = np.random.default_rng(0)
        graph = bf.load_topology()
        out_nbrs = {r: sorted(d for d in graph.successors(r) if d != r)
                    for r in range(SIZE)}
        value = x
        for _ in range(5):
            # column-stochastic: self + dst weights sum to 1 per source
            alpha = {r: 1.0 / (len(out_nbrs[r]) + 1) for r in range(SIZE)}
            dst_w = [{d: alpha[r] for d in out_nbrs[r]} for r in range(SIZE)]
            self_w = [alpha[r] for r in range(SIZE)]
            bf.win_accumulate(value, "w_ps", self_weight=self_w,
                              dst_weights=dst_w)
            value = bf.win_update_then_collect("w_ps")
            ps = [bf.win_associated_p("w_ps", rank=r) for r in range(SIZE)]
            np.testing.assert_allclose(sum(ps), SIZE, rtol=1e-10)
        bf.win_free("w_ps")
    finally:
        bf.turn_off_win_ops_with_associated_p()


def test_push_sum_converges_to_average(bf_ctx):
    """The full push-sum recursion x/p -> mean(x0) (the algorithmic point of
    associated-P, reference pytorch_optimization.py push_diging)."""
    bf.set_topology(ExponentialTwoGraph(SIZE))
    bf.turn_on_win_ops_with_associated_p()
    try:
        x0 = bf.from_rank_values(
            lambda r: np.array([float(r), 2.0 * r]))
        bf.win_create(x0, "w_psavg", zero_init=True)
        graph = bf.load_topology()
        out_nbrs = {r: sorted(d for d in graph.successors(r) if d != r)
                    for r in range(SIZE)}
        value = x0
        for _ in range(60):
            alpha = {r: 1.0 / (len(out_nbrs[r]) + 1) for r in range(SIZE)}
            dst_w = [{d: alpha[r] for d in out_nbrs[r]} for r in range(SIZE)]
            self_w = [alpha[r] for r in range(SIZE)]
            bf.win_accumulate(value, "w_psavg", self_weight=self_w,
                              dst_weights=dst_w)
            value = bf.win_update_then_collect("w_psavg")
        ps = np.array([bf.win_associated_p("w_psavg", rank=r)
                       for r in range(SIZE)])
        debiased = np.asarray(value) / ps[:, None]
        mean = np.mean([[r, 2.0 * r] for r in range(SIZE)], axis=0)
        np.testing.assert_allclose(debiased, np.tile(mean, (SIZE, 1)),
                                   rtol=1e-6)
    finally:
        bf.turn_off_win_ops_with_associated_p()


def test_varying_gossip_weights_do_not_recompile(bf_ctx):
    """Round-1 hazard regression (windows.py): per-step gossip weights used
    to be baked into the compile-cache key, so any dynamic schedule
    retraced every step with unbounded cache growth.  Weights are traced
    operands now: N steps with N different weight sets -> ONE cached
    program per op kind."""
    from bluefog_tpu.context import get_context

    bf.set_topology(ExponentialTwoGraph(SIZE))
    x = bf.from_rank_values(lambda r: np.full((3,), float(r)))
    bf.win_create(x, "w_retrace")
    graph = bf.load_topology()
    out_nbrs = {r: sorted(d for d in graph.successors(r) if d != r)
                for r in range(SIZE)}
    in_nbrs = {r: sorted(s for s in graph.predecessors(r) if s != r)
               for r in range(SIZE)}
    ctx = get_context()
    cache_sizes = []
    for step in range(6):
        scale = 1.0 / (2.0 + step)  # different weights every step
        dst_w = [{d: scale for d in out_nbrs[r]} for r in range(SIZE)]
        self_w = [1.0 - scale * len(out_nbrs[r]) for r in range(SIZE)]
        bf.win_put(x, "w_retrace", self_weight=self_w, dst_weights=dst_w)
        nbr_w = [{s: scale for s in in_nbrs[r]} for r in range(SIZE)]
        x = bf.win_update("w_retrace", self_weight=self_w,
                          neighbor_weights=nbr_w)
        cache_sizes.append(len(ctx._op_cache))
    # cache stabilizes after the first step: no per-step growth
    assert cache_sizes[-1] == cache_sizes[0], cache_sizes
    bf.win_free("w_retrace")


def test_put_weight_variation_changes_values_not_programs(bf_ctx):
    """Varying weights through the one cached program still produces the
    right numbers (weights really are traced operands, not constants)."""
    bf.set_topology(RingGraph(SIZE))
    x = bf.from_rank_values(lambda r: np.full((2,), float(r)))
    bf.win_create(x, "w_wval")
    for w in (0.5, 0.25):
        bf.win_put(x, "w_wval", self_weight=1.0,
                   dst_weights=[{(r + 1) % SIZE: w} for r in range(SIZE)])
        from bluefog_tpu import api as bf_api
        win = bf_api._wm().window("w_wval")
        mb = np.asarray(win.mailbox)
        for r in range(SIZE):
            src = (r - 1) % SIZE
            slot = win.in_lists[r].index(src)
            np.testing.assert_allclose(mb[r, slot], w * src, rtol=1e-6)
    bf.win_free("w_wval")
