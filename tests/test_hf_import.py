"""HuggingFace Llama checkpoint import: converted weights must reproduce
``transformers``' logits to float32 roundoff — the interop contract for
users switching to this framework with published weights in hand
(reference users come from the torch ecosystem; SURVEY.md §2.1 torch
adapter role).
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from bluefog_tpu import models  # noqa: E402
from bluefog_tpu.interop.hf_llama import (  # noqa: E402
    llama_config_from_hf,
    llama_params_from_hf,
)

B, T = 2, 12


def _tiny_hf():
    hf_cfg = transformers.LlamaConfig(
        vocab_size=256, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2,
        intermediate_size=128, max_position_embeddings=256,
        rope_theta=500000.0, rms_norm_eps=1e-5, attention_bias=False,
        mlp_bias=False, tie_word_embeddings=False)
    torch.manual_seed(0)
    m = transformers.LlamaForCausalLM(hf_cfg)
    m = m.float().eval()
    return hf_cfg, m


def _hf_logits(hf_model, tokens_np):
    with torch.no_grad():
        out = hf_model(input_ids=torch.from_numpy(tokens_np).long())
    return out.logits.float().numpy()


@pytest.mark.parametrize("scan_layers", [False, True])
def test_hf_logits_match(scan_layers):
    hf_cfg, hf_model = _tiny_hf()
    cfg = llama_config_from_hf(hf_cfg, dtype=jnp.float32,
                               scan_layers=scan_layers)
    params = llama_params_from_hf(hf_model, cfg)
    model = models.Llama(cfg)
    rng = np.random.RandomState(0)
    tokens = rng.randint(0, 256, size=(B, T)).astype(np.int32)

    ours = np.asarray(model.apply(params, tokens))
    theirs = _hf_logits(hf_model, tokens)
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)


def test_hf_config_mapping():
    hf_cfg, _ = _tiny_hf()
    cfg = llama_config_from_hf(hf_cfg)
    assert cfg.dim == 64 and cfg.n_layers == 2
    assert cfg.n_heads == 4 and cfg.n_kv_heads == 2
    assert cfg.ffn_dim == 128 and cfg.rope_theta == 500000.0


def test_hf_rope_scaled_logits_match():
    """Round-2 verdict item 6: a Llama-3.1-style rope-scaled checkpoint
    (rope_type='llama3') converts AND reproduces transformers' logits —
    mainstream checkpoints no longer bounce off the importer."""
    hf_cfg = transformers.LlamaConfig(
        vocab_size=256, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2,
        intermediate_size=128, max_position_embeddings=256,
        rope_theta=500000.0, rms_norm_eps=1e-5, attention_bias=False,
        mlp_bias=False, tie_word_embeddings=False,
        rope_scaling={"rope_type": "llama3", "factor": 8.0,
                      "low_freq_factor": 1.0, "high_freq_factor": 4.0,
                      "original_max_position_embeddings": 32})
    torch.manual_seed(0)
    hf_model = transformers.LlamaForCausalLM(hf_cfg).float().eval()
    cfg = llama_config_from_hf(hf_cfg, dtype=jnp.float32)
    assert cfg.rope_scaling_kind == "llama3"
    assert cfg.rope_scaling == (8.0, 1.0, 4.0, 32)
    params = llama_params_from_hf(hf_model, cfg)
    model = models.Llama(cfg)
    # positions past original_max_position_embeddings/factor exercise the
    # scaled low-frequency band
    tokens = np.random.RandomState(0).randint(
        0, 256, size=(B, 48)).astype(np.int32)
    ours = np.asarray(model.apply(params, tokens))
    theirs = _hf_logits(hf_model, tokens)
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)
    # and the scaling genuinely changes the model (guards against the
    # scaling being silently dropped on either side)
    plain = llama_config_from_hf(hf_cfg, dtype=jnp.float32,
                                 rope_scaling_kind="none")
    unscaled = np.asarray(models.Llama(plain).apply(
        llama_params_from_hf(hf_model, plain), tokens))
    assert np.abs(unscaled - theirs).max() > 1e-3


def test_hf_unsupported_features_raise():
    """Features this framework does not implement must fail loudly: a
    silent pass-through (e.g. yarn rope scaling) would convert into a
    model whose logits quietly diverge from transformers."""
    hf_cfg, _ = _tiny_hf()
    hf_cfg.rope_scaling = {"rope_type": "yarn", "factor": 8.0}
    with pytest.raises(NotImplementedError, match="rope_scaling"):
        llama_config_from_hf(hf_cfg)
    # llama3 with missing sub-fields must refuse, not guess defaults
    hf_cfg.rope_scaling = {"rope_type": "llama3", "factor": 8.0}
    with pytest.raises(ValueError, match="missing required"):
        llama_config_from_hf(hf_cfg)
    hf_cfg.rope_scaling = None
    hf_cfg.attention_bias = True
    with pytest.raises(NotImplementedError, match="attention_bias"):
        llama_config_from_hf(hf_cfg)


def test_hf_import_feeds_parallel_layouts():
    """The imported tree is the same TREE every parallel layout uses:
    shard it rank-major with pp specs and take one pipelined step."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    import optax

    from bluefog_tpu.models.llama import llama_param_specs, llama_pp_loss_fn
    from bluefog_tpu.optim import functional as F

    hf_cfg, hf_model = _tiny_hf()
    cfg = llama_config_from_hf(hf_cfg, dtype=jnp.float32, scan_layers=True)
    variables = llama_params_from_hf(hf_model, cfg)

    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("bf", "pp"))
    specs = llama_param_specs(variables, tp_axis=None, ep_axis=None,
                              pp_axis="pp")
    opt = optax.sgd(0.1)
    opt_specs = F.optax_state_specs(opt, variables, specs)
    step = F.build_train_step(
        llama_pp_loss_fn(cfg, pp_axis="pp", n_stages=2, n_micro=2),
        opt, mesh, comm_mode="none", pp_axis="pp", batch_specs=P("bf"),
        param_specs=specs, opt_state_specs=opt_specs, donate=False)
    params = F.rank_major(variables, mesh, specs=specs)
    opt_state = F.rank_major(opt.init(variables), mesh, specs=opt_specs)
    raw = np.random.RandomState(0).randint(
        0, 256, (2, B, T + 1)).astype(np.int32)
    sharding = NamedSharding(mesh, P("bf"))
    batch = (jax.device_put(raw[:, :, :-1], sharding),
             jax.device_put(raw[:, :, 1:], sharding))
    _, _, loss = step(params, opt_state, batch, jnp.int32(0))
    assert np.all(np.isfinite(np.asarray(loss)))
