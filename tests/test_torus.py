"""Torus-aware schedules + machine-checked ICI congestion accounting
(topology/torus.py) — the round-4 evidence behind the scaling projection's
pessimistic routing model.

The reference has no counterpart (its NCCL/MPI backends never see link
topology); these tests pin the congestion counter to hand-derived cases
and the torus schedules to their construction guarantees.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bluefog_tpu.optim import functional as F
from bluefog_tpu.topology import (
    TorusSpec,
    consensus_contraction,
    one_peer_dynamic_schedule,
    round_congestion,
    rounds_to_consensus,
    schedule_congestion,
    torus_one_peer_schedule,
    torus_shift_round,
)
from bluefog_tpu.topology.torus import link_loads, mixing_matrix

N = 8


def test_coord_rank_roundtrip_and_neighbors():
    spec = TorusSpec((4, 8))
    for r in range(spec.size):
        assert spec.rank(spec.coord(r)) == r
    # wraparound neighbors on both axes
    assert spec.is_neighbor(spec.rank((0, 0)), spec.rank((3, 0)))
    assert spec.is_neighbor(spec.rank((0, 0)), spec.rank((0, 7)))
    assert not spec.is_neighbor(spec.rank((0, 0)), spec.rank((1, 1)))
    assert not spec.is_neighbor(spec.rank((0, 0)), spec.rank((0, 2)))


def test_unit_shift_congestion_is_one():
    """A +1 rotation along a ring loads every directed link exactly once."""
    spec = TorusSpec((8,))
    send = {r: (r + 1) % 8 for r in range(8)}
    loads = link_loads(send, spec)
    assert set(loads.values()) == {1.0}
    assert len(loads) == 8  # the 8 forward links, nothing else


def test_half_ring_shift_splits_both_directions():
    """An L/2 shift ties both directions; splitting halves the congestion
    (4 nodes x 2 hops x 0.5 = 1.0 per directed link on a 4-ring)."""
    spec = TorusSpec((4,))
    send = {r: (r + 2) % 4 for r in range(4)}
    assert round_congestion(send, spec) == pytest.approx(1.0)


def test_tie_split_walks_opposite_semicircle():
    """The -1 half of an L/2 tie must load the backward semicircle FROM
    THE SOURCE, not retrace the forward path's links in reverse (round-4
    review regression): send {0->4, 6->5, 7->6} on an 8-ring piles the
    0->4 backward half (links leaving 0,7,6,5 in -1) on top of the two
    -1 unit hops, so links (7,-1) and (6,-1) carry 1.5 payloads."""
    spec = TorusSpec((8,))
    loads = link_loads({0: 4, 6: 5, 7: 6}, spec)
    assert loads[((7,), 0, -1)] == pytest.approx(1.5)
    assert loads[((6,), 0, -1)] == pytest.approx(1.5)
    assert round_congestion({0: 4, 6: 5, 7: 6}, spec) == pytest.approx(1.5)


def test_long_shift_congestion_matches_hand_count():
    """Shift +2 on an 8-ring: every payload takes 2 forward hops; each of
    the 8 forward links carries exactly 2 payloads."""
    spec = TorusSpec((8,))
    send = {r: (r + 2) % 8 for r in range(8)}
    assert round_congestion(send, spec) == pytest.approx(2.0)
    # and the backward direction is minimal for shift +6
    send = {r: (r + 6) % 8 for r in range(8)}
    assert round_congestion(send, spec) == pytest.approx(2.0)


def test_exp2_schedule_congestion_beats_1d_bound():
    """The one-peer exp2 schedule machine-routed on the (8, 16) torus is
    far below the 1-D closed-form min(2^k, n-2^k) hop guess — the round-3
    projection's pessimistic model was a loose bound, not the truth."""
    spec = TorusSpec((8, 16))
    sched = one_peer_dynamic_schedule(128)
    prof = schedule_congestion(sched, spec)
    one_d = [min(2 ** k, 128 - 2 ** k) for k in range(7)]
    assert prof["mean"] < np.mean(one_d) / 5  # 2.29 vs 18.14
    for got, bound in zip(prof["per_round"], one_d):
        assert 1.0 <= got <= bound


def test_single_hop_schedule_properties():
    """Every round: a permutation of in/out degree 1, every edge a physical
    ICI neighbor, congestion exactly 1, weights 1/2-1/2."""
    for axes in ((2, 4), (8, 16)):
        spec = TorusSpec(axes)
        sched = torus_one_peer_schedule(axes, "single_hop")
        assert len(sched) == sum(2 if L > 2 else 1 for L in axes)
        for rnd in sched:
            srcs = [s for s, _ in rnd.edges]
            dsts = [d for _, d in rnd.edges]
            assert sorted(srcs) == list(range(spec.size))
            assert sorted(dsts) == list(range(spec.size))
            assert all(spec.is_neighbor(s, d) for s, d in rnd.edges)
            # length-2 axes have two links joining each pair (wrap +
            # direct), so the tie-split halves the load there
            cong = round_congestion(rnd, spec)
            if min(axes) > 2:
                assert cong == pytest.approx(1.0)
            else:
                assert cong <= 1.0
            assert set(rnd.edge_weight_values) == {0.5}
            assert set(rnd.self_weight_values) == {0.5}


def test_exp2_mode_reaches_exact_average():
    """Per-axis exp2 with power-of-two axes: one period is exact recursive
    halving (sigma == 0), both on (4, 4) and the pod shape (8, 16)."""
    for axes in ((4, 4), (8, 16)):
        sched = torus_one_peer_schedule(axes, "exp2")
        assert consensus_contraction(sched) < 1e-12
        assert rounds_to_consensus(sched) == len(sched)
        # simulate: arbitrary vector -> exact mean after one period
        n = int(np.prod(axes))
        x = np.arange(n, dtype=np.float64) ** 2
        for rnd in sched:
            x = mixing_matrix(rnd) @ x
        np.testing.assert_allclose(x, np.mean(np.arange(n) ** 2.0),
                                   rtol=1e-12)


def test_single_hop_mixing_contracts():
    """The single-hop schedule mixes (sigma < 1) but slower than exp2 —
    the tradeoff the projection's mixing table quantifies."""
    sched = torus_one_peer_schedule((4, 4), "single_hop")
    sigma = consensus_contraction(sched)
    assert 0.0 < sigma < 1.0
    r = rounds_to_consensus(sched, eps=1e-3)
    assert np.isfinite(r) and r > len(sched)


def test_shift_round_weight_structure():
    rnd = torus_shift_round(TorusSpec((2, 4)), axis=1, shift=1,
                            self_weight=0.75)
    assert set(rnd.edge_weight_values) == {0.25}
    W = mixing_matrix(rnd)
    np.testing.assert_allclose(W.sum(axis=1), 1.0)  # row-stochastic


def test_train_step_with_torus_schedule():
    """Integration: the single-hop torus schedule drives the jitted train
    step on the 8-device (2, 4) virtual torus and reaches consensus under
    pure averaging, exactly like the exp2 dynamic schedule."""
    mesh = Mesh(np.array(jax.devices()[:N]), ("bf",))
    schedule = torus_one_peer_schedule((2, 4), "single_hop")

    def loss_fn(params, batch):
        return jnp.mean((batch @ params["x"]) ** 2)

    step_fn = F.build_train_step(
        loss_fn, optax.sgd(0.0), mesh, comm_mode="cta", schedule=schedule)
    params = {"x": jax.device_put(
        np.arange(N * 4, dtype=np.float64).reshape(N, 4),
        NamedSharding(mesh, P("bf")))}
    opt_state = F.rank_major(optax.sgd(0.0).init({"x": jnp.zeros(4)}), mesh)
    batch = jax.device_put(np.ones((N, 2, 4)), NamedSharding(mesh, P("bf")))
    for i in range(20 * len(schedule)):
        params, opt_state, _ = step_fn(params, opt_state, batch,
                                       jnp.int32(i))
    assert float(F.consensus_distance(params)) < 1e-6


def test_score_schedule_figures():
    """score_schedule reports the per-step wire multiplier (mean
    congestion) and the congestion-weighted rounds to consensus —
    hand-checkable on the (8, 16) pod torus: exp2 = exact average in 7
    rounds at mean congestion 16/7, so cost_to_consensus == 16."""
    from bluefog_tpu.topology import score_schedule

    spec = TorusSpec((8, 16))
    exp2 = score_schedule(torus_one_peer_schedule((8, 16), "exp2"), spec)
    assert exp2["rounds_per_period"] == 7
    assert exp2["exact_average_per_period"] == 1.0
    np.testing.assert_allclose(exp2["mean_congestion"], 16 / 7, rtol=1e-12)
    np.testing.assert_allclose(exp2["cost_to_consensus"], 16.0, rtol=1e-12)
    hop = score_schedule(
        torus_one_peer_schedule((8, 16), "single_hop"), spec)
    assert hop["mean_congestion"] == 1.0
    assert hop["cost_to_consensus"] > 40 * exp2["cost_to_consensus"]


def test_default_pod_schedule_picks_exp2_on_pod_tori():
    """On power-of-two tori the machine-counted score selects the torus
    exp2 schedule (exact average, ~45x cheaper to consensus than
    single-hop), and the returned schedule is the winner itself."""
    from bluefog_tpu.topology import default_pod_schedule

    for axes in ((4, 4), (8, 16)):
        sched, report = default_pod_schedule(axes)
        assert report["exp2"]["selected"] == 1.0
        assert report["single_hop"]["selected"] == 0.0
        assert consensus_contraction(sched) < 1e-12  # it IS the exp2 one
        assert len(sched) == sum(
            int(np.log2(L)) for L in axes if L > 1)
    with pytest.raises(ValueError):
        default_pod_schedule((1, 1))


def test_default_pod_schedule_drives_train_step():
    """The selected default schedule plugs straight into build_train_step
    and reaches the exact average each period on the (2, 4) virtual
    torus."""
    from bluefog_tpu.topology import default_pod_schedule

    mesh = Mesh(np.array(jax.devices()[:N]), ("bf",))
    schedule, _ = default_pod_schedule((2, 4))

    def loss_fn(params, batch):
        return jnp.mean((batch @ params["x"]) ** 2)

    step_fn = F.build_train_step(
        loss_fn, optax.sgd(0.0), mesh, comm_mode="cta", schedule=schedule)
    params = {"x": jax.device_put(
        np.arange(N * 4, dtype=np.float64).reshape(N, 4),
        NamedSharding(mesh, P("bf")))}
    opt_state = F.rank_major(optax.sgd(0.0).init({"x": jnp.zeros(4)}), mesh)
    batch = jax.device_put(np.ones((N, 2, 4)), NamedSharding(mesh, P("bf")))
    for i in range(len(schedule)):
        params, opt_state, _ = step_fn(params, opt_state, batch,
                                       jnp.int32(i))
    # pure averaging (lr 0): one period -> exact consensus
    assert float(F.consensus_distance(params)) < 1e-6


def test_link_loads_duplicate_src_multi_shift_additive():
    """The multi-shift form of ``link_loads`` (an a2a round: one src
    sends to SEVERAL dsts in the same round) must price exactly like
    the sum of its per-shift parts: loads are additive over pair lists,
    duplicate pairs accumulate, and per-pair payloads scale linearly —
    the property the all-to-all compiler's round costs rest on."""
    spec = TorusSpec((4, 4))
    n = spec.size

    def shift_pairs(s):
        return [(i, (i + s) % n) for i in range(n)]

    a, b = shift_pairs(3), shift_pairs(7)
    both = link_loads(a + b, spec)           # duplicate srcs across shifts
    la, lb = link_loads(a, spec), link_loads(b, spec)
    merged = dict(la)
    for k, v in lb.items():
        merged[k] = merged.get(k, 0.0) + v
    assert set(both) == set(merged)
    for k in merged:
        assert both[k] == pytest.approx(merged[k])

    # duplicate PAIRS accumulate (the docstring's contract)
    twice = link_loads(a + a, spec)
    for k, v in la.items():
        assert twice[k] == pytest.approx(2.0 * v)

    # payloads scale each pair's contribution linearly
    scaled = link_loads(a, spec, payloads={p: 3.0 for p in a})
    for k, v in la.items():
        assert scaled[k] == pytest.approx(3.0 * v)
    # zero payload pairs route nothing
    assert link_loads(a, spec, payloads={p: 0.0 for p in a}) == {}
