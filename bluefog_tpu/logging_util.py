"""Leveled logging for bluefog_tpu.

Reference parity: the C++ ``BFLOG`` macros (bluefog/common/logging.h:54-73)
and the Python logger "bluefog" (bluefog/common/basics.py:27-34).  Level
comes from ``BLUEFOG_LOG_LEVEL`` with the same names.

``BLUEFOG_LOG_FORMAT=json`` switches to structured output: one JSON
object per line carrying ``ts`` (unix seconds), ``level``, ``logger``,
``rank``, and ``msg`` — what a log aggregator ingests without a parse
rule, and the textual counterpart of the observe subsystem's JSONL
event log (docs/observability.md).  When the calling thread is inside
an open tracer span, the line additionally carries ``span`` and
``track`` correlation fields, so structured logs JOIN against the
Chrome trace (grep the log, find the span, load the timeline).
"""

from __future__ import annotations

import json
import logging
import sys

from bluefog_tpu import config as bfconfig

_LEVELS = {
    "trace": logging.DEBUG,  # python logging has no TRACE; map to DEBUG
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warn": logging.WARNING,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "fatal": logging.CRITICAL,
}

_logger = None


class _JsonFormatter(logging.Formatter):
    """One JSON object per record; exceptions fold into ``exc``; the
    calling thread's open tracer span (if any) folds into
    ``span``/``track`` so the line joins the Chrome trace."""

    def format(self, record: logging.LogRecord) -> str:
        obj = {
            "ts": round(record.created, 6),
            "level": record.levelname,
            "logger": record.name,
            "rank": bfconfig.process_id() or 0,
            "msg": record.getMessage(),
        }
        try:
            # lazy import: logging comes up before (and without) the
            # observe layer; a formatter must never fail a log call
            from bluefog_tpu.observe.tracer import publish_tracer

            tr = publish_tracer()
            sp = tr.active_span() if tr is not None else None
            if sp is not None:
                obj["track"], obj["span"] = sp
        except Exception:
            pass
        if record.exc_info:
            obj["exc"] = self.formatException(record.exc_info)
        return json.dumps(obj)


def _make_formatter() -> logging.Formatter:
    if bfconfig.log_format() == "json":
        return _JsonFormatter()
    fmt = "[%(levelname)s] %(name)s: %(message)s"
    if not bfconfig.log_hide_time():
        fmt = "%(asctime)s " + fmt
    return logging.Formatter(fmt)


def get_logger() -> logging.Logger:
    global _logger
    if _logger is None:
        logger = logging.getLogger("bluefog_tpu")
        logger.setLevel(_LEVELS.get(bfconfig.log_level(), logging.WARNING))
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(_make_formatter())
        logger.addHandler(handler)
        logger.propagate = False
        _logger = logger
    return _logger
