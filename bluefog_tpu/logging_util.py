"""Leveled logging for bluefog_tpu.

Reference parity: the C++ ``BFLOG`` macros (bluefog/common/logging.h:54-73)
and the Python logger "bluefog" (bluefog/common/basics.py:27-34).  Level
comes from ``BLUEFOG_LOG_LEVEL`` with the same names.
"""

from __future__ import annotations

import logging
import sys

from bluefog_tpu import config as bfconfig

_LEVELS = {
    "trace": logging.DEBUG,  # python logging has no TRACE; map to DEBUG
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warn": logging.WARNING,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "fatal": logging.CRITICAL,
}

_logger = None


def get_logger() -> logging.Logger:
    global _logger
    if _logger is None:
        logger = logging.getLogger("bluefog_tpu")
        logger.setLevel(_LEVELS.get(bfconfig.log_level(), logging.WARNING))
        handler = logging.StreamHandler(sys.stderr)
        fmt = "[%(levelname)s] %(name)s: %(message)s"
        if not bfconfig.log_hide_time():
            fmt = "%(asctime)s " + fmt
        handler.setFormatter(logging.Formatter(fmt))
        logger.addHandler(handler)
        logger.propagate = False
        _logger = logger
    return _logger
