"""Jaxpr/HLO contract checker: the semantic half of the analyzer.

This pass builds the REAL programs — ``build_train_step`` across the
same comm_mode x guard x health x hierarchical x overlap matrix the
epilogue parity tests pin, the topology compiler's scheduled programs,
and the serving engine's resident executables — then walks their traced
jaxprs (and, for scheduled exchanges, their compiled HLO) to verify the
three framework contracts mechanically:

**weights-as-data** (:func:`check_step`)
    The comm-weight tables (``F.comm_weight_inputs`` pytree — class
    weights + self weights per round; the same shapes healing /
    elastic membership substitute at runtime) must enter the program as
    live traced invars with the declared avals.  Violations:

    * ``missing-weight-operand`` — the program doesn't end with the
      declared weight leaves (or their avals disagree);
    * ``dead-weight-operand`` — a weight invar exists but nothing
      reachable from the outputs consumes it (the combine ignored the
      traced table, i.e. it used something else — typically a baked
      constant);
    * ``baked-weight-const`` — a closed-over constant with a weight
      table's exact shape/dtype profile appears anywhere in the jaxpr
      (including sub-jaxprs).  This is the recompile bug: healing would
      swap the operand while XLA keeps folding the constant.

**no cond over per-rank-divergent predicates** (PR-3 guard rule)
    A forward replicated/per-rank taint walk: params / opt_state /
    batch shards and ``axis_index`` results are per-rank; the step
    counter, weight operands, and constants are replicated; ``psum``
    (and friends) launder per-rank values back to replicated;
    ``ppermute`` does not.  Any ``lax.cond``/``switch`` whose predicate
    carries per-rank taint is flagged ``divergent-cond``: under SPMD
    the branches would disagree across ranks inside one collective
    program — the silent-deadlock/garbage class of bug the guard
    refactor banned.

**collective contract** (:func:`check_collective_contracts`)
    The scheduled exchange programs (flat switch over the
    ``compile_topology`` schedule; hierarchical per-machine-round) are
    lowered and held to ``predicted_collectives`` through the supported
    :func:`bluefog_tpu.benchutil.verify_collective_contract` — permute
    count after in-degree-1 fusion, per-permute payload bytes,
    grouped-all-reduce count and replica groups.

:func:`run_sweep` runs everything; the CLI and the tier-1 test both
call it.  Mutation tests in tests/test_analysis.py prove the teeth: a
step with baked weight constants, a program that drops its weight
operand, a divergent cond, and a tampered prediction must each be
flagged.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from bluefog_tpu.analysis import Finding

__all__ = ["check_step", "check_traced", "check_collective_contracts",
           "check_serving_residents", "sweep_cases", "run_sweep",
           "N_RANKS"]

N_RANKS = 8          # the sweep's mesh width (tier-1 CPU device count)
_LARGE_CONST = 4096  # float elements: a closed-over tensor this big in
                     # a resident program is model state baked at trace
                     # time, not a legitimate epsilon/table

# collectives whose OUTPUT is identical on every rank of the axis —
# they launder per-rank taint back to replicated
_REPLICATING_PRIMS = {"psum", "psum2", "pmax", "pmin", "all_gather",
                      "all_gather_invariant", "reduce_scatter"}
# primitives that INTRODUCE per-rank divergence
_DIVERGING_PRIMS = {"axis_index"}


# --------------------------------------------------------------------- #
# jaxpr plumbing
# --------------------------------------------------------------------- #

def _as_open(j):
    """(core.Jaxpr, consts) from a jax.stages.Traced, a ClosedJaxpr,
    or a raw Jaxpr."""
    if hasattr(j, "jaxpr") and not hasattr(j, "consts"):
        j = j.jaxpr                  # Traced -> ClosedJaxpr
    if hasattr(j, "consts"):         # ClosedJaxpr
        return j.jaxpr, list(j.consts)
    return j, []


def _sub_jaxprs(eqn) -> List[Any]:
    """Every jaxpr-valued entry in an equation's params (pjit 'jaxpr',
    shard_map 'jaxpr', cond 'branches', scan 'jaxpr', while
    'cond_jaxpr'/'body_jaxpr', custom_* 'call_jaxpr'/'fun_jaxpr'...),
    discovered structurally so new primitives are covered for free."""
    subs: List[Any] = []
    for v in eqn.params.values():
        for cand in (v if isinstance(v, (tuple, list)) else (v,)):
            if hasattr(cand, "eqns") or (hasattr(cand, "jaxpr")
                                         and hasattr(cand.jaxpr, "eqns")):
                subs.append(cand)
    return subs


def _walk_consts(closed) -> List[Any]:
    """All closed-over constants of a program, recursively (a baked
    weight table can hide inside a pjit/cond/scan sub-jaxpr)."""
    out: List[Any] = []
    seen: set = set()
    stack = [closed]
    while stack:
        jaxpr, consts = _as_open(stack.pop())
        if id(jaxpr) in seen:
            continue
        seen.add(id(jaxpr))
        out.extend(consts)
        for eqn in jaxpr.eqns:
            stack.extend(_sub_jaxprs(eqn))
    return out


def _direct_sub(eqn):
    """The single sub-jaxpr whose invars align 1:1 with the equation's
    operands (pjit / closed_call / shard_map and lookalikes), else
    None."""
    subs = _sub_jaxprs(eqn)
    if len(subs) != 1:
        return None
    jaxpr, _ = _as_open(subs[0])
    if len(jaxpr.invars) == len(eqn.invars):
        return jaxpr
    return None


def _is_var(v) -> bool:
    return hasattr(v, "aval") and not hasattr(v, "val")  # Var, not Literal


def _live_invars(jaxpr) -> set:
    """Invars reachable (backwards) from the outputs.  Refined through
    1:1 call-like equations (pjit / shard_map): an operand is live only
    if the callee actually uses it — that's precisely how a dropped
    weight table hides behind a jit boundary."""
    live = {v for v in jaxpr.outvars if _is_var(v)}
    for eqn in reversed(jaxpr.eqns):
        if not any(ov in live for ov in eqn.outvars):
            continue
        sub = _direct_sub(eqn)
        if sub is not None:
            sub_live = _live_invars(sub)
            for v, sv in zip(eqn.invars, sub.invars):
                if _is_var(v) and sv in sub_live:
                    live.add(v)
        else:
            live.update(v for v in eqn.invars if _is_var(v))
    return {v for v in jaxpr.invars if v in live}


def _taint_walk(jaxpr, invar_taint: Dict[Any, bool], consts: Sequence,
                findings: List[Finding], name: str) -> List[bool]:
    """Forward replicated/per-rank walk; returns outvar taints.  True =
    per-rank (divergent), False = replicated."""
    taint: Dict[Any, bool] = dict(invar_taint)
    for cv in getattr(jaxpr, "constvars", ()):
        taint[cv] = False

    def t(v) -> bool:
        return taint.get(v, False) if _is_var(v) else False

    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        in_taints = [t(v) for v in eqn.invars]
        if prim in ("cond", "switch"):
            if in_taints[0]:
                findings.append(Finding(
                    "divergent-cond", name, 0, "cond-predicate",
                    f"lax.{prim} predicate is per-rank-divergent: "
                    "branches would disagree across ranks inside one "
                    "SPMD program (PR-3 guard rule — reduce the "
                    "predicate with psum/consensus first)"))
            out_t = [False] * len(eqn.outvars)
            for br in eqn.params["branches"]:
                sub, _ = _as_open(br)
                sub_taint = {sv: ti for sv, ti in
                             zip(sub.invars, in_taints[1:])}
                br_out = _taint_walk(sub, sub_taint, [], findings, name)
                out_t = [a or b for a, b in zip(out_t, br_out)]
        elif prim in _DIVERGING_PRIMS:
            out_t = [True] * len(eqn.outvars)
        elif prim in _REPLICATING_PRIMS:
            out_t = [False] * len(eqn.outvars)
        else:
            sub = _direct_sub(eqn)
            if sub is not None:
                sub_taint = {sv: ti for sv, ti in
                             zip(sub.invars, in_taints)}
                out_t = _taint_walk(sub, sub_taint, [], findings, name)
            else:
                # conservative default: any per-rank operand taints
                # every output (covers scan/while/ppermute/elementwise)
                any_t = any(in_taints)
                for s in _sub_jaxprs(eqn):
                    subj, _ = _as_open(s)
                    # still recurse for nested conds, seeding
                    # conservatively from the operand taints
                    sub_taint = {sv: any_t for sv in subj.invars}
                    _taint_walk(subj, sub_taint, [], findings, name)
                out_t = [any_t] * len(eqn.outvars)
        for ov, ot in zip(eqn.outvars, out_t):
            taint[ov] = ot
    return [t(v) for v in jaxpr.outvars]


# --------------------------------------------------------------------- #
# the checks
# --------------------------------------------------------------------- #

def _weight_shape_profile(leaves) -> set:
    """(shape, dtype-kind) profiles of the declared weight tables."""
    import numpy as np

    return {(tuple(np.shape(leaf)), "f") for leaf in leaves}


def check_traced(closed, *, name: str,
                 weight_leaves: Sequence = (),
                 taint_seed: Optional[List[bool]] = None,
                 large_const_floor: Optional[int] = None) -> List[Finding]:
    """Contract-check one traced program (a ClosedJaxpr).

    ``weight_leaves``: the declared comm-weight arrays; when non-empty
    the trailing ``len(weight_leaves)`` invars must carry their avals
    and be live, and no closed-over constant may match their shape
    profile.  ``taint_seed``: per-invar per-rank flags enabling the
    divergent-cond walk.  ``large_const_floor``: additionally flag any
    float constant with at least that many elements (serving residents:
    model state must arrive as arguments, not baked weights).
    """
    import numpy as np

    findings: List[Finding] = []
    jaxpr, consts = _as_open(closed)
    n_w = len(weight_leaves)

    if n_w:
        invars = jaxpr.invars
        if len(invars) < n_w:
            findings.append(Finding(
                "missing-weight-operand", name, 0, "comm_weights",
                f"program has {len(invars)} operands, fewer than the "
                f"{n_w} declared weight leaves"))
        else:
            for i, leaf in enumerate(weight_leaves):
                v = invars[len(invars) - n_w + i]
                want = tuple(np.shape(leaf))
                got = tuple(getattr(v.aval, "shape", ()))
                if got != want:
                    findings.append(Finding(
                        "missing-weight-operand", name, 0,
                        "comm_weights",
                        f"weight operand {i}: aval shape {got} != "
                        f"declared {want} (weights not traced as "
                        "comm_weight_inputs data)"))
                    break
            else:
                live = _live_invars(jaxpr)
                dead = [i for i in range(n_w)
                        if invars[len(invars) - n_w + i] not in live]
                if dead:
                    findings.append(Finding(
                        "dead-weight-operand", name, 0, "comm_weights",
                        f"weight leaves {dead} are traced operands but "
                        "unreachable from the outputs — the combine is "
                        "not consuming the traced tables"))
        profiles = _weight_shape_profile(weight_leaves)
        for c in _walk_consts(closed):
            arr = np.asarray(c)
            if arr.dtype.kind == "f" \
                    and (tuple(arr.shape), "f") in profiles \
                    and arr.size > 1 \
                    and np.all(np.isfinite(arr)) \
                    and float(arr.min()) >= 0.0 \
                    and float(arr.max()) <= 1.0:
                findings.append(Finding(
                    "baked-weight-const", name, 0, "consts",
                    f"closed-over float constant of weight-table shape "
                    f"{arr.shape} — a baked table recompiles on every "
                    "heal/membership change instead of swapping an "
                    "operand"))

    if large_const_floor:
        for c in _walk_consts(closed):
            arr = np.asarray(c)
            if arr.dtype.kind == "f" and arr.size >= large_const_floor:
                findings.append(Finding(
                    "baked-weight-const", name, 0, "consts",
                    f"closed-over float constant of {arr.size} elements "
                    f"(shape {arr.shape}) — model/table state must be a "
                    "traced argument"))

    if taint_seed is not None:
        if len(taint_seed) == len(jaxpr.invars):
            seed = {v: ti for v, ti in zip(jaxpr.invars, taint_seed)}
            _taint_walk(jaxpr, seed, consts, findings, name)
        else:
            findings.append(Finding(
                "divergent-cond", name, 0, "cond-predicate",
                f"taint seed length {len(taint_seed)} does not match "
                f"{len(jaxpr.invars)} invars — cannot run the "
                "divergence walk"))
    return findings


def check_step(step, args: Tuple, *, name: str) -> List[Finding]:
    """Contract-check one built train step against its public call
    ``step(*args)``.

    The step's ``.trace`` (shared with ``.lower`` — same program) maps
    the public signature onto the jitted program, whose flattened
    operand list ends with the ``default_comm_weights`` leaves in both
    the guarded (explicit argument) and unguarded (default operand)
    builds.  The taint walk seeds params/opt_state/batch as per-rank
    and the step counter + weight tables as replicated.
    """
    import jax

    closed = step.trace(*args)
    weight_leaves = jax.tree.leaves(
        getattr(step, "default_comm_weights", ()))
    jaxpr, _ = _as_open(closed)
    n = len(jaxpr.invars)
    n_w = len(weight_leaves)
    # per-rank everywhere except the trailing [step_counter, *weights]
    seed = [True] * n
    for i in range(max(0, n - n_w - 1), n):
        seed[i] = False
    return check_traced(closed, name=name, weight_leaves=weight_leaves,
                        taint_seed=seed)


# --------------------------------------------------------------------- #
# the sweep: every program the repo ships
# --------------------------------------------------------------------- #

def _mesh():
    import jax
    import numpy as np
    from jax.sharding import Mesh

    devs = jax.devices()
    if len(devs) < N_RANKS:
        raise RuntimeError(
            f"analysis sweep needs {N_RANKS} devices (run under "
            "config.configure_host_platform(); bfcheck does this "
            "automatically)")
    return Mesh(np.array(devs[:N_RANKS]), ("bf",))


def _problem():
    import jax.numpy as jnp
    import numpy as np

    base = {"w1": jnp.asarray(np.random.RandomState(7).randn(4, 4) * .3),
            "b1": jnp.zeros((4,)),
            "w2": jnp.asarray(np.random.RandomState(8).randn(4, 2) * .3),
            "b2": jnp.zeros((2,))}

    def loss_fn(params, batch):
        h = jnp.tanh(batch @ params["w1"] + params["b1"])
        return jnp.mean((h @ params["w2"] + params["b2"]) ** 2)

    return base, loss_fn


def _weighted_ring():
    import numpy as np
    from bluefog_tpu.topology.spec import Topology

    W = np.zeros((N_RANKS, N_RANKS))
    for r in range(N_RANKS):
        W[(r - 1) % N_RANKS, r] = 0.3
        W[(r + 1) % N_RANKS, r] = 0.1
        W[r, r] = 0.6
    return Topology.from_weight_matrix(W)


def _machine_ring():
    import numpy as np
    from bluefog_tpu.topology.spec import Topology

    m = N_RANKS // 2
    W = np.zeros((m, m))
    for r in range(m):
        W[(r - 1) % m, r] = 0.3
        W[(r + 1) % m, r] = 0.1
        W[r, r] = 0.6
    return Topology.from_weight_matrix(W)


def _weighted_schedule():
    from bluefog_tpu.topology.dynamic import one_peer_dynamic_schedule
    from bluefog_tpu.topology.spec import DynamicTopology

    out = []
    for s in one_peer_dynamic_schedule(N_RANKS):
        out.append(DynamicTopology.from_edges(
            s.size, {e: 0.3 for e in s.edges}, [0.7] * s.size))
    return out


def sweep_cases() -> List[dict]:
    """The build_train_step configurations the sweep traces: the
    epilogue parity matrix (tests/test_epilogue.py ``_matrix``) —
    guard x health x compress x comm_mode x overlap on the weighted
    static ring, int8 wire, push_sum (the in-graph gossip mix),
    lax.switch schedules, hierarchical two-level — so the analyzer
    covers exactly the program space the parity tests pin."""
    ring = _weighted_ring()
    cases: List[dict] = []
    for comm_mode in ("cta", "atc"):
        for overlap in ("none", "bucketed"):
            for guard in (False, True):
                for health in (False, True):
                    cases.append(dict(
                        comm_mode=comm_mode, overlap=overlap,
                        guard=guard, health=health, compress=None,
                        topology=ring))
        for guard in (False, True):
            cases.append(dict(comm_mode=comm_mode, overlap="bucketed",
                              guard=guard, health=True, compress="int8",
                              topology=ring))
    cases.append(dict(comm_mode="atc", overlap="none", guard=True,
                      health=True, compress="int8", topology=ring))
    # error-feedback compressed mixing: the "topk" epilogue threads
    # MixState through the switch branches — lint it like any other
    cases.append(dict(comm_mode="cta", overlap="none", guard=False,
                      health=False, compress="topk", topology=ring))
    cases.append(dict(comm_mode="atc", overlap="bucketed", guard=True,
                      health=True, compress="topk", topology=ring))
    for overlap in ("none", "bucketed"):
        for health in (False, True):
            cases.append(dict(comm_mode="push_sum", overlap=overlap,
                              guard=False, health=health, compress=None,
                              topology=ring))
    cases.append(dict(comm_mode="atc", overlap="none", guard=False,
                      health=False, compress=None, schedule="one_peer"))
    cases.append(dict(comm_mode="atc", overlap="bucketed", guard=True,
                      health=True, compress=None, schedule="one_peer"))
    # expert-parallel MoE: route tables / capacity masks are traced
    # communication-authority DATA (dispatch.py is _WEIGHT_AUTHORITY),
    # and the expert subtree must stay out of the consensus epilogue
    cases.append(dict(comm_mode="cta", overlap="none", guard=False,
                      health=False, compress=None, topology=ring,
                      moe=True))
    cases.append(dict(comm_mode="atc", overlap="none", guard=True,
                      health=True, compress=None, topology=ring,
                      moe=True))
    mring = _machine_ring()
    for comm_mode, overlap, guard, health, compress in (
            ("cta", "none", False, False, None),
            ("cta", "bucketed", True, True, None),
            ("atc", "none", True, False, None),
            ("atc", "bucketed", False, True, None),
            ("cta", "bucketed", True, True, "int8"),
            ("atc", "none", True, True, "int8"),
            ("cta", "none", False, False, "topk")):
        cases.append(dict(comm_mode=comm_mode, overlap=overlap,
                          guard=guard, health=health, compress=compress,
                          topology=mring, hierarchical=2))
    return cases


def case_id(c: dict) -> str:
    return "-".join([
        c["comm_mode"], c["overlap"],
        "guard" if c["guard"] else "noguard",
        "health" if c["health"] else "nohealth",
        c["compress"] or "fp",
        "hier" if "hierarchical" in c
        else ("sched" if "schedule" in c else "static")]
        + (["moe"] if c.get("moe") else []))


def _build_and_check(case: dict, mesh) -> List[Finding]:
    import jax.numpy as jnp
    import numpy as np
    import optax
    from bluefog_tpu.optim import functional as F

    opt = optax.sgd(0.05, momentum=0.9)
    base, loss_fn = _problem()
    c = dict(case)
    guarded = c.pop("guard")
    health = c.pop("health")
    push_sum = c["comm_mode"] == "push_sum"
    moe = c.pop("moe", False)
    if moe:
        import jax
        from bluefog_tpu.moe import (dispatch_plan, init_moe_params,
                                     make_moe_loss)
        from bluefog_tpu.topology.compiler import PodSpec, compile_all_to_all

        plan = dispatch_plan(
            compile_all_to_all(PodSpec(4, N_RANKS // 4)).schedule)
        base = init_moe_params(jax.random.PRNGKey(0), 4, 4, 4)
        loss_fn = make_moe_loss(plan, "bf", 2)
        c["moe"] = F.MoEConfig(n_experts=4, capacity=2)
    kwargs = dict(c)
    if kwargs.pop("overlap") != "none":
        kwargs.update(overlap="bucketed", overlap_buckets=3)
    if kwargs.get("compress") is None:
        kwargs.pop("compress")
    if kwargs.get("schedule") == "one_peer":
        kwargs["schedule"] = _weighted_schedule()
    if "hierarchical" in kwargs:
        pass  # hierarchical=2 passes through verbatim
    if guarded:
        kwargs["guard"] = F.GuardConfig()
    if health:
        kwargs["health"] = F.HealthConfig()

    step = F.build_train_step(loss_fn, opt, mesh, donate=False, **kwargs)
    params = F.rank_major(base, mesh)
    ostate = F.rank_major(opt.init(base), mesh)
    if push_sum:
        ostate = (ostate, F.push_sum_weights(mesh))
    if getattr(step, "mix_config", None) is not None:
        ostate = (ostate, step.init_mix_state(params))
    if moe:
        from bluefog_tpu.moe import default_route_table, capacity_mask_of
        # rank-major route data: tokens, this-rank route rows, and the
        # tiled liveness mask all shard over the leading rank axis
        batch = (np.zeros((N_RANKS, 3, 4), np.float32),
                 np.asarray(default_route_table(N_RANKS, 4)),
                 np.broadcast_to(capacity_mask_of(np.zeros(N_RANKS))[None],
                                 (N_RANKS, N_RANKS)).copy())
    else:
        batch = np.zeros((N_RANKS, 3, 4), np.float32)
    args = (params, ostate, batch, jnp.int32(0))
    if guarded:
        args = args + (step.default_comm_weights,)
    return check_step(step, args, name=f"step[{case_id(case)}]")


def check_collective_contracts() -> List[Finding]:
    """Lower the topology compiler's scheduled programs and hold the
    HLO to ``predicted_collectives`` via the supported
    ``verify_collective_contract`` — the flat (1, 8)-pod switch program
    (every round in ONE executable, exactly how build_train_step
    consumes a schedule) and the hierarchical (4, 2)-pod rounds."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from bluefog_tpu import benchutil
    from bluefog_tpu.parallel import collectives as C
    from bluefog_tpu.topology.compiler import PodSpec, compile_topology

    mesh = _mesh()
    payload = 64 * 4
    x = jnp.zeros((N_RANKS, 64), jnp.float32)
    findings: List[Finding] = []

    compiled = compile_topology(PodSpec(1, 8))
    pred = compiled.predicted_collectives(payload)
    schedule = compiled.schedule

    def combine(v, step):
        branches = [
            (lambda s: lambda y: C.neighbor_allreduce(y, s, "bf"))(s)
            for s in schedule]
        return jax.lax.switch(step % len(branches), branches, v)

    sm = jax.shard_map(combine, mesh=mesh, in_specs=(P("bf"), P()),
                       out_specs=P("bf"), check_vma=False)
    hlo = jax.jit(sm).lower(x, jnp.asarray(0)).compile().as_text()
    for msg in benchutil.verify_collective_contract(hlo, pred, payload):
        findings.append(Finding("collective-contract",
                                "schedule[pod_1x8]", 0, "period", msg))
    for i, rnd in enumerate(schedule):
        def one(v, r=rnd):
            return C.neighbor_allreduce(v, r, "bf")
        smr = jax.shard_map(one, mesh=mesh, in_specs=P("bf"),
                            out_specs=P("bf"), check_vma=False)
        hlo_r = jax.jit(smr).lower(x).compile().as_text()
        for msg in benchutil.verify_collective_contract(
                hlo_r, pred, payload, round_index=i):
            findings.append(Finding(
                "collective-contract", "schedule[pod_1x8]", 0,
                f"round_{i}", msg))

    # the MoE dispatch wire: lower the compiled all-to-all and hold it
    # to ITS predicted_collectives, full period and round-by-round —
    # the same contract the mixing schedules above answer to
    from bluefog_tpu.moe import all_to_all_dispatch, dispatch_plan
    from bluefog_tpu.topology.compiler import compile_all_to_all

    a2a = compile_all_to_all(PodSpec(4, 2))
    shard = jnp.zeros((N_RANKS, N_RANKS, 16), jnp.float32)
    a2a_payload = 16 * 4
    apred = a2a.predicted_collectives(a2a_payload)

    def _a2a_prog(plan):
        def run(v):
            return all_to_all_dispatch(v[0], plan, "bf")[None]
        sma = jax.shard_map(run, mesh=mesh, in_specs=P("bf"),
                            out_specs=P("bf"), check_vma=False)
        return jax.jit(sma).lower(shard).compile().as_text()

    hlo_a = _a2a_prog(dispatch_plan(a2a.schedule))
    for msg in benchutil.verify_collective_contract(hlo_a, apred,
                                                    a2a_payload):
        findings.append(Finding("collective-contract", "a2a[pod_4x2]",
                                0, "period", msg))
    for i, rnd in enumerate(a2a.schedule):
        hlo_ar = _a2a_prog(dispatch_plan([rnd]))
        for msg in benchutil.verify_collective_contract(
                hlo_ar, apred, a2a_payload, round_index=i):
            findings.append(Finding("collective-contract",
                                    "a2a[pod_4x2]", 0, f"round_{i}",
                                    msg))

    hier = compile_topology(PodSpec(4, 2), hierarchical=True)
    hpred = hier.predicted_collectives(payload)
    for i, rnd in enumerate(hier.machine_schedule):
        def two(v, r=rnd):
            return C.hierarchical_neighbor_allreduce(
                v, r, hier.local_size, "bf")
        smh = jax.shard_map(two, mesh=mesh, in_specs=P("bf"),
                            out_specs=P("bf"), check_vma=False)
        hlo_h = jax.jit(smh).lower(x).compile().as_text()
        for msg in benchutil.verify_collective_contract(
                hlo_h, hpred, payload, round_index=i):
            findings.append(Finding(
                "collective-contract", "hier[pod_4x2]", 0,
                f"round_{i}", msg))
    return findings


def check_serving_residents() -> List[Finding]:
    """Trace every resident serving executable (the engine's
    build-time registry: prefill chunk + decode step, and the
    speculative draft/verify pair) and require model/table state to
    arrive as traced arguments — any large closed-over float constant
    is baked state that would recompile on every weight swap."""
    import jax
    import jax.numpy as jnp

    from bluefog_tpu import models
    from bluefog_tpu.serving.engine import ServingEngine, SpeculativeConfig

    findings: List[Finding] = []
    cfg = models.LlamaConfig.tiny(dtype=jnp.float32)
    variables = models.Llama(cfg).init(
        jax.random.PRNGKey(1), jnp.zeros((2, 4), jnp.int32))
    engines = {
        "serving": ServingEngine(variables, cfg, capacity=2, max_len=48,
                                 prefill_chunk=4),
        "spec_serving": ServingEngine(
            variables, cfg, capacity=2, max_len=48, prefill_chunk=4,
            speculative=SpeculativeConfig(variables=variables, cfg=cfg,
                                          lookahead=2)),
    }
    for eng_name, eng in engines.items():
        for prog, (fn, thunk, static) in eng._resident.items():
            closed = fn.trace(*thunk(), **static)
            findings += check_traced(
                closed, name=f"{eng_name}[{prog}]",
                large_const_floor=_LARGE_CONST)
    return findings


def run_sweep(*, include_serving: bool = True,
              include_collectives: bool = True,
              cases: Optional[Iterable[dict]] = None) -> List[Finding]:
    """The full semantic sweep: every train-step matrix point, the
    scheduled-exchange collective contracts, and the serving
    residents.  Returns all findings (empty = every contract holds)."""
    mesh = _mesh()
    findings: List[Finding] = []
    for case in (sweep_cases() if cases is None else cases):
        findings += _build_and_check(case, mesh)
    if include_collectives:
        findings += check_collective_contracts()
    if include_serving:
        findings += check_serving_residents()
    return findings
