"""``python -m bluefog_tpu.analysis`` / the ``bfcheck`` console
script: run both analyzer passes and exit nonzero on any unsuppressed
finding.

Order matters: the host platform must be configured for the 8-device
sweep BEFORE anything imports jax, so :func:`main` calls
``config.configure_host_platform`` first and defers every jax-touching
import until after it.
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from bluefog_tpu.analysis import (Finding, default_root, format_findings,
                                  load_baseline, split_suppressed)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="bfcheck",
        description="bluefog_tpu static contract checker: AST lint + "
                    "jaxpr/HLO sweep of the zero-recompile / "
                    "traced-weights invariants")
    parser.add_argument("--root", default=None,
                        help="repo root to scan (default: cwd when it "
                             "holds pyproject.toml, else the installed "
                             "package's tree)")
    parser.add_argument("--baseline", default=None,
                        help="suppression file (default: the committed "
                             "bluefog_tpu/analysis/baseline.txt)")
    parser.add_argument("--no-lint", action="store_true",
                        help="skip the AST lint pass")
    parser.add_argument("--no-jaxpr", action="store_true",
                        help="skip the jaxpr/HLO sweep (the slow pass: "
                             "builds every train-step variant on an "
                             "8-device host mesh)")
    parser.add_argument("--no-serving", action="store_true",
                        help="within the jaxpr sweep, skip the serving "
                             "resident programs")
    parser.add_argument("--list-baseline", action="store_true",
                        help="print the active suppression keys and "
                             "exit")
    args = parser.parse_args(argv)

    baseline = load_baseline(args.baseline)
    if args.list_baseline:
        for key in baseline:
            print(key)
        return 0

    findings: List[Finding] = []
    if not args.no_lint:
        from bluefog_tpu.analysis.lint import run_lint

        root = args.root or default_root()
        findings += run_lint(root)

    if not args.no_jaxpr:
        # the sweep traces on an 8-device host mesh; set the platform
        # up before jax initializes (no-op if the user already did)
        from bluefog_tpu import config as bfconfig

        bfconfig.configure_host_platform()
        from bluefog_tpu.analysis.jaxpr_check import run_sweep

        findings += run_sweep(include_serving=not args.no_serving)

    active, suppressed = split_suppressed(findings, baseline)
    if active:
        print(format_findings(active))
    print(f"bfcheck: {len(active)} finding(s), "
          f"{len(suppressed)} baseline-suppressed", file=sys.stderr)
    return 1 if active else 0


if __name__ == "__main__":
    sys.exit(main())
