"""Static contract checker for the zero-recompile / traced-weights
invariants.

Every subsystem since the guarded-step work rests on two framework
contracts, and this package checks them mechanically before anything
executes:

* **weights-as-data** — comm weights, dead/membership masks, and health
  tables reach a built program as *traced operands*
  (``F.comm_weight_inputs``-shaped invars), never as closed-over
  constants.  A baked weight table means healing / elastic membership /
  topology hot-swap would RECOMPILE — the production failure mode the
  whole healing discipline exists to prevent.
* **collective contract** — the lowered HLO contains exactly the
  collectives the schedule predicts (``predicted_collectives``):
  permute count, payload bytes, grouped-all-reduce structure.  The
  TACCL-style agreement between declared sketch and emitted algorithm.

Two complementary passes:

* :mod:`bluefog_tpu.analysis.jaxpr_check` — semantic: builds the real
  programs (the ``build_train_step`` parity matrix, serving resident
  programs) and walks their ClosedJaxprs/HLO for baked weight tables,
  dead weight operands, ``lax.cond`` over per-rank-divergent
  predicates, and predicted-vs-lowered collective mismatches.
* :mod:`bluefog_tpu.analysis.lint` — syntactic: an AST lint over the
  repo with the project-specific rules (env reads outside ``config``,
  host syncs inside jitted bodies, Python ``if`` on traced values,
  weight-matrix construction bypassing the shared row-stochastic
  helpers, unseeded benchmark randomness, unregistered pytest markers).

Vetted exceptions live in the committed ``baseline.txt`` next to this
file — every suppression is explicit, keyed on stable
``rule path::symbol`` triples (no line numbers, so unrelated edits
never churn it), and carries a justifying comment.

CLI: ``python -m bluefog_tpu.analysis`` (installed as ``bfcheck``)
runs both passes and exits nonzero on any unsuppressed finding; see
``docs/analysis.md``.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Iterable, List, Sequence, Tuple

__all__ = ["Finding", "baseline_path", "load_baseline",
           "split_suppressed", "format_findings", "default_root"]


@dataclasses.dataclass(frozen=True)
class Finding:
    """One contract violation.

    ``key()`` deliberately omits the line number: a baseline entry must
    keep suppressing the same (rule, file, symbol) finding across
    unrelated edits, and must NOT silently absorb a second finding of
    the same rule elsewhere in the file.
    """

    rule: str      # e.g. "env-read-outside-config"
    path: str      # repo-relative posix path, or the program name for
                   # jaxpr findings (e.g. "step[atc,guard,health]")
    line: int      # 1-based; 0 when not tied to source text
    symbol: str    # enclosing function/class qualname, or the checked
                   # sub-contract for jaxpr findings
    message: str

    def key(self) -> str:
        return f"{self.rule} {self.path}::{self.symbol}"

    def render(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        return f"{loc}: [{self.rule}] {self.symbol}: {self.message}"


def baseline_path() -> str:
    """The committed baseline-suppression file shipped with the
    package."""
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.txt")


def load_baseline(path: str = None) -> List[str]:
    """Suppression keys from a baseline file: one ``rule path::symbol``
    per line; blank lines and ``#`` comments (full-line or trailing)
    ignored.  Missing file = empty baseline."""
    if path is None:
        path = baseline_path()
    keys: List[str] = []
    if not os.path.exists(path):
        return keys
    with open(path) as f:
        for raw in f:
            line = raw.split("#", 1)[0].strip()
            if line:
                keys.append(line)
    return keys


def split_suppressed(
        findings: Iterable[Finding],
        baseline: Sequence[str]) -> Tuple[List[Finding], List[Finding]]:
    """``(active, suppressed)`` — a finding is suppressed iff its
    ``key()`` appears verbatim in the baseline."""
    allowed = set(baseline)
    active, suppressed = [], []
    for f in findings:
        (suppressed if f.key() in allowed else active).append(f)
    return active, suppressed


def format_findings(findings: Sequence[Finding]) -> str:
    return "\n".join(f.render() for f in findings)


def default_root() -> str:
    """Repo root to scan: the cwd when it holds a ``pyproject.toml``
    (the normal checkout invocation), else the tree this package was
    imported from."""
    if os.path.exists(os.path.join(os.getcwd(), "pyproject.toml")):
        return os.getcwd()
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
