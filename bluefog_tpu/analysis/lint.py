"""AST lint: the repo-specific rules no generic linter knows.

Each rule encodes a convention this codebase already enforces by review
and by scattered tests; the lint makes them mechanical:

``env-read-outside-config``
    ``os.environ`` / ``os.getenv`` anywhere in ``bluefog_tpu/`` outside
    ``config.py``.  Every knob goes through one audited accessor module
    (PR-1 discipline) so ``docs/env_variables.md`` can stay the single
    source of truth and tests can monkeypatch one seam.
``host-sync-in-jit``
    ``float(x)`` / ``.item()`` / ``np.asarray`` / ``np.array`` inside a
    function that gets traced (``jax.jit`` / ``shard_map`` /
    ``lax.cond`` / ``lax.switch`` / ``lax.scan`` operands, and their
    nested defs).  On a traced value these force a device sync or a
    tracer leak — the classic silent-latency bug.
``python-if-on-traced``
    Python ``if`` whose test reads a parameter of a traced function.
    Branching on a tracer either crashes (ConcretizationTypeError) or —
    worse — silently bakes one branch per compile, the exact
    recompile-on-topology-change failure the weights-as-data contract
    exists to prevent.
``weight-matrix-bypass``
    Assigning a ``*comm_weights``-style name from a raw ndarray
    constructor outside the modules that own weight construction
    (marked ``_WEIGHT_AUTHORITY = True``).  Hand-rolled weight tables
    skip the row-stochastic normalization + shape contract of the
    shared helpers (``topology.spec`` / ``resilience.healing``).
``weight-swap-outside-boundary``
    In-place mutation of a live weight operand (``comm_weights[i] =
    ...``, ``class_weights += ...``) outside the sanctioned
    step-boundary swap helper (``topology.control.swap_comm_weights``)
    and outside ``_WEIGHT_AUTHORITY`` modules.  The zero-recompile
    contract delivers topology changes as whole replacement
    ``(class_weights, self_weights)`` pairs at a step boundary;
    element-wise edits of the live operands bypass the
    healing/projection pipeline and can desynchronize ranks mid-step.
``unseeded-randomness``
    Legacy global-state ``np.random.*`` draws in ``benchmarks/``.
    Benchmark numbers must replay bit-identically; every script
    threads an explicit ``default_rng(seed)`` / ``RandomState(seed)``.
``unregistered-pytest-marker``
    ``pytest.mark.<name>`` in ``tests/`` not declared in
    ``pyproject.toml`` — with ``--strict-markers`` ambitions, a typo'd
    marker silently deselects tests.
``sleep-without-backoff``
    ``time.sleep`` inside a loop under ``bluefog_tpu/serving/``.  Every
    serving retry loop must sleep through the seeded-backoff helper
    (``serving.resilience.backoff_sleep``): deterministic delays keyed
    on (seed, request, attempt) are what make chaos runs replayable and
    keep retry storms from synchronizing across replicas.
``decision-outside-recorder``
    A control plane's state-transition method (the topology plane's
    swap/synthesize path, membership admit/promote/kick, router
    excision, drain/failover, heal re-plans) that never emits through
    the decision flight recorder (``observe.blackbox.record_decision``
    or a ``_decide`` helper that wraps it).  Every plane transition
    must leave a causal audit record — a silent transition is exactly
    the unexplainable swap the blackbox exists to prevent.  The
    sanctioned method list lives in ``_DECISION_PLANE_METHODS`` (the
    ``_WEIGHT_AUTHORITY``-style registry for this rule).
``wallclock-in-sim``
    ``time.time()`` / ``time.monotonic()`` / ``time.perf_counter()``
    (and their ``_ns`` variants, however imported) under
    ``bluefog_tpu/sim/``.  The simulator's whole contract is that the
    same seed replays byte-equal: every timestamp must come from the
    injected :class:`~bluefog_tpu.sim.clock.VirtualClock` (or, for
    calibration, an injected ``timer`` argument) — one wall-clock read
    makes event logs non-reproducible and silently couples simulated
    dynamics to host load.

Pure-syntactic by design: no imports of the scanned modules, so the
lint runs in milliseconds and can't be confused by import-time side
effects.  The semantic complement (building real programs and walking
their jaxprs) is :mod:`bluefog_tpu.analysis.jaxpr_check`.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Iterable, List, Optional, Set

from bluefog_tpu.analysis import Finding

__all__ = ["run_lint", "lint_file", "registered_markers",
           "BUILTIN_MARKERS", "WEIGHT_NAME_RE", "WEIGHT_HELPERS"]

# --------------------------------------------------------------------- #
# shared vocabulary
# --------------------------------------------------------------------- #

# entry points whose function operands are traced by jax
_TRACING_CALLS = {
    "jit", "shard_map", "pmap", "vmap", "grad", "value_and_grad",
    "remat", "checkpoint", "custom_vjp", "custom_jvp", "scan",
    "while_loop", "fori_loop", "cond", "switch", "named_call",
}

# names that count as a host-sync when called on (potentially) traced
# values inside a traced scope
_NUMPY_ALIASES = {"np", "numpy", "onp"}
_HOST_SYNC_NP_FNS = {"asarray", "array"}

# legacy global-state numpy.random entry points (everything that is not
# an explicit generator/seed-container constructor draws from the
# shared hidden RandomState)
_SEEDED_RANDOM_OK = {
    "default_rng", "RandomState", "SeedSequence", "Generator",
    "PCG64", "Philox", "MT19937", "BitGenerator",
}

# markers pytest itself defines — always registered
BUILTIN_MARKERS = {
    "parametrize", "skip", "skipif", "xfail", "usefixtures",
    "filterwarnings", "tryfirst", "trylast",
}

# a binding whose last path component matches this is "a comm-weight
# table" for the bypass rule.  MoE route tables and capacity masks are
# the same kind of communication-authority data (they steer the expert
# all-to-all the way comm weights steer the mixing wire), so they
# answer to the same rule.
WEIGHT_NAME_RE = re.compile(
    r"(^|_)((comm|class|self|recv|mix)_weights?"
    r"|route_tables?|capacity_masks?)$")

# sanctioned constructors: any call to one of these anywhere in the RHS
# means the value came through the shared row-stochastic machinery
WEIGHT_HELPERS = {
    "comm_weight_inputs", "default_comm_weights", "weights_for_round",
    "healed_comm_weights", "healed_hierarchical_comm_weights",
    "class_recv_weights", "self_weight_vector", "self_weights_of",
    "push_sum_weights", "grow_comm_weights", "row_stochastic",
    "neighbor_weights", "hierarchical_comm_weights",
    "default_route_table", "heal_route_table", "capacity_mask_of",
}

# the one sanctioned seam for replacing live weight operands mid-run:
# the step-boundary swap helper (topology.control).  Functions with
# these names may touch weight tables element-wise.
_SWAP_BOUNDARY_HELPERS = {"swap_comm_weights"}

# control-plane state-transition methods that must emit a decision
# record (the decision-outside-recorder rule): repo-relative module ->
# method/function names.  This is the sanctioned-callsite registry —
# adding a plane transition means adding it here AND wiring it through
# observe.blackbox.
_DECISION_PLANE_METHODS = {
    "bluefog_tpu/topology/control.py": frozenset(
        {"on_step", "_synthesize", "force_candidate",
         "_mix_ladder_step", "plan_all_to_all"}),
    "bluefog_tpu/elastic/membership.py": frozenset(
        {"admit", "promote", "kick", "mark_dead"}),
    "bluefog_tpu/serving/fleet.py": frozenset({"poll", "submit"}),
    "bluefog_tpu/serving/engine.py": frozenset({"drain"}),
    "bluefog_tpu/serving/resilience.py": frozenset(
        {"failover_stranded"}),
    "bluefog_tpu/resilience/healing.py": frozenset(
        {"healed_comm_weights"}),
    "bluefog_tpu/moe/dispatch.py": frozenset({"heal_route_table"}),
    "bluefog_tpu/sim/serving.py": frozenset({"_kill"}),
}

# a call with one of these terminal names counts as "emitted through
# the recorder": the blackbox API itself, or a plane's _decide wrapper
_DECISION_EMITTERS = {"record_decision", "_decide"}

# raw ndarray constructors that build a table from scratch
_RAW_CONSTRUCTORS = {
    "array", "asarray", "ones", "zeros", "full", "eye", "stack",
    "concatenate", "tile", "repeat", "ones_like", "zeros_like",
    "full_like",
}


def _last_attr(node: ast.expr) -> Optional[str]:
    """Terminal identifier of a Name / dotted Attribute chain."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _dotted(node: ast.expr) -> str:
    """Best-effort dotted path ("jax.lax.cond") for a Name/Attribute
    chain; "" when the chain includes calls/subscripts."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


class _ScopeTracker(ast.NodeVisitor):
    """Base visitor that maintains the enclosing-definition qualname,
    so findings carry a stable ``symbol``."""

    def __init__(self) -> None:
        self.scope: List[str] = []

    @property
    def symbol(self) -> str:
        return ".".join(self.scope) if self.scope else "<module>"

    def _visit_scoped(self, node) -> None:
        self.scope.append(node.name)
        self.generic_visit(node)
        self.scope.pop()

    visit_FunctionDef = _visit_scoped
    visit_AsyncFunctionDef = _visit_scoped
    visit_ClassDef = _visit_scoped


# --------------------------------------------------------------------- #
# rule: env-read-outside-config
# --------------------------------------------------------------------- #

class _EnvReadVisitor(_ScopeTracker):
    def __init__(self, path: str) -> None:
        super().__init__()
        self.path = path
        self.findings: List[Finding] = []

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if node.attr == "environ" and _dotted(node) == "os.environ":
            self.findings.append(Finding(
                "env-read-outside-config", self.path, node.lineno,
                self.symbol,
                "os.environ accessed directly; route through a "
                "bluefog_tpu.config accessor (or "
                "config.environ_passthrough for whole-env reads)"))
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        if _dotted(node.func) in ("os.getenv", "os.environb"):
            self.findings.append(Finding(
                "env-read-outside-config", self.path, node.lineno,
                self.symbol,
                "os.getenv bypasses bluefog_tpu.config; add an "
                "accessor there instead"))
        self.generic_visit(node)


# --------------------------------------------------------------------- #
# rules: host-sync-in-jit + python-if-on-traced
# --------------------------------------------------------------------- #

def _collect_path_callbacks(tree: ast.AST) -> Set[str]:
    """Function names passed (by reference) as the callback of a
    ``tree_map_with_path`` / ``tree_flatten_with_path`` style call.
    Their FIRST parameter is the static pytree key path — not a traced
    value — so the if-on-traced rule must not consider it."""
    names: Set[str] = set()

    class V(ast.NodeVisitor):
        def visit_Call(self, node: ast.Call) -> None:
            tail = _last_attr(node.func)
            if tail and tail.endswith("_with_path") and node.args \
                    and isinstance(node.args[0], ast.Name):
                names.add(node.args[0].id)
            self.generic_visit(node)

    V().visit(tree)
    return names


def _collect_traced_names(tree: ast.AST) -> Set[str]:
    """Names of module-level/inner functions handed to a tracing entry
    point by reference: ``jax.jit(step)``, ``shard_map(body, ...)``,
    ``lax.cond(p, true_fn, false_fn, x)``, ``lax.switch(i, [f, g])``."""
    traced: Set[str] = set()

    class V(ast.NodeVisitor):
        def visit_Call(self, node: ast.Call) -> None:
            tail = _last_attr(node.func)
            if tail in _TRACING_CALLS:
                operands: List[ast.expr] = list(node.args)
                for kw in node.keywords:
                    if kw.arg in ("f", "fun", "body_fun", "cond_fun",
                                  "true_fun", "false_fun"):
                        operands.append(kw.value)
                for arg in operands:
                    if isinstance(arg, ast.Name):
                        traced.add(arg.id)
                    elif isinstance(arg, (ast.List, ast.Tuple)):
                        for el in arg.elts:
                            if isinstance(el, ast.Name):
                                traced.add(el.id)
            self.generic_visit(node)

    V().visit(tree)
    return traced


def _has_tracing_decorator(node) -> bool:
    for dec in node.decorator_list:
        target = dec
        # @partial(jax.jit, ...) / @functools.partial(shard_map, ...)
        if isinstance(dec, ast.Call) and _last_attr(dec.func) == "partial" \
                and dec.args:
            target = dec.args[0]
        if isinstance(target, ast.Call):  # @jax.jit(static_argnums=...)
            target = target.func
        if _last_attr(target) in _TRACING_CALLS:
            return True
    return False


class _TracedBodyVisitor(_ScopeTracker):
    """Walks traced function bodies flagging host syncs and Python
    ``if`` over parameters.  ``traced_depth`` > 0 while inside any
    traced def (nested defs inherit tracedness — jax traces through
    them)."""

    def __init__(self, path: str, traced_names: Set[str],
                 path_callbacks: Set[str] = frozenset()) -> None:
        super().__init__()
        self.path = path
        self.traced_names = traced_names
        self.path_callbacks = path_callbacks
        self.traced_depth = 0
        self.param_stack: List[Set[str]] = []
        self.findings: List[Finding] = []

    # -- scope management ------------------------------------------- #

    def _function(self, node) -> None:
        is_traced = (self.traced_depth > 0
                     or node.name in self.traced_names
                     or _has_tracing_decorator(node))
        args = node.args
        positional = args.posonlyargs + args.args
        if node.name in self.path_callbacks and positional:
            positional = positional[1:]  # key path: static, not traced
        params = {a.arg for a in positional + args.kwonlyargs}
        if args.vararg:
            params.add(args.vararg.arg)
        params.discard("self")
        self.scope.append(node.name)
        if is_traced:
            self.traced_depth += 1
            self.param_stack.append(params)
        self.generic_visit(node)
        if is_traced:
            self.traced_depth -= 1
            self.param_stack.pop()
        self.scope.pop()

    visit_FunctionDef = _function
    visit_AsyncFunctionDef = _function

    # -- host syncs -------------------------------------------------- #

    def visit_Call(self, node: ast.Call) -> None:
        if self.traced_depth > 0:
            f = node.func
            if isinstance(f, ast.Name) and f.id == "float" and node.args \
                    and not isinstance(node.args[0], ast.Constant):
                self.findings.append(Finding(
                    "host-sync-in-jit", self.path, node.lineno,
                    self.symbol,
                    "float() on a traced value forces a device sync "
                    "(use jnp/astype to stay on device)"))
            elif isinstance(f, ast.Attribute) and f.attr == "item":
                self.findings.append(Finding(
                    "host-sync-in-jit", self.path, node.lineno,
                    self.symbol,
                    ".item() inside a traced function blocks on device "
                    "transfer"))
            elif isinstance(f, ast.Attribute) \
                    and f.attr in _HOST_SYNC_NP_FNS \
                    and isinstance(f.value, ast.Name) \
                    and f.value.id in _NUMPY_ALIASES:
                self.findings.append(Finding(
                    "host-sync-in-jit", self.path, node.lineno,
                    self.symbol,
                    f"np.{f.attr}() materializes on host inside a "
                    "traced function (use jnp)"))
        self.generic_visit(node)

    # -- Python if over traced parameters ---------------------------- #

    def visit_If(self, node: ast.If) -> None:
        if self.traced_depth > 0 and self.param_stack:
            params = self.param_stack[-1]
            for name in ast.walk(node.test):
                if isinstance(name, ast.Name) \
                        and isinstance(name.ctx, ast.Load) \
                        and name.id in params:
                    self.findings.append(Finding(
                        "python-if-on-traced", self.path, node.lineno,
                        self.symbol,
                        f"Python `if` on parameter '{name.id}' of a "
                        "traced function — branch with lax.cond/"
                        "jnp.where, or hoist to a static argument"))
                    break
        self.generic_visit(node)


# --------------------------------------------------------------------- #
# rule: weight-matrix-bypass
# --------------------------------------------------------------------- #

def _module_is_weight_authority(tree: ast.Module) -> bool:
    """True when the module declares ``_WEIGHT_AUTHORITY = True`` at
    top level — the opt-in marker for "this module is where weight
    tables are legitimately constructed from scratch"."""
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                if isinstance(t, ast.Name) and t.id == "_WEIGHT_AUTHORITY":
                    return isinstance(stmt.value, ast.Constant) \
                        and stmt.value.value is True
    return False


class _WeightBypassVisitor(_ScopeTracker):
    def __init__(self, path: str) -> None:
        super().__init__()
        self.path = path
        self.findings: List[Finding] = []

    def _check(self, targets: Iterable[ast.expr], value: ast.expr,
               lineno: int) -> None:
        names = [_last_attr(t) for t in targets]
        if not any(n and WEIGHT_NAME_RE.search(n) for n in names):
            return
        raw = sanctioned = False
        for node in ast.walk(value):
            if isinstance(node, ast.Call):
                tail = _last_attr(node.func)
                if tail in WEIGHT_HELPERS:
                    sanctioned = True
                elif tail in _RAW_CONSTRUCTORS:
                    raw = True
        if raw and not sanctioned:
            bound = next(n for n in names if n and WEIGHT_NAME_RE.search(n))
            self.findings.append(Finding(
                "weight-matrix-bypass", self.path, lineno, self.symbol,
                f"'{bound}' built from a raw ndarray constructor; use "
                "the shared row-stochastic helpers (topology.spec / "
                "resilience.healing) or mark the module "
                "_WEIGHT_AUTHORITY"))

    def visit_Assign(self, node: ast.Assign) -> None:
        self._check(node.targets, node.value, node.lineno)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._check([node.target], node.value, node.lineno)
        self.generic_visit(node)


# --------------------------------------------------------------------- #
# rule: weight-swap-outside-boundary
# --------------------------------------------------------------------- #

class _WeightSwapVisitor(_ScopeTracker):
    """Element-wise mutation of a live weight operand outside the
    sanctioned step-boundary swap helper.  Whole-name rebinding
    (``comm_weights = healed_comm_weights(...)``) is the delivery
    pattern and stays legal; ``comm_weights[0] = ...`` and
    ``class_weights += ...`` are not — they edit the operand the
    compiled step is already closed over, skipping projection/healing
    and risking rank desync mid-step."""

    def __init__(self, path: str) -> None:
        super().__init__()
        self.path = path
        self.findings: List[Finding] = []

    def _in_boundary(self) -> bool:
        return any(s in _SWAP_BOUNDARY_HELPERS for s in self.scope)

    def _weight_base(self, target: ast.expr) -> Optional[str]:
        base = target.value if isinstance(target, ast.Subscript) \
            else target
        name = _last_attr(base)
        if name and WEIGHT_NAME_RE.search(name):
            return name
        return None

    def _flag(self, name: str, lineno: int) -> None:
        self.findings.append(Finding(
            "weight-swap-outside-boundary", self.path, lineno,
            self.symbol,
            f"'{name}' mutated element-wise outside the step-boundary "
            "swap helper; live (class_weights, self_weights) operands "
            "must be replaced wholesale via "
            "topology.control.swap_comm_weights"))

    def visit_Assign(self, node: ast.Assign) -> None:
        if not self._in_boundary():
            for t in node.targets:
                if isinstance(t, ast.Subscript):
                    name = self._weight_base(t)
                    if name:
                        self._flag(name, node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        if not self._in_boundary():
            name = self._weight_base(node.target)
            if name:
                self._flag(name, node.lineno)
        self.generic_visit(node)


# --------------------------------------------------------------------- #
# rule: unseeded-randomness (benchmarks/)
# --------------------------------------------------------------------- #

class _UnseededRandomVisitor(_ScopeTracker):
    def __init__(self, path: str) -> None:
        super().__init__()
        self.path = path
        self.findings: List[Finding] = []

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        if isinstance(f, ast.Attribute) \
                and f.attr not in _SEEDED_RANDOM_OK:
            owner = _dotted(f.value)
            if owner in ("np.random", "numpy.random", "onp.random"):
                self.findings.append(Finding(
                    "unseeded-randomness", self.path, node.lineno,
                    self.symbol,
                    f"np.random.{f.attr} draws from hidden global "
                    "state; benchmarks must use an explicit "
                    "default_rng(seed) / RandomState(seed)"))
        self.generic_visit(node)


# --------------------------------------------------------------------- #
# rule: sleep-without-backoff (bluefog_tpu/serving/)
# --------------------------------------------------------------------- #

class _SleepInLoopVisitor(_ScopeTracker):
    """``time.sleep`` inside a ``for``/``while`` under the serving
    package is a hand-rolled retry loop: it must go through
    ``serving.resilience.backoff_sleep`` (seeded, jittered,
    deterministic).  Injected sleeps (``self._sleep``, a ``sleep=``
    parameter) are fine — determinism is the caller's choice there."""

    def __init__(self, path: str) -> None:
        super().__init__()
        self.path = path
        self.loop_depth = 0
        self.findings: List[Finding] = []

    def _loop(self, node) -> None:
        self.loop_depth += 1
        self.generic_visit(node)
        self.loop_depth -= 1

    visit_For = _loop
    visit_AsyncFor = _loop
    visit_While = _loop

    def visit_Call(self, node: ast.Call) -> None:
        if self.loop_depth > 0 and _dotted(node.func) == "time.sleep":
            self.findings.append(Finding(
                "sleep-without-backoff", self.path, node.lineno,
                self.symbol,
                "time.sleep in a serving retry loop; use "
                "serving.resilience.backoff_sleep (seeded exponential "
                "backoff) so delays are deterministic and de-synced"))
        self.generic_visit(node)


# --------------------------------------------------------------------- #
# rule: wallclock-in-sim (bluefog_tpu/sim/)
# --------------------------------------------------------------------- #

# time-module entry points that read the host clock
_WALLCLOCK_FNS = {
    "time", "monotonic", "perf_counter", "process_time",
    "time_ns", "monotonic_ns", "perf_counter_ns", "process_time_ns",
}


class _WallClockVisitor(_ScopeTracker):
    """Any host-clock read under the simulator package breaks the
    same-seed ⇒ byte-equal-event-log contract.  Both spellings are
    caught: ``time.perf_counter()`` and a bare ``perf_counter()``
    bound by ``from time import perf_counter [as alias]``.  Injected
    timers (a ``timer=`` parameter the caller passes from outside the
    package) are the sanctioned calibration seam."""

    def __init__(self, path: str) -> None:
        super().__init__()
        self.path = path
        self.from_imports: Set[str] = set()
        self.findings: List[Finding] = []

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module == "time":
            for alias in node.names:
                if alias.name in _WALLCLOCK_FNS:
                    self.from_imports.add(alias.asname or alias.name)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        name = None
        if isinstance(f, ast.Attribute) and f.attr in _WALLCLOCK_FNS \
                and _dotted(f) == f"time.{f.attr}":
            name = _dotted(f)
        elif isinstance(f, ast.Name) and f.id in self.from_imports:
            name = f.id
        if name:
            self.findings.append(Finding(
                "wallclock-in-sim", self.path, node.lineno, self.symbol,
                f"{name}() reads the host clock inside the simulator; "
                "virtual time must come from the injected VirtualClock "
                "(or an injected timer= for calibration) so same-seed "
                "runs replay byte-equal"))
        self.generic_visit(node)


# --------------------------------------------------------------------- #
# rule: decision-outside-recorder (control-plane modules)
# --------------------------------------------------------------------- #

def _decision_findings(tree: ast.Module, rel: str,
                       methods: Set[str]) -> List[Finding]:
    """Flag every function/method in ``methods`` whose body (nested
    defs included) never calls a ``_DECISION_EMITTERS`` name.  The
    check is name-anchored, not class-anchored, so fixtures and
    refactors keep working; a method that delegates to a ``_decide``
    wrapper passes (the wrapper is the plane's sanctioned seam)."""
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
            continue
        if node.name not in methods:
            continue
        emits = any(
            isinstance(n, ast.Call)
            and _last_attr(n.func) in _DECISION_EMITTERS
            for n in ast.walk(node))
        if not emits:
            findings.append(Finding(
                "decision-outside-recorder", rel, node.lineno,
                node.name,
                f"control-plane transition '{node.name}' never emits "
                "through the decision flight recorder; record it via "
                "observe.blackbox.record_decision (or the plane's "
                "_decide wrapper) so the transition stays auditable"))
    return findings


# --------------------------------------------------------------------- #
# rule: unregistered-pytest-marker (tests/)
# --------------------------------------------------------------------- #

def registered_markers(root: str) -> Set[str]:
    """Markers declared in ``[tool.pytest.ini_options] markers`` of the
    repo's pyproject.toml, parsed textually (python 3.10: no tomllib;
    the markers block is a simple list of ``"name: description"``
    strings)."""
    path = os.path.join(root, "pyproject.toml")
    if not os.path.exists(path):
        return set()
    text = open(path).read()
    m = re.search(r"^markers\s*=\s*\[(.*?)\]", text,
                  re.MULTILINE | re.DOTALL)
    if not m:
        return set()
    body = m.group(1)
    # TOML strings; try double-quoted first (apostrophes inside
    # descriptions must not act as delimiters), else single-quoted
    entries = re.findall(r'"([^"]*)"', body) \
        or re.findall(r"'([^']*)'", body)
    return {entry.split(":", 1)[0].strip() for entry in entries}


class _MarkerVisitor(_ScopeTracker):
    def __init__(self, path: str, known: Set[str]) -> None:
        super().__init__()
        self.path = path
        self.known = known | BUILTIN_MARKERS
        self.findings: List[Finding] = []

    def visit_Attribute(self, node: ast.Attribute) -> None:
        # pytest.mark.<name>, possibly called: pytest.mark.foo(...)
        v = node.value
        if isinstance(v, ast.Attribute) and v.attr == "mark" \
                and _dotted(v) == "pytest.mark" \
                and node.attr not in self.known:
            self.findings.append(Finding(
                "unregistered-pytest-marker", self.path, node.lineno,
                self.symbol,
                f"marker '{node.attr}' is not declared in "
                "pyproject.toml [tool.pytest.ini_options] markers"))
        self.generic_visit(node)


# --------------------------------------------------------------------- #
# drivers
# --------------------------------------------------------------------- #

def lint_file(path: str, rel: str, *, markers: Set[str],
              in_package: bool, in_benchmarks: bool,
              in_tests: bool,
              in_serving: Optional[bool] = None,
              in_sim: Optional[bool] = None,
              plane_methods: Optional[Set[str]] = None) -> List[Finding]:
    """All findings for one file.  ``rel`` is the repo-relative posix
    path recorded on the findings; the ``in_*`` flags select which rule
    families apply (set by :func:`run_lint` from the file's location).
    ``in_serving`` / ``in_sim`` default from ``rel`` (files under
    ``bluefog_tpu/serving/`` / ``bluefog_tpu/sim/``); pass them
    explicitly to force the rule on a fixture.  ``plane_methods``
    defaults from ``_DECISION_PLANE_METHODS[rel]`` (empty elsewhere);
    pass a method-name set explicitly to force the
    decision-outside-recorder rule on a fixture."""
    try:
        tree = ast.parse(open(path).read(), filename=path)
    except SyntaxError as e:
        return [Finding("syntax-error", rel, e.lineno or 0, "<module>",
                        f"file does not parse: {e.msg}")]
    if in_serving is None:
        in_serving = rel.startswith("bluefog_tpu/serving/")
    if in_sim is None:
        in_sim = rel.startswith("bluefog_tpu/sim/")
    if plane_methods is None:
        plane_methods = _DECISION_PLANE_METHODS.get(rel, frozenset())
    findings: List[Finding] = []
    if plane_methods:
        findings += _decision_findings(tree, rel, plane_methods)
    if in_package:
        if os.path.basename(path) != "config.py":
            v = _EnvReadVisitor(rel)
            v.visit(tree)
            findings += v.findings
        tv = _TracedBodyVisitor(rel, _collect_traced_names(tree),
                                _collect_path_callbacks(tree))
        tv.visit(tree)
        findings += tv.findings
        if not _module_is_weight_authority(tree):
            wv = _WeightBypassVisitor(rel)
            wv.visit(tree)
            findings += wv.findings
            ws = _WeightSwapVisitor(rel)
            ws.visit(tree)
            findings += ws.findings
    if in_serving:
        sv = _SleepInLoopVisitor(rel)
        sv.visit(tree)
        findings += sv.findings
    if in_sim:
        cv = _WallClockVisitor(rel)
        cv.visit(tree)
        findings += cv.findings
    if in_benchmarks:
        rv = _UnseededRandomVisitor(rel)
        rv.visit(tree)
        findings += rv.findings
    if in_tests:
        mv = _MarkerVisitor(rel, markers)
        mv.visit(tree)
        findings += mv.findings
    return findings


def _py_files(base: str):
    for dirpath, dirnames, filenames in os.walk(base):
        dirnames[:] = [d for d in dirnames
                       if d not in ("__pycache__", ".git")]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def run_lint(root: str) -> List[Finding]:
    """Lint the whole checkout at ``root``: ``bluefog_tpu/`` (package
    rules), ``benchmarks/`` (randomness rule), ``tests/`` (marker
    rule).  Missing directories are skipped, so the lint also works on
    an installed package tree."""
    markers = registered_markers(root)
    findings: List[Finding] = []
    scans = [("bluefog_tpu", dict(in_package=True, in_benchmarks=False,
                                  in_tests=False)),
             ("benchmarks", dict(in_package=False, in_benchmarks=True,
                                 in_tests=False)),
             ("tests", dict(in_package=False, in_benchmarks=False,
                            in_tests=True))]
    for sub, flags in scans:
        base = os.path.join(root, sub)
        if not os.path.isdir(base):
            continue
        for path in _py_files(base):
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            findings += lint_file(path, rel, markers=markers, **flags)
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule))
