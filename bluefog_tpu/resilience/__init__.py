"""Resilience subsystem: fault injection, failure detection, topology
healing, and guarded-rollback training.

The reference (and the paper) argue decentralized neighbor averaging
tolerates imperfect communication; this package makes the TPU build
actually survive it, in four shape-stable layers — faults change
jitted-program *inputs*, never shapes, so nothing ever recompiles:

* :mod:`~bluefog_tpu.resilience.faults` — deterministic fault plans
  (NaN/Inf gradient bursts, rank death, host stalls) injected through
  the batch, for tests and the chaos benchmark
  (benchmarks/chaos_resilience.py);
* :mod:`~bluefog_tpu.resilience.detector` — per-rank numeric health
  from the guard's in-graph ``isfinite`` reduce + process liveness from
  the heartbeat beacons;
* :mod:`~bluefog_tpu.resilience.healing` — dead-rank excision as a
  weight re-planning problem: row-stochasticity-preserving healed
  weights delivered as traced DATA through the train step's existing
  ``lax.switch`` schedule machinery;
* :mod:`~bluefog_tpu.resilience.runner` — ``run_resilient``, the
  skip -> detect -> heal -> rollback-with-backoff control loop over the
  ``Checkpointer``.

The jitted half lives in ``optim.functional``:
``build_train_step(..., guard=GuardConfig(...))``.  The GROWTH
direction of the lifecycle — ranks that join back, with quarantined
bootstrap and the exact inverse of healing — is the sibling package
:mod:`bluefog_tpu.elastic` (``run_resilient(elastic=...)``).  Guide:
docs/resilience.md.
"""

from bluefog_tpu.optim.functional import (  # noqa: F401
    GuardConfig,
    comm_weight_inputs,
)
from bluefog_tpu.resilience.faults import (  # noqa: F401
    Fault,
    FaultPlan,
    PREEMPT,
    ServingFault,
    ServingFaultPlan,
)
from bluefog_tpu.resilience.detector import (  # noqa: F401
    FailureDetector,
    update_health,
)
from bluefog_tpu.resilience.healing import (  # noqa: F401
    consensus_simulation,
    heal_spec,
    heal_weights,
    healed_comm_weights,
    is_row_stochastic,
    mixing_matrix,
    mixing_matrix_from_weights,
    row_sums,
)
from bluefog_tpu.resilience.runner import (  # noqa: F401
    ResilienceEvent,
    ResilientResult,
    run_resilient,
)
# the growth direction of the lifecycle rides run_resilient(elastic=...),
# so its config is part of this package's surface too
from bluefog_tpu.elastic.membership import ElasticConfig  # noqa: F401

__all__ = [
    "ElasticConfig",
    "GuardConfig",
    "comm_weight_inputs",
    "Fault",
    "FaultPlan",
    "PREEMPT",
    "ServingFault",
    "ServingFaultPlan",
    "FailureDetector",
    "update_health",
    "consensus_simulation",
    "heal_spec",
    "heal_weights",
    "healed_comm_weights",
    "is_row_stochastic",
    "mixing_matrix",
    "mixing_matrix_from_weights",
    "row_sums",
    "ResilienceEvent",
    "ResilientResult",
    "run_resilient",
]
