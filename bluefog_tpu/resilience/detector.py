"""Failure detection: numeric health + liveness heartbeats.

Two independent failure signals, fused here:

* **Numeric health** — the guarded train step's in-graph
  ``jnp.isfinite`` reduce over (loss, updates) surfaces as a rank-major
  ``skipped`` vector every step (see
  ``optim.functional._all_finite``); :class:`FailureDetector` folds the
  per-step flags into per-rank *consecutive* and *total* skip counts.
  A rank that skips ``k`` steps in a row is a death suspect — a
  transient NaN burst recovers its streak to zero, a dead rank never
  does.
* **Liveness heartbeats** — the ``_Heartbeat`` beacons every process
  already publishes (``context.py``; the stall watchdog reads them to
  *name* a hang).  ``heartbeat_dead_processes`` re-exposes that
  judgment for proactive health checks, and
  ``heartbeat_dead_ranks`` maps stale processes to the mesh ranks
  (devices) they own — the mask topology healing consumes.

The detector itself is pure host-side bookkeeping: it never touches the
device, so calling it every step costs nothing against the jitted
program.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

__all__ = ["FailureDetector", "update_health"]


def update_health(tree) -> np.ndarray:
    """Per-rank finiteness of a rank-major pytree: entry ``r`` is True
    iff every inexact leaf's slice ``[r]`` is fully finite.  The eager
    counterpart of the guard's in-graph health reduce — use it to audit
    params/updates outside a guarded step."""
    import jax

    leaves = [np.asarray(l) for l in jax.tree.leaves(tree)]
    ok: Optional[np.ndarray] = None
    for leaf in leaves:
        if not np.issubdtype(leaf.dtype, np.inexact):
            continue
        if leaf.ndim < 1:
            raise ValueError(
                "update_health needs rank-major leaves (leading rank "
                f"axis); got a scalar leaf of dtype {leaf.dtype}")
        h = np.isfinite(leaf.reshape(leaf.shape[0], -1)).all(axis=1)
        ok = h if ok is None else (ok & h)
    if ok is None:
        raise ValueError("update_health: tree has no inexact leaves")
    return ok


class FailureDetector:
    """Per-rank failure bookkeeping over the guarded step's skip flags.

    ``observe`` one rank-major skip vector per step; ``suspects(k)``
    names ranks with >= k CONSECUTIVE skips that have not already been
    declared dead; ``declare_dead`` commits a verdict.  Death is not
    rescinded by recovery — a healed topology has no path back for a
    rank whose state silently diverged — but it IS reversible through
    the elastic membership lifecycle: ``readmit`` (called by
    ``MembershipController.promote`` once a rejoining rank's
    bootstrapped state has re-converged) clears the verdict along with
    the latched streak/suspicion that would instantly re-excise the
    rank.  ``dead_mask`` is the boolean mask topology healing takes."""

    def __init__(self, size: int):
        if size < 1:
            raise ValueError(f"size must be >= 1, got {size}")
        self.size = size
        self._consecutive = np.zeros(size, np.int64)
        self._total = np.zeros(size, np.int64)
        self._dead = np.zeros(size, bool)
        # rank -> the set of SOURCES currently suspecting it: several
        # independent monitors (straggler gossip, heartbeats, an
        # operator) may suspect the same rank, and one source clearing
        # its claim must not erase the others'
        self._external: Dict[int, set] = {}

    # ------------------------------------------------------------- #
    # numeric health
    # ------------------------------------------------------------- #
    def observe(self, skipped) -> None:
        """Fold one step's rank-major skip flags into the counters."""
        sk = np.asarray(skipped).reshape(-1).astype(bool)
        if sk.shape[0] != self.size:
            raise ValueError(
                f"skip vector of length {sk.shape[0]} does not match "
                f"world size {self.size}")
        self._total += sk
        self._consecutive = np.where(sk, self._consecutive + 1, 0)

    def consecutive_bad(self) -> np.ndarray:
        return self._consecutive.copy()

    def total_skips(self) -> np.ndarray:
        return self._total.copy()

    def streak_suspects(self, k: int) -> List[int]:
        """Live ranks with >= k consecutive skipped steps — the purely
        NUMERIC evidence.  This is what the rollback loop's death
        declaration keys on: a straggler flag (external suspicion) must
        never convert a NaN window into an execution of a
        healthy-but-slow rank."""
        return [int(r) for r in
                np.nonzero((self._consecutive >= k) & ~self._dead)[0]]

    def suspects(self, k: int) -> List[int]:
        """The fused suspicion view: live ranks with >= k consecutive
        skipped steps, plus any EXTERNALLY suspected live ranks
        (``suspect`` — the fleet telemetry layer's straggler flags land
        here).  For monitoring/policy; death attribution uses
        :meth:`streak_suspects`."""
        out = set(self.streak_suspects(k))
        out |= {r for r, srcs in self._external.items()
                if srcs and not self._dead[r]}
        return sorted(out)

    def suspect(self, ranks: Sequence[int],
                source: str = "external") -> None:
        """Register external suspicion from ``source`` (e.g.
        ``"straggler"`` for the gossiped flags of
        ``observe.fleet.StragglerDetector``); already-dead ranks are
        ignored.  A rank stays suspected while ANY source claims it."""
        for r in ranks:
            if not 0 <= r < self.size:
                raise ValueError(f"rank {r} outside world {self.size}")
            if not self._dead[r]:
                self._external.setdefault(int(r), set()).add(source)

    def clear_suspicion(self, ranks: Optional[Sequence[int]] = None,
                        source: Optional[str] = None) -> None:
        """Withdraw external suspicion: ``source``'s claims only (every
        source's with ``source=None``), on ``ranks`` (all ranks with
        ``ranks=None``).  A rank another source still suspects stays
        suspected — one monitor's recovery never erases another's
        standing claim."""
        targets = (list(self._external) if ranks is None
                   else [int(r) for r in ranks])
        for r in targets:
            srcs = self._external.get(r)
            if srcs is None:
                continue
            if source is None:
                srcs.clear()
            else:
                srcs.discard(source)
            if not srcs:
                self._external.pop(r, None)

    def external_suspects(self) -> List[int]:
        return sorted(r for r, srcs in self._external.items()
                      if srcs and not self._dead[r])

    def declare_dead(self, ranks: Sequence[int]) -> None:
        for r in ranks:
            if not 0 <= r < self.size:
                raise ValueError(f"rank {r} outside world {self.size}")
            self._dead[r] = True

    def readmit(self, ranks: Sequence[int]) -> None:
        """Reverse a death verdict for ranks the elastic membership
        lifecycle has re-bootstrapped (``MembershipController.promote``
        calls this once quarantine disagreement clears the threshold).

        Clearing the dead flag alone would NOT be enough: the
        consecutive-skip streak kept counting while the rank was dead
        (``observe`` has no dead special-case) and external suspicion
        latches until its source withdraws it — either one would make
        ``suspects()`` re-excise the rank on its first live step.  So
        readmission also zeroes the streak and drops every source's
        external claim.  ``total_skips`` is history, not suspicion, and
        is kept."""
        for r in ranks:
            if not 0 <= r < self.size:
                raise ValueError(f"rank {r} outside world {self.size}")
            if not self._dead[r]:
                raise ValueError(
                    f"rank {r} is not dead — nothing to readmit")
        for r in ranks:
            r = int(r)
            self._dead[r] = False
            self._consecutive[r] = 0
            self._external.pop(r, None)

    def dead_mask(self) -> np.ndarray:
        return self._dead.copy()

    def live_bad(self, skipped) -> bool:
        """Did any NOT-yet-declared-dead rank skip this step?  (Dead
        ranks skip forever by design — only live skips should count
        toward a rollback trigger.)"""
        sk = np.asarray(skipped).reshape(-1).astype(bool)
        return bool((sk & ~self._dead).any())

    def reset_streaks(self) -> None:
        """Clear the consecutive counters (after a rollback: the
        restored state re-earns its health)."""
        self._consecutive[:] = 0

    # ------------------------------------------------------------- #
    # liveness heartbeats
    # ------------------------------------------------------------- #
    @staticmethod
    def heartbeat_dead_processes(threshold: float) -> List[int]:
        """Processes whose liveness heartbeat has not advanced for
        ``threshold`` seconds (empty when liveness cannot be determined
        — single process / no KV store).  Thin re-export of the beacon
        judgment the stall watchdog uses (context._Heartbeat)."""
        from bluefog_tpu.context import _heartbeat

        return _heartbeat.stale_processes(threshold)

    @staticmethod
    def heartbeat_dead_ranks(threshold: float) -> List[int]:
        """Mesh ranks owned by heartbeat-stale processes — the rank mask
        a healed topology excises.  Requires an initialized context;
        empty when liveness cannot be determined."""
        from bluefog_tpu import context as ctx_mod

        stale = FailureDetector.heartbeat_dead_processes(threshold)
        if not stale or not ctx_mod.is_initialized():
            return []
        ctx = ctx_mod.get_context()
        stale_set = set(stale)
        return [r for r, d in enumerate(ctx.devices)
                if d.process_index in stale_set]
