"""Deterministic fault injection — the chaos harness.

The paper's core claim is that decentralized neighbor averaging
tolerates imperfect communication; proving the *system* tolerates it
needs faults that are reproducible enough to assert exact outcomes
against.  A :class:`FaultPlan` is a pure host-side schedule: at step S,
rank r emits NaN/Inf gradients (a burst of ``duration`` steps), goes
dead (emits garbage forever — the SPMD simulation of a lost device,
whose slot keeps executing but whose contribution must be excluded), or
stalls the host loop.

Injection is SHAPE-STABLE by construction: faults enter the jitted
train step only through its *inputs* (the batch rows of the faulted
rank are poisoned host-side before ``device_put``), so a guarded step
compiled once serves every fault pattern — the zero-recompile contract
tests/test_resilience.py asserts the same way test_serving.py asserts
compile counts.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Sequence, Tuple

import numpy as np

__all__ = ["Fault", "FaultPlan", "NAN", "INF", "DEAD", "STALL", "PREEMPT",
           "CONGEST", "ServingFault", "ServingFaultPlan", "REPLICA_DEATH",
           "REPLICA_STALL", "SUBMIT_REJECT"]

NAN, INF, DEAD, STALL, PREEMPT = "nan", "inf", "dead", "stall", "preempt"
CONGEST = "congest"
_KINDS = (NAN, INF, DEAD, STALL, PREEMPT, CONGEST)

REPLICA_DEATH = "replica_death"
REPLICA_STALL = "replica_stall"
SUBMIT_REJECT = "submit_reject"
_SERVING_KINDS = (REPLICA_DEATH, REPLICA_STALL, SUBMIT_REJECT)


@dataclasses.dataclass(frozen=True)
class Fault:
    """One scheduled fault.

    ``step``: first step the fault is active.  ``duration``: steps a
    nan/inf burst — or a ``stall``, or a ``preempt`` — lasts (ignored
    for ``dead``, which is permanent).  A ``preempt`` is
    duration-limited death: the rank emits NaN like a dead rank for
    ``[step, step + duration)`` and computes healthily again after —
    the deterministic, replayable input of a preempt -> rejoin cycle
    (elastic membership; the returning rank is ``rejoinable_ranks``'s
    answer, not automatically live).  ``stall_seconds``: host-loop
    sleep injected PER ACTIVE STEP by a ``stall`` fault (exercises the
    watchdog / op timeout / straggler detector, not the numerics); a
    multi-step stall on one rank is the injected-straggler scenario.

    A ``congest`` fault degrades the directed LINK ``rank -> dst`` by
    ``factor`` (time per byte, not correctness) for ``duration`` steps
    — the fault class the topology control plane exists to route
    around.  It corrupts nothing and stalls nothing by itself; a chaos
    harness reads :meth:`FaultPlan.congested_links` each step and
    charges the active schedule's use of the slowed link (virtual
    per-edge seconds fed into ``bf_edge_seconds_total`` and the
    per-rank step-time vector)."""

    step: int
    rank: int
    kind: str
    duration: int = 1
    stall_seconds: float = 0.0
    dst: int = -1
    factor: float = 1.0

    def __post_init__(self):
        if self.kind not in _KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}; "
                             f"one of {_KINDS}")
        if self.step < 0:
            raise ValueError(f"fault step must be >= 0, got {self.step}")
        if self.duration < 1:
            raise ValueError(
                f"fault duration must be >= 1, got {self.duration}")
        if self.kind == CONGEST:
            if self.dst < 0:
                raise ValueError("a congest fault names a directed link "
                                 "— dst must be a valid rank")
            if self.factor < 1.0:
                raise ValueError(f"congestion factor must be >= 1 "
                                 f"(a slowdown), got {self.factor}")


class FaultPlan:
    """An immutable, deterministic schedule of faults over ``size`` ranks.

    The plan answers two questions per step: which ranks' gradients are
    corrupted (``corrupt_codes`` / ``corrupt_batch``) and how long the
    host loop should stall (``stall_seconds``).  A ``dead`` rank is
    modeled as a permanent NaN emitter from its death step on — the
    in-process stand-in for a lost device: the guard skips it every
    step, the detector's consecutive-skip count crosses the death
    threshold, and healing excises it from the mixing matrix.
    """

    def __init__(self, size: int, faults: Sequence[Fault] = ()):
        if size < 1:
            raise ValueError(f"size must be >= 1, got {size}")
        for f in faults:
            if not 0 <= f.rank < size:
                raise ValueError(
                    f"fault rank {f.rank} outside world of size {size}")
            if f.kind == CONGEST and not 0 <= f.dst < size:
                raise ValueError(
                    f"congest dst {f.dst} outside world of size {size}")
        self.size = size
        self.faults: Tuple[Fault, ...] = tuple(
            sorted(faults, key=lambda f: (f.step, f.rank)))

    # ------------------------------------------------------------- #
    # constructors for the common chaos scenarios
    # ------------------------------------------------------------- #
    @staticmethod
    def healthy(size: int) -> "FaultPlan":
        return FaultPlan(size, ())

    @staticmethod
    def nan_burst(size: int, rank: int, step: int,
                  duration: int = 1) -> "FaultPlan":
        return FaultPlan(size, [Fault(step, rank, NAN, duration)])

    @staticmethod
    def rank_death(size: int, rank: int, step: int) -> "FaultPlan":
        return FaultPlan(size, [Fault(step, rank, DEAD)])

    @staticmethod
    def straggler(size: int, rank: int, step: int, duration: int,
                  stall_seconds: float) -> "FaultPlan":
        """One rank runs ``stall_seconds`` slow for ``duration``
        consecutive steps — the injected-straggler scenario the
        ``observe.fleet.StragglerDetector`` must name (chaos bench:
        detection latency is a machine-checked claim)."""
        return FaultPlan(size, [Fault(step, rank, STALL, duration,
                                      stall_seconds=stall_seconds)])

    @staticmethod
    def preempt(size: int, rank: int, step: int,
                duration: int) -> "FaultPlan":
        """Duration-limited death: ``rank`` is a NaN emitter for
        ``[step, step + duration)`` and healthy after — a preemptible
        host losing and regaining its slot.  Pick ``duration`` past the
        guard's death threshold so the detector actually declares the
        rank dead mid-window; once the window ends the rank shows up in
        :meth:`rejoinable_ranks`, which is the default admission signal
        of ``run_resilient(elastic=...)`` — the full preempt -> heal ->
        bootstrap -> rejoin cycle from one deterministic plan."""
        return FaultPlan(size, [Fault(step, rank, PREEMPT, duration)])

    @staticmethod
    def persistent_straggler(size: int, rank: int, step: int,
                             stall_seconds: float,
                             duration: int = 1_000_000) -> "FaultPlan":
        """One rank runs ``stall_seconds`` slow from ``step`` ON — the
        open-ended straggler that never recovers on its own (a bad
        host, a thermally-throttled chip).  Where :meth:`straggler`
        models a transient the detector merely names, a persistent
        straggler is a standing degradation signal the topology
        control plane must eventually re-plan around.  ``duration``
        defaults far past any bench horizon."""
        return FaultPlan(size, [Fault(step, rank, STALL, duration,
                                      stall_seconds=stall_seconds)])

    @staticmethod
    def congest_link(size: int, src: int, dst: int, factor: float,
                     start: int, duration: int) -> "FaultPlan":
        """The directed link ``src -> dst`` carries bytes ``factor``x
        slower for ``[start, start + duration)`` — an injected DCN
        congestion event.  Purely a cost-model fault: nothing is
        corrupted and the host loop is not stalled; the chaos harness
        reads :meth:`congested_links` per step and charges whatever
        the ACTIVE schedule ships across the slowed link, which is
        exactly the signal (``bf_edge_seconds_total`` deltas) the
        topology control plane re-plans from."""
        return FaultPlan(size, [Fault(start, src, CONGEST, duration,
                                      dst=dst, factor=factor)])

    def merged(self, other: "FaultPlan") -> "FaultPlan":
        if other.size != self.size:
            raise ValueError("cannot merge plans over different sizes")
        return FaultPlan(self.size, self.faults + other.faults)

    # ------------------------------------------------------------- #
    # queries
    # ------------------------------------------------------------- #
    def active(self, step: int) -> List[Fault]:
        """Faults live at ``step`` (dead = live forever after onset)."""
        out = []
        for f in self.faults:
            if f.kind == DEAD:
                live = step >= f.step
            else:
                live = f.step <= step < f.step + f.duration
            if live:
                out.append(f)
        return out

    def corrupt_codes(self, step: int) -> np.ndarray:
        """Per-rank corruption codes at ``step``: 0 healthy, 1 NaN,
        2 Inf.  Dead ranks read as 1 (permanent NaN emitters)."""
        codes = np.zeros((self.size,), np.int8)
        for f in self.active(step):
            if f.kind in (NAN, DEAD, PREEMPT):
                codes[f.rank] = 1
            elif f.kind == INF:
                codes[f.rank] = 2
        return codes

    def dead_ranks(self, step: int) -> List[int]:
        return sorted({f.rank for f in self.faults
                       if f.kind == DEAD and step >= f.step})

    def preempted_ranks(self, step: int) -> List[int]:
        """Ranks inside an active preempt window at ``step`` — dead for
        now, but scheduled to come back."""
        return sorted({f.rank for f in self.active(step)
                       if f.kind == PREEMPT})

    def rejoinable_ranks(self, step: int) -> List[int]:
        """Ranks whose preempt window has ENDED by ``step`` and that no
        other fault currently holds — the deterministic admission
        signal for elastic membership (``run_resilient(elastic=...)``
        polls this when no explicit ``admit`` callable is given).  A
        rank re-preempted by a later fault drops out again until that
        window too has passed."""
        ended = {f.rank for f in self.faults
                 if f.kind == PREEMPT and step >= f.step + f.duration}
        held = {f.rank for f in self.active(step)}
        return sorted(ended - held)

    def stall_seconds(self, step: int) -> float:
        return float(sum(f.stall_seconds for f in self.active(step)
                         if f.kind == STALL))

    def stall_seconds_by_rank(self, step: int) -> np.ndarray:
        """Per-rank injected stall at ``step`` — the ``[n]`` vector a
        per-rank step-time synthesizer adds on top of the measured
        wall time (``run_resilient(step_times_fn=...)``)."""
        out = np.zeros(self.size, np.float64)
        for f in self.active(step):
            if f.kind == STALL:
                out[f.rank] += f.stall_seconds
        return out

    def congested_links(self, step: int) -> dict:
        """Directed links degraded at ``step``: ``{(src, dst):
        factor}``, overlapping congestions multiplying.  The virtual
        cost-model input of the adaptive-topology chaos bench: a
        harness multiplies each active edge's nominal transfer time by
        the link's factor before billing ``bf_edge_seconds_total``."""
        out: dict = {}
        for f in self.active(step):
            if f.kind == CONGEST:
                key = (f.rank, f.dst)
                out[key] = out.get(key, 1.0) * f.factor
        return out

    def last_onset(self) -> int:
        """The latest fault onset step (0 for an empty plan) — a chaos
        run should train past this to observe recovery."""
        return max((f.step for f in self.faults), default=0)

    def corrupt_batch(self, batch: Any, step: int) -> Any:
        """Poison the faulted ranks' rows of a HOST rank-major batch.

        Every floating leaf must carry the ``[size, ...]`` leading rank
        axis; faulted ranks' rows are overwritten with NaN/Inf, which the
        backward pass turns into non-finite gradients on exactly those
        ranks — faults become jitted-program *inputs*, never new shapes.
        Healthy steps return ``batch`` unchanged (no copies)."""
        import jax

        codes = self.corrupt_codes(step)
        if not codes.any():
            return batch

        def poison(leaf):
            arr = np.asarray(leaf)
            if not np.issubdtype(arr.dtype, np.floating):
                return leaf
            if arr.ndim < 1 or arr.shape[0] != self.size:
                raise ValueError(
                    f"corrupt_batch needs rank-major leaves with leading "
                    f"dim {self.size}, got shape {arr.shape}")
            arr = arr.copy()
            arr[codes == 1] = np.nan
            arr[codes == 2] = np.inf
            return arr

        return jax.tree.map(poison, batch)

    def __repr__(self):
        return f"FaultPlan(size={self.size}, faults={list(self.faults)})"


# ------------------------------------------------------------------ #
# serving-side chaos: the same deterministic-schedule idiom, over
# replicas and engine steps instead of ranks and train steps
# ------------------------------------------------------------------ #
@dataclasses.dataclass(frozen=True)
class ServingFault:
    """One scheduled serving fault.

    ``step``: first ENGINE step (per-replica step counter, not wall
    time) the fault is active.  ``replica_death`` is permanent from its
    onset: the replica stops stepping entirely — its gauges go stale and
    the router's staleness guard excises it, the in-process stand-in for
    a lost serving host.  ``replica_stall`` sleeps ``stall_seconds`` of
    host time per active step for ``duration`` steps (a GC pause / noisy
    neighbor — the replica is *slow*, not gone).  ``submit_reject``
    makes the replica refuse admission (``RequestRejected``) for every
    submit landing during ``[step, step + duration)`` of its step count
    — the transient-overload input the router's retry/backoff path must
    absorb."""

    step: int
    replica: int
    kind: str
    duration: int = 1
    stall_seconds: float = 0.0

    def __post_init__(self):
        if self.kind not in _SERVING_KINDS:
            raise ValueError(f"unknown serving fault kind {self.kind!r}; "
                             f"one of {_SERVING_KINDS}")
        if self.step < 0:
            raise ValueError(f"fault step must be >= 0, got {self.step}")
        if self.duration < 1:
            raise ValueError(
                f"fault duration must be >= 1, got {self.duration}")


class ServingFaultPlan:
    """An immutable, deterministic schedule of faults over ``size``
    serving replicas.

    Injection is pure host-side control flow wrapped AROUND
    ``ServingEngine.step`` (:class:`bluefog_tpu.serving.FaultyReplica`):
    a dead replica simply stops calling ``step``, a stalled one sleeps
    before it, a rejecting one raises before ``submit`` reaches the
    scheduler.  Nothing enters the jitted programs — the resident
    program set and jit cache sizes are identical under every fault
    pattern (the serving zero-recompile contract).
    """

    def __init__(self, size: int, faults: Sequence[ServingFault] = ()):
        if size < 1:
            raise ValueError(f"size must be >= 1, got {size}")
        for f in faults:
            if not 0 <= f.replica < size:
                raise ValueError(
                    f"fault replica {f.replica} outside fleet of "
                    f"size {size}")
        self.size = size
        self.faults: Tuple[ServingFault, ...] = tuple(
            sorted(faults, key=lambda f: (f.step, f.replica)))

    # ------------------------------------------------------------- #
    # constructors for the common chaos scenarios
    # ------------------------------------------------------------- #
    @staticmethod
    def healthy(size: int) -> "ServingFaultPlan":
        return ServingFaultPlan(size, ())

    @staticmethod
    def replica_death(size: int, replica: int,
                      step: int) -> "ServingFaultPlan":
        return ServingFaultPlan(
            size, [ServingFault(step, replica, REPLICA_DEATH)])

    @staticmethod
    def replica_stall(size: int, replica: int, step: int, duration: int,
                      stall_seconds: float) -> "ServingFaultPlan":
        return ServingFaultPlan(
            size, [ServingFault(step, replica, REPLICA_STALL, duration,
                                stall_seconds=stall_seconds)])

    @staticmethod
    def submit_rejection(size: int, replica: int, step: int,
                         duration: int = 1) -> "ServingFaultPlan":
        return ServingFaultPlan(
            size, [ServingFault(step, replica, SUBMIT_REJECT, duration)])

    def merged(self, other: "ServingFaultPlan") -> "ServingFaultPlan":
        if other.size != self.size:
            raise ValueError("cannot merge plans over different sizes")
        return ServingFaultPlan(self.size, self.faults + other.faults)

    # ------------------------------------------------------------- #
    # queries
    # ------------------------------------------------------------- #
    def active(self, step: int) -> List[ServingFault]:
        """Faults live at ``step`` (death = live forever after onset)."""
        out = []
        for f in self.faults:
            if f.kind == REPLICA_DEATH:
                live = step >= f.step
            else:
                live = f.step <= step < f.step + f.duration
            if live:
                out.append(f)
        return out

    def is_dead(self, replica: int, step: int) -> bool:
        return any(f.replica == replica for f in self.faults
                   if f.kind == REPLICA_DEATH and step >= f.step)

    def dead_replicas(self, step: int) -> List[int]:
        return sorted({f.replica for f in self.faults
                       if f.kind == REPLICA_DEATH and step >= f.step})

    def stall_seconds(self, replica: int, step: int) -> float:
        return float(sum(f.stall_seconds for f in self.active(step)
                         if f.kind == REPLICA_STALL
                         and f.replica == replica))

    def rejects_submit(self, replica: int, step: int) -> bool:
        return any(f.replica == replica for f in self.active(step)
                   if f.kind == SUBMIT_REJECT)

    def last_onset(self) -> int:
        """The latest fault onset step (0 for an empty plan) — a chaos
        run should serve past this to observe recovery."""
        return max((f.step for f in self.faults), default=0)

    def __repr__(self):
        return (f"ServingFaultPlan(size={self.size}, "
                f"faults={list(self.faults)})")
