"""Topology healing: re-plan the mixing weights around dead ranks.

A dead rank silently breaks the row-stochasticity of the mixing matrix:
its in-edges deliver stale (or garbage) payloads that still carry
weight, so every neighbor's combine drifts off the consensus manifold.
Healing treats rank loss as a RE-PLANNING problem over the existing
data-plumbed schedules (the framing of the schedule-synthesis line in
PAPERS.md — "Efficient All-to-All Collective Communication Schedules
for Direct-Connect Topologies"): the edge STRUCTURE (which ppermutes
exist) is compile-time and never changes; the weights are runtime data.

The heal rule, per receiving rank ``dst``:

* every in-edge from a dead ``src`` is zeroed and its weight mass is
  transferred to ``dst``'s self-weight — row sums are preserved
  EXACTLY (no renormalization error), so the healed matrix stays
  row-stochastic and iterated averaging over the surviving ranks still
  contracts to their consensus;
* a dead ``dst`` keeps self-weight 1.0 and no in-weights: its state is
  frozen in place and, with its out-edges zeroed everywhere, it is
  unreachable — excised without touching a single program shape.

Delivery: :func:`healed_comm_weights` emits the same
``(class_weights, self_weights)`` pytree as
``optim.functional.comm_weight_inputs`` — same shapes over the same
shift classes — so a guarded train step swaps topologies as pure input
data through its existing ``lax.switch`` schedule machinery.  Zero
recompiles is the whole point: the zero-weight edges still transfer
(the reference also ships scaled-by-zero payloads rather than skipping
sends, mpi_controller.cc:594-600), which is sound because the skip
guard keeps every rank's params finite — 0 * finite == 0.
"""

from __future__ import annotations

# This module legitimately constructs weight tables from scratch — the
# analysis lint's weight-matrix-bypass rule treats it as an authority
# (everywhere else, tables must come from the shared helpers here).
_WEIGHT_AUTHORITY = True

from typing import List, Sequence, Union

import numpy as np

from bluefog_tpu.topology.spec import (DynamicTopology, Topology,
                                       self_weights_of as _self_weights_of)

CommSpec = Union[Topology, DynamicTopology]

__all__ = [
    "mixing_matrix",
    "mixing_matrix_from_weights",
    "row_sums",
    "is_row_stochastic",
    "heal_weights",
    "heal_spec",
    "healed_comm_weights",
    "machine_dead_mask",
    "healed_hierarchical_comm_weights",
    "consensus_simulation",
]


def mixing_matrix(spec: CommSpec) -> np.ndarray:
    """The round's mixing matrix M, RECEIVER-major: one round of
    neighbor averaging is ``x_new = M @ x`` with
    ``M[dst, src]`` the weight dst applies to src's value and
    ``M[dst, dst]`` the self weight.  (Note this is the transpose of
    ``Topology.weights``' sender-major convention.)"""
    n = spec.size
    M = np.zeros((n, n), np.float64)
    M[np.arange(n), np.arange(n)] = np.asarray(_self_weights_of(spec),
                                               np.float64)
    for cls in spec.shift_classes:
        for (src, dst) in cls.perm:
            if cls.recv_weights[dst] != 0.0:
                M[dst, src] += cls.recv_weights[dst]
    return M


def mixing_matrix_from_weights(spec: CommSpec, class_weights,
                               self_weights) -> np.ndarray:
    """The receiver-major mixing matrix a ``(class_weights [n_classes,
    n], self_weights [n])`` table pair induces over ``spec``'s edge
    structure — the numpy view of exactly what a compiled step does
    with re-planned weight DATA (healed, grown, or bootstrap-annealed),
    for simulation and row-sum audits."""
    n = spec.size
    cw = np.asarray(class_weights, np.float64)
    sw = np.asarray(self_weights, np.float64).reshape(-1)
    classes = spec.shift_classes
    if cw.shape != (len(classes), n) or sw.shape[0] != n:
        raise ValueError(
            f"weight tables of shapes {cw.shape}/{sw.shape} do not "
            f"match {len(classes)} classes over size {n}")
    M = np.zeros((n, n), np.float64)
    M[np.arange(n), np.arange(n)] = sw
    for c, cls in enumerate(classes):
        for (src, dst) in cls.perm:
            if cw[c, dst] != 0.0:
                M[dst, src] += cw[c, dst]
    return M


def row_sums(spec: CommSpec) -> np.ndarray:
    return mixing_matrix(spec).sum(axis=1)


def is_row_stochastic(spec: CommSpec, tol: float = 1e-9) -> bool:
    """Every rank's combine weights (self + in-edges) sum to 1 — the
    invariant that makes iterated neighbor averaging consensus-
    preserving, and the one a dead rank breaks until healed."""
    return bool(np.all(np.abs(row_sums(spec) - 1.0) <= tol))


def heal_weights(spec: CommSpec, dead_mask) -> tuple:
    """Healed ``(class_weights [n_classes, n], self_weights [n])``
    float64 arrays over ``spec``'s OWN shift classes (same shapes as the
    unhealed ``collectives.class_recv_weights`` / ``self_weight_vector``
    tables — shape-stability is the contract).

    Dead srcs' weight mass moves to the receiver's self weight (exact
    row-sum preservation); dead receivers get self weight 1.0 and no
    in-weights."""
    n = spec.size
    dead = np.asarray(dead_mask, bool).reshape(-1)
    if dead.shape[0] != n:
        raise ValueError(
            f"dead mask of length {dead.shape[0]} does not match "
            f"topology size {n}")
    classes = spec.shift_classes
    cw = (np.array([cls.recv_weights for cls in classes], np.float64)
          if classes else np.zeros((0, n), np.float64))
    sw = np.asarray(_self_weights_of(spec), np.float64).copy()
    for c, cls in enumerate(classes):
        for dst in range(n):
            w = cw[c, dst]
            if w == 0.0:
                continue
            src = (dst - cls.shift) % n
            if dead[dst]:
                cw[c, dst] = 0.0
            elif dead[src]:
                sw[dst] += w
                cw[c, dst] = 0.0
    sw[dead] = 1.0
    return cw, sw


def heal_spec(spec: CommSpec, dead_mask) -> CommSpec:
    """A standalone healed spec of the same type (for eager ops and
    simulation).  A DynamicTopology keeps its edge tuple — dead edges
    stay DECLARED at weight 0.0, preserving the shift-class structure
    (and thus the compiled program) exactly; a Topology is rebuilt from
    the healed weight matrix (zero edges drop — fine for an eager spec,
    but data delivery into a compiled step must go through
    :func:`healed_comm_weights` instead)."""
    cw, sw = heal_weights(spec, dead_mask)
    n = spec.size
    if isinstance(spec, DynamicTopology):
        healed = {}
        classes = spec.shift_classes
        by_edge = {}
        for c, cls in enumerate(classes):
            for (src, dst) in cls.perm:
                by_edge[(src, dst)] = cw[c, dst]
        vals = tuple(float(by_edge.get(e, 0.0)) for e in spec.edges)
        return DynamicTopology(n, spec.edges, vals,
                               tuple(float(w) for w in sw))
    W = np.zeros((n, n), np.float64)
    for c, cls in enumerate(spec.shift_classes):
        for (src, dst) in cls.perm:
            W[src, dst] += cw[c, dst]
    W[np.arange(n), np.arange(n)] = sw
    return Topology.from_weight_matrix(W)


# the last (n_specs, dead-index tuple) recorded into the flight
# recorder: healed_comm_weights runs on EVERY weight render, so the
# healing plane records a decision only when the excised set actually
# changes — a re-render of the same heal is data delivery, not a new
# decision
_last_healed_recorded = None


def healed_comm_weights(specs: Sequence[CommSpec], dead_mask) -> tuple:
    """The healed schedule as traced-operand DATA: one
    ``(class_weights, self_weights)`` jnp pair per round, structurally
    identical to ``optim.functional.comm_weight_inputs(specs)`` — pass
    it as a guarded train step's ``comm_weights`` and the dead ranks
    are excised without a recompile."""
    import jax.numpy as jnp

    global _last_healed_recorded
    dead = np.asarray(dead_mask, bool).reshape(-1)
    key = (len(specs), tuple(int(i) for i in np.flatnonzero(dead)))
    if key != _last_healed_recorded and (
            dead.any() or _last_healed_recorded is not None):
        _last_healed_recorded = key
        from bluefog_tpu.observe import blackbox as _blackbox

        _blackbox.record_decision(
            "healing", "replan", step=-1,
            telemetry={"dead": list(key[1]), "rounds": len(specs),
                       "size": int(dead.shape[0])})
    out = []
    for s in specs:
        cw, sw = heal_weights(s, dead_mask)
        out.append((jnp.asarray(cw), jnp.asarray(sw)))
    return tuple(out)


def machine_dead_mask(dead_mask, local_size: int) -> np.ndarray:
    """Collapse a RANK-level dead mask to the MACHINE level: a machine is
    dead when ANY of its ``local_size`` ranks is dead.

    Under the hierarchical exchange the machine is the failure domain:
    the intra-machine reduce is an exact grouped psum whose program
    cannot skip a member, so a machine containing a dead rank has a
    polluted mean and is excised from the inter-machine mixing as a
    unit (conservative — its surviving ranks keep their machine-local
    consensus and rejoin with the machine)."""
    from bluefog_tpu.parallel.collectives import validate_machine_decomposition

    dead = np.asarray(dead_mask, bool).reshape(-1)
    validate_machine_decomposition(dead.shape[0], local_size)
    return dead.reshape(-1, int(local_size)).any(axis=1)


def healed_hierarchical_comm_weights(machine_specs: Sequence[CommSpec],
                                     dead_mask, local_size: int) -> tuple:
    """Healed MACHINE-level weight tables from a RANK-level dead mask —
    the hierarchical train step's ``comm_weights`` delivery.  The rank
    mask collapses through :func:`machine_dead_mask` and the machine
    schedule heals exactly like a flat one; the tables are machine-sized
    (``[n_classes, n_machines]`` / ``[n_machines]``) so dead ranks and
    joiners swap in as pure data — zero recompiles, same contract as
    :func:`healed_comm_weights`."""
    return healed_comm_weights(machine_specs,
                               machine_dead_mask(dead_mask, local_size))


def consensus_simulation(specs: Sequence[CommSpec], rounds: int,
                         dim: int = 32, seed: int = 0,
                         dead_mask=None, weights=None) -> np.ndarray:
    """Seeded consensus-distance trace of iterated mixing (the
    wire_quant_consensus harness's pure-numpy machinery, pointed at
    healing): iterate ``x <- M_t @ x`` over the schedule and report,
    per round, the max deviation of the LIVE ranks from their own
    running mean.

    Dead ranks model a real failure: their rows are FROZEN (a dead
    device computes nothing) while neighbors keep reading whatever the
    schedule's weights say.  Under a healed schedule those weights are
    zero and the survivors contract to their own consensus; under an
    UNHEALED schedule the frozen rows act as disagreeing anchors that
    hold the live ranks apart — the stalled floor this function makes
    measurable (benchmarks/chaos_resilience.py).

    ``weights`` overrides the specs' own tables with re-planned
    per-round ``(class_weights, self_weights)`` pairs (one per spec,
    cycled) — the same data a compiled step would be fed, so healed,
    grown, and bootstrap-annealed schedules simulate through the one
    code path (:func:`mixing_matrix_from_weights`)."""
    n = specs[0].size
    dead = (np.zeros(n, bool) if dead_mask is None
            else np.asarray(dead_mask, bool).reshape(-1))
    live = ~dead
    if not live.any():
        raise ValueError("no live ranks to simulate")
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, dim))
    if weights is None:
        mats = [mixing_matrix(s) for s in specs]
    else:
        if len(weights) != len(specs):
            raise ValueError(
                f"{len(weights)} weight pairs against {len(specs)} specs")
        mats = [mixing_matrix_from_weights(s, cw, sw)
                for s, (cw, sw) in zip(specs, weights)]
    trace = np.zeros(rounds)
    for t in range(rounds):
        new = mats[t % len(mats)] @ x
        new[dead] = x[dead]
        x = new
        xbar = x[live].mean(axis=0)
        trace[t] = np.abs(x[live] - xbar).max()
    return trace
