"""Guarded-rollback resilient training: the host-side control loop.

``run_resilient`` drives a GUARDED train step (built with
``build_train_step(..., guard=GuardConfig(...))``) through a fault
environment:

* every step's rank-major ``skipped`` flags feed the
  :class:`~bluefog_tpu.resilience.detector.FailureDetector`;
* transient faults cost exactly the faulty rank's skipped steps —
  nothing else happens;
* after K (= ``guard.max_consecutive_bad``) consecutive steps with a
  LIVE-rank skip, the loop (1) declares the persistently-bad ranks dead,
  (2) heals the mixing weights (``healing.healed_comm_weights`` — new
  weight data, same compiled program), (3) rolls back to the last good
  :class:`~bluefog_tpu.checkpoint.Checkpointer` state, and (4) sleeps an
  exponential backoff before resuming;
* checkpoints are taken every ``checkpoint_every`` steps, but only at
  steps with no live-rank skip — rollback always lands on a state the
  guard certified finite.

Determinism contract: batches come from ``batch_fn(step)`` (a pure
function of the step index), so replayed steps after a rollback see the
SAME data — a run is reproducible fault plan included, which is what
lets tests parity-check the rollback against the saved checkpoint.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from bluefog_tpu import observe
from bluefog_tpu.context import BluefogError
from bluefog_tpu.optim.functional import GuardConfig
from bluefog_tpu.resilience.detector import FailureDetector
from bluefog_tpu.resilience.faults import FaultPlan
from bluefog_tpu.resilience.healing import (healed_comm_weights,
                                            healed_hierarchical_comm_weights)

__all__ = ["ResilienceEvent", "ResilientResult", "run_resilient"]


@dataclasses.dataclass(frozen=True)
class ResilienceEvent:
    """One entry of the run's event log: ``kind`` in {"checkpoint",
    "skip", "rank_dead", "rollback", "straggler",
    "bad_window_unattributed", "rank_joining", "rank_promoted",
    "rank_join_failed", "topology_trigger", "topology_reject",
    "topology_swap", "topology_commit", "topology_rollback"} (the
    ``topology_*`` kinds come from the topology control plane when the
    run was started with ``control=``; their ``detail`` carries the
    plane's reason/schedule/score fields);
    ``step`` is the step index the event fired at;
    ``detail`` carries kind-specific fields (rollback:
    ``restored_step``, ``backoff``, ``dead``; straggler: ``ranks``,
    ``z``; the elastic kinds: ``rank``, plus ``disagreement``/``rounds``
    on promotion and ``reason`` on a failed join —
    ``"quarantine_expired"``, ``"rollback"`` for an in-flight joiner a
    rollback stranded, or ``"promotion_rolled_back"`` for a rank whose
    promotion postdates the restored checkpoint)."""

    kind: str
    step: int
    detail: dict


@dataclasses.dataclass
class ResilientResult:
    params: Any
    opt_state: Any
    step: int
    last_loss: Optional[np.ndarray]
    total_skips: np.ndarray       # [n] skips per rank, replays included
    n_rollbacks: int
    dead_mask: np.ndarray         # [n] bool
    events: List[ResilienceEvent]
    # final per-rank membership states ("live"/"dead"/"joining") when
    # the run was elastic; None otherwise
    membership: Optional[List[str]] = None


def run_resilient(
    train_step: Callable,
    params: Any,
    opt_state: Any,
    batch_fn: Callable[[int], Any],
    *,
    steps: int,
    checkpointer,
    mesh,
    axis_name: str = "bf",
    guard: Optional[GuardConfig] = None,
    schedule: Optional[Sequence] = None,
    comm_weights: Optional[tuple] = None,
    fault_plan: Optional[FaultPlan] = None,
    detector: Optional[FailureDetector] = None,
    checkpoint_every: int = 10,
    sleep: Callable[[float], None] = time.sleep,
    on_event: Optional[Callable[[ResilienceEvent], None]] = None,
    straggler=None,
    step_times_fn: Optional[Callable[[int, float], Any]] = None,
    elastic=None,
    control=None,
) -> ResilientResult:
    """Train ``steps`` steps under faults; see the module docstring for
    the recovery semantics.

    ``train_step`` must be guard-built (it exposes
    ``default_comm_weights`` and returns the ``skipped`` vector).
    ``schedule`` is the list of topology specs backing the step's
    combine (one element for a static topology) — required for healing;
    without it a rollback restores state but the mixing weights stay as
    passed.  For a HIERARCHICAL step (``build_train_step(...,
    hierarchical=...)``) the schedule is MACHINE-level and the loop
    detects it via the step's ``hierarchical_local_size`` attribute:
    the detector keeps watching RANKS, and every heal delivery collapses
    the rank mask through ``healing.machine_dead_mask`` (a machine with
    any dead member is excised as a unit) before healing the machine
    schedule.  ``checkpointer`` needs ``save(step, state, force=)`` and
    ``restore_latest(mesh, like=)`` (the orbax ``Checkpointer``'s
    surface); checkpoint steps store ``{"params", "opt_state", "step"}``.
    ``sleep`` is injectable so tests (and the chaos bench) run backoff
    under a virtual clock.

    ``straggler`` (an ``observe.fleet.StragglerDetector``) turns the
    loop's per-step wall time into a fleet health signal: each step the
    detector observes the per-rank step-time vector, newly-flagged
    ranks are emitted as ``straggler`` events and registered with
    ``FailureDetector.suspect`` (so a slow rank is *named* before the
    blunt ``BLUEFOG_OP_TIMEOUT`` fires), and the suspicion set tracks
    the detector's flags (a recovered rank is un-suspected).
    ``step_times_fn(step, wall_s) -> [n]`` supplies the per-rank
    vector; the default broadcasts the measured local wall time to all
    ranks (what each process would gossip in a real fleet — the chaos
    bench injects per-rank stalls here instead).  Per-step wall time
    also lands in the ``bf_step_wall_seconds{loop="train"}`` histogram,
    the local metric ``observe.fleet.collect_local`` picks up for
    gossip.

    ``elastic`` (a :class:`bluefog_tpu.elastic.ElasticConfig`) turns on
    the full membership lifecycle: between steps the loop polls the
    admission signal (``elastic.admit``, defaulting to the fault plan's
    ``rejoinable_ranks``) and moves returning dead ranks to JOINING —
    quarantined bootstrap by pulled neighbor averaging
    (:mod:`bluefog_tpu.elastic.bootstrap`), all of it weight DATA
    through the one compiled step.  A joiner whose params' disagreement
    against the live mean drops under the quarantine threshold is
    PROMOTED (``rank_promoted``; the detector readmits it), one still
    over threshold after ``max_quarantine_steps`` is kicked back to
    DEAD (``rank_join_failed``), and a rollback kicks every in-flight
    joiner (the restored checkpoint predates its bootstrap).  Promotion
    forces a checkpoint on the next clean step so a promoted rank's
    certified state is normally durable; if a rollback nevertheless
    restores a step that predates a promotion (the promotion happened
    inside the bad window, where checkpoints are refused), the promoted
    rank is demoted back to DEAD (``rank_join_failed`` with
    ``reason="promotion_rolled_back"``) so its rewound, uncertified
    rows never mix into the fleet as live weight — the admission poll
    re-offers it for a fresh quarantined bootstrap.  Requires
    ``schedule=``; while elastic is on, the controller owns
    ``comm_weights``.

    ``control`` (a :class:`bluefog_tpu.topology.TopologyControlPlane`
    built over this step's schedule as its carrier) closes the topology
    loop: each step boundary the plane's ``on_step`` advances its
    detect -> re-plan -> hot-swap state machine, its events are
    re-emitted as ``topology_*`` resilience events, and after a swap or
    a probation rollback the loop re-delivers weights from the plane's
    ACTIVE schedule healed under the current dead mask (swap and heal
    compose through the one ``swap_comm_weights`` boundary).  While
    elastic is also on, a swap ``reschedule``-s the
    ``MembershipController`` onto the new specs and the controller
    keeps owning ``comm_weights``.  Requires ``schedule=``; flat steps
    only (a hierarchical schedule is machine-level while the plane's
    carrier projection is rank-level).
    """
    if not hasattr(train_step, "default_comm_weights"):
        raise ValueError(
            "run_resilient needs a GUARDED train step — build it with "
            "build_train_step(..., guard=GuardConfig(...))")
    if getattr(train_step, "has_aux", False):
        raise ValueError(
            "run_resilient drives the no-aux step signature "
            "(params, opt_state, batch, step, comm_weights); a "
            "has_aux=True guarded step takes an extra aux tree — drive "
            "it with your own loop, or fold the aux state into params")
    # policy default: the GuardConfig the step was BUILT with (attached
    # by build_train_step) — passing guard= here only to repeat it
    # would be a silent-drift trap
    if guard is None:
        guard = getattr(train_step, "guard_config", None) or GuardConfig()
    n = int(mesh.shape[axis_name])
    detector = detector or FailureDetector(n)
    if comm_weights is None:
        comm_weights = train_step.default_comm_weights
    # a hierarchical step's schedule specs are MACHINE-level; the
    # detector stays RANK-level, and every heal delivery collapses the
    # rank mask through the machine failure domain
    hier_l = getattr(train_step, "hierarchical_local_size", None)
    if control is not None:
        if not schedule:
            raise ValueError(
                "run_resilient(control=...) needs schedule= — the "
                "control plane is a weight re-plan over the step's "
                "carrier specs")
        if hier_l:
            raise ValueError(
                "run_resilient(control=...) does not drive a "
                "hierarchical step: the plane projects RANK-level "
                "candidates while a hierarchical schedule is "
                "MACHINE-level — synthesize hierarchically offline or "
                "train flat")
        if len(control.carrier) != len(schedule):
            raise ValueError(
                f"control plane carrier has {len(control.carrier)} "
                f"rounds but the step's schedule has {len(schedule)} — "
                "build the plane over the schedule the step compiled")

    def heal(dead_mask):
        # with a control plane, healing applies to the ACTIVE (possibly
        # swapped) schedule, not the build-time one — a heal right
        # after a hot swap must not silently revert the swap
        if control is not None:
            return control.healed_weights(dead_mask)
        if hier_l:
            return healed_hierarchical_comm_weights(
                schedule, dead_mask, hier_l)
        return healed_comm_weights(schedule, dead_mask)

    dead = detector.dead_mask()
    if schedule and (dead.any() or control is not None):
        # the control plane's initial active plan may differ from the
        # carrier's own weights (``initial=``) — deliver it up front
        comm_weights = heal(dead)

    controller = None
    admit_fn = None
    _bootstrap = None
    if elastic is not None:
        if not schedule:
            raise ValueError(
                "run_resilient(elastic=...) needs schedule= — membership "
                "is a weight re-plan over the topology specs")
        if hier_l:
            raise ValueError(
                "run_resilient(elastic=...) does not drive a hierarchical "
                "step: the MembershipController anneals RANK-level "
                "weights while a hierarchical schedule is MACHINE-level. "
                "Drive membership yourself over the machine schedule "
                "(elastic.grown_comm_weights / MembershipController on "
                "the machine specs feed the step's comm_weights as data "
                "— see tests/test_hierarchical.py) or train flat.")
        # imported here, not at module top: bluefog_tpu.elastic imports
        # resilience.healing, and this module loads as part of the
        # resilience package __init__
        from bluefog_tpu.elastic import (MembershipController,
                                         bootstrap as _bootstrap)

        controller = MembershipController(
            schedule,
            bootstrap_rounds=elastic.bootstrap_rounds,
            quarantine_threshold=elastic.quarantine_threshold,
            detector=detector)
        controller.seed_dead(dead)
        if elastic.max_quarantine_steps < controller.bootstrap_rounds:
            raise ValueError(
                f"max_quarantine_steps ({elastic.max_quarantine_steps}) "
                "must cover the bootstrap anneal "
                f"({controller.bootstrap_rounds} rounds)")
        admit_fn = elastic.admit
        if admit_fn is None and fault_plan is not None:
            admit_fn = fault_plan.rejoinable_ranks
        if control is not None:
            # the controller renders weights over the plane's ACTIVE
            # plan (swap-aware) while keeping membership authority
            controller.reschedule(control.active_schedule())
        comm_weights = controller.comm_weights()

    events: List[ResilienceEvent] = []

    # the subset of loop events that are control DECISIONS (state
    # transitions with a cause), mirrored into the blackbox flight
    # recorder; high-rate telemetry kinds (skip, straggler, checkpoint)
    # stay out of the ring
    _decision_kinds = frozenset(
        ("rollback", "rank_dead", "rank_join_failed",
         "bad_window_unattributed"))

    def emit(kind: str, step: int, **detail):
        ev = ResilienceEvent(kind, step, detail)
        events.append(ev)
        # aggregate the run's events where a dashboard can see them —
        # the event list was previously consumed (or not) by each caller
        if observe.enabled():
            observe.get_registry().counter(
                "bf_resilience_events_total",
                "resilience control-loop events", kind=kind).inc()
            observe.get_tracer().instant(f"resilience.{kind}",
                                         track="resilience")
        if kind in _decision_kinds:
            from bluefog_tpu.observe import blackbox as _blackbox

            _blackbox.record_decision("resilience", kind, step=step,
                                      detail=detail or None)
        if on_event is not None:
            on_event(ev)

    def save(step: int):
        checkpointer.save(
            step, {"params": params, "opt_state": opt_state,
                   "step": step}, force=True)
        emit("checkpoint", step)

    like = {"params": params, "opt_state": opt_state, "step": 0}
    prev_flagged: set = set()
    total_skips = np.zeros(n, np.int64)
    last_loss: Optional[np.ndarray] = None
    consecutive_bad = 0
    n_rollbacks = 0
    # a pending promotion forces a checkpoint on the next clean step,
    # so restore_latest can normally never predate a promotion
    force_ckpt = False
    step = 0
    save(0)  # rollback anchor: the pristine initial state

    def _repack(fixed, tree):
        # fixed rows go back to the device with their original sharding
        import jax

        if fixed is tree:
            return tree
        return jax.tree.map(
            lambda new, old: old if new is old else (
                jax.device_put(new, old.sharding)
                if hasattr(old, "sharding") else new),
            fixed, tree)

    def sanitized(tree, mask):
        # admission hygiene: a rank that died OUTSIDE the guard's
        # frozen-finite invariant may carry garbage
        return _repack(_bootstrap.sanitize_rank_rows(tree, mask), tree)

    def zeroed(tree, mask):
        return _repack(_bootstrap.zero_rank_rows(tree, mask), tree)

    # rank -> step it was promoted at: a rollback demotes any rank
    # whose promotion the restored checkpoint does not contain
    promoted_at: dict = {}

    while step < steps:
        if controller is not None:
            # stamp the loop step so membership decisions (admit /
            # promote / kick / mark_dead) land at the right step in
            # the flight recorder's causal chains
            controller.current_step = step
        if controller is not None and admit_fn is not None:
            wanting = [int(r) for r in admit_fn(step)
                       if controller.is_dead(int(r))]
            if wanting:
                controller.admit(wanting)
                # mask only the NEWLY admitted ranks: an in-flight
                # joiner's rows are already mid-rebuild and must not be
                # touched again
                wm = np.zeros(n, bool)
                wm[wanting] = True
                if elastic.sanitize:
                    params = sanitized(params, wm)
                    opt_state = sanitized(opt_state, wm)
                if elastic.reset_opt_state:
                    # stale-but-finite optimizer moments pass the
                    # params-only promotion gate untouched; zeroing
                    # them makes quarantine rebuild the moments from
                    # fresh gradients instead
                    opt_state = zeroed(opt_state, wm)
                for r in wanting:
                    emit("rank_joining", step, rank=r)
        if controller is not None and controller.joining_ranks():
            # the anneal advances every quarantined round — fresh
            # weight DATA for the same compiled program
            comm_weights = controller.comm_weights()
        batch = batch_fn(step)
        if fault_plan is not None:
            stall = fault_plan.stall_seconds(step)
            if stall > 0:
                sleep(stall)  # straggler injection: the stall watchdog /
                # BLUEFOG_OP_TIMEOUT layer owns this failure class
            batch = fault_plan.corrupt_batch(batch, step)
        t_step = time.monotonic()
        out = train_step(
            params, opt_state, batch, jnp.int32(step), comm_weights)
        # a health-built step appends the HealthVector; the loop keys
        # on the guard outputs either way
        params, opt_state, loss, skipped = out[:4]
        sk = np.asarray(skipped).reshape(-1) != 0
        detector.observe(sk)
        total_skips += sk
        if sk.any() and observe.enabled():
            reg = observe.get_registry()
            for r in np.nonzero(sk)[0]:
                reg.counter("bf_resilience_skips_total",
                            "guarded-step skips (replays included)",
                            rank=int(r)).inc()
        last_loss = np.asarray(loss)  # sync point: the step is done
        wall = time.monotonic() - t_step
        if observe.enabled():
            observe.get_registry().histogram(
                "bf_step_wall_seconds", "train/engine step wall time",
                loop="train").observe(wall)
        if straggler is not None:
            times = (np.asarray(step_times_fn(step, wall), np.float64)
                     if step_times_fn is not None
                     else np.full(n, wall))
            newly = straggler.observe(times)
            # suspicion tracks the detector's CURRENT flags — a
            # recovered rank is withdrawn, but only OUR flags are
            # touched: suspicion other sources registered (heartbeats,
            # the operator) is not ours to clear
            flagged_now = set(straggler.flagged())
            withdrawn = prev_flagged - flagged_now
            if withdrawn:
                detector.clear_suspicion(sorted(withdrawn),
                                         source="straggler")
            detector.suspect(sorted(flagged_now), source="straggler")
            prev_flagged = flagged_now
            if newly:
                z = straggler.z_scores()
                emit("straggler", step, ranks=[int(r) for r in newly],
                     z=[float(z[r]) for r in newly])
        if controller is not None:
            joiners = controller.joining_ranks()
            if joiners:
                controller.tick()
                check_every = max(1, elastic.check_every)
                for r in joiners:
                    prog = controller.progress(r)
                    at_check = (prog >= controller.bootstrap_rounds
                                and (prog - controller.bootstrap_rounds)
                                % check_every == 0)
                    d = None
                    if at_check:
                        d = _bootstrap.disagreement(
                            params, r, controller.live_mask())
                        if observe.enabled():
                            observe.get_registry().gauge(
                                "bf_elastic_disagreement",
                                "joiner bootstrap disagreement vs the "
                                "live mean", rank=r).set(float(d))
                        if d <= controller.quarantine_threshold:
                            controller.promote([r])
                            promoted_at[r] = step
                            force_ckpt = True
                            emit("rank_promoted", step, rank=r,
                                 disagreement=float(d), rounds=prog)
                            continue
                    # the deadline is enforced every tick, not only on
                    # check-cadence steps — with check_every > 1 a
                    # failed joiner must not linger past its quarantine
                    # budget waiting for the next measurement
                    if prog >= elastic.max_quarantine_steps:
                        detail = {"rank": r,
                                  "reason": "quarantine_expired"}
                        if d is not None:
                            detail["disagreement"] = float(d)
                        controller.kick([r])
                        emit("rank_join_failed", step, **detail)
                if controller.joining_ranks() != joiners:
                    comm_weights = controller.comm_weights()
        live_bad = detector.live_bad(sk)
        if live_bad:
            # only LIVE-rank skips are events: a declared-dead rank
            # skips every remaining step by design, and logging that
            # forever would grow the event list linearly in run length
            emit("skip", step, ranks=[int(r) for r in np.nonzero(sk)[0]])
        consecutive_bad = consecutive_bad + 1 if live_bad else 0
        step += 1

        if consecutive_bad >= guard.max_consecutive_bad:
            # Rollback is only useful when the badness is ATTRIBUTABLE:
            # a rank bad for the whole window is declared dead and
            # healed out, and restoring pre-poison state gives the
            # survivors a clean trajectory.  A window of overlapping
            # transients from DIFFERENT ranks has nothing to heal —
            # the skip guard already contained every one of them, and
            # a rollback would deterministically replay the identical
            # transients (batch_fn and the fault environment are
            # functions of the step index) in a futile loop.  Note the
            # window and keep training instead.
            # attribution is NUMERIC only (streak_suspects): an
            # externally-suspected straggler is slow, not poisonous —
            # killing it here would destroy healthy capacity and leave
            # the actual NaN source live
            newly = detector.streak_suspects(guard.max_consecutive_bad)
            if not newly:
                emit("bad_window_unattributed", step,
                     window=guard.max_consecutive_bad)
                consecutive_bad = 0
                continue
            if n_rollbacks >= guard.max_rollbacks:
                raise BluefogError(
                    f"run_resilient: giving up after {n_rollbacks} "
                    f"rollbacks (guard.max_rollbacks) with live ranks "
                    "still failing — the fault is not survivable by "
                    "skip/heal/rollback")
            detector.declare_dead(newly)
            dead = detector.dead_mask()
            for r in newly:
                emit("rank_dead", step, rank=r)
            if dead.all():
                raise BluefogError(
                    "run_resilient: every rank has been declared "
                    "dead — there is no surviving state to heal "
                    "around; the job must be restarted")
            state = checkpointer.restore_latest(mesh, like=like)
            params, opt_state = state["params"], state["opt_state"]
            restored_step = int(state["step"])
            if controller is not None:
                controller.current_step = step
                controller.mark_dead(newly)
                for r in newly:
                    promoted_at.pop(r, None)
                # in-flight joiners are invalidated too: the restored
                # checkpoint predates their bootstrap
                stranded = controller.joining_ranks()
                if stranded:
                    controller.kick(stranded)
                    for r in stranded:
                        emit("rank_join_failed", step, rank=r,
                             reason="rollback")
                # so is a rank PROMOTED after the restored checkpoint
                # (its promotion happened inside the bad window, where
                # checkpoints are refused): the restore rewinds its
                # rows to mid-bootstrap state the disagreement gate
                # never certified, so leaving it LIVE would mix
                # uncertified weight into the fleet.  Demote to DEAD —
                # the admission poll re-offers it for a fresh
                # quarantined bootstrap.  A checkpoint at step T holds
                # params after steps < T, so a promotion at step s is
                # contained only when s < restored_step.
                rewound = sorted(
                    r for r, s in promoted_at.items()
                    if s >= restored_step and controller.is_live(r))
                if rewound:
                    controller.mark_dead(rewound)
                    for r in rewound:
                        promoted_at.pop(r, None)
                        emit("rank_join_failed", step, rank=r,
                             reason="promotion_rolled_back")
                    dead = detector.dead_mask()
                    if dead.all():
                        raise BluefogError(
                            "run_resilient: every rank has been "
                            "declared dead — there is no surviving "
                            "state to heal around; the job must be "
                            "restarted")
                force_ckpt = False
                comm_weights = controller.comm_weights()
            elif schedule:
                comm_weights = heal(dead)
            backoff = min(
                guard.backoff_base * guard.backoff_factor ** n_rollbacks,
                guard.max_backoff)
            n_rollbacks += 1
            emit("rollback", step, restored_step=restored_step,
                 backoff=backoff, dead=[int(r) for r in np.nonzero(dead)[0]])
            step = restored_step
            consecutive_bad = 0
            detector.reset_streaks()
            if backoff > 0:
                sleep(backoff)
            continue

        if control is not None:
            # step boundary: the plane may hand back a swap (accepted
            # candidate), a probation verdict, or telemetry-window
            # transitions — re-deliver weights whenever the active
            # schedule changed hands
            acts = control.on_step(step, dead_mask=detector.dead_mask(),
                                   params=params)
            for kind, detail in acts:
                emit(kind, step, **detail)
            if any(k in ("topology_swap", "topology_rollback")
                   for k, _ in acts):
                if controller is not None:
                    controller.reschedule(control.active_schedule())
                    comm_weights = controller.comm_weights()
                else:
                    comm_weights = heal(detector.dead_mask())

        if (force_ckpt or (checkpoint_every > 0
                           and step % checkpoint_every == 0)) \
                and not live_bad:
            save(step)
            force_ckpt = False

    return ResilientResult(
        params=params, opt_state=opt_state, step=step, last_loss=last_loss,
        total_skips=total_skips, n_rollbacks=n_rollbacks,
        dead_mask=detector.dead_mask(), events=events,
        membership=controller.states() if controller is not None else None)
