"""bluefog_tpu — a TPU-native decentralized deep-learning training framework.

A from-scratch JAX/XLA re-design of the capabilities of BlueFog
(reference: /root/reference, a Horovod-style C++ MPI/NCCL core with torch
bindings).  Instead of a background negotiation thread + MPI graph
communicators, this build lowers every decentralized primitive to XLA
collectives (``lax.ppermute`` / ``psum`` / ``all_gather``) over a
``jax.sharding.Mesh``, so neighbor averaging rides the ICI/DCN fabric with
no host round-trips.

Public surface mirrors ``bluefog.torch`` (reference
bluefog/torch/__init__.py:34-110); see ``bluefog_tpu.api`` for the
flat op API and ``bluefog_tpu.topology`` for graph generators.
"""

from bluefog_tpu import _compat  # noqa: F401  (installs jax API shims)
from bluefog_tpu.version import __version__

# Flat API re-exports (reference: bluefog/torch/__init__.py:34-110).
from bluefog_tpu.api import (  # noqa: F401
    init,
    shutdown,
    is_initialized,
    size,
    local_size,
    rank,
    local_rank,
    machine_size,
    machine_rank,
    load_topology,
    set_topology,
    is_topo_weighted,
    load_machine_topology,
    set_machine_topology,
    is_machine_topo_weighted,
    in_neighbor_ranks,
    out_neighbor_ranks,
    in_neighbor_machine_ranks,
    out_neighbor_machine_ranks,
    is_homogeneous,
    suspend,
    resume,
    set_skip_negotiate_stage,
    get_skip_negotiate_stage,
    mpi_threads_supported,
    unified_mpi_window_model_supported,
    nccl_built,
    # collectives
    allreduce,
    allreduce_nonblocking,
    allreduce_,
    allreduce_nonblocking_,
    allgather,
    allgather_nonblocking,
    broadcast,
    broadcast_nonblocking,
    broadcast_,
    broadcast_nonblocking_,
    neighbor_allgather,
    neighbor_allgather_nonblocking,
    neighbor_allreduce,
    neighbor_allreduce_nonblocking,
    hierarchical_neighbor_allreduce,
    hierarchical_neighbor_allreduce_nonblocking,
    pair_gossip,
    pair_gossip_nonblocking,
    barrier,
    poll,
    synchronize,
    wait,
    # windows
    win_create,
    win_free,
    win_update,
    win_update_then_collect,
    win_put,
    win_put_nonblocking,
    win_get,
    win_get_nonblocking,
    win_accumulate,
    win_accumulate_nonblocking,
    win_set_value,
    win_wait,
    win_poll,
    win_mutex,
    win_lock,
    win_unlock,
    win_fence,
    get_win_version,
    get_current_created_window_names,
    win_associated_p,
    turn_on_win_ops_with_associated_p,
    turn_off_win_ops_with_associated_p,
    # timeline
    timeline_start_activity,
    timeline_end_activity,
    timeline_context,
    # data helpers
    rank_sharded,
    from_rank_values,
    to_rank_values,
)

from bluefog_tpu.utility import (  # noqa: F401
    broadcast_parameters,
    allreduce_parameters,
    broadcast_optimizer_state,
)

from bluefog_tpu import topology  # noqa: F401
from bluefog_tpu.topology import (  # noqa: F401
    # reference exposes these on the main module (torch/__init__.py:109)
    InferDestinationFromSourceRanks,
    InferSourceFromDestinationRanks,
    # the documented default one-peer schedule for pod torus shapes,
    # picked by machine-counted congestion + mixing score (torus.py)
    default_pod_schedule,
)
from bluefog_tpu import observe  # noqa: F401
from bluefog_tpu import optim  # noqa: F401
from bluefog_tpu import resilience  # noqa: F401
from bluefog_tpu import data  # noqa: F401
from bluefog_tpu.data import (  # noqa: F401
    DataLoader,
    DistributedSampler,
    device_prefetch,
    load_mnist,
    load_cifar10,
)
