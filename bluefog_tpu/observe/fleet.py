"""Fleet telemetry: decentralized cross-rank metric aggregation.

PR 4's observe layer is strictly process-local; the quantities that
decide whether *decentralized* training is healthy — consensus distance
between neighbor replicas, per-edge exchange volume, rank-to-rank
step-time skew — only exist as fleet-level facts.  The paper's premise
is that state is averaged over a digraph rather than centralized, so
telemetry travels the same way: this module aggregates metrics over the
EXISTING neighbor topology via push-sum gossip (the same
column-stochastic structure as ``parallel.collectives.push_sum_mix`` /
``push_sum_structure``) instead of assuming a metrics server every rank
can reach.  Three pieces:

* :class:`FleetAggregator` — exact weighted means of per-rank scalars
  by iterated push-sum over a topology schedule, no central collector:
  the pair ``(x, w)`` mixes through the column-stochastic matrices, the
  sums ``Σx`` and ``Σw`` are INVARIANTS, so when every rank's de-biased
  estimate ``z_i = x_i / w_i`` agrees it equals the true mean *exactly*
  (the finite-round residual is the measured ``spread``).  Dead ranks
  are excised exactly like ``resilience.healing`` excises them from the
  mixing weights — zeroed edges drop out of the push-sum structure —
  and a hierarchical intra-host/inter-host mode (HiCCL-style,
  arXiv:2408.05962) reduces each machine exactly first and gossips
  machine sums inter-host.
* per-edge traffic accounting — ``bf_edge_bytes_total{src,dst}``
  counter families derived from the topology's shift classes
  (:func:`edge_list`); the train-step wrappers and the gossip itself
  publish through :func:`record_edge_traffic`.
* :class:`StragglerDetector` — flags ranks whose gossiped step-time
  z-score (robust: median/MAD across ranks) stays above a threshold
  for ``patience`` consecutive observations; feeds
  ``resilience.FailureDetector.suspect`` via ``run_resilient`` so a
  slow rank is *named* instead of only tripping the blunt
  ``BLUEFOG_OP_TIMEOUT``.

Aggregated values land back in the local
:class:`~bluefog_tpu.observe.registry.MetricsRegistry` under
``bf_fleet_*`` gauges, so every exporter in ``observe.export``
(Prometheus text, JSONL, snapshot) serves fleet metrics unchanged.
"""

from __future__ import annotations

import dataclasses
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from bluefog_tpu.observe import registry as _registry_mod
from bluefog_tpu.parallel.collectives import (
    push_sum_structure, validate_machine_decomposition)
from bluefog_tpu.topology.spec import DynamicTopology, Topology

CommSpec = Union[Topology, DynamicTopology]

__all__ = [
    "edge_list",
    "gossip_edge_list",
    "record_edge_traffic",
    "record_edge_timing",
    "traffic_snapshot",
    "TrafficDeltas",
    "push_sum_matrix",
    "FleetAggregate",
    "FleetAggregator",
    "StragglerDetector",
    "collect_local",
]

_EDGE_BYTES_HELP = "per-edge neighbor-exchange payload (logical bytes)"
_EDGE_SECONDS_HELP = "per-edge exchange wall time (measured seconds)"

# the aggregator's per-dead-mask matrix cache is LRU-bounded: elastic
# membership churns the mask in BOTH directions (die -> heal -> rejoin
# -> grow), and an unbounded dict would retain every pattern ever seen
_MATS_CACHE_MAX = 32


def _resolve_dead_mask(dead_mask, size: int) -> np.ndarray:
    """Normalize a dead-mask argument: ``None`` (nobody dead), a bool
    array, or any object with an ``effective_dead_mask()`` method — the
    duck-typed hook a ``bluefog_tpu.elastic.MembershipController``
    satisfies, so the gossip layer heals and RE-GROWS in lockstep with
    the data plane's membership (a JOINING rank is still excised: it is
    not yet read from)."""
    if dead_mask is None:
        return np.zeros(size, bool)
    eff = getattr(dead_mask, "effective_dead_mask", None)
    if callable(eff):
        dead_mask = eff()
    return np.asarray(dead_mask, bool).reshape(-1)


def edge_list(spec: CommSpec) -> List[tuple]:
    """The spec's declared edges ``(src, dst)``, sorted — derived from
    the shift-class decomposition (the compile-time skeleton), so the
    traffic account indexes exactly the ppermutes the data plane
    issues.  ``neighbor_allreduce`` ppermutes every DECLARED edge
    (weights are traced operands, a 0.0 weight still moves bytes);
    for the push-sum wire behavior use :func:`gossip_edge_list`."""
    return sorted(p for cls in spec.shift_classes for p in cls.perm)


def gossip_edge_list(spec: CommSpec) -> List[tuple]:
    """The spec's edges that actually carry push-sum payload — the
    weight-FILTERED structure (``push_sum_structure``): a declared
    0.0-weight edge pushes nothing, matching ``push_sum_mix``'s wire
    behavior (it only ppermutes the filtered perms), so a healed spec's
    zeroed edges are billed nothing."""
    _, perms = push_sum_structure(spec)
    return sorted(p for perm in perms for p in perm)


def record_edge_traffic(spec: CommSpec, payload_bytes: float,
                        registry=None, pairs=None,
                        link: Optional[str] = None) -> None:
    """Add ``payload_bytes`` to ``bf_edge_bytes_total{src,dst}`` for
    every declared edge of ``spec`` (one exchange round) — or for the
    explicit ``pairs`` (e.g. :func:`gossip_edge_list` for push-sum
    wires).  Logical payload bytes — wire compression is not folded
    in.

    ``link`` ("ici"/"dcn") tags the counters with the fabric LEG the
    bytes crossed — the hierarchical exchange bills its two legs
    separately so :func:`traffic_snapshot` can hand the compiler's
    ``PodSpec.from_telemetry`` only the expensive inter-machine load.
    Unlabeled counters (flat exchanges, old recorders) stay the
    back-compat family."""
    reg = registry if registry is not None else (
        _registry_mod.get_registry() if _registry_mod.enabled() else None)
    if reg is None:
        return
    extra = {} if link is None else {"link": link}
    for (src, dst) in (edge_list(spec) if pairs is None else pairs):
        reg.counter("bf_edge_bytes_total", _EDGE_BYTES_HELP,
                    src=src, dst=dst, **extra).inc(payload_bytes)


def record_edge_timing(spec: CommSpec, seconds: float,
                       registry=None, pairs=None,
                       link: Optional[str] = None) -> None:
    """Add ``seconds`` of measured exchange wall time to
    ``bf_edge_seconds_total{src,dst}`` for every declared edge of
    ``spec`` (or the explicit ``pairs``) — the TIMING twin of
    :func:`record_edge_traffic`.  A congested link carries the same
    bytes but more seconds, so the control plane prices links by the
    seconds counters where they exist: byte volume alone cannot see a
    link that got slow."""
    reg = registry if registry is not None else (
        _registry_mod.get_registry() if _registry_mod.enabled() else None)
    if reg is None:
        return
    extra = {} if link is None else {"link": link}
    for (src, dst) in (edge_list(spec) if pairs is None else pairs):
        reg.counter("bf_edge_seconds_total", _EDGE_SECONDS_HELP,
                    src=src, dst=dst, **extra).inc(seconds)


def traffic_snapshot(registry=None,
                     link: Optional[str] = None,
                     since: Optional[Dict[tuple, float]] = None,
                     metric: str = "bf_edge_bytes_total"
                     ) -> Dict[tuple, float]:
    """The accumulated per-edge exchange traffic, read back OUT of the
    registry: ``{(src, dst): bytes}`` from every
    ``bf_edge_bytes_total{src,dst}`` counter — the feed the topology
    compiler's :meth:`~bluefog_tpu.topology.compiler.PodSpec.calibrated`
    cost model consumes, so synthesized schedules adapt to the link
    traffic the fleet actually measured (train-step exchanges + gossip
    wire cost, everything :func:`record_edge_traffic` billed).  Empty
    when observability is off or nothing was recorded.

    ``link=None`` sums every family (labeled or not — the whole-fleet
    view); ``link="dcn"``/``link="ici"`` selects ONLY the counters
    tagged with that leg by a hierarchical recorder, which is what
    hierarchical ``PodSpec.from_telemetry`` calibration reads so cheap
    intra-machine traffic never masquerades as DCN load.

    ``since`` turns the lifetime totals into a WINDOWED delta: pass a
    previous snapshot (same ``registry``/``link``/``metric``) and only
    the traffic accumulated after it comes back, edges that moved
    nothing omitted.  Lifetime counters are monotonic, so a long-lived
    fleet's history drowns any new hotspot — calibrating from totals
    prices links by what they carried LAST WEEK; the delta prices what
    they carry NOW (the stale-calibration fix, unit-tested in
    tests/test_fleet.py).  :class:`TrafficDeltas` packages the marker
    bookkeeping.  ``metric`` selects the counter family —
    ``bf_edge_seconds_total`` reads the timing leg
    (:func:`record_edge_timing`) through the same machinery."""
    reg = registry if registry is not None else (
        _registry_mod.get_registry() if _registry_mod.enabled() else None)
    if reg is None:
        return {}
    out: Dict[tuple, float] = {}
    for name, kind, _help, labels, m in reg.collect():
        if name != metric or kind != "counter":
            continue
        if link is not None and labels.get("link") != link:
            continue
        try:
            key = (int(labels["src"]), int(labels["dst"]))
        except (KeyError, ValueError):
            continue
        out[key] = out.get(key, 0.0) + float(m.value)
    if since is not None:
        out = {k: v - since.get(k, 0.0) for k, v in out.items()
               if v - since.get(k, 0.0) > 0.0}
    return out


class TrafficDeltas:
    """Windowed per-edge traffic reader: every :meth:`take` returns
    what moved SINCE the previous take and advances the marker — the
    handle the topology control plane holds so each telemetry window
    prices recent load, never lifetime monotonic totals.

    Construction snapshots the current counters, so the first
    :meth:`take` already excludes everything that happened before the
    watcher existed (the stale-calibration case)."""

    def __init__(self, registry=None, link: Optional[str] = None,
                 metric: str = "bf_edge_bytes_total"):
        self._registry = registry
        self._link = link
        self._metric = metric
        self._mark = traffic_snapshot(registry, link=link, metric=metric)

    def take(self) -> Dict[tuple, float]:
        """Per-edge traffic since the previous take (or construction):
        ``{(src, dst): amount}``, quiet edges omitted."""
        cur = traffic_snapshot(self._registry, link=self._link,
                               metric=self._metric)
        out = {k: v - self._mark.get(k, 0.0) for k, v in cur.items()
               if v - self._mark.get(k, 0.0) > 0.0}
        self._mark = cur
        return out

    def peek(self) -> Dict[tuple, float]:
        """The delta :meth:`take` would return, without advancing."""
        return traffic_snapshot(self._registry, link=self._link,
                                since=self._mark, metric=self._metric)


def push_sum_matrix(spec: CommSpec, dead_mask=None) -> np.ndarray:
    """The column-stochastic push-sum matrix of ``spec``'s edge
    structure, receiver-major (``A[dst, src]``): every rank scales by
    ``1/(out_degree+1)`` and pushes along its nonzero-weight out-edges
    — numerically THE matrix one round of
    ``collectives.push_sum_mix`` applies (parity-tested in
    tests/test_fleet.py).

    ``dead_mask`` excises ranks the same way a
    ``resilience.healing.heal_spec`` re-plan does: their edges drop
    from the structure (a healed spec's zeroed weights produce the
    identical matrix) and the dead rank keeps its own (zero) mass via
    ``A[d, d] = 1`` — columns stay stochastic, so the LIVE sums remain
    invariant."""
    n = spec.size
    dead = (np.zeros(n, bool) if dead_mask is None
            else np.asarray(dead_mask, bool).reshape(-1))
    if dead.shape[0] != n:
        raise ValueError(f"dead mask of length {dead.shape[0]} does not "
                         f"match topology size {n}")
    _, perms = push_sum_structure(spec)
    pairs = [(s, d) for perm in perms for (s, d) in perm
             if not (dead[s] or dead[d])]
    deg = np.zeros(n, np.int64)
    for (s, _) in pairs:
        deg[s] += 1
    a = 1.0 / (deg + 1.0)
    A = np.zeros((n, n), np.float64)
    A[np.arange(n), np.arange(n)] = a
    for (s, d) in pairs:
        A[d, s] += a[s]
    A[dead, dead] = 1.0
    return A


@dataclasses.dataclass(frozen=True)
class FleetAggregate:
    """One gossip result: ``per_rank[i, j]`` is rank *i*'s converged
    estimate of metric *j*'s fleet mean (dead rows are NaN), ``mean``
    the live ranks' average view, ``rounds`` the gossip rounds run, and
    ``spread`` the final relative disagreement across live ranks — the
    honest residual of a finite-round decentralized protocol."""

    names: tuple
    per_rank: np.ndarray
    mean: np.ndarray
    rounds: int
    spread: float

    def as_dict(self) -> Dict[str, float]:
        return {n: float(v) for n, v in zip(self.names, self.mean)}


class FleetAggregator:
    """Decentralized aggregation of per-rank scalars by push-sum gossip
    over a topology schedule.

    ``schedule`` is the same object a train step communicates over (one
    spec, or the dynamic round list); gossip round *t* uses
    ``schedule[t % len(schedule)]``'s edge structure with the uniform
    column-stochastic push scales — metrics travel the edges the data
    plane already exercises.  ``aggregate`` iterates until the live
    ranks' de-biased estimates agree to ``tol`` (relative), which by
    the sum invariant means every estimate equals the centralized mean
    to that tolerance (the ≤1e-12 acceptance bar of ISSUE 5 runs at
    n=32 in tests/test_fleet.py, dead-rank excision included).

    ``rank`` names the local rank whose converged view ``publish``
    lands in the registry (``bf_fleet_<metric>`` gauges) — in a real
    fleet every process runs its own aggregator and publishes its own
    view; the single-process test world simulates all of them at once.
    """

    def __init__(self, schedule, *, tol: float = 1e-13,
                 max_rounds: int = 10_000, rank: int = 0,
                 registry=None, record_traffic: bool = True):
        if isinstance(schedule, (Topology, DynamicTopology)):
            schedule = [schedule]
        if not schedule:
            raise ValueError("FleetAggregator needs a non-empty schedule")
        sizes = {s.size for s in schedule}
        if len(sizes) != 1:
            raise ValueError(f"schedule mixes topology sizes {sizes}")
        self.schedule = list(schedule)
        self.size = sizes.pop()
        self.tol = float(tol)
        self.max_rounds = int(max_rounds)
        self.rank = int(rank)
        self._registry = registry
        self.record_traffic = record_traffic
        # matrices cache: keyed by dead-mask bytes (flat gossip) or
        # (machine-schedule digests, machine-dead bytes) (hierarchical);
        # LRU-bounded — elastic membership churns the mask both ways
        self._mats: "OrderedDict[object, list]" = OrderedDict()

    # ------------------------------------------------------------- #
    # gossip core
    # ------------------------------------------------------------- #
    def _cache_put(self, key, mats: list) -> None:
        self._mats[key] = mats
        self._mats.move_to_end(key)
        while len(self._mats) > _MATS_CACHE_MAX:
            self._mats.popitem(last=False)

    def _cache_get(self, key):
        mats = self._mats.get(key)
        if mats is not None:
            self._mats.move_to_end(key)
        return mats

    def _matrices(self, dead: np.ndarray) -> list:
        key = dead.tobytes()
        mats = self._cache_get(key)
        if mats is None:
            mats = [push_sum_matrix(s, dead) for s in self.schedule]
            self._cache_put(key, mats)
        return mats

    @staticmethod
    def _fold_isolated(mats: list, dead: np.ndarray, rebuild) -> tuple:
        """Fold ISOLATED live ranks — no gossip edge in any round's
        matrix — into the effective dead mask (``rebuild(eff_dead)``
        supplies the re-excised matrices).  This is exactly what a
        ``healing.heal_spec`` re-plan produces when the caller passes
        the healed schedule WITHOUT a dead mask: the excised rank's
        edges are zero-weight, so it can neither reach nor be reached
        by the rest and would block convergence forever while
        polluting the mean with its stale value.  A single live rank
        (nothing to gossip with) is left alone — it trivially
        converges to its own value."""
        iso = ~dead
        for A in mats:
            off = A - np.diag(np.diag(A))
            touched = (off.sum(axis=0) > 0) | (off.sum(axis=1) > 0)
            iso &= ~touched
        if not iso.any():
            return dead, mats
        live = ~dead
        if not (live & ~iso).any():
            if live.sum() == 1:
                return dead, mats
            raise ValueError(
                "gossip schedule has no edges among live ranks")
        eff = dead | iso
        return eff, rebuild(eff)

    def _gossip(self, mats: list, x: np.ndarray, w: np.ndarray,
                live: np.ndarray) -> tuple:
        """Iterate push-sum rounds until the live ranks' de-biased
        estimates agree to ``tol`` (relative) — the shared core of the
        flat and hierarchical paths."""
        rounds = 0
        spread = np.inf
        while rounds < self.max_rounds:
            A = mats[rounds % len(mats)]
            x = A @ x
            w = A @ w
            rounds += 1
            z = x[live] / w[live, None]
            scale = max(np.abs(z).max(initial=0.0), 1.0)
            spread = float((z.max(axis=0) - z.min(axis=0)).max(initial=0.0)
                           / scale)
            if spread <= self.tol:
                break
        return x, w, rounds, spread

    def aggregate(self, values, dead_mask=None,
                  names: Optional[Sequence[str]] = None) -> FleetAggregate:
        """Gossip ``values`` (``[n, k]`` rank-major, or ``[n]`` for one
        metric) to every live rank's estimate of the live mean.

        Dead ranks (``dead_mask`` — a bool mask, or a
        ``bluefog_tpu.elastic.MembershipController`` whose
        ``effective_dead_mask()`` is read live, so gossip shrinks AND
        grows with the data plane's membership) contribute nothing and
        receive nothing — their rows come back NaN; this matches a
        ``healing.heal_spec``-re-planned schedule exactly (the test
        asserts matrix equality).  A healed schedule passed WITHOUT a
        dead mask works too: ranks the re-plan fully excised (no edges
        left in any round) are detected and folded into the effective
        dead mask, so a fleet that healed its mixing weights gets
        consistent gossip for free either way."""
        x = np.asarray(values, np.float64)
        if x.ndim == 1:
            x = x[:, None]
        if x.shape[0] != self.size:
            raise ValueError(f"values for {x.shape[0]} ranks against a "
                             f"size-{self.size} schedule")
        k = x.shape[1]
        names = tuple(names) if names is not None else tuple(
            f"m{j}" for j in range(k))
        dead = _resolve_dead_mask(dead_mask, self.size)
        if not (~dead).any():
            raise ValueError("no live ranks to aggregate over")
        dead, mats = self._fold_isolated(self._matrices(dead), dead,
                                         self._matrices)
        live = ~dead
        x = np.where(live[:, None], x, 0.0)
        w = live.astype(np.float64)
        x, w, rounds, spread = self._gossip(mats, x, w, live)
        per_rank = np.full((self.size, k), np.nan)
        per_rank[live] = x[live] / w[live, None]
        agg = FleetAggregate(names=names, per_rank=per_rank,
                             mean=per_rank[live].mean(axis=0),
                             rounds=rounds, spread=spread)
        self._record_gossip_traffic(self.schedule, rounds, k, dead)
        return agg

    def aggregate_hierarchical(self, values, local_size: int,
                               machine_schedule,
                               dead_mask=None,
                               names: Optional[Sequence[str]] = None
                               ) -> FleetAggregate:
        """Two-level aggregation in the spirit of HiCCL
        (arXiv:2408.05962): (1) each machine of ``local_size`` ranks
        reduces its LIVE members' sum + count exactly (the intra-host
        interconnect is assumed reliable and cheap), (2) the machine
        sums gossip by push-sum over ``machine_schedule`` with the
        weight initialized to the machine's live-rank COUNT — the
        de-biased fixed point is then the rank-weighted global mean,
        exactly, uneven machines included, (3) every rank reads its
        machine's converged view (the intra-host broadcast)."""
        x = np.asarray(values, np.float64)
        if x.ndim == 1:
            x = x[:, None]
        n, k = x.shape
        names = tuple(names) if names is not None else tuple(
            f"m{j}" for j in range(k))
        dead = _resolve_dead_mask(dead_mask, n)
        live = ~dead
        if isinstance(machine_schedule, (Topology, DynamicTopology)):
            machine_schedule = [machine_schedule]
        # the one shared machine-decomposition validator (also the
        # training exchange's — collectives.py is the source of truth)
        groups = validate_machine_decomposition(n, local_size,
                                                machine_schedule)
        m = len(groups)
        sums = np.zeros((m, k))
        counts = np.zeros(m)
        for mi, g in enumerate(groups):
            members = np.asarray(g)[live[np.asarray(g)]]
            counts[mi] = len(members)
            if len(members):
                sums[mi] = x[members].sum(axis=0)
        mdead = counts == 0
        if mdead.all():
            raise ValueError("no live ranks to aggregate over")

        # cached like aggregate()'s matrices: a steady-state telemetry
        # loop calls this every publish interval
        def machine_mats(md: np.ndarray) -> list:
            mkey = (tuple(s.digest() for s in machine_schedule),
                    md.tobytes())
            mats = self._cache_get(mkey)
            if mats is None:
                mats = [push_sum_matrix(s, md) for s in machine_schedule]
                self._cache_put(mkey, mats)
            return mats

        mdead, mats = self._fold_isolated(machine_mats(mdead), mdead,
                                          machine_mats)
        mlive = ~mdead
        xs = np.where(mlive[:, None], sums, 0.0)
        ws = np.where(mlive, counts, 0.0)
        xs, ws, rounds, spread = self._gossip(mats, xs, ws, mlive)
        per_rank = np.full((n, k), np.nan)
        filled = np.zeros(n, bool)
        for mi, g in enumerate(groups):
            if mlive[mi]:
                view = xs[mi] / ws[mi]
                for r in g:
                    if live[r]:
                        per_rank[r] = view
                        filled[r] = True
        # inter-host gossip wire cost, attributed to the machines'
        # LEADER ranks (machine m's counterpart link is rank
        # m*local_size -> m'*local_size) so the same bf_edge_bytes_total
        # family covers flat and hierarchical gossip
        self._record_gossip_traffic(
            machine_schedule, rounds, k, mdead,
            relabel=lambda s, d: (s * local_size, d * local_size),
            link="dcn")
        return FleetAggregate(names=names, per_rank=per_rank,
                              mean=per_rank[filled].mean(axis=0),
                              rounds=rounds, spread=spread)

    # ------------------------------------------------------------- #
    # registry integration
    # ------------------------------------------------------------- #
    def _reg(self):
        if self._registry is not None:
            return self._registry
        return (_registry_mod.get_registry()
                if _registry_mod.enabled() else None)

    def _record_gossip_traffic(self, schedule, rounds: int, k: int,
                               dead: np.ndarray, relabel=None,
                               link: Optional[str] = None) -> None:
        """The gossip's OWN wire cost, per edge: each round pushes the
        ``k`` metric scalars + the push-sum weight as f64.  Only edges
        that actually push are billed (:func:`gossip_edge_list` —
        zero-weight declared edges carry nothing); ``relabel`` maps
        schedule-level edges to rank-level labels (the hierarchical
        path's machine→leader-rank attribution), and ``link`` tags the
        leg like :func:`record_edge_traffic` (the hierarchical
        inter-machine gossip is DCN traffic)."""
        reg = self._reg()
        if reg is None or not self.record_traffic or rounds == 0:
            return
        payload = (k + 1) * 8
        totals: Dict[tuple, float] = {}
        n_specs = len(schedule)
        for si, spec in enumerate(schedule):
            # rounds r with r % n_specs == si
            uses = rounds // n_specs + (1 if rounds % n_specs > si else 0)
            if uses == 0:
                continue
            for (s, d) in gossip_edge_list(spec):
                if dead[s] or dead[d]:
                    continue
                key = (s, d) if relabel is None else relabel(s, d)
                totals[key] = totals.get(key, 0.0) + payload * uses
        extra = {} if link is None else {"link": link}
        for (s, d), b in totals.items():
            reg.counter("bf_edge_bytes_total", _EDGE_BYTES_HELP,
                        src=s, dst=d, **extra).inc(b)

    def publish(self, names: Sequence[str], values, dead_mask=None
                ) -> FleetAggregate:
        """Aggregate and land the LOCAL rank's converged view in the
        registry as ``bf_fleet_<name>`` gauges (plus
        ``bf_fleet_gossip_rounds`` / ``bf_fleet_gossip_spread``), so
        ``export.prometheus_text()`` / ``snapshot()`` serve fleet
        metrics with no exporter changes."""
        agg = self.aggregate(values, dead_mask=dead_mask, names=names)
        reg = self._reg()
        if reg is not None:
            view = agg.per_rank[self.rank]
            for name, v in zip(agg.names, view):
                if np.isfinite(v):
                    reg.gauge(f"bf_fleet_{name}",
                              "push-sum-gossiped fleet mean (local "
                              "rank's converged view)").set(float(v))
            reg.gauge("bf_fleet_gossip_rounds",
                      "gossip rounds to convergence").set(agg.rounds)
            reg.gauge("bf_fleet_gossip_spread",
                      "relative disagreement at stop").set(agg.spread)
        return agg


def collect_local(registry=None) -> Dict[str, float]:
    """The local registry scalars worth gossiping — step wall-time
    (p50 of ``bf_step_wall_seconds`` across loops), total guarded-step
    skips, and the serving queue depth.  Returns ``{}``-able floats (0
    where a subsystem never published), in a stable key order."""
    reg = registry if registry is not None else _registry_mod.get_registry()
    step_p50 = 0.0
    skips = 0.0
    queue = 0.0
    for name, kind, _help, _labels, m in reg.collect():
        if name == "bf_step_wall_seconds" and kind == "histogram":
            step_p50 = max(step_p50, m.percentile(50))
        elif name == "bf_resilience_skips_total" and kind == "counter":
            skips += m.value
        elif name == "bf_serving_queue_depth" and kind == "gauge":
            queue = m.value
    return {"step_time_p50": float(step_p50), "skips_total": float(skips),
            "queue_depth": float(queue)}


class StragglerDetector:
    """Names the slow rank from gossiped per-rank step times.

    Per observation (one fleet-aggregated step-time vector), computes a
    ROBUST z-score across ranks — ``(t - median) / sigma`` with
    ``sigma = max(1.4826·MAD, min_rel_spread·median)`` so one extreme
    straggler cannot hide itself by inflating a plain standard
    deviation, and microscopic jitter on an idle fleet never flags.  A
    rank above ``z_threshold`` for ``patience`` CONSECUTIVE
    observations is flagged (detection latency is therefore bounded by
    ``patience`` observations after onset — the machine-checked claim
    in benchmarks/chaos_resilience.py); dipping below the threshold
    clears the streak and the flag (a recovered rank is not a
    straggler).

    ``observe`` returns the NEWLY flagged ranks, which
    ``run_resilient`` feeds to ``FailureDetector.suspect`` and emits as
    ``straggler`` events; gauges ``bf_fleet_step_time_z{rank=}`` and
    ``bf_fleet_straggler{rank=}`` land in the registry each
    observation."""

    def __init__(self, size: int, z_threshold: Optional[float] = None,
                 patience: int = 3, min_rel_spread: float = 0.05,
                 registry=None):
        if size < 1:
            raise ValueError(f"size must be >= 1, got {size}")
        if patience < 1:
            raise ValueError(f"patience must be >= 1, got {patience}")
        if z_threshold is None:
            from bluefog_tpu import config as bfconfig

            z_threshold = bfconfig.straggler_z_threshold()
        self.size = size
        self.z_threshold = float(z_threshold)
        self.patience = int(patience)
        self.min_rel_spread = float(min_rel_spread)
        self._registry = registry
        self._above = np.zeros(size, np.int64)
        self._flagged = np.zeros(size, bool)
        self._z = np.zeros(size)
        self.n_observations = 0
        self._gauge_cache = None

    def _reg(self):
        if self._registry is not None:
            return self._registry
        return (_registry_mod.get_registry()
                if _registry_mod.enabled() else None)

    def _gauges(self, reg) -> list:
        """Per-rank ``(z_gauge, straggler_gauge)`` handles, cached per
        registry: ``observe`` runs once per training step in
        ``run_resilient``'s host loop, and 2·size labeled-family dict
        lookups per step is avoidable overhead — the handles are
        stable, only the values change."""
        cache = self._gauge_cache
        if cache is None or cache[0] is not reg:
            pairs = [
                (reg.gauge("bf_fleet_step_time_z",
                           "robust step-time z-score (gossiped)", rank=r),
                 reg.gauge("bf_fleet_straggler",
                           "1 while the rank is flagged as a straggler",
                           rank=r))
                for r in range(self.size)]
            cache = self._gauge_cache = (reg, pairs)
        return cache[1]

    def observe(self, step_times) -> List[int]:
        """Fold one per-rank step-time vector in; returns the ranks
        that JUST crossed into flagged state."""
        t = np.asarray(step_times, np.float64).reshape(-1)
        if t.shape[0] != self.size:
            raise ValueError(f"step-time vector of length {t.shape[0]} "
                             f"does not match world size {self.size}")
        med = float(np.median(t))
        mad = float(np.median(np.abs(t - med)))
        sigma = max(1.4826 * mad, self.min_rel_spread * max(med, 0.0),
                    1e-12)
        self._z = (t - med) / sigma
        above = self._z > self.z_threshold
        self._above = np.where(above, self._above + 1, 0)
        was = self._flagged
        self._flagged = self._above >= self.patience
        newly = self._flagged & ~was
        self.n_observations += 1
        reg = self._reg()
        if reg is not None:
            for r, (zg, fg) in enumerate(self._gauges(reg)):
                zg.set(float(self._z[r]))
                fg.set(1.0 if self._flagged[r] else 0.0)
        return [int(r) for r in np.nonzero(newly)[0]]

    def z_scores(self) -> Dict[int, float]:
        """Rank -> robust z snapshot of the LAST observation — the
        whole vector, not only threshold crossings, so the topology
        control plane (and an operator dashboard) can read
        sub-threshold drift before a rank is formally flagged.  A
        recovered rank's next observation recomputes its z near zero,
        so the snapshot clears with recovery (tested in
        tests/test_fleet.py); all-zero before the first observation."""
        return {int(r): float(z) for r, z in enumerate(self._z)}

    def flagged(self) -> List[int]:
        """Ranks currently flagged (clears when the streak breaks)."""
        return [int(r) for r in np.nonzero(self._flagged)[0]]
