"""Decision flight recorder: a causal audit trail for every control plane.

The stack self-heals at every layer — topology hot-swaps, mix-ratio
ladder steps, dead-rank healing and elastic promotion, serving
failover/cooldown/excision, lazy a2a re-plans — and every one of those
autonomous transitions should be answerable to "why did it do that?".
This module is the answer: a process-local, bounded-ring **black box**
of :class:`DecisionEvent` records, each carrying the plane, the trigger
kind, a canonical digest of the telemetry inputs that drove it, the
candidates scored with their costs, the winner and margin, and the
eventual outcome — all causally chained by ``(parent_event_id, step)``
so :func:`explain` renders the full trigger→synthesize→swap→probation→
outcome story for any decision.

Design contracts:

* **Bounded**: the ring holds at most ``capacity`` events (default
  ``BLUEFOG_BLACKBOX_CAPACITY``); at capacity the oldest is evicted and
  counted (``bf_blackbox_dropped_events``).  O(1) memory however long
  the run.
* **Byte-stable**: every event folds one canonical line into a
  streaming SHA-256 (:meth:`BlackBox.chain_digest`) using
  :func:`bluefog_tpu.sim.engine.canonical_detail` — the same sorted-key
  ``%.9g`` formatting the sim's :class:`~bluefog_tpu.sim.engine.EventLog`
  uses, so "two same-seed runs produce byte-identical decision chains"
  is a machine-checkable claim (gated in ``benchmarks/fleet_sim.py``).
  Wall-clock timestamps and the free-form ``detail`` dict are carried
  on the event but **excluded** from the digested line: measured floats
  (probation health, wall time) may differ between a real run and its
  simulated twin without breaking chain equality.
* **Replayable**: a ``synthesize`` event records the full telemetry
  snapshot (edge-seconds deltas, z-scores, dead set, calibrated
  traffic) next to the scored candidates, so
  :meth:`TopologyControlPlane.replay_decision` can re-derive the same
  winner/cost/margin from the audit log alone.
* **Host-side only**: recording never touches a compiled program — jit
  cache sizes and step outputs are bit-identical with the recorder on
  vs off (tested with the PR-4 ``BLUEFOG_OBSERVE`` methodology).
  ``BLUEFOG_BLACKBOX=0`` disables the process-global recorder.
* **Anomaly dump**: recording an anomaly kind (``rollback``,
  ``rank_join_failed``, ``lost``, ``saturated``,
  ``bench_gate_failure``) emits a Chrome-trace instant
  (``blackbox.dump.<kind>``) and — when ``BLUEFOG_BLACKBOX_DUMP``
  names a directory — dumps the whole ring to
  ``<dir>/blackbox_<kind>.jsonl`` (first occurrence per kind, so a
  million lost requests cost one file write).

CLI::

    python -m bluefog_tpu.observe.blackbox dump.jsonl            # all chains
    python -m bluefog_tpu.observe.blackbox dump.jsonl --explain 7

See docs/observability.md "Decision audit".
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Union

from bluefog_tpu import config

# Imported lazily: bluefog_tpu.sim's package __init__ pulls the sim
# fleet drivers, which pull the control planes, which call back into
# this module — a top-level import here would be circular.  By the
# time anything records a decision the interpreter is past module
# initialization, so the first-use import is safe and cached.
_canonical_detail = None


def canonical_detail(**detail) -> str:
    """:func:`bluefog_tpu.sim.engine.canonical_detail`, bound on first
    use — the recorder and the sim's EventLog share one definition of
    "byte-stable"."""
    global _canonical_detail
    if _canonical_detail is None:
        from bluefog_tpu.sim.engine import canonical_detail as _cd
        _canonical_detail = _cd
    return _canonical_detail(**detail)

__all__ = [
    "ANOMALY_KINDS",
    "BlackBox",
    "DecisionEvent",
    "explain",
    "get_blackbox",
    "record_decision",
]

# Terminal kinds resolve the outcome of their whole causal chain: a
# probation commit retroactively marks the trigger/synthesize/swap
# ancestors "committed" (rendering only — the digest is append-only).
_TERMINAL_OUTCOMES = {
    "commit": "committed",
    "rollback": "rolled_back",
    "kick": "kicked",
    "reject": "rejected",
    "lost": "lost",
    "expired": "expired",
}

#: Kinds whose recording dumps the ring (the "something went wrong,
#: preserve the evidence" set).
ANOMALY_KINDS = frozenset({
    "rollback", "rank_join_failed", "lost", "saturated",
    "bench_gate_failure",
})


@dataclass
class DecisionEvent:
    """One recorded control-plane transition.

    ``detail`` and ``t`` are carried for rendering but excluded from
    :meth:`canonical_line` — only the structural decision record
    (ids, step, plane, kind, telemetry digest, candidates, winner,
    cost, margin) is digested."""

    event_id: int
    parent_id: Optional[int]
    step: int
    plane: str
    kind: str
    telemetry: dict = field(default_factory=dict)
    telemetry_digest: str = ""
    candidates: Optional[Dict[str, float]] = None
    winner: Optional[str] = None
    winner_cost: Optional[float] = None
    margin: Optional[float] = None
    outcome: str = "pending"
    detail: dict = field(default_factory=dict)
    t: float = 0.0

    def canonical_line(self) -> str:
        """The byte-stable line the chain digest folds.  ``detail``
        and ``t`` are deliberately absent; ``outcome`` is digested as
        it stood AT RECORD TIME (always ``pending`` for non-terminal
        kinds) so later chain resolution never rewrites history."""
        return canonical_detail(
            id=self.event_id,
            parent="-" if self.parent_id is None else self.parent_id,
            step=self.step,
            plane=self.plane,
            kind=self.kind,
            telemetry=self.telemetry_digest or "-",
            candidates=self.candidates if self.candidates else "-",
            winner="-" if self.winner is None else str(self.winner),
            winner_cost=("-" if self.winner_cost is None
                         else self.winner_cost),
            margin="-" if self.margin is None else self.margin,
            outcome=_TERMINAL_OUTCOMES.get(self.kind, "pending"),
        )

    def to_json(self) -> dict:
        return {
            "event_id": self.event_id,
            "parent_id": self.parent_id,
            "step": self.step,
            "plane": self.plane,
            "kind": self.kind,
            "telemetry": self.telemetry,
            "telemetry_digest": self.telemetry_digest,
            "candidates": self.candidates,
            "winner": self.winner,
            "winner_cost": self.winner_cost,
            "margin": self.margin,
            "outcome": self.outcome,
            "detail": self.detail,
            "t": self.t,
        }

    @staticmethod
    def from_json(obj: dict) -> "DecisionEvent":
        return DecisionEvent(
            event_id=int(obj["event_id"]),
            parent_id=(None if obj.get("parent_id") is None
                       else int(obj["parent_id"])),
            step=int(obj.get("step", -1)),
            plane=str(obj.get("plane", "")),
            kind=str(obj.get("kind", "")),
            telemetry=dict(obj.get("telemetry") or {}),
            telemetry_digest=str(obj.get("telemetry_digest", "")),
            candidates=(None if obj.get("candidates") is None
                        else dict(obj["candidates"])),
            winner=obj.get("winner"),
            winner_cost=obj.get("winner_cost"),
            margin=obj.get("margin"),
            outcome=str(obj.get("outcome", "pending")),
            detail=dict(obj.get("detail") or {}),
            t=float(obj.get("t", 0.0)),
        )

    def describe(self) -> str:
        """One human line: ``[id] step=.. plane/kind`` plus whatever
        decision fields are set."""
        bits = [f"[{self.event_id}] step={self.step} "
                f"{self.plane}/{self.kind}"]
        if self.winner is not None:
            bits.append(f"winner={self.winner}")
        if self.winner_cost is not None:
            bits.append(f"cost={format(float(self.winner_cost), '.9g')}")
        if self.margin is not None:
            bits.append(f"margin={format(float(self.margin), '.9g')}")
        if self.candidates:
            bits.append(f"candidates={len(self.candidates)}")
        if self.telemetry_digest:
            bits.append(f"telemetry=sha256:{self.telemetry_digest[:12]}")
        for k in sorted(self.detail):
            bits.append(f"{k}={self.detail[k]}")
        bits.append(f"outcome={self.outcome}")
        return " ".join(bits)


def _digest_telemetry(telemetry: dict) -> str:
    if not telemetry:
        return ""
    line = canonical_detail(**telemetry)
    return hashlib.sha256(line.encode("utf-8")).hexdigest()


class BlackBox:
    """Bounded ring of :class:`DecisionEvent` with a streaming chain
    digest.

    Thread-safe: control planes record from the step loop, async
    synthesis threads, and serving pollers concurrently.  ``capacity``
    defaults to :func:`bluefog_tpu.config.blackbox_capacity`.  Metrics
    publish to ``registry`` when given, else to the process registry
    gated by :func:`bluefog_tpu.observe.registry.enabled`."""

    def __init__(self, capacity: Optional[int] = None, *,
                 registry=None):
        self._lock = threading.RLock()
        self.capacity = int(capacity if capacity is not None
                            else config.blackbox_capacity())
        if self.capacity < 1:
            raise ValueError("blackbox capacity must be >= 1")
        self._ring: "OrderedDict[int, DecisionEvent]" = OrderedDict()
        self._children: Dict[int, List[int]] = {}
        self._sha = hashlib.sha256()
        self._next_id = 0
        self.n_recorded = 0
        self.dropped = 0
        self._registry = registry
        self._dumped_kinds: set = set()
        # metric handles cached per (registry, labels): the registry's
        # labeled lookup costs ~20us and record() is the sim's inner
        # loop, so the handles are resolved once and reused
        self._counter_cache: dict = {}
        self._gauge_cache: dict = {}
        if registry is not None:
            registry.gauge(
                "bf_blackbox_dropped_events",
                "Decision events evicted from the flight recorder ring",
            ).set(0.0)

    # -- recording ----------------------------------------------------

    def record(self, plane: str, kind: str, *, step: int,
               parent: Union[None, int, DecisionEvent] = None,
               telemetry: Optional[dict] = None,
               candidates: Optional[Dict[str, float]] = None,
               winner: Optional[str] = None,
               winner_cost: Optional[float] = None,
               margin: Optional[float] = None,
               detail: Optional[dict] = None) -> DecisionEvent:
        """Append one decision to the ring and fold its canonical line
        into the chain digest.  Returns the event (its ``event_id`` is
        the causal handle for children)."""
        parent_id = (parent.event_id if isinstance(parent, DecisionEvent)
                     else parent)
        telemetry = dict(telemetry) if telemetry else {}
        with self._lock:
            ev = DecisionEvent(
                event_id=self._next_id,
                parent_id=parent_id,
                step=int(step),
                plane=str(plane),
                kind=str(kind),
                telemetry=telemetry,
                telemetry_digest=_digest_telemetry(telemetry),
                candidates=(dict(candidates) if candidates is not None
                            else None),
                winner=winner,
                winner_cost=(None if winner_cost is None
                             else float(winner_cost)),
                margin=None if margin is None else float(margin),
                detail=dict(detail) if detail else {},
                t=_now(),
            )
            self._next_id += 1
            self.n_recorded += 1
            self._sha.update(ev.canonical_line().encode("utf-8"))
            self._sha.update(b"\n")
            self._ring[ev.event_id] = ev
            if parent_id is not None:
                self._children.setdefault(parent_id, []).append(
                    ev.event_id)
            while len(self._ring) > self.capacity:
                old_id, _ = self._ring.popitem(last=False)
                self._children.pop(old_id, None)
                self.dropped += 1
            outcome = _TERMINAL_OUTCOMES.get(ev.kind)
            if outcome is not None:
                self._resolve_chain_locked(ev, outcome)
            self._publish(ev, outcome)
        if ev.kind in ANOMALY_KINDS:
            self._on_anomaly(ev)
        return ev

    def _resolve_chain_locked(self, ev: DecisionEvent,
                              outcome: str) -> None:
        """A terminal kind settles the whole ancestor chain's outcome
        (rendering only; digested lines are immutable)."""
        ev.outcome = outcome
        seen = set()
        pid = ev.parent_id
        while pid is not None and pid not in seen:
            seen.add(pid)
            anc = self._ring.get(pid)
            if anc is None:
                break
            if anc.outcome == "pending":
                anc.outcome = outcome
            pid = anc.parent_id

    def _publish(self, ev: DecisionEvent,
                 outcome: Optional[str]) -> None:
        reg = self._registry
        if reg is None:
            from bluefog_tpu.observe import registry as _registry
            if not _registry.enabled():
                return
            reg = _registry.get_registry()
        key = (id(reg), ev.plane, ev.kind, outcome)
        ctr = self._counter_cache.get(key)
        if ctr is None:
            ctr = self._counter_cache[key] = reg.counter(
                "bf_decisions_total",
                "Control-plane decisions recorded by the flight "
                "recorder",
                plane=ev.plane, kind=ev.kind,
                outcome=outcome if outcome is not None else "pending")
        ctr.inc()
        gauge = self._gauge_cache.get(id(reg))
        if gauge is None:
            gauge = self._gauge_cache[id(reg)] = reg.gauge(
                "bf_blackbox_dropped_events",
                "Decision events evicted from the flight recorder ring")
        gauge.set(float(self.dropped))

    def _on_anomaly(self, ev: DecisionEvent) -> None:
        """Preserve the evidence: Chrome-trace instant always (when
        observe is on), ring dump to BLUEFOG_BLACKBOX_DUMP once per
        anomaly kind."""
        try:
            from bluefog_tpu.observe.tracer import publish_tracer
            tracer = publish_tracer()
            if tracer is not None:
                tracer.instant(f"blackbox.dump.{ev.kind}", "blackbox")
        except Exception:
            pass
        dump_dir = config.blackbox_dump_dir()
        if not dump_dir:
            return
        with self._lock:
            if ev.kind in self._dumped_kinds:
                return
            self._dumped_kinds.add(ev.kind)
        try:
            os.makedirs(dump_dir, exist_ok=True)
            self.dump(os.path.join(dump_dir,
                                   f"blackbox_{ev.kind}.jsonl"))
        except OSError:
            pass

    # -- queries ------------------------------------------------------

    def events(self) -> List[DecisionEvent]:
        with self._lock:
            return list(self._ring.values())

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def get(self, event_id: int) -> Optional[DecisionEvent]:
        with self._lock:
            return self._ring.get(int(event_id))

    def children(self, event_id: int) -> List[DecisionEvent]:
        with self._lock:
            return [self._ring[c]
                    for c in self._children.get(int(event_id), ())
                    if c in self._ring]

    def chain(self, event: Union[int, DecisionEvent]
              ) -> List[DecisionEvent]:
        """The full causal chain through ``event``: ancestors back to
        the root (oldest first), then the subtree below it in id
        order.  Evicted ancestors are simply absent — the chain is as
        deep as the ring still remembers."""
        ev = (event if isinstance(event, DecisionEvent)
              else self.get(event))
        if ev is None:
            return []
        with self._lock:
            up: List[DecisionEvent] = []
            seen = set()
            cur: Optional[DecisionEvent] = ev
            while cur is not None and cur.event_id not in seen:
                seen.add(cur.event_id)
                up.append(cur)
                cur = (self._ring.get(cur.parent_id)
                       if cur.parent_id is not None else None)
            up.reverse()
            down: List[DecisionEvent] = []
            stack = list(self._children.get(ev.event_id, ()))
            while stack:
                cid = stack.pop(0)
                child = self._ring.get(cid)
                if child is None or cid in seen:
                    continue
                seen.add(cid)
                down.append(child)
                stack.extend(self._children.get(cid, ()))
            return up + down

    def chain_digest(self) -> str:
        """Hex SHA-256 over every canonical line recorded so far —
        byte-identical across two same-seed runs, unaffected by ring
        eviction (streaming, like the sim's EventLog)."""
        with self._lock:
            return self._sha.hexdigest()

    # -- export -------------------------------------------------------

    def jsonl(self) -> str:
        """One JSON object per retained event, preceded by a meta line
        with counts and the chain digest."""
        with self._lock:
            meta = {"blackbox": {
                "n_recorded": self.n_recorded,
                "retained": len(self._ring),
                "dropped": self.dropped,
                "capacity": self.capacity,
                "chain_digest": self.chain_digest(),
            }}
            lines = [json.dumps(meta, sort_keys=True)]
            lines.extend(json.dumps(ev.to_json(), sort_keys=True)
                         for ev in self._ring.values())
        return "\n".join(lines) + "\n"

    def dump(self, path: str) -> str:
        payload = self.jsonl()
        with open(path, "w") as f:
            f.write(payload)
        return path

    def explain(self, event: Union[int, DecisionEvent]) -> str:
        """Render the causal chain through ``event`` as an indented
        tree — the "why did it do that?" answer."""
        chain = self.chain(event)
        if not chain:
            return "(no such decision in the ring)"
        lines = [f"decision chain ({len(chain)} events, "
                 f"plane={chain[0].plane}):"]
        for depth, ev in enumerate(chain):
            prefix = "  " + "   " * depth + ("└─ " if depth else "")
            lines.append(prefix + ev.describe())
        return "\n".join(lines)


def _now() -> float:
    import time
    return time.time()


_global_lock = threading.Lock()
_global_blackbox: Optional[BlackBox] = None


def get_blackbox() -> BlackBox:
    """The process-global flight recorder (capacity from
    ``BLUEFOG_BLACKBOX_CAPACITY`` at first use)."""
    global _global_blackbox
    bb = _global_blackbox
    if bb is None:
        with _global_lock:
            bb = _global_blackbox
            if bb is None:
                bb = BlackBox()
                _global_blackbox = bb
    return bb


def record_decision(plane: str, kind: str, *, step: int,
                    parent: Union[None, int, DecisionEvent] = None,
                    telemetry: Optional[dict] = None,
                    candidates: Optional[Dict[str, float]] = None,
                    winner: Optional[str] = None,
                    winner_cost: Optional[float] = None,
                    margin: Optional[float] = None,
                    blackbox: Union[None, bool, BlackBox] = None,
                    detail: Optional[dict] = None
                    ) -> Optional[DecisionEvent]:
    """The one emission seam every control plane calls (the
    ``decision-outside-recorder`` lint rule enforces it).

    ``blackbox=None`` records to the process-global ring, gated by
    ``BLUEFOG_BLACKBOX``; an explicit :class:`BlackBox` records
    unconditionally (benches inject their own for determinism checks);
    ``blackbox=False`` disables recording for this call — the "off"
    arm of the recorder-transparency check.  Returns the event, or
    ``None`` when disabled (callers thread ``None`` parents through
    untouched)."""
    if blackbox is False:
        return None
    if blackbox is None or blackbox is True:
        if not config.blackbox_enabled():
            return None
        blackbox = get_blackbox()
    return blackbox.record(
        plane, kind, step=step, parent=parent, telemetry=telemetry,
        candidates=candidates, winner=winner, winner_cost=winner_cost,
        margin=margin, detail=detail)


def explain(event: Union[int, DecisionEvent],
            blackbox: Optional[BlackBox] = None) -> str:
    """``bf.observe.explain(event)``: render the causal chain through
    ``event`` from the given (default process-global) recorder."""
    bb = blackbox if blackbox is not None else get_blackbox()
    return bb.explain(event)


# -- CLI --------------------------------------------------------------


def _load_dump(path: str) -> "BlackBox":
    bb = BlackBox(capacity=1 << 30)
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            obj = json.loads(line)
            if "blackbox" in obj and "event_id" not in obj:
                continue
            ev = DecisionEvent.from_json(obj)
            bb._ring[ev.event_id] = ev
            if ev.parent_id is not None:
                bb._children.setdefault(ev.parent_id, []).append(
                    ev.event_id)
            bb.n_recorded += 1
    return bb


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m bluefog_tpu.observe.blackbox",
        description="Render decision chains from a flight-recorder "
                    "JSONL dump (or the live process ring when no "
                    "file is given).")
    parser.add_argument("dump", nargs="?", default=None,
                        help="JSONL dump written by BlackBox.dump()")
    parser.add_argument("--explain", type=int, default=None,
                        metavar="ID",
                        help="render only the chain through event ID")
    args = parser.parse_args(argv)

    bb = _load_dump(args.dump) if args.dump else get_blackbox()
    events = bb.events()
    if not events:
        print("(empty ring)")
        return 0
    if args.explain is not None:
        print(bb.explain(args.explain))
        return 0 if bb.get(args.explain) is not None else 1
    roots = [ev for ev in events
             if ev.parent_id is None or bb.get(ev.parent_id) is None]
    for root in roots:
        print(bb.explain(root))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
