"""Span tracer: nested spans, instant events, per-thread tracks.

Generalizes ``timeline.py``'s span machinery (itself the port of the
reference C++ ``Timeline``, bluefog/common/timeline.{h,cc}) into a
subsystem-neutral tracer.  Every producer — the serving engine's
request lifecycle (admission → prefill → decode → retire), the
resilience runner's skip/detect/heal/rollback events, the eager op
API's enqueue/compute spans, and ``build_train_step`` callers — reports
into ONE :class:`Tracer`; consumers attach as **sinks**:

* the Chrome-trace file writer (``timeline.py`` is now a thin exporter:
  its native/Python writers implement the sink protocol directly);
* the in-memory ring buffer every tracer carries (bounded — a tracer
  left running forever costs a fixed amount of memory), which feeds the
  JSONL and chrome-trace exporters in :mod:`bluefog_tpu.observe.export`.

The sink protocol is the timeline writers' existing surface::

    sink.record(name: str, tid: str, phase: str)   # "B" | "E" | "i"

Spans nest per **track** (the Chrome-trace ``tid``): ``begin`` pushes,
``end`` pops, and the balanced B/E stream is what chrome://tracing
renders as stacked bars.  ``span()`` is the context-manager form; with
no explicit track it uses the calling thread's name, so concurrent
producers get separate rows for free.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Callable, Dict, List, Optional

from bluefog_tpu.observe.registry import enabled, get_registry

__all__ = ["Tracer", "get_tracer", "publish_tracer", "effective_tracer"]

#: consecutive ``record()`` failures after which a sink is detached —
#: a persistently broken sink (full disk, closed pipe) must not keep
#: throwing inside every producer's span emission
SINK_ERROR_LIMIT = 3


class Tracer:
    """Span/event recorder with pluggable sinks and a bounded buffer.

    Args:
      clock: monotonic-seconds source (injectable for deterministic
        tests; default ``time.perf_counter``).  Timestamps are recorded
        as microseconds since the tracer's construction, matching the
        Chrome-trace ``ts`` convention.
      max_events: ring-buffer bound; the oldest events fall off first
        and :attr:`dropped_events` counts them (sinks see every event
        regardless — the bound protects memory, not the file).
      pid: the Chrome-trace ``pid`` field (the process/rank identity).
    """

    def __init__(self, clock: Callable[[], float] = time.perf_counter,
                 max_events: int = 65536, pid: int = 0):
        self._clock = clock
        self._t0 = clock()
        self.pid = pid
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=max_events)
        self._n_emitted = 0
        self._sinks: List[object] = []
        # id(sink) -> consecutive record() failures; any success resets
        self._sink_errors: Dict[int, int] = {}
        self._open_spans: Dict[str, List[str]] = {}
        # per-thread (track, name) stack: which span THIS thread is
        # inside right now — the correlation source structured logs
        # join the trace on (active_span)
        self._tls = threading.local()

    # -- sinks --------------------------------------------------------- #
    def add_sink(self, sink) -> None:
        """Attach a ``record(name, tid, phase)`` consumer (e.g. a
        timeline file writer).  Idempotent."""
        with self._lock:
            if sink not in self._sinks:
                self._sinks.append(sink)
            self._sink_errors.pop(id(sink), None)

    def remove_sink(self, sink) -> None:
        with self._lock:
            if sink in self._sinks:
                self._sinks.remove(sink)
            self._sink_errors.pop(id(sink), None)

    # -- core emit ----------------------------------------------------- #
    def _now_us(self) -> float:
        return (self._clock() - self._t0) * 1e6

    def _emit_locked(self, phase: str, name: str, track: str) -> None:
        """Append + fan out; the CALLER holds ``self._lock`` — span
        bookkeeping and event emission must be one atomic step (two
        lock acquisitions would let a concurrent producer interleave an
        E between a track's bookkeeping and its B record), and the
        native timeline writer is a single-producer ring, so sink
        fan-out must stay serialized too (the pre-tracer Timeline held
        the same lock around its writer)."""
        self._events.append((phase, name, track, self._now_us()))
        self._n_emitted += 1
        # sink fan-out is fault-isolated: one raising sink must not
        # break span emission for the producers (or starve the other
        # sinks), and the per-thread span stack stays consistent
        # because the event was already buffered above.  A sink that
        # fails SINK_ERROR_LIMIT times in a row is detached.
        for sink in list(self._sinks):
            try:
                sink.record(name, track, phase)
            except Exception:
                errs = self._sink_errors.get(id(sink), 0) + 1
                self._sink_errors[id(sink)] = errs
                if enabled():
                    get_registry().counter(
                        "bf_tracer_sink_errors_total",
                        "tracer sink record() failures",
                        sink=type(sink).__name__).inc()
                if errs >= SINK_ERROR_LIMIT:
                    if sink in self._sinks:
                        self._sinks.remove(sink)
                    self._sink_errors.pop(id(sink), None)
            else:
                self._sink_errors.pop(id(sink), None)

    # -- spans --------------------------------------------------------- #
    def begin(self, track: str, name: str) -> None:
        """Open a span named ``name`` on ``track`` (nested within the
        track's currently-open span, if any)."""
        with self._lock:
            self._open_spans.setdefault(track, []).append(name)
            self._emit_locked("B", name, track)
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        stack.append((track, name))

    def end(self, track: str) -> None:
        """Close the innermost open span on ``track`` (a no-op end on a
        track with no open span still records the E event so a foreign
        B/E producer — the flat timeline API — stays balanced)."""
        with self._lock:
            spans = self._open_spans.get(track)
            if spans:
                spans.pop()
            if not spans:
                # drop the empty per-track entry: tracks are often
                # unique (request.<rid>, <op>.noname.<handle>), so
                # keeping them would leak one dict entry per request
                # for the life of the default-on global tracer
                self._open_spans.pop(track, None)
            self._emit_locked("E", "", track)
        stack = getattr(self._tls, "stack", None)
        if stack:
            # remove the INNERMOST entry for that track — producers
            # like the eager op API end spans non-LIFO (begin A,
            # begin B, end A, end B: concurrent in-flight handles), and
            # a top-only pop would leak A's entry in the thread-local
            # stack forever.  A track this thread never began (foreign
            # B/E through the flat timeline API) removes nothing.
            for i in range(len(stack) - 1, -1, -1):
                if stack[i][0] == track:
                    del stack[i]
                    break

    def _prune_stale_locked(self, stack) -> None:
        """Drop trailing thread-local entries whose track has NO open
        span globally: a span begun on this thread may be ENDED by
        another (the nonblocking handle API dispatches on one thread
        and synchronizes on another), which closes ``_open_spans`` but
        cannot touch the beginner's TLS stack.  Pruned lazily here so
        the stack neither grows unboundedly nor mis-stamps log lines
        with long-closed spans.  Caller holds ``self._lock``."""
        while stack and stack[-1][0] not in self._open_spans:
            stack.pop()

    def active_span(self) -> Optional[tuple]:
        """The ``(track, name)`` of the innermost span the CALLING
        thread is inside, or ``None`` — what ``BLUEFOG_LOG_FORMAT=json``
        stamps on log lines so structured logs join the Chrome trace."""
        stack = getattr(self._tls, "stack", None)
        if not stack:
            return None
        with self._lock:
            self._prune_stale_locked(stack)
        return stack[-1] if stack else None

    def instant(self, name: str, track: str = "") -> None:
        """A zero-duration marker event."""
        with self._lock:
            self._emit_locked("i", name, track)

    @contextmanager
    def span(self, track: Optional[str], name: str):
        """``with tracer.span("serving", "decode"): ...`` — the span
        covers the block; ``track=None`` uses the calling thread's name
        (per-thread tracks)."""
        if track is None:
            track = threading.current_thread().name
        self.begin(track, name)
        try:
            yield
        finally:
            self.end(track)

    def open_depth(self, track: str) -> int:
        """Current span-nesting depth on ``track`` (tests; a balanced
        producer returns to 0)."""
        with self._lock:
            return len(self._open_spans.get(track, ()))

    # -- buffer views -------------------------------------------------- #
    @property
    def dropped_events(self) -> int:
        """Events that fell off the ring buffer (sinks saw them; the
        in-memory view did not)."""
        with self._lock:
            return self._n_emitted - len(self._events)

    def events(self) -> List[tuple]:
        """The buffered ``(phase, name, track, ts_us)`` tuples, oldest
        first."""
        with self._lock:
            return list(self._events)

    @staticmethod
    def chrome_events(events: List[tuple], pid: int = 0) -> List[dict]:
        """Format ``(phase, name, track, ts_us)`` tuples as Chrome-trace
        JSON records — the same shape the timeline file writers stream
        (``ph``/``ts``/``pid``/``tid``; instants carry ``s: "p"``)."""
        out = []
        for phase, name, track, ts in events:
            if phase == "B":
                out.append({"name": name, "cat": track, "ph": "B",
                            "ts": ts, "pid": pid, "tid": track})
            elif phase == "E":
                out.append({"ph": "E", "ts": ts, "pid": pid,
                            "tid": track})
            else:
                out.append({"name": name, "ph": "i", "ts": ts,
                            "pid": pid, "s": "p"})
        return out

    def to_chrome_trace(self) -> List[dict]:
        """The buffered events in Chrome-trace JSON form."""
        return self.chrome_events(self.events(), self.pid)

    def clear(self) -> None:
        """Drop the buffered events (sinks and open-span bookkeeping
        are untouched)."""
        with self._lock:
            self._events.clear()
            self._n_emitted = 0


_tracer: Optional[Tracer] = None
_tracer_lock = threading.Lock()


def get_tracer() -> Tracer:
    """The process-global tracer: what the built-in producers publish
    into and what ``start_timeline`` attaches the Chrome-trace file
    writer to."""
    global _tracer
    if _tracer is None:
        with _tracer_lock:
            if _tracer is None:
                _tracer = Tracer()
    return _tracer


def publish_tracer() -> Optional[Tracer]:
    """The tracer built-in producers should publish into, or ``None``
    when ``BLUEFOG_OBSERVE=0`` — callers guard with
    ``tr = publish_tracer();  if tr is not None: tr.instant(...)``."""
    if not enabled():
        return None
    return get_tracer()


def effective_tracer(timeline) -> Optional[Tracer]:
    """The ONE fallback policy for span producers that predate the
    tracer (eager ops, serving metrics): the global tracer when observe
    is enabled (a started timeline rides it as a file sink), else the
    caller's started ``timeline``'s PRIVATE tracer — so
    ``BLUEFOG_TIMELINE`` alone keeps recording spans under
    ``BLUEFOG_OBSERVE=0`` — else ``None``.  A timeline that was started
    while observe was ENABLED is bound to the global tracer; falling
    back to it would keep filling the observe buffers despite the
    opt-out, so that case yields ``None`` (flip ``BLUEFOG_OBSERVE``
    before ``start_timeline`` for the private-file mode)."""
    tr = publish_tracer()
    if tr is not None:
        return tr
    if timeline is not None and timeline.tracer is not _tracer:
        return timeline.tracer
    return None
