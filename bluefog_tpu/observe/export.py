"""Exporters: Prometheus text, structured JSONL event log, Chrome trace.

Three formats over the same two stores (the global
:class:`~bluefog_tpu.observe.registry.MetricsRegistry` and
:class:`~bluefog_tpu.observe.tracer.Tracer`):

* :func:`prometheus_text` — the text exposition format a scrape
  endpoint serves (``# TYPE`` headers, ``name{label="v"} value`` lines;
  histograms as ``_count``/``_sum`` plus ``quantile`` samples);
* :func:`jsonl_events` — one JSON object per tracer event, the
  machine-greppable log (``{"ph","name","track","ts_us","pid"}``);
* :func:`chrome_trace` — the chrome://tracing JSON array, identical in
  shape to what the timeline file writers stream.

``snapshot()`` is the one-call dump (``bf.observe.snapshot()``): the
structured metrics + trace summary as a dict, optionally written to a
directory as ``metrics.prom`` / ``events.jsonl`` / ``trace.json``.
"""

from __future__ import annotations

import json
import os
from typing import Optional

from bluefog_tpu.observe import registry as _registry_mod
from bluefog_tpu.observe import tracer as _tracer_mod

__all__ = ["prometheus_text", "jsonl_events", "chrome_trace", "snapshot"]


def _prom_escape(v: str) -> str:
    return v.replace("\\", r"\\").replace('"', r'\"').replace("\n", r"\n")


def _prom_help_escape(v: str) -> str:
    # HELP text escapes only backslash and newline (label values also
    # escape the double quote) — exposition format spec
    return v.replace("\\", r"\\").replace("\n", r"\n")


def _prom_labels(labels: dict, extra: Optional[dict] = None) -> str:
    items = dict(labels)
    if extra:
        items.update(extra)
    if not items:
        return ""
    body = ",".join(f'{k}="{_prom_escape(str(v))}"'
                    for k, v in sorted(items.items()))
    return "{" + body + "}"


def prometheus_text(registry=None) -> str:
    """The registry in Prometheus text exposition format (one ``# TYPE``
    per family; histograms exported as summaries: lifetime
    ``_count``/``_sum`` + windowed p50/p99 ``quantile`` samples)."""
    reg = registry if registry is not None else _registry_mod.get_registry()
    lines = []
    last_name = None
    for name, kind, help, labels, m in reg.collect():
        if name != last_name:
            if help:
                lines.append(f"# HELP {name} {_prom_help_escape(help)}")
            lines.append(f"# TYPE {name} "
                         f"{'summary' if kind == 'histogram' else kind}")
            last_name = name
        if kind == "histogram":
            lines.append(f"{name}_count{_prom_labels(labels)} {m.count}")
            lines.append(f"{name}_sum{_prom_labels(labels)} {m.sum}")
            for q in (0.5, 0.99):
                val = m.percentile(q * 100)
                lines.append(
                    f"{name}{_prom_labels(labels, {'quantile': q})} {val}")
        else:
            lines.append(f"{name}{_prom_labels(labels)} {m.value}")
    return "\n".join(lines) + ("\n" if lines else "")


def _jsonl(events, pid: int) -> str:
    lines = []
    for phase, name, track, ts in events:
        lines.append(json.dumps({"ph": phase, "name": name, "track": track,
                                 "ts_us": round(ts, 3), "pid": pid}))
    return "\n".join(lines) + ("\n" if lines else "")


def jsonl_events(tracer=None) -> str:
    """The tracer's buffered events as one JSON object per line."""
    tr = tracer if tracer is not None else _tracer_mod.get_tracer()
    return _jsonl(tr.events(), tr.pid)


def chrome_trace(tracer=None) -> list:
    """The tracer's buffered events as a chrome://tracing event list."""
    tr = tracer if tracer is not None else _tracer_mod.get_tracer()
    return tr.to_chrome_trace()


def snapshot(out_dir: Optional[str] = None) -> dict:
    """One-call dump of the whole observability state.

    Returns ``{"metrics": registry.snapshot(), "trace": {"n_events",
    "dropped_events"}}``; with ``out_dir`` also writes ``metrics.prom``
    (Prometheus text), ``events.jsonl`` (structured log), and
    ``trace.json`` (Chrome trace) there and records the paths under
    ``"files"``."""
    reg = _registry_mod.get_registry()
    tr = _tracer_mod.get_tracer()
    events = tr.events()  # ONE buffer copy feeds count + both formats
    snap = {
        "metrics": reg.snapshot(),
        "trace": {"n_events": len(events),
                  "dropped_events": tr.dropped_events},
    }
    if out_dir is not None:
        os.makedirs(out_dir, exist_ok=True)
        files = {}
        for fname, payload in (
                ("metrics.prom", prometheus_text(reg)),
                ("events.jsonl", _jsonl(events, tr.pid)),
                ("trace.json",
                 json.dumps(tr.chrome_events(events, tr.pid)))):
            path = os.path.join(out_dir, fname)
            with open(path, "w") as f:
                f.write(payload)
            files[fname] = path
        snap["files"] = files
    return snap
