"""Unified observability: metrics registry, span tracer, step profiler.

The one telemetry layer every subsystem reports into (the reference
treats its C++ ``Timeline`` as first-class infrastructure; this
subsystem extends that stance to metrics and per-op cost attribution):

* :mod:`~bluefog_tpu.observe.registry` — process-local counters,
  gauges, and windowed histograms with labeled families; cheap enough
  for per-step use, host-side only (enabling it never touches a
  compiled program — asserted via jit cache sizes and bit-identical
  step outputs in tests/test_observe.py);
* :mod:`~bluefog_tpu.observe.tracer` — nested spans / instant events /
  per-thread tracks; the serving engine, resilience runner, eager op
  API, and ``build_train_step`` wrappers publish here, and
  ``timeline.py`` is a thin Chrome-trace exporter over it;
* :mod:`~bluefog_tpu.observe.stepprof` — ``profile_step`` returns a
  :class:`StepProfile` (FLOPs, per-collective bytes, overlap windows,
  MFU) from XLA's own view of the compiled module;
* :mod:`~bluefog_tpu.observe.export` — Prometheus text / JSONL event
  log / Chrome trace, plus the one-call ``bf.observe.snapshot()``;
* :mod:`~bluefog_tpu.observe.fleet` — decentralized CROSS-RANK
  aggregation: push-sum gossip of registry metrics over the training
  topology (``FleetAggregator``), per-edge traffic accounting
  (``bf_edge_bytes_total{src,dst}``), and the gossip-fed
  ``StragglerDetector``.

Opt out with ``BLUEFOG_OBSERVE=0`` (publication stops; explicitly-held
registries/tracers keep working).  See docs/observability.md.
"""

from bluefog_tpu.observe.registry import (Counter, Gauge, Histogram,
                                          MetricsRegistry, enabled,
                                          get_registry, percentile)
from bluefog_tpu.observe.tracer import Tracer, get_tracer, publish_tracer
from bluefog_tpu.observe.stepprof import (StepProfile, hlo_op_breakdown,
                                          profile_step,
                                          verify_collective_contract)
from bluefog_tpu.observe.export import (chrome_trace, jsonl_events,
                                        prometheus_text, snapshot)
from bluefog_tpu.observe.fleet import (FleetAggregate, FleetAggregator,
                                       StragglerDetector, collect_local,
                                       edge_list, push_sum_matrix,
                                       record_edge_traffic)

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "enabled",
    "get_registry", "percentile",
    "Tracer", "get_tracer", "publish_tracer",
    "StepProfile", "profile_step", "hlo_op_breakdown",
    "verify_collective_contract",
    "prometheus_text", "jsonl_events", "chrome_trace", "snapshot",
    "FleetAggregate", "FleetAggregator", "StragglerDetector",
    "collect_local", "edge_list", "push_sum_matrix",
    "record_edge_traffic",
    "BlackBox", "DecisionEvent", "explain", "get_blackbox",
    "record_decision",
]

# The decision flight recorder resolves lazily: its module reaches
# into bluefog_tpu.sim for the canonical byte-stable formatting, and
# the sim package in turn imports the control planes that record into
# it — binding it here eagerly would cycle the package imports.
_BLACKBOX_EXPORTS = ("BlackBox", "DecisionEvent", "explain",
                     "get_blackbox", "record_decision")


def __getattr__(name):
    if name in _BLACKBOX_EXPORTS:
        from bluefog_tpu.observe import blackbox as _blackbox
        return getattr(_blackbox, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")
