"""HLO-attributed step profiler: one supported attribution path.

The round-5 VERDICT flagged per-op cost accounting as bespoke — FLOPs,
collective bytes, and overlap windows lived inside individual benchmark
scripts.  :func:`profile_step` promotes ``benchutil``'s HLO machinery
(``compiled_step_flops``, ``hlo_collective_bytes``,
``scheduled_collective_windows``, ``overlap_accounting``) into one call
that every consumer — the decode/overlap/serving benchmarks AND the
tests — goes through, so a throughput claim always ships with the same
machine-readable breakdown:

    prof = profile_step(train_step, params, opt_state, batch, step)
    prof.flops                 # XLA cost analysis, per device
    prof.collective_bytes      # {kind: {count, bytes}} per execution
    prof.windows               # per-collective overlap windows
    prof.mfu(step_seconds)     # against chip_peak_flops()

Profiling compiles (AOT) but never executes: pass measured
``step_seconds`` for MFU/utilization figures.  The compile hits jax's
jit cache, so profiling a step that already ran costs one lowering and
no extra executable; repeat profiles of the SAME executable also hit a
per-module analysis cache (XLA cost analysis + the per-op/collective/
window parses run once per optimized module — ``profile_cache_info``
exposes the hit counters).

Self-consistency is part of the contract (asserted in
tests/test_observe.py): ``prof.flops`` equals
``benchutil.compiled_step_flops`` on the same call, the per-kind byte
totals equal ``benchutil.hlo_collective_bytes`` of the compiled module,
and on the bucketed overlap step the per-collective windows reproduce
``benchutil.overlap_accounting``'s numbers exactly.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

from bluefog_tpu import benchutil
from bluefog_tpu.observe.registry import enabled, get_registry

__all__ = ["StepProfile", "profile_step", "hlo_op_breakdown",
           "verify_collective_contract", "profile_cache_info",
           "profile_cache_clear"]

# the per-op view lives with the rest of the HLO machinery in benchutil
# (public there); re-exported here because StepProfile.op_breakdown is
# its supported entry point
hlo_op_breakdown = benchutil.hlo_op_breakdown

# the predicted-vs-lowered collective check rides the same HLO
# machinery; re-exported because a step profile and a contract check
# are the two supported consumers of one compiled artifact
# (bluefog_tpu.analysis and tests/test_hlo_guarantees.py both call it)
verify_collective_contract = benchutil.verify_collective_contract


@dataclasses.dataclass
class StepProfile:
    """The attribution record :func:`profile_step` returns.

    FLOPs/bytes are PER DEVICE per execution (``compiled_step_flops`` /
    ``hlo_collective_bytes`` conventions).  ``overlap`` is the
    ``overlap_accounting`` dict (byte-weighted overlappable fraction +
    per-window detail) when link bandwidth was provided, else None.
    """

    name: str
    flops: float
    cost_bytes_accessed: float          # XLA cost analysis, 0.0 if absent
    collective_bytes: Dict[str, dict]   # kind -> {count, bytes}
    op_breakdown: Dict[str, dict]       # op -> {count, flops} (estimator)
    windows: List[dict]                 # scheduled_collective_windows
    overlap: Optional[dict]
    peak_flops: float                   # chip peak (0.0 unknown, e.g. CPU)
    hbm_bandwidth: float                # chip HBM bytes/s (0.0 unknown)
    step_seconds: Optional[float] = None

    def non_collective_ops(self) -> int:
        """Instruction count of everything that is NOT a collective in
        the optimized module — the epilogue-overhead measure the fused
        per-bucket pipeline is audited on (tests/test_hlo_guarantees.py
        asserts the fused step's count never exceeds the unfused
        builder's at the same config)."""
        return sum(
            rec["count"] for op, rec in self.op_breakdown.items()
            if not _is_collective_op(op))

    def non_collective_flops(self) -> float:
        """Estimator flops of the non-collective instructions (same
        estimator as ``op_breakdown``)."""
        return float(sum(
            rec["flops"] for op, rec in self.op_breakdown.items()
            if not _is_collective_op(op)))

    def mfu(self, step_seconds: Optional[float] = None) -> float:
        """Achieved FLOP/s over peak; 0.0 when either is unknown."""
        s = step_seconds if step_seconds is not None else self.step_seconds
        if not s:
            return 0.0
        return benchutil.mfu(self.flops, s, self.peak_flops or None) \
            if self.peak_flops else 0.0

    def hbm_utilization(self, step_seconds: Optional[float] = None) -> float:
        """Cost-analysis bytes over (HBM bandwidth x step time); 0.0
        when either is unknown."""
        s = step_seconds if step_seconds is not None else self.step_seconds
        if not s or not self.hbm_bandwidth or not self.cost_bytes_accessed:
            return 0.0
        return self.cost_bytes_accessed / s / self.hbm_bandwidth

    def to_dict(self) -> dict:
        """JSON-ready dict — what the benchmarks check into their
        artifacts instead of hand-rolled breakdowns."""
        out = dataclasses.asdict(self)
        out["mfu"] = self.mfu()
        out["hbm_utilization"] = self.hbm_utilization()
        return out

    def publish(self, registry=None) -> None:
        """Write the headline figures as registry gauges
        (``bf_step_*{step=name}``)."""
        reg = registry if registry is not None else get_registry()
        reg.gauge("bf_step_flops", "per-device FLOPs of one execution",
                  step=self.name).set(self.flops)
        for kind, rec in self.collective_bytes.items():
            reg.gauge("bf_step_collective_bytes",
                      "per-device collective payload bytes per execution",
                      step=self.name, kind=kind).set(rec["bytes"])
        if self.overlap is not None:
            reg.gauge("bf_step_overlap_fraction",
                      "byte-weighted overlappable fraction",
                      step=self.name).set(self.overlap["fraction"])
        if self.step_seconds:
            reg.gauge("bf_step_seconds", "measured step wall seconds",
                      step=self.name).set(self.step_seconds)
            reg.gauge("bf_step_mfu", "model FLOPs utilization",
                      step=self.name).set(self.mfu())


def _is_collective_op(op: str) -> bool:
    # ONE classification source: benchutil's kind list (the same one
    # hlo_collective_bytes / scheduled_collective_windows use), so the
    # non-collective accounting can never drift from the collective one
    return any(op == c or op.startswith(c + "-")
               for c in benchutil._COLLECTIVE_OPS)


def _compiled(fn, args, kwargs):
    """AOT-compile ``fn(*args)``: jit functions and the train-step
    wrappers both expose ``.lower``; plain callables get jitted."""
    if hasattr(fn, "lower"):
        return fn.lower(*args, **kwargs).compile()
    import jax

    return jax.jit(fn).lower(*args, **kwargs).compile()


# ----------------------------------------------------------------- #
# Per-executable analysis cache (ISSUE 6 satellite): repeat
# profile_step calls on the same compiled step used to re-run XLA
# cost analysis + the per-op HLO parse from scratch every time —
# pure host overhead when a benchmark profiles the same program at
# several step timings.  The parsed artifacts are pure functions of
# the optimized module text, so they cache on its fingerprint.
# ----------------------------------------------------------------- #
_analysis_cache: Dict[int, dict] = {}
_cache_hits = 0
_cache_misses = 0
_CACHE_MAX = 64  # distinct compiled programs per process — plenty


def profile_cache_info() -> dict:
    """``{"hits", "misses", "entries"}`` of the per-executable HLO
    analysis cache (test hook + ops visibility)."""
    return {"hits": _cache_hits, "misses": _cache_misses,
            "entries": len(_analysis_cache)}


def profile_cache_clear() -> None:
    """Drop the analysis cache and reset its counters."""
    global _cache_hits, _cache_misses
    _analysis_cache.clear()
    _cache_hits = 0
    _cache_misses = 0


def _analyzed(compiled):
    """``(record, hlo_text)`` — cost analysis + parsed per-op/
    collective/window artifacts of a compiled executable, cached on
    the optimized module's text hash (the executable object itself is
    not reliably hashable across jax versions; the module text is what
    every artifact derives from).  The text itself is recomputed per
    call anyway (it IS the cache key) and returned alongside, but NOT
    stored: pinning multi-hundred-MB module strings of every profiled
    program for process lifetime would dwarf the parse cost the cache
    saves."""
    global _cache_hits, _cache_misses
    hlo = compiled.as_text()
    key = hash(hlo)
    rec = _analysis_cache.get(key)
    if rec is not None:
        _cache_hits += 1
        return rec, hlo
    _cache_misses += 1
    cost = compiled.cost_analysis()
    if isinstance(cost, (list, tuple)):  # older jax: one dict per device
        cost = cost[0]
    rec = {
        "cost": cost or {},
        "collective_bytes": benchutil.hlo_collective_bytes(hlo),
        "op_breakdown": hlo_op_breakdown(hlo),
        "windows": benchutil.scheduled_collective_windows(hlo),
    }
    if len(_analysis_cache) >= _CACHE_MAX:
        _analysis_cache.pop(next(iter(_analysis_cache)))
    _analysis_cache[key] = rec
    return rec, hlo


def profile_step(fn, *args, name: str = "step",
                 step_seconds: Optional[float] = None,
                 peak_flops: Optional[float] = None,
                 hbm_bytes_per_s: Optional[float] = None,
                 link_bytes_per_s: Optional[float] = None,
                 congestion: float = 1.0,
                 kinds: tuple = ("collective-permute",),
                 publish: bool = True,
                 **kwargs: Any) -> StepProfile:
    """Compile ``fn(*args)`` and return its :class:`StepProfile`.

    ``fn`` is anything with a jit ``.lower`` — a ``jax.jit`` function,
    a ``build_train_step`` result, or the serving engine's resident
    programs — or a plain callable (jitted here).  Chip figures default
    to :func:`benchutil.chip_peak_flops` /
    :func:`benchutil.chip_hbm_bandwidth` (0.0 on CPU test meshes —
    pass the target chip's numbers when auditing from a CPU host, the
    ``llama_8b_overlap.py`` pattern).  Overlap accounting runs only
    when ``link_bytes_per_s`` is given (it needs a wire speed to score
    transfer time against) and scores the collectives of ``kinds``.

    The profile is published to the registry as gauges unless
    ``publish=False`` or ``BLUEFOG_OBSERVE=0``.
    """
    compiled = _compiled(fn, args, kwargs)
    rec, hlo = _analyzed(compiled)
    cost = rec["cost"]
    if peak_flops is None:
        peak_flops = benchutil.chip_peak_flops()
    if hbm_bytes_per_s is None:
        hbm_bytes_per_s = benchutil.chip_hbm_bandwidth()
    overlap = None
    if link_bytes_per_s:
        overlap = benchutil.overlap_accounting(
            hlo, peak_flops_per_s=peak_flops,
            link_bytes_per_s=link_bytes_per_s,
            hbm_bytes_per_s=hbm_bytes_per_s or 0.0,
            congestion=congestion, kinds=kinds)
    prof = StepProfile(
        name=name,
        flops=float(cost.get("flops", 0.0)),
        cost_bytes_accessed=float(cost.get("bytes accessed", 0.0)),
        collective_bytes=rec["collective_bytes"],
        op_breakdown=rec["op_breakdown"],
        windows=rec["windows"],
        overlap=overlap,
        peak_flops=peak_flops,
        hbm_bandwidth=hbm_bytes_per_s,
        step_seconds=step_seconds,
    )
    if publish and enabled():
        prof.publish()
    return prof
