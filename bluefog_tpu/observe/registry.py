"""Process-local metrics registry: counters, gauges, windowed histograms.

One registry for the whole process (``get_registry()``); every subsystem
publishes into it — the serving engine, the resilience runner, the eager
op API, the train-step wrappers, and the timeline writer — so ONE
exporter call (:func:`bluefog_tpu.observe.export.prometheus_text` or
``bf.observe.snapshot()``) sees everything.  Design constraints:

* **host-side only** — a metric update is a dict lookup plus a float
  add; nothing here is ever traced, so enabling observability cannot
  change a compiled program (asserted via jit cache sizes in
  tests/test_observe.py, the same way the resilience suite pins its
  zero-recompile contract);
* **labeled families** — ``registry.counter("bf_ops_total", op=...)``
  returns the per-label child; children are created on first touch and
  live for the process (Prometheus semantics);
* **windowed histograms** — percentiles (p50/p99 via
  :func:`percentile`) over the last ``window`` observations, because a
  serving dashboard wants *recent* tail latency, while ``count``/``sum``
  stay lifetime totals.

Publication is opt-out: ``BLUEFOG_OBSERVE=0`` makes every built-in
publisher skip the registry (and the tracer); see :func:`enabled`.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

__all__ = ["percentile", "enabled", "Counter", "Gauge", "Histogram",
           "MetricsRegistry", "get_registry"]


def percentile(values, q: float) -> float:
    """Linear-interpolation percentile (numpy's default); 0.0 on empty —
    summaries stay total-function even for a load that never finished a
    request.  (Promoted from ``serving/metrics.py``, which re-exports it
    for backward compatibility.)"""
    vals = [v for v in values if v is not None]
    if not vals:
        return 0.0
    return float(np.percentile(np.asarray(vals, np.float64), q))


def enabled() -> bool:
    """Whether the built-in publishers (serving engine, resilience
    runner, eager ops, train-step wrappers, timeline) write into the
    registry/tracer.  ``BLUEFOG_OBSERVE=0`` opts out; read dynamically
    so tests can flip it per-case.  Note this gates *publication* only:
    a registry you hold and update yourself always works.  (The env
    access itself lives in :func:`bluefog_tpu.config.observe_raw`;
    imported lazily — config comes up before the observe layer.)"""
    from bluefog_tpu import config as bfconfig

    return bfconfig.observe_raw()


LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, object]) -> LabelKey:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


class Counter:
    """Monotonic counter.  ``inc`` only; resets with its registry.
    Updates are locked: producers include multi-threaded callers (the
    handle API, per-thread tracer tracks), and an unlocked ``+=`` can
    lose increments between its load and store."""

    __slots__ = ("_value", "_lock")

    def __init__(self):
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counters only go up (inc by {amount})")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    """Last-write-wins instantaneous value (inc/dec locked, like
    :class:`Counter`)."""

    __slots__ = ("_value", "_lock")

    def __init__(self):
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value -= amount

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    """Windowed histogram: percentiles over the last ``window``
    observations, lifetime ``count``/``sum`` totals (observations
    locked, like :class:`Counter`)."""

    __slots__ = ("_window", "_count", "_sum", "_lock")

    def __init__(self, window: int = 2048):
        if window < 1:
            raise ValueError(f"window ({window}) must be >= 1")
        self._window: deque = deque(maxlen=window)
        self._count = 0
        self._sum = 0.0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        with self._lock:
            self._window.append(float(value))
            self._count += 1
            self._sum += float(value)

    def percentile(self, q: float) -> float:
        return percentile(self.window_values, q)

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    @property
    def window_values(self) -> List[float]:
        # copy under the lock: iterating a maxlen deque while a
        # producer appends raises "deque mutated during iteration" —
        # the scrape path must not crash under the load it observes
        with self._lock:
            return list(self._window)


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class MetricsRegistry:
    """Metric families keyed by ``(name, labels)``.

    The accessors (``counter``/``gauge``/``histogram``) create on first
    touch and return the existing child afterwards — call them on the
    hot path, there is no separate registration step.  A name is bound
    to ONE kind for the registry's lifetime (re-declaring
    ``bf_ops_total`` as a gauge raises), matching Prometheus's model.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._kinds: Dict[str, str] = {}
        self._help: Dict[str, str] = {}
        self._metrics: Dict[Tuple[str, LabelKey], object] = {}

    def _get(self, kind: str, name: str, help: str, window: Optional[int],
             labels: Dict[str, object]):
        key = (name, _label_key(labels))
        with self._lock:
            have = self._kinds.get(name)
            if have is None:
                self._kinds[name] = kind
                self._help[name] = help
            elif have != kind:
                raise ValueError(
                    f"metric {name!r} is already a {have}, not a {kind}")
            metric = self._metrics.get(key)
            if metric is None:
                # None -> default; 0 stays 0 so Histogram's own
                # window-validation ValueError is not masked
                metric = (Histogram(2048 if window is None else window)
                          if kind == "histogram" else _KINDS[kind]())
                self._metrics[key] = metric
            return metric

    def counter(self, name: str, help: str = "", **labels) -> Counter:
        return self._get("counter", name, help, None, labels)

    def gauge(self, name: str, help: str = "", **labels) -> Gauge:
        return self._get("gauge", name, help, None, labels)

    def histogram(self, name: str, help: str = "", window: int = 2048,
                  **labels) -> Histogram:
        return self._get("histogram", name, help, window, labels)

    def collect(self) -> Iterator[tuple]:
        """Yield ``(name, kind, help, labels_dict, metric)`` sorted by
        (name, labels) — the deterministic order the exporters emit."""
        with self._lock:
            items = sorted(self._metrics.items())
        for (name, lkey), metric in items:
            yield (name, self._kinds[name], self._help.get(name, ""),
                   dict(lkey), metric)

    def snapshot(self) -> dict:
        """``{name: [{"labels": {...}, ...values}]}`` — the structured
        (JSON-ready) view; histograms carry count/sum/p50/p99."""
        out: dict = {}
        for name, kind, _help, labels, m in self.collect():
            rec: dict = {"labels": labels}
            if kind == "histogram":
                rec.update(count=m.count, sum=m.sum,
                           p50=m.percentile(50), p99=m.percentile(99))
            else:
                rec["value"] = m.value
            out.setdefault(name, []).append(rec)
        return out

    def reset(self) -> None:
        """Drop every metric (tests; a long-lived process keeps its
        registry for life, Prometheus-style)."""
        with self._lock:
            self._kinds.clear()
            self._help.clear()
            self._metrics.clear()


_registry: Optional[MetricsRegistry] = None
_registry_lock = threading.Lock()


def get_registry() -> MetricsRegistry:
    """The process-global registry every built-in publisher writes to."""
    global _registry
    if _registry is None:
        with _registry_lock:
            if _registry is None:
                _registry = MetricsRegistry()
    return _registry
