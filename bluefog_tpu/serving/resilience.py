"""Serving-side fault tolerance: chaos injection, token-exact failover,
and the seeded backoff the router retries with.

The training stack proved the methodology (``resilience/faults.py``:
deterministic fault plans, shape-stable injection, machine-checked
chaos benches); this module is the serving twin.  Three pieces:

* :class:`FaultyReplica` — wraps one ``ServingEngine`` and injects a
  :class:`~bluefog_tpu.resilience.faults.ServingFaultPlan` AROUND its
  ``step``/``submit``: a dead replica stops stepping (its heartbeat
  gauge goes stale, the router's staleness guard excises it), a stalled
  one sleeps host time before stepping, a rejecting one raises
  :class:`RequestRejected` before the scheduler sees the submit.
  Everything is host-side control flow — the resident jitted programs
  and their cache sizes are identical under every fault pattern (the
  serving zero-recompile contract, asserted by the chaos bench).

* :func:`failover_stranded` — moves a dead replica's in-flight
  requests to survivors, token-exactly: each stranded request retires
  with outcome ``failover``, resets to QUEUED **keeping its emitted
  tokens**, and is resubmitted; the target replica re-prefills
  ``prompt ‖ tokens`` (chain-hash-matched chunks restore from the
  shared prefix cache, the novel tail computes cold) and its decode
  continues the per-request rng fold chain at ``len(tokens)`` — the
  resumed stream is bit-equal to a run that never faulted.  A request
  whose deadline passed while its replica was dead retires as
  ``expired`` instead (a terminal record, not a silent strand).

* :func:`seeded_backoff` / :func:`backoff_sleep` — the deterministic
  exponential-backoff-with-jitter every retry loop in this package must
  use (``bfcheck`` flags bare ``time.sleep`` retry loops under
  ``bluefog_tpu/serving/``): delays derive from (seed, salt, attempt),
  so chaos runs replay bit-identically.

Knobs: ``BLUEFOG_REPLICA_STALE_S``, ``BLUEFOG_ROUTER_RETRIES``,
``BLUEFOG_ROUTER_RETRY_BASE_S``, ``BLUEFOG_ROUTER_COOLDOWN_S`` (all via
:mod:`bluefog_tpu.config`).  Guide: docs/serving.md (failure model).
"""

from __future__ import annotations

import time
from typing import Callable, List, Optional, Tuple

import numpy as np

from bluefog_tpu.resilience.faults import ServingFaultPlan
from bluefog_tpu.serving.engine import (EXPIRED, FAILOVER, Request,
                                        ServingEngine)
from bluefog_tpu.serving.scheduler import RequestRejected

__all__ = ["FaultyReplica", "failover_stranded", "seeded_backoff",
           "backoff_sleep"]


def seeded_backoff(attempt: int, *, base: float = 0.05, cap: float = 2.0,
                   seed: int = 0, salt: int = 0) -> float:
    """Deterministic exponential backoff with jitter: attempt ``k``
    yields ``min(cap, base * 2**k * jitter)`` with ``jitter`` drawn
    uniformly from [0.5, 1.5) by a RandomState keyed on (seed, salt,
    attempt) — two routers with the same seed retrying the same request
    sleep the same schedule, so chaos runs replay exactly."""
    if attempt < 0:
        raise ValueError(f"attempt must be >= 0, got {attempt}")
    rs = np.random.RandomState(
        (seed * 1_000_003 + salt * 9_176 + attempt * 31) % (2 ** 32))
    jitter = 0.5 + rs.random_sample()
    return float(min(cap, base * (2.0 ** attempt) * jitter))


def backoff_sleep(attempt: int, *, base: float = 0.05, cap: float = 2.0,
                  seed: int = 0, salt: int = 0,
                  sleep: Optional[Callable[[float], None]] = None
                  ) -> float:
    """Sleep one :func:`seeded_backoff` delay (injectable ``sleep`` —
    the virtual-time bench passes its clock's advance) and return it."""
    delay = seeded_backoff(attempt, base=base, cap=cap, seed=seed,
                           salt=salt)
    (sleep if sleep is not None else time.sleep)(delay)
    return delay


class FaultyReplica:
    """One serving replica under a deterministic fault plan.

    Wraps a :class:`ServingEngine` (attribute access passes through, so
    the router and the fleet harness treat it as the engine) and applies
    ``plan``'s faults for ``replica`` keyed on the replica's OWN step
    counter:

    * ``replica_death`` at step s: from the s-th :meth:`step` call on,
      the replica never steps again (``step`` returns False without
      touching the engine) and refuses submits — the process is gone;
      its last-step heartbeat freezes and the router's staleness guard
      marks it suspect.  ``dead`` latches True so the harness can see
      the transition and trigger :func:`failover_stranded`.
    * ``replica_stall``: sleeps ``stall_seconds`` of host time before
      each active step (the replica is slow, not gone).
    * ``submit_reject``: every submit landing during the fault window
      raises :class:`RequestRejected` before the engine sees it — the
      transient-overload input the router's retry/backoff absorbs.
    """

    def __init__(self, engine: ServingEngine, plan: ServingFaultPlan,
                 replica: int, *,
                 sleep: Optional[Callable[[float], None]] = None):
        if not 0 <= replica < plan.size:
            raise ValueError(f"replica {replica} outside plan of size "
                             f"{plan.size}")
        self.engine = engine
        self.plan = plan
        self.replica = replica
        self.steps = 0
        self.dead = False
        self._sleep = sleep if sleep is not None else time.sleep

    def __getattr__(self, name):
        return getattr(self.engine, name)

    def submit(self, request: Request) -> Request:
        sched = self.engine.scheduler
        if self.dead or self.plan.is_dead(self.replica, self.steps):
            self.dead = True
            raise RequestRejected(f"replica {self.replica} dead",
                                  queue_depth=sched.queue_depth,
                                  max_queue=sched.max_queue)
        if self.plan.rejects_submit(self.replica, self.steps):
            raise RequestRejected(
                f"replica {self.replica} injected submit rejection",
                queue_depth=sched.queue_depth,
                max_queue=sched.max_queue)
        return self.engine.submit(request)

    def step(self) -> bool:
        if self.dead or self.plan.is_dead(self.replica, self.steps):
            self.dead = True
            return False
        stall = self.plan.stall_seconds(self.replica, self.steps)
        if stall > 0:
            self._sleep(stall)
        out = self.engine.step()
        self.steps += 1
        return out


def failover_stranded(engine, resubmit: Callable[[Request], object], *,
                      now: Optional[float] = None
                      ) -> Tuple[List[Request], List[Request]]:
    """Move a dead replica's stranded requests to survivors.

    ``engine`` may be the :class:`ServingEngine` or its
    :class:`FaultyReplica` wrapper.  Every resident (mid-prefill or
    decoding, in slot order) and every queued request is given a
    terminal outcome on the dead replica:

    * deadline already passed -> retired with outcome ``expired`` (the
      satellite guarantee: a request that died WITH its replica still
      emits a terminal timeline span and a retired counter);
    * otherwise -> retired with outcome ``failover``, reset to QUEUED
      with its emitted tokens kept, and handed to ``resubmit`` (usually
      ``FleetRouter.submit``) — replay via the prefix-cache chain-hash
      path makes the resumed output bit-equal to an unfaulted run.

    Unlike :meth:`ServingEngine.drain`, nothing is flushed to the
    prefix cache here: the dead replica's device K/V is gone by
    definition — replay relies on the chunks the ORIGINAL prefill
    stashed into the shared cache, plus cold compute for the tail.

    Returns ``(moved, expired)`` request lists.
    """
    eng = getattr(engine, "engine", engine)
    if now is None:
        now = eng.clock()
    stranded = sorted(eng._running.values(), key=lambda r: r.slot)
    if eng._admitting is not None:
        stranded = sorted(stranded + [eng._admitting],
                          key=lambda r: r.slot)
    stranded += eng.scheduler.drain()
    moved: List[Request] = []
    expired: List[Request] = []
    for req in stranded:
        if req.deadline is not None and now >= req.deadline:
            eng._retire(req, EXPIRED, now)
            expired.append(req)
            continue
        eng._retire(req, FAILOVER, now)
        eng.metrics.on_failover(req.rid, now)
        req.reset_for_resume()
        resubmit(req)
        moved.append(req)
    if stranded:
        from bluefog_tpu.observe.blackbox import record_decision

        record_decision(
            "serving", "failover", step=-1,
            telemetry={"moved": len(moved), "expired": len(expired)})
    return moved, expired
