"""Request admission for the serving engine: FIFO + backpressure +
deadlines.

Policy (deliberately boring — the measurable wins live in the engine's
batching, not in clever queueing):

* **FIFO admission.**  Requests are admitted to K/V slots in arrival
  order; nothing overtakes (so TTFT percentiles reflect load, not luck).
* **Backpressure, not stalls.**  A full slot pool queues the request; a
  full queue REJECTS the submit immediately with the current queue depth
  attached (:class:`RequestRejected`) — the graceful-degradation policy:
  a loaded server tells callers to back off rather than accumulating
  unbounded latency.
* **Deadlines.**  A request may carry an absolute deadline (engine-clock
  seconds).  Expired queued requests are dropped at admission time;
  expired RUNNING requests are cancelled by the engine between decode
  steps.  Explicit :meth:`cancel` works on both.

The scheduler owns no device state and never touches jax — it is plain
host bookkeeping the engine consults once per step.
"""

from __future__ import annotations

from collections import deque
from typing import Deque, List, Optional

__all__ = ["FifoScheduler", "RequestRejected"]


class RequestRejected(RuntimeError):
    """Submit refused under overload.  Carries the backpressure signal a
    client needs to back off intelligently."""

    def __init__(self, msg: str, queue_depth: int, max_queue: int):
        super().__init__(f"{msg} (queue depth {queue_depth}/{max_queue})")
        self.queue_depth = queue_depth
        self.max_queue = max_queue


class FifoScheduler:
    def __init__(self, max_queue: int = 64):
        if max_queue < 0:
            raise ValueError(f"max_queue ({max_queue}) must be >= 0")
        self.max_queue = max_queue
        self._queue: Deque = deque()

    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    def submit(self, request) -> None:
        """Enqueue, or raise :class:`RequestRejected` when the queue is
        at capacity (never blocks, never silently drops)."""
        if len(self._queue) >= self.max_queue:
            raise RequestRejected("serving queue full",
                                  queue_depth=len(self._queue),
                                  max_queue=self.max_queue)
        self._queue.append(request)

    def cancel(self, request) -> bool:
        """Remove a queued request; returns False if it is not queued
        (already admitted — the engine handles running cancellations)."""
        try:
            self._queue.remove(request)
            return True
        except ValueError:
            return False

    def expire(self, now: float) -> List:
        """Drop and return every queued request whose deadline has
        passed — a request that cannot start before its deadline is dead
        weight; shedding it in the queue costs zero device time."""
        expired = [r for r in self._queue
                   if r.deadline is not None and now >= r.deadline]
        for r in expired:
            self._queue.remove(r)
        return expired

    def drain(self) -> List:
        """Remove and return EVERY queued request, deadline-expired ones
        included — unlike :meth:`admit`, which silently sheds expired
        entries, drain/failover must see them all so each gets a
        terminal outcome (handed off, rejected, or expired)."""
        out = list(self._queue)
        self._queue.clear()
        return out

    def admit(self, now: float) -> Optional[object]:
        """Pop the next admissible request (FIFO after shedding expired
        ones), or ``None`` when the queue is empty.  The caller admits
        only while it has a free slot."""
        self.expire(now)
        if not self._queue:
            return None
        return self._queue.popleft()
