"""Serving metrics + request-lifecycle timeline spans.

Numbers a serving operator actually pages on:

* **TTFT** (time to first token): submit -> first generated token, the
  user-visible latency of the prefill path + queueing.
* **Request latency**: submit -> retire.
* **Aggregate tokens/s**: generated tokens over the serving window — the
  throughput continuous batching exists to maximize.
* **Slot occupancy / queue depth**: sampled once per engine step; low
  occupancy under load means admission is the bottleneck, deep queues
  mean capacity is.

Lifecycle spans go through the existing :mod:`bluefog_tpu.timeline`
writer (same chrome://tracing file format as the op-level spans), one
track per request: ``admission -> prefill -> decode -> retire``.  Load a
timeline in chrome://tracing and the continuous-batching interleaving is
visible directly — staggered prefills riding between decode steps.

All timestamps come from the engine's injected clock, so tests drive
virtual time and percentiles are deterministic.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from bluefog_tpu import timeline as timeline_mod

__all__ = ["ServingMetrics", "percentile"]


def percentile(values, q: float) -> float:
    """Linear-interpolation percentile (numpy's default); 0.0 on empty —
    summaries stay total-function even for a load that never finished a
    request."""
    vals = [v for v in values if v is not None]
    if not vals:
        return 0.0
    return float(np.percentile(np.asarray(vals, np.float64), q))


class _RequestRecord:
    __slots__ = ("submit_t", "admit_t", "first_token_t", "finish_t",
                 "n_tokens", "outcome")

    def __init__(self, submit_t: float):
        self.submit_t = submit_t
        self.admit_t: Optional[float] = None
        self.first_token_t: Optional[float] = None
        self.finish_t: Optional[float] = None
        self.n_tokens = 0
        self.outcome: Optional[str] = None


class ServingMetrics:
    def __init__(self):
        self._req: Dict[object, _RequestRecord] = {}
        self._occupancy: List[float] = []
        self._queue_depth: List[int] = []
        self.n_rejected = 0

    # -- timeline plumbing -------------------------------------------- #
    def _span(self, rid, activity: Optional[str]):
        """Close the request's open span and (unless retiring) open the
        next lifecycle phase on its per-request track."""
        tl = timeline_mod.get_timeline()
        if tl is None:
            return
        track = f"request.{rid}"
        tl.end_activity(track)
        if activity is not None:
            tl.start_activity(track, activity)

    # -- lifecycle events (engine calls these) ------------------------ #
    def on_submit(self, rid, now: float):
        self._req[rid] = _RequestRecord(now)
        tl = timeline_mod.get_timeline()
        if tl is not None:
            tl.start_activity(f"request.{rid}", "admission")

    def on_reject(self, rid, now: float):
        self.n_rejected += 1

    def on_admit(self, rid, now: float):
        self._req[rid].admit_t = now
        self._span(rid, "prefill")

    def on_first_token(self, rid, now: float):
        rec = self._req[rid]
        rec.first_token_t = now
        rec.n_tokens += 1
        self._span(rid, "decode")

    def on_token(self, rid, now: float):
        self._req[rid].n_tokens += 1

    def on_retire(self, rid, now: float, outcome: str):
        rec = self._req[rid]
        rec.finish_t = now
        rec.outcome = outcome
        self._span(rid, "retire")
        self._span(rid, None)
        tl = timeline_mod.get_timeline()
        if tl is not None:
            tl.instant(f"request.{rid}.{outcome}")

    def on_step(self, occupancy: float, queue_depth: int):
        self._occupancy.append(occupancy)
        self._queue_depth.append(queue_depth)

    # -- summaries ----------------------------------------------------- #
    def ttfts(self) -> List[float]:
        return [r.first_token_t - r.submit_t for r in self._req.values()
                if r.first_token_t is not None]

    def latencies(self) -> List[float]:
        return [r.finish_t - r.submit_t for r in self._req.values()
                if r.finish_t is not None]

    def summary(self) -> dict:
        """One dict with the operator dashboard: percentile latencies,
        aggregate tokens/s over the active window, mean occupancy/queue
        depth, and outcome counts."""
        recs = list(self._req.values())
        finished = [r for r in recs if r.finish_t is not None]
        tokens = sum(r.n_tokens for r in recs)
        if finished:
            t0 = min(r.submit_t for r in recs)
            t1 = max(r.finish_t for r in finished)
            window = max(t1 - t0, 1e-12)
        else:
            window = 0.0
        outcomes: Dict[str, int] = {}
        for r in recs:
            if r.outcome:
                outcomes[r.outcome] = outcomes.get(r.outcome, 0) + 1
        ttft = self.ttfts()
        lat = self.latencies()
        return {
            "n_requests": len(recs),
            "n_finished": len(finished),
            "n_rejected": self.n_rejected,
            "outcomes": outcomes,
            "tokens_generated": tokens,
            "tokens_per_sec": (tokens / window) if window else 0.0,
            "ttft_p50": percentile(ttft, 50),
            "ttft_p99": percentile(ttft, 99),
            "latency_p50": percentile(lat, 50),
            "latency_p99": percentile(lat, 99),
            "mean_slot_occupancy": (float(np.mean(self._occupancy))
                                    if self._occupancy else 0.0),
            "mean_queue_depth": (float(np.mean(self._queue_depth))
                                 if self._queue_depth else 0.0),
            "max_queue_depth": (int(np.max(self._queue_depth))
                                if self._queue_depth else 0),
        }
