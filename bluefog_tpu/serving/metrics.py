"""Serving metrics + request-lifecycle spans, on the observe substrate.

Numbers a serving operator actually pages on:

* **TTFT** (time to first token): submit -> first generated token, the
  user-visible latency of the prefill path + queueing.
* **Request latency**: submit -> retire.
* **Aggregate tokens/s**: generated tokens over the serving window — the
  throughput continuous batching exists to maximize.
* **Slot occupancy / queue depth**: sampled once per engine step; low
  occupancy under load means admission is the bottleneck, deep queues
  mean capacity is.

Everything is published twice, through the unified observability layer
(:mod:`bluefog_tpu.observe`):

* the :class:`~bluefog_tpu.observe.registry.MetricsRegistry` —
  counters (``bf_serving_requests_total``,
  ``bf_serving_retired_total{outcome=}``), windowed histograms
  (``bf_serving_ttft_seconds``, ``bf_serving_latency_seconds``), and
  per-step gauges, scrapeable as Prometheus text;
* the :class:`~bluefog_tpu.observe.tracer.Tracer` — one track per
  request (``admission -> prefill -> decode -> retire``), which the
  Chrome-trace timeline exports when started: load a timeline in
  chrome://tracing and the continuous-batching interleaving is visible
  directly — staggered prefills riding between decode steps.

``summary()`` keeps its original dict shape (the operator dashboard the
serving tests and bench consume); ``BLUEFOG_OBSERVE=0`` stops the
registry/tracer publication while leaving the summary intact.

All timestamps come from the engine's injected clock, so tests drive
virtual time and percentiles are deterministic.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from bluefog_tpu import timeline as timeline_mod
from bluefog_tpu.observe import registry as obs_registry
from bluefog_tpu.observe import tracer as obs_tracer
from bluefog_tpu.observe.registry import percentile  # noqa: F401  (moved
# to observe/registry.py; re-exported here for backward compatibility)

__all__ = ["ServingMetrics", "percentile"]


class _RequestRecord:
    __slots__ = ("submit_t", "admit_t", "first_token_t", "finish_t",
                 "n_tokens", "outcome", "tracer")

    def __init__(self, submit_t: float, tracer=None):
        self.submit_t = submit_t
        self.admit_t: Optional[float] = None
        self.first_token_t: Optional[float] = None
        self.finish_t: Optional[float] = None
        self.n_tokens = 0
        self.outcome: Optional[str] = None
        # the tracer the request's spans BEGAN on, pinned at submit: a
        # BLUEFOG_OBSERVE flip or timeline stop mid-request must not
        # send the closing E records to a different tracer than the Bs
        # (same policy as context._timeline_open)
        self.tracer = tracer


class ServingMetrics:
    """Per-engine request records + publication into the global
    registry/tracer (opt out with ``BLUEFOG_OBSERVE=0``; pass an
    explicit ``registry=`` to isolate, e.g. per-test)."""

    def __init__(self, registry=None):
        self._req: Dict[object, _RequestRecord] = {}
        self._occupancy: List[float] = []
        self._queue_depth: List[int] = []
        self.n_rejected = 0
        self.n_failovers = 0
        self.last_step_ts: Optional[float] = None
        self._registry = registry
        # prefix-cache / prefill accounting
        self.n_prefill_chunks = 0
        self.n_prefix_chunks_restored = 0
        self.n_prefix_tokens_restored = 0
        # speculative decoding accounting
        self.n_spec_steps = 0
        self.n_spec_active = 0
        self.n_spec_emitted = 0

    # -- observe plumbing --------------------------------------------- #
    def _reg(self):
        if self._registry is not None:
            return self._registry
        if not obs_registry.enabled():
            return None
        return obs_registry.get_registry()

    def _tracer(self):
        return obs_tracer.effective_tracer(timeline_mod.get_timeline())

    def _span(self, rid, activity: Optional[str]):
        """Close the request's open span and (unless retiring) open the
        next lifecycle phase on its per-request track — on the tracer
        the request's spans began on."""
        rec = self._req.get(rid)
        tr = rec.tracer if rec is not None else None
        if tr is None:
            return
        track = f"request.{rid}"
        tr.end(track)
        if activity is not None:
            tr.begin(track, activity)

    # -- lifecycle events (engine calls these) ------------------------ #
    def on_submit(self, rid, now: float):
        tr = self._tracer()
        self._req[rid] = _RequestRecord(now, tracer=tr)
        if tr is not None:
            tr.begin(f"request.{rid}", "admission")
        reg = self._reg()
        if reg is not None:
            reg.counter("bf_serving_requests_total",
                        "requests submitted").inc()

    def on_reject(self, rid, now: float):
        self.n_rejected += 1
        reg = self._reg()
        if reg is not None:
            reg.counter("bf_serving_rejected_total",
                        "requests refused (backpressure or too long)").inc()

    def on_admit(self, rid, now: float):
        self._req[rid].admit_t = now
        self._span(rid, "prefill")

    def on_first_token(self, rid, now: float):
        rec = self._req[rid]
        rec.first_token_t = now
        rec.n_tokens += 1
        self._span(rid, "decode")
        reg = self._reg()
        if reg is not None:
            reg.histogram("bf_serving_ttft_seconds",
                          "submit -> first token").observe(
                              now - rec.submit_t)
            reg.counter("bf_serving_tokens_total",
                        "tokens generated").inc()

    def on_token(self, rid, now: float):
        self._req[rid].n_tokens += 1
        reg = self._reg()
        if reg is not None:
            reg.counter("bf_serving_tokens_total",
                        "tokens generated").inc()

    def on_retire(self, rid, now: float, outcome: str):
        rec = self._req[rid]
        rec.finish_t = now
        rec.outcome = outcome
        self._span(rid, "retire")
        self._span(rid, None)
        tr = rec.tracer
        if tr is not None:
            tr.instant(f"request.{rid}.{outcome}")
        reg = self._reg()
        if reg is not None:
            reg.counter("bf_serving_retired_total",
                        "requests retired", outcome=outcome).inc()
            reg.histogram("bf_serving_latency_seconds",
                          "submit -> retire").observe(now - rec.submit_t)

    def on_failover(self, rid, now: float):
        """``rid`` was handed off to another replica (replica death or
        graceful drain) — it retired HERE with outcome ``failover`` and
        resumes elsewhere with its tokens intact."""
        self.n_failovers += 1
        rec = self._req.get(rid)
        tr = rec.tracer if rec is not None else None
        if tr is not None:
            tr.instant(f"request.{rid}.failover")
        reg = self._reg()
        if reg is not None:
            reg.counter("bf_serving_failovers_total",
                        "requests handed off to another replica").inc()

    def on_prefill_chunk(self):
        """One cold prefill chunk ran (a model forward over one chunk).
        Together with :meth:`on_prefix_restore` this splits prompt
        coverage into compute vs copy."""
        self.n_prefill_chunks += 1
        reg = self._reg()
        if reg is not None:
            reg.counter("bf_serving_prefill_chunks_total",
                        "cold prefill chunks computed").inc()

    def on_prefix_restore(self, rid, n_chunks: int, n_tokens: int):
        """``n_chunks`` cached K/V chunks (``n_tokens`` prompt tokens)
        were copied into ``rid``'s slot instead of being prefilled."""
        if n_chunks <= 0:
            return
        self.n_prefix_chunks_restored += n_chunks
        self.n_prefix_tokens_restored += n_tokens
        rec = self._req.get(rid)
        tr = rec.tracer if rec is not None else None
        if tr is not None:
            tr.instant(f"request.{rid}.prefix_restore[{n_chunks}]")
        reg = self._reg()
        if reg is not None:
            reg.counter("bf_serving_prefix_chunks_restored_total",
                        "prompt chunks admitted from the prefix cache"
                        ).inc(n_chunks)
            reg.counter("bf_serving_prefix_tokens_restored_total",
                        "prompt tokens admitted from the prefix cache"
                        ).inc(n_tokens)

    def on_spec_step(self, n_active: int, n_emitted: int):
        """One speculative decode step over ``n_active`` slots emitted
        ``n_emitted`` tokens total (per-token accounting still flows
        through ``on_first_token``/``on_token``; this records the
        accepted-tokens-per-step ratio speculation is judged by)."""
        self.n_spec_steps += 1
        self.n_spec_active += n_active
        self.n_spec_emitted += n_emitted
        reg = self._reg()
        if reg is not None:
            reg.counter("bf_serving_spec_steps_total",
                        "speculative decode steps").inc()
            reg.counter("bf_serving_spec_emitted_total",
                        "tokens emitted by speculative steps"
                        ).inc(n_emitted)
            if n_active:
                reg.gauge("bf_serving_spec_accepted_per_step",
                          "tokens emitted per active slot, last step"
                          ).set(n_emitted / n_active)

    def on_step(self, occupancy: float, queue_depth: int,
                step_seconds: Optional[float] = None,
                now: Optional[float] = None):
        self._occupancy.append(occupancy)
        self._queue_depth.append(queue_depth)
        if now is not None:
            # the replica's liveness heartbeat (engine-clock seconds):
            # the fleet router's staleness guard compares this against
            # its own clock — a replica that stops stepping stops
            # advancing it and goes suspect after BLUEFOG_REPLICA_STALE_S
            self.last_step_ts = now
        reg = self._reg()
        if reg is not None:
            reg.counter("bf_serving_steps_total", "engine steps").inc()
            reg.gauge("bf_serving_slot_occupancy",
                      "active slots / capacity, last step").set(occupancy)
            reg.gauge("bf_serving_queue_depth",
                      "queued requests, last step").set(queue_depth)
            if now is not None:
                reg.gauge("bf_serving_last_step_ts",
                          "engine-clock time of the last step").set(now)
            if step_seconds is not None:
                # the engine's measured step wall time, in the SAME
                # histogram family the train loop reports into — the
                # per-rank step-time signal the fleet gossip
                # (observe.fleet.collect_local) aggregates
                reg.histogram("bf_step_wall_seconds",
                              "train/engine step wall time",
                              loop="serving").observe(step_seconds)

    # -- summaries ----------------------------------------------------- #
    def ttfts(self) -> List[float]:
        return [r.first_token_t - r.submit_t for r in self._req.values()
                if r.first_token_t is not None]

    def latencies(self) -> List[float]:
        return [r.finish_t - r.submit_t for r in self._req.values()
                if r.finish_t is not None]

    def summary(self) -> dict:
        """One dict with the operator dashboard: percentile latencies,
        aggregate tokens/s over the active window, mean occupancy/queue
        depth, and outcome counts."""
        recs = list(self._req.values())
        finished = [r for r in recs if r.finish_t is not None]
        tokens = sum(r.n_tokens for r in recs)
        if finished:
            t0 = min(r.submit_t for r in recs)
            t1 = max(r.finish_t for r in finished)
            window = max(t1 - t0, 1e-12)
        else:
            window = 0.0
        outcomes: Dict[str, int] = {}
        for r in recs:
            if r.outcome:
                outcomes[r.outcome] = outcomes.get(r.outcome, 0) + 1
        ttft = self.ttfts()
        lat = self.latencies()
        prefix_total = self.n_prefill_chunks + self.n_prefix_chunks_restored
        return {
            "n_requests": len(recs),
            "n_finished": len(finished),
            "n_rejected": self.n_rejected,
            "n_failovers": self.n_failovers,
            "outcomes": outcomes,
            "tokens_generated": tokens,
            "tokens_per_sec": (tokens / window) if window else 0.0,
            "ttft_p50": percentile(ttft, 50),
            "ttft_p99": percentile(ttft, 99),
            "latency_p50": percentile(lat, 50),
            "latency_p99": percentile(lat, 99),
            "mean_slot_occupancy": (float(np.mean(self._occupancy))
                                    if self._occupancy else 0.0),
            "mean_queue_depth": (float(np.mean(self._queue_depth))
                                 if self._queue_depth else 0.0),
            "max_queue_depth": (int(np.max(self._queue_depth))
                                if self._queue_depth else 0),
            "prefill_chunks": self.n_prefill_chunks,
            "prefix_chunks_restored": self.n_prefix_chunks_restored,
            "prefix_tokens_restored": self.n_prefix_tokens_restored,
            # restored / (restored + computed): how much prompt coverage
            # the prefix cache turned from forwards into copies
            "prefix_hit_rate": ((self.n_prefix_chunks_restored
                                 / prefix_total) if prefix_total else 0.0),
            "spec_steps": self.n_spec_steps,
            # tokens emitted per active slot-step: > 1 means speculation
            # is paying for its draft passes
            "accepted_per_step": ((self.n_spec_emitted
                                   / self.n_spec_active)
                                  if self.n_spec_active else 0.0),
        }
