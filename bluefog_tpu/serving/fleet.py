"""Decentralized multi-replica request routing over gossiped gauges.

A fleet of :class:`~bluefog_tpu.serving.ServingEngine` replicas needs a
way to spread load, and the paper's whole premise is that coordination
does not require a center: just as training averages parameters by
push-sum over a sparse topology instead of an allreduce, the fleet
spreads requests by GOSSIPING each replica's serving gauges — slot
occupancy, queue depth, TTFT p50 — through
:class:`bluefog_tpu.observe.fleet.FleetAggregator` and letting every
participant rank replicas from its own converged view.  There is no
load-balancer process to deploy, scale, or lose.

The per-replica signals survive the mean-reducing gossip through the
ONE-HOT BLOCK layout: replica *i* contributes a ``[n*k]`` row that is
zero outside its own ``k``-wide block.  Push-sum converges every column
to its live mean, so column ``i*k + m`` lands at ``signal[i, m] /
n_live`` everywhere — multiplying back by the live count recovers the
full ``[n, k]`` signal matrix at EVERY rank, exactly (the de-biased
push-sum fixed point), at the cost of gossiping ``n*k`` scalars instead
of ``k``.  Fine for fleet-sized ``n``.

Routing is then pure local arithmetic on the snapshot: replicas are
ranked by a weighted score (queue depth dominates, then occupancy, then
normalized TTFT; index breaks ties) and :meth:`FleetRouter.submit`
walks that order, letting each replica's own
:class:`~bluefog_tpu.serving.RequestRejected` backpressure stand — a
replica never takes a request its queue cannot hold.  When every
replica refuses, :class:`FleetSaturated` (a ``RequestRejected``
subclass, so existing client backoff code keeps working) carries all
the per-replica depths.

Determinism: routing decisions are a pure function of the snapshot, and
the snapshot is a pure function of the registries and the topology
schedule — no RNG, no wall clock — so two routers over the same state
route identically (property-tested).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from bluefog_tpu import config as bfconfig
from bluefog_tpu.observe.fleet import FleetAggregator
from bluefog_tpu.serving.scheduler import RequestRejected

__all__ = ["FleetRouter", "FleetSaturated", "RouterSnapshot",
           "collect_serving_signals", "SIGNAL_NAMES"]

# gossiped per-replica serving signals, in block order
SIGNAL_NAMES = ("occupancy", "queue_depth", "ttft_p50")


def collect_serving_signals(registry) -> Dict[str, float]:
    """Scrape one replica's routing signals out of its (isolated)
    metrics registry: the ``bf_serving_slot_occupancy`` /
    ``bf_serving_queue_depth`` gauges the engine sets every step and the
    ``bf_serving_ttft_seconds`` windowed-histogram p50.  Zeros where the
    engine has not published yet — a fresh replica looks maximally
    attractive, which is the right cold-start bias.  ``last_step_ts``
    is the replica's liveness heartbeat (``bf_serving_last_step_ts``,
    engine-clock seconds; -1.0 before the first step) — the staleness
    guard's input, so the router never scores a replica on gauges it
    stopped updating."""
    occupancy = 0.0
    queue_depth = 0.0
    ttft_p50 = 0.0
    last_step_ts = -1.0
    for name, kind, _help, _labels, m in registry.collect():
        if name == "bf_serving_slot_occupancy" and kind == "gauge":
            occupancy = float(m.value)
        elif name == "bf_serving_queue_depth" and kind == "gauge":
            queue_depth = float(m.value)
        elif name == "bf_serving_ttft_seconds" and kind == "histogram":
            ttft_p50 = float(m.percentile(50))
        elif name == "bf_serving_last_step_ts" and kind == "gauge":
            last_step_ts = float(m.value)
    return {"occupancy": occupancy, "queue_depth": queue_depth,
            "ttft_p50": ttft_p50, "last_step_ts": last_step_ts}


class FleetSaturated(RequestRejected):
    """Every live replica refused the request.  ``queue_depths[i]`` is
    the depth each rejecting replica reported — the fleet-wide
    backpressure picture, for clients that scale their backoff.
    ``causes`` keeps the walk's evidence: ``(replica_index, exception)``
    per refusal, across every retry attempt — an operator debugging a
    saturation event sees WHICH replica said WHAT instead of a bare
    count."""

    def __init__(self, queue_depths: Sequence[int], max_queue: int,
                 causes: Optional[Sequence] = None):
        depths = [int(d) for d in queue_depths]
        super().__init__(
            f"all {len(depths)} replicas at capacity "
            f"(queue depths {depths})",
            queue_depth=max(depths) if depths else 0,
            max_queue=max_queue)
        self.queue_depths = depths
        self.causes = list(causes or [])


@dataclasses.dataclass(frozen=True)
class RouterSnapshot:
    """One routing view: ``signals[i]`` is replica *i*'s
    ``(occupancy, queue_depth, ttft_p50)`` as recovered from gossip,
    ``scores`` the router's ranking key (lower routes first), ``order``
    the resulting replica preference, and ``rounds``/``spread`` the
    gossip's convergence record (0/0.0 for a single replica, which
    bypasses gossip entirely).  ``ages[i]`` is seconds since replica
    *i* last published a step heartbeat (-1.0 if it never has);
    ``suspect[i]`` is the staleness verdict — age beyond
    ``BLUEFOG_REPLICA_STALE_S`` — that excised the replica from this
    snapshot's scoring."""

    signals: np.ndarray
    scores: np.ndarray
    order: tuple
    rounds: int
    spread: float
    ages: tuple = ()
    suspect: tuple = ()

    def as_dict(self) -> Dict[str, List[float]]:
        out = {name: [float(v) for v in self.signals[:, m]]
               for m, name in enumerate(SIGNAL_NAMES)}
        out["ages"] = [float(a) for a in self.ages]
        return out


class FleetRouter:
    """Spread requests over ``engines`` by their gossiped gauges.

    Args:
      engines: the replica :class:`ServingEngine` list.  Each replica
        should carry its OWN metrics registry (``ServingEngine(...,
        registry=MetricsRegistry())``) — the router scrapes signals
        per-replica, and a shared global registry would alias them.
      registries: the per-replica registries to scrape.  Defaults to
        each engine's ``metrics`` registry.
      schedule: gossip topology schedule (anything
        :class:`FleetAggregator` accepts).  Defaults to the static
        exponential-two graph over ``len(engines)`` ranks — the same
        default sparse topology the training side mixes over.  Ignored
        (no gossip at all) for a single replica.
      rank: which replica's converged view this router reads and
        publishes.  Any rank works — convergence makes the views agree
        to ``tol`` — but a real deployment runs one router per replica,
        each reading its own rank.
      registry: where :meth:`publish` lands ``bf_fleet_serving_*``
        gauges (default: the global registry via the aggregator).
      weights: score weights for ``(occupancy, queue_depth, ttft_p50)``.
        Queue depth dominates by default: a queued request waits a full
        drain, occupancy only predicts the NEXT rejection, and TTFT is
        a tiebreaker-grade signal (normalized by the fleet max).
      stale_after: staleness window in seconds (default
        ``BLUEFOG_REPLICA_STALE_S``; 0 disables).  A replica whose last
        step heartbeat is older than this is *suspect*: its gossip row
        is masked and its score pinned to +inf, exactly the dead-mask
        path — and it is re-admitted the moment it steps again.
        Replicas that have NEVER stepped are exempt (cold replicas must
        stay routable).
      retries: extra full-fleet submit walks after the first exhausts
        every live replica (default ``BLUEFOG_ROUTER_RETRIES`` = 0, the
        historical single-walk behavior), separated by seeded
        exponential backoff and a fresh poll.
      retry_base_s: backoff base delay (default
        ``BLUEFOG_ROUTER_RETRY_BASE_S``).
      cooldown_s: after ``cooldown_after`` consecutive rejections from
        one replica, demote it to the BACK of the walk for this long
        (default ``BLUEFOG_ROUTER_COOLDOWN_S`` = 0, off).  Cooldown
        only re-orders — a cooling replica is still tried last, so it
        can never manufacture a ``FleetSaturated`` by itself.
      seed: backoff determinism seed (delays derive from
        ``(seed, request.rid, attempt)``).
      clock: staleness/cooldown clock.  Defaults to the first engine's
        injected clock, so virtual-time fleets age virtually.
      sleep: backoff sleep callable (default ``time.sleep``; the
        virtual-time bench passes its clock's advance).
    """

    def __init__(self, engines: Sequence, *,
                 registries: Optional[Sequence] = None,
                 schedule=None, rank: int = 0,
                 tol: float = 1e-13, registry=None,
                 weights: Sequence[float] = (1.0, 4.0, 0.5),
                 stale_after: Optional[float] = None,
                 retries: Optional[int] = None,
                 retry_base_s: Optional[float] = None,
                 cooldown_s: Optional[float] = None,
                 cooldown_after: int = 3, seed: int = 0,
                 clock: Optional[Callable[[], float]] = None,
                 sleep: Optional[Callable[[float], None]] = None,
                 blackbox=None):
        if not engines:
            raise ValueError("FleetRouter needs at least one engine")
        self.engines = list(engines)
        if registries is None:
            registries = [e.metrics._registry for e in self.engines]
            if any(r is None for r in registries):
                raise ValueError(
                    "replica engines share the global registry; build "
                    "each with its own (ServingEngine(..., "
                    "registry=MetricsRegistry())) or pass registries=")
        if len(registries) != len(self.engines):
            raise ValueError(
                f"{len(registries)} registries for "
                f"{len(self.engines)} engines")
        self.registries = list(registries)
        self.rank = int(rank)
        if not (0 <= self.rank < len(self.engines)):
            raise ValueError(f"rank {rank} outside fleet of "
                             f"{len(self.engines)}")
        if len(weights) != len(SIGNAL_NAMES):
            raise ValueError(f"need {len(SIGNAL_NAMES)} score weights")
        self.weights = tuple(float(w) for w in weights)
        n = len(self.engines)
        self._agg = None
        if n > 1:
            if schedule is None:
                from bluefog_tpu.topology import (ExponentialTwoGraph,
                                                  uniform_topology_spec)

                schedule = uniform_topology_spec(ExponentialTwoGraph(n))
            self._agg = FleetAggregator(schedule, tol=tol,
                                        rank=self.rank,
                                        registry=registry)
            if self._agg.size != n:
                raise ValueError(
                    f"gossip schedule of size {self._agg.size} against "
                    f"{n} replicas")
        self._registry = registry
        self.stale_after = float(bfconfig.replica_stale_s()
                                 if stale_after is None else stale_after)
        self.retries = int(bfconfig.router_retries()
                           if retries is None else retries)
        self.retry_base_s = float(bfconfig.router_retry_base_s()
                                  if retry_base_s is None
                                  else retry_base_s)
        self.cooldown_s = float(bfconfig.router_cooldown_s()
                                if cooldown_s is None else cooldown_s)
        self.cooldown_after = int(cooldown_after)
        self.seed = int(seed)
        self._clock = (clock if clock is not None
                       else getattr(self.engines[0], "clock",
                                    time.monotonic))
        self._sleep = sleep if sleep is not None else time.sleep
        self._fail_count = [0] * n
        self._cooldown_until = [float("-inf")] * n
        self.n_routed = 0
        self.n_saturated = 0
        # decision flight recorder: poll count is the router's "step"
        # (it has no training step), excision events parent their
        # eventual readmission, and only CHANGES record — a steady
        # excised set costs nothing per poll.
        self.blackbox = blackbox
        self._n_polls = 0
        self._excised_prev = np.zeros(n, bool)
        self._excise_events: Dict[int, object] = {}

    def _decide(self, kind: str, *, parent=None, telemetry=None,
                winner=None, **detail):
        """The one blackbox emission seam of the router plane (the
        ``decision-outside-recorder`` lint rule holds excision,
        cooldown, and saturation decisions to it)."""
        from bluefog_tpu.observe import blackbox as _blackbox

        return _blackbox.record_decision(
            "router", kind, step=self._n_polls, parent=parent,
            telemetry=telemetry, winner=winner, blackbox=self.blackbox,
            detail=detail or None)

    # -- gossip --------------------------------------------------------- #
    def _scrape(self):
        rows = [collect_serving_signals(r) for r in self.registries]
        local = np.array([[row[name] for name in SIGNAL_NAMES]
                          for row in rows], np.float64)
        heartbeats = np.array([row["last_step_ts"] for row in rows],
                              np.float64)
        return local, heartbeats

    def _local_signals(self) -> np.ndarray:
        return self._scrape()[0]

    def poll(self, dead_mask=None,
             now: Optional[float] = None) -> RouterSnapshot:
        """Scrape every replica's local gauges, gossip them through the
        one-hot block layout, and rank replicas from rank ``rank``'s
        converged view.  ``dead_mask`` excises replicas exactly the way
        the training-side gossip excises dead ranks — their signals
        vanish and their scores come back ``+inf`` (never routed to).
        The staleness guard feeds the same path implicitly: a replica
        whose step heartbeat is older than ``stale_after`` is excised
        like a dead one (and re-admitted once it steps again)."""
        n, k = len(self.engines), len(SIGNAL_NAMES)
        local, heartbeats = self._scrape()
        now = self._clock() if now is None else now
        ages = np.where(heartbeats >= 0.0, now - heartbeats, -1.0)
        suspect = np.zeros(n, bool)
        if self.stale_after > 0:
            # never-published replicas (heartbeat -1) stay routable:
            # cold replicas must look attractive, not dead
            suspect = (heartbeats >= 0.0) & (ages > self.stale_after)
        dead = (np.zeros(n, bool) if dead_mask is None
                else np.asarray(dead_mask, bool).reshape(-1))
        excised = dead | suspect
        if self._agg is None:
            signals = local
            rounds, spread = 0, 0.0
        else:
            # one-hot block: replica i's row is zero outside block i,
            # so the converged column means are signal/n_live — exactly
            # invertible at every rank
            x = np.zeros((n, n * k))
            for i in range(n):
                x[i, i * k:(i + 1) * k] = local[i]
            agg = self._agg.aggregate(
                x, dead_mask=excised if excised.any() else dead_mask)
            n_live = int((~np.isnan(agg.per_rank[:, 0])).sum())
            view = agg.per_rank[self.rank] * n_live
            signals = view.reshape(n, k)
            rounds, spread = agg.rounds, agg.spread
        scores = self._score(signals)
        scores = np.where(excised, np.inf, scores)
        self._n_polls += 1
        if not np.array_equal(excised, self._excised_prev):
            for i in np.flatnonzero(excised & ~self._excised_prev):
                i = int(i)
                ev = self._decide(
                    "excise", winner=str(i),
                    telemetry={"replica": i, "dead": bool(dead[i]),
                               "suspect": bool(suspect[i]),
                               "age": float(ages[i])})
                if ev is not None:
                    self._excise_events[i] = ev
            for i in np.flatnonzero(self._excised_prev & ~excised):
                i = int(i)
                self._decide(
                    "readmit", winner=str(i),
                    parent=self._excise_events.pop(i, None),
                    telemetry={"replica": i})
            self._excised_prev = excised.copy()
        order = tuple(int(i) for i in np.lexsort(
            (np.arange(n), scores)))  # score, then index — deterministic
        return RouterSnapshot(signals=signals, scores=scores,
                              order=order, rounds=rounds, spread=spread,
                              ages=tuple(float(a) for a in ages),
                              suspect=tuple(bool(s) for s in suspect))

    def _score(self, signals: np.ndarray) -> np.ndarray:
        occ, depth, ttft = (signals[:, 0], signals[:, 1], signals[:, 2])
        t_max = float(np.max(ttft)) if np.max(ttft) > 0 else 1.0
        w = self.weights
        return w[0] * occ + w[1] * depth + w[2] * (ttft / t_max)

    # -- routing -------------------------------------------------------- #
    def route(self, snapshot: Optional[RouterSnapshot] = None) -> int:
        """Index of the replica a request should go to next (the head of
        the snapshot's preference order).  Pass a held snapshot to
        amortize one gossip over a batch of decisions."""
        snap = snapshot if snapshot is not None else self.poll()
        return snap.order[0]

    def _walk(self, snap: RouterSnapshot, now: float) -> List[int]:
        """The submit candidate list: live (finite-score) replicas in
        preference order, with replicas inside a rejection cooldown
        demoted to the back — still tried, just last, so cooldown alone
        can never manufacture a :class:`FleetSaturated`."""
        live = [i for i in snap.order if np.isfinite(snap.scores[i])]
        if self.cooldown_s <= 0:
            return live
        hot = [i for i in live if self._cooldown_until[i] <= now]
        cooling = [i for i in live if self._cooldown_until[i] > now]
        return hot + cooling

    def submit(self, request,
               snapshot: Optional[RouterSnapshot] = None,
               dead_mask=None):
        """Submit ``request`` to the best replica, falling through the
        preference order on per-replica :class:`RequestRejected`
        backpressure.  With ``retries`` > 0, a walk that exhausts every
        live replica sleeps one seeded-backoff delay, re-polls, and
        walks again — transient rejection windows (GC pauses, admission
        bursts) are absorbed instead of surfaced.  Returns
        ``(replica_index, request)``; raises :class:`FleetSaturated`
        (with per-replica ``causes``) only after every attempt's walk
        exhausted the live fleet."""
        snap = snapshot if snapshot is not None else self.poll(
            dead_mask=dead_mask)
        depths: List[int] = []
        causes: List[tuple] = []
        max_queue = 0
        for attempt in range(self.retries + 1):
            if attempt > 0:
                from bluefog_tpu.serving.resilience import backoff_sleep

                backoff_sleep(attempt - 1, base=self.retry_base_s,
                              seed=self.seed,
                              salt=int(getattr(request, "rid", 0)),
                              sleep=self._sleep)
                snap = self.poll(dead_mask=dead_mask)
            now = self._clock()
            for i in self._walk(snap, now):
                try:
                    self.engines[i].submit(request)
                except RequestRejected as e:
                    depths.append(e.queue_depth)
                    max_queue = max(max_queue, e.max_queue)
                    causes.append((i, e))
                    self._fail_count[i] += 1
                    if (self.cooldown_s > 0 and self._fail_count[i]
                            >= self.cooldown_after):
                        if self._fail_count[i] == self.cooldown_after:
                            self._decide(
                                "cooldown", winner=str(i),
                                telemetry={"replica": i,
                                           "fails": self._fail_count[i]})
                        self._cooldown_until[i] = now + self.cooldown_s
                    continue
                self._fail_count[i] = 0
                self.n_routed += 1
                return i, request
        self.n_saturated += 1
        self._decide(
            "saturated",
            telemetry={"depths": [int(d) for d in depths],
                       "max_queue": int(max_queue)},
            rejections=len(causes))
        raise FleetSaturated(depths, max_queue, causes=causes)

    # -- observability -------------------------------------------------- #
    def publish(self, snapshot: Optional[RouterSnapshot] = None
                ) -> RouterSnapshot:
        """Land the local view as ``bf_fleet_serving_<signal>[replica]``
        gauges (plus the routed/saturated counters), so the same
        Prometheus scrape that serves training fleet metrics shows the
        serving fleet too."""
        snap = snapshot if snapshot is not None else self.poll()
        reg = self._registry
        if reg is None:
            from bluefog_tpu.observe import registry as obs_registry

            reg = (obs_registry.get_registry()
                   if obs_registry.enabled() else None)
        if reg is not None:
            for i in range(len(self.engines)):
                for m, name in enumerate(SIGNAL_NAMES):
                    v = snap.signals[i, m]
                    if np.isfinite(v):
                        reg.gauge(f"bf_fleet_serving_{name}",
                                  "gossiped replica serving signal",
                                  replica=str(i)).set(float(v))
            for i in range(len(self.engines)):
                s = (snap.suspect[i] if i < len(snap.suspect) else False)
                reg.gauge("bf_replica_suspect",
                          "1 while the staleness guard excises the "
                          "replica", replica=str(i)).set(1.0 if s
                                                         else 0.0)
            reg.gauge("bf_fleet_serving_best_replica",
                      "router's current first choice").set(snap.order[0])
            reg.counter("bf_fleet_serving_routed_total",
                        "requests routed").inc(0)
        return snap

    def summary(self) -> dict:
        return {
            "n_replicas": len(self.engines),
            "n_routed": self.n_routed,
            "n_saturated": self.n_saturated,
        }
