"""Continuous-batching inference engine.

One-shot :func:`~bluefog_tpu.models.generate.llama_generate` is a
benchmark artifact: fixed batch, fixed prompt length, everyone finishes
together.  A server sees none of that — requests arrive whenever,
prompts differ, budgets differ — and a bandwidth-bound TPU decode loop
that waits for batch formation or pads dead rows is idle silicon.  This
engine keeps ONE resident jitted program busy across an arbitrary
arrival pattern:

* every request owns a **slot** of the fixed-capacity K/V pool
  (:class:`~bluefog_tpu.serving.kv_pool.SlotPool`);
* each host-loop :meth:`~ServingEngine.step` admits queued requests and
  runs up to ``prefill_budget`` **chunked-prefill** calls (fixed chunk
  shape — a long prompt spreads over several steps instead of stalling
  running decodes), then advances EVERY active slot ``decode_horizon``
  tokens in a single vmapped program with a per-slot active mask and
  per-slot cache index;
* slots retire on EOS / token budget / deadline / cancellation and are
  zeroed for reuse.

The resident program set is FIXED AT BUILD TIME and its shapes depend
only on ``(capacity, max_len, prefill_chunk, decode_horizon)`` — never
on the arrival pattern: no recompiles across requests.  A plain engine
residents a prefill-chunk and a decode-step program (plus the slot
housekeeping scatter); a :class:`SpeculativeConfig` swaps the decode
step for a draft/verify pair — the draft model proposes ``lookahead``
tokens through the same single-token step, ONE multi-token target
forward scores the whole window (``verify_window``), and acceptance is
rejection sampling (token-exact greedy at temperature 0).  Either way
the count is fixed before the first request arrives, and
:meth:`ServingEngine.profile` enumerates whatever is resident.

Two optional subsystems ride the same fixed programs: a
:class:`~bluefog_tpu.serving.prefix_cache.PrefixCache` admits requests
that share a prompt prefix by COPYING cached K/V chunks into the slot
instead of re-running prefill (chain-hashed whole chunks — bit-exact vs
cold prefill), and ``registry=`` isolates the engine's metrics for
multi-replica fleets (:mod:`bluefog_tpu.serving.fleet`).

Numerics are the one-shot path's numerics: both are built from the same
:func:`prefill_cache` / :func:`decode_token_step` pieces, so a GREEDY
request served through the engine reproduces its one-shot
``llama_generate(prompt[None], n, max_len=pool_max_len)`` output token
for token (tests/test_serving.py).  Temperature sampling is
deterministic per request (the rng folds the request seed with the
token index) but uses a different rng chain than the one-shot scan, so
sampled streams are engine-reproducible, not one-shot-identical.
Chunked prefill stays exact because
attention is causal: a padded chunk's real rows never attend to the pad
tail, and the corrected per-slot cache index masks the tail until real
tokens overwrite it.
"""

from __future__ import annotations

import dataclasses
import itertools
import time
from functools import partial
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bluefog_tpu.models.generate import (decode_config, decode_token_step,
                                         prefill_cache, verify_window)
from bluefog_tpu.models.llama import Llama, LlamaConfig
from bluefog_tpu.serving.kv_pool import SlotPool
from bluefog_tpu.serving.metrics import ServingMetrics
from bluefog_tpu.serving.scheduler import FifoScheduler, RequestRejected

__all__ = ["ServingEngine", "Request", "RequestRejected",
           "SpeculativeConfig", "EXPIRED", "FAILOVER"]

_rid_counter = itertools.count()

# terminal / live request states
QUEUED, PREFILL, DECODE = "queued", "prefill", "decode"
COMPLETED, CANCELLED, REJECTED = "completed", "cancelled", "rejected"
# EXPIRED: terminal — deadline passed while the request was stranded on
# a dead/draining replica (the queue-shedding path stays CANCELLED).
# FAILOVER: transitional retire outcome — the slot is released here but
# the request immediately resets to QUEUED for resubmission elsewhere,
# so ``done`` stays False.
EXPIRED, FAILOVER = "expired", "failover"


@dataclasses.dataclass(eq=False)  # identity semantics: the scheduler
# removes by object (a generated __eq__ would compare prompt arrays)
class Request:
    """One generation request.

    ``deadline`` is in absolute engine-clock seconds (the engine's
    injected ``clock``, ``time.monotonic`` by default): a request that
    has not RETIRED by its deadline is cancelled — queued ones are shed
    without ever touching the device.  ``temperature``/``seed`` drive
    per-request sampling (greedy at 0.0); sampling is deterministic
    given the seed and independent of what the request is co-batched
    with (the rng folds in the per-request token index, not the engine
    step)."""
    prompt: np.ndarray
    max_new_tokens: int
    eos_id: Optional[int] = None
    temperature: float = 0.0
    seed: int = 0
    deadline: Optional[float] = None
    rid: int = dataclasses.field(default_factory=lambda: next(_rid_counter))

    # engine-owned state
    state: str = dataclasses.field(default=QUEUED, init=False)
    tokens: List[int] = dataclasses.field(default_factory=list, init=False)
    slot: Optional[int] = dataclasses.field(default=None, init=False)
    _prefill_pos: int = dataclasses.field(default=0, init=False)
    _cancel: bool = dataclasses.field(default=False, init=False)
    _prefix_keys: Optional[List[str]] = dataclasses.field(default=None,
                                                          init=False)

    def __post_init__(self):
        self.prompt = np.asarray(self.prompt, np.int32).reshape(-1)
        if self.prompt.size < 1:
            raise ValueError("prompt must hold at least one token")
        if self.max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens ({self.max_new_tokens}) must be >= 1")

    @property
    def done(self) -> bool:
        return self.state in (COMPLETED, CANCELLED, REJECTED, EXPIRED)

    def output(self) -> np.ndarray:
        """prompt ‖ generated tokens (no padding — streaming semantics:
        exactly what was emitted, EOS included when it fired)."""
        return np.concatenate(
            [self.prompt, np.asarray(self.tokens, np.int32)])

    def reset_for_resume(self) -> "Request":
        """Return the request to the submittable QUEUED state while
        KEEPING its emitted tokens — the failover/drain primitive.  The
        next engine re-prefills ``prompt ‖ tokens`` (cached chunks by
        chain hash, cold tail otherwise) and its decode continues the
        rng fold chain at ``len(tokens)``, so the resumed stream is
        bit-equal to an unfaulted run."""
        self.state = QUEUED
        self.slot = None
        self._prefill_pos = 0
        self._cancel = False
        self._prefix_keys = None
        return self


def _sample(logits, key, temp):
    """Per-row sampling: greedy argmax at temp 0.0 (bit-identical to the
    one-shot path), categorical otherwise.  Both branches are computed
    and selected by ``where`` so temperature stays a traced operand."""
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    sampled = jax.random.categorical(
        key, logits / jnp.maximum(temp, 1e-6), axis=-1).astype(jnp.int32)
    return jnp.where(temp > 0.0, sampled, greedy)


def _corrected_index(new_cache, old_cache, valid_len):
    """Rewrite every ``cache_index`` leaf to ``old + valid_len``: the
    model advanced the index by the full (padded) chunk length; the
    request only wrote ``valid_len`` real tokens.  The pad tail's K/V
    stays in the cache but above the index, where the causal mask hides
    it until real tokens overwrite it — exactness needs only the index."""
    def fix(path, new, old):
        name = getattr(path[-1], "key", None)
        if name == "cache_index":
            return old + jnp.asarray(valid_len, old.dtype)
        return new

    return jax.tree_util.tree_map_with_path(fix, new_cache, old_cache)


@partial(jax.jit, static_argnames=("cfg",), donate_argnums=(1,))
def _prefill_chunk_prog(params, pool, slot, chunk, valid_len,
                        cfg: LlamaConfig):
    """Write one fixed-shape prompt chunk into ``slot``'s cache.  Only
    the K/V side effect matters: the engine prefills ``prompt[:-1]``
    through chunks (their logits are never sampled — in decode layout
    the model only materializes the FINAL position's logits, which for a
    padded chunk is a pad row) and routes the last prompt token through
    the regular decode step, whose output IS the first generated token.
    Shapes depend on ``(cfg, chunk_len)`` alone."""
    model = Llama(cfg)
    cache = jax.tree.map(
        lambda leaf: lax.dynamic_index_in_dim(leaf, slot, 0,
                                              keepdims=False), pool)
    _, new_cache = prefill_cache(model, params, cache, chunk)
    new_cache = _corrected_index(new_cache, cache, valid_len)
    return jax.tree.map(
        lambda p, c: lax.dynamic_update_index_in_dim(p, c, slot, 0),
        pool, new_cache)


@partial(jax.jit, static_argnames=("cfg", "horizon"), donate_argnums=(1,))
def _decode_step_prog(params, pool, toks, active, keys, counts, temps,
                      cfg: LlamaConfig, horizon: int):
    """Advance EVERY slot ``horizon`` decode tokens (vmapped
    single-token steps inside one ``lax.scan`` — each slot carries its
    own cache index, so rotary/mask positions are per-request) and
    freeze inactive slots' caches via the mask.  Inactive slots still
    compute — that is the fixed-shape price that buys zero recompiles —
    but their state is bit-frozen.

    ``horizon`` amortizes the host loop (dispatch + token fetch) over
    several tokens; each token is the SAME per-slot step (and the rng
    folds in the per-request token index), so the emitted stream is
    identical for every horizon — the host truncates a retiring slot's
    surplus tail, and the slot's zero-on-free makes its overrun cache
    writes unobservable.  Returns ``(pool, tokens [horizon, n_slots])``.
    """
    model = Llama(cfg)

    def keep_index(path, new, old):
        # Freezing an inactive slot needs only its cache_index: the
        # step's K/V write lands AT the frozen index, where the causal
        # mask hides it until something real overwrites it — the next
        # prefill chunk (mid-admission slots), the next real decode
        # write, or the zero-on-free (free slots).  Masking just the
        # index leaves skips two whole-pool copies per token.
        if getattr(path[-1], "key", None) != "cache_index":
            return new
        m = active.reshape(active.shape + (1,) * (new.ndim - 1))
        return jnp.where(m, new, old)

    def hstep(carry, j):
        pool, toks = carry

        def one(cache, tok, key, count, temp):
            last, cache = decode_token_step(model, params, cache,
                                            tok[None, None])
            nxt = _sample(last[0], jax.random.fold_in(key, count + j),
                          temp)
            return cache, nxt

        new_pool, nxt = jax.vmap(one)(pool, toks, keys, counts, temps)
        nxt = jnp.where(active, nxt, toks)
        return (jax.tree_util.tree_map_with_path(keep_index, new_pool,
                                                 pool), nxt), nxt

    (pool, _), hist = lax.scan(hstep, (pool, toks),
                               jnp.arange(horizon, dtype=jnp.int32))
    return pool, hist


@dataclasses.dataclass(frozen=True)
class SpeculativeConfig:
    """Draft model spec for speculative decoding.

    ``variables``/``cfg`` are the DRAFT model (same vocabulary as the
    target; typically much smaller).  Each engine step the draft
    proposes ``lookahead`` tokens through the resident single-token
    step, the target scores the whole window in ONE multi-token forward
    (:func:`~bluefog_tpu.models.generate.verify_window`), and standard
    rejection sampling accepts a prefix of the proposals plus one
    correction/bonus token — so every step emits between 1 and
    ``lookahead + 1`` tokens with the TARGET model's distribution
    (bit-exact greedy argmax at temperature 0; provably unbiased
    sampling otherwise).  The engine reserves ``lookahead`` cache
    positions of headroom per slot (checked at submit)."""

    variables: dict
    cfg: LlamaConfig
    lookahead: int = 4
    weight_quant: str = "none"


@partial(jax.jit, static_argnames=("cfg_t", "cfg_d", "k"),
         donate_argnums=(2, 3))
def _spec_step_prog(params_t, params_d, pool_t, pool_d, toks, active,
                    keys, counts, temps, cfg_t: LlamaConfig,
                    cfg_d: LlamaConfig, k: int):
    """One speculative decode step for EVERY slot: draft ``k`` proposals
    (a ``k+1``-step single-token scan — the extra step writes the last
    proposal's K/V so the draft cache index stays position-aligned
    whatever gets accepted), verify the window in one multi-token target
    forward, accept by rejection sampling, and emit ``n_acc + 1`` tokens
    per slot (accepted prefix + correction/bonus).

    Exactness at temperature 0: the accepted tokens ARE the target's
    greedy argmaxes (acceptance literally compares them), and the
    correction token is the argmax after the accepted prefix — the
    emitted stream is bitwise the non-speculative greedy stream, relying
    only on the row-wise bit-stability of the multi-token forward that
    chunked prefill already depends on.  At temperature > 0 the
    accept-with-``min(1, p/q)`` + residual-resample scheme emits tokens
    distributed exactly as target sampling (Leviathan et al.) — streams
    are deterministic per request (salted ``fold_in`` chains off the
    request seed and token count) but follow a different rng chain than
    the non-speculative step.

    Cache discipline: both pools' writes advance ``k + 1`` positions;
    the per-slot index is corrected to ``old + n_emit`` (0 for inactive
    slots), so rejected drafts sit ABOVE the index where the causal
    mask hides them until real tokens overwrite — the same invariant
    padded prefill chunks use.  Returns
    ``(pool_t, pool_d, emitted [cap, k+1], n_emit [cap])``."""
    target = Llama(cfg_t)
    draft = Llama(cfg_d)

    def one(cache_t, cache_d, tok, act, key, count, temp):
        old_t, old_d = cache_t, cache_d
        tmp = jnp.maximum(temp, 1e-6)

        def dstep(carry, i):
            cache_d, cur = carry
            last, cache_d = decode_token_step(draft, params_d, cache_d,
                                              cur[None, None])
            lg = last[0]
            nxt = _sample(lg, jax.random.fold_in(
                jax.random.fold_in(key, 1), count + i), temp)
            return (cache_d, nxt), (cur, nxt, lg)

        (cache_d, _), (window, props, dlg) = lax.scan(
            dstep, (cache_d, tok), jnp.arange(k + 1, dtype=jnp.int32))
        # window = [cur, d_1..d_k] (the tokens whose K/V lands in the
        # cache); props = [d_1..d_{k+1}] (the k+1-th proposal is only
        # drafted so d_k's K/V gets written — it is never considered);
        # dlg[i] is the draft distribution that proposed props[i]
        vlogits, cache_t = verify_window(target, params_t, cache_t,
                                         window[None])
        vlogits = vlogits[0]                          # [k+1, V]
        tgt = jnp.argmax(vlogits, axis=-1).astype(jnp.int32)

        # greedy acceptance: leading run where draft == target argmax
        hit = (props[:k] == tgt[:k]).astype(jnp.int32)
        acc_greedy = jnp.cumprod(hit).sum()
        # rejection sampling: accept d_i with prob min(1, p_i/q_i)
        p = jax.nn.softmax(vlogits / tmp, axis=-1)    # [k+1, V]
        q = jax.nn.softmax(dlg / tmp, axis=-1)        # [k+1, V]
        idx = jnp.arange(k)
        ratio = (p[idx, props[:k]]
                 / jnp.maximum(q[idx, props[:k]], 1e-30))
        u = jax.vmap(lambda i: jax.random.uniform(jax.random.fold_in(
            jax.random.fold_in(key, 2), count + i)))(idx)
        ok = (u < jnp.minimum(ratio, 1.0)).astype(jnp.int32)
        acc_sample = jnp.cumprod(ok).sum()
        n_acc = jnp.where(temp > 0.0, acc_sample, acc_greedy)

        # correction token after the accepted prefix: residual resample
        # max(0, p - q) on a rejection, plain target sample on the
        # all-accepted bonus (no draft proposed there, q := 0)
        p_row = p[n_acc]
        q_row = jnp.where(n_acc < k, q[n_acc], 0.0)
        resid = jnp.maximum(p_row - q_row, 0.0)
        rsum = resid.sum()
        resid = jnp.where(rsum > 1e-30, resid / jnp.maximum(rsum, 1e-30),
                          p_row)
        corr_sample = jax.random.categorical(
            jax.random.fold_in(jax.random.fold_in(key, 3), count + n_acc),
            jnp.log(jnp.maximum(resid, 1e-38))).astype(jnp.int32)
        corr = jnp.where(temp > 0.0, corr_sample, tgt[n_acc])

        n_emit = jnp.where(act, n_acc + 1, 0)
        emitted = jnp.where(jnp.arange(k + 1) < n_acc, props, corr)
        cache_t = _corrected_index(cache_t, old_t, n_emit)
        cache_d = _corrected_index(cache_d, old_d, n_emit)
        return cache_t, cache_d, emitted, n_emit

    return jax.vmap(one)(pool_t, pool_d, toks, active, keys, counts,
                         temps)


class ServingEngine:
    """Continuous-batching serving loop over a :class:`SlotPool`.

    Args:
      variables: ``{"params": ...}`` (full-precision, or the
        ``quantize_llama_params`` tree with ``weight_quant`` set — same
        contract as ``llama_generate``).
      cfg: model config (training layout fine; normalized through
        :func:`decode_config`).
      capacity: resident request slots (= decode batch).
      max_len: per-slot cache length; every request needs
        ``len(prompt) + max_new_tokens <= max_len`` (checked at submit).
      prefill_chunk: fixed prompt-chunk length; must divide ``max_len``
        (chunk windows then never cross the cache end — an overrunning
        ``dynamic_update_slice`` start would CLAMP, silently corrupting
        near-``max_len`` prompts).  Smaller chunks bound how long
        running decodes stall behind one admission; larger chunks
        finish prefill in fewer steps.
      decode_horizon: tokens every active slot advances per host
        iteration (one inner ``lax.scan``).  1 = lowest TTFT and
        per-token scheduling; larger values amortize host dispatch over
        the horizon (throughput mode — retirements, admissions, and
        deadline checks happen at horizon boundaries).  The emitted
        streams are identical for every horizon.
      prefill_budget: max prefill CHUNKS one step may run (admissions
        continue until the budget or the pool is exhausted).  1
        (default) bounds per-step admission work to one chunk — the
        lowest decode jitter; raise it alongside ``decode_horizon`` so
        admission keeps the pool full in throughput mode.
      max_queue: backpressure bound — submits beyond it raise
        :class:`RequestRejected` with the queue depth attached.
      clock: injectable monotonic clock (tests drive virtual time; the
        Poisson bench uses the default ``time.monotonic``).
      decode_attn: attention lowering for the resident programs ("xla"
        default — the vmapped per-slot step; the fused Pallas kernel is
        a single-request-batch kernel, measure before switching).
      registry: explicit metrics registry for this engine's
        :class:`ServingMetrics` (default: the global observe registry).
        A multi-replica fleet gives each replica its own so the router
        can read per-replica occupancy/queue/TTFT signals
        (:mod:`bluefog_tpu.serving.fleet`).
      zero_on_free: passed to :class:`SlotPool` (default: the
        ``BLUEFOG_KV_ZERO_ON_FREE`` env knob, off).
      prefix_cache: ``True`` builds a
        :class:`~bluefog_tpu.serving.prefix_cache.PrefixCache` sized by
        ``prefix_cache_bytes`` (default ``BLUEFOG_PREFIX_CACHE_MB``);
        or pass an instance to share/inspect it.  Admission then
        restores any chain-hash-matched prompt chunks by device copy
        and prefills only the novel tail — bit-exact vs cold prefill.
      speculative: a :class:`SpeculativeConfig` — swaps the resident
        decode step for the draft/verify program pair.  Requires
        ``decode_horizon=1`` (a speculative step already advances up to
        ``lookahead+1`` tokens) and reserves ``lookahead`` cache
        positions of headroom per request (checked at submit).
    """

    def __init__(self, variables, cfg: LlamaConfig, *, capacity: int,
                 max_len: int, prefill_chunk: int = 32,
                 decode_horizon: int = 1, prefill_budget: int = 1,
                 kv_quant: str = "none", weight_quant: str = "none",
                 max_queue: int = 64,
                 clock: Optional[Callable[[], float]] = None,
                 decode_attn: str = "xla", registry=None,
                 zero_on_free: Optional[bool] = None,
                 prefix_cache=False,
                 prefix_cache_bytes: Optional[int] = None,
                 speculative: Optional[SpeculativeConfig] = None):
        from bluefog_tpu.models.quant import is_quantized_params

        if (weight_quant != "none") != is_quantized_params(variables):
            raise ValueError(
                "weight_quant='int8'/'w8a8' requires params converted by "
                "quantize_llama_params (and full-precision params require "
                "weight_quant='none'); got a mismatched tree")
        if prefill_chunk < 1:
            raise ValueError(f"prefill_chunk ({prefill_chunk}) must be "
                             ">= 1")
        if max_len % prefill_chunk != 0:
            # chunk writes land at multiples of prefill_chunk, so this
            # guarantees no chunk's fixed-size window crosses max_len —
            # XLA CLAMPS an out-of-range dynamic_update_slice start,
            # which would silently overwrite earlier K/V positions for
            # near-max_len prompts instead of erroring
            raise ValueError(
                f"prefill_chunk ({prefill_chunk}) must divide max_len "
                f"({max_len}) so no chunk window crosses the cache end")
        if decode_horizon < 1:
            raise ValueError(f"decode_horizon ({decode_horizon}) must be "
                             ">= 1")
        if prefill_budget < 1:
            raise ValueError(f"prefill_budget ({prefill_budget}) must be "
                             ">= 1")
        if speculative is not None:
            if decode_horizon != 1:
                raise ValueError(
                    "speculative decoding requires decode_horizon=1 (a "
                    "speculative step already advances up to lookahead+1 "
                    f"tokens); got decode_horizon={decode_horizon}")
            if speculative.lookahead < 1:
                raise ValueError(
                    f"lookahead ({speculative.lookahead}) must be >= 1")
            if speculative.cfg.vocab_size != cfg.vocab_size:
                raise ValueError(
                    f"draft vocab ({speculative.cfg.vocab_size}) != "
                    f"target vocab ({cfg.vocab_size}) — speculative "
                    "decoding needs one tokenizer")
            if ((speculative.weight_quant != "none")
                    != is_quantized_params(speculative.variables)):
                raise ValueError(
                    "SpeculativeConfig.weight_quant does not match the "
                    "draft param tree (quantize_llama_params contract)")
        self.cfg = decode_config(cfg, max_len, kv_quant=kv_quant,
                                 weight_quant=weight_quant,
                                 decode_attn=decode_attn)
        from bluefog_tpu.serving.prefix_cache import PrefixCache

        prefix = None
        # NB: isinstance first — an EMPTY PrefixCache is falsy (__len__)
        if isinstance(prefix_cache, PrefixCache) or prefix_cache:
            prefix = (prefix_cache if isinstance(prefix_cache, PrefixCache)
                      else PrefixCache(prefill_chunk, prefix_cache_bytes))
            if prefix.chunk != prefill_chunk:
                raise ValueError(
                    f"prefix cache chunk ({prefix.chunk}) != prefill_chunk"
                    f" ({prefill_chunk}) — hashes must match the chunk "
                    "grid prefill writes")
        self.pool = SlotPool(cfg, capacity, max_len, kv_quant=kv_quant,
                             zero_on_free=zero_on_free, prefix=prefix)
        self._spec = speculative
        self._draft_pool: Optional[SlotPool] = None
        self._draft_params = None
        self.draft_cfg: Optional[LlamaConfig] = None
        if speculative is not None:
            from bluefog_tpu.serving.prefix_cache import PrefixCache

            dprefix = (PrefixCache(prefill_chunk,
                                   prefix_cache_bytes)
                       if prefix is not None else None)
            self.draft_cfg = decode_config(
                speculative.cfg, max_len, kv_quant=kv_quant,
                weight_quant=speculative.weight_quant,
                decode_attn=decode_attn)
            # the draft pool mirrors the target pool's alloc/free order,
            # so slot i means the same request in both trees
            self._draft_pool = SlotPool(speculative.cfg, capacity,
                                        max_len, kv_quant=kv_quant,
                                        zero_on_free=zero_on_free,
                                        prefix=dprefix)
            self._draft_params = speculative.variables["params"]
        self.scheduler = FifoScheduler(max_queue=max_queue)
        self.metrics = ServingMetrics(registry=registry)
        self.prefill_chunk = prefill_chunk
        self.decode_horizon = decode_horizon
        self.prefill_budget = prefill_budget
        self.clock = clock or time.monotonic
        self._params = variables["params"]
        self._running: Dict[int, Request] = {}   # slot -> request
        self._admitting: Optional[Request] = None  # mid-prefill request
        self._draining = False     # drain(): admission permanently off
        self._drain_flushed = 0    # KV chunks flushed to the prefix
        # cache on behalf of migrating/completing drain residents
        self._resident = self._build_resident()

    # -- submission ---------------------------------------------------- #
    def submit(self, request: Request) -> Request:
        """Enqueue a request.  Raises :class:`RequestRejected` under
        backpressure (queue at ``max_queue``) and ``ValueError`` when the
        request cannot fit a slot at all."""
        total = request.prompt.size + request.max_new_tokens
        if self._spec is not None:
            # a speculative step may write lookahead draft positions
            # past the final emitted token; reserving that headroom at
            # admission keeps every window inside the slot (an
            # overrunning dynamic_update_slice start would CLAMP and
            # silently overwrite real K/V)
            total += self._spec.lookahead
        if total > self.pool.max_len:
            # refusal paths agree: a request the engine will never run
            # is terminal (done == True) AND counted in n_rejected,
            # whichever way it was refused — caller loops polling
            # req.done must not wait on a phantom, and a dashboard
            # must see every refusal
            request.state = REJECTED
            self.metrics.on_reject(request.rid, self.clock())
            raise ValueError(
                f"request needs {total} cache positions but slots hold "
                f"{self.pool.max_len} (prompt {request.prompt.size} + "
                f"max_new_tokens {request.max_new_tokens}"
                + (f" + speculative headroom {self._spec.lookahead}"
                   if self._spec is not None else "") + ")")
        now = self.clock()
        if self._draining:
            request.state = REJECTED
            self.metrics.on_reject(request.rid, now)
            raise RequestRejected("engine draining",
                                  queue_depth=self.scheduler.queue_depth,
                                  max_queue=self.scheduler.max_queue)
        try:
            self.scheduler.submit(request)
        except RequestRejected:
            request.state = REJECTED
            self.metrics.on_reject(request.rid, now)
            raise
        # a request one replica refused may be accepted by the next in
        # the router's walk — acceptance supersedes the earlier REJECTED
        request.state = QUEUED
        self.metrics.on_submit(request.rid, now)
        return request

    def cancel(self, request: Request) -> bool:
        """Cancel a queued or running request (idempotent; False once the
        request already retired)."""
        if request.done:
            return False
        if self.scheduler.cancel(request):
            request.state = CANCELLED
            self.metrics.on_retire(request.rid, self.clock(), CANCELLED)
            return True
        request._cancel = True  # picked up at the next step boundary
        return True

    # -- the serving loop --------------------------------------------- #
    def step(self) -> bool:
        """One engine iteration: shed/cancel, admit + one prefill chunk,
        one decode step over all active slots.  Returns True while there
        is live work (queued, prefilling, or decoding)."""
        t_step = time.perf_counter()  # real wall time (the injected
        # clock may be virtual) — feeds the fleet step-time view
        now = self.clock()
        # 1. deadline shedding in the queue (zero device cost)
        for req in self.scheduler.expire(now):
            req.state = CANCELLED
            self.metrics.on_retire(req.rid, now, CANCELLED)
        # 2. running cancellations (explicit or deadline) — including a
        #    request still mid-prefill, whose slot must come back too
        live = list(self._running.values())
        if self._admitting is not None:
            live.append(self._admitting)
        for req in live:
            if req._cancel or (req.deadline is not None
                               and now >= req.deadline):
                self._retire(req, CANCELLED, now)
        # 3+4. admission + chunked prefill, bounded by the per-step
        #      chunk budget (prefill work is what stalls running
        #      decodes, so IT is what gets budgeted — not admissions)
        chunks = 0
        while chunks < self.prefill_budget:
            if self._admitting is None:
                if self._draining:
                    break  # drain(): the current prefill finishes, but
                    # nothing new leaves the queue
                if self.pool.n_free == 0:
                    break
                req = self.scheduler.admit(now)
                if req is None:
                    break
                req.slot = self.pool.alloc()
                if self._draft_pool is not None:
                    dslot = self._draft_pool.alloc()
                    assert dslot == req.slot, (dslot, req.slot)
                self.metrics.on_admit(req.rid, now)
                # a failed-over request resumes with emitted tokens: its
                # prefill region is (prompt ‖ tokens)[:-1] — the same
                # chunk grid the original prefill stashed, so the replay
                # restores cached chunks and computes only the tail
                n_ctx = req.prompt.size + len(req.tokens)
                if n_ctx > 1:
                    self._restore_prefix(req)  # no-op without the cache
                    if req._prefill_pos >= n_ctx - 1:
                        # the whole prefill region came out of the
                        # prefix cache — straight to decode, zero
                        # prefill compute spent
                        req.state = DECODE
                        self._running[req.slot] = req
                        continue
                    req.state = PREFILL
                    self._admitting = req
                else:  # single-token prompt: nothing to prefill — the
                    # decode step consumes the whole prompt directly
                    req.state = DECODE
                    self._running[req.slot] = req
                    continue
            self._prefill_one_chunk(self._admitting)
            chunks += 1
        # 5. one decode token for every active slot
        decoding = {s: r for s, r in self._running.items()
                    if r.state == DECODE}
        if decoding:
            if self._spec is not None:
                self._spec_decode_step(decoding)
            else:
                self._decode_step(decoding)
        self.metrics.on_step(self.pool.occupancy(),
                             self.scheduler.queue_depth,
                             time.perf_counter() - t_step, now=now)
        return bool(self._running or self._admitting
                    or self.scheduler.queue_depth)

    def run(self, max_steps: int = 100_000) -> None:
        """Drive :meth:`step` until idle (drain the queue and every
        slot); ``max_steps`` guards against a caller submitting faster
        than the loop drains."""
        for _ in range(max_steps):
            if not self.step():
                return
        raise RuntimeError(f"engine still busy after {max_steps} steps")

    def drain(self, handoff: Optional[Callable[[Request], object]] = None,
              max_steps: int = 100_000) -> Dict[str, int]:
        """Retire this replica cleanly — the elastic-serving primitive.

        Admission stops permanently (subsequent :meth:`submit` raises
        :class:`RequestRejected`; the admission loop stops popping the
        queue).  Then:

        * with a ``handoff`` callable (e.g. ``router.submit``): every
          resident request flushes its written K/V chunks to the shared
          prefix cache, retires here with outcome ``failover``, resets
          to QUEUED **keeping its emitted tokens**, and is handed off —
          the target replica re-prefills ``prompt ‖ tokens`` (restored
          chunks + novel tail) and continues bit-exactly.  Queued
          requests hand off as-is.
        * without one: queued requests are REJECTED (backpressure — the
          caller resubmits elsewhere), residents run to completion in
          place, flushing their chunks as they retire.

        Host-side control flow only: no new programs, no recompiles.
        Returns a summary dict (``handed_off`` / ``completed`` /
        ``rejected_queue`` / ``cancelled_queue`` / ``flushed_chunks``).
        """
        now = self.clock()
        self._draining = True
        summary = {"handed_off": 0, "completed": 0, "rejected_queue": 0,
                   "cancelled_queue": 0, "flushed_chunks": 0}
        # queue: deadline-expired requests shed exactly as step() would
        for req in self.scheduler.expire(now):
            req.state = CANCELLED
            self.metrics.on_retire(req.rid, now, CANCELLED)
            summary["cancelled_queue"] += 1
        queued = self.scheduler.drain()
        if handoff is None:
            for req in queued:
                req.state = REJECTED
                self.metrics.on_reject(req.rid, now)
                self.metrics.on_retire(req.rid, now, REJECTED)
                summary["rejected_queue"] += 1
            residents = {r.rid: r for r in self._running.values()}
            if self._admitting is not None:
                residents[self._admitting.rid] = self._admitting
            for _ in range(max_steps):
                if not self.step():
                    break
            else:
                raise RuntimeError(
                    f"drain still busy after {max_steps} steps")
            summary["completed"] = sum(
                1 for r in residents.values() if r.state == COMPLETED)
        else:
            residents = sorted(self._running.values(),
                               key=lambda r: r.slot)
            if self._admitting is not None:
                residents = sorted(residents + [self._admitting],
                                   key=lambda r: r.slot)
            for req in residents + queued:
                # _retire flushes the written chunks (self._draining is
                # set) and releases the slot; reset_for_resume returns
                # the request to QUEUED with its tokens intact
                self._retire(req, FAILOVER, now)
                self.metrics.on_failover(req.rid, now)
                req.reset_for_resume()
                handoff(req)
                summary["handed_off"] += 1
        summary["flushed_chunks"] = self._drain_flushed
        from bluefog_tpu.observe.blackbox import record_decision

        record_decision(
            "serving", "drain", step=-1,
            telemetry={k: int(v) for k, v in sorted(summary.items())},
            winner="handoff" if handoff is not None else "complete")
        return summary

    def _build_resident(self) -> Dict[str, tuple]:
        """The engine's resident data-plane executables, fixed at build
        time: ``{name: (jitted_fn, example_args_thunk, static_kwargs)}``.
        A plain engine residents the prefill chunk + decode step; a
        speculative engine swaps the decode step for the draft-prefill /
        draft+verify pair.  :meth:`profile` (and any future
        introspection) enumerates THIS dict instead of hardcoding the
        program list, so new programs are profiled without another
        special case.  (The slot-housekeeping scatters — zero /
        index-reset on free — are deliberately not listed: they are
        O(slot) bookkeeping, not the serving data plane.)"""
        cap = self.pool.capacity

        def decode_args(pool):
            return lambda: (
                self._params, pool.cache, jnp.zeros((cap,), jnp.int32),
                jnp.zeros((cap,), bool), jnp.zeros((cap, 2), jnp.uint32),
                jnp.zeros((cap,), jnp.int32), jnp.zeros((cap,),
                                                        jnp.float32))

        resident: Dict[str, tuple] = {
            "prefill_chunk": (
                _prefill_chunk_prog,
                lambda: (self._params, self.pool.cache, jnp.int32(0),
                         jnp.zeros((1, self.prefill_chunk), jnp.int32),
                         jnp.int32(0)),
                {"cfg": self.cfg}),
        }
        if self._spec is None:
            resident["decode_step"] = (
                _decode_step_prog, decode_args(self.pool),
                {"cfg": self.cfg, "horizon": self.decode_horizon})
        else:
            resident["draft_prefill_chunk"] = (
                _prefill_chunk_prog,
                lambda: (self._draft_params, self._draft_pool.cache,
                         jnp.int32(0),
                         jnp.zeros((1, self.prefill_chunk), jnp.int32),
                         jnp.int32(0)),
                {"cfg": self.draft_cfg})
            resident["spec_step"] = (
                _spec_step_prog,
                lambda: (self._params, self._draft_params,
                         self.pool.cache, self._draft_pool.cache,
                         jnp.zeros((cap,), jnp.int32),
                         jnp.zeros((cap,), bool),
                         jnp.zeros((cap, 2), jnp.uint32),
                         jnp.zeros((cap,), jnp.int32),
                         jnp.zeros((cap,), jnp.float32)),
                {"cfg_t": self.cfg, "cfg_d": self.draft_cfg,
                 "k": self._spec.lookahead})
        return resident

    def profile(self, **kw) -> Dict[str, "object"]:
        """HLO-attributed :class:`~bluefog_tpu.observe.StepProfile` of
        EVERY resident device program — enumerated generically from the
        build-time registry (``prefill_chunk`` + ``decode_step`` for a
        plain engine; ``prefill_chunk`` + ``draft_prefill_chunk`` +
        ``spec_step`` for a speculative one), via
        :func:`bluefog_tpu.observe.profile_step`.  AOT — compiles
        (hitting the jit cache when the engine already ran) but executes
        nothing, so it is safe on a live engine.  Keyword args
        (``step_seconds``, chip figures, ...) pass through; the serving
        bench emits these instead of hand-rolled cost dicts."""
        from bluefog_tpu.observe import profile_step

        return {name: profile_step(fn, *args(),
                                   name=f"serving.{name}", **static, **kw)
                for name, (fn, args, static) in self._resident.items()}

    # -- internals ----------------------------------------------------- #
    @staticmethod
    def _context(req: Request) -> np.ndarray:
        """The request's full prefill context: the prompt, plus any
        tokens already emitted on a previous replica (failover resume).
        The decode step then consumes context[-1] and continues the
        per-request rng fold chain at ``len(tokens)`` — bit-equal to
        never having moved."""
        if not req.tokens:
            return req.prompt
        return np.concatenate(
            [req.prompt, np.asarray(req.tokens, np.int32)])

    def _restore_prefix(self, req: Request) -> int:
        """Admission-time prefix reuse: chain-hash the prompt's full
        chunks and device-copy the longest cached run into the slot
        (both pools, lockstep, under speculation — target and draft K/V
        are different tensors for the same tokens, so the usable prefix
        is the MINIMUM of the two matches).  Advances ``_prefill_pos``
        past the restored region; restores do not consume prefill
        budget (they replace the model forward, not ride next to it)."""
        if self.pool.prefix is None:
            return 0
        keys = self.pool.prefix.chunk_keys(self._context(req))
        req._prefix_keys = keys
        if not keys:
            return 0
        matched = self.pool.prefix.match(keys)
        if self._draft_pool is not None:
            matched = min(matched,
                          self._draft_pool.prefix.match(keys))
        if matched:
            self.pool.restore_prefix(req.slot, keys, matched)
            if self._draft_pool is not None:
                self._draft_pool.restore_prefix(req.slot, keys, matched)
            req._prefill_pos = matched * self.prefill_chunk
            self.metrics.on_prefix_restore(
                req.rid, matched, matched * self.prefill_chunk)
        return matched

    def _prefill_one_chunk(self, req: Request) -> None:
        # chunks cover prompt[:-1] — the K/V everyone after needs; the
        # final prompt token goes through the decode step below, whose
        # logits yield the request's first generated token (the exact
        # split the one-shot path computes inside one big call)
        c = self.prefill_chunk
        pos = req._prefill_pos
        ctx = self._context(req)
        n_prefill = ctx.size - 1
        valid = min(c, n_prefill - pos)
        chunk = np.zeros((1, c), np.int32)
        chunk[0, :valid] = ctx[pos:pos + valid]
        chunk = jnp.asarray(chunk)
        self.pool.cache = _prefill_chunk_prog(
            self._params, self.pool.cache, jnp.int32(req.slot),
            chunk, jnp.int32(valid), cfg=self.cfg)
        if self._draft_pool is not None:
            # the draft model needs the SAME context in its own cache;
            # its chunk rides the target's budget slot (one admission
            # unit of work, two trees)
            self._draft_pool.cache = _prefill_chunk_prog(
                self._draft_params, self._draft_pool.cache,
                jnp.int32(req.slot), chunk, jnp.int32(valid),
                cfg=self.draft_cfg)
        self.metrics.on_prefill_chunk()
        if (valid == c and req._prefix_keys
                and pos // c < len(req._prefix_keys)):
            # a FULL cold chunk just landed on the chunk grid — stash
            # its K/V while it provably matches the chain hash
            key = req._prefix_keys[pos // c]
            self.pool.stash_chunk(req.slot, key, pos)
            if self._draft_pool is not None:
                self._draft_pool.stash_chunk(req.slot, key, pos)
        req._prefill_pos = pos + valid
        if req._prefill_pos < n_prefill:
            return  # more chunks to go; decodes keep running meanwhile
        self._admitting = None
        self._running[req.slot] = req
        req.state = DECODE

    def _decode_step(self, decoding: Dict[int, Request]) -> None:
        cap = self.pool.capacity
        toks = np.zeros((cap,), np.int32)
        active = np.zeros((cap,), bool)
        keys = np.zeros((cap, 2), np.uint32)
        counts = np.zeros((cap,), np.int32)
        temps = np.zeros((cap,), np.float32)
        for slot, req in decoding.items():
            # first step after prefill consumes the LAST prompt token
            # (writing its K/V and sampling the first generated token);
            # afterwards the request's own stream feeds back
            toks[slot] = req.tokens[-1] if req.tokens else req.prompt[-1]
            active[slot] = True
            keys[slot] = np.asarray(jax.random.PRNGKey(req.seed))
            counts[slot] = len(req.tokens)
            temps[slot] = req.temperature
        self.pool.cache, hist = _decode_step_prog(
            self._params, self.pool.cache, jnp.asarray(toks),
            jnp.asarray(active), jnp.asarray(keys), jnp.asarray(counts),
            jnp.asarray(temps), cfg=self.cfg,
            horizon=self.decode_horizon)
        hist = np.asarray(hist)  # the per-step host sync: tokens stream
        now = self.clock()
        for slot, req in decoding.items():
            for j in range(self.decode_horizon):
                first = not req.tokens
                req.tokens.append(int(hist[j, slot]))
                if first:
                    self.metrics.on_first_token(req.rid, now)
                else:
                    self.metrics.on_token(req.rid, now)
                if self._maybe_finish(req):
                    break  # surplus horizon tokens for a retired slot
                    # are discarded (its cache is zeroed on free)

    def _spec_decode_step(self, decoding: Dict[int, Request]) -> None:
        """The speculative twin of :meth:`_decode_step`: one resident
        draft/verify program advances every active slot by 1 to
        ``lookahead+1`` tokens.  The host appends each slot's emitted
        run with the same EOS/budget truncation the plain path applies —
        surplus accepted tokens past a retirement are discarded (the
        freed slot's index reset makes their cache writes
        unobservable)."""
        cap = self.pool.capacity
        toks = np.zeros((cap,), np.int32)
        active = np.zeros((cap,), bool)
        keys = np.zeros((cap, 2), np.uint32)
        counts = np.zeros((cap,), np.int32)
        temps = np.zeros((cap,), np.float32)
        for slot, req in decoding.items():
            toks[slot] = req.tokens[-1] if req.tokens else req.prompt[-1]
            active[slot] = True
            keys[slot] = np.asarray(jax.random.PRNGKey(req.seed))
            counts[slot] = len(req.tokens)
            temps[slot] = req.temperature
        (self.pool.cache, self._draft_pool.cache, hist,
         n_emit) = _spec_step_prog(
            self._params, self._draft_params, self.pool.cache,
            self._draft_pool.cache, jnp.asarray(toks),
            jnp.asarray(active), jnp.asarray(keys), jnp.asarray(counts),
            jnp.asarray(temps), cfg_t=self.cfg, cfg_d=self.draft_cfg,
            k=self._spec.lookahead)
        hist = np.asarray(hist)      # [cap, lookahead+1]
        n_emit = np.asarray(n_emit)  # [cap]
        now = self.clock()
        emitted = 0
        for slot, req in decoding.items():
            for j in range(int(n_emit[slot])):
                first = not req.tokens
                req.tokens.append(int(hist[slot, j]))
                emitted += 1
                if first:
                    self.metrics.on_first_token(req.rid, now)
                else:
                    self.metrics.on_token(req.rid, now)
                if self._maybe_finish(req):
                    break  # surplus accepted tokens for a retired slot
                    # are discarded (index reset on free)
        self.metrics.on_spec_step(len(decoding), emitted)

    def _maybe_finish(self, req: Request) -> bool:
        hit_eos = (req.eos_id is not None
                   and req.tokens[-1] == req.eos_id)
        if hit_eos or len(req.tokens) >= req.max_new_tokens:
            self._retire(req, COMPLETED, self.clock())
            return True
        return False

    def _flush_resident(self, req: Request) -> int:
        """Flush a resident request's WRITTEN full K/V chunks into the
        shared prefix cache — the drain migration path: a request
        completing or handing off mid-drain leaves its context behind so
        the replica inheriting the conversation restores instead of
        recomputing.  Only positions actually written are eligible: a
        PREFILL resident has written ``_prefill_pos``; a DECODE one has
        written ``context − 1`` positions (the final token's K/V lands
        with its NEXT decode step, which will not run here)."""
        if self.pool.prefix is None or req.slot is None:
            return 0
        c = self.prefill_chunk
        ctx = self._context(req)
        keys = self.pool.prefix.chunk_keys(ctx)
        written = (req._prefill_pos if req.state == PREFILL
                   else ctx.size - 1)
        flushed = 0
        for i in range(min(len(keys), written // c)):
            if keys[i] not in self.pool.prefix:
                self.pool.stash_chunk(req.slot, keys[i], i * c)
                flushed += 1
            if (self._draft_pool is not None
                    and keys[i] not in self._draft_pool.prefix):
                self._draft_pool.stash_chunk(req.slot, keys[i], i * c)
        return flushed

    def _retire(self, req: Request, outcome: str, now: float) -> None:
        if req is self._admitting:
            self._admitting = None
        if self._draining and outcome in (COMPLETED, FAILOVER):
            self._drain_flushed += self._flush_resident(req)
        if req.slot is not None:
            self._running.pop(req.slot, None)
            self.pool.free(req.slot)
            if self._draft_pool is not None:
                self._draft_pool.free(req.slot)
            req.slot = None
        req.state = outcome
        self.metrics.on_retire(req.rid, now, outcome)
