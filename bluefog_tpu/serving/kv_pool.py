"""Slot-pooled K/V caches for the continuous-batching engine.

One resident jitted program serves many requests by giving every request
a SLOT: index ``i`` of a fixed-capacity stacked cache tree whose leaves
are ``[capacity, *single_request_cache_shape]`` (the shapes
:func:`bluefog_tpu.models.generate.init_cache` builds for batch size 1,
in either the full-precision or the int8+scale layout).  Slot shapes are
functions of ``(capacity, max_len)`` only — never of the arrival
pattern — which is what keeps the engine free of recompiles.

Allocation is host-side bookkeeping (a free list); the device tree is
mutated only through the engine's jitted programs.  Freeing a slot
zeroes it with one jitted donated scatter, so a reused slot starts from
the exact state a fresh pool has — "slot reuse is invisible" is a
testable property, not an argument about masked garbage.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional

import jax
import jax.numpy as jnp

from bluefog_tpu.models.generate import decode_config, init_cache
from bluefog_tpu.models.llama import LlamaConfig

__all__ = ["SlotPool"]


@partial(jax.jit, donate_argnums=(0,))
def _zero_slot(pool, slot):
    return jax.tree.map(
        lambda leaf: leaf.at[slot].set(jnp.zeros((), leaf.dtype)), pool)


class SlotPool:
    """Fixed-capacity pool of per-request K/V caches.

    Args:
      cfg: the model's config (training layout fine — normalized through
        :func:`decode_config` internally, same as ``llama_generate``).
      capacity: number of resident request slots.  Decode advances ALL
        slots every step (inactive ones are masked), so capacity is the
        decode batch size the hardware is sized for.
      max_len: per-slot cache length (prompt + generation budget ceiling
        for any single request).
      kv_quant: "none" | "int8" — the cache layout
        (``models/generate.py``); int8 halves decode's cache traffic.
    """

    def __init__(self, cfg: LlamaConfig, capacity: int, max_len: int,
                 kv_quant: str = "none"):
        if capacity < 1:
            raise ValueError(f"capacity ({capacity}) must be >= 1")
        dcfg = decode_config(cfg, max_len, kv_quant=kv_quant)
        slot_shapes = jax.eval_shape(
            lambda: init_cache(dcfg, 1, max_len, kv_quant=kv_quant))
        self.cache = jax.tree.map(
            lambda s: jnp.zeros((capacity,) + s.shape, s.dtype),
            slot_shapes)
        self.capacity = capacity
        self.max_len = max_len
        self.kv_quant = kv_quant
        self._free: List[int] = list(range(capacity - 1, -1, -1))
        self._in_use: set = set()

    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def n_active(self) -> int:
        return len(self._in_use)

    def occupancy(self) -> float:
        """Fraction of slots holding a live request (a serving metric:
        idle slots are decode compute spent on nothing)."""
        return len(self._in_use) / self.capacity

    def alloc(self) -> Optional[int]:
        """Claim a slot, or ``None`` when the pool is full (the scheduler
        turns ``None`` into queueing/backpressure — the pool never
        blocks)."""
        if not self._free:
            return None
        slot = self._free.pop()
        self._in_use.add(slot)
        return slot

    def free(self, slot: int) -> None:
        """Return ``slot`` to the pool and zero its cache (index AND
        contents), so the next request admitted into it sees exactly the
        fresh-pool state."""
        if slot not in self._in_use:
            raise ValueError(f"slot {slot} is not allocated")
        self._in_use.remove(slot)
        self._free.append(slot)
        self.cache = _zero_slot(self.cache, jnp.int32(slot))
