"""Slot-pooled K/V caches for the continuous-batching engine.

One resident jitted program serves many requests by giving every request
a SLOT: index ``i`` of a fixed-capacity stacked cache tree whose leaves
are ``[capacity, *single_request_cache_shape]`` (the shapes
:func:`bluefog_tpu.models.generate.init_cache` builds for batch size 1,
in either the full-precision or the int8+scale layout).  Slot shapes are
functions of ``(capacity, max_len)`` only — never of the arrival
pattern — which is what keeps the engine free of recompiles.

Allocation is host-side bookkeeping (a free list); the device tree is
mutated only through the engine's jitted programs.  Freeing a slot
resets its ``cache_index`` leaves (one tiny jitted scatter) — that alone
makes reuse exact, because everything above the index sits behind the
causal mask and the next request overwrites positions as it writes them.
``BLUEFOG_KV_ZERO_ON_FREE=1`` (or ``zero_on_free=True``) additionally
zeroes the slot's contents: a whole-slot HBM write per retirement that
buys nothing for correctness (tests assert bit-exactness BOTH ways) but
makes "reuse leaves no trace" literal — the debugging mode.  It also
destroys K/V a :class:`~bluefog_tpu.serving.prefix_cache.PrefixCache`
could have stashed, which is why retention-friendly index-reset is the
default.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from bluefog_tpu.models.generate import decode_config, init_cache
from bluefog_tpu.models.llama import LlamaConfig

__all__ = ["SlotPool"]


@partial(jax.jit, donate_argnums=(0,))
def _zero_slot(pool, slot):
    return jax.tree.map(
        lambda leaf: leaf.at[slot].set(jnp.zeros((), leaf.dtype)), pool)


@partial(jax.jit, donate_argnums=(0,))
def _reset_index_slot(pool, slot):
    """Zero only ``slot``'s ``cache_index`` leaves — scalar writes
    instead of a whole-slot scatter.  The index is the only state a
    fresh admission observes: K/V above it is causally masked and gets
    overwritten position by position as the new request prefills."""
    def fix(path, leaf):
        if getattr(path[-1], "key", None) == "cache_index":
            return leaf.at[slot].set(jnp.zeros((), leaf.dtype))
        return leaf

    return jax.tree_util.tree_map_with_path(fix, pool)


class SlotPool:
    """Fixed-capacity pool of per-request K/V caches.

    Args:
      cfg: the model's config (training layout fine — normalized through
        :func:`decode_config` internally, same as ``llama_generate``).
      capacity: number of resident request slots.  Decode advances ALL
        slots every step (inactive ones are masked), so capacity is the
        decode batch size the hardware is sized for.
      max_len: per-slot cache length (prompt + generation budget ceiling
        for any single request).
      kv_quant: "none" | "int8" — the cache layout
        (``models/generate.py``); int8 halves decode's cache traffic.
      zero_on_free: ``True`` zeroes a freed slot's whole cache; the
        default (``None``) follows ``BLUEFOG_KV_ZERO_ON_FREE`` (off —
        only the ``cache_index`` leaves reset, see module docstring).
      prefix: an optional
        :class:`~bluefog_tpu.serving.prefix_cache.PrefixCache` whose
        ``chunk`` is the engine's prefill chunk; enables
        :meth:`restore_prefix` / :meth:`stash_chunk`.
    """

    def __init__(self, cfg: LlamaConfig, capacity: int, max_len: int,
                 kv_quant: str = "none",
                 zero_on_free: Optional[bool] = None,
                 prefix=None):
        if capacity < 1:
            raise ValueError(f"capacity ({capacity}) must be >= 1")
        if zero_on_free is None:
            from bluefog_tpu import config as bfconfig

            zero_on_free = bfconfig.kv_zero_on_free()
        dcfg = decode_config(cfg, max_len, kv_quant=kv_quant)
        slot_shapes = jax.eval_shape(
            lambda: init_cache(dcfg, 1, max_len, kv_quant=kv_quant))
        self.cache = jax.tree.map(
            lambda s: jnp.zeros((capacity,) + s.shape, s.dtype),
            slot_shapes)
        self.capacity = capacity
        self.max_len = max_len
        self.kv_quant = kv_quant
        self.zero_on_free = bool(zero_on_free)
        self.prefix = prefix
        self._seq_axes = None
        if prefix is not None:
            from bluefog_tpu.serving.prefix_cache import seq_axes

            if max_len % prefix.chunk != 0:
                raise ValueError(
                    f"prefix cache chunk ({prefix.chunk}) must divide "
                    f"max_len ({max_len}) — restores land on the same "
                    f"chunk grid prefill writes")
            self._seq_axes = seq_axes(cfg, max_len, kv_quant)
        self._free: List[int] = list(range(capacity - 1, -1, -1))
        self._in_use: set = set()

    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def n_active(self) -> int:
        return len(self._in_use)

    def occupancy(self) -> float:
        """Fraction of slots holding a live request (a serving metric:
        idle slots are decode compute spent on nothing)."""
        return len(self._in_use) / self.capacity

    def alloc(self) -> Optional[int]:
        """Claim a slot, or ``None`` when the pool is full (the scheduler
        turns ``None`` into queueing/backpressure — the pool never
        blocks)."""
        if not self._free:
            return None
        slot = self._free.pop()
        self._in_use.add(slot)
        return slot

    def free(self, slot: int) -> None:
        """Return ``slot`` to the pool.  Resets the slot's cache index
        (always — a stale index would misplace the next request's
        prefill); zeroes the contents too only under ``zero_on_free``."""
        if slot not in self._in_use:
            raise ValueError(f"slot {slot} is not allocated")
        self._in_use.remove(slot)
        self._free.append(slot)
        if self.zero_on_free:
            self.cache = _zero_slot(self.cache, jnp.int32(slot))
        else:
            self.cache = _reset_index_slot(self.cache, jnp.int32(slot))

    # -- prefix reuse --------------------------------------------------- #
    def restore_prefix(self, slot: int, keys,
                       n: Optional[int] = None) -> int:
        """Copy the longest cached run of ``keys``'s chunks into
        ``slot`` (ascending, so ``cache_index`` ends at the restored
        length) and return how many chunks were restored.  ``n`` caps
        the run when the caller already matched (the speculative engine
        restores the MINIMUM of the target/draft matches into both
        pools).  Each restore is one device copy — the prefill forward
        it replaces is the savings."""
        if self.prefix is None:
            return 0
        matched = self.prefix.match(keys) if n is None else int(n)
        for i in range(matched):
            self._restore_one(slot, keys[i], i * self.prefix.chunk)
        return matched

    def _restore_one(self, slot: int, key: str, pos: int) -> None:
        from bluefog_tpu.serving.prefix_cache import _restore_chunk_prog

        self.cache = _restore_chunk_prog(
            self.cache, jnp.int32(slot), jnp.int32(pos),
            [jnp.asarray(a) for a in self.prefix.get(key)],
            axes=self._seq_axes, chunk=self.prefix.chunk)

    def stash_chunk(self, slot: int, key: str, pos: int) -> None:
        """Pull the chunk at grid position ``pos`` out of ``slot`` and
        retain it under ``key`` (no-op without a prefix cache).  Called
        by the engine right after a FULL cold chunk prefills — the K/V
        is extracted while it provably matches the chain hash."""
        if self.prefix is None:
            return
        from bluefog_tpu.serving.prefix_cache import _extract_chunk_prog

        leaves = _extract_chunk_prog(self.cache, jnp.int32(slot),
                                     jnp.int32(pos), axes=self._seq_axes,
                                     chunk=self.prefix.chunk)
        self.prefix.insert(key, [np.asarray(leaf) for leaf in leaves])
