"""Chunk-hashed prefix/KV reuse for the serving slot pool.

Fleet traffic is prefix-heavy: every request behind one front end opens
with the same system prompt, and chat turns replay their whole history.
Cold chunked prefill re-runs the model over those shared tokens on every
admission.  This module keeps the K/V of already-computed PROMPT CHUNKS
in a bounded host-side cache so a new request admits by COPYING cached
chunks into its slot and prefilling only its novel tail.

Design points that make this exact rather than approximate:

* **Chain hashing over whole chunks.**  A chunk's K/V depends on every
  token before it (attention is causal but K/V projections see the whole
  prefix through earlier layers' attention), so chunk *i*'s key is
  ``sha256(key_{i-1} ‖ tokens_i)`` — two requests share a cached chunk
  iff they share the ENTIRE token prefix up to its end.  A hash hit is a
  semantic guarantee, not a heuristic.
* **Only FULL chunks of ``prompt[:-1]`` are cached.**  Chunked prefill
  covers ``prompt[:-1]`` (the last prompt token rides the first decode
  step), and a partial tail chunk's K/V window is not aligned to the
  chunk grid — misaligned tails simply prefill cold, which keeps the
  restore path a pure chunk-grid copy and the exactness argument one
  sentence: a restored chunk is bit-identical to the chunk prefill that
  produced it.
* **The cache stores device bytes, not activations.**  Extraction
  slices a chunk window out of every seq-axis leaf of the pooled cache
  (one jitted gather program); restore writes it back at the same grid
  position in another slot and sets the slot's ``cache_index`` — the
  same "garbage above the index is invisible" invariant the engine's
  padded chunks already rely on covers everything above the restored
  prefix.
* **Bounded, LRU.**  Host memory is the budget
  (``BLUEFOG_PREFIX_CACHE_MB``); insertion evicts least-recently-USED
  entries.  Eviction only loses a future shortcut, never correctness.

The per-leaf sequence axis is detected structurally: the cache tree is
shape-evaluated at two ``max_len`` values and the axis that scales is
the sequence axis (leaves with no scaling axis — ``cache_index`` — are
index leaves).  That keeps this module layout-agnostic: full-precision
and int8+scale K/V layouts, unrolled and scanned layer stacks, all work
from the same two programs.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bluefog_tpu.models.generate import init_cache
from bluefog_tpu.models.llama import LlamaConfig

__all__ = ["PrefixCache", "seq_axes"]


def seq_axes(cfg: LlamaConfig, max_len: int,
             kv_quant: str = "none") -> Tuple[Optional[int], ...]:
    """Per-leaf sequence axis of the SINGLE-REQUEST cache tree, in
    ``jax.tree.leaves`` order (None for index leaves).  Detected by
    comparing the cache's shapes at two cache lengths — the axis that
    scales with ``max_len`` is the sequence axis — so new layouts never
    need a registry entry here."""
    a = jax.eval_shape(lambda: init_cache(cfg, 1, max_len,
                                          kv_quant=kv_quant))
    b = jax.eval_shape(lambda: init_cache(cfg, 1, 2 * max_len,
                                          kv_quant=kv_quant))
    axes: List[Optional[int]] = []
    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        diff = [i for i, (sa, sb) in enumerate(zip(la.shape, lb.shape))
                if sa != sb]
        if not diff:
            axes.append(None)  # cache_index-style leaf
        elif len(diff) == 1:
            axes.append(diff[0])
        else:
            raise ValueError(
                f"cache leaf {la.shape} scales {len(diff)} axes with "
                f"max_len; prefix extraction needs exactly one")
    return tuple(axes)


@partial(jax.jit, static_argnames=("axes", "chunk"))
def _extract_chunk_prog(pool, slot, pos, axes, chunk: int):
    """Slice ``slot``'s K/V window ``[pos, pos+chunk)`` out of every
    seq-axis leaf (index leaves skipped).  Shapes depend on
    ``(axes, chunk)`` only — one compiled program per pool layout."""
    out = []
    for leaf, ax in zip(jax.tree.leaves(pool), axes):
        if ax is None:
            continue
        row = lax.dynamic_index_in_dim(leaf, slot, 0, keepdims=False)
        out.append(lax.dynamic_slice_in_dim(row, pos, chunk, axis=ax))
    return out


@partial(jax.jit, static_argnames=("axes", "chunk"), donate_argnums=(0,))
def _restore_chunk_prog(pool, slot, pos, chunk_leaves, axes, chunk: int):
    """Write one cached chunk back into ``slot`` at grid position
    ``pos`` and set the slot's ``cache_index`` leaves to ``pos+chunk``
    (restores run in ascending chunk order, so the last write leaves the
    index at the full restored length).  The donated in-place update is
    the same cost shape as a prefill chunk's K/V write — without the
    model forward in front of it."""
    leaves = jax.tree.leaves(pool)
    treedef = jax.tree.structure(pool)
    it = iter(chunk_leaves)
    new = []
    for leaf, ax in zip(leaves, axes):
        if ax is None:
            row = jnp.full(leaf.shape[1:], pos + chunk, leaf.dtype)
            new.append(lax.dynamic_update_index_in_dim(leaf, row, slot, 0))
            continue
        row = lax.dynamic_index_in_dim(leaf, slot, 0, keepdims=False)
        row = lax.dynamic_update_slice_in_dim(row, next(it), pos, axis=ax)
        new.append(lax.dynamic_update_index_in_dim(leaf, row, slot, 0))
    return jax.tree.unflatten(treedef, new)


class PrefixCache:
    """Bounded host-side LRU of prompt-chunk K/V, keyed by chain hash.

    One instance serves one :class:`~bluefog_tpu.serving.SlotPool` (the
    speculative engine runs a lockstep PAIR — target and draft K/V are
    different tensors for the same tokens).  ``capacity_bytes`` bounds
    the numpy payload; ``0`` disables retention (every ``insert`` is
    dropped), which is also the ``BLUEFOG_PREFIX_CACHE_MB=0`` escape
    hatch."""

    def __init__(self, chunk: int, capacity_bytes: Optional[int] = None):
        if chunk < 1:
            raise ValueError(f"chunk ({chunk}) must be >= 1")
        if capacity_bytes is None:
            from bluefog_tpu import config as bfconfig

            capacity_bytes = bfconfig.prefix_cache_mb() << 20
        self.chunk = int(chunk)
        self.capacity_bytes = int(capacity_bytes)
        self._store: "OrderedDict[str, List[np.ndarray]]" = OrderedDict()
        self._nbytes = 0
        # observability (the engine folds these into its summary)
        self.hits = 0
        self.misses = 0
        self.insertions = 0
        self.evictions = 0

    # -- keys ---------------------------------------------------------- #
    def chunk_keys(self, prompt: np.ndarray) -> List[str]:
        """Chain-hash keys of the FULL chunks of ``prompt[:-1]`` (the
        prefill region).  ``keys[i]`` commits to every token through the
        end of chunk *i*, so equal keys mean equal whole prefixes."""
        toks = np.asarray(prompt, np.int32).reshape(-1)
        n_full = max(toks.size - 1, 0) // self.chunk
        h = hashlib.sha256(f"prefix:{self.chunk}".encode())
        keys = []
        for i in range(n_full):
            h = h.copy()
            h.update(toks[i * self.chunk:(i + 1) * self.chunk].tobytes())
            keys.append(h.hexdigest())
        return keys

    # -- store --------------------------------------------------------- #
    def match(self, keys: Sequence[str]) -> int:
        """Length (in chunks) of the longest cached prefix of ``keys``,
        touching each hit for LRU.  Chain keys make this a simple walk:
        a miss at chunk *i* means chunk *j > i* can never hit (its key
        commits to *i*'s tokens too — it was inserted through the same
        chain or not at all)."""
        n = 0
        for k in keys:
            if k not in self._store:
                self.misses += 1
                break
            self._store.move_to_end(k)
            self.hits += 1
            n += 1
        return n

    def get(self, key: str) -> List[np.ndarray]:
        return self._store[key]

    def insert(self, key: str, leaves: Sequence[np.ndarray]) -> None:
        if key in self._store:
            self._store.move_to_end(key)
            return
        payload = [np.asarray(leaf) for leaf in leaves]
        size = sum(a.nbytes for a in payload)
        if size > self.capacity_bytes:
            return  # a chunk larger than the whole budget never fits
        while self._nbytes + size > self.capacity_bytes and self._store:
            _, old = self._store.popitem(last=False)
            self._nbytes -= sum(a.nbytes for a in old)
            self.evictions += 1
        self._store[key] = payload
        self._nbytes += size
        self.insertions += 1

    @property
    def nbytes(self) -> int:
        return self._nbytes

    def __len__(self) -> int:
        return len(self._store)

    def __contains__(self, key: str) -> bool:
        """Membership WITHOUT touching LRU order or hit/miss counters —
        the drain flush asks "already cached?" before paying a device
        extract; that probe must not distort the reuse statistics."""
        return key in self._store

    def stats(self) -> Dict[str, float]:
        total = self.hits + self.misses
        return {
            "entries": len(self._store),
            "bytes": self._nbytes,
            "hits": self.hits,
            "misses": self.misses,
            "hit_rate": (self.hits / total) if total else 0.0,
            "insertions": self.insertions,
            "evictions": self.evictions,
        }
