"""Continuous-batching serving engine, fleet-scale.

Turns the K/V-cached decode substrate (``models/generate.py``,
``models/quant.py``, ``parallel/pallas_decode.py``) into the serving
path the ROADMAP north star requires: a slot-pooled resident program
that admits requests as they arrive, mixes chunked prefill with batched
decode every step, and retires slots on EOS / budget / deadline —
no recompiles across arrival patterns, token-exact with the one-shot
``llama_generate`` path.  On top of the single engine: chunk-hashed
prefix/KV reuse (``prefix_cache``), speculative decoding as a resident
draft/verify program pair (``SpeculativeConfig``), and a decentralized
multi-replica router fed by gossiped serving gauges (``fleet``).  See
docs/serving.md.
"""

from bluefog_tpu.serving.engine import (Request, RequestRejected,
                                        ServingEngine, SpeculativeConfig)
from bluefog_tpu.serving.fleet import (FleetRouter, FleetSaturated,
                                       RouterSnapshot,
                                       collect_serving_signals)
from bluefog_tpu.serving.kv_pool import SlotPool
from bluefog_tpu.serving.metrics import ServingMetrics, percentile
from bluefog_tpu.serving.prefix_cache import PrefixCache
from bluefog_tpu.serving.resilience import (FaultyReplica, backoff_sleep,
                                            failover_stranded,
                                            seeded_backoff)
from bluefog_tpu.serving.scheduler import FifoScheduler

__all__ = ["ServingEngine", "Request", "RequestRejected",
           "SpeculativeConfig", "SlotPool", "PrefixCache",
           "FleetRouter", "FleetSaturated", "RouterSnapshot",
           "collect_serving_signals", "FifoScheduler", "ServingMetrics",
           "percentile", "FaultyReplica", "failover_stranded",
           "seeded_backoff", "backoff_sleep"]
