"""Continuous-batching serving engine.

Turns the K/V-cached decode substrate (``models/generate.py``,
``models/quant.py``, ``parallel/pallas_decode.py``) into the serving
path the ROADMAP north star requires: a slot-pooled resident program
that admits requests as they arrive, mixes chunked prefill with batched
decode every step, and retires slots on EOS / budget / deadline —
no recompiles across arrival patterns, token-exact with the one-shot
``llama_generate`` path.  See docs/serving.md.
"""

from bluefog_tpu.serving.engine import (Request, RequestRejected,
                                        ServingEngine)
from bluefog_tpu.serving.kv_pool import SlotPool
from bluefog_tpu.serving.metrics import ServingMetrics, percentile
from bluefog_tpu.serving.scheduler import FifoScheduler

__all__ = ["ServingEngine", "Request", "RequestRejected", "SlotPool",
           "FifoScheduler", "ServingMetrics", "percentile"]
