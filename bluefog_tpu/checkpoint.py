"""Checkpoint / resume.

The reference has no checkpoint subsystem — its examples rely on torch
``state_dict`` plus ``broadcast_optimizer_state`` for initial consistency
(reference torch/utility.py:89-216; SURVEY.md §5 recommends leaning on
orbax here and adding nothing bespoke).  This module is a thin orbax
wrapper specialized for decentralized training state:

* the whole rank-major train state (params/opt_state/aux, every leaf with a
  leading ``[n_ranks]`` axis) checkpoints as one pytree — each rank's
  *divergent* parameters are preserved exactly, which a naive "save rank 0"
  scheme would lose;
* restore re-applies the rank sharding over the current mesh, so a job can
  resume on a different device count only if the rank axis still matches
  (checked, with a clear error).

Usage::

    ckpt = bf.checkpoint.Checkpointer("/path/ckpts")
    ckpt.save(step, {"params": params, "opt_state": opt_state})
    state = ckpt.restore_latest(mesh)        # or .restore(step, mesh)
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bluefog_tpu.logging_util import get_logger

__all__ = ["Checkpointer"]

logger = get_logger()


class Checkpointer:
    def __init__(self, directory: str, max_to_keep: Optional[int] = None,
                 axis_name: str = "bf"):
        self.directory = os.path.abspath(directory)
        self.axis_name = axis_name
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep),
        )

    def save(self, step: int, state: Any, force: bool = False,
             blocking: bool = True) -> bool:
        """Save a (rank-major) pytree at ``step``.

        ``blocking=False`` returns as soon as the state is staged (orbax
        saves on a background thread): training overlaps the checkpoint
        I/O, the standard TPU recipe for large states.  Call
        :meth:`wait` (or the next ``save``/``close``, which serialize
        internally) before relying on the files being on disk — e.g.
        before an elastic-restart epoch reads them.
        """
        saved = self._mgr.save(
            step, args=ocp.args.StandardSave(state), force=force)
        if blocking:
            self._mgr.wait_until_finished()
        return saved

    def wait(self) -> None:
        """Block until every async save has committed to disk."""
        self._mgr.wait_until_finished()

    def all_steps(self):
        return sorted(self._mgr.all_steps())

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def _leaf_spec(self, shape, dtype, mesh: Mesh):
        """Shared leaf policy: scalars replicate; everything else must be
        rank-major over the mesh (checked, with a clear error)."""
        n = mesh.shape[self.axis_name]
        shape = tuple(shape)
        if not shape:  # scalar leaves (step counters etc.) replicate
            return jax.ShapeDtypeStruct(shape, dtype,
                                        sharding=NamedSharding(mesh, P()))
        if shape[0] != n:
            raise ValueError(
                f"checkpoint leaf has rank axis {shape[0]} but the mesh "
                f"has {n} ranks; resume on a matching '{self.axis_name}' "
                "axis size")
        return jax.ShapeDtypeStruct(
            shape, dtype, sharding=NamedSharding(mesh, P(self.axis_name)))

    def _restore_args(self, step: int, mesh: Optional[Mesh]):
        if mesh is None:
            return ocp.args.StandardRestore()
        item = self._mgr.item_metadata(step)
        return ocp.args.StandardRestore(
            jax.tree.map(lambda m: self._leaf_spec(m.shape, m.dtype, mesh),
                         item, is_leaf=lambda x: hasattr(x, "shape")))

    def restore(self, step: int, mesh: Optional[Mesh] = None,
                like: Any = None) -> Any:
        """Restore the pytree saved at ``step``; with ``mesh``, leaves come
        back sharded over the rank axis (otherwise host-local arrays).

        ``like``: an example pytree with the ORIGINAL container types
        (optax NamedTuple states etc.) — without it orbax returns plain
        dict/list containers, which optax transformations reject.  Leaf
        shapes/dtypes come from ``like``; array leaves are placed on the
        rank sharding (scalars replicate) when ``mesh`` is given.
        """
        if like is None:
            return self._mgr.restore(step, args=self._restore_args(step, mesh))
        if mesh is not None:
            def spec_of(leaf):
                if not hasattr(leaf, "dtype"):  # python scalars round-trip
                    return leaf
                return self._leaf_spec(np.shape(leaf), leaf.dtype, mesh)

            template = jax.tree.map(spec_of, like)
        else:
            template = like
        return self._mgr.restore(step, args=ocp.args.StandardRestore(template))

    def restore_latest(self, mesh: Optional[Mesh] = None,
                       like: Any = None) -> Any:
        """Restore the newest *restorable* step.

        A corrupt or partially-written latest step (truncated array file,
        interrupted save without a commit marker orbax still lists) must
        not kill an elastic restart when an older intact checkpoint
        exists: restore errors fall back to the next-newest step with a
        warning.  Caller-contract errors (the rank-axis mesh mismatch
        from ``_leaf_spec``) are NOT corruption and re-raise immediately
        — falling back would silently resume a mismatched world.  If no
        step restores, the newest step's error is re-raised."""
        steps = self.all_steps()
        if not steps:
            raise FileNotFoundError(
                f"no checkpoints under {self.directory}")
        first_error: Optional[Exception] = None
        for step in reversed(steps):
            try:
                return self.restore(step, mesh, like=like)
            except ValueError as exc:
                if "rank axis" in str(exc):
                    raise  # mesh mismatch: a caller error, not damage
                error = exc
            except Exception as exc:  # orbax surfaces many error types
                error = exc
            first_error = first_error or error
            logger.warning(
                "checkpoint step %d under %s is not restorable "
                "(%s: %s); falling back to the next-newest step",
                step, self.directory, type(error).__name__, error)
        raise first_error

    def close(self):
        self._mgr.close()
