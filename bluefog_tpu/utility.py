"""Model/optimizer state distribution helpers.

Reference parity: bluefog/torch/utility.py (broadcast_parameters:26,
allreduce_parameters:58, broadcast_optimizer_state:89).  Parameters are
pytrees whose leaves are rank-major ``[size, ...]`` arrays (or plain arrays,
which are treated as already-replicated and broadcast into rank-major form).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from bluefog_tpu import api

__all__ = [
    "broadcast_parameters",
    "allreduce_parameters",
    "broadcast_optimizer_state",
]


def _leaf_broadcast(leaf, root_rank: int):
    from bluefog_tpu.context import get_context

    ctx = get_context()
    arr = jnp.asarray(leaf)
    if arr.ndim >= 1 and arr.shape[0] == ctx.size():
        return api.broadcast(arr, root_rank)
    # Replicated leaf: tile into rank-major form from root's value.
    tiled = jnp.broadcast_to(arr[None], (ctx.size(),) + arr.shape)
    return api.broadcast(tiled, root_rank)


def broadcast_parameters(params: Any, root_rank: int = 0) -> Any:
    """Broadcast rank ``root_rank``'s parameters to every rank.
    Reference: torch/utility.py:26-55 (used to make initial models
    consistent)."""
    return jax.tree_util.tree_map(lambda p: _leaf_broadcast(p, root_rank), params)


def allreduce_parameters(params: Any) -> Any:
    """Average parameters across all ranks.
    Reference: torch/utility.py:58-86."""
    return jax.tree_util.tree_map(lambda p: api.allreduce(p, average=True), params)


def broadcast_optimizer_state(opt_state: Any, root_rank: int = 0) -> Any:
    """Broadcast optimizer state (an optax state pytree).
    Reference: torch/utility.py:89-216 — the reference walks torch
    state_dicts; optax states are already pytrees so a tree_map suffices.
    Non-array leaves (step counts etc.) pass through from root unchanged."""

    def bcast(leaf):
        if isinstance(leaf, (int, float, bool)) or leaf is None:
            return leaf
        try:
            return _leaf_broadcast(leaf, root_rank)
        except TypeError:
            return leaf

    return jax.tree_util.tree_map(bcast, opt_state)
