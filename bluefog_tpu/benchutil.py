"""Shared benchmark timing helpers.

Tunneled TPU backends make ``jax.block_until_ready`` a no-op, so the only
reliable device sync is fetching a value that depends on the computation.
That fetch carries one host<->device round trip, which these helpers
measure honestly: the overhead probe computes a FRESH value each time
(``x + 1``), because re-fetching the same jax.Array hits its cached host
copy and measures ~0.
"""

from __future__ import annotations

import time

import jax
import numpy as np

__all__ = ["device_fetch", "fetch_overhead", "timed"]


def device_fetch(a) -> np.ndarray:
    """Synchronize by materializing ``a`` on the host."""
    return np.asarray(jax.device_get(a))


def fetch_overhead(repeats: int = 3) -> float:
    """Median wall time of dispatching + fetching a fresh trivial
    computation — the per-sync overhead to subtract from timed loops."""
    x = jax.device_put(np.zeros(1, np.float32))
    y = x + 1.0
    device_fetch(y)  # compile outside timing
    times = []
    for i in range(repeats):
        t0 = time.perf_counter()
        device_fetch(x + float(i + 2))
        times.append(time.perf_counter() - t0)
    return float(np.median(times))


def timed(run_steps, sync_value_fn, overhead: float = None) -> float:
    """Run ``run_steps()`` (which enqueues work), sync via
    ``sync_value_fn()`` (returning a computation-dependent array), and
    return wall seconds with the fetch overhead subtracted."""
    if overhead is None:
        overhead = fetch_overhead()
    t0 = time.perf_counter()
    run_steps()
    device_fetch(sync_value_fn())
    return max(time.perf_counter() - t0 - overhead, 1e-9)
