"""Shared benchmark timing helpers.

Tunneled TPU backends make ``jax.block_until_ready`` a no-op, so the only
reliable device sync is fetching a value that depends on the computation.
That fetch carries one host<->device round trip, which these helpers
measure honestly: the overhead probe computes a FRESH value each time
(``x + 1``), because re-fetching the same jax.Array hits its cached host
copy and measures ~0.
"""

from __future__ import annotations

import re
import time

import jax
import numpy as np

__all__ = ["device_fetch", "fetch_overhead", "timed",
           "chain_time", "fwd_bwd_time",
           "chip_peak_flops", "chip_hbm_bandwidth", "compiled_step_flops",
           "mfu", "hlo_collective_bytes"]

# Dense bf16 peak FLOP/s per chip, from published TPU specs.  Keyed by
# substrings of jax's ``device_kind``; override with BLUEFOG_CHIP_PEAK_TFLOPS
# when the kind is unlisted (e.g. a new generation).
_PEAK_BF16_TFLOPS = (
    ("v6e", 918.0),      # Trillium
    ("v6", 918.0),
    ("v5p", 459.0),
    ("v5e", 197.0),
    ("v5 lite", 197.0),  # v5e's device_kind spelling in some releases
    ("v4", 275.0),
    ("v3", 123.0),
    ("v2", 45.0),
)


def chip_peak_flops(device=None) -> float:
    """Peak dense bf16 FLOP/s of one chip, or 0.0 when unknown (CPU test
    meshes).  Override: BLUEFOG_CHIP_PEAK_TFLOPS=<float>."""
    import os

    override = os.environ.get("BLUEFOG_CHIP_PEAK_TFLOPS")
    if override:
        return float(override) * 1e12
    if device is None:
        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "").lower()
    for key, tf in _PEAK_BF16_TFLOPS:
        if key in kind:
            return tf * 1e12
    return 0.0


# HBM bandwidth per chip (bytes/s), published specs; same keying and
# override pattern as the FLOPs table (BLUEFOG_CHIP_HBM_GBPS).
_HBM_GBPS = (
    ("v6e", 1638.0),
    ("v6", 1638.0),
    ("v5p", 2765.0),
    ("v5e", 819.0),
    ("v5 lite", 819.0),
    ("v4", 1228.0),
    ("v3", 900.0),
    ("v2", 700.0),
)


def chip_hbm_bandwidth(device=None) -> float:
    """HBM bandwidth of one chip in bytes/s, or 0.0 when unknown (CPU
    test meshes).  Override: BLUEFOG_CHIP_HBM_GBPS=<float>."""
    import os

    override = os.environ.get("BLUEFOG_CHIP_HBM_GBPS")
    if override:
        return float(override) * 1e9
    if device is None:
        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "").lower()
    for key, gbps in _HBM_GBPS:
        if key in kind:
            return gbps * 1e9
    return 0.0


def compiled_step_flops(jitted, *args) -> float:
    """Per-device FLOPs of one execution of ``jitted(*args)`` from XLA's
    own cost analysis of the optimized module — the hardware-honest count
    (rematerialized FLOPs included, which is what the chip executes).
    Returns 0.0 if the backend exposes no cost model."""
    try:
        compiled = jitted.lower(*args).compile()
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):  # older jax: one dict/device
            cost = cost[0]
        return float(cost.get("flops", 0.0))
    except Exception:
        return 0.0


def mfu(flops_per_step: float, step_seconds: float,
        peak_per_chip: float = None) -> float:
    """Model FLOPs utilization: achieved FLOP/s over peak FLOP/s.
    ``flops_per_step`` is PER DEVICE (as ``compiled_step_flops`` reports);
    returns 0.0 when the peak is unknown."""
    if peak_per_chip is None:
        peak_per_chip = chip_peak_flops()
    if not peak_per_chip or step_seconds <= 0:
        return 0.0
    return flops_per_step / step_seconds / peak_per_chip


_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
}

# one HLO collective instruction: `%name = TYPE op-name(%operand, ...)` —
# optimized HLO prints operands as bare names, so the payload shape is the
# RESULT type to the left of the op name (tuple types for fused/async ops)
_COLLECTIVE_RE = re.compile(
    r"=\s*(?P<types>[^=]*?)\s*\b(?P<op>collective-permute|all-reduce|"
    r"all-gather|reduce-scatter|all-to-all)(?P<suffix>-start|-done)?\(")
_SHAPE_RE = re.compile(r"\b(pred|[sub]8|[sufb]\d+|bf16)\[([0-9,]*)\]")


def hlo_collective_bytes(hlo_text: str) -> dict:
    """Per-collective-kind payload bytes of one execution of an optimized
    HLO module: ``{kind: {"count": n_instructions, "bytes": sum}}``.

    Bytes come from each collective's result type — the PER-DEVICE shard
    payload (tuple results summed; async ``-start`` skipped and counted
    at the matching ``-done`` so pairs are not double-counted).  For
    all-gather the result is the gathered buffer, an upper bound within
    (n-1)/n of the wire bytes.  Collectives inside ``conditional``
    branches (``lax.switch`` dynamic schedules) are all present in the
    module text but only one branch executes per step — callers divide by
    the branch count for per-step figures."""
    out: dict = {}
    # tuple types are printed with /*index=N*/ comments whose '=' would
    # truncate the types capture — strip them first
    hlo_text = re.sub(r"/\*.*?\*/", "", hlo_text)
    for m in _COLLECTIVE_RE.finditer(hlo_text):
        if m.group("suffix") == "-start":
            continue
        kind = m.group("op")
        nbytes = 0
        for sm in _SHAPE_RE.finditer(m.group("types")):
            dt, dims = sm.group(1), sm.group(2)
            n = 1
            for d in dims.split(","):
                if d:
                    n *= int(d)
            nbytes += n * _DTYPE_BYTES.get(dt, 4)
        rec = out.setdefault(kind, {"count": 0, "bytes": 0})
        rec["count"] += 1
        rec["bytes"] += nbytes
    return out


def device_fetch(a) -> np.ndarray:
    """Synchronize by materializing ``a`` on the host."""
    return np.asarray(jax.device_get(a))


def fetch_overhead(repeats: int = 3) -> float:
    """Median wall time of dispatching + fetching a fresh trivial
    computation — the per-sync overhead to subtract from timed loops."""
    x = jax.device_put(np.zeros(1, np.float32))
    y = x + 1.0
    device_fetch(y)  # compile outside timing
    times = []
    for i in range(repeats):
        t0 = time.perf_counter()
        device_fetch(x + float(i + 2))
        times.append(time.perf_counter() - t0)
    return float(np.median(times))


def timed(run_steps, sync_value_fn, overhead: float = None) -> float:
    """Run ``run_steps()`` (which enqueues work), sync via
    ``sync_value_fn()`` (returning a computation-dependent array), and
    return wall seconds with the fetch overhead subtracted."""
    if overhead is None:
        overhead = fetch_overhead()
    t0 = time.perf_counter()
    run_steps()
    device_fetch(sync_value_fn())
    return max(time.perf_counter() - t0 - overhead, 1e-9)


def chain_time(f, params, x0, n=20, reps=3):
    """Per-iteration seconds of ``x <- barrier(f(params, x)*eps + x0)``
    iterated INSIDE one jitted fori_loop — per-call tunnel dispatch is
    ~3 ms on this rig and would floor every sub-3ms op if the chain were
    a host loop.  ``params`` ride as jit ARGUMENTS (closure constants
    >100 MB overflow the remote compile transport).  Promoted verbatim
    from benchmarks/llama_roofline.py (round 5), whose composition
    reproduces the measured 1B train step exactly — the validation that
    makes this the trusted micro-timing harness on the tunnel rig.
    """
    import jax.numpy as jnp

    @jax.jit
    def chained(p, x):
        def body(i, x):
            y = f(p, x)
            if y.shape != x0.shape:
                # consume EVERY element (a slice would let XLA narrow
                # the producing dot to the sliced columns — observed as
                # a 116% "MFU" on the vocab head)
                y = jnp.mean(y.astype(jnp.float32), axis=-1,
                             keepdims=True)
                y = jnp.broadcast_to(y, x0.shape[:-1] + (1,))
            y = (y.astype(jnp.float32) * 1e-30).astype(x0.dtype)
            return jax.lax.optimization_barrier(x0 + y)
        return jax.lax.fori_loop(0, n, body, x)

    device_fetch(chained(params, x0)[..., :1])
    ov = fetch_overhead()
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        device_fetch(chained(params, x0)[..., :1])
        times.append((time.perf_counter() - t0 - ov) / n)
    return float(np.median(times))


def fwd_bwd_time(f, x0, params, n=20, reps=3):
    """fwd+bwd seconds of y = f(params, x) with grads wrt both, chained
    through dx inside one jitted fori_loop (see chain_time)."""
    import jax.numpy as jnp

    def loss(p, x):
        return jnp.sum(f(p, x).astype(jnp.float32) ** 2)

    grad = jax.grad(loss, argnums=(0, 1))

    @jax.jit
    def chained(p, x):
        def body(i, x):
            dp, dx = grad(p, x)
            # consume EVERY gradient: an unused dp would let XLA DCE
            # the dW matmuls and report a 2N-FLOP backward as 4N
            dp_sum = sum(jnp.sum(leaf.astype(jnp.float32)) * 1e-30
                         for leaf in jax.tree.leaves(dp))
            return jax.lax.optimization_barrier(
                (dx.astype(jnp.float32) * 1e-30 + dp_sum
                 ).astype(x0.dtype) + x0)
        return jax.lax.fori_loop(0, n, body, x)

    device_fetch(chained(params, x0)[..., :1])
    ov = fetch_overhead()
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        device_fetch(chained(params, x0)[..., :1])
        times.append((time.perf_counter() - t0 - ov) / n)
    return float(np.median(times))
