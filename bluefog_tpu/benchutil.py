"""Shared benchmark timing helpers.

Tunneled TPU backends make ``jax.block_until_ready`` a no-op, so the only
reliable device sync is fetching a value that depends on the computation.
That fetch carries one host<->device round trip, which these helpers
measure honestly: the overhead probe computes a FRESH value each time
(``x + 1``), because re-fetching the same jax.Array hits its cached host
copy and measures ~0.
"""

from __future__ import annotations

import re
import time

import jax
import numpy as np

__all__ = ["device_fetch", "fetch_overhead", "timed",
           "chain_time", "fwd_bwd_time", "poisson_arrivals",
           "chip_peak_flops", "chip_hbm_bandwidth", "compiled_step_flops",
           "mfu", "hlo_collective_bytes", "hlo_op_breakdown",
           "scheduled_collective_windows", "overlap_accounting",
           "LATENCY_HIDING_XLA_FLAGS", "latency_hiding_xla_flags",
           "bench_headline", "bench_compare", "bench_regression_gate"]

# Dense bf16 peak FLOP/s per chip, from published TPU specs.  Keyed by
# substrings of jax's ``device_kind``; override with BLUEFOG_CHIP_PEAK_TFLOPS
# when the kind is unlisted (e.g. a new generation).
_PEAK_BF16_TFLOPS = (
    ("v6e", 918.0),      # Trillium
    ("v6", 918.0),
    ("v5p", 459.0),
    ("v5e", 197.0),
    ("v5 lite", 197.0),  # v5e's device_kind spelling in some releases
    ("v4", 275.0),
    ("v3", 123.0),
    ("v2", 45.0),
)


def chip_peak_flops(device=None) -> float:
    """Peak dense bf16 FLOP/s of one chip, or 0.0 when unknown (CPU test
    meshes).  Override: BLUEFOG_CHIP_PEAK_TFLOPS=<float>."""
    from bluefog_tpu import config as bfconfig

    override = bfconfig.chip_peak_tflops_override()
    if override:
        return override * 1e12
    if device is None:
        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "").lower()
    for key, tf in _PEAK_BF16_TFLOPS:
        if key in kind:
            return tf * 1e12
    return 0.0


# HBM bandwidth per chip (bytes/s), published specs; same keying and
# override pattern as the FLOPs table (BLUEFOG_CHIP_HBM_GBPS).
_HBM_GBPS = (
    ("v6e", 1638.0),
    ("v6", 1638.0),
    ("v5p", 2765.0),
    ("v5e", 819.0),
    ("v5 lite", 819.0),
    ("v4", 1228.0),
    ("v3", 900.0),
    ("v2", 700.0),
)


def chip_hbm_bandwidth(device=None) -> float:
    """HBM bandwidth of one chip in bytes/s, or 0.0 when unknown (CPU
    test meshes).  Override: BLUEFOG_CHIP_HBM_GBPS=<float>."""
    from bluefog_tpu import config as bfconfig

    override = bfconfig.chip_hbm_gbps_override()
    if override:
        return override * 1e9
    if device is None:
        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "").lower()
    for key, gbps in _HBM_GBPS:
        if key in kind:
            return gbps * 1e9
    return 0.0


def compiled_step_flops(jitted, *args) -> float:
    """Per-device FLOPs of one execution of ``jitted(*args)`` from XLA's
    own cost analysis of the optimized module — the hardware-honest count
    (rematerialized FLOPs included, which is what the chip executes).
    Returns 0.0 if the backend exposes no cost model."""
    try:
        compiled = jitted.lower(*args).compile()
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):  # older jax: one dict/device
            cost = cost[0]
        return float(cost.get("flops", 0.0))
    except Exception:
        return 0.0


def mfu(flops_per_step: float, step_seconds: float,
        peak_per_chip: float = None) -> float:
    """Model FLOPs utilization: achieved FLOP/s over peak FLOP/s.
    ``flops_per_step`` is PER DEVICE (as ``compiled_step_flops`` reports);
    returns 0.0 when the peak is unknown."""
    if peak_per_chip is None:
        peak_per_chip = chip_peak_flops()
    if not peak_per_chip or step_seconds <= 0:
        return 0.0
    return flops_per_step / step_seconds / peak_per_chip


_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
}

# one HLO collective instruction: `%name = TYPE op-name(%operand, ...)` —
# optimized HLO prints operands as bare names, so the payload shape is the
# RESULT type to the left of the op name (tuple types for fused/async ops)
_COLLECTIVE_RE = re.compile(
    r"=\s*(?P<types>[^=]*?)\s*\b(?P<op>collective-permute|all-reduce|"
    r"all-gather|reduce-scatter|all-to-all)(?P<suffix>-start|-done)?\(")
_SHAPE_RE = re.compile(r"\b(pred|[sub]8|[sufb]\d+|bf16)\[([0-9,]*)\]")


def hlo_collective_bytes(hlo_text: str) -> dict:
    """Per-collective-kind payload bytes of one execution of an optimized
    HLO module: ``{kind: {"count": n_instructions, "bytes": sum}}``.

    Bytes come from each collective's result type — the PER-DEVICE shard
    payload (tuple results summed; async ``-start`` skipped and counted
    at the matching ``-done`` so pairs are not double-counted).  For
    all-gather the result is the gathered buffer, an upper bound within
    (n-1)/n of the wire bytes.  Collectives inside ``conditional``
    branches (``lax.switch`` dynamic schedules) are all present in the
    module text but only one branch executes per step — callers divide by
    the branch count for per-step figures."""
    out: dict = {}
    # tuple types are printed with /*index=N*/ comments whose '=' would
    # truncate the types capture — strip them first
    hlo_text = re.sub(r"/\*.*?\*/", "", hlo_text)
    for m in _COLLECTIVE_RE.finditer(hlo_text):
        if m.group("suffix") == "-start":
            continue
        kind = m.group("op")
        rec = out.setdefault(kind, {"count": 0, "bytes": 0})
        rec["count"] += 1
        rec["bytes"] += _shape_bytes(m.group("types"))
    return out


# ---------------------------------------------------------------------------
# Overlap accounting over the scheduled HLO module.
#
# The overlap engine (build_train_step(overlap="bucketed")) structures the
# program so the latency-hiding scheduler CAN overlap the decentralized
# exchange with compute; this section is the "prove it" half.  Two measures,
# one threshold:
#
# * overlap_available — schedule-INVARIANT: for each collective, the flops
#   of instructions that are neither its dataflow ancestors nor descendants
#   (compute that may legally execute while the transfer is in flight).
#   Computable from any lowering, including the CPU AOT audit modules
#   (benchmarks/llama_8b_structural.py style) where collectives lower
#   synchronously.
# * overlap_scheduled — what the scheduler DID: flops of instructions the
#   schedule placed inside each async ``-start``/``-done`` window.  Only
#   nonzero on async lowerings (TPU with the latency-hiding scheduler).
#
# A collective's payload counts as OVERLAPPABLE when the measured flops
# cover the payload's transfer time: flops/peak >= bytes*congestion/link.
# ---------------------------------------------------------------------------

# Flags that let the TPU latency-hiding scheduler overlap collectives with
# compute — set them identically for benchmarks and prod so measured overlap
# fractions transfer (append to XLA_FLAGS before jax initializes; NOTE the
# tunneled single-chip rig rejects client-side TPU flags — these are for
# real pods, see docs/performance.md).
LATENCY_HIDING_XLA_FLAGS = (
    "--xla_tpu_enable_latency_hiding_scheduler=true",
    "--xla_tpu_enable_async_collective_permute=true",
    "--xla_enable_async_all_gather=true",
    "--xla_tpu_enable_async_collective_fusion=true",
    "--xla_tpu_enable_async_collective_fusion_fuse_all_gather=true",
    # scheduler memory budget: HBM headroom the scheduler may spend
    # keeping transfers in flight instead of minimizing live ranges
    "--xla_tpu_scheduler_percent_shared_memory_limit=90",
)


def latency_hiding_xla_flags(extra: tuple = ()) -> str:
    """Merge ``LATENCY_HIDING_XLA_FLAGS`` (+ any extras) into the
    XLA_FLAGS environment string and return it; flags already present in
    the environment win (so a deployment can pin its own scheduler
    budget).  Call BEFORE the first jax import/initialization."""
    import os

    current = os.environ.get("XLA_FLAGS", "")
    have = {f.split("=")[0] for f in current.split() if f}
    add = [f for f in tuple(LATENCY_HIDING_XLA_FLAGS) + tuple(extra)
           if f.split("=")[0] not in have]
    merged = " ".join(filter(None, [current] + add))
    os.environ["XLA_FLAGS"] = merged
    return merged


_COLLECTIVE_OPS = ("collective-permute", "all-reduce", "all-gather",
                   "reduce-scatter", "all-to-all")
# `%name = <types> op(args...)[, attrs]` with optional ROOT; types may be a
# tuple `(f32[..], ...)`.  args are cut at the matching close-paren by hand.
_HLO_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?(?P<name>[\w.\-]+)\s*=\s*"
    r"(?P<types>\([^)]*\)|[^\s]+)\s+(?P<op>[\w\-]+)\((?P<rest>.*)$")
_OPERAND_RE = re.compile(r"%([\w.\-]+)")
_CALLS_RE = re.compile(r"calls=%?([\w.\-]+)")
_CONTRACT_RE = re.compile(r"lhs_contracting_dims=\{([0-9,]*)\}")
# computation header: `[ENTRY] %name (params...) -> type {` — the param
# list may contain nested parens (tuple-typed args of conditional
# branches / while bodies), so the name is captured up to the first "("
# and the rest of the line is only checked for the "-> ... {" tail
_COMP_HEADER_RE = re.compile(r"^(?:ENTRY\s+)?%?([\w.\-]+)\s*\(")

# ops that move/alias bytes or carry no arithmetic — zero flops
_ZERO_FLOP_OPS = frozenset((
    "parameter", "constant", "tuple", "get-tuple-element", "bitcast",
    "copy", "copy-start", "copy-done", "broadcast", "reshape", "transpose",
    "convert", "iota", "slice", "dynamic-slice", "dynamic-update-slice",
    "concatenate", "pad", "after-all", "partition-id", "replica-id",
    "custom-call", "send", "recv", "send-done", "recv-done",
    "opt-barrier", "optimization-barrier", "domain", "gather", "scatter",
))


def _shape_elems(type_text: str) -> int:
    """Total elements across every shape in an HLO type string."""
    total = 0
    for sm in _SHAPE_RE.finditer(type_text):
        n = 1
        for d in sm.group(2).split(","):
            if d:
                n *= int(d)
        total += n
    return max(total, 0)


def _shape_bytes(type_text: str) -> int:
    nbytes = 0
    for sm in _SHAPE_RE.finditer(type_text):
        dt, dims = sm.group(1), sm.group(2)
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        nbytes += n * _DTYPE_BYTES.get(dt, 4)
    return nbytes


def _dot_flops(types: str, rest: str) -> float:
    """2 * result_elems * contracted_size from the printed dot line:
    result type on the left, lhs operand type + lhs_contracting_dims on
    the right."""
    result_elems = _shape_elems(types)
    lhs_m = _SHAPE_RE.search(rest)
    cm = _CONTRACT_RE.search(rest)
    if not lhs_m or not cm:
        return 2.0 * result_elems  # malformed print; floor estimate
    lhs_dims = [int(d) for d in lhs_m.group(2).split(",") if d]
    contracted = 1
    for idx in (int(i) for i in cm.group(1).split(",") if i):
        if idx < len(lhs_dims):
            contracted *= lhs_dims[idx]
    return 2.0 * result_elems * contracted


def _parse_computations(hlo_text: str):
    """{computation_name: [instruction dicts in scheduled order]}.

    Each instruction: name, op, types, rest (text after the open paren),
    operands (referenced %names), line index within the computation."""
    hlo_text = re.sub(r"/\*.*?\*/", "", hlo_text)
    comps: dict = {}
    cur_name, cur_list = None, None
    for line in hlo_text.splitlines():
        if cur_name is None:
            st = line.strip()
            if st.endswith("{") and "->" in st and "=" not in st:
                m = _COMP_HEADER_RE.match(st)
                if m:
                    cur_name, cur_list = m.group(1), []
            continue
        if line.strip().startswith("}"):
            comps[cur_name] = cur_list
            cur_name, cur_list = None, None
            continue
        im = _HLO_INSTR_RE.match(line)
        if im:
            cur_list.append({
                "name": im.group("name"),
                "op": im.group("op"),
                "types": im.group("types"),
                "rest": im.group("rest"),
                "operands": _OPERAND_RE.findall(im.group("rest")),
                "idx": len(cur_list),
            })
    return comps


def _instr_flops(instr: dict, comps: dict, _memo: dict) -> float:
    """Estimated flops of one instruction: dots get the exact
    2*M*N*K; fusions add their called computation's dots to an
    elementwise sweep of the fusion result; reductions read their
    operand; other arithmetic ops count one flop per result element;
    pure data movement counts zero.  This intentionally mirrors what
    XLA's own cost analysis charges for the ops that matter here
    (collective-window compute is dominated by dots and elementwise
    fusions)."""
    op = instr["op"]
    if op in _ZERO_FLOP_OPS or any(op.startswith(c) for c in
                                   _COLLECTIVE_OPS):
        return 0.0
    if op == "dot":
        return _dot_flops(instr["types"], instr["rest"])
    if op == "fusion":
        cm = _CALLS_RE.search(instr["rest"])
        inner = 0.0
        if cm and cm.group(1) in comps:
            key = cm.group(1)
            if key not in _memo:
                _memo[key] = 0.0  # cycle guard
                _memo[key] = sum(
                    _instr_flops(i, comps, _memo)
                    for i in comps[key] if i["op"] != "fusion")
            inner = _memo[key]
        return inner + _shape_elems(instr["types"])
    if op in ("reduce", "reduce-window"):
        # a reduction reads every operand element once
        return float(_shape_elems(instr["rest"]))
    if op in ("while", "conditional", "call", "sort", "scatter"):
        return 0.0  # accounted inside their own computations
    return float(_shape_elems(instr["types"]))


def _instr_bytes_accessed(instr: dict) -> int:
    """Estimated HBM bytes an instruction touches: result + operand
    shapes from the printed line (elementwise compute is
    bandwidth-bound; its capacity to hide a transfer is bytes/HBM_bw,
    not flops/peak)."""
    return _shape_bytes(instr["types"]) + _shape_bytes(instr["rest"])


def scheduled_collective_windows(hlo_text: str) -> list:
    """One record per collective instruction of a (scheduled) HLO module:

    ``{kind, computation, bytes, async, window_flops,
    window_bytes_accessed, independent_flops,
    independent_bytes_accessed}``

    * ``window_flops`` — flops the SCHEDULE placed between the
      collective's ``-start`` and ``-done`` (async lowerings; 0 when the
      op lowered synchronously): compute that provably executes during
      the transfer.
    * ``independent_flops`` — flops of instructions in the same
      computation that are neither dataflow ancestors nor descendants of
      the collective: compute a latency-hiding scheduler MAY place in
      flight, measurable even from sync lowerings (CPU AOT audits).

    Bytes come from the result payload (the ``-done``/sync result), per
    device, same convention as :func:`hlo_collective_bytes`.
    """
    comps = _parse_computations(hlo_text)
    memo: dict = {}
    out = []
    for cname, instrs in comps.items():
        by_name = {i["name"]: i for i in instrs}
        flops = [_instr_flops(i, comps, memo) for i in instrs]
        # users map for ancestor/descendant walks
        users: dict = {i["name"]: [] for i in instrs}
        for i in instrs:
            for o in i["operands"]:
                if o in users:
                    users[o].append(i["name"])

        def _closure(start_name, direction):
            seen, stack = set(), [start_name]
            while stack:
                n = stack.pop()
                if n in seen or n not in by_name:
                    continue
                seen.add(n)
                nxt = (by_name[n]["operands"] if direction == "up"
                       else users.get(n, ()))
                stack.extend(nxt)
            return seen

        touched = [_instr_bytes_accessed(i) if f or i["op"] == "fusion"
                   else 0 for i, f in zip(instrs, flops)]
        for i in instrs:
            op = i["op"]
            kind = next((c for c in _COLLECTIVE_OPS
                         if op == c or op == c + "-start"), None)
            if kind is None:
                continue
            is_async = op.endswith("-start")
            done_idx = None
            if is_async:
                for j in instrs[i["idx"] + 1:]:
                    if (j["op"] == kind + "-done"
                            and i["name"] in j["operands"]):
                        done_idx = j["idx"]
                        break
            window = wbytes = 0.0
            if done_idx is not None:
                rng = range(i["idx"] + 1, done_idx)
                window = sum(flops[k] for k in rng)
                wbytes = sum(touched[k] for k in rng)
            blocked = _closure(i["name"], "up") | _closure(i["name"],
                                                           "down")
            independent = sum(
                f for j, f in zip(instrs, flops)
                if j["name"] not in blocked)
            ibytes = sum(
                t for j, t in zip(instrs, touched)
                if j["name"] not in blocked)
            payload = i["types"]
            if done_idx is not None:
                payload = instrs[done_idx]["types"]
            elif is_async:
                # unmatched start (done in another computation print):
                # charge the operand payload
                payload = i["rest"]
            out.append({
                "kind": kind,
                "computation": cname,
                "bytes": _shape_bytes(payload),
                "async": bool(is_async),
                "window_flops": float(window),
                "window_bytes_accessed": float(wbytes),
                "independent_flops": float(independent),
                "independent_bytes_accessed": float(ibytes),
            })
    return out


def _count_hlo_collectives(hlo_text: str, kind: str) -> int:
    """Instruction count of one collective ``kind`` in optimized HLO —
    sync spelling plus async ``-start`` (the start counted alone so an
    async pair is one op), the counting rule the HLO-guarantee tests
    always used."""
    return len(re.findall(re.escape(kind) + r"(?:-start)?\(", hlo_text))


def _expected_replica_groups(n_groups: int, group_size: int) -> str:
    """The ``replica_groups`` attribute text of a grouped all-reduce over
    contiguous rank blocks — machine g owns ranks
    ``[g*L, (g+1)*L)``, exactly how the hierarchical exchange groups."""
    groups = ",".join(
        "{" + ",".join(str(g * group_size + i) for i in range(group_size))
        + "}" for g in range(n_groups))
    return "replica_groups={" + groups + "}"


def verify_collective_contract(compiled, predicted, payload_bytes,
                               *, round_index=None) -> list:
    """Hold a lowered program to its declared collective sketch.

    ``compiled`` is optimized HLO text or anything with ``.as_text()``
    (a jit ``Compiled``); ``predicted`` is a
    ``CompiledTopology.predicted_collectives(payload_bytes)`` /
    ``CompiledHierarchicalTopology`` dict.  With ``round_index=None``
    the module is the full (e.g. ``lax.switch``) program and is checked
    against the per-period totals; with ``round_index=i`` it is round
    *i* lowered alone and is checked against ``per_round[i]``.

    Returns a list of human-readable mismatch strings — empty means the
    contract holds.  This is the supported promotion of the
    predicted-vs-lowered comparison the HLO-guarantee tests pioneered
    (tests/test_hlo_guarantees.py is now a thin wrapper, and
    ``bluefog_tpu.analysis`` runs the same check statically): permute
    count, per-permute payload bytes, total bytes, and — for
    hierarchical predictions — the grouped all-reduce count and its
    ``replica_groups`` machine decomposition.

    ``payload_bytes`` is one admissible per-permute payload or a
    collection of them: compressed mixing moves a DIFFERENT (but still
    statically known) wire size per bucket, so a multi-bucket program
    legitimately lowers heterogeneous permutes.  Every lowered payload
    must be a member of the collection, and the per-period TOTAL must
    still match exactly, so an unexpected payload cannot hide inside an
    admissible multiset.
    """
    hlo = compiled.as_text() if hasattr(compiled, "as_text") else compiled
    problems = []

    per_round = predicted.get("per_round", [])
    # internal consistency of the prediction itself: the per-period
    # totals must be the per-round sum, or the dict was tampered/stale
    if per_round:
        tot_p = sum(r["permutes"] for r in per_round)
        if tot_p != predicted["permutes_per_period"]:
            problems.append(
                f"prediction inconsistent: per_round permutes sum {tot_p}"
                f" != permutes_per_period "
                f"{predicted['permutes_per_period']}")
        tot_b = float(sum(r["permutes"] * r["bytes_per_permute"]
                          for r in per_round))
        if tot_b != predicted["bytes_per_period"]:
            problems.append(
                f"prediction inconsistent: per_round bytes sum {tot_b}"
                f" != bytes_per_period {predicted['bytes_per_period']}")

    wins = [w for w in scheduled_collective_windows(hlo)
            if w["kind"] == "collective-permute"]
    if round_index is None:
        want_p = predicted["permutes_per_period"]
        want_bytes = predicted["bytes_per_period"]
        want_r = predicted.get("all_reduces_per_period")
    else:
        rp = per_round[round_index]
        want_p = rp["permutes"]
        want_bytes = rp["permutes"] * rp["bytes_per_permute"]
        want_r = rp.get("all_reduces")
        payload_bytes = rp.get("bytes_per_permute", payload_bytes)

    where = ("program" if round_index is None
             else f"round {round_index}")
    if len(wins) != want_p:
        problems.append(
            f"{where}: {len(wins)} collective-permutes lowered, "
            f"predicted {want_p}")
    admissible = (set(int(p) for p in payload_bytes)
                  if isinstance(payload_bytes, (set, frozenset, list,
                                                tuple))
                  else {int(payload_bytes)})
    bad = [w["bytes"] for w in wins if w["bytes"] not in admissible]
    if bad:
        problems.append(
            f"{where}: permute payloads {bad} not in predicted "
            f"{sorted(admissible)} bytes")
    got_bytes = sum(w["bytes"] for w in wins)
    if got_bytes != want_bytes:
        problems.append(
            f"{where}: {got_bytes} permute bytes lowered, predicted "
            f"{want_bytes}")
    if want_r is not None:
        got_r = _count_hlo_collectives(hlo, "all-reduce")
        if got_r != want_r:
            problems.append(
                f"{where}: {got_r} all-reduces lowered, predicted "
                f"{want_r}")
        groups = predicted.get("all_reduce_groups")
        size = predicted.get("all_reduce_group_size")
        if got_r and groups and size and size > 1:
            expect = _expected_replica_groups(groups, size)
            if expect not in hlo:
                problems.append(
                    f"{where}: grouped all-reduce missing machine "
                    f"decomposition {expect}")
    return problems


def hlo_op_breakdown(hlo_text: str) -> dict:
    """Per-op-kind accounting of an HLO module: ``{op: {"count",
    "flops"}}``, flops from the same estimator the overlap windows use
    (dots exact 2*M*N*K, fusions their called computation + an
    elementwise sweep, data movement zero).  Computations reached only
    through ``fusion(... calls=...)`` are charged at the fusion site,
    not double-counted as free-standing computations.  Loop bodies are
    counted once (a scan executes its body T times — scale by trip
    count when attributing a multi-token program).  This is the
    "per-op accounting" view the round-5 VERDICT asked for on the
    large-batch decode path; the supported entry point is
    ``bluefog_tpu.observe.profile_step`` (which records it as
    ``StepProfile.op_breakdown``)."""
    comps = _parse_computations(hlo_text)
    fusion_called = set()
    for instrs in comps.values():
        for i in instrs:
            if i["op"] == "fusion":
                m = _CALLS_RE.search(i["rest"])
                if m:
                    fusion_called.add(m.group(1))
    memo: dict = {}
    out: dict = {}
    for cname, instrs in comps.items():
        if cname in fusion_called:
            continue
        for i in instrs:
            rec = out.setdefault(i["op"], {"count": 0, "flops": 0.0})
            rec["count"] += 1
            rec["flops"] += _instr_flops(i, comps, memo)
    return out


def overlap_accounting(hlo_text: str,
                       peak_flops_per_s: float,
                       link_bytes_per_s: float,
                       hbm_bytes_per_s: float = 0.0,
                       congestion: float = 1.0,
                       kinds: tuple = ("collective-permute",)) -> dict:
    """Overlappable-bytes accounting for the collectives of ``kinds``.

    A collective's payload is overlappable when the compute available to
    hide it runs at least as long as the transfer::

        max(flops / peak, bytes_accessed / hbm_bw)
            >= payload_bytes * congestion / link_bytes_per_s

    (the bandwidth term matters because the natural hiding material at
    LLM scale — the optimizer's elementwise parameter sweeps — is
    HBM-bound: its wall time is bytes/819GB/s on v5e, far more than its
    flop count suggests; pass ``hbm_bytes_per_s=0`` to score on flops
    alone).

    The measure is chosen PER COLLECTIVE: an async-lowered one is
    scored on its start->done window (the scheduler DID overlap), a
    sync-lowered one on its dataflow-independent compute (the scheduler
    CAN overlap; schedule-invariant, so measurable from the CPU AOT
    audit modules too).  ``basis`` summarizes the module:
    ``"scheduled"`` (all async), ``"dataflow"`` (all sync), or
    ``"mixed"``.  Returns per-kind and total bytes, overlappable bytes,
    and the byte-weighted fraction.
    """
    if peak_flops_per_s <= 0 or link_bytes_per_s <= 0:
        raise ValueError("peak_flops_per_s and link_bytes_per_s must be "
                         "positive (pass the target chip's figures when "
                         "auditing from a CPU host)")
    windows = [w for w in scheduled_collective_windows(hlo_text)
               if w["kind"] in kinds]
    n_async = sum(1 for w in windows if w["async"])
    basis = ("scheduled" if n_async == len(windows) and windows else
             "dataflow" if n_async == 0 else "mixed")
    per_kind: dict = {}
    for w in windows:
        rec = per_kind.setdefault(
            w["kind"], {"count": 0, "bytes": 0, "bytes_overlappable": 0})
        rec["count"] += 1
        rec["bytes"] += w["bytes"]
        # basis PER WINDOW: an async-lowered collective is judged on
        # what the scheduler actually placed in its start->done window;
        # a sync-lowered one (even in the same module) on its
        # dataflow-independent headroom
        if w["async"]:
            flops, touched = w["window_flops"], w["window_bytes_accessed"]
        else:
            flops, touched = (w["independent_flops"],
                              w["independent_bytes_accessed"])
        hide_s = flops / peak_flops_per_s
        if hbm_bytes_per_s > 0:
            hide_s = max(hide_s, touched / hbm_bytes_per_s)
        transfer_s = w["bytes"] * congestion / link_bytes_per_s
        if hide_s >= transfer_s and w["bytes"] > 0:
            rec["bytes_overlappable"] += w["bytes"]
    total = sum(r["bytes"] for r in per_kind.values())
    good = sum(r["bytes_overlappable"] for r in per_kind.values())
    return {
        "basis": basis,
        "per_kind": per_kind,
        "bytes_total": int(total),
        "bytes_overlappable": int(good),
        "fraction": (good / total) if total else 0.0,
        "windows": windows,
    }


def poisson_arrivals(rate: float, n: int, seed: int = 0) -> np.ndarray:
    """Arrival times (seconds, ascending, starting at 0.0) of ``n``
    Poisson arrivals at ``rate`` requests/s: the cumulative sum of
    seeded exponential inter-arrival gaps.  Pure function of
    ``(rate, n, seed)`` — no wall clock anywhere — so the serving bench
    and the serving tests replay the SAME trace
    (benchmarks/serving_bench.py, tests/test_serving.py)."""
    if rate <= 0:
        raise ValueError(f"rate ({rate}) must be positive")
    if n < 1:
        return np.zeros((0,), np.float64)
    gaps = np.random.RandomState(seed).exponential(1.0 / rate, size=n)
    gaps[0] = 0.0
    return np.cumsum(gaps)


def _unit_poisson_targets(n: int, seed: int) -> np.ndarray:
    """Unit-rate Poisson cumulative targets — the shared substrate of
    the non-homogeneous generators below (inversion method: arrival
    *i* lands where the cumulative rate function crosses target *i*).
    Same convention as :func:`poisson_arrivals`: first arrival at 0."""
    gaps = np.random.RandomState(seed).exponential(1.0, size=n)
    gaps[0] = 0.0
    return np.cumsum(gaps)


def diurnal_arrivals(rate: float, n: int, seed: int = 0, *,
                     period: float = 60.0, depth: float = 0.5,
                     phase: float = 0.0) -> np.ndarray:
    """Arrival times of ``n`` requests from a sinusoidally modulated
    Poisson process — the diurnal load shape: instantaneous rate
    ``rate * (1 + depth * sin(2*pi*t/period + phase))`` requests/s.
    Exact inversion of the cumulative rate function (vectorized
    bisection), so counts over any window match its integral in
    expectation and the trace is a pure function of the arguments —
    no thinning, no wall clock, no resampling loop.  ``0 <= depth < 1``
    keeps the rate strictly positive."""
    if rate <= 0:
        raise ValueError(f"rate ({rate}) must be positive")
    if not 0.0 <= depth < 1.0:
        raise ValueError(f"depth ({depth}) must be in [0, 1)")
    if period <= 0:
        raise ValueError(f"period ({period}) must be positive")
    if n < 1:
        return np.zeros((0,), np.float64)
    targets = _unit_poisson_targets(n, seed)
    w = 2.0 * np.pi / period
    amp = rate * depth / w

    def cum_rate(t):
        return rate * t + amp * (np.cos(phase) - np.cos(w * t + phase))

    # cum_rate(t) >= rate*t - 2*amp, so t <= (target + 2*amp)/rate
    lo = np.zeros(n, np.float64)
    hi = (targets + 2.0 * amp) / rate + 1.0
    for _ in range(64):  # bisection to ~1 ulp of the window width
        mid = 0.5 * (lo + hi)
        below = cum_rate(mid) < targets
        lo = np.where(below, mid, lo)
        hi = np.where(below, hi, mid)
    out = 0.5 * (lo + hi)
    out[0] = 0.0
    return out


def flash_crowd_arrivals(rate: float, n: int, seed: int = 0, *,
                         at: float = 0.0, factor: float = 4.0,
                         duration: float = 1.0) -> np.ndarray:
    """Arrival times of ``n`` requests from a Poisson process at
    ``rate`` requests/s with one flash crowd: inside ``[at, at +
    duration)`` the rate jumps to ``rate * factor``.  The cumulative
    rate function is piecewise linear, so the inversion is closed-form
    and exact; outside the burst the trace statistics match
    :func:`poisson_arrivals` at the same base rate.  Deterministic in
    ``(rate, n, seed, at, factor, duration)``."""
    if rate <= 0:
        raise ValueError(f"rate ({rate}) must be positive")
    if factor <= 0:
        raise ValueError(f"factor ({factor}) must be positive")
    if duration < 0 or at < 0:
        raise ValueError(f"burst window (at={at}, duration={duration}) "
                         f"must be non-negative")
    if n < 1:
        return np.zeros((0,), np.float64)
    targets = _unit_poisson_targets(n, seed)
    c1 = rate * at                           # cum rate at burst start
    c2 = c1 + rate * factor * duration       # cum rate at burst end
    out = np.where(
        targets < c1, targets / rate,
        np.where(targets < c2,
                 at + (targets - c1) / (rate * factor),
                 at + duration + (targets - c2) / rate))
    return out.astype(np.float64)


def device_fetch(a) -> np.ndarray:
    """Synchronize by materializing ``a`` on the host."""
    return np.asarray(jax.device_get(a))


def fetch_overhead(repeats: int = 3) -> float:
    """Median wall time of dispatching + fetching a fresh trivial
    computation — the per-sync overhead to subtract from timed loops."""
    x = jax.device_put(np.zeros(1, np.float32))
    y = x + 1.0
    device_fetch(y)  # compile outside timing
    times = []
    for i in range(repeats):
        t0 = time.perf_counter()
        device_fetch(x + float(i + 2))
        times.append(time.perf_counter() - t0)
    return float(np.median(times))


def timed(run_steps, sync_value_fn, overhead: float = None) -> float:
    """Run ``run_steps()`` (which enqueues work), sync via
    ``sync_value_fn()`` (returning a computation-dependent array), and
    return wall seconds with the fetch overhead subtracted."""
    if overhead is None:
        overhead = fetch_overhead()
    t0 = time.perf_counter()
    run_steps()
    device_fetch(sync_value_fn())
    return max(time.perf_counter() - t0 - overhead, 1e-9)


def chain_time(f, params, x0, n=20, reps=3):
    """Per-iteration seconds of ``x <- barrier(f(params, x)*eps + x0)``
    iterated INSIDE one jitted fori_loop — per-call tunnel dispatch is
    ~3 ms on this rig and would floor every sub-3ms op if the chain were
    a host loop.  ``params`` ride as jit ARGUMENTS (closure constants
    >100 MB overflow the remote compile transport).  Promoted verbatim
    from benchmarks/llama_roofline.py (round 5), whose composition
    reproduces the measured 1B train step exactly — the validation that
    makes this the trusted micro-timing harness on the tunnel rig.
    """
    import jax.numpy as jnp

    @jax.jit
    def chained(p, x):
        def body(i, x):
            y = f(p, x)
            if y.shape != x0.shape:
                # consume EVERY element (a slice would let XLA narrow
                # the producing dot to the sliced columns — observed as
                # a 116% "MFU" on the vocab head)
                y = jnp.mean(y.astype(jnp.float32), axis=-1,
                             keepdims=True)
                y = jnp.broadcast_to(y, x0.shape[:-1] + (1,))
            y = (y.astype(jnp.float32) * 1e-30).astype(x0.dtype)
            return jax.lax.optimization_barrier(x0 + y)
        return jax.lax.fori_loop(0, n, body, x)

    device_fetch(chained(params, x0)[..., :1])
    ov = fetch_overhead()
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        device_fetch(chained(params, x0)[..., :1])
        times.append((time.perf_counter() - t0 - ov) / n)
    return float(np.median(times))


def fwd_bwd_time(f, params, x0, n=20, reps=3):
    """fwd+bwd seconds of y = f(params, x) with grads wrt both, chained
    through dx inside one jitted fori_loop (see chain_time).

    Signature is ``(f, params, x0)`` — the SAME argument order as
    ``chain_time`` (round-5 advice: the two public timers previously
    disagreed, silently swapping operands at call sites)."""
    import jax.numpy as jnp

    def loss(p, x):
        return jnp.sum(f(p, x).astype(jnp.float32) ** 2)

    grad = jax.grad(loss, argnums=(0, 1))

    @jax.jit
    def chained(p, x):
        def body(i, x):
            dp, dx = grad(p, x)
            # consume EVERY gradient: an unused dp would let XLA DCE
            # the dW matmuls and report a 2N-FLOP backward as 4N
            dp_sum = sum(jnp.sum(leaf.astype(jnp.float32)) * 1e-30
                         for leaf in jax.tree.leaves(dp))
            return jax.lax.optimization_barrier(
                (dx.astype(jnp.float32) * 1e-30 + dp_sum
                 ).astype(x0.dtype) + x0)
        return jax.lax.fori_loop(0, n, body, x)

    device_fetch(chained(params, x0)[..., :1])
    ov = fetch_overhead()
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        device_fetch(chained(params, x0)[..., :1])
        times.append((time.perf_counter() - t0 - ov) / n)
    return float(np.median(times))


# --------------------------------------------------------------------- #
# bench regression gate (ISSUE 5 satellite): compare a fresh run's
# headline numbers against a prior BENCH_*.json — per-metric tolerance,
# one-line delta table, nonzero exit on regression.  The BENCH
# trajectory was previously unaggregated; this makes each run a gate.
# --------------------------------------------------------------------- #
# headline fields worth gating, with their GOOD direction
_HEADLINE_HIGHER = ("value", "mfu", "tokens_per_sec", "useful_tokens",
                    "speedup_tokens_per_sec", "vs_baseline",
                    "compiled_advantage", "hit_rate",
                    "accepted_per_step", "fleet_speedup",
                    "throughput_recovery", "tp_overlap_fraction",
                    "cost_to_consensus_advantage", "decisions_replayed")
_HEADLINE_LOWER = ("ttft_p50", "ttft_p99", "latency_p50", "latency_p99",
                   "makespan_s", "p99", "p50", "cost_to_consensus",
                   "post_rejoin_floor", "dcn_bytes_per_step",
                   "lost_requests", "step_time_ratio",
                   "consensus_floor", "mean_drift", "detect_to_swap_s",
                   "cost_to_dispatch", "mismatches")


def bench_headline(record: dict) -> dict:
    """Extract the gateable headline metrics of a bench JSON record as
    ``{name: float}``.  Understands the three shapes this repo emits:
    the raw ``bench.py`` line (``{"metric", "value", "mfu", ...}``),
    the driver's ``BENCH_*.json`` wrapper (same dict under
    ``"parsed"``), and section records like ``serving_bench``'s
    (headline fields under ``"continuous"``)."""
    if isinstance(record.get("parsed"), dict):
        record = record["parsed"]
    keys = set(_HEADLINE_HIGHER) | set(_HEADLINE_LOWER)
    out: dict = {}

    def grab(d: dict, prefix: str) -> None:
        for k, v in d.items():
            if (k in keys and isinstance(v, (int, float))
                    and not isinstance(v, bool)):
                out[prefix + k] = float(v)

    grab(record, "")
    for section in ("continuous", "static", "chaos", "straggler",
                    "rejoin", "pod_4x8", "pod_8x16", "fleet_one",
                    "fleet_two", "prefix", "speculative",
                    "hierarchical", "fault_free", "chaos_serving",
                    "drain", "adaptation", "congested", "shrink",
                    "rollback", "compressed", "sim_training",
                    "sim_serving", "moe", "measured", "replay"):
        if isinstance(record.get(section), dict):
            grab(record[section], section + ".")
    return out


def _direction(name: str) -> int:
    """+1 = higher is better, -1 = lower is better (latency tails)."""
    base = name.rsplit(".", 1)[-1]
    return -1 if base in _HEADLINE_LOWER else +1


def bench_compare(current: dict, previous: dict, tolerance: float = 0.05,
                  tolerances: dict = None) -> tuple:
    """Compare two bench records' shared headline metrics.

    Returns ``(ok, rows)``: ``rows`` is one dict per shared metric
    (``name, prev, cur, delta_frac, tol, regressed``); ``ok`` is False
    iff any metric moved more than its tolerance in the BAD direction
    (improvements never fail the gate).  ``tolerances`` overrides the
    per-metric relative tolerance by headline name."""
    cur_h = bench_headline(current)
    prev_h = bench_headline(previous)
    rows = []
    ok = True
    for name in sorted(set(cur_h) & set(prev_h)):
        prev, cur = prev_h[name], cur_h[name]
        tol = float((tolerances or {}).get(name, tolerance))
        denom = max(abs(prev), 1e-12)
        delta = (cur - prev) / denom
        regressed = (-delta if _direction(name) > 0 else delta) > tol
        ok = ok and not regressed
        rows.append(dict(name=name, prev=prev, cur=cur,
                         delta_frac=delta, tol=tol, regressed=regressed))
    return ok, rows


def _record_round(path: str, record: dict) -> str:
    """The baseline record's round, for gate attribution: the ``_r<N>``
    filename convention first (``fleet_sim_r20.json`` -> ``r20``), then
    an explicit ``round`` field, else ``r?``."""
    m = re.search(r"_r(\d+)", path.rsplit("/", 1)[-1])
    if m:
        return "r" + m.group(1)
    rec = record.get("parsed") if isinstance(record.get("parsed"),
                                             dict) else record
    rnd = rec.get("round") if isinstance(rec, dict) else None
    return f"r{rnd}" if rnd is not None else "r?"


def _record_sections(record: dict) -> str:
    """Comma-joined section names (dict-valued keys) of a bench record —
    what a no-shared-metrics mismatch message lists for each side."""
    if isinstance(record.get("parsed"), dict):
        record = record["parsed"]
    secs = sorted(k for k, v in record.items() if isinstance(v, dict))
    return ",".join(secs) if secs else "-"


def bench_regression_gate(current: dict, prev_path: str,
                          tolerance: float = 0.05,
                          tolerances: dict = None) -> bool:
    """Gate ``current`` against the record stored at ``prev_path``:
    prints the one-line delta table (naming the baseline file and its
    record round, so a failing gate says exactly which artifact it
    compared against) and returns False on regression (callers
    ``sys.exit(1)``)."""
    import json as _json

    with open(prev_path) as fh:
        previous = _json.load(fh)
    rnd = _record_round(prev_path, previous)
    ok, rows = bench_compare(current, previous, tolerance, tolerances)
    if not rows:
        print(f"[bench-gate] no shared headline metrics with {prev_path} "
              f"({rnd}): current sections [{_record_sections(current)}] "
              f"vs baseline sections [{_record_sections(previous)}]")
        return True
    cells = []
    for r in rows:
        mark = "REGRESSED" if r["regressed"] else "ok"
        cells.append(f"{r['name']} {r['prev']:.4g}->{r['cur']:.4g} "
                     f"({r['delta_frac']:+.1%} {mark})")
    print(f"[bench-gate] vs {prev_path} ({rnd}): " + " | ".join(cells))
    return ok
