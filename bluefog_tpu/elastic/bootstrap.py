"""Joiner bootstrap: state sync by pulled neighbor averaging only.

A rank rejoining the fleet must recover the live consensus without a
global broadcast — broadcast is exactly the centralized primitive the
paper's decentralized premise avoids, and it would need a program the
fixed-shape fleet never compiled.  Instead the joiner syncs through the
SAME compiled mixing rounds everyone runs, as pure weight data:

* the joiner's row pulls from its LIVE in-neighbors with its
  self-weight annealed ``0 -> w`` (its pristine self-weight) over
  ``rounds`` steps.  At anneal fraction 0 the first pull REPLACES the
  joiner's stale state with a weighted average of live neighbors (its
  own value enters with weight 0 — sound because the guard froze it
  finite, and ``0 * finite == 0``); by fraction 1 the row is the
  pristine row (rescaled over the live in-mass if some in-neighbors
  are still dead) and the joiner mixes like any live rank;
* live receivers keep their HEALED (zero) weights for the joiner for
  the whole quarantine — a half-bootstrapped value never leaks into
  the fleet.  Promotion flips those rows via
  :func:`~bluefog_tpu.elastic.membership.grow_weights`.

Both comm modes are covered by the same schedule: an ATC step pulls
exactly (the joiner's combine output IS the neighbor average), a CTA
step pulls then applies one local finite-gradient update — either way
the disagreement gate below decides promotion, not the mode.

Every row emitted here sums to 1 exactly in the row-stochastic
tolerance sense: the anneal distributes ``1 - theta`` proportionally
over the live in-edges, so iterated averaging keeps contracting while
the joiner converges — the token-exact consensus-floor recovery the
chaos bench machine-checks (benchmarks/chaos_resilience.py part 4).
"""

from __future__ import annotations

from typing import Dict, Mapping, Sequence, Union

import numpy as np

from bluefog_tpu.resilience.healing import heal_weights
from bluefog_tpu.topology.spec import (DynamicTopology, Topology,
                                       self_weights_of as _self_weights_of)

CommSpec = Union[Topology, DynamicTopology]

__all__ = [
    "anneal_fraction",
    "bootstrap_weights",
    "bootstrap_comm_weights",
    "disagreement",
    "sanitize_rank_rows",
    "zero_rank_rows",
]


def anneal_fraction(progress: int, rounds: int) -> float:
    """Anneal fraction after ``progress`` quarantined mixing rounds:
    0 at admission (first pull is a pure neighbor average), 1 from
    ``rounds`` on (the joiner's row is pristine, it just isn't read
    yet)."""
    if rounds < 1:
        raise ValueError(f"rounds must be >= 1, got {rounds}")
    if progress < 0:
        raise ValueError(f"progress must be >= 0, got {progress}")
    return min(float(progress) / float(rounds), 1.0)


def bootstrap_weights(spec: CommSpec, live_mask,
                      anneal: Mapping[int, float]) -> tuple:
    """One round's ``(class_weights [n_classes, n], self_weights [n])``
    float64 tables under quarantine: ranks in ``anneal`` (joining rank
    -> anneal fraction in [0, 1]) pull from their LIVE in-neighbors
    with self-weight ``theta = fraction * w_pristine``; everyone NOT
    live and not joining is dead; live rows are plain
    :func:`healing.heal_weights` rows around the whole non-live set
    (joiners included — quarantine means nobody reads them).

    With an empty ``anneal`` this IS ``heal_weights(spec, ~live)`` —
    the controller uses it as the single render path for both steady
    and bootstrapping states.

    A joiner with no live in-neighbor this round freezes (self-weight
    1.0): a one-peer schedule reaches it on another round."""
    n = spec.size
    live = np.asarray(live_mask, bool).reshape(-1)
    if live.shape[0] != n:
        raise ValueError(
            f"live mask of length {live.shape[0]} does not match "
            f"topology size {n}")
    joiners: Dict[int, float] = {}
    for r, f in anneal.items():
        r = int(r)
        if not 0 <= r < n:
            raise ValueError(f"rank {r} outside topology of size {n}")
        if live[r]:
            raise ValueError(
                f"rank {r} is live — a live rank cannot be bootstrapping")
        if not 0.0 <= float(f) <= 1.0:
            raise ValueError(
                f"anneal fraction for rank {r} must be in [0, 1], "
                f"got {f}")
        joiners[r] = float(f)
    # receivers' view: everything not LIVE is excised (quarantine)
    cw, sw = heal_weights(spec, ~live)
    if not joiners:
        return cw, sw
    classes = spec.shift_classes
    cw0 = (np.array([cls.recv_weights for cls in classes], np.float64)
           if classes else np.zeros((0, n), np.float64))
    sw0 = np.asarray(_self_weights_of(spec), np.float64)
    for j, frac in joiners.items():
        pulls = []
        mass = 0.0
        for c, cls in enumerate(classes):
            w = cw0[c, j]
            if w == 0.0:
                continue
            src = (j - cls.shift) % n
            if live[src]:
                pulls.append((c, w))
                mass += w
        if mass <= 0.0:
            sw[j] = 1.0  # no live in-neighbor this round: freeze
            continue
        theta = frac * sw0[j]
        scale = (1.0 - theta) / mass
        for c, w in pulls:
            cw[c, j] = w * scale
        sw[j] = theta
    return cw, sw


def bootstrap_comm_weights(specs: Sequence[CommSpec], live_mask,
                           anneal: Mapping[int, float]) -> tuple:
    """The quarantine round as traced-operand data — one jnp
    ``(class_weights, self_weights)`` pair per round, same structure as
    ``optim.functional.comm_weight_inputs(specs)``, so the anneal is a
    per-step weight-data change through the one compiled program."""
    import jax.numpy as jnp

    out = []
    for s in specs:
        cw, sw = bootstrap_weights(s, live_mask, anneal)
        out.append((jnp.asarray(cw), jnp.asarray(sw)))
    return tuple(out)


def disagreement(tree, rank: int, live_mask) -> float:
    """Normalized disagreement of ``rank``'s state rows against the
    LIVE ranks — the promotion gate.  The L2 distance of the rank's
    rows from the live mean, in units of the live ranks' own maximum
    deviation from that mean: decentralized training never drives the
    replicas to exact agreement mid-run (they intentionally differ by
    the consensus distance), so an absolute threshold would either
    never fire or fire vacuously.  A value <= 1 means the joiner sits
    INSIDE the live consensus cloud — indistinguishable from a replica
    that never left — which is what ``quarantine_threshold`` (default
    1.0) gates on.  A tiny relative floor keeps the ratio meaningful
    when the live ranks are at exact consensus (pure-mixing
    simulations: both numerator and denominator at the ~1e-16 floor).

    Host-side and O(params): called once per check cadence, never
    inside the jitted step.  Non-finite joiner entries count as
    infinite disagreement (never promote garbage)."""
    import jax

    live = np.asarray(live_mask, bool).reshape(-1)
    if not live.any():
        raise ValueError("no live ranks to compare against")
    num = 0.0
    live_dev2 = np.zeros(int(live.sum()))
    scale2 = 0.0
    saw = False
    for leaf in jax.tree.leaves(tree):
        arr = np.asarray(leaf)
        if not np.issubdtype(arr.dtype, np.inexact):
            continue
        if arr.ndim < 1 or arr.shape[0] != live.shape[0]:
            raise ValueError(
                "disagreement needs rank-major leaves with leading dim "
                f"{live.shape[0]}, got shape {arr.shape}")
        saw = True
        mine = np.asarray(arr[rank], np.float64).reshape(-1)
        if not np.isfinite(mine).all():
            return float("inf")
        rows = np.asarray(arr[live], np.float64).reshape(live.sum(), -1)
        ref = rows.mean(axis=0)
        num += float(((mine - ref) ** 2).sum())
        live_dev2 += ((rows - ref) ** 2).sum(axis=1)
        scale2 += float((ref ** 2).sum())
    if not saw:
        raise ValueError("disagreement: tree has no inexact leaves")
    denom = float(np.sqrt(live_dev2.max())) + 1e-9 * float(
        np.sqrt(scale2)) + 1e-300
    return float(np.sqrt(num) / denom)


def sanitize_rank_rows(tree, rank_mask):
    """Zero every non-finite entry on the masked ranks' rows of a
    rank-major pytree — admission hygiene for state that died OUTSIDE
    the guard's frozen-finite invariant (a re-attached host's memory
    is not certified by anything).  Finite values pass through
    untouched; with anneal fraction 0 the first pull overwrites the
    row anyway, this just keeps ``0 * x`` well-defined on the way."""
    import jax

    mask = np.asarray(rank_mask, bool).reshape(-1)
    if not mask.any():
        return tree

    def fix(leaf):
        arr = np.asarray(leaf)
        if not np.issubdtype(arr.dtype, np.inexact):
            return leaf
        if arr.ndim < 1 or arr.shape[0] != mask.shape[0]:
            raise ValueError(
                "sanitize_rank_rows needs rank-major leaves with leading "
                f"dim {mask.shape[0]}, got shape {arr.shape}")
        rows = arr[mask]
        if np.isfinite(rows).all():
            return leaf
        arr = arr.copy()
        arr[mask] = np.where(np.isfinite(rows), rows, 0.0)
        return arr

    return jax.tree.map(fix, tree)


def zero_rank_rows(tree, rank_mask):
    """Zero the masked ranks' rows of every inexact rank-major leaf —
    admission hygiene for OPTIMIZER state.  A rejoining rank's moments
    are finite (the guard froze them) but STALE: they describe the
    gradient field as of the preemption, and the promotion gate
    measures params only, so :func:`sanitize_rank_rows` would wave them
    through untouched.  Zeroing the rows at admission makes quarantine
    rebuild the moments from fresh gradients, so a promoted rank's
    first live updates are steered by current curvature, not
    pre-preemption history.  Already-zero rows pass through as
    identity (no copy); non-row leaves (int counters etc.) are left
    alone."""
    import jax

    mask = np.asarray(rank_mask, bool).reshape(-1)
    if not mask.any():
        return tree

    def fix(leaf):
        arr = np.asarray(leaf)
        if not np.issubdtype(arr.dtype, np.inexact):
            return leaf
        if arr.ndim < 1 or arr.shape[0] != mask.shape[0]:
            raise ValueError(
                "zero_rank_rows needs rank-major leaves with leading "
                f"dim {mask.shape[0]}, got shape {arr.shape}")
        if not arr[mask].any():
            return leaf
        arr = arr.copy()
        arr[mask] = 0.0
        return arr

    return jax.tree.map(fix, tree)
