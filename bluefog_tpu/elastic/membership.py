"""Elastic membership: the LIVE -> DEAD -> JOINING -> LIVE lifecycle.

``healing.py`` is one-directional: dead ranks are excised and the mesh
only ever shrinks.  This module adds the way back.  Membership is a
host-side state machine (:class:`MembershipController`) whose entire
device-visible output is traced DATA — a ``[n_max]`` membership mask
plus re-planned ``(class_weights, self_weights)`` pairs in exactly the
shapes ``optim.functional.comm_weight_inputs`` emits — so a guarded
train step compiled once at max fleet size serves every join / leave /
rejoin without a recompile (the PR-3 fixed-shape trick, generalized).

The inverse of healing is :func:`grow_weights`.  Healing moved a dead
``src``'s in-edge mass onto each receiver's self-weight; growth must
give it back EXACTLY.  Floating-point subtraction cannot do that
(``(a + w) - w != a`` in general), so growth never subtracts: it
re-plans from the PRISTINE spec against the shrunken dead set, walking
the same ``(class, dst)`` order as :func:`healing.heal_weights`.  The
result is therefore byte-equal to a fresh heal of the remaining dead
set — and byte-equal to the original tables once everyone is back —
while staying row-stochastic at every intermediate step.

State machine::

    LIVE --mark_dead--> DEAD --admit--> JOINING --promote--> LIVE
                          ^                |
                          +-----kick------ +   (bootstrap failed /
                                               rollback invalidated it)

While JOINING, a rank is quarantined: live receivers keep their healed
(zero) weights for it, the :class:`~bluefog_tpu.resilience.detector.
FailureDetector` still counts it dead (its skips must not trigger
fleet rollbacks), and only the joiner's OWN row pulls — the annealed
bootstrap schedule of :mod:`bluefog_tpu.elastic.bootstrap`.  Promotion
calls ``FailureDetector.readmit`` so the returning rank is not
instantly re-excised by a latched suspicion.
"""

from __future__ import annotations

# This module legitimately constructs weight tables from scratch — the
# analysis lint's weight-matrix-bypass rule treats it as an authority
# (everywhere else, tables must come from the shared helpers here).
_WEIGHT_AUTHORITY = True

import dataclasses
from collections import OrderedDict
from typing import (Any, Callable, Dict, List, Optional, Sequence, Tuple,
                    Union)

import numpy as np

from bluefog_tpu import config as _config
from bluefog_tpu.resilience.healing import (heal_spec, heal_weights,
                                            mixing_matrix_from_weights)
from bluefog_tpu.topology.spec import DynamicTopology, Topology

CommSpec = Union[Topology, DynamicTopology]

__all__ = [
    "LIVE",
    "DEAD",
    "JOINING",
    "ElasticConfig",
    "MembershipController",
    "grow_weights",
    "grow_spec",
    "grown_comm_weights",
]

LIVE, DEAD, JOINING = "live", "dead", "joining"
_CODE = {LIVE: 0, DEAD: 1, JOINING: 2}
_STATE = {v: k for k, v in _CODE.items()}

# steady-state (no joiner) weight tables are cached per membership
# pattern; bounded so a long churn history cannot grow host memory
_STEADY_CACHE_MAX = 16


def _as_ranks(ranks: Union[int, Sequence[int]]) -> List[int]:
    if isinstance(ranks, (int, np.integer)):
        return [int(ranks)]
    return [int(r) for r in ranks]


def grow_weights(spec: CommSpec, dead_mask,
                 rejoin_ranks: Union[int, Sequence[int]]) -> tuple:
    """Re-plan ``(class_weights [n_classes, n], self_weights [n])``
    after ``rejoin_ranks`` (a subset of the dead set) come back: their
    in-edge mass moves OFF the receivers' self-weights and back onto
    the edges, and their own rows are restored.

    Implementation note — growth is a re-plan from the PRISTINE spec
    against the shrunken dead set, never a subtraction from the healed
    tables: recomputing in :func:`healing.heal_weights`'s own iteration
    order makes ``heal -> grow`` round-trip BYTE-EQUAL (``grow(spec,
    dead, dead) == (pristine class/self tables)`` bit for bit, and any
    partial growth equals a fresh heal of the survivors' dead set),
    where ``(a + w) - w`` would leave rounding residue on every healed
    self-weight.  Row sums are preserved exactly at every step for the
    same reason heals preserve them."""
    n = spec.size
    dead = np.asarray(dead_mask, bool).reshape(-1).copy()
    if dead.shape[0] != n:
        raise ValueError(
            f"dead mask of length {dead.shape[0]} does not match "
            f"topology size {n}")
    for r in _as_ranks(rejoin_ranks):
        if not 0 <= r < n:
            raise ValueError(f"rank {r} outside topology of size {n}")
        if not dead[r]:
            raise ValueError(
                f"rank {r} is not dead — only dead ranks can rejoin")
        dead[r] = False
    return heal_weights(spec, dead)


def grow_spec(spec: CommSpec, dead_mask,
              rejoin_ranks: Union[int, Sequence[int]]) -> CommSpec:
    """A standalone re-grown spec of the same type (for eager ops and
    simulation) — :func:`healing.heal_spec` of the shrunken dead set,
    so ``heal_spec -> grow_spec`` with everyone rejoining reproduces
    the original weights exactly."""
    n = spec.size
    dead = np.asarray(dead_mask, bool).reshape(-1).copy()
    if dead.shape[0] != n:
        raise ValueError(
            f"dead mask of length {dead.shape[0]} does not match "
            f"topology size {n}")
    for r in _as_ranks(rejoin_ranks):
        if not 0 <= r < n:
            raise ValueError(f"rank {r} outside topology of size {n}")
        if not dead[r]:
            raise ValueError(
                f"rank {r} is not dead — only dead ranks can rejoin")
        dead[r] = False
    return heal_spec(spec, dead)


def grown_comm_weights(specs: Sequence[CommSpec], dead_mask,
                       rejoin_ranks: Union[int, Sequence[int]]) -> tuple:
    """The re-grown schedule as traced-operand data: one
    ``(class_weights, self_weights)`` jnp pair per round, structurally
    identical to ``healing.healed_comm_weights`` — the growth-direction
    twin that restores rejoined ranks without a recompile."""
    import jax.numpy as jnp

    out = []
    for s in specs:
        cw, sw = grow_weights(s, dead_mask, rejoin_ranks)
        out.append((jnp.asarray(cw), jnp.asarray(sw)))
    return tuple(out)


@dataclasses.dataclass(frozen=True)
class ElasticConfig:
    """Policy knobs for ``run_resilient(elastic=...)``.

    ``bootstrap_rounds``: mixing rounds a joiner's self-weight anneals
    over (0 -> its pristine weight); default
    ``BLUEFOG_ELASTIC_BOOTSTRAP_ROUNDS``.  ``quarantine_threshold``:
    max normalized bootstrap disagreement for promotion (joiner params
    vs the live mean, in units of the live ranks' own dispersion —
    :func:`bluefog_tpu.elastic.bootstrap.disagreement`; <= 1.0 = inside
    the live consensus cloud); default
    ``BLUEFOG_ELASTIC_QUARANTINE_THRESHOLD``.
    ``max_quarantine_steps``: a joiner still above threshold after this
    many quarantined steps is kicked back to DEAD.  ``admit``: a
    ``step -> ranks`` callable naming ranks that want in at the top of
    a step; ``None`` derives it from the run's
    ``FaultPlan.rejoinable_ranks`` (deterministic replay).
    ``check_every``: disagreement-check cadence (steps) once the anneal
    has finished (the ``max_quarantine_steps`` deadline is enforced
    every step regardless).  ``sanitize``: zero non-finite entries on a
    joiner's state rows at admission (a real re-attached host arrives
    with garbage memory; the guard's frozen-finite invariant only
    covers ranks that died in-graph).  ``reset_opt_state``: zero the
    joiner's OPTIMIZER state rows at admission
    (:func:`bluefog_tpu.elastic.bootstrap.zero_rank_rows`) — the
    promotion gate measures params only, so stale-but-finite
    pre-preemption moments would otherwise rejoin silently; zeroed
    moments rebuild from fresh gradients during quarantine."""

    bootstrap_rounds: Optional[int] = None
    quarantine_threshold: Optional[float] = None
    max_quarantine_steps: int = 64
    admit: Optional[Callable[[int], Sequence[int]]] = None
    check_every: int = 1
    sanitize: bool = True
    reset_opt_state: bool = True


class MembershipController:
    """Host-side membership state machine over a mixing schedule.

    The controller owns the rank lifecycle (LIVE / DEAD / JOINING) and
    renders it, on demand, into the two traced-data views the compiled
    programs consume: :meth:`comm_weights` (per-round ``(class_weights,
    self_weights)`` pairs — healed around DEAD+JOINING receivers, with
    JOINING rows replaced by the annealed bootstrap pull of
    :func:`bluefog_tpu.elastic.bootstrap.bootstrap_weights`) and
    :meth:`membership_mask` (a float ``[n]`` LIVE indicator).  It
    composes with a :class:`~bluefog_tpu.resilience.detector.
    FailureDetector`: deaths are forwarded immediately, readmission
    only at PROMOTE time — while JOINING, the detector keeps the rank
    dead so bootstrap-window skips cannot trigger a fleet rollback.

    ``effective_dead_mask`` (everything not LIVE) is also the gossip
    mask: ``observe.fleet.FleetAggregator`` accepts the controller
    directly, so fleet telemetry heals and RE-GROWS in lockstep with
    the data plane."""

    def __init__(self, schedule, *,
                 bootstrap_rounds: Optional[int] = None,
                 quarantine_threshold: Optional[float] = None,
                 detector=None,
                 blackbox=None):
        if isinstance(schedule, (Topology, DynamicTopology)):
            schedule = [schedule]
        if not schedule:
            raise ValueError(
                "MembershipController needs a non-empty schedule")
        sizes = {s.size for s in schedule}
        if len(sizes) != 1:
            raise ValueError(f"schedule mixes topology sizes {sizes}")
        self.schedule: Tuple[CommSpec, ...] = tuple(schedule)
        self.size = sizes.pop()
        self.bootstrap_rounds = int(
            bootstrap_rounds if bootstrap_rounds is not None
            else _config.elastic_bootstrap_rounds())
        if self.bootstrap_rounds < 1:
            raise ValueError("bootstrap_rounds must be >= 1")
        self.quarantine_threshold = float(
            quarantine_threshold if quarantine_threshold is not None
            else _config.elastic_quarantine_threshold())
        self.detector = detector
        self._code = np.zeros(self.size, np.int8)
        self._progress = np.zeros(self.size, np.int64)
        self._steady: "OrderedDict[bytes, tuple]" = OrderedDict()
        # decision flight recorder: ``current_step`` is stamped by the
        # driving loop (run_resilient / SimTrainingFleet) so lifecycle
        # decisions carry the training step they happened at; each
        # joiner's admit event parents its eventual promote/kick, so
        # the audit chain reads admit→promote or admit→kick.
        self.blackbox = blackbox
        self.current_step = -1
        self._join_events: Dict[int, object] = {}

    # ------------------------------------------------------------- #
    # views
    # ------------------------------------------------------------- #
    def state(self, rank: int) -> str:
        return _STATE[int(self._code[self._check(rank)])]

    def states(self) -> List[str]:
        return [_STATE[int(c)] for c in self._code]

    def live_mask(self) -> np.ndarray:
        return self._code == _CODE[LIVE]

    def dead_mask(self) -> np.ndarray:
        return self._code == _CODE[DEAD]

    def joining_mask(self) -> np.ndarray:
        return self._code == _CODE[JOINING]

    def effective_dead_mask(self) -> np.ndarray:
        """Everything NOT live — the mask receivers (and the gossip
        layer) excise.  A JOINING rank is still excised here: it pulls
        but is not yet pulled from."""
        return self._code != _CODE[LIVE]

    def live_ranks(self) -> List[int]:
        return [int(r) for r in np.nonzero(self.live_mask())[0]]

    def dead_ranks(self) -> List[int]:
        return [int(r) for r in np.nonzero(self.dead_mask())[0]]

    def joining_ranks(self) -> List[int]:
        return [int(r) for r in np.nonzero(self.joining_mask())[0]]

    def is_live(self, rank: int) -> bool:
        return self._code[self._check(rank)] == _CODE[LIVE]

    def is_dead(self, rank: int) -> bool:
        return self._code[self._check(rank)] == _CODE[DEAD]

    def is_joining(self, rank: int) -> bool:
        return self._code[self._check(rank)] == _CODE[JOINING]

    def progress(self, rank: int) -> int:
        """Quarantined mixing rounds rank has participated in (0 for
        non-joining ranks)."""
        return int(self._progress[self._check(rank)])

    def counts(self) -> Dict[str, int]:
        return {s: int((self._code == c).sum()) for s, c in _CODE.items()}

    def _check(self, rank: int) -> int:
        r = int(rank)
        if not 0 <= r < self.size:
            raise ValueError(f"rank {r} outside world of size {self.size}")
        return r

    # ------------------------------------------------------------- #
    # transitions
    # ------------------------------------------------------------- #
    def seed_dead(self, dead_mask) -> None:
        """Adopt an existing dead set (e.g. ``detector.dead_mask()`` at
        loop start) without re-announcing the deaths."""
        dead = np.asarray(dead_mask, bool).reshape(-1)
        if dead.shape[0] != self.size:
            raise ValueError(
                f"dead mask of length {dead.shape[0]} does not match "
                f"world size {self.size}")
        self._code[dead] = _CODE[DEAD]
        self._progress[dead] = 0

    def mark_dead(self, ranks: Union[int, Sequence[int]]) -> None:
        """Any state -> DEAD (a JOINING rank that dies mid-bootstrap is
        simply dead again).  Forwarded to the detector immediately."""
        rs = [self._check(r) for r in _as_ranks(ranks)]
        for r in rs:
            self._code[r] = _CODE[DEAD]
            self._progress[r] = 0
        if rs and self.detector is not None:
            self.detector.declare_dead(
                [r for r in rs if not self.detector.dead_mask()[r]])
        for r in rs:
            self._decide("mark_dead", rank=r,
                         parent=self._join_events.pop(r, None))
        self._publish("dead", len(rs))

    def admit(self, ranks: Union[int, Sequence[int]]) -> None:
        """DEAD -> JOINING: start the quarantined bootstrap.  The
        detector deliberately still counts the rank dead (its skips
        must not look like live-rank failures); readmission happens at
        :meth:`promote`."""
        for r in _as_ranks(ranks):
            r = self._check(r)
            if self._code[r] != _CODE[DEAD]:
                raise ValueError(
                    f"rank {r} is {self.state(r)}, not dead — only dead "
                    "ranks can be admitted")
            self._code[r] = _CODE[JOINING]
            self._progress[r] = 0
            ev = self._decide("admit", rank=r)
            if ev is not None:
                self._join_events[r] = ev
        self._publish("joining", len(_as_ranks(ranks)))

    def promote(self, ranks: Union[int, Sequence[int]]) -> None:
        """JOINING -> LIVE: quarantine over.  Readmits the rank with
        the detector (clearing its latched streak/suspicion — without
        this ``suspects()`` would instantly re-excise it) and drops it
        from every subsequent healed view."""
        rs = []
        for r in _as_ranks(ranks):
            r = self._check(r)
            if self._code[r] != _CODE[JOINING]:
                raise ValueError(
                    f"rank {r} is {self.state(r)}, not joining — only "
                    "joining ranks can be promoted")
            rs.append(r)
        for r in rs:
            self._code[r] = _CODE[LIVE]
            self._progress[r] = 0
        if rs and self.detector is not None:
            self.detector.readmit(rs)
        for r in rs:
            self._decide("promote", rank=r,
                         parent=self._join_events.pop(r, None))
        self._publish("live", len(rs))

    def kick(self, ranks: Union[int, Sequence[int]]) -> None:
        """JOINING -> DEAD: bootstrap failed (over-threshold too long,
        or a rollback restored state that predates the bootstrap)."""
        for r in _as_ranks(ranks):
            r = self._check(r)
            if self._code[r] != _CODE[JOINING]:
                raise ValueError(
                    f"rank {r} is {self.state(r)}, not joining — only "
                    "joining ranks can be kicked")
            progress = int(self._progress[r])
            self._code[r] = _CODE[DEAD]
            self._progress[r] = 0
            self._decide("kick", rank=r,
                         parent=self._join_events.pop(r, None),
                         progress=progress)
        self._publish("dead", len(_as_ranks(ranks)))

    def tick(self) -> None:
        """One quarantined mixing round happened: advance every
        joiner's anneal progress."""
        self._progress[self._code == _CODE[JOINING]] += 1

    def reschedule(self, schedule) -> None:
        """Swap the topology schedule under the SAME membership: the
        topology control plane hot-swaps a re-planned schedule into a
        running step, and the membership weights must re-render over
        the new specs (re-plan from the pristine spec, then re-apply
        the current masks).  Rank states and joiner progress are
        untouched; the steady-weight cache is dropped — its entries
        were rendered over the old specs and keying is by membership
        pattern only."""
        if isinstance(schedule, (Topology, DynamicTopology)):
            schedule = [schedule]
        if not schedule:
            raise ValueError(
                "MembershipController.reschedule needs a non-empty "
                "schedule")
        sizes = {s.size for s in schedule}
        if sizes != {self.size}:
            raise ValueError(
                f"reschedule sizes {sizes} do not match world size "
                f"{self.size} — membership cannot survive a world "
                "resize")
        self.schedule = tuple(schedule)
        self._steady.clear()

    # ------------------------------------------------------------- #
    # traced-data renders
    # ------------------------------------------------------------- #
    def anneal(self) -> Dict[int, float]:
        """Joining rank -> anneal fraction in [0, 1] (progress over
        ``bootstrap_rounds``, clamped)."""
        from bluefog_tpu.elastic.bootstrap import anneal_fraction

        return {r: anneal_fraction(int(self._progress[r]),
                                   self.bootstrap_rounds)
                for r in self.joining_ranks()}

    def comm_weight_arrays(self) -> List[tuple]:
        """Per-round ``(class_weights, self_weights)`` float64 numpy
        pairs for the CURRENT membership: healed around every non-LIVE
        rank, with JOINING rows replaced by the annealed bootstrap
        pull.  Steady states (no joiner) are cached per membership
        pattern (bounded LRU — churn in both directions must not grow
        host memory); cached tables come back READ-ONLY, so treat them
        as immutable and copy before editing."""
        from bluefog_tpu.elastic.bootstrap import bootstrap_weights

        anneal = self.anneal()
        live = self.live_mask()
        if not anneal:
            key = self._code.tobytes()
            hit = self._steady.get(key)
            if hit is not None:
                self._steady.move_to_end(key)
                return [tuple(p) for p in hit]
            out = [bootstrap_weights(s, live, {}) for s in self.schedule]
            # cached arrays are handed out on every later hit, so they
            # are frozen: a caller mutating a returned table gets a
            # loud ValueError instead of silently corrupting every
            # subsequent render of this membership pattern
            for cw, sw in out:
                cw.flags.writeable = False
                sw.flags.writeable = False
            self._steady[key] = tuple(out)
            while len(self._steady) > _STEADY_CACHE_MAX:
                self._steady.popitem(last=False)
            return out
        return [bootstrap_weights(s, live, anneal) for s in self.schedule]

    def comm_weights(self) -> tuple:
        """The membership as traced-operand data: one jnp
        ``(class_weights, self_weights)`` pair per round, structurally
        identical to ``optim.functional.comm_weight_inputs(schedule)``
        — pass it straight into the compiled guarded step."""
        import jax.numpy as jnp

        return tuple((jnp.asarray(cw), jnp.asarray(sw))
                     for cw, sw in self.comm_weight_arrays())

    def membership_mask(self):
        """The traced ``[n_max]`` LIVE mask (float32, 1.0 = live) — for
        program logic that weights by membership rather than by the
        mixing rows (e.g. masked metrics)."""
        import jax.numpy as jnp

        return jnp.asarray(self.live_mask().astype(np.float32))

    def mixing_matrices(self) -> List[np.ndarray]:
        """Per-round receiver-major mixing matrices of the current
        membership — the pure-numpy view ``consensus_simulation``-style
        harnesses iterate (benchmarks/chaos_resilience.py part 4)."""
        return [mixing_matrix_from_weights(s, cw, sw)
                for s, (cw, sw) in zip(self.schedule,
                                       self.comm_weight_arrays())]

    # ------------------------------------------------------------- #
    # observability
    # ------------------------------------------------------------- #
    def _decide(self, kind: str, *, rank: int, parent=None, **detail):
        """The one blackbox emission seam of the membership plane (the
        ``decision-outside-recorder`` lint rule holds every lifecycle
        transition to it)."""
        from bluefog_tpu.observe import blackbox as _blackbox

        counts = self.counts()
        return _blackbox.record_decision(
            "membership", kind, step=self.current_step, parent=parent,
            telemetry={"rank": int(rank), "live": counts[LIVE],
                       "dead": counts[DEAD], "joining": counts[JOINING]},
            winner=str(int(rank)), blackbox=self.blackbox,
            detail=detail or None)

    def _publish(self, to_state: str, moved: int) -> None:
        from bluefog_tpu import observe

        if not observe.enabled():
            return
        reg = observe.get_registry()
        if moved:
            reg.counter("bf_elastic_transitions_total",
                        "membership transitions",
                        to=to_state).inc(moved)
        for s, c in self.counts().items():
            reg.gauge(f"bf_elastic_{s}_ranks",
                      f"ranks currently {s}").set(float(c))

    def __repr__(self):
        c = self.counts()
        return (f"MembershipController(size={self.size}, live={c[LIVE]}, "
                f"dead={c[DEAD]}, joining={c[JOINING]})")
