"""Elastic fleet membership: ranks that join, not just die.

The resilience stack (``bluefog_tpu.resilience``) excises dead ranks by
re-planning mixing weights; this package closes the loop with the
growth direction — the full LIVE -> DEAD -> JOINING -> LIVE lifecycle,
all of it delivered as traced DATA into programs compiled once at max
fleet size (no recompile on join, leave, or rejoin):

* :mod:`~bluefog_tpu.elastic.membership` — the
  :class:`MembershipController` state machine, and
  :func:`grow_weights`: the exact inverse of ``healing.heal_weights``
  (re-planned from the pristine spec, so ``heal -> grow`` round-trips
  byte-equal and stays row-stochastic at every step);
* :mod:`~bluefog_tpu.elastic.bootstrap` — a joiner syncs params/opt
  state by pulled neighbor averaging only: its self-weight anneals
  0 -> w over a few quarantined mixing rounds, no global broadcast;
* the runner integration —
  ``run_resilient(elastic=ElasticConfig(...))`` admits joiners between
  steps, quarantines them until bootstrap disagreement clears the
  threshold, and emits ``bf_elastic_*`` events/gauges.

Guide: docs/resilience.md "Elastic membership".
"""

from bluefog_tpu.elastic.membership import (  # noqa: F401
    DEAD,
    JOINING,
    LIVE,
    ElasticConfig,
    MembershipController,
    grow_spec,
    grow_weights,
    grown_comm_weights,
)
from bluefog_tpu.elastic.bootstrap import (  # noqa: F401
    anneal_fraction,
    bootstrap_comm_weights,
    bootstrap_weights,
    disagreement,
    sanitize_rank_rows,
    zero_rank_rows,
)

__all__ = [
    "LIVE",
    "DEAD",
    "JOINING",
    "ElasticConfig",
    "MembershipController",
    "grow_spec",
    "grow_weights",
    "grown_comm_weights",
    "anneal_fraction",
    "bootstrap_comm_weights",
    "bootstrap_weights",
    "disagreement",
    "sanitize_rank_rows",
    "zero_rank_rows",
]
