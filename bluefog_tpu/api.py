"""Flat BlueFog-compatible op API.

Mirrors ``bluefog.torch``'s public surface (reference
bluefog/torch/__init__.py:34-110, bluefog/torch/mpi_ops.py,
bluefog/common/basics.py) on rank-major JAX arrays.  Every tensor argument
and result is a global array of shape ``[size, ...]`` sharded over the rank
mesh axis — slice r is rank r's tensor.  Nonblocking variants return an int
handle; ``synchronize(handle)`` gives the array (JAX async dispatch makes
the "nonblocking" real: the program is enqueued, not executed, when the
handle returns).
"""

from __future__ import annotations

from contextlib import contextmanager
from typing import Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from bluefog_tpu import config as bfconfig
from bluefog_tpu import context as ctx_mod
from bluefog_tpu import timeline as timeline_mod
from bluefog_tpu.context import AXIS, BluefogContext, BluefogError, get_context
from bluefog_tpu.logging_util import get_logger
from bluefog_tpu.parallel import collectives as C
from bluefog_tpu.topology.graphs import ExponentialGraph
from bluefog_tpu.topology.spec import DynamicTopology
from bluefog_tpu.windows import WindowManager, win_lock_ctx, win_mutex_ctx

logger = get_logger()

_win_manager: Optional[WindowManager] = None


# ------------------------------------------------------------------ #
# lifecycle (reference basics.py:49-76)
# ------------------------------------------------------------------ #
_distributed_initialized = False


def _maybe_init_distributed() -> None:
    """Join the jax.distributed job described by the BLUEFOG_TPU_* env vars
    that ``bfrun`` sets (bluefog_tpu/run/run.py) — must happen before the
    first backend touch."""
    global _distributed_initialized

    coord = bfconfig.coordinator()
    nproc = bfconfig.num_processes()
    if _distributed_initialized or not coord or nproc <= 1:
        return
    pid = bfconfig.process_id()
    if pid is None:
        raise BluefogError(
            "BLUEFOG_TPU_COORDINATOR and BLUEFOG_TPU_NUM_PROCESSES are set "
            "but BLUEFOG_TPU_PROCESS_ID is missing; every process must "
            "export its id (bfrun sets all three).")
    try:
        jax.distributed.initialize(coordinator_address=coord,
                                   num_processes=nproc, process_id=pid)
    except RuntimeError as exc:  # already initialized by the platform
        logger.warning("jax.distributed.initialize skipped: %s", exc)
    _distributed_initialized = True


def init(topology_fn=None, is_weighted: bool = False, *,
         devices=None, local_size: Optional[int] = None) -> None:
    """Initialize the global context over ``devices`` (default: all).

    ``topology_fn``: callable returning the virtual topology; called with
    the world size if it accepts an argument (reference basics.py:49-69 —
    default ExponentialGraph).
    """
    global _win_manager
    _maybe_init_distributed()
    ctx = BluefogContext(devices=devices, local_size=local_size)
    ctx_mod.set_context(ctx)
    _win_manager = WindowManager(ctx)
    if topology_fn is not None:
        try:
            topo = topology_fn(ctx.size())
        except TypeError:
            topo = topology_fn()
    else:
        topo = ExponentialGraph(ctx.size())
    if not ctx.set_topology(topo, is_weighted):
        raise BluefogError("Failed to set initial topology.")
    tl_path = bfconfig.timeline_path()
    if tl_path:
        ctx.timeline = timeline_mod.start_timeline(tl_path, rank=jax.process_index())
    if jax.process_count() > 1 and bfconfig.stall_warning_time() > 0:
        # liveness beacons for the watchdog's rank attribution (reference
        # operations.cc:388-433 names the missing ranks); pointless when
        # the watchdog — their only consumer — is disabled
        interval = max(1.0, bfconfig.stall_warning_time() / 4)
        ctx_mod._heartbeat.start(interval)


def shutdown() -> None:
    global _win_manager
    ctx_mod._heartbeat.stop()
    timeline_mod.stop_timeline()
    _win_manager = None
    ctx_mod.set_context(None)


def is_initialized() -> bool:
    return ctx_mod.is_initialized()


def _wm() -> WindowManager:
    if _win_manager is None:
        raise BluefogError("BlueFog-TPU is not initialized; call init() first.")
    return _win_manager


# ------------------------------------------------------------------ #
# introspection (reference basics.py:78-265)
# ------------------------------------------------------------------ #
def size() -> int:
    return get_context().size()


def local_size() -> int:
    return get_context().local_size()


def rank() -> int:
    return get_context().rank()


def local_rank() -> int:
    return get_context().local_rank()


def machine_size() -> int:
    return get_context().machine_size()


def machine_rank() -> int:
    return get_context().machine_rank()


def is_homogeneous() -> bool:
    return get_context().is_homogeneous()


def load_topology():
    return get_context().load_topology()


def is_topo_weighted() -> bool:
    return get_context().is_topo_weighted()


def set_topology(topology=None, is_weighted: bool = False) -> bool:
    return get_context().set_topology(topology, is_weighted)


def load_machine_topology():
    return get_context().load_machine_topology()


def is_machine_topo_weighted() -> bool:
    return get_context().is_machine_topo_weighted()


def set_machine_topology(topology, is_weighted: bool = False) -> bool:
    return get_context().set_machine_topology(topology, is_weighted)


def in_neighbor_ranks(rank: Optional[int] = None) -> List[int]:
    return get_context().in_neighbor_ranks(rank)


def out_neighbor_ranks(rank: Optional[int] = None) -> List[int]:
    return get_context().out_neighbor_ranks(rank)


def in_neighbor_machine_ranks(machine_rank: Optional[int] = None) -> List[int]:
    return get_context().in_neighbor_machine_ranks(machine_rank)


def out_neighbor_machine_ranks(machine_rank: Optional[int] = None) -> List[int]:
    return get_context().out_neighbor_machine_ranks(machine_rank)


def suspend():
    get_context().suspend()


def resume():
    get_context().resume()


def set_skip_negotiate_stage(value: bool):
    get_context().set_skip_negotiate_stage(value)


def get_skip_negotiate_stage() -> bool:
    return get_context().get_skip_negotiate_stage()


def mpi_threads_supported() -> bool:
    """Parity shim — there is no MPI; SPMD dispatch is thread-safe."""
    return True


def unified_mpi_window_model_supported() -> bool:
    """Parity shim (reference basics.py unified window check)."""
    return True


def nccl_built() -> bool:
    """Parity shim — the data plane is XLA over ICI/DCN, not NCCL."""
    return False


# ------------------------------------------------------------------ #
# rank-major array helpers (TPU-build addition)
# ------------------------------------------------------------------ #
def rank_sharded(array) -> jax.Array:
    return get_context().rank_sharded(array)


def from_rank_values(values) -> jax.Array:
    return get_context().from_rank_values(values)


def to_rank_values(array) -> List[np.ndarray]:
    return get_context().to_rank_values(array)


# ------------------------------------------------------------------ #
# collectives (reference mpi_ops.py)
# ------------------------------------------------------------------ #
def allreduce(tensor, average: bool = True, name: Optional[str] = None,
              is_hierarchical_local: bool = False) -> jax.Array:
    return synchronize(
        allreduce_nonblocking(tensor, average, name, is_hierarchical_local)
    )


def allreduce_nonblocking(tensor, average: bool = True,
                          name: Optional[str] = None,
                          is_hierarchical_local: bool = False) -> int:
    ctx = get_context()
    if is_hierarchical_local:
        groups = C.machine_groups(ctx.size(), ctx.local_size())
        local = ctx.local_size()

        def kernel(x, _groups=groups, _local=local, _avg=average):
            import jax.numpy as jnp
            from jax import lax
            acc = lax.psum(x.astype(jnp.float32), AXIS, axis_index_groups=_groups)
            if _avg:
                acc = acc / _local
            return acc.astype(x.dtype)

        out = ctx.run_op(("allreduce_local", average, ctx.local_size()), kernel, tensor)
    else:
        out = ctx.run_op(("allreduce", average),
                         lambda x: C.allreduce(x, AXIS, average), tensor)
    return ctx.register_handle(name, "allreduce", out)


def allreduce_(tensor, average: bool = True, name: Optional[str] = None) -> jax.Array:
    """In-place spelling kept for parity; JAX arrays are immutable, so this
    returns the new array (callers rebind)."""
    return allreduce(tensor, average, name)


def allreduce_nonblocking_(tensor, average: bool = True,
                           name: Optional[str] = None) -> int:
    return allreduce_nonblocking(tensor, average, name)


def broadcast(tensor, root_rank: int, name: Optional[str] = None) -> jax.Array:
    return synchronize(broadcast_nonblocking(tensor, root_rank, name))


def broadcast_nonblocking(tensor, root_rank: int,
                          name: Optional[str] = None) -> int:
    ctx = get_context()
    out = ctx.run_op(("broadcast", root_rank),
                     lambda x: C.broadcast(x, root_rank, AXIS), tensor)
    return ctx.register_handle(name, "broadcast", out)


def broadcast_(tensor, root_rank: int, name: Optional[str] = None) -> jax.Array:
    return broadcast(tensor, root_rank, name)


def broadcast_nonblocking_(tensor, root_rank: int,
                           name: Optional[str] = None) -> int:
    return broadcast_nonblocking(tensor, root_rank, name)


def allgather(tensor, name: Optional[str] = None) -> jax.Array:
    return synchronize(allgather_nonblocking(tensor, name))


def allgather_nonblocking(tensor, name: Optional[str] = None) -> int:
    """Concatenate all ranks' tensors along dim 0.

    Equal per-rank shapes take the direct ``all_gather`` path.  Variable
    dim-0 sizes (reference allgatherv, mpi_controller.cc:136-168) are
    accepted as a list/tuple of per-rank arrays: payloads are padded to the
    max row count, gathered in one collective, and the pad rows dropped on
    device by a static row-gather (see ``collectives.allgatherv``).
    """
    ctx = get_context()
    if isinstance(tensor, (list, tuple)):
        parts = [np.asarray(t) for t in tensor]
        if len(parts) != ctx.size():
            raise BluefogError(
                f"variable-size allgather needs one tensor per rank "
                f"({ctx.size()}), got {len(parts)}")
        if any(p.ndim < 1 for p in parts):
            raise BluefogError(
                "variable-size allgather needs at least rank-1 tensors "
                "(the concat axis is dim 0)")
        trailing = {p.shape[1:] for p in parts}
        if len(trailing) != 1:
            raise BluefogError(
                f"variable-size allgather: trailing dims must match, "
                f"got {sorted(trailing)}")
        dtypes = {p.dtype for p in parts}
        if len(dtypes) != 1:
            raise BluefogError(
                f"variable-size allgather: dtypes must match, "
                f"got {sorted(str(d) for d in dtypes)}")
        sizes = tuple(p.shape[0] for p in parts)
        pad = max(sizes) if sizes else 0
        padded = np.zeros((len(parts), pad) + parts[0].shape[1:],
                          dtype=parts[0].dtype)
        for r, p in enumerate(parts):
            padded[r, :p.shape[0]] = p
        out = ctx.run_op(("allgatherv", sizes),
                         lambda x: C.allgatherv(x, sizes, AXIS), padded)
    else:
        out = ctx.run_op(("allgather",), lambda x: C.allgather(x, AXIS),
                         tensor)
    return ctx.register_handle(name, "allgather", out)


def neighbor_allreduce(tensor, *, self_weight=None, src_weights=None,
                       dst_weights=None, enable_topo_check: bool = True,
                       compress: Optional[str] = None,
                       name: Optional[str] = None) -> jax.Array:
    return synchronize(neighbor_allreduce_nonblocking(
        tensor, self_weight=self_weight, src_weights=src_weights,
        dst_weights=dst_weights, enable_topo_check=enable_topo_check,
        compress=compress, name=name))


def neighbor_allreduce_nonblocking(tensor, *, self_weight=None,
                                   src_weights=None, dst_weights=None,
                                   enable_topo_check: bool = True,
                                   compress: Optional[str] = None,
                                   name: Optional[str] = None) -> int:
    ctx = get_context()
    spec, _dynamic = ctx.resolve_neighbor_spec(
        self_weight, src_weights, dst_weights,
        enable_topo_check=enable_topo_check)
    if isinstance(spec, DynamicTopology):
        # Compile-cache key = edge STRUCTURE only; the combine weights
        # enter as traced operands, so a schedule that varies weight
        # VALUES every step (e.g. decaying averaging weights) reuses ONE
        # compiled program (windows.py put/update design; round-2
        # verdict item 2).
        out = ctx.run_op(
            ("neighbor_allreduce", spec.size, spec.edges, compress),
            lambda x, wv, sw: C.neighbor_allreduce(
                x, C.edge_structure(spec), AXIS, compress=compress,
                class_weights=wv, self_weights=sw),
            tensor, C.class_recv_weights(spec), C.self_weight_vector(spec))
    else:
        out = ctx.run_op(("neighbor_allreduce", spec.digest(), compress),
                         lambda x: C.neighbor_allreduce(
                             x, spec, AXIS, compress=compress), tensor)
    return ctx.register_handle(name, "neighbor_allreduce", out)


def hierarchical_neighbor_allreduce(tensor, *, self_weight=None,
                                    src_machine_weights=None,
                                    dst_machine_weights=None,
                                    enable_topo_check: bool = False,
                                    name: Optional[str] = None) -> jax.Array:
    return synchronize(hierarchical_neighbor_allreduce_nonblocking(
        tensor, self_weight=self_weight,
        src_machine_weights=src_machine_weights,
        dst_machine_weights=dst_machine_weights,
        enable_topo_check=enable_topo_check, name=name))


def hierarchical_neighbor_allreduce_nonblocking(
        tensor, *, self_weight=None, src_machine_weights=None,
        dst_machine_weights=None, enable_topo_check: bool = False,
        name: Optional[str] = None) -> int:
    ctx = get_context()
    if ctx.load_machine_topology() is None and (
            self_weight is None and src_machine_weights is None):
        raise BluefogError(
            "hierarchical_neighbor_allreduce needs set_machine_topology() "
            "or explicit machine weights."
        )
    spec, _dynamic = ctx.resolve_neighbor_spec(
        self_weight, src_machine_weights, dst_machine_weights,
        machine_level=True)
    local = ctx.local_size()
    if isinstance(spec, DynamicTopology):
        # structure-keyed + weights-as-operands, like neighbor_allreduce
        out = ctx.run_op(
            ("hierarchical_neighbor_allreduce", spec.size, spec.edges,
             local),
            lambda x, wv, sw: C.hierarchical_neighbor_allreduce(
                x, C.edge_structure(spec), local, AXIS,
                class_weights=wv, self_weights=sw),
            tensor, C.class_recv_weights(spec), C.self_weight_vector(spec))
    else:
        out = ctx.run_op(
            ("hierarchical_neighbor_allreduce", spec.digest(), local),
            lambda x: C.hierarchical_neighbor_allreduce(x, spec, local, AXIS),
            tensor)
    return ctx.register_handle(name, "hierarchical_neighbor_allreduce", out)


def neighbor_allgather(tensor, *, src_ranks=None, dst_ranks=None,
                       enable_topo_check: bool = True,
                       name: Optional[str] = None):
    """Concatenate in-neighbor tensors along dim 0 (reference
    torch/mpi_ops.py:400-476).  Returns a rank-major array
    ``[size, in_degree * d0, ...]`` when every rank has the same in-degree,
    otherwise a list of per-rank arrays (ragged)."""
    return synchronize(neighbor_allgather_nonblocking(
        tensor, src_ranks=src_ranks, dst_ranks=dst_ranks,
        enable_topo_check=enable_topo_check, name=name))


def neighbor_allgather_nonblocking(tensor, *, src_ranks=None, dst_ranks=None,
                                   enable_topo_check: bool = True,
                                   name: Optional[str] = None) -> int:
    ctx = get_context()
    n = ctx.size()
    if (src_ranks is None) != (dst_ranks is None):
        raise ValueError(
            "Arguments src_ranks and dst_ranks should be presented at the "
            "same time")
    if src_ranks is None:
        spec = ctx.topology_spec()
    else:
        from bluefog_tpu.context import WeightArg
        src_per = WeightArg.per_rank(src_ranks, n, "src")
        dst_per = WeightArg.per_rank(dst_ranks, n, "dst")
        edge_weights = {}
        for dstr in range(n):
            entry = src_per[dstr] or []
            srcs = list(entry.keys()) if isinstance(entry, dict) else list(entry)
            for s in srcs:
                if int(s) == dstr:
                    raise BluefogError(
                        f"neighbor_allgather src_ranks for rank {dstr} "
                        "contains itself; self values are not gathered.")
                edge_weights[(int(s), dstr)] = 1.0
        # cross-check like enable_topo_check
        if enable_topo_check:
            for srcr in range(n):
                entry = dst_per[srcr] or []
                dsts = list(entry.keys()) if isinstance(entry, dict) else list(entry)
                for d in dsts:
                    if (srcr, int(d)) not in edge_weights:
                        raise BluefogError(
                            "Send and recv neighbors mismatch in "
                            "neighbor_allgather dynamic mode.")
        spec = DynamicTopology.from_edges(n, edge_weights)
    # The kernel orders slots by the spec-derived sorted in-neighbor
    # lists; use the same derivation here so finalize can never disagree
    # with the kernel's slot layout.
    in_lists = C.in_neighbor_lists(spec)
    # Padded in-degree-sized kernel: per-shard memory O(max_in_degree*|x|)
    # (the dense [n, ...] buffer would be O(n*|x|) per shard — O(n^2)
    # total; the reference also allocates in-degree-sized output,
    # mpi_controller.cc:282-361).  Slots are ordered by source rank.
    padded = ctx.run_op(
        ("neighbor_allgather_padded", spec.digest()),
        lambda x: C.neighbor_allgather_padded(x, spec, AXIS), tensor)
    uniform = len({len(l) for l in in_lists}) == 1

    if uniform:
        # [n, d, d0, ...] -> [n, d*d0, ...] on device: already the
        # reference's concat-by-source layout.  No host round trip (and
        # no jit: reshape on a committed array preserves the sharding).
        out = padded.reshape((padded.shape[0],
                              padded.shape[1] * padded.shape[2])
                             + padded.shape[3:])
        return ctx.register_handle(name, "neighbor_allgather", out)

    def finalize(padded_arr):
        from bluefog_tpu.context import host_fetch
        host = host_fetch(padded_arr)
        per_rank = [
            np.concatenate([host[r, k] for k in range(len(in_lists[r]))],
                           axis=0)
            if in_lists[r] else host[r, :0].reshape((0,) + host.shape[3:])
            for r in range(n)
        ]
        return per_rank

    out = _LazyResult(padded, finalize)
    return ctx.register_handle(name, "neighbor_allgather", out)


class _LazyResult:
    """Defers host-side post-processing until synchronize()."""

    def __init__(self, raw, finalize):
        self.raw = raw
        self.finalize = finalize

    def block(self):
        jax.block_until_ready(self.raw)
        return self.finalize(self.raw)


def pair_gossip(tensor, target_rank, self_weight: Optional[float] = None,
                pair_weight: Optional[float] = None,
                name: Optional[str] = None) -> jax.Array:
    return synchronize(pair_gossip_nonblocking(
        tensor, target_rank, self_weight, pair_weight, name))


def pair_gossip_nonblocking(tensor, target_rank,
                            self_weight: Optional[float] = None,
                            pair_weight: Optional[float] = None,
                            name: Optional[str] = None) -> int:
    """``target_rank``: length-``size`` sequence, entry r = rank r's pair
    (reference per-rank scalar arg, torch/mpi_ops.py:883-945)."""
    ctx = get_context()
    targets = tuple(int(t) for t in target_rank)
    if len(targets) != ctx.size():
        raise ValueError(
            f"target_rank must list every rank's pair (length {ctx.size()})")
    out = ctx.run_op(
        ("pair_gossip", targets, self_weight, pair_weight),
        lambda x: C.pair_gossip(x, targets, AXIS, self_weight, pair_weight),
        tensor)
    return ctx.register_handle(name, "pair_gossip", out)


def barrier():
    get_context().barrier()


def synchronize(handle: int):
    value = get_context().synchronize(handle)
    if isinstance(value, _LazyResult):
        return value.block()
    return value


def wait(handle: int):
    return synchronize(handle)


def poll(handle: int) -> bool:
    return get_context().poll(handle)


# ------------------------------------------------------------------ #
# windows (reference mpi_ops.py:1014-1503)
# ------------------------------------------------------------------ #
def win_create(tensor, name: str, zero_init: bool = False) -> bool:
    return _wm().create(tensor, name, zero_init)


def win_free(name: Optional[str] = None) -> bool:
    return _wm().free(name)


def win_update(name: str, self_weight: Optional[float] = None,
               neighbor_weights: Optional[Dict[int, float]] = None,
               reset: bool = False, clone: bool = False,
               require_mutex: bool = False) -> jax.Array:
    return _wm().update(name, self_weight, neighbor_weights, reset, clone,
                        require_mutex)


def win_update_then_collect(name: str, require_mutex: bool = True) -> jax.Array:
    ctx = get_context()
    n = ctx.size()
    neighbor_weights = [
        {r: 1.0 for r in ctx.in_neighbor_ranks(dst)} for dst in range(n)
    ]
    return win_update(name, self_weight=1.0,
                      neighbor_weights=neighbor_weights, reset=True,
                      require_mutex=require_mutex)


def win_put_nonblocking(tensor, name: str, self_weight: Optional[float] = None,
                        dst_weights=None, require_mutex: bool = False) -> int:
    return _wm().put(tensor, name, self_weight, dst_weights, require_mutex,
                     accumulate=False)


def win_put(tensor, name: str, self_weight: Optional[float] = None,
            dst_weights=None, require_mutex: bool = False) -> bool:
    return win_wait(win_put_nonblocking(tensor, name, self_weight,
                                        dst_weights, require_mutex))


def win_accumulate_nonblocking(tensor, name: str,
                               self_weight: Optional[float] = None,
                               dst_weights=None,
                               require_mutex: bool = False) -> int:
    return _wm().put(tensor, name, self_weight, dst_weights, require_mutex,
                     accumulate=True)


def win_accumulate(tensor, name: str, self_weight: Optional[float] = None,
                   dst_weights=None, require_mutex: bool = False) -> bool:
    return win_wait(win_accumulate_nonblocking(tensor, name, self_weight,
                                               dst_weights, require_mutex))


def win_get_nonblocking(name: str, src_weights=None,
                        require_mutex: bool = False) -> int:
    return _wm().get(name, src_weights, require_mutex)


def win_get(name: str, src_weights=None, require_mutex: bool = False) -> bool:
    return win_wait(win_get_nonblocking(name, src_weights, require_mutex))


def win_set_value(name: str, tensor) -> None:
    """Replace the window's base tensor (TPU-build addition: the reference
    mutates the registered torch tensor in place, mpi_win_ops.cc:83-105;
    immutable jax arrays need an explicit rebind)."""
    _wm().set_value(name, tensor)


def win_wait(handle: int) -> bool:
    return _wm().wait(handle)


def win_poll(handle: int) -> bool:
    return _wm().poll(handle)


@contextmanager
def win_mutex(name: str, for_self: bool = False,
              ranks: Optional[List[int]] = None):
    with win_mutex_ctx(_wm(), name, for_self, ranks):
        yield


@contextmanager
def win_lock(name: str):
    with win_lock_ctx(_wm(), name):
        yield


def win_unlock(name: str):
    _wm().window(name)  # validate; epochs are implicit under SPMD


def win_fence(name: str):
    # fence BOTH the window value and the mailbox: win_put with
    # self_weight rebinds win.value (the in-place local scale), so a
    # fence that only drained the mailbox could return while the scaled
    # self value is still in flight (round-5 verdict item 7)
    win = _wm().window(name)
    ctx_mod.timed_wait(f"win_fence.{name}",
                       lambda: jax.block_until_ready((win.value,
                                                      win.mailbox)))


def get_win_version(name: str, rank: Optional[int] = None) -> Dict[int, int]:
    return _wm().versions_of(name, rank)


def get_current_created_window_names() -> List[str]:
    return _wm().names()


def win_associated_p(name: str, rank: Optional[int] = None) -> float:
    return _wm().associated_p(name, rank)


def turn_on_win_ops_with_associated_p():
    get_context().win_ops_with_associated_p = True


def turn_off_win_ops_with_associated_p():
    get_context().win_ops_with_associated_p = False


# ------------------------------------------------------------------ #
# timeline (reference basics.py:456-546)
# ------------------------------------------------------------------ #
def timeline_start_activity(tensor_name: str, activity_name: str) -> bool:
    tl = timeline_mod.get_timeline()
    if tl is None:
        return False
    tl.start_activity(tensor_name, activity_name)
    return True


def timeline_end_activity(tensor_name: str) -> bool:
    tl = timeline_mod.get_timeline()
    if tl is None:
        return False
    tl.end_activity(tensor_name)
    return True


@contextmanager
def timeline_context(tensor_name: str, activity_name: str):
    timeline_start_activity(tensor_name, activity_name)
    try:
        yield
    finally:
        timeline_end_activity(tensor_name)
