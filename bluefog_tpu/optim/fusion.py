"""Shared trace-time bucket/fusion planner.

One grouping policy, two consumers:

* the EAGER optimizer wrappers (``bluefog_tpu.optim.wrappers``) pack
  parameter leaves into few flat fusion buffers per combine (reference
  operations.cc:943-1020 + FusionBufferManager tensor_queue.h:75-124),
  so one eager step issues O(#buffers) collective programs instead of
  O(#leaves);
* the JITTED overlap engine (``bluefog_tpu.optim.functional``,
  ``build_train_step(overlap="bucketed")``) splits the param tree into
  K size-balanced buckets so the decentralized exchange lowers to K
  independent collective-permutes the latency-hiding scheduler can
  interleave with compute, instead of one per leaf clumped at the tail.

Both paths MUST agree on bucket assignments for the same leaf signature
and threshold (asserted by tests/test_fusion.py): the grouping walk
lives here and nowhere else.

Grouping policy (identical to the reference's fusion buffer): walk the
leaves in tree order, packing consecutive same-dtype leaves into the
current bucket until adding the next leaf would exceed ``threshold``
bytes; a dtype change always closes the bucket (no silent casting), and
a leaf larger than the threshold gets a bucket of its own.  Sound for
any elementwise-linear collective (allreduce / neighbor_allreduce /
hierarchical): the weighted combine distributes over concatenation.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "plan_groups",
    "size_balanced_threshold",
    "leaf_signature",
    "bucket_signature",
    "epilogue_stages",
    "EpilogueBucket",
    "EpiloguePlan",
    "EPILOGUE_STAGE_ORDER",
    "FusionPlan",
]

# Canonical stage order of the fused per-bucket epilogue pipeline
# (build_train_step's jitted fast path).  Every feature that used to
# re-traverse the full param tree around the exchange is expressed as a
# per-bucket stage instead, so the compiler sees ONE composed pass over
# each bucket's leaves — HiCCL's composable-primitive decomposition
# applied to the train-step epilogue (PAPERS.md: HiCCL):
#
#   pack         gather the bucket's leaves into one flat buffer
#   ef_encode    error-feedback delta + top-k sparsify (compressed
#                mixing: wire becomes compress(x - ref + e), residual
#                folds into e — collectives.mix_compress_exchange)
#   quantize     wire compression encode (int8 absmax / bf16 round;
#                under ef_encode it quantizes the kept top-k VALUES)
#   exchange     the bucket's own neighbor collective
#   dequantize   wire decode + weighted combine (f32 accumulation)
#   ef_decode    receiver-side reconstruction ref + delta (the mirror
#                integration of the sparse wire)
#   guard_select per-rank skip: elementwise select against last-good
#   health_norm  partial grad/update sq-sums for the HealthVector
#   consensus    partial ||pre - mixed||^2 from the exchange's own
#                buffers (no re-mix, no second tree walk)
#   unpack       scatter the combined buffer back to leaf shapes
EPILOGUE_STAGE_ORDER = (
    "pack", "ef_encode", "quantize", "exchange", "dequantize",
    "ef_decode", "guard_select", "health_norm", "consensus", "unpack",
)


def epilogue_stages(compress=None, guard: bool = False,
                    health: bool = False,
                    consensus: bool = False,
                    mix: bool = False) -> Tuple[str, ...]:
    """The epilogue stage list a feature combination composes to, in
    canonical order.  ``pack``/``exchange``/``unpack`` are always
    present (a single-leaf bucket's pack/unpack are identity);
    ``quantize``/``dequantize`` ride with wire compression,
    ``ef_encode``/``ef_decode`` with error-feedback compressed mixing
    (``compress="topk"``, where ``quantize``/``dequantize`` then apply
    to the kept top-k values if the mix config says so),
    ``guard_select`` with a GuardConfig, ``health_norm`` with a
    HealthConfig, and ``consensus`` with ``HealthConfig.consensus``."""
    on = {"pack", "exchange", "unpack"}
    if compress:
        on |= {"quantize", "dequantize"}
    if mix:
        on |= {"ef_encode", "ef_decode"}
    if guard:
        on.add("guard_select")
    if health:
        on.add("health_norm")
    if consensus:
        on.add("consensus")
    return tuple(s for s in EPILOGUE_STAGE_ORDER if s in on)


@dataclasses.dataclass(frozen=True)
class EpilogueBucket:
    """One fusion-plan bucket plus the epilogue stage list that runs
    over it as a single composed pass (the per-bucket closure
    ``optim.functional`` emits)."""

    index: int                  # bucket position in plan order
    leaves: Tuple[int, ...]     # leaf indices, tree order
    nbytes: int                 # per-shard payload bytes
    dtype: str                  # uniform dtype of the bucket's leaves
    stages: Tuple[str, ...]     # subset of EPILOGUE_STAGE_ORDER


@dataclasses.dataclass(frozen=True)
class EpiloguePlan:
    """Trace-time plan of the fused per-bucket epilogue pipeline: the
    grouping walk's buckets, each carrying its stage list.  Built by
    :meth:`for_leaves` from the SAME grouping walk as the eager fusion
    buffers and the overlap engine (``plan_groups``) — one bucket per
    leaf when ``n_buckets`` is None (the plain, non-overlapped path:
    per-tensor wire scales and no concat traffic), size-balanced
    buckets otherwise."""

    buckets: Tuple[EpilogueBucket, ...]
    stages: Tuple[str, ...]

    @classmethod
    def for_leaves(cls, leaves, n_buckets, *, compress=None,
                   guard: bool = False, health: bool = False,
                   consensus: bool = False,
                   mix: bool = False) -> "EpiloguePlan":
        rows = bucket_signature(leaves)
        if n_buckets is None:
            groups = [[i] for i in range(len(rows))]
        else:
            threshold = size_balanced_threshold(rows, n_buckets)
            groups = plan_groups(rows, threshold)
        stages = epilogue_stages(compress=compress, guard=guard,
                                 health=health, consensus=consensus,
                                 mix=mix)
        buckets = tuple(
            EpilogueBucket(
                index=b,
                leaves=tuple(g),
                nbytes=sum(rows[i][0] for i in g),
                dtype=rows[g[0]][1],
                stages=stages)
            for b, g in enumerate(groups))
        return cls(buckets=buckets, stages=stages)

    @property
    def groups(self) -> List[List[int]]:
        """The bare grouping (``plan_groups`` layout) for consumers
        that only pack/unpack."""
        return [list(b.leaves) for b in self.buckets]

# (nbytes, dtype_str) per leaf — the only inputs the grouping walk sees.
SizeDtype = Tuple[int, str]


def plan_groups(sizes_dtypes: Sequence[SizeDtype],
                threshold: int) -> List[List[int]]:
    """The ONE grouping walk: consecutive same-dtype leaves pack into a
    bucket of at most ``threshold`` bytes (an oversize leaf stands
    alone).  Returns a list of buckets, each a list of leaf indices in
    order; every index appears exactly once."""
    groups: List[List[int]] = []
    cur: List[int] = []
    cur_bytes = 0
    cur_dtype = None
    for i, (nbytes, dtype) in enumerate(sizes_dtypes):
        nbytes = int(nbytes)
        if cur and (dtype != cur_dtype or cur_bytes + nbytes > threshold):
            groups.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nbytes
        cur_dtype = dtype
    if cur:
        groups.append(cur)
    return groups


def size_balanced_threshold(sizes_dtypes: Sequence[SizeDtype],
                            n_buckets: int) -> int:
    """Byte threshold that makes ``plan_groups`` yield ~``n_buckets``
    size-balanced buckets: ceil(total/K).  Dtype boundaries can only
    INCREASE the bucket count.  Granularity is the LEAF (the walk never
    splits one — it must agree with the eager fusion plan), so a single
    leaf larger than ceil(total/K) absorbs more than its share and the
    final count can land below K on dominated trees (e.g. one stacked
    scan_layers kernel holding most of the bytes); the count is then
    the best achievable at leaf granularity."""
    if n_buckets <= 0:
        raise ValueError(f"n_buckets must be positive, got {n_buckets}")
    total = sum(int(nb) for nb, _ in sizes_dtypes)
    return max(1, math.ceil(total / n_buckets))


def leaf_signature(leaves) -> Tuple[Tuple[Tuple[int, ...], str], ...]:
    """((shape, dtype_str), ...) — the hashable trace-time identity of a
    leaf list (works on arrays and on ShapeDtypeStructs)."""
    return tuple(
        (tuple(l.shape), str(jnp.asarray(l).dtype
                             if not hasattr(l, "dtype") else l.dtype))
        for l in leaves)


def bucket_signature(leaves, skip_leading_axis: bool = False):
    """(nbytes, dtype) rows for ``plan_groups`` from a leaf list.

    ``skip_leading_axis=True`` measures per-rank bytes of rank-major
    ``[n, ...]`` leaves (the eager wrappers' layout); the jitted path
    measures the whole per-shard leaf."""
    rows = []
    for shape, dtype in leaf_signature(leaves):
        dims = shape[1:] if skip_leading_axis else shape
        rows.append((int(np.prod(dims, dtype=np.int64))
                     * jnp.dtype(dtype).itemsize, dtype))
    return rows


class FusionPlan:
    """Rank-major tensor fusion for the eager path: same-dtype parameter
    leaves are packed, in order, into flat ``[n, K]`` buffers of at most
    ``threshold`` bytes per rank, so one combine issues O(#buffers)
    collective programs instead of O(#leaves) — ~160 leaves of ResNet-50
    become 2-3 dispatches (reference operations.cc:943-1020).

    ``pack`` and ``unpack`` are each ONE jitted program, cached per leaf
    signature (module-level, bounded by the distinct model shapes in the
    process).
    """

    _cache: Dict[Any, "FusionPlan"] = {}

    def __init__(self, signature, threshold: int):
        self.signature = signature  # tuple of ((n, ...) shape, dtype str)
        rows = [
            (int(np.prod(shape[1:], dtype=np.int64))
             * jnp.dtype(dtype).itemsize, dtype)
            for shape, dtype in signature
        ]
        groups = plan_groups(rows, threshold)
        self.groups = groups
        # each bucket carries its epilogue stage list; the eager path's
        # combine is uncompressed/unguarded, so the stages are the bare
        # pack -> exchange -> unpack pipeline — the jitted builder
        # constructs richer plans via EpiloguePlan.for_leaves
        stages = epilogue_stages()
        self.buckets = tuple(
            EpilogueBucket(
                index=b, leaves=tuple(g),
                nbytes=sum(rows[i][0] for i in g),
                dtype=rows[g[0]][1], stages=stages)
            for b, g in enumerate(groups))

        def pack(leaves):
            n = leaves[0].shape[0]
            return tuple(
                jnp.concatenate(
                    [jnp.reshape(leaves[i], (n, -1)) for i in g], axis=1)
                if len(g) > 1 else leaves[g[0]]
                for g in groups)

        def unpack(buffers):
            outs = [None] * len(signature)
            for g, buf in zip(groups, buffers):
                if len(g) == 1:
                    outs[g[0]] = buf
                    continue
                off = 0
                for i in g:
                    shape = signature[i][0]
                    k = int(np.prod(shape[1:]))
                    outs[i] = jnp.reshape(buf[:, off:off + k], shape)
                    off += k
            return tuple(outs)

        self.pack = jax.jit(pack)
        self.unpack = jax.jit(unpack)

    @classmethod
    def for_leaves(cls, leaves, threshold: int) -> "FusionPlan":
        signature = leaf_signature(leaves)
        key = (signature, threshold)
        plan = cls._cache.get(key)
        if plan is None:
            plan = cls(signature, threshold)
            cls._cache[key] = plan
        return plan
