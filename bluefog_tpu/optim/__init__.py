"""Distributed optimizer wrappers (optax-based).

Reference parity: bluefog/torch/optimizers.py — five mechanisms:
gradient allreduce, adapt-with-combine (CTA), adapt-then-combine (ATC),
win-put/pull-get (async gossip), push-sum.
"""

from bluefog_tpu.optim.wrappers import (  # noqa: F401
    CommunicationType,
    DistributedGradientAllreduceOptimizer,
    DistributedAdaptWithCombineOptimizer,
    DistributedAdaptThenCombineOptimizer,
    DistributedAllreduceOptimizer,
    DistributedNeighborAllreduceOptimizer,
    DistributedHierarchicalNeighborAllreduceOptimizer,
    DistributedWinPutOptimizer,
    DistributedPullGetOptimizer,
    DistributedPushSumOptimizer,
)
from bluefog_tpu.optim.functional import (  # noqa: F401
    GuardConfig,
    HealthConfig,
    HealthVector,
    MoEConfig,
    build_train_step,
    comm_weight_inputs,
    consensus_distance,
    rank_major,
    rank_spec_tree,
)
from bluefog_tpu.optim.fusion import (  # noqa: F401
    FusionPlan,
    plan_groups,
    size_balanced_threshold,
)
