"""Fully-jitted decentralized train steps.

The eager optimizer wrappers (``bluefog_tpu.optim.wrappers``) mirror the
reference's host-driven hook model (reference bluefog/torch/optimizers.py) —
good for parity, but each op is a separate dispatch.  This module is the
TPU-first fast path: ONE compiled SPMD program per train step containing
forward, backward, the base optax update, and the decentralized combine —
XLA overlaps the ppermutes with compute, exactly what the reference gets
from its background thread + tensor fusion (reference
common/operations.cc:453-1020), but compiler-scheduled instead of
hand-scheduled.

Key design points (SURVEY.md §7 "hard parts"):

* **Dynamic topologies without retrace storms** — pass ``schedule`` (a list
  of topology specs, e.g. the log2(n) one-peer exponential-2 rounds); the
  step index selects the round's combine via ``lax.switch`` inside the one
  compiled program.  No retracing, no host round-trip per iteration.
* **Rank-major state** — every rank owns its own parameters (decentralized
  DP: nothing is replicated).  Params/opt-state/batch leaves all carry a
  leading ``[n_ranks]`` axis sharded over ``axis_name``; use
  :func:`rank_major` / :func:`rank_spec_tree` to build them.
* **Sequence parallelism composes** — give the mesh an extra axis and pass
  ``sp_axis``; gradients are psum-reduced over it (params are replicated
  across sp), so a ring-attention model trains with dp x sp on one mesh.

Combine math is f32-accumulated via the shard-level kernels in
``bluefog_tpu.parallel.collectives``.

**Fused per-bucket epilogue pipeline** (default since ISSUE 6): the
skip guard's isfinite reduce, the HealthVector's norms, wire
quantization, and the consensus distance used to each re-traverse the
full param tree around the same neighbor exchange — pure non-collective
overhead stacked on the hot path (the flat 2723→2746 img/s/chip BENCH
trajectory across r01–r05).  The builder now plans the param tree into
fusion buckets (``optim.fusion.EpiloguePlan`` — one bucket per leaf on
the plain path, size-balanced buckets under ``overlap="bucketed"``) and
emits ONE composed closure per bucket running quantize → exchange →
dequantize → guard-select → health-norm over that bucket's leaves; the
guard/health reductions are accumulated as per-bucket partials combined
at the end, and the consensus distance is computed from the exchange's
already-materialized pre/post buffers (no re-mix, no second tree walk).
``BLUEFOG_FUSE_EPILOGUES=0`` restores the pre-fusion builders — the
debugging escape hatch and the golden reference of the epilogue parity
matrix (tests/test_epilogue.py).  The fused combine weights ride as
TRACED OPERANDS in both the guarded and unguarded builds, so the two
share one association order: the uniform-weight static-CTA constant-
fold 1-ulp caveat of the pre-fusion path (CHANGES.md PR 3) is gone.
"""

from __future__ import annotations

# This module legitimately constructs weight tables from scratch — the
# analysis lint's weight-matrix-bypass rule treats it as an authority
# (everywhere else, tables must come from the shared helpers here).
_WEIGHT_AUTHORITY = True

import dataclasses
from functools import partial
from typing import Any, Callable, NamedTuple, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bluefog_tpu import config as _config
from bluefog_tpu.compressor import _resolve_k
from bluefog_tpu.optim import fusion as _fusion
from bluefog_tpu.parallel import collectives as C
from bluefog_tpu.topology.spec import DynamicTopology, Topology

CommSpec = Union[Topology, DynamicTopology]

__all__ = [
    "GuardConfig",
    "HealthConfig",
    "HealthVector",
    "MixCompressConfig",
    "MixState",
    "MoEConfig",
    "build_train_step",
    "comm_weight_inputs",
    "push_sum_weights",
    "rank_major",
    "rank_major_init",
    "rank_spec_tree",
    "optax_state_specs",
    "consensus_distance",
]


@dataclasses.dataclass(frozen=True)
class GuardConfig:
    """Fault-tolerance policy for :func:`build_train_step`.

    Only the PRESENCE of a GuardConfig changes the compiled program (the
    non-finite skip guard + skip-flag output + traced combine weights);
    the fields below are host-side policy consumed by
    :func:`bluefog_tpu.resilience.run_resilient`:

    * ``max_consecutive_bad`` — K: after this many consecutive steps
      with a live-rank skip, the runner escalates — IF some rank was
      bad for the whole window it is declared dead, the topology heals,
      and the state rolls back to the last good checkpoint (an
      unattributable window is noted and training continues: the skip
      guard already contained it).
    * ``backoff_base`` / ``backoff_factor`` / ``max_backoff`` — the
      exponential backoff (seconds) slept before resuming after each
      rollback: ``min(base * factor**i, max_backoff)``.
    * ``max_rollbacks`` — give up (raise) after this many rollbacks.
    """

    max_consecutive_bad: int = 3
    backoff_base: float = 0.5
    backoff_factor: float = 2.0
    max_backoff: float = 30.0
    max_rollbacks: int = 8


@dataclasses.dataclass(frozen=True)
class HealthConfig:
    """In-graph training-health instrumentation policy for
    :func:`build_train_step`.

    Only the PRESENCE of a HealthConfig changes the compiled program:
    the step additionally emits a :class:`HealthVector` — a small,
    FIXED-SHAPE bundle of per-rank health scalars computed from tensors
    the step already materializes.  It is shape-stable across every
    fault pattern (faults are traced inputs, same discipline as
    :class:`GuardConfig` — zero recompiles, asserted via jit cache
    sizes in tests/test_fleet.py), and with ``health=None`` (the
    default) the built step is bit-identical to one built without the
    feature.

    * ``consensus`` — include the consensus distance
      ``‖x_i − Σ_j w_ij x_j‖`` (the rank's pre-combine state vs the
      neighbor combine's output, which the exchange materializes
      anyway).  ``False`` reports 0.0 there and skips the reduction.
    """

    consensus: bool = True


class HealthVector(NamedTuple):
    """Per-rank in-graph health scalars a train step emits under
    ``health=HealthConfig(...)`` — rank-major ``[n]`` float32 vectors
    (inside ``shard_map`` each field is the rank's scalar):

    * ``loss`` — the rank's step loss (duplicated from the step output
      so the vector is self-contained for gossip);
    * ``grad_norm`` — global L2 norm of the rank's LOCAL gradients
      (before any cross-rank reduction; model-parallel leaves
      contribute their shard);
    * ``update_norm`` — global L2 norm of the optax update;
    * ``skipped`` — the guard's skip flag under ``guard=``; without a
      guard, the same in-graph isfinite reduce as a *would-skip* bit
      (reported, not acted on);
    * ``consensus`` — ``‖x_i − Σ_j w_ij x_j‖`` over the rank's local
      parameter shard (0.0 when no neighbor combine ran this step:
      off-cycle steps under ``num_steps_per_communication``, or comm
      modes without a neighbor exchange).

    Being a NamedTuple it is a pytree: feed it straight to host-side
    consumers (``bluefog_tpu.observe.fleet``) or stack fields for
    gossip.
    """

    loss: Any
    grad_norm: Any
    update_norm: Any
    skipped: Any
    consensus: Any


@dataclasses.dataclass(frozen=True)
class MixCompressConfig:
    """Error-feedback compressed parameter mixing policy for
    :func:`build_train_step` (``compress="topk"`` is shorthand for the
    defaults here, with ``BLUEFOG_MIX_COMPRESS_RATIO`` consulted).

    The cta/atc combine's wire payload becomes
    ``compress(x − ref + e)``: a per-bucket top-k-by-magnitude delta
    against the reference copy of the last-exchanged state, with the
    residual accumulating into the per-rank error-feedback state ``e``
    and receivers reconstructing ``ref + delta``
    (:func:`bluefog_tpu.parallel.collectives.mix_compress_exchange`).

    * ``ratio`` — kept fraction of each bucket's elements, in (0, 1).
      This is the BUILD-TIME ratio: it fixes the static per-bucket k
      (``compressor._resolve_k``) and therefore the wire shapes.  The
      LIVE ratio is ``MixState.ratio`` — traced data the control plane
      tightens online (``k_live <= k``) with zero recompiles.  A value
      >= 1.0 means "keep everything" and builds the ordinary
      uncompressed exchange (bit-identical by construction).
    * ``values`` — wire encoding of the kept values: ``"int8"``
      (absmax per bucket, round-to-nearest — composes the existing
      int8 stage on top of the sparsity), ``"int8_sr"`` (stochastic
      rounding, per-step/per-rank/per-bucket PRNG folding), or
      ``"none"`` (f32 values).
    * ``error_feedback`` — accumulate the compression residual into
      ``e`` (the construction that keeps the mixing recursion
      contractive).  ``False`` drops the residual — the ablation arm of
      benchmarks/wire_quant_consensus.py's ratio sweep, not a mode to
      train with.
    """

    ratio: float = 0.25
    values: str = "int8"
    error_feedback: bool = True


class MixState(NamedTuple):
    """Per-rank error-feedback mixing state (rank-major pytree data,
    carried as the second element of the step's ``opt_state`` —
    ``(base_opt_state, MixState)``, the same convention as push_sum's
    weight).  Ordinary traced data: checkpoints, healing rollbacks, and
    elastic swaps move it with the rest of the state, nothing
    recompiles.  Build with ``train_step.init_mix_state(params)``.

    * ``ratio`` — ``[n]`` f32, each rank's LIVE compression ratio (the
      control plane's online knob; starts at the build ratio);
    * ``err`` — per compressible bucket, ``[n, numel]`` f32
      error-feedback accumulators;
    * ``ref`` — per compressible bucket, ``[n, R, numel]`` f32: the
      sender-side reference copies, one row per schedule round (a
      rotating schedule pairs different partners per round, so each
      round integrates its own delta stream);
    * ``mirror`` — per compressible bucket, ``[n, G, numel]`` f32: the
      receiver-side mirrors of each in-edge's sender state
      (``G = sum of mix_mirror_slots(spec) over rounds``)."""

    ratio: Any
    err: Any
    ref: Any
    mirror: Any


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    """Expert-sharded MoE policy for :func:`build_train_step`: which
    parameter leaves are EXPERT-LOCAL and therefore excluded from the
    neighbor mixing epilogue.  Everything else — router, embeddings,
    dense trunk — keeps flowing through the ordinary cta/atc combine
    unchanged, so guard/health/compression compose without new code
    paths; the expert all-to-all itself lives inside ``loss_fn``
    (:mod:`bluefog_tpu.moe`), not in the builder.

    * ``n_experts`` — expert count (each rank hosts replica
      ``rank % n_experts``; see ``moe.dispatch.expert_owner``);
    * ``capacity`` — per-destination shard depth of the dispatch wire
      (``moe.layer.default_capacity`` derives one from the
      ``BLUEFOG_MOE_CAPACITY_FACTOR`` knob);
    * ``expert_path_tokens`` — a param leaf whose tree path contains
      any of these substrings is expert-local (matched against
      ``jax.tree_util.keystr``; the default matches the ``"expert"``
      subtree of ``moe.layer.init_moe_params``).
    """

    n_experts: int
    capacity: int
    expert_path_tokens: Tuple[str, ...] = ("expert",)

    def __post_init__(self):
        if self.n_experts < 1 or self.capacity < 1:
            raise ValueError(
                f"MoEConfig needs n_experts >= 1 and capacity >= 1, "
                f"got {self.n_experts} / {self.capacity}")
        if not self.expert_path_tokens:
            raise ValueError("expert_path_tokens must be non-empty — "
                             "an MoE step with no local leaves is just "
                             "a dense step")


def _moe_shared_mask(tree, moe: "MoEConfig"):
    """Per-leaf booleans in ``jax.tree.leaves`` order: True = shared
    (mixed by the epilogue), False = expert-local (never on the mixing
    wire).  Path-based so it works on any pytree shape at trace time."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [not any(tok in jax.tree_util.keystr(path)
                    for tok in moe.expert_path_tokens)
            for path, _ in flat]


def _tree_sq_sum(tree) -> jax.Array:
    """f32 sum of squares over every inexact leaf (0.0 for none)."""
    acc = jnp.zeros((), jnp.float32)
    for leaf in jax.tree.leaves(tree):
        leaf = jnp.asarray(leaf)
        if jnp.issubdtype(leaf.dtype, jnp.inexact):
            acc = acc + jnp.sum(jnp.square(leaf.astype(jnp.float32)))
    return acc


def _tree_distance(a, b) -> jax.Array:
    """f32 L2 distance between two structurally-identical trees
    (inexact leaves only) — the in-graph consensus-distance kernel."""
    acc = jnp.zeros((), jnp.float32)
    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        la = jnp.asarray(la)
        if jnp.issubdtype(la.dtype, jnp.inexact):
            d = la.astype(jnp.float32) - jnp.asarray(lb).astype(jnp.float32)
            acc = acc + jnp.sum(jnp.square(d))
    return jnp.sqrt(acc)


def comm_weight_inputs(specs: Sequence[CommSpec]) -> tuple:
    """The combine weights of a topology/schedule as TRACED-OPERAND data:
    one ``(class_weights [n_classes, n], self_weights [n])`` pair per
    round, the pytree a guarded train step takes as its ``comm_weights``
    argument.  Healing a topology (``resilience.healing``) produces a
    pytree of the SAME shapes over the same edge structure, so swapping
    weights never recompiles — the shape-stability contract of the
    resilience layer."""
    return tuple(
        (C.class_recv_weights(s), C.self_weight_vector(s)) for s in specs)


def _all_finite(loss: jax.Array, updates: Any) -> jax.Array:
    """Scalar health bit: loss and every inexact update leaf finite —
    the in-graph ``jnp.isfinite`` reduce the failure detector and the
    skip guard share."""
    ok = jnp.all(jnp.isfinite(loss))
    for leaf in jax.tree.leaves(updates):
        if jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.inexact):
            ok = ok & jnp.all(jnp.isfinite(leaf))
    return ok


def _grouped_sq_sum(leaves, groups) -> jax.Array:
    """f32 sum of squares over inexact leaves, accumulated as
    per-bucket partials in plan order — the epilogue pipeline's
    incremental form of :func:`_tree_sq_sum`.  Groups partition the
    leaves in tree order, so the accumulation association is identical
    to the flat walk (bitwise-equal totals)."""
    acc = jnp.zeros((), jnp.float32)
    for g in groups:
        for i in g:
            leaf = jnp.asarray(leaves[i])
            if jnp.issubdtype(leaf.dtype, jnp.inexact):
                acc = acc + jnp.sum(jnp.square(leaf.astype(jnp.float32)))
    return acc


def _grouped_all_finite(loss: jax.Array, upd_leaves, groups) -> jax.Array:
    """The guard's isfinite reduce as per-bucket partials combined at
    the end (boolean AND is associative — same flag as
    :func:`_all_finite`), so the reduce fuses into the same per-bucket
    pass as the norms instead of a separate full-tree walk."""
    ok = jnp.all(jnp.isfinite(loss))
    for g in groups:
        part = jnp.bool_(True)
        for i in g:
            leaf = jnp.asarray(upd_leaves[i])
            if jnp.issubdtype(leaf.dtype, jnp.inexact):
                part = part & jnp.all(jnp.isfinite(leaf))
        ok = ok & part
    return ok


def _bucket_cons_sq(pre_buf: jax.Array, out_buf: jax.Array) -> jax.Array:
    """Squared consensus-distance partial of one bucket, from the
    exchange's own pre/post buffers — the tensors the combine already
    materializes, so no second tree walk and no re-mix survives in the
    HLO."""
    d = pre_buf.astype(jnp.float32) - out_buf.astype(jnp.float32)
    return jnp.sum(jnp.square(d))


def _make_health_vector(loss, grad_sq, updates, consensus,
                        skipped=None) -> "HealthVector":
    """The per-rank HealthVector (traced scalars), shared by the
    guarded and unguarded builders so the field definitions cannot
    drift — ``skipped`` defaults to the same in-graph isfinite reduce
    the guard uses, reported as a would-skip bit."""
    if skipped is None:
        ok = _all_finite(loss, updates)
        skipped = jnp.where(ok, jnp.float32(0), jnp.float32(1))
    return HealthVector(
        loss=jnp.asarray(loss, jnp.float32),
        grad_norm=jnp.sqrt(grad_sq),
        update_norm=jnp.sqrt(_tree_sq_sum(updates)),
        skipped=jnp.asarray(skipped, jnp.float32),
        consensus=jnp.asarray(consensus, jnp.float32))


def _loss_and_grads(loss_fn, has_aux, sp_axis, pp_axis, param_specs,
                    params, aux, batch):
    """Forward+backward with the cross-axis reductions every builder
    shares: sp shards pmean grads/loss (params replicated over sp, each
    shard saw a different sequence slice); pp psums the last-stage-
    masked loss and restores pp-replicated leaves' gradients (the
    layer stacks sharded over pp got exact stage-local gradients
    through the reversed ppermutes — no reduction for those)."""
    if has_aux:
        (loss, new_aux), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, aux, batch)
    else:
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        new_aux = aux
    if sp_axis is not None:
        grads = lax.pmean(grads, sp_axis)
        loss = lax.pmean(loss, sp_axis)
    if pp_axis is not None:
        loss = lax.psum(loss, pp_axis)

        def _pp_reduce(g, spec):
            names = set()
            for el in spec:
                if isinstance(el, tuple):
                    names.update(el)
                elif el is not None:
                    names.add(el)
            return g if pp_axis in names else lax.psum(g, pp_axis)

        grads = jax.tree.map(_pp_reduce, grads, param_specs)
    return loss, grads, new_aux


def _weighted_combine_fn(spec: CommSpec, axis_name: str,
                         compress: Optional[str],
                         n_buckets: Optional[int],
                         hierarchical_local_size: Optional[int] = None,
                         ) -> Callable:
    """Combine branch ``fn(tree, key, (class_w, self_w))`` with the
    weights as traced operands — ``spec`` contributes only the edge
    structure (same design as windows.py's put/update kernels).  With
    ``n_buckets`` the bucketed overlap packing is applied around the
    weighted combine.  Under ``hierarchical_local_size`` the spec and
    the weight tables are MACHINE-level and the exchange is the
    two-level combine (compression on the DCN leg only)."""
    wire = compress == "int8_sr"
    wire_compress = "int8" if wire else compress
    hls = hierarchical_local_size

    def one(p, key, cw, sw):
        if hls is not None:
            return C.hierarchical_neighbor_allreduce(
                p, spec, hls, axis_name, compress=wire_compress,
                wire_key=key, class_weights=cw, self_weights=sw)
        return C.neighbor_allreduce(
            p, spec, axis_name, compress=wire_compress, wire_key=key,
            class_weights=cw, self_weights=sw)

    def fn(tree, key, w):
        cw, sw = w
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        if not leaves:
            return tree
        if n_buckets is None:
            outs = [
                one(p, (jax.random.fold_in(key, i) if wire else None),
                    cw, sw)
                for i, p in enumerate(leaves)
            ]
            return jax.tree_util.tree_unflatten(treedef, outs)
        groups = _bucket_groups(leaves, n_buckets)
        buffers = [_pack_bucket(leaves, g) for g in groups]
        combined = C.neighbor_allreduce_buckets(
            buffers, spec, axis_name, compress=wire_compress,
            wire_key=key if wire else None,
            hierarchical_local_size=hls,
            class_weights=cw, self_weights=sw)
        outs = [None] * len(leaves)
        for g, buf in zip(groups, combined):
            _unpack_bucket(buf, leaves, g, outs)
        return jax.tree_util.tree_unflatten(treedef, outs)

    return fn


def rank_major(tree, mesh: Mesh, axis_name: str = "bf", specs=None):
    """Stack ``n`` copies of every leaf along a new leading rank axis and
    shard it over ``axis_name`` — the initial state of decentralized
    training where every rank starts from the same point (the reference
    gets this from broadcast_parameters, torch/utility.py:26).
    ``specs``: optional PartitionSpec tree (leading rank axis included)
    for model-parallel leaves; default rank-sharded / replicated."""
    n = mesh.shape[axis_name]
    if specs is None:
        specs = jax.tree.map(lambda _: P(axis_name), tree)

    def stack(leaf, spec):
        leaf = jnp.asarray(leaf)
        return jax.device_put(
            jnp.broadcast_to(leaf[None], (n,) + leaf.shape),
            NamedSharding(mesh, spec))

    return jax.tree.map(stack, tree, specs)


def rank_major_init(init_fn: Callable[[], Any], mesh: Mesh,
                    axis_name: str = "bf", specs=None):
    """Build rank-major state directly sharded over the mesh: ``init_fn()``
    is traced once and compiled with rank-sharded outputs, so no device
    ever materializes the full unsharded ``[n, ...]`` stack — required at
    LLM scale where a single-device staging copy would not fit HBM.
    ``specs``: optional PartitionSpec tree for model-parallel leaves."""
    n = mesh.shape[axis_name]

    def build():
        tree = init_fn()
        return jax.tree.map(
            lambda leaf: jnp.broadcast_to(leaf[None], (n,) + leaf.shape),
            tree)

    shapes = jax.eval_shape(build)
    if specs is None:
        specs = jax.tree.map(lambda _: P(axis_name), shapes)
    out_shardings = jax.tree.map(
        lambda _, s: NamedSharding(mesh, s), shapes, specs)
    return jax.jit(build, out_shardings=out_shardings)()


def optax_state_specs(optimizer: optax.GradientTransformation,
                      params_shapes, param_specs,
                      axis_name: str = "bf"):
    """PartitionSpec tree for an optax state: any sub-tree structurally
    identical to the param tree (momentum, Adam moments, ...) inherits
    ``param_specs``; everything else (step counters, hyperparams) is
    rank-replicated scalars sharded only over the rank axis."""
    state_shapes = jax.eval_shape(optimizer.init, params_shapes)
    params_treedef = jax.tree.structure(params_shapes)
    default = P(axis_name)

    def match_specs(node):
        """param_specs, but leaves whose SHAPE differs from the matching
        param fall back to the default — factored optimizers (adafactor)
        keep param-structured subtrees with rank-reduced leaves, and a
        model-parallel spec longer than the leaf's rank would fail at
        device_put.  The fallback is only sound when the param itself is
        rank-sharded: a factored moment of a MODEL-PARALLEL param (e.g. a
        tp-sharded kernel's row statistics) would be replicated while the
        per-shard gradient is sliced, mismatching inside
        ``optimizer.update`` at trace time — reject that combination up
        front with a fix-it message instead."""

        def pick(st, ps, spec):
            if tuple(st.shape) == tuple(ps.shape):
                return spec
            model_axes = [ax for el in spec
                          for ax in (el if isinstance(el, tuple) else (el,))
                          if ax is not None and ax != axis_name]
            if model_axes:
                raise ValueError(
                    f"optimizer state leaf of shape {tuple(st.shape)} is "
                    f"shape-reduced relative to its param "
                    f"{tuple(ps.shape)} whose spec {spec} is model-"
                    f"parallel over {model_axes} — factored optimizers "
                    "(e.g. adafactor) do not compose with model-parallel "
                    "param shardings here; pass an explicit "
                    "opt_state_specs tree that shards the factored "
                    "moments to match, or use a non-factored optimizer")
            return default

        return jax.tree.map(pick, node, params_shapes, param_specs)

    def assign(node):
        try:
            matches = jax.tree.structure(node) == params_treedef
        except Exception:
            matches = False
        if matches:
            return match_specs(node)
        if isinstance(node, tuple) and hasattr(node, "_fields"):
            return type(node)(*[assign(c) for c in node])
        if isinstance(node, tuple):
            return tuple(assign(c) for c in node)
        if isinstance(node, list):
            return [assign(c) for c in node]
        if isinstance(node, dict):
            return {k: assign(v) for k, v in node.items()}
        return default

    return assign(state_shapes)


def rank_spec_tree(tree, axis_name: str = "bf"):
    """PartitionSpec tree: leading rank axis on every leaf."""
    return jax.tree.map(lambda _: P(axis_name), tree)


def consensus_distance(params) -> jax.Array:
    """Mean squared distance of each rank's parameters from the rank-mean —
    the standard measure of decentralized disagreement.  ``params`` is
    rank-major."""
    leaves = jax.tree.leaves(params)
    total = 0.0
    count = 0
    for leaf in leaves:
        mean = jnp.mean(leaf, axis=0, keepdims=True)
        total = total + jnp.sum((leaf - mean) ** 2)
        count += leaf.size
    return total / count


def push_sum_weights(mesh: Mesh, axis_name: str = "bf") -> jax.Array:
    """Rank-major push-sum weight vector (init 1 per rank) — pair it with
    the base optimizer state for ``comm_mode='push_sum'``:
    ``opt_state = (base_opt_state, push_sum_weights(mesh))``."""
    n = mesh.shape[axis_name]
    return jax.device_put(jnp.ones((n,), jnp.float32),
                          NamedSharding(mesh, P(axis_name)))


def _bucket_groups(leaves, n_buckets: int):
    """Trace-time size-balanced bucket assignment over per-shard leaves —
    the SAME grouping walk as the eager wrappers' fusion planner
    (optim.fusion.plan_groups), thresholded at ceil(total/K) so the
    buckets are size-balanced.  Dtype boundaries only ever increase the
    count; leaf granularity bounds it from above (a single dominant
    leaf — one stacked scan_layers kernel, the embed table — is never
    split, so such trees get the best bucket count achievable at leaf
    granularity, possibly < K; see fusion.size_balanced_threshold)."""
    rows = _fusion.bucket_signature(leaves)
    threshold = _fusion.size_balanced_threshold(rows, n_buckets)
    return _fusion.plan_groups(rows, threshold)


def _pack_bucket(leaves, group):
    """Concatenate a bucket's leaves into one flat per-shard buffer (a
    single-leaf bucket keeps its shape: no reshape traffic, and compress
    stays per-tensor for it)."""
    if len(group) == 1:
        return leaves[group[0]]
    return jnp.concatenate(
        [jnp.reshape(leaves[i], (-1,)) for i in group])


def _unpack_bucket(buf, leaves, group, outs):
    """Slice a combined bucket buffer back into ``outs`` at the bucket's
    leaf indices (shapes/dtypes from the uncombined ``leaves``)."""
    if len(group) == 1:
        outs[group[0]] = buf
        return
    off = 0
    for i in group:
        k = leaves[i].size
        outs[i] = jnp.reshape(buf[off:off + k], leaves[i].shape)
        off += k


def _bucketed_combine_fn(spec: CommSpec, axis_name: str,
                         hierarchical_local_size: Optional[int],
                         compress: Optional[str],
                         n_buckets: int) -> Callable:
    """Bucketed combine branch ``fn(tree, key)`` (CTA): the param tree is
    packed into K size-balanced buckets and each bucket issues its own
    neighbor combine, in tree order.  Under CTA the forward consumes the
    combined params bucket-by-bucket (tree order IS layer order for the
    standard model trees), so forward compute that only needs early
    buckets is dataflow-independent of late buckets' transfers — exactly
    the freedom the latency-hiding scheduler needs to overlap them."""
    wire = compress == "int8_sr"
    wire_compress = "int8" if wire else compress

    def fn(tree, key):
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        if not leaves:
            return tree
        groups = _bucket_groups(leaves, n_buckets)
        buffers = [_pack_bucket(leaves, g) for g in groups]
        combined = C.neighbor_allreduce_buckets(
            buffers, spec, axis_name, compress=wire_compress,
            wire_key=key if wire else None,
            hierarchical_local_size=hierarchical_local_size)
        outs = [None] * len(leaves)
        for g, buf in zip(groups, combined):
            _unpack_bucket(buf, leaves, g, outs)
        return jax.tree_util.tree_unflatten(treedef, outs)

    return fn


def _bucketed_apply_combine_fn(spec: CommSpec, axis_name: str,
                               hierarchical_local_size: Optional[int],
                               compress: Optional[str],
                               n_buckets: int) -> Callable:
    """Bucketed ATC branch ``fn((params, updates), key) -> params``:
    bucket *i*'s optax update is applied and its neighbor combine issued
    BEFORE bucket *i+1*'s update is applied — the jitted counterpart of
    the reference's per-parameter hooks that enqueue communication while
    the framework keeps computing (reference optimizers.py:485-841).
    Bucket *i+1*'s apply arithmetic is dataflow-independent of bucket
    *i*'s in-flight collective-permute, so the latency-hiding scheduler
    can place it inside the start->done window."""
    wire = compress == "int8_sr"
    wire_compress = "int8" if wire else compress

    def fn(operand, key):
        params, updates = operand
        leaves, treedef = jax.tree_util.tree_flatten(params)
        upd_leaves = jax.tree_util.tree_flatten(updates)[0]
        if not leaves:
            return params
        groups = _bucket_groups(leaves, n_buckets)
        outs = [None] * len(leaves)
        for bi, g in enumerate(groups):
            fresh = list(leaves)
            for i in g:
                fresh[i] = optax.apply_updates(leaves[i], upd_leaves[i])
            buf = _pack_bucket(fresh, g)
            wk = jax.random.fold_in(key, bi) if wire else None
            if hierarchical_local_size is not None:
                out = C.hierarchical_neighbor_allreduce(
                    buf, spec, hierarchical_local_size, axis_name,
                    compress=wire_compress, wire_key=wk)
            else:
                out = C.neighbor_allreduce(
                    buf, spec, axis_name, compress=wire_compress,
                    wire_key=wk)
            _unpack_bucket(out, fresh, g, outs)
        return jax.tree_util.tree_unflatten(treedef, outs)

    return fn


def _combine_fn(spec: CommSpec, axis_name: str,
                hierarchical_local_size: Optional[int],
                compress: Optional[str] = None) -> Callable:
    """Combine branch ``fn(tree, key)``; ``key`` feeds the stochastic
    wire rounder under ``compress='int8_sr'`` and is ignored (then DCE'd
    by XLA) everywhere else."""
    if hierarchical_local_size is not None:
        wire = compress == "int8_sr"
        wire_compress = "int8" if wire else compress

        def hier_fn(tree, key):
            leaves, treedef = jax.tree_util.tree_flatten(tree)
            outs = [
                C.hierarchical_neighbor_allreduce(
                    p, spec, hierarchical_local_size, axis_name,
                    compress=wire_compress,
                    wire_key=(jax.random.fold_in(key, i) if wire
                              else None))
                for i, p in enumerate(leaves)
            ]
            return jax.tree_util.tree_unflatten(treedef, outs)
        return hier_fn
    if compress == "int8_sr":
        def fn(tree, key):
            leaves, treedef = jax.tree_util.tree_flatten(tree)
            outs = [
                C.neighbor_allreduce(
                    p, spec, axis_name, compress="int8",
                    wire_key=jax.random.fold_in(key, i))
                for i, p in enumerate(leaves)
            ]
            return jax.tree_util.tree_unflatten(treedef, outs)
        return fn
    return lambda tree, key: jax.tree.map(
        lambda p: C.neighbor_allreduce(p, spec, axis_name,
                                       compress=compress), tree)


def _observed_step(step_fn: Callable, labels: dict,
                   edge_traffic: Optional[tuple] = None) -> Callable:
    """Host-side observability wrapper for a built train step: each
    dispatch increments ``bf_train_steps_total{comm_mode,overlap,
    guarded}`` and runs inside a ``train_step`` span on the ``train``
    track.  Everything happens OUTSIDE the traced program — the wrapper
    calls the same jitted executable, so jit cache sizes and step
    outputs are bit-identical with ``BLUEFOG_OBSERVE`` on or off
    (asserted in tests/test_observe.py).  The span measures host
    dispatch (jax is async); sync before reading it as a step time.

    ``edge_traffic`` — ``(specs, step_argpos, k_comm, n_ranks,
    filtered, local_size)`` for the neighbor modes: per on-cycle
    dispatch, the round's edges each get the per-rank parameter payload
    added to ``bf_edge_bytes_total{src,dst}`` through
    ``observe.fleet.record_edge_traffic`` (logical bytes — wire
    compression is not folded in), the fleet-telemetry traffic account
    derived from the topology's shift classes.  ``filtered`` selects
    the weight-filtered push-sum edge set (``push_sum_mix`` only
    ppermutes nonzero-weight edges) instead of the declared one
    (``neighbor_allreduce`` moves bytes on every declared edge — its
    weights are traced operands).  Under a hierarchical exchange
    (``local_size`` set, ``specs`` machine-level) the two legs are
    billed SEPARATELY — the intra-machine ring edges as
    ``link="ici"`` and the expanded counterpart machine edges as
    ``link="dcn"`` — so ``PodSpec.from_telemetry`` can calibrate the
    inter-machine links without mistaking cheap ICI traffic for DCN
    load."""
    payload_cache: list = []
    pairs_cache: dict = {}

    def record_edges(args) -> None:
        specs, step_argpos, k_comm, n_ranks, filtered, local_size = \
            edge_traffic
        try:
            step_i = int(args[step_argpos])
        except (TypeError, ValueError, IndexError):
            return
        if step_i % k_comm != 0:
            return
        if not payload_cache:
            payload_cache.append(sum(
                int(getattr(leaf, "nbytes", 0))
                for leaf in jax.tree.leaves(args[0])) // max(n_ranks, 1))
        from bluefog_tpu.observe import fleet as _fleet

        si = step_i % len(specs)
        if local_size:
            pairs = pairs_cache.get(si)
            if pairs is None:
                L = int(local_size)
                dcn = [(ms * L + j, md * L + j)
                       for (ms, md) in _fleet.edge_list(specs[si])
                       for j in range(L)]
                ici = []
                for g in C.machine_groups(n_ranks, L):
                    if len(g) > 1:
                        ici.extend((g[k], g[(k + 1) % len(g)])
                                   for k in range(len(g)))
                pairs = pairs_cache[si] = (ici, dcn)
            ici, dcn = pairs
            if ici:
                _fleet.record_edge_traffic(specs[si], payload_cache[0],
                                           pairs=ici, link="ici")
            _fleet.record_edge_traffic(specs[si], payload_cache[0],
                                       pairs=dcn, link="dcn")
            return
        pairs = pairs_cache.get(si)
        if pairs is None:
            pairs = pairs_cache[si] = (
                _fleet.gossip_edge_list(specs[si]) if filtered
                else _fleet.edge_list(specs[si]))
        _fleet.record_edge_traffic(specs[si], payload_cache[0],
                                   pairs=pairs)

    def step(*args, **kwargs):
        from bluefog_tpu import observe

        tr = observe.publish_tracer()
        if tr is None:
            return step_fn(*args, **kwargs)
        observe.get_registry().counter(
            "bf_train_steps_total", "train-step dispatches",
            **labels).inc()
        if edge_traffic is not None:
            record_edges(args)
        with tr.span("train", "train_step"):
            return step_fn(*args, **kwargs)

    return step


def _build_fused_train_step(
    loss_fn: Callable,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    *,
    axis_name: str,
    comm_mode: str,
    specs: Sequence[CommSpec],
    k_comm: int,
    hierarchical_local_size: Optional[int],
    sp_axis: Optional[str],
    pp_axis: Optional[str],
    batch_specs: Any,
    param_specs: Any,
    opt_state_specs: Any,
    donate: bool,
    has_aux: bool,
    compress: Optional[str],
    n_buckets: Optional[int],
    guard: Optional[GuardConfig],
    health: Optional[HealthConfig],
    mix: Optional[MixCompressConfig] = None,
    moe: Optional[MoEConfig] = None,
) -> Callable:
    """The fused per-bucket epilogue pipeline — the default
    :func:`build_train_step` data plane (see its docstring for the
    user contract, and the module docstring for the design).

    One builder serves every feature combination: the param tree is
    planned into fusion buckets (``EpiloguePlan`` — one bucket per leaf
    on the plain path, size-balanced under ``overlap='bucketed'``) and
    each bucket runs its epilogue stages (quantize → exchange →
    dequantize → guard-select → health-norm → consensus) as one
    composed pass, for every comm mode including push_sum.  The
    guard's isfinite reduce and the health norms accumulate as
    per-bucket partials in plan order (bitwise-equal to the flat walk);
    the consensus distance reuses the exchange's own pre/post bucket
    buffers.  The cta/atc combine weights are TRACED OPERANDS in the
    guarded AND unguarded builds, so both share one association order
    (the pre-fusion uniform-weight static-CTA constant-fold caveat is
    gone) and topology healing swaps weight data without recompiling
    either variant."""
    guarded = guard is not None
    want_health = health is not None
    want_cons = want_health and health.consensus
    neighbor = comm_mode in ("cta", "atc") and bool(specs)
    # traced combine-weight operands for every neighbor exchange: flat
    # tables are rank-level, hierarchical tables are MACHINE-level (the
    # machine is the failure domain — healing/elastic swap the
    # inter-machine matrix as data); push_sum derives its
    # column-stochastic scales from the edge structure
    use_traced_w = neighbor
    wire = compress == "int8_sr"
    wire_compress = "int8" if wire else compress
    zero = lambda: jnp.zeros((), jnp.float32)
    # error-feedback compressed mixing: per-round sender refs +
    # per-in-edge receiver mirrors, laid out contiguously over the
    # schedule (round r's mirror rows live at [offset_r, offset_r+slots))
    mix_on = mix is not None
    mix_sr = mix_on and mix.values == "int8_sr"
    mix_slots = [C.mix_mirror_slots(s) for s in specs] if mix_on else []
    mix_offsets = list(np.cumsum([0] + mix_slots))
    stage_compress = compress if not mix_on else (
        "int8" if mix.values in ("int8", "int8_sr") else None)

    def _plan(leaves):
        return _fusion.EpiloguePlan.for_leaves(
            leaves, n_buckets, compress=stage_compress, guard=guarded,
            health=want_health, consensus=want_cons, mix=mix_on)

    def _bucket_exchange(pre, spec, key, b, w, mix_state, r_index, ci):
        """One bucket's exchange stage: the EF-compressed sparse wire
        for compressible buckets under a mix config (returning the
        advanced (ref, mirrors, err) slices), the ordinary dense
        exchange otherwise.  Returns (out, mix_update | None)."""
        cw, sw = w
        if mix_on and jnp.issubdtype(jnp.dtype(b.dtype), jnp.inexact):
            off = mix_offsets[r_index]
            rows = mix_slots[r_index]
            numel = int(np.prod(pre.shape))
            out, nr, nm, ne = C.mix_compress_exchange(
                pre, spec, axis_name,
                ref_row=mix_state.ref[ci][r_index],
                mirrors=mix_state.mirror[ci][off:off + rows],
                err=mix_state.err[ci],
                ratio=mix_state.ratio,
                k=_resolve_k(None, mix.ratio, numel),
                values=mix.values,
                error_feedback=mix.error_feedback,
                class_weights=cw, self_weights=sw,
                wire_key=(jax.random.fold_in(key, b.index)
                          if mix_sr else None),
                hierarchical_local_size=hierarchical_local_size)
            return out, (nr, nm, ne)
        if hierarchical_local_size is not None:
            out = C.hierarchical_neighbor_allreduce(
                pre, spec, hierarchical_local_size, axis_name,
                compress=wire_compress,
                wire_key=(jax.random.fold_in(key, b.index)
                          if wire else None),
                class_weights=cw, self_weights=sw)
        else:
            out = C.neighbor_allreduce(
                pre, spec, axis_name, compress=wire_compress,
                wire_key=(jax.random.fold_in(key, b.index)
                          if wire else None),
                class_weights=cw, self_weights=sw)
        return out, None

    def _advance_mix(mix_state, r_index, ci, upd, acc):
        """Fold one bucket's (ref, mirrors, err) advance into the
        accumulating (err, ref, mirror) lists."""
        nr, nm, ne = upd
        errs, refs, mirs = acc
        off = mix_offsets[r_index]
        rows = mix_slots[r_index]
        refs[ci] = refs[ci].at[r_index].set(nr)
        mirs[ci] = mirs[ci].at[off:off + rows].set(nm)
        errs[ci] = ne

    def _mix_result(mix_state, acc):
        if not mix_on:
            return mix_state
        errs, refs, mirs = acc
        return MixState(ratio=mix_state.ratio, err=tuple(errs),
                        ref=tuple(refs), mirror=tuple(mirs))

    def _fused_combine_branch(spec: CommSpec, r_index: int) -> Callable:
        """fn(tree, key, w, mix_state) -> (combined_tree, cons_sq,
        mix_state'): the per-bucket pipeline over an already-
        materialized param tree (cta pre-update; guarded/plain atc
        post-update)."""

        def fn(tree, key, w, mix_state):
            leaves, treedef = jax.tree_util.tree_flatten(tree)
            if not leaves:
                return tree, zero(), mix_state
            plan = _plan(leaves)
            outs = [None] * len(leaves)
            cons = zero()
            acc = ([list(mix_state.err), list(mix_state.ref),
                    list(mix_state.mirror)] if mix_on else None)
            ci = 0
            for b in plan.buckets:
                pre = _pack_bucket(leaves, list(b.leaves))
                out, upd = _bucket_exchange(pre, spec, key, b, w,
                                            mix_state, r_index, ci)
                if upd is not None:
                    _advance_mix(mix_state, r_index, ci, upd, acc)
                    ci += 1
                if want_cons and jnp.issubdtype(jnp.dtype(b.dtype),
                                                jnp.inexact):
                    cons = cons + _bucket_cons_sq(pre, out)
                _unpack_bucket(out, leaves, list(b.leaves), outs)
            return (jax.tree_util.tree_unflatten(treedef, outs), cons,
                    _mix_result(mix_state, acc))

        return fn

    def _fused_apply_combine_branch(spec: CommSpec,
                                    r_index: int) -> Callable:
        """fn((params, updates), key, w, mix_state) -> (params,
        cons_sq, mix_state'): the unguarded ATC pipeline — bucket *i*'s
        optax apply feeds its own exchange before bucket *i+1*'s apply,
        and the consensus partial comes from the bucket's applied/mixed
        buffers (the pre-fusion path re-applied the full update tree
        just to measure it)."""

        def fn(operand, key, w, mix_state):
            params, updates = operand
            leaves, treedef = jax.tree_util.tree_flatten(params)
            upd_leaves = jax.tree_util.tree_flatten(updates)[0]
            if not leaves:
                return params, zero(), mix_state
            plan = _plan(leaves)
            outs = [None] * len(leaves)
            cons = zero()
            acc = ([list(mix_state.err), list(mix_state.ref),
                    list(mix_state.mirror)] if mix_on else None)
            ci = 0
            for b in plan.buckets:
                g = list(b.leaves)
                fresh = list(leaves)
                for i in g:
                    fresh[i] = optax.apply_updates(leaves[i],
                                                   upd_leaves[i])
                pre = _pack_bucket(fresh, g)
                out, upd = _bucket_exchange(pre, spec, key, b, w,
                                            mix_state, r_index, ci)
                if upd is not None:
                    _advance_mix(mix_state, r_index, ci, upd, acc)
                    ci += 1
                if want_cons and jnp.issubdtype(jnp.dtype(b.dtype),
                                                jnp.inexact):
                    cons = cons + _bucket_cons_sq(pre, out)
                _unpack_bucket(out, fresh, g, outs)
            return (jax.tree_util.tree_unflatten(treedef, outs), cons,
                    _mix_result(mix_state, acc))

        return fn

    def _fused_push_sum_branch(spec: CommSpec) -> Callable:
        """fn((params, ps)) -> (debiased, mixed_ps, cons_sq): the
        push-sum pipeline — bias, mix, and de-bias run on bucket
        buffers (the extended payload [buckets ‖ ps] mixes as a unit,
        column-stochastic scales from the edge structure), with the
        consensus partial from the same pre/post buffers."""

        def fn(operand):
            params, ps = operand
            leaves, treedef = jax.tree_util.tree_flatten(params)
            if not leaves:
                return params, ps, zero()
            plan = _plan(leaves)
            bufs = [_pack_bucket(leaves, list(b.leaves))
                    for b in plan.buckets]
            # re-bias -> mix -> de-bias stays in f32 (see the unfused
            # combine_push_sum for the digraph-correctness rationale);
            # push_sum_mix takes any pytree, so the bucket-buffer list
            # mixes as one extended payload [buckets ‖ ps] — column-
            # stochastic mixing distributes over concatenation, each
            # bucket its own independent collective
            biased = [buf.astype(jnp.float32) * ps for buf in bufs]
            mixed, mixed_ps = C.push_sum_mix(biased, ps, spec,
                                             axis_name)
            outs = [None] * len(leaves)
            cons = zero()
            for b, pre, mix in zip(plan.buckets, bufs, mixed):
                deb = (mix / mixed_ps).astype(jnp.dtype(b.dtype))
                if want_cons and jnp.issubdtype(jnp.dtype(b.dtype),
                                                jnp.inexact):
                    cons = cons + _bucket_cons_sq(pre, deb)
                _unpack_bucket(deb, leaves, list(b.leaves), outs)
            return (jax.tree_util.tree_unflatten(treedef, outs),
                    mixed_ps, cons)

        return fn

    branches = [_fused_combine_branch(s, r)
                for r, s in enumerate(specs)] \
        if neighbor else []
    # the interleaved apply+exchange rides the BUCKETED unguarded atc
    # path only: on the plain path the whole-tree apply stays outside
    # the combine (and outside any lax.switch branch) so the healthy
    # arithmetic is bit-identical to the pre-fusion builder — an apply
    # moved inside a conditional invites a different mul+add
    # contraction (1-ulp) on some backends
    ac_branches = [_fused_apply_combine_branch(s, r)
                   for r, s in enumerate(specs)] \
        if (neighbor and comm_mode == "atc" and not guarded
            and n_buckets is not None and moe is None) else []
    ps_branches = [_fused_push_sum_branch(s) for s in specs] \
        if comm_mode == "push_sum" else []

    def fused_combine(params, step, comm_weights, mix_state):
        if not branches:
            return params, zero(), mix_state

        def run(operand):
            params, mix_state = operand
            key = jax.random.fold_in(jax.random.PRNGKey(0x51EED), step)
            if len(branches) == 1:
                return branches[0](params, key,
                                   comm_weights[0] if use_traced_w
                                   else (), mix_state)
            picked = [
                (lambda fn, i: lambda p, k, ws, m: fn(
                    p, k, ws[i] if use_traced_w else (), m))(fn, i)
                for i, fn in enumerate(branches)
            ]
            return lax.switch(step % len(branches), picked, params, key,
                              comm_weights, mix_state)

        if k_comm > 1:
            # lax.cond actually skips the collectives (and the epilogue
            # stages riding them) on off-cycle steps — the mix state
            # rides through untouched (no wire, no delta)
            return lax.cond(step % k_comm == 0, run,
                            lambda op: (op[0], zero(), op[1]),
                            (params, mix_state))
        return run((params, mix_state))

    if moe is not None:
        # Expert-sharded MoE: only the SHARED leaves ride the mixing
        # wire.  Wrapping here (a leaf LIST is itself a pytree, so the
        # branch machinery replans over it unchanged) covers every
        # fused_combine call site — cta, guarded atc, and the plain atc
        # fallback — with one partition; expert leaves pass through
        # untouched and never cost a byte of exchange.
        _dense_fused_combine = fused_combine

        def fused_combine(params, step, comm_weights, mix_state):
            leaves, treedef = jax.tree_util.tree_flatten(params)
            mask = _moe_shared_mask(params, moe)
            if not any(mask):
                raise ValueError(
                    f"MoEConfig.expert_path_tokens "
                    f"{moe.expert_path_tokens!r} match EVERY param "
                    "leaf — nothing left to mix, the fleet would "
                    "never reach consensus")
            shared = [l for l, m in zip(leaves, mask) if m]
            mixed, cons, mix_state = _dense_fused_combine(
                shared, step, comm_weights, mix_state)
            it = iter(mixed)
            out = [next(it) if m else l for l, m in zip(leaves, mask)]
            return (jax.tree_util.tree_unflatten(treedef, out), cons,
                    mix_state)

    def fused_apply_then_combine(params, updates, step, comm_weights,
                                 mix_state):
        if not ac_branches:
            return (optax.apply_updates(params, updates), zero(),
                    mix_state)

        def run(operand):
            params, updates, mix_state = operand
            key = jax.random.fold_in(jax.random.PRNGKey(0x51EED), step)
            if len(ac_branches) == 1:
                return ac_branches[0]((params, updates), key,
                                      comm_weights[0] if use_traced_w
                                      else (), mix_state)
            picked = [
                (lambda fn, i: lambda op, k, ws, m: fn(
                    op, k, ws[i] if use_traced_w else (), m))(fn, i)
                for i, fn in enumerate(ac_branches)
            ]
            return lax.switch(step % len(ac_branches), picked,
                              (params, updates), key, comm_weights,
                              mix_state)

        if k_comm > 1:
            # off-cycle steps still apply the optax update — only the
            # collectives (and their epilogue stages) are skipped
            return lax.cond(
                step % k_comm == 0, run,
                lambda op: (optax.apply_updates(op[0], op[1]), zero(),
                            op[2]),
                (params, updates, mix_state))
        return run((params, updates, mix_state))

    def fused_push_sum(params, ps, step):
        def run(operand):
            if len(ps_branches) == 1:
                return ps_branches[0](operand)
            return lax.switch(step % len(ps_branches), ps_branches,
                              operand)

        if k_comm > 1:
            return lax.cond(step % k_comm == 0, run,
                            lambda op: (op[0], op[1], zero()),
                            (params, ps))
        return run((params, ps))

    def per_rank_step(params, aux, opt_state, batch, step, comm_weights):
        mix_state = ()
        if mix_on:
            # the MixState rides opt_state as (base, MixState) — the
            # push_sum convention; the GUARD's pick below applies to
            # the base only (the exchange ran on the wire regardless of
            # a local skip, so ref/mirror/err must advance to stay
            # bitwise-consistent with what the neighbors received)
            opt_state, mix_state = opt_state
        loss, grads, new_aux = _loss_and_grads(
            loss_fn, has_aux, sp_axis, pp_axis, param_specs,
            params, aux, batch)
        groups = _plan(jax.tree.leaves(params)).groups \
            if (want_health or guarded) else None
        # local (pre-allreduce) gradient norm as per-bucket partials
        grad_sq = _grouped_sq_sum(jax.tree.leaves(grads), groups) \
            if want_health else None
        cons = zero()
        if comm_mode == "gradient_allreduce":
            # (guarded note: the allreduce mixes GRADIENTS, so one
            # rank's NaN reaches every rank — the guard skips globally;
            # the neighbor modes contain the blast radius)
            grads = jax.tree.map(
                lambda g: C.allreduce(g, axis_name, average=True), grads)
        if comm_mode == "push_sum":
            base_state, ps = opt_state
            params, ps, cons = fused_push_sum(params, ps, step)
            updates, base_state = optimizer.update(grads, base_state,
                                                   params)
            params = optax.apply_updates(params, updates)
            hv = _fused_health(loss, grad_sq, updates, groups, cons,
                               None) if want_health else None
            return params, new_aux, (base_state, ps), loss, None, hv
        if comm_mode == "cta":
            params, cons, mix_state = fused_combine(
                params, step, comm_weights, mix_state)
        updates, new_opt = optimizer.update(grads, opt_state, params)
        skipped = None
        if guarded:
            ok = _grouped_all_finite(
                loss, jax.tree_util.tree_flatten(updates)[0], groups)

            # elementwise select, NOT lax.cond — see the unfused
            # guarded builder for why (bit-identity + mul+add fusion)
            def pick(new, old):
                return jnp.where(ok, new, old)

            params = jax.tree.map(
                pick, optax.apply_updates(params, updates), params)
            new_aux = jax.tree.map(pick, new_aux, aux)
            new_opt = jax.tree.map(pick, new_opt, opt_state)
            if comm_mode == "atc":
                params, cons, mix_state = fused_combine(
                    params, step, comm_weights, mix_state)
            skipped = jnp.where(ok, jnp.int32(0), jnp.int32(1))
        else:
            if comm_mode == "atc" and ac_branches:
                params, cons, mix_state = fused_apply_then_combine(
                    params, updates, step, comm_weights, mix_state)
            else:
                params = optax.apply_updates(params, updates)
                if comm_mode == "atc":
                    params, cons, mix_state = fused_combine(
                        params, step, comm_weights, mix_state)
        if mix_on:
            new_opt = (new_opt, mix_state)
        hv = _fused_health(loss, grad_sq, updates, groups, cons,
                           skipped) if want_health else None
        return params, new_aux, new_opt, loss, skipped, hv

    def _fused_health(loss, grad_sq, updates, groups, cons_sq, skipped):
        upd_leaves = jax.tree_util.tree_flatten(updates)[0]
        if skipped is None:
            ok = _grouped_all_finite(loss, upd_leaves, groups)
            skipped = jnp.where(ok, jnp.float32(0), jnp.float32(1))
        return HealthVector(
            loss=jnp.asarray(loss, jnp.float32),
            grad_norm=jnp.sqrt(grad_sq),
            update_norm=jnp.sqrt(_grouped_sq_sum(upd_leaves, groups)),
            skipped=jnp.asarray(skipped, jnp.float32),
            consensus=jnp.sqrt(cons_sq))

    squeeze = lambda t: jax.tree.map(lambda x: x[0], t)
    expand = lambda t: jax.tree.map(lambda x: x[None], t)

    def wrapped(params, aux, opt_state, batch, step, comm_weights):
        params, aux, opt_state, loss, skipped, hv = per_rank_step(
            squeeze(params), squeeze(aux), squeeze(opt_state),
            squeeze(batch), step, comm_weights)
        outs = (expand(params), expand(aux), expand(opt_state),
                jnp.reshape(loss, (1,)))
        if guarded:
            outs = outs + (jnp.reshape(skipped, (1,)),)
        if want_health:
            outs = outs + (HealthVector(
                *[jnp.reshape(x, (1,)) for x in hv]),)
        return outs

    p_rank = P(axis_name)
    # MixState layout: dim 0 is ranks; the packed/flat axis (last)
    # shards over every OTHER mesh axis, matching the per-device bucket
    # shards the exchange packs (see init_mix_state / _local_shapes)
    _mix_rest = tuple(a for a in mesh.axis_names if a != axis_name)
    p_mix = MixState(
        ratio=p_rank,
        err=P(axis_name, _mix_rest or None),
        ref=P(axis_name, None, _mix_rest or None),
        mirror=P(axis_name, None, _mix_rest or None))
    if batch_specs is None:
        batch_specs = p_rank
    p_params = param_specs if param_specs is not None else p_rank
    p_opt = opt_state_specs if opt_state_specs is not None else p_rank
    if mix_on:
        # opt_state = (base, MixState), a per-FIELD pytree-prefix spec:
        # the ratio is one scalar per rank, but err/ref/mirror hold one
        # flat EF row per DEVICE — their packed axis shards over every
        # non-rank mesh axis so a tp slice sees exactly its own bucket
        # shards (P(axis_name) alone would hand each device the full
        # per-rank row, 4x the bucket under tp=4)
        p_opt = (p_opt, p_mix)
    # comm weights ride replicated (every rank reads the full tables)
    p_comm = tuple((P(), P()) for _ in specs) if use_traced_w else ()
    out_specs = (p_params, p_rank, p_opt, p_rank)
    if guarded:
        out_specs = out_specs + (p_rank,)
    if want_health:
        out_specs = out_specs + (p_rank,)  # spec prefix over HealthVector
    sm = jax.shard_map(
        wrapped,
        mesh=mesh,
        in_specs=(p_params, p_rank, p_opt, batch_specs, P(), p_comm),
        out_specs=out_specs,
        check_vma=False,
    )
    donate_argnums = (0, 1, 2) if donate else ()
    jitted = jax.jit(sm, donate_argnums=donate_argnums)
    default_w = comm_weight_inputs(specs) if use_traced_w else ()

    obs_labels = dict(
        comm_mode=comm_mode,
        overlap="bucketed" if n_buckets is not None else "none",
        guarded="true" if guarded else "false")
    needs_topo = comm_mode in ("cta", "atc", "push_sum")
    edge_traffic = (list(specs), 4 if has_aux else 3, k_comm,
                    int(mesh.shape[axis_name]),
                    comm_mode == "push_sum",
                    hierarchical_local_size if neighbor else None) \
        if (specs and needs_topo) else None

    stages = _fusion.epilogue_stages(
        compress=stage_compress, guard=guarded, health=want_health,
        consensus=want_cons, mix=mix_on)

    def _local_shapes(params):
        """Per-DEVICE leaf shapes exactly as the shard_map body sees
        them: the leading rank axis stripped, every other dim divided
        by the mesh axes its param spec shards over.  ``_plan`` buckets
        on these shapes inside the trace, so every MixState buffer must
        be sized by them too — under model parallelism (a
        ``param_specs`` tree naming other mesh axes) the EF state
        follows the SHARDS, one independent accumulator per device."""
        leaves = jax.tree.leaves(params)
        is_p = lambda s: s is None or isinstance(s, P)
        if param_specs is None:
            sp = [P(axis_name)] * len(leaves)
        elif is_p(param_specs):
            sp = [param_specs] * len(leaves)
        else:
            sp = jax.tree.leaves(param_specs, is_leaf=is_p)
        if len(sp) != len(leaves):
            raise ValueError(
                "compressed mixing needs param_specs to be None, one "
                "PartitionSpec, or a tree matching params exactly "
                f"(got {len(sp)} specs for {len(leaves)} leaves)")
        out = []
        for l, s in zip(leaves, sp):
            dims = list(np.shape(l))
            for i, names in enumerate(tuple(s or ())):
                if names is None:
                    continue
                for a in ((names,) if isinstance(names, str)
                          else tuple(names)):
                    dims[i] //= int(mesh.shape[a])
            out.append(jax.ShapeDtypeStruct(
                tuple(dims[1:]),
                getattr(l, "dtype", None) or jnp.asarray(l).dtype))
        return out

    def init_mix_state(params):
        """The MixState for rank-major ``params`` (attach it as
        ``opt_state = (base_opt_state, init_mix_state(params))``).

        ``ref``/``mirror`` start at each rank's OWN packed parameters:
        exact when every rank holds identical parameters at the start
        (the rank_major broadcast init — the normal case), so round
        one's wire already carries small deltas.  Ranks that start from
        DIVERGED states should zero ``ref``/``mirror`` instead (always
        bitwise-consistent, at the cost of sparse early rounds).
        Under a hierarchical exchange the same identical-init
        assumption makes the packed params equal the machine means.

        Built THROUGH a shard_map over the step's own mesh/specs, so
        the buffers are packed per device shard and land sharded as
        ``mix_state_specs`` — bitwise the layout the train step's
        exchange indexes into, whatever the model-parallel layout."""
        R = len(specs)
        G = int(sum(mix_slots))

        def body(p):
            leaves = [l[0] for l in jax.tree.leaves(p)]
            if moe is not None:
                # EF state exists only for leaves that ride the wire
                mask = _moe_shared_mask(p, moe)
                leaves = [l for l, m in zip(leaves, mask) if m]
            errs, refs, mirs = [], [], []
            for b in _plan(leaves).buckets:
                if not jnp.issubdtype(jnp.dtype(b.dtype), jnp.inexact):
                    continue
                flat = _pack_bucket(leaves, list(b.leaves)) \
                    .reshape(-1).astype(jnp.float32)
                errs.append(jnp.zeros((1, flat.size), jnp.float32))
                refs.append(jnp.broadcast_to(
                    flat[None, None, :], (1, R, flat.size)) + 0.0)
                mirs.append(jnp.broadcast_to(
                    flat[None, None, :], (1, G, flat.size)) + 0.0)
            return MixState(
                ratio=jnp.full((1,), jnp.float32(mix.ratio)),
                err=tuple(errs), ref=tuple(refs), mirror=tuple(mirs))

        return jax.jit(jax.shard_map(
            body, mesh=mesh, in_specs=(p_params,),
            out_specs=p_mix, check_vma=False))(params)

    def mix_wire_layout(params):
        """Per compressible bucket, the host-side wire facts the
        collectives contract audits against: ``(bucket_index, numel,
        k, wire_bytes)`` — ``wire_bytes`` being the single uint8
        payload each ppermute of that bucket moves per DCN pair.
        ``numel`` is the per-DEVICE packed size (model-parallel layouts
        exchange shards, so each tp slice moves its own wire)."""
        rows = []
        shapes = _local_shapes(params)
        if moe is not None:
            mask = _moe_shared_mask(params, moe)
            shapes = [s for s, m in zip(shapes, mask) if m]
        for b in _plan(shapes).buckets:
            if not jnp.issubdtype(jnp.dtype(b.dtype), jnp.inexact):
                continue
            numel = int(sum(
                int(np.prod(s.shape))
                for i, s in enumerate(shapes) if i in b.leaves))
            k = _resolve_k(None, mix.ratio, numel)
            rows.append(dict(bucket=b.index, numel=numel, k=k,
                             wire_bytes=C.mix_wire_bytes(
                                 numel, k, mix.values)))
        return tuple(rows)

    def set_mix_ratio(opt_state, ratio):
        """A new opt_state with every rank's LIVE compression ratio set
        to ``ratio`` — pure data (``k_live`` masking inside the traced
        program), so the swap never recompiles.  The control plane's
        sanctioned step-boundary producer
        (``topology.control.swap_mix_ratio``) feeds this."""
        base, ms = opt_state
        return (base, ms._replace(
            ratio=jnp.full_like(ms.ratio, jnp.float32(float(ratio)))))

    def _decorate(step_fn, adapt):
        # ``adapt`` maps the step's PUBLIC signature to the jitted
        # program's full argument tuple; .lower and .trace share it so
        # AOT compilation (benchmarks) and jaxpr inspection
        # (bluefog_tpu.analysis) see the identical program.
        step_fn.jitted = jitted
        step_fn.lower = lambda *args: jitted.lower(*adapt(*args))
        step_fn.trace = lambda *args: jitted.trace(*adapt(*args))
        step_fn.health_config = health
        step_fn.epilogue_stages = stages
        step_fn.has_aux = has_aux
        step_fn.hierarchical_local_size = \
            hierarchical_local_size if neighbor else None
        step_fn.mix_config = mix
        step_fn.moe_config = moe
        if mix_on:
            step_fn.init_mix_state = init_mix_state
            step_fn.mix_wire_layout = mix_wire_layout
            step_fn.set_mix_ratio = set_mix_ratio
            # pytree-prefix PartitionSpecs of the MixState (AOT callers
            # turn these into NamedShardings for abstract avals)
            step_fn.mix_state_specs = p_mix
        if guarded:
            step_fn.guard_config = guard
        if guarded or use_traced_w:
            step_fn.default_comm_weights = default_w
        return step_fn

    if guarded:
        if has_aux:
            def aux_step(params, aux, opt_state, batch, step,
                         comm_weights):
                return jitted(params, aux, opt_state, batch, step,
                              comm_weights)

            return _decorate(
                _observed_step(aux_step, obs_labels, edge_traffic),
                lambda params, aux, opt_state, batch, step,
                comm_weights: (params, aux, opt_state, batch, step,
                               comm_weights))

        if health is None:
            def no_aux_step(params, opt_state, batch, step,
                            comm_weights):
                params, _, opt_state, loss, skipped = jitted(
                    params, (), opt_state, batch, step, comm_weights)
                return params, opt_state, loss, skipped
        else:
            def no_aux_step(params, opt_state, batch, step,
                            comm_weights):
                params, _, opt_state, loss, skipped, hv = jitted(
                    params, (), opt_state, batch, step, comm_weights)
                return params, opt_state, loss, skipped, hv

        return _decorate(
            _observed_step(no_aux_step, obs_labels, edge_traffic),
            lambda params, opt_state, batch, step, comm_weights:
            (params, (), opt_state, batch, step, comm_weights))

    if has_aux:
        def aux_step(params, aux, opt_state, batch, step):
            return jitted(params, aux, opt_state, batch, step,
                          default_w)

        return _decorate(
            _observed_step(aux_step, obs_labels, edge_traffic),
            lambda params, aux, opt_state, batch, step:
            (params, aux, opt_state, batch, step, default_w))

    if health is None:
        def no_aux_step(params, opt_state, batch, step):
            params, _, opt_state, loss = jitted(
                params, (), opt_state, batch, step, default_w)
            return params, opt_state, loss
    else:
        def no_aux_step(params, opt_state, batch, step):
            params, _, opt_state, loss, hv = jitted(
                params, (), opt_state, batch, step, default_w)
            return params, opt_state, loss, hv

    return _decorate(
        _observed_step(no_aux_step, obs_labels, edge_traffic),
        lambda params, opt_state, batch, step:
        (params, (), opt_state, batch, step, default_w))


def build_train_step(
    loss_fn: Callable[[Any, Any], jax.Array],
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    *,
    axis_name: str = "bf",
    comm_mode: str = "cta",
    topology: Optional[CommSpec] = None,
    schedule: Optional[Sequence[CommSpec]] = None,
    num_steps_per_communication: int = 1,
    hierarchical_local_size: Optional[int] = None,
    hierarchical: Any = None,
    sp_axis: Optional[str] = None,
    pp_axis: Optional[str] = None,
    batch_specs: Any = None,
    param_specs: Any = None,
    opt_state_specs: Any = None,
    donate: bool = True,
    has_aux: bool = False,
    compress: Union[str, MixCompressConfig, None] = None,
    overlap: str = "none",
    overlap_buckets: int = 4,
    guard: Optional[GuardConfig] = None,
    health: Optional[HealthConfig] = None,
    moe: Optional[MoEConfig] = None,
) -> Callable:
    """Compile one decentralized SGD/optax step over ``mesh``.

    loss_fn(params, batch) -> scalar loss, evaluated per rank on its local
    shard (under ``shard_map``; it may use ``sp_axis`` collectives, e.g.
    ring attention).  With ``has_aux=True`` the signature becomes
    ``loss_fn(params, aux, batch) -> (loss, new_aux)`` for mutable model
    state (e.g. batch-norm statistics), and the returned step takes and
    returns the rank-major ``aux`` tree:
    ``train_step(params, aux, opt_state, batch, step)``.

    comm_mode:
      * ``"cta"``  — combine-then-adapt (reference _DistributedReduceOptimizer)
      * ``"atc"``  — adapt-then-combine (reference _DistributedAdaptThenCombine)
      * ``"gradient_allreduce"`` — global gradient averaging (reference
        _DistributedOptimizer)
      * ``"push_sum"`` — bias-corrected directed averaging (reference
        _DistributedPushSumOptimizer, optimizers.py:1026-1177): column-
        stochastic mix of the extended payload [params ‖ ps_weight], then
        de-bias by the mixed weight.  The step's ``opt_state`` must be
        ``(base_opt_state, push_sum_weights(mesh))``.  Only the topology's
        edge structure is used — combine weights are replaced by the
        uniform ``1/(out_degree+1)`` push scales (see
        ``collectives.push_sum_mix``); hierarchical_local_size is not
        supported in this mode.
      * ``"none"`` — no communication (pure local SGD)

    Exactly one of ``topology`` (static) or ``schedule`` (dynamic, indexed
    by ``step % len(schedule)`` via ``lax.switch``) for the neighbor modes.

    ``compress="int8"`` quantizes the cta/atc combine's wire payload
    (per-tensor absmax int8; see ``collectives.neighbor_allreduce``) —
    4x less ICI/DCN traffic at ~0.4% relative error per exchange.
    ``compress="int8_sr"`` is the same wire format with UNBIASED
    stochastic rounding (per-step, per-rank, per-leaf PRNG folding):
    round-to-nearest's deterministic snaps can accumulate into a
    consensus error floor in iterated averaging at pod rank counts,
    stochastic rounding's zero-mean noise averages out instead — the
    n=128 floor comparison is benchmarks/wire_quant_consensus.py.
    ``compress="bf16"`` rounds the wire payload to bfloat16 (2x less
    traffic for f32 params, self term stays full precision).

    ``compress="topk"`` (or an explicit :class:`MixCompressConfig`)
    is ERROR-FEEDBACK COMPRESSED MIXING — the sparsity rung below
    int8.  Each rank keeps a per-bucket reference copy of its
    last-exchanged state plus an error accumulator; the wire carries
    ``topk(x − ref + e)`` as a packed keep-mask + (int8-quantized)
    kept values (``collectives.mix_compress_exchange`` /
    ``mix_wire_bytes``), the residual folds into ``e``, and receivers
    reconstruct ``ref + delta`` so the mixing recursion stays
    contractive (consensus floor vs ratio: benchmarks/
    wire_quant_consensus.py's ratio sweep).  The step's ``opt_state``
    must then be ``(base_opt_state, train_step.init_mix_state(params))``
    — the ref/error state is ordinary rank-major pytree data, so
    checkpoints, healing rollbacks, and elastic swaps carry it with
    everything else.  k is FIXED at build time from the config ratio
    (static shapes — the zero-recompile contract); the LIVE ratio is
    ``MixState.ratio``, traced data the topology control plane
    tightens online under congestion (``topology.control.
    swap_mix_ratio`` → ``train_step.set_mix_ratio``) with zero
    recompiles.  ``ratio >= 1.0`` builds the ordinary uncompressed
    exchange (bit-identical by construction).  cta/atc only; under a
    hierarchical exchange the sparse wire rides the DCN leg only (the
    ICI machine reduce stays exact, ref/mirror state at machine-mean
    granularity).  Env defaults: ``BLUEFOG_MIX_COMPRESS`` /
    ``BLUEFOG_MIX_COMPRESS_RATIO`` (explicit arguments win).  Needs
    the fused epilogue pipeline (not available under
    ``BLUEFOG_FUSE_EPILOGUES=0``) and does not compose with the
    string wire modes (the int8 stage already rides the kept
    values).

    ``overlap="bucketed"`` (cta/atc only) is the overlap engine: the
    param tree is split into ``overlap_buckets`` size-balanced buckets
    (same trace-time planner as the eager wrappers' tensor fusion,
    ``optim.fusion``) and each bucket issues its OWN neighbor combine —
    for ATC, bucket *i*'s combine launches as soon as its optax update
    is applied, before bucket *i+1*'s update; for CTA, buckets combine
    in tree (= layer) order ahead of the forward that consumes them.
    Every bucket's collective is dataflow-independent of the other
    buckets' arithmetic, which is the program structure XLA's
    latency-hiding scheduler needs to run transfers concurrently with
    compute (the reference gets the same overlap from its background
    MPI thread + fusion buffers, operations.cc:943-1020); the HLO-level
    guarantee (>= K collective-permutes — leaf granularity permitting,
    see ``_bucket_groups`` — with compute scheduled between them) is
    regression-checked in tests/test_hlo_guarantees.py.
    Numerics match ``overlap="none"`` exactly except under
    ``compress="int8*"``, where the absmax scale becomes per-bucket.
    ``compress=`` and dynamic ``schedule=`` plumb through unchanged.

    ``guard=GuardConfig(...)`` compiles the RESILIENT variant of the
    step (the jitted half of ``bluefog_tpu.resilience``):

    * the optax apply is wrapped in a per-rank ``lax.cond`` on an
      in-graph ``jnp.isfinite`` health check over (loss, updates) — a
      rank whose step is non-finite SKIPS it (params, aux, and
      opt_state all keep their previous finite values) and contributes
      its pre-update params to the neighbor combine, so one poisoned
      rank never contaminates its neighbors; the returned per-rank
      ``skipped`` flags are the skip counter's per-step increments;
    * the cta/atc combine weights become a TRACED INPUT (the
      ``comm_weights`` pytree from :func:`comm_weight_inputs`, default
      exposed as ``train_step.default_comm_weights``): topology healing
      after a rank death swaps in new weight DATA over the same edge
      structure — shapes never change, nothing recompiles.

    With no faults present the guarded step's (params, opt_state,
    loss) are bit-identical to the unguarded step's.  Not supported
    with ``comm_mode='push_sum'`` (the (x, w) pair must mix as a unit).
    Under a hierarchical exchange the guard composes at MACHINE
    granularity: ``comm_weights`` are the machine-level tables and
    ``resilience.healing.healed_hierarchical_comm_weights`` collapses a
    rank-level dead mask to the machine failure domain.

    **Hierarchical exchange** — ``hierarchical=PodSpec(...)`` (or a
    plain int local size; equivalently ``hierarchical_local_size=``, or
    the ``BLUEFOG_HIER_LOCAL_SIZE`` env default) decomposes the cta/atc
    combine into ``W_dcn ⊗ exact-local-mean``: ONE exact intra-machine
    allreduce over the ICI submesh (``collectives.machine_groups``),
    then decentralized weighted mixing of the machine means over the
    (smaller) inter-machine schedule — ``topology=``/``schedule=`` are
    then MACHINE-level specs of size ``n_ranks / local_size`` (the
    hierarchical compiler emits them: ``topology.compiler.
    compile_topology(..., hierarchical=...)``).  ``compress=`` applies
    to the DCN leg only (the ICI reduce stays full precision), and the
    combine weights ride as traced MACHINE-level tables, so healing and
    elastic membership swap the inter-machine matrix as pure data —
    zero recompiles.  With ``local_size == 1`` the step is bitwise the
    flat exchange.

    ``health=HealthConfig(...)`` additionally emits a rank-major
    :class:`HealthVector` as the step's LAST output — loss, local grad
    norm, update norm, skip flag, and the consensus distance
    ``‖x_i − Σ_j w_ij x_j‖`` computed from tensors the neighbor
    exchange already materializes (both the plain and
    ``overlap="bucketed"`` paths).  The vector is fixed-shape — faults
    are inputs, nothing recompiles across fault patterns (same
    discipline as ``guard=``) — and ``health=None`` (default) leaves
    the step bit-identical to a pre-feature build.  Composes with
    ``guard=`` (``skipped`` then carries the guard's actual flags).

    **Fused epilogue pipeline** (default): every feature above is
    emitted as a per-bucket stage of ONE composed pass per fusion-plan
    bucket — quantize → exchange → dequantize → guard-select →
    health-norm — instead of separate full-tree walks around the
    exchange (see the module docstring).  All comm modes ride it,
    including ``push_sum`` (whose exchange now also accepts
    ``overlap="bucketed"``); the cta/atc combine weights are traced
    operands in BOTH the guarded and unguarded builds, so the two share
    one association order (guarded == unguarded bitwise on every
    topology, including uniform-weight static CTA) and healing swaps
    weight data without recompiling either.  Set
    ``BLUEFOG_FUSE_EPILOGUES=0`` to fall back to the pre-fusion
    builders (debugging escape hatch; also the golden reference of
    tests/test_epilogue.py's parity matrix).

    Returns ``train_step(params, opt_state, batch, step) ->
    (params, opt_state, loss)`` — all rank-major, jit-compiled with
    params/opt_state donated.  Under ``guard=`` the signature is
    ``train_step(params, opt_state, batch, step, comm_weights) ->
    (params, opt_state, loss, skipped)`` with ``skipped`` a rank-major
    ``[n]`` int32 vector of this step's skip flags (``comm_weights`` is
    ``()`` for comm modes without neighbor weights).  Under ``health=``
    every variant appends the ``HealthVector`` of ``[n]`` f32 fields.
    """
    if comm_mode not in ("cta", "atc", "gradient_allreduce", "push_sum",
                         "none"):
        raise ValueError(f"unknown comm_mode {comm_mode!r}")
    needs_topo = comm_mode in ("cta", "atc", "push_sum")
    if needs_topo and (topology is None) == (schedule is None):
        raise ValueError(
            "neighbor modes need exactly one of topology= or schedule=")
    if hierarchical is not None:
        # a PodSpec (duck-typed: machines/chips_per_machine) or a plain
        # int local size — either way it resolves to the ICI group width
        hier_l = int(getattr(hierarchical, "chips_per_machine",
                             hierarchical))
        if (hierarchical_local_size is not None
                and int(hierarchical_local_size) != hier_l):
            raise ValueError(
                f"hierarchical={hierarchical!r} (local size {hier_l}) "
                f"conflicts with hierarchical_local_size="
                f"{hierarchical_local_size!r}")
        hierarchical_local_size = hier_l
    if hierarchical_local_size is None and comm_mode in ("cta", "atc"):
        hierarchical_local_size = _config.hier_local_size()
    if comm_mode == "push_sum" and hierarchical_local_size is not None:
        raise ValueError(
            "hierarchical_local_size is not supported with "
            "comm_mode='push_sum' (flat rank-level push-sum only)")
    if hierarchical_local_size is not None and comm_mode in ("cta", "atc"):
        n_ranks = int(mesh.shape[axis_name])
        hier_specs = ([topology] if topology is not None
                      else list(schedule or []))
        C.validate_machine_decomposition(
            n_ranks, hierarchical_local_size, hier_specs)
        machines = getattr(hierarchical, "machines", None)
        if machines is not None and \
                int(machines) * int(hierarchical_local_size) != n_ranks:
            raise ValueError(
                f"hierarchical pod of {machines} machines x "
                f"{hierarchical_local_size} chips does not cover the "
                f"{n_ranks}-rank mesh axis {axis_name!r}")
    if pp_axis is not None and param_specs is None:
        raise ValueError(
            "pp_axis requires param_specs: the spec tree is what tells "
            "pipeline-sharded leaves (layer stacks, NOT reduced over pp) "
            "apart from pp-replicated ones (embeddings/head, psum'd)")
    if compress is None and comm_mode in ("cta", "atc"):
        # BLUEFOG_MIX_COMPRESS supplies the default wire mode when the
        # builder did not choose one (explicit arguments always win)
        compress = _config.mix_compress()
    mix = None
    if isinstance(compress, MixCompressConfig):
        mix, compress = compress, None
    elif compress == "topk":
        env_ratio = _config.mix_compress_ratio()
        mix = (MixCompressConfig() if env_ratio is None
               else MixCompressConfig(ratio=env_ratio))
        compress = None
    if mix is not None:
        if comm_mode not in ("cta", "atc"):
            raise ValueError(
                "compress='topk' (error-feedback compressed mixing) "
                "rides the cta/atc combine only "
                f"(got comm_mode={comm_mode!r})")
        if mix.values not in ("int8", "int8_sr", "none"):
            raise ValueError(
                f"unknown MixCompressConfig values mode {mix.values!r}")
        if not mix.ratio > 0:
            raise ValueError(
                f"MixCompressConfig.ratio must be > 0, got {mix.ratio}")
        if mix.ratio >= 1.0:
            # keep-everything: build the ordinary uncompressed exchange
            # so ratio=1.0 is bit-identical to compress=None by
            # construction (no wire round-trip to be identical THROUGH)
            mix = None
    if compress is not None:
        if compress not in ("int8", "int8_sr", "bf16"):
            raise ValueError(f"unknown compress mode {compress!r}")
        if comm_mode not in ("cta", "atc"):
            raise ValueError(
                "compress= is only honored by the cta/atc combine "
                f"(got comm_mode={comm_mode!r})")
    if overlap not in ("none", "bucketed"):
        raise ValueError(f"unknown overlap mode {overlap!r}")
    if guard is not None:
        if comm_mode == "push_sum":
            raise ValueError(
                "guard= does not compose with comm_mode='push_sum': the "
                "(params, ps_weight) pair must mix as a unit, and a "
                "per-rank skip would break the column-stochastic "
                "sum(ps) == n invariant")
    if moe is not None and comm_mode not in ("cta", "atc"):
        raise ValueError(
            "moe= (expert-sharded MoE) partitions the NEIGHBOR combine "
            "into shared/expert leaves, so it needs comm_mode='cta' or "
            f"'atc' (got {comm_mode!r}); gradient_allreduce would "
            "average expert gradients across ranks hosting DIFFERENT "
            "experts, and push_sum's (x, w) pair cannot be split")
    if overlap == "bucketed":
        if comm_mode not in ("cta", "atc", "push_sum"):
            raise ValueError(
                "overlap='bucketed' buckets the cta/atc/push_sum "
                f"neighbor exchange only (got comm_mode={comm_mode!r}); "
                "gradient_allreduce relies on XLA's all-reduce combiner")
        if overlap_buckets < 1:
            raise ValueError(
                f"overlap_buckets must be >= 1, got {overlap_buckets}")
    bucketed = overlap == "bucketed"
    atc_bucketed = bucketed and comm_mode == "atc"

    specs = list(schedule) if schedule is not None else (
        [topology] if topology is not None else [])
    if _config.fuse_epilogues():
        return _build_fused_train_step(
            loss_fn, optimizer, mesh, axis_name=axis_name,
            comm_mode=comm_mode, specs=specs,
            k_comm=int(num_steps_per_communication),
            hierarchical_local_size=hierarchical_local_size,
            sp_axis=sp_axis, pp_axis=pp_axis, batch_specs=batch_specs,
            param_specs=param_specs, opt_state_specs=opt_state_specs,
            donate=donate, has_aux=has_aux, compress=compress,
            n_buckets=overlap_buckets if bucketed else None,
            guard=guard, health=health, mix=mix, moe=moe)
    # ------- BLUEFOG_FUSE_EPILOGUES=0: the pre-fusion builders -------
    if mix is not None:
        raise ValueError(
            "compress='topk' (error-feedback compressed mixing) needs "
            "the fused epilogue pipeline — unset "
            "BLUEFOG_FUSE_EPILOGUES=0 (the pre-fusion builders have no "
            "ef_encode/ef_decode stages)")
    if moe is not None:
        raise ValueError(
            "moe= (expert-sharded MoE) needs the fused epilogue "
            "pipeline — unset BLUEFOG_FUSE_EPILOGUES=0 (the pre-fusion "
            "builders mix the whole param tree and would drag expert "
            "leaves onto the wire)")
    if comm_mode == "push_sum" and bucketed:
        raise ValueError(
            "overlap='bucketed' with comm_mode='push_sum' needs the "
            "fused epilogue pipeline (unset BLUEFOG_FUSE_EPILOGUES=0): "
            "the unfused builder mixes the extended payload whole")
    if guard is not None:
        return _build_guarded_train_step(
            loss_fn, optimizer, mesh, guard=guard, axis_name=axis_name,
            comm_mode=comm_mode, specs=specs,
            num_steps_per_communication=num_steps_per_communication,
            hierarchical_local_size=hierarchical_local_size,
            sp_axis=sp_axis, pp_axis=pp_axis, batch_specs=batch_specs,
            param_specs=param_specs, opt_state_specs=opt_state_specs,
            donate=donate, has_aux=has_aux, compress=compress,
            n_buckets=overlap_buckets if bucketed else None,
            health=health)
    if bucketed and comm_mode == "cta":
        branches = [
            _bucketed_combine_fn(s, axis_name, hierarchical_local_size,
                                 compress, overlap_buckets)
            for s in specs
        ]
    elif atc_bucketed:
        branches = []  # ATC bucketed routes through ac_branches only
    else:
        branches = [
            _combine_fn(s, axis_name, hierarchical_local_size, compress)
            for s in specs
        ]
    ac_branches = [
        _bucketed_apply_combine_fn(s, axis_name, hierarchical_local_size,
                                   compress, overlap_buckets)
        for s in specs
    ] if atc_bucketed else []
    ps_branches = [
        (lambda spec: lambda op: C.push_sum_mix(op[0], op[1], spec,
                                                axis_name))(s)
        for s in specs
    ] if comm_mode == "push_sum" else []
    k_comm = int(num_steps_per_communication)

    def combine(params, step):
        if not branches:
            return params

        def run(params):
            # per-step key for the stochastic wire rounder (int8_sr);
            # unused operands are dead-code-eliminated otherwise
            key = jax.random.fold_in(
                jax.random.PRNGKey(0x51EED), step)
            if len(branches) == 1:
                return branches[0](params, key)
            return lax.switch(step % len(branches), branches, params, key)

        if k_comm > 1:
            # lax.cond actually skips the collectives on off-cycle steps
            # (a select/where would still execute them every step).
            return lax.cond(step % k_comm == 0, run, lambda p: p, params)
        return run(params)

    def combine_push_sum(params, ps, step):
        def run(operand):
            params, ps = operand
            # Push-sum state is the BIASED pair (x, w) with readout
            # z = x / w; we carry (z, w) so the user-visible params stay
            # de-biased, and re-bias before every mix (x = z * w) — mixing
            # z directly is only correct on doubly-stochastic graphs and
            # diverges on general digraphs.  The whole re-bias -> mix ->
            # de-bias round stays in f32 (push_sum_mix returns the
            # accumulation dtype); one cast back at the end.
            dtypes = jax.tree.map(lambda z: z.dtype, params)
            biased = jax.tree.map(
                lambda z: z.astype(jnp.float32) * ps, params)
            if len(ps_branches) == 1:
                mixed, mixed_ps = ps_branches[0]((biased, ps))
            else:
                mixed, mixed_ps = lax.switch(
                    step % len(ps_branches), ps_branches, (biased, ps))
            # de-bias: z = x / w (reference optimizers.py:1151-1155)
            debiased = jax.tree.map(
                lambda x, dt: (x / mixed_ps).astype(dt), mixed, dtypes)
            return debiased, mixed_ps

        if k_comm > 1:
            return lax.cond(step % k_comm == 0, run, lambda op: op,
                            (params, ps))
        return run((params, ps))

    def apply_then_combine(params, updates, step):
        """ATC overlap engine: the interleaved per-bucket apply+combine
        (see _bucketed_apply_combine_fn).  Off-cycle steps under
        num_steps_per_communication still apply the optax update —
        only the collectives are skipped (lax.cond, like combine())."""
        if not ac_branches:
            return optax.apply_updates(params, updates)

        def run(operand):
            params, updates = operand
            key = jax.random.fold_in(
                jax.random.PRNGKey(0x51EED), step)
            if len(ac_branches) == 1:
                return ac_branches[0]((params, updates), key)
            return lax.switch(step % len(ac_branches), ac_branches,
                              (params, updates), key)

        if k_comm > 1:
            return lax.cond(step % k_comm == 0, run,
                            lambda op: optax.apply_updates(op[0], op[1]),
                            (params, updates))
        return run((params, updates))

    def per_rank_step(params, aux, opt_state, batch, step):
        loss, grads, new_aux = _loss_and_grads(
            loss_fn, has_aux, sp_axis, pp_axis, param_specs,
            params, aux, batch)
        # local (pre-allreduce) gradient norm: the per-rank attribution
        # signal the fleet layer gossips
        grad_sq = _tree_sq_sum(grads) if health is not None else None
        consensus = jnp.zeros((), jnp.float32)
        if comm_mode == "gradient_allreduce":
            grads = jax.tree.map(
                lambda g: C.allreduce(g, axis_name, average=True), grads)
        if comm_mode == "push_sum":
            base_state, ps = opt_state
            pre = params
            params, ps = combine_push_sum(params, ps, step)
            if health is not None and health.consensus:
                consensus = _tree_distance(pre, params)
            updates, base_state = optimizer.update(grads, base_state, params)
            params = optax.apply_updates(params, updates)
            hv = (_make_health_vector(loss, grad_sq, updates, consensus)
                  if health is not None else None)
            return params, new_aux, (base_state, ps), loss, hv
        if comm_mode == "cta":
            pre = params
            params = combine(params, step)
            if health is not None and health.consensus:
                consensus = _tree_distance(pre, params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        if atc_bucketed:
            new_params = apply_then_combine(params, updates, step)
            if health is not None and health.consensus:
                # the per-bucket applies inside apply_then_combine are
                # the same pure arithmetic — XLA CSEs the duplicate
                applied = optax.apply_updates(params, updates)
                consensus = _tree_distance(applied, new_params)
            params = new_params
        else:
            params = optax.apply_updates(params, updates)
            if comm_mode == "atc":
                pre = params
                params = combine(params, step)
                if health is not None and health.consensus:
                    consensus = _tree_distance(pre, params)
        hv = (_make_health_vector(loss, grad_sq, updates, consensus)
              if health is not None else None)
        return params, new_aux, opt_state, loss, hv

    squeeze = lambda t: jax.tree.map(lambda x: x[0], t)
    expand = lambda t: jax.tree.map(lambda x: x[None], t)

    obs_labels = dict(comm_mode=comm_mode, overlap=overlap,
                      guarded="false")

    def wrapped(params, aux, opt_state, batch, step):
        # strip the leading per-shard rank axis of size 1
        params, aux, opt_state, loss, hv = per_rank_step(
            squeeze(params), squeeze(aux), squeeze(opt_state),
            squeeze(batch), step)
        outs = (expand(params), expand(aux), expand(opt_state),
                jnp.reshape(loss, (1,)))
        if health is not None:
            outs = outs + (HealthVector(
                *[jnp.reshape(x, (1,)) for x in hv]),)
        return outs

    p_rank = P(axis_name)
    if batch_specs is None:
        batch_specs = p_rank
    # Model-parallel (e.g. tensor-parallel) param layouts: per-leaf specs
    # carry the extra mesh axes (see models.llama.llama_param_specs);
    # grads/updates follow params automatically under shard_map.
    p_params = param_specs if param_specs is not None else p_rank
    p_opt = opt_state_specs if opt_state_specs is not None else p_rank
    out_specs = (p_params, p_rank, p_opt, p_rank)
    if health is not None:
        out_specs = out_specs + (p_rank,)  # spec prefix over HealthVector
    sm = jax.shard_map(
        wrapped,
        mesh=mesh,
        in_specs=(p_params, p_rank, p_opt, batch_specs, P()),
        out_specs=out_specs,
        check_vma=False,
    )
    donate_argnums = (0, 1, 2) if donate else ()
    jitted = jax.jit(sm, donate_argnums=donate_argnums)
    # traffic accounting only for modes that actually run a neighbor
    # exchange — a topology passed alongside comm_mode='none' /
    # 'gradient_allreduce' must not count phantom edge bytes
    edge_traffic = (list(specs), 4 if has_aux else 3, k_comm,
                    int(mesh.shape[axis_name]),
                    comm_mode == "push_sum",
                    hierarchical_local_size
                    if comm_mode in ("cta", "atc") else None) \
        if (specs and needs_topo) else None
    if has_aux:
        aux_step = _observed_step(jitted, obs_labels, edge_traffic)
        aux_step.jitted = jitted
        aux_step.lower = jitted.lower
        aux_step.trace = jitted.trace
        aux_step.health_config = health
        aux_step.hierarchical_local_size = \
            hierarchical_local_size if comm_mode in ("cta", "atc") else None
        return aux_step

    if health is None:
        def no_aux_step(params, opt_state, batch, step):
            params, _, opt_state, loss = jitted(
                params, (), opt_state, batch, step)
            return params, opt_state, loss
    else:
        def no_aux_step(params, opt_state, batch, step):
            params, _, opt_state, loss, hv = jitted(
                params, (), opt_state, batch, step)
            return params, opt_state, loss, hv

    step_fn = _observed_step(no_aux_step, obs_labels, edge_traffic)
    # AOT access for benchmarks: lower/compile the real program (e.g. for
    # XLA cost analysis / MFU accounting) without re-jitting the wrapper;
    # .trace is the jaxpr-inspection analog bluefog_tpu.analysis uses.
    step_fn.jitted = jitted
    step_fn.lower = lambda params, opt_state, batch, step: jitted.lower(
        params, (), opt_state, batch, step)
    step_fn.trace = lambda params, opt_state, batch, step: jitted.trace(
        params, (), opt_state, batch, step)
    step_fn.health_config = health
    step_fn.hierarchical_local_size = \
        hierarchical_local_size if comm_mode in ("cta", "atc") else None
    return step_fn


def _build_guarded_train_step(
    loss_fn: Callable,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    *,
    guard: GuardConfig,
    axis_name: str,
    comm_mode: str,
    specs: Sequence[CommSpec],
    num_steps_per_communication: int,
    hierarchical_local_size: Optional[int],
    sp_axis: Optional[str],
    pp_axis: Optional[str],
    batch_specs: Any,
    param_specs: Any,
    opt_state_specs: Any,
    donate: bool,
    has_aux: bool,
    compress: Optional[str],
    n_buckets: Optional[int],
    health: Optional[HealthConfig] = None,
) -> Callable:
    """The ``guard=`` variant of :func:`build_train_step` (see its
    docstring for the contract).  Kept separate so the unguarded fast
    path stays byte-for-byte what it was; numerics are identical when
    every rank is healthy — the skip guard's taken branch IS the
    unguarded arithmetic, and the traced combine weights carry the same
    values the unguarded branches bake in."""
    k_comm = int(num_steps_per_communication)
    neighbor = comm_mode in ("cta", "atc")
    wbranches = [
        _weighted_combine_fn(s, axis_name, compress, n_buckets,
                             hierarchical_local_size)
        for s in specs
    ] if neighbor else []

    def combine(params, step, comm_weights):
        if not wbranches:
            return params

        def run(params):
            key = jax.random.fold_in(jax.random.PRNGKey(0x51EED), step)
            if len(wbranches) == 1:
                return wbranches[0](params, key, comm_weights[0])
            picked = [
                (lambda fn, i: lambda p, k, ws: fn(p, k, ws[i]))(fn, i)
                for i, fn in enumerate(wbranches)
            ]
            return lax.switch(step % len(wbranches), picked, params, key,
                              comm_weights)

        if k_comm > 1:
            return lax.cond(step % k_comm == 0, run, lambda p: p, params)
        return run(params)

    def per_rank_step(params, aux, opt_state, batch, step, comm_weights):
        loss, grads, new_aux = _loss_and_grads(
            loss_fn, has_aux, sp_axis, pp_axis, param_specs,
            params, aux, batch)
        grad_sq = _tree_sq_sum(grads) if health is not None else None
        consensus = jnp.zeros((), jnp.float32)
        if comm_mode == "gradient_allreduce":
            # NOTE: the allreduce mixes GRADIENTS, so one rank's NaN
            # reaches every rank's update — the guard then skips
            # globally (all ranks keep their state).  The neighbor
            # modes contain the blast radius to the faulty rank.
            grads = jax.tree.map(
                lambda g: C.allreduce(g, axis_name, average=True), grads)
        if comm_mode == "cta":
            pre = params
            params = combine(params, step, comm_weights)
            if health is not None and health.consensus:
                consensus = _tree_distance(pre, params)
        updates, new_opt_state = optimizer.update(grads, opt_state, params)
        ok = _all_finite(loss, updates)

        # The skip guard: a per-rank conditional over pure arithmetic
        # only — the collective combine stays OUTSIDE (a per-rank-
        # divergent branch must never contain a collective).  The
        # skipping rank keeps params/aux/opt_state, so the combine
        # below feeds its last-good params to its neighbors.  Lowered
        # as an elementwise select over the unconditionally-applied
        # update rather than a lax.cond: a traced-pred cond becomes a
        # select anyway, but the cond's branch boundary would also
        # block XLA's mul+add contraction inside apply_updates and cost
        # the healthy path its bit-identity with the unguarded step.
        # A discarded non-finite branch is safe under select: it is
        # elementwise, and nothing differentiates through it here.
        def pick(new, old):
            return jnp.where(ok, new, old)

        params = jax.tree.map(pick, optax.apply_updates(params, updates),
                              params)
        out_aux = jax.tree.map(pick, new_aux, aux)
        out_opt = jax.tree.map(pick, new_opt_state, opt_state)
        if comm_mode == "atc":
            pre = params
            params = combine(params, step, comm_weights)
            if health is not None and health.consensus:
                consensus = _tree_distance(pre, params)
        skipped = jnp.where(ok, jnp.int32(0), jnp.int32(1))
        hv = (_make_health_vector(loss, grad_sq, updates, consensus,
                                  skipped=skipped)
              if health is not None else None)
        return params, out_aux, out_opt, loss, skipped, hv

    squeeze = lambda t: jax.tree.map(lambda x: x[0], t)
    expand = lambda t: jax.tree.map(lambda x: x[None], t)

    def wrapped(params, aux, opt_state, batch, step, comm_weights):
        params, aux, opt_state, loss, skipped, hv = per_rank_step(
            squeeze(params), squeeze(aux), squeeze(opt_state),
            squeeze(batch), step, comm_weights)
        outs = (expand(params), expand(aux), expand(opt_state),
                jnp.reshape(loss, (1,)), jnp.reshape(skipped, (1,)))
        if health is not None:
            outs = outs + (HealthVector(
                *[jnp.reshape(x, (1,)) for x in hv]),)
        return outs

    p_rank = P(axis_name)
    if batch_specs is None:
        batch_specs = p_rank
    p_params = param_specs if param_specs is not None else p_rank
    p_opt = opt_state_specs if opt_state_specs is not None else p_rank
    # comm weights ride replicated (every rank reads the full tables)
    p_comm = tuple((P(), P()) for _ in wbranches)
    out_specs = (p_params, p_rank, p_opt, p_rank, p_rank)
    if health is not None:
        out_specs = out_specs + (p_rank,)  # spec prefix over HealthVector
    sm = jax.shard_map(
        wrapped,
        mesh=mesh,
        in_specs=(p_params, p_rank, p_opt, batch_specs, P(), p_comm),
        out_specs=out_specs,
        check_vma=False,
    )
    donate_argnums = (0, 1, 2) if donate else ()
    jitted = jax.jit(sm, donate_argnums=donate_argnums)
    default_w = comm_weight_inputs(specs) if wbranches else ()

    obs_labels = dict(
        comm_mode=comm_mode,
        overlap="bucketed" if n_buckets is not None else "none",
        guarded="true")

    # guarded steps are cta/atc only — neighbor_allreduce moves bytes
    # on every declared edge, so the unfiltered edge set is correct
    edge_traffic = (list(specs), 4 if has_aux else 3, k_comm,
                    int(mesh.shape[axis_name]), False,
                    hierarchical_local_size) \
        if wbranches else None
    if has_aux:
        def aux_step(params, aux, opt_state, batch, step, comm_weights):
            return jitted(params, aux, opt_state, batch, step,
                          comm_weights)

        step_fn = _observed_step(aux_step, obs_labels, edge_traffic)
        step_fn.jitted = jitted
        step_fn.lower = jitted.lower
        step_fn.trace = jitted.trace
        step_fn.default_comm_weights = default_w
        step_fn.has_aux = True  # run_resilient rejects aux signatures
        step_fn.guard_config = guard
        step_fn.health_config = health
        step_fn.hierarchical_local_size = \
            hierarchical_local_size if neighbor else None
        return step_fn

    if health is None:
        def no_aux_step(params, opt_state, batch, step, comm_weights):
            params, _, opt_state, loss, skipped = jitted(
                params, (), opt_state, batch, step, comm_weights)
            return params, opt_state, loss, skipped
    else:
        def no_aux_step(params, opt_state, batch, step, comm_weights):
            params, _, opt_state, loss, skipped, hv = jitted(
                params, (), opt_state, batch, step, comm_weights)
            return params, opt_state, loss, skipped, hv

    step_fn = _observed_step(no_aux_step, obs_labels, edge_traffic)
    step_fn.jitted = jitted
    step_fn.lower = (
        lambda params, opt_state, batch, step, comm_weights:
        jitted.lower(params, (), opt_state, batch, step, comm_weights))
    step_fn.trace = (
        lambda params, opt_state, batch, step, comm_weights:
        jitted.trace(params, (), opt_state, batch, step, comm_weights))
    step_fn.default_comm_weights = default_w
    step_fn.has_aux = False
    step_fn.guard_config = guard
    step_fn.health_config = health
    step_fn.hierarchical_local_size = \
        hierarchical_local_size if neighbor else None
    return step_fn
