"""Distributed optimizer wrappers over optax.

Reference parity: bluefog/torch/optimizers.py — the five mechanisms:

=====================================  =======================================
reference (torch.optim subclasses)      this build (optax wrappers)
=====================================  =======================================
_DistributedOptimizer (:166)            DistributedGradientAllreduceOptimizer
_DistributedReduceOptimizer (:297)      DistributedAdaptWithCombineOptimizer
  (CTA: combine params, then adapt)       (+ deprecated per-comm-type aliases)
_DistributedAdaptThenCombine (:485)     DistributedAdaptThenCombineOptimizer
_DistributedWinOptimizer (:844)         DistributedWinPutOptimizer /
  (win_put push / win_get pull)           DistributedPullGetOptimizer
_DistributedPushSumOptimizer (:1026)    DistributedPushSumOptimizer
=====================================  =======================================

The reference launches communication from forward/backward *hooks* to overlap
with compute, then waits in ``optimizer.step()``.  These wrappers expose a
host-driven ``step(params, grads, state)`` API: each collective is dispatched
nonblocking per parameter leaf and synchronized once at the end of the step,
so JAX async dispatch provides the overlap the reference gets from its
background thread.  ``step`` itself must NOT be wrapped in ``jax.jit`` — it
re-reads host-side knobs (dynamic weights, communication cadence) every call.
For a fully-jitted train step, inline the shard-level kernels from
``bluefog_tpu.parallel.collectives`` (see ``bluefog_tpu.optim.functional``).

Dynamic-topology knobs: ``opt.self_weight / opt.src_weights / opt.dst_weights``
are mutable attributes re-read every step (reference optimizers.py:326-331),
so per-iteration one-peer schedules work the same way as the reference's
``dynamic_topology_update`` pattern (examples/pytorch_resnet.py:333-372).

``num_steps_per_communication`` implements local-SGD-style periodic
communication (reference optimizers.py:343-348).
"""

from __future__ import annotations

import enum
from typing import Any, Callable, Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from bluefog_tpu import api
from bluefog_tpu import config as bfconfig
from bluefog_tpu.context import get_context
from bluefog_tpu.optim.fusion import FusionPlan

__all__ = [
    "CommunicationType",
    "DistributedGradientAllreduceOptimizer",
    "DistributedAdaptWithCombineOptimizer",
    "DistributedAdaptThenCombineOptimizer",
    "DistributedAllreduceOptimizer",
    "DistributedNeighborAllreduceOptimizer",
    "DistributedHierarchicalNeighborAllreduceOptimizer",
    "DistributedWinPutOptimizer",
    "DistributedPullGetOptimizer",
    "DistributedPushSumOptimizer",
]


class CommunicationType(enum.Enum):
    """Reference optimizers.py:28-35."""

    neighbor_allreduce = "neighbor.allreduce"
    hierarchical_neighbor_allreduce = "hierarchical.neighbor.allreduce"
    allreduce = "allreduce"
    empty = "empty"


class _OptState(NamedTuple):
    base: Any
    step: jnp.ndarray  # scalar int32


# The fusion planner (grouping walk + rank-major pack/unpack) now lives in
# the shared trace-time module so the jitted overlap engine
# (functional.build_train_step(overlap="bucketed")) and this eager path
# provably use ONE grouping policy (tests/test_fusion.py).
_FusionPlan = FusionPlan


def _tree_names(params) -> Dict[str, Any]:
    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    return {jax.tree_util.keystr(path): leaf for path, leaf in flat}


class _DistributedOptimizerBase:
    """Shared machinery: base optax transform + comm cadence + weight knobs."""

    def __init__(self, base_optimizer: optax.GradientTransformation,
                 num_steps_per_communication: int = 1):
        self.base = base_optimizer
        self.num_steps_per_communication = int(num_steps_per_communication)
        # Mutable dynamic-topology knobs (reference optimizers.py:326-331).
        self.self_weight = None
        self.src_weights = None
        self.dst_weights = None
        self._step_count = 0

    def init(self, params) -> _OptState:
        return _OptState(base=self.base.init(params), step=jnp.zeros((), jnp.int32))

    def _should_communicate(self) -> bool:
        self._step_count += 1
        return self._step_count % self.num_steps_per_communication == 0

    def _base_apply(self, params, grads, state: _OptState):
        updates, new_base = self.base.update(grads, state.base, params)
        new_params = optax.apply_updates(params, updates)
        return new_params, _OptState(base=new_base, step=state.step + 1)

    # communication helpers ------------------------------------------------
    def _pipelined(self, params, launch: Callable) -> Any:
        """Dispatch ``launch(buffer) -> handle`` for every fusion buffer,
        then synchronize once — all collectives are enqueued before the
        first host wait (the reference gets this overlap from its hooks +
        background thread; here JAX async dispatch provides it).

        Leaves are packed into flat fusion buffers first (see
        ``_FusionPlan``; threshold via BLUEFOG_FUSION_THRESHOLD, 0 to
        disable), mirroring the reference's response fusion
        (operations.cc:943-1020) — an eager ResNet-50 combine issues a
        handful of programs, not one per parameter.

        Records a COMMUNICATE timeline span when the timeline is enabled
        (the reference's optimizers register timeline hooks,
        optimizers.py:112-163)."""
        leaves, treedef = jax.tree_util.tree_flatten(params)
        threshold = bfconfig.fusion_threshold()
        with api.timeline_context(type(self).__name__, "COMMUNICATE"):
            if threshold and len(leaves) > 1:
                plan = _FusionPlan.for_leaves(leaves, threshold)
                buffers = plan.pack(leaves)
                handles = [launch(b) for b in buffers]
                outs = plan.unpack([api.synchronize(h) for h in handles])
            else:
                handles = [launch(leaf) for leaf in leaves]
                outs = [api.synchronize(h) for h in handles]
        return jax.tree_util.tree_unflatten(treedef, list(outs))

    def _combine(self, params):
        return self._pipelined(
            params,
            lambda p: api.neighbor_allreduce_nonblocking(
                p, self_weight=self.self_weight, src_weights=self.src_weights,
                dst_weights=self.dst_weights, enable_topo_check=False))


class DistributedGradientAllreduceOptimizer(_DistributedOptimizerBase):
    """Horovod-style synchronous gradient averaging (reference
    optimizers.py:166-294, factory :1376-1423)."""

    def step(self, params, grads, state: _OptState):
        if self._should_communicate():
            grads = self._pipelined(
                grads, lambda g: api.allreduce_nonblocking(g, average=True))
        return self._base_apply(params, grads, state)


class DistributedAdaptWithCombineOptimizer(_DistributedOptimizerBase):
    """CTA — combine-then-adapt: neighbor-average the *parameters*, then take
    the base optimizer step with the local gradients (reference
    _DistributedReduceOptimizer optimizers.py:297-482, factory :1497-1554)."""

    def __init__(self, base_optimizer, communication_type=CommunicationType.neighbor_allreduce,
                 num_steps_per_communication: int = 1):
        super().__init__(base_optimizer, num_steps_per_communication)
        self.communication_type = communication_type

    def _communicate(self, params):
        ct = self.communication_type
        if ct == CommunicationType.empty:
            return params
        if ct == CommunicationType.allreduce:
            return self._pipelined(
                params, lambda p: api.allreduce_nonblocking(p, average=True))
        if ct == CommunicationType.hierarchical_neighbor_allreduce:
            return self._pipelined(
                params,
                lambda p: api.hierarchical_neighbor_allreduce_nonblocking(
                    p, self_weight=self.self_weight,
                    src_machine_weights=self.src_weights,
                    dst_machine_weights=self.dst_weights))
        return self._combine(params)

    def step(self, params, grads, state: _OptState):
        if self._should_communicate():
            params = self._communicate(params)
        return self._base_apply(params, grads, state)


class DistributedAdaptThenCombineOptimizer(DistributedAdaptWithCombineOptimizer):
    """ATC — adapt-then-combine: take the base step first, then
    neighbor-average the updated parameters (reference
    _DistributedAdaptThenCombineOptimizer optimizers.py:485-841,
    factory :1426-1494)."""

    def step(self, params, grads, state: _OptState):
        params, state = self._base_apply(params, grads, state)
        if self._should_communicate():
            params = self._communicate(params)
        return params, state


# Deprecated aliases (reference optimizers.py:1301-1373) -------------------
def DistributedAllreduceOptimizer(base_optimizer,
                                  num_steps_per_communication: int = 1):
    return DistributedAdaptWithCombineOptimizer(
        base_optimizer, CommunicationType.allreduce,
        num_steps_per_communication)


def DistributedNeighborAllreduceOptimizer(base_optimizer,
                                          num_steps_per_communication: int = 1):
    return DistributedAdaptWithCombineOptimizer(
        base_optimizer, CommunicationType.neighbor_allreduce,
        num_steps_per_communication)


def DistributedHierarchicalNeighborAllreduceOptimizer(
        base_optimizer, num_steps_per_communication: int = 1):
    return DistributedAdaptWithCombineOptimizer(
        base_optimizer, CommunicationType.hierarchical_neighbor_allreduce,
        num_steps_per_communication)


class _DistributedWindowOptimizerBase(_DistributedOptimizerBase):
    """Common window lifecycle for the async-gossip optimizers."""

    def __init__(self, base_optimizer, num_steps_per_communication: int = 1,
                 window_prefix: Optional[str] = None):
        super().__init__(base_optimizer, num_steps_per_communication)
        self.window_prefix = (window_prefix + ".") if window_prefix else ""
        self.force_barrier = False
        self._registered = False
        self._names: Dict[str, Any] = {}

    def _window_name(self, key: str) -> str:
        return f"{self.window_prefix}param{key}"

    def register_windows(self, params, zero_init: bool = False):
        """win_create per parameter leaf (reference optimizers.py:933-944)."""
        for key, leaf in _tree_names(params).items():
            name = self._window_name(key)
            if not api.win_create(leaf, name, zero_init=zero_init):
                raise ValueError(f"Cannot allocate window for parameter {name}")
            self._names[key] = name
        self._registered = True

    def unregister_windows(self):
        for name in self._names.values():
            if name in api.get_current_created_window_names():
                api.win_free(name)
        self._names.clear()
        self._registered = False

    def init(self, params) -> _OptState:
        if not self._registered and get_context().size() > 1:
            self.register_windows(params, zero_init=self._zero_init())
        return super().init(params)

    def _zero_init(self) -> bool:
        return False


class DistributedWinPutOptimizer(_DistributedWindowOptimizerBase):
    """Asynchronous push gossip: win_put parameters to out-neighbors, combine
    with win_update, then take the base step (reference
    _DistributedWinOptimizer push style, optimizers.py:844-1023,
    factory :1271-1298)."""

    def step(self, params, grads, state: _OptState):
        if self.force_barrier:
            api.barrier()
        if get_context().size() > 1 and self._should_communicate():
            flat = _tree_names(params)
            handles = {}
            for key, leaf in flat.items():
                handles[key] = api.win_put_nonblocking(
                    leaf, self._names[key], dst_weights=self.dst_weights,
                    require_mutex=False)
            new_flat = {}
            for key in flat:
                api.win_wait(handles[key])
                new_flat[key] = api.win_update(self._names[key],
                                               require_mutex=True)
            params = _rebuild(params, new_flat)
        return self._base_apply(params, grads, state)


class DistributedPullGetOptimizer(_DistributedWindowOptimizerBase):
    """Asynchronous pull gossip: win_get from in-neighbors then combine
    (reference pull style, optimizers.py:844-1023, factory :1225-1268)."""

    def step(self, params, grads, state: _OptState):
        if self.force_barrier:
            api.barrier()
        if get_context().size() > 1 and self._should_communicate():
            flat = _tree_names(params)
            handles = {}
            for key in flat:
                # The window tensor must track the live parameter for
                # neighbors' gets to see fresh values.
                api._wm().set_value(self._names[key], flat[key])
                handles[key] = api.win_get_nonblocking(
                    self._names[key], src_weights=self.src_weights,
                    require_mutex=True)
            new_flat = {}
            for key in flat:
                api.win_wait(handles[key])
                new_flat[key] = api.win_update(self._names[key],
                                               require_mutex=True)
            params = _rebuild(params, new_flat)
        return self._base_apply(params, grads, state)


class DistributedPushSumOptimizer(_DistributedWindowOptimizerBase):
    """Push-sum / gradient-push for directed graphs (reference
    _DistributedPushSumOptimizer optimizers.py:1026-1177, factory :1180-1222).

    Windows hold the extended payload [flatten(param) ‖ ps_weight]
    (ps_weight init 1).  Each communication:
      1. win_accumulate(extended * a) into out-neighbors, a = 1/(outdeg+1)
         — the same scale applied to self via ``self_weight``
      2. win_update_then_collect: extended += sum(mailbox); reset mailbox
      3. de-bias: param = x / ps_weight.
    The invariant sum_i ps_weight_i == size is what the reference's
    associated-P tests assert (test/torch_win_ops_test.py:780-863).
    """

    def __init__(self, base_optimizer, num_steps_per_communication: int = 1,
                 window_prefix: Optional[str] = None):
        super().__init__(base_optimizer, num_steps_per_communication,
                         window_prefix)
        self.force_barrier = True
        ctx = get_context()
        self._outdeg = {
            r: len(ctx.out_neighbor_ranks(r)) for r in range(ctx.size())
        }
        # Uniform column-stochastic weights (reference optimizers.py:1031-1035)
        self.dst_weights = [
            {d: 1.0 / (self._outdeg[r] + 1) for d in ctx.out_neighbor_ranks(r)}
            for r in range(ctx.size())
        ]
        self.self_weight = [
            1.0 / (self._outdeg[r] + 1) for r in range(ctx.size())
        ]

    def _zero_init(self) -> bool:
        return True

    def register_windows(self, params, zero_init: bool = True):
        ctx = get_context()
        n = ctx.size()
        for key, leaf in _tree_names(params).items():
            name = self._window_name(key)
            flatdim = int(np.prod(leaf.shape[1:]))
            extended = jnp.concatenate(
                [jnp.reshape(leaf, (n, flatdim)),
                 jnp.ones((n, 1), leaf.dtype)], axis=1)
            if not api.win_create(extended, name, zero_init=True):
                raise ValueError(f"Cannot allocate window for parameter {name}")
            self._names[key] = name
        self._registered = True

    def step(self, params, grads, state: _OptState):
        if self.force_barrier:
            api.barrier()
        ctx = get_context()
        if ctx.size() > 1 and self._should_communicate():
            n = ctx.size()
            flat = _tree_names(params)
            new_flat = {}
            for key, leaf in flat.items():
                name = self._names[key]
                win = api._wm().window(name)
                # current extended payload: fresh param + current ps weight
                ps = win.value[:, -1:]
                flatdim = int(np.prod(leaf.shape[1:]))
                extended = jnp.concatenate(
                    [jnp.reshape(leaf, (n, flatdim)).astype(win.dtype), ps],
                    axis=1)
                api._wm().set_value(name, extended)
                h = api.win_accumulate_nonblocking(
                    extended, name, self_weight=self.self_weight,
                    dst_weights=self.dst_weights, require_mutex=True)
                api.win_wait(h)
                collected = api.win_update_then_collect(name)
                corrected = collected[:, :-1] / collected[:, -1:]
                new_flat[key] = jnp.reshape(corrected, leaf.shape).astype(leaf.dtype)
            params = _rebuild(params, new_flat)
        return self._base_apply(params, grads, state)


def _rebuild(params, new_flat: Dict[str, Any]):
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    leaves = [new_flat[jax.tree_util.keystr(path)] for path, _ in flat]
    return jax.tree_util.tree_unflatten(treedef, leaves)
