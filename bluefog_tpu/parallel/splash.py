"""Splash-attention train backend (library kernel, fused backward).

Round-5 A/B at the 1B per-layer train shapes (benchmarks/splash_ab.py,
v5e-1, [B=4, H=32, KV=8, S=2048, D=64] bf16, causal, chained-loop
timing) measured ``jax.experimental.pallas.ops.tpu.splash_attention``
with its fused one-pass dq/dk/dv backward at **6.37 ms fwd+bwd** per
layer vs **8.72 ms** for our ``pallas_attention`` kernel (forward is a
wash: 2.63 vs 2.71 ms — the win is the fused backward).  End-to-end
(examples/llama_benchmark.py): **+10.0% tokens/s at 1B (58.5% MFU) and
+10.5% at 200M (50.0%)**, loss identical.  ``LlamaConfig(
attn_impl="splash")`` opts the plain causal full-sequence train path
into it; at the 8B tp8_seqshard shard shapes the whole-layer chain
still favors our flash kernel (llama_8b_measured_r05.json sweep), so
the 8B composition keeps ``flash``.

Our kernel remains the default and the only backend with an LSE output
(ring/blockwise composition, ``flash_attention_with_lse``) and
``q_offset``/``kv_offset`` support (decode); splash is a train-time
throughput knob.  GQA is native on both (q heads grouped over kv heads,
never materialized).  Precision note: splash downcasts its Q/K/V VMEM
scratch to bf16 (``downcast_smem_data=True``), the same precision class
as our bf16 train path; measured f32-input deltas vs our kernel are
~7e-4 (fwd) / ~9e-4 (dq).

Reference parity note: the reference framework has no attention kernels
at all (it is a DP communication library); this module is part of the
beyond-parity model stack.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from bluefog_tpu.parallel.pallas_attention import _fit_block

__all__ = ["splash_attention", "library_supports_head_dim"]


@functools.lru_cache(maxsize=8)
def library_supports_head_dim(d: int) -> bool:
    """Whether the INSTALLED splash library kernel accepts ``head_dim=d``.

    Older jax releases hard-require head_dim to be a whole 128-lane
    multiple; newer ones pad narrower heads internally.  Probed by
    abstractly tracing a tiny call (no compute), so callers and tests
    can gate instead of tripping the library's NotImplementedError deep
    inside a model trace."""
    if d % 128 == 0:
        return True
    try:
        with jax.enable_x64(False):
            q = jax.ShapeDtypeStruct((1, 128, 1, d), jnp.float32)
            jax.eval_shape(
                lambda a, b, c: splash_attention(
                    a, b, c, block_q=128, block_kv=128, interpret=True),
                q, q, q)
        return True
    except NotImplementedError:
        return False


def _auto_interpret(interpret: Optional[bool]) -> bool:
    if interpret is not None:
        return interpret
    return jax.default_backend() != "tpu"




@functools.lru_cache(maxsize=64)
def _make_kernel(n_heads: int, seq: int, block_q: int, block_kv: int,
                 interpret: bool):
    from jax.experimental.pallas.ops.tpu import splash_attention as sa

    mask = sa.MultiHeadMask([sa.CausalMask((seq, seq))
                             for _ in range(n_heads)])
    # q blocks must be whole 8-row sublane tiles (the library kernel's
    # grid math otherwise fails deep inside Mosaic with an opaque
    # layout error); seq is a multiple of 128 here (checked by the
    # wrapper), so fitting over seq//8 then scaling back up keeps every
    # candidate divisor tile-aligned — the same construction bkv uses
    # for whole 128-lane tiles below.
    bq = _fit_block(seq // 8, max(block_q // 8, 1)) * 8
    # kv blocks must be whole 128-lane tiles (kernel NUM_LANES check)
    bkv = _fit_block(seq // 128, max(block_kv // 128, 1)) * 128
    sizes = sa.BlockSizes(
        block_q=bq, block_kv=bkv, block_kv_compute=bkv,
        block_q_dkv=bq, block_kv_dkv=bkv, block_kv_dkv_compute=bkv,
        # fused backward: block_q_dq/block_kv_dq must stay unset
        use_fused_bwd_kernel=True)
    return sa.make_splash_mha(mask=mask, block_sizes=sizes,
                              head_shards=1, q_seq_shards=1,
                              interpret=interpret)


def splash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                     causal: bool = True, scale: Optional[float] = None,
                     block_q: int = 1024, block_kv: int = 1024,
                     interpret: Optional[bool] = None) -> jax.Array:
    """Causal self-attention via the splash kernel.

    Same contract as ``pallas_attention.flash_attention``'s train path:
    ``q [B, T, H, D]``, ``k/v [B, T, H_kv, D]`` -> ``[B, T, H, D]``,
    softmax(scale * q k^T + causal mask) v, differentiable.  The kernel
    wants head-major operands and pre-scaled queries; this wrapper
    adapts both and vmaps over the batch.
    """
    if not causal:
        raise NotImplementedError(
            "attn_impl='splash' supports the causal train path only; "
            "use attn_impl='flash' or 'xla' for non-causal attention")
    if jax.config.read("jax_enable_x64"):
        # the library's index maps mix int32 program ids with Python
        # ints, which promote to int64 under x64 and fail lax.div/rem
        # dtype checks (in backward traces too, beyond any local scope)
        raise NotImplementedError(
            "attn_impl='splash' is incompatible with jax_enable_x64; "
            "scope it off around the train step: "
            "`with jax.enable_x64(False): ...`")
    b, t, h, d = q.shape
    if t % 128:
        raise NotImplementedError(
            f"attn_impl='splash' needs the sequence length to be a "
            f"multiple of 128 (kv blocks are whole 128-lane tiles; "
            f"got {t}) — use attn_impl='flash' for odd lengths")
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    k = k.astype(q.dtype)
    v = v.astype(q.dtype)
    kernel = _make_kernel(h, t, block_q, block_kv,
                          _auto_interpret(interpret))
    qh = jnp.swapaxes(q * jnp.asarray(scale, q.dtype), 1, 2)  # [B,H,T,D]
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    out = jax.vmap(kernel)(qh, kh, vh)  # [B,H,T,D]
    return jnp.swapaxes(out, 1, 2)
