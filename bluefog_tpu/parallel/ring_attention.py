"""Ring attention — sequence/context parallelism over the device mesh.

The reference has no sequence parallelism (SURVEY.md §5: absent), but its
graph-neighbor ring exchange (reference bluefog/common/mpi_controller.cc:282-
361) is exactly the communication shape ring attention needs.  This module
makes long-context a first-class capability of the TPU build: the sequence
axis is sharded over a mesh axis, K/V blocks rotate around the ring via
``lax.ppermute`` while each device accumulates blockwise attention with a
numerically-stable online softmax (flash-attention style log-sum-exp merge).

Per ring step the transfer is one K/V block over ICI — the same "one unit
delay, one payload, no conflicts" property BlueFog claims for its one-peer
exponential graphs (reference README.rst:51-60), applied to attention.

Everything is f32-accumulated regardless of payload dtype.  Must be called
under ``shard_map`` with ``axis_name`` bound and the sequence axis sharded.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["ring_attention", "blockwise_attention", "full_attention"]

_NEG_INF = -1e30


def _merge_block(carry_m, carry_l, carry_acc, scores, v):
    """Online-softmax merge of one score block into the running state.

    carry_m: [B, H, Tq]      running row max
    carry_l: [B, H, Tq]      running denominator
    carry_acc: [B, H, Tq, D] running numerator
    scores: [B, H, Tq, Tk]   this block's logits (already masked)
    v: [B, Tk, H, D]         this block's values
    """
    block_m = jnp.max(scores, axis=-1)
    new_m = jnp.maximum(carry_m, block_m)
    correction = jnp.exp(carry_m - new_m)
    p = jnp.exp(scores - new_m[..., None])  # [B, H, Tq, Tk]
    # fully-masked rows (scores == new_m == -1e30) must contribute 0, not
    # exp(0) = 1
    p = jnp.where(scores <= _NEG_INF / 2, 0.0, p)
    new_l = carry_l * correction + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bhqd", p, v.astype(jnp.float32))
    new_acc = carry_acc * correction[..., None] + pv
    return new_m, new_l, new_acc


def _block_scores(q, k, q_offset, kv_offset, scale, causal):
    """Scaled dot-product logits for one (Q block, KV block) pair with the
    causal mask applied in *global* coordinates."""
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        q_pos = q_offset + jnp.arange(tq)
        kv_pos = kv_offset + jnp.arange(tk)
        mask = q_pos[:, None] >= kv_pos[None, :]  # [Tq, Tk]
        scores = jnp.where(mask[None, None], scores, _NEG_INF)
    return scores


def _repeat_kv(k, v, n_heads):
    """Grouped-query attention: tile KV heads up to the query head count."""
    n_kv = k.shape[2]
    if n_kv == n_heads:
        return k, v
    assert n_heads % n_kv == 0, (n_heads, n_kv)
    rep = n_heads // n_kv
    k = jnp.repeat(k, rep, axis=2)
    v = jnp.repeat(v, rep, axis=2)
    return k, v


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    causal: bool = True,
    scale: Optional[float] = None,
    impl: str = "xla",
) -> jax.Array:
    """Blockwise attention with K/V rotating around the mesh-axis ring.

    q: [B, T_local, H, D], k/v: [B, T_local, H_kv, D] — the local sequence
    shard of each array.  Returns [B, T_local, H, D] in q's dtype.

    At ring step s, this device holds the K/V block that originated on rank
    ``(idx - s) mod n``; after the local merge the block moves to rank
    ``idx + 1``.  n steps cover the full sequence.

    impl="flash" runs each per-step block attention as the Pallas flash
    kernel (bluefog_tpu.parallel.pallas_attention) and merges partial
    outputs via their log-sum-exp residuals; the custom ring-level VJP
    re-runs the Pallas backward kernels per ring step against the global
    (out, lse) residuals, so flash is fully trainable under sequence
    parallelism.
    """
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, t_local, n_heads, d = q.shape
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    if impl == "flash":
        return _ring_flash(q, k, v, axis_name, causal, scale, n, t_local)

    q_offset = idx * t_local
    m0 = jnp.full((b, n_heads, t_local), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, n_heads, t_local), jnp.float32)
    acc0 = jnp.zeros((b, n_heads, t_local, d), jnp.float32)
    shift = [(i, (i + 1) % n) for i in range(n)]

    def merge(m, l, acc, k_blk, v_blk, s):
        kv_offset = ((idx - s) % n) * t_local
        # GQA heads are widened only here, locally — the ring carries the
        # narrow [B, T, H_kv, D] blocks, so ICI traffic stays minimal.
        k_full, v_full = _repeat_kv(k_blk, v_blk, n_heads)
        scores = _block_scores(q, k_full, q_offset, kv_offset, scale, causal)
        return _merge_block(m, l, acc, scores, v_full)

    # Step 0 is the resident (self) block: no transfer needed.
    m0, l0, acc0 = merge(m0, l0, acc0, k, v, 0)

    def body(carry, s):
        k_blk, v_blk, m, l, acc = carry
        # Rotate first, then merge — the scan runs n-1 times, so no K/V
        # transfer is ever discarded; XLA overlaps ppermute with compute.
        k_blk = lax.ppermute(k_blk, axis_name, shift)
        v_blk = lax.ppermute(v_blk, axis_name, shift)
        m, l, acc = merge(m, l, acc, k_blk, v_blk, s)
        return (k_blk, v_blk, m, l, acc), None

    (_, _, m, l, acc), _ = lax.scan(
        body, (k, v, m0, l0, acc0), jnp.arange(1, n)
    )
    # Rows with no unmasked key (can't happen for causal with self block,
    # but guard anyway) divide by max(l, tiny).
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return jnp.einsum("bhqd->bqhd", out).astype(q.dtype)


from functools import partial as _partial


def _ring_flash_impl(q, k, v, axis_name, causal, scale, n, t_local):
    """Forward ring over the Pallas flash kernel: per step the kernel
    returns (out_s, lse_s); partials merge with logsumexp weights, so the
    full softmax is exact.  Returns (out_f32, lse) — lse is the residual
    the backward needs."""
    from bluefog_tpu.parallel.pallas_attention import flash_attention_with_lse

    idx = lax.axis_index(axis_name)
    q_offset = idx * t_local
    shift = [(i, (i + 1) % n) for i in range(n)]

    def step(s, k_blk, v_blk, o, lse):
        kv_offset = ((idx - s) % n) * t_local
        o_s, lse_s = flash_attention_with_lse(
            q, k_blk, v_blk, causal=causal, scale=scale,
            q_offset=q_offset, kv_offset=kv_offset)
        new_lse = jnp.logaddexp(lse, lse_s)  # [B, H, T]
        w_old = jnp.exp(lse - new_lse)
        w_new = jnp.exp(lse_s - new_lse)
        # weights come as [B, H, T]; outputs are [B, T, H, D]
        o = (o * jnp.moveaxis(w_old, 1, 2)[..., None] +
             o_s.astype(jnp.float32) * jnp.moveaxis(w_new, 1, 2)[..., None])
        return o, new_lse

    o0 = jnp.zeros(q.shape, jnp.float32)
    lse0 = jnp.full((q.shape[0], q.shape[2], t_local), _NEG_INF, jnp.float32)
    o, lse = step(0, k, v, o0, lse0)

    def body(carry, s):
        k_blk, v_blk, o, lse = carry
        k_blk = lax.ppermute(k_blk, axis_name, shift)
        v_blk = lax.ppermute(v_blk, axis_name, shift)
        o, lse = step(s, k_blk, v_blk, o, lse)
        return (k_blk, v_blk, o, lse), None

    (_, _, o, lse), _ = lax.scan(body, (k, v, o, lse), jnp.arange(1, n))
    return o, lse


@_partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _ring_flash(q, k, v, axis_name, causal, scale, n, t_local):
    """custom_vjp wraps the WHOLE ring (not just one kernel call):
    differentiation must never trace into the Pallas forward — the
    backward re-runs the Pallas bwd kernels per ring step instead."""
    o, _ = _ring_flash_impl(q, k, v, axis_name, causal, scale, n, t_local)
    return o.astype(q.dtype)


def _ring_flash_fwd(q, k, v, axis_name, causal, scale, n, t_local):
    o, lse = _ring_flash_impl(q, k, v, axis_name, causal, scale, n, t_local)
    out = o.astype(q.dtype)
    return out, (q, k, v, out, lse)


def _ring_flash_bwd(axis_name, causal, scale, n, t_local, res, g):
    """Ring backward: each step runs the Pallas backward kernels for one
    (Q, K/V-block) pair against the GLOBAL (out, lse) residuals — the
    per-block probabilities exp(S - lse) are then exactly the global
    softmax slices, so per-block (dQ, dK, dV) contributions sum to the
    exact gradients.  dK/dV accumulators rotate around the ring WITH their
    K/V block; after the final step one more ppermute delivers every
    accumulator back to its home rank."""
    from bluefog_tpu.parallel.pallas_attention import (
        _auto_interpret,
        _flash_bwd_impl,
    )

    q, k, v, out, lse = res
    interpret = _auto_interpret(None)
    idx = lax.axis_index(axis_name)
    q_offset = idx * t_local
    shift = [(i, (i + 1) % n) for i in range(n)]

    def block_grads(s, k_blk, v_blk):
        kv_offset = ((idx - s) % n) * t_local
        return _flash_bwd_impl(
            q, k_blk, v_blk, out, lse, g, q_offset, kv_offset,
            causal=causal, scale=scale, block_q=512, block_k=512,
            interpret=interpret)

    dq_c, dk_c, dv_c = block_grads(0, k, v)
    dq = dq_c.astype(jnp.float32)
    dk = dk_c.astype(jnp.float32)
    dv = dv_c.astype(jnp.float32)

    def body(carry, s):
        k_blk, v_blk, dq, dk, dv = carry
        k_blk = lax.ppermute(k_blk, axis_name, shift)
        v_blk = lax.ppermute(v_blk, axis_name, shift)
        dk = lax.ppermute(dk, axis_name, shift)
        dv = lax.ppermute(dv, axis_name, shift)
        dq_c, dk_c, dv_c = block_grads(s, k_blk, v_blk)
        dq = dq + dq_c.astype(jnp.float32)
        dk = dk + dk_c.astype(jnp.float32)
        dv = dv + dv_c.astype(jnp.float32)
        return (k_blk, v_blk, dq, dk, dv), None

    (_, _, dq, dk, dv), _ = lax.scan(
        body, (k, v, dq, dk, dv), jnp.arange(1, n))
    # the carried block now originated at rank idx+1; one final rotation
    # brings each dK/dV accumulator home
    dk = lax.ppermute(dk, axis_name, shift)
    dv = lax.ppermute(dv, axis_name, shift)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def blockwise_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    block_size: int,
    causal: bool = True,
    scale: Optional[float] = None,
) -> jax.Array:
    """Single-device blockwise (memory-efficient) attention with the same
    online-softmax math as :func:`ring_attention` — HBM-friendly for long
    sequences on one chip.  q/k/v: [B, T, H(,_kv), D]."""
    b, t, n_heads, d = q.shape
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    block_size = min(block_size, t)
    assert t % block_size == 0, (t, block_size)
    n_blocks = t // block_size
    # K/V stay narrow ([.., H_kv, ..]) — GQA heads are widened per block
    # inside the loop body, never materialized for the whole sequence.
    n_kv = k.shape[2]
    k_blocks = k.reshape(b, n_blocks, block_size, n_kv, d)
    v_blocks = v.reshape(b, n_blocks, block_size, n_kv, d)

    def q_block_attn(q_blk, q_idx):
        m = jnp.full((b, n_heads, block_size), _NEG_INF, jnp.float32)
        l = jnp.zeros((b, n_heads, block_size), jnp.float32)
        acc = jnp.zeros((b, n_heads, block_size, d), jnp.float32)

        def body(kv_idx, carry):
            m, l, acc = carry
            k_full, v_full = _repeat_kv(
                k_blocks[:, kv_idx], v_blocks[:, kv_idx], n_heads)
            scores = _block_scores(
                q_blk, k_full, q_idx * block_size,
                kv_idx * block_size, scale, causal)
            return _merge_block(m, l, acc, scores, v_full)

        # Causal: KV blocks strictly above the diagonal are fully masked —
        # skip them.  q_idx is a Python int, so the bound is static and the
        # loop stays reverse-mode differentiable.
        upper = q_idx + 1 if causal else n_blocks
        m, l, acc = lax.fori_loop(0, upper, body, (m, l, acc))
        out = acc / jnp.maximum(l, 1e-30)[..., None]
        return jnp.einsum("bhqd->bqhd", out)

    q_blocks = q.reshape(b, n_blocks, block_size, n_heads, d)
    outs = [q_block_attn(q_blocks[:, i], i) for i in range(n_blocks)]
    out = jnp.stack(outs, axis=1).reshape(b, t, n_heads, d)
    return out.astype(q.dtype)


def full_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    scale: Optional[float] = None,
    q_offset=0,
    kv_offset=0,
) -> jax.Array:
    """Dense reference attention (q/k/v: [B, T, H(,_kv), D]).  The offsets
    place the blocks in global coordinates for the causal mask — the same
    semantics the Pallas kernel implements (its backward pass recomputes
    through this function)."""
    b, t, n_heads, d = q.shape
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    k, v = _repeat_kv(k, v, n_heads)
    scores = _block_scores(q, k, q_offset, kv_offset, scale, causal)
    # jax.nn.softmax keeps XLA's fused softmax (an explicit exp/sum chain
    # measured 12x slower on TPU); the row-level guard zeroes rows whose
    # every key is masked (softmax would give uniform 1/T there)
    m = jnp.max(scores, axis=-1, keepdims=True)
    p = jnp.where(m <= _NEG_INF / 2, 0.0, jax.nn.softmax(scores, axis=-1))
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)
