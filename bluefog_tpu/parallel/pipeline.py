"""Pipeline parallelism over a mesh axis — GPipe on the ICI torus.

The reference framework is data-parallel only (SURVEY.md §2.3: PP marked
absent); this module is a capability past it, built the TPU way rather
than the torch way:

* The pipeline is ONE jitted SPMD program.  Stages are shards of a mesh
  axis (``pp``); activations move stage-to-stage with a single
  ``lax.ppermute`` shift per tick — a nearest-neighbor ICI hop, the
  cheapest collective on the torus.
* Microbatches stream through a ``lax.scan`` over ``M + S - 1`` ticks
  (GPipe schedule).  There is no hand-written backward schedule: JAX
  differentiates the scan, and the transpose of a ``ppermute`` is the
  reverse ``ppermute`` — the backward pipeline falls out of autodiff,
  running the same schedule in reverse (the 1F1B interleaving the
  reference ecosystems hand-schedule is here left to XLA's latency
  hiding; the bubble fraction is the standard ``(S-1)/(M+S-1)``).
* Layer parameters live stage-local: with a scanned-layer model
  (``scan_layers=True``) the leading ``[n_layers]`` axis of every block
  leaf is sharded over ``pp``, so each stage holds ``n_layers/S`` layers
  and NO parameter ever moves — only activations do.

Under ``jax.grad`` each stage's layer gradients are exact without any
cross-stage reduction (cotangents arrive through the reversed permutes);
parameters replicated over ``pp`` (embeddings, the head) need one
``psum`` over the axis, which :func:`bluefog_tpu.optim.functional.
build_train_step` applies for every leaf whose PartitionSpec does not
mention the pipeline axis.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

__all__ = ["gpipe", "gpipe_circular", "circular_layer_permutation"]


def gpipe(stage_fn: Callable, stage_params, x_micro: jax.Array,
          pp_axis: str, n_stages: int, with_aux: bool = False):
    """Run ``stage_fn`` as a GPipe pipeline over ``pp_axis``.

    Must be called inside ``shard_map`` with ``pp_axis`` bound.

    Args:
      stage_fn: ``(stage_params, x) -> y`` with ``y.shape == x.shape`` —
        this stage's slice of the network (e.g. a ``lax.scan`` over its
        local decoder layers).  With ``with_aux=True`` the signature is
        ``(stage_params, x) -> (y, aux)`` where ``aux`` is a scalar
        (e.g. a MoE load-balance term).
      stage_params: the stage-local parameter pytree (already sharded:
        each pp shard passes its own slice).
      x_micro: ``[M, ...]`` microbatched activations entering stage 0.
        Every shard passes an identically-shaped array; only stage 0's
        values are consumed (others may pass the same replicated array).
      pp_axis: mesh axis name the stages live on.
      n_stages: static size of that axis.
      with_aux: accumulate stage_fn's scalar aux over the ticks where
        this stage is processing a REAL microbatch (bubble/garbage ticks
        are masked out), returning ``(outputs, aux_sum)`` — caller
        typically divides by ``M`` for a per-microbatch mean.

    Returns:
      ``[M, ...]`` outputs of the LAST stage (plus the stage-local
      ``aux_sum`` with ``with_aux``).  Only the last stage's output
      values are meaningful; other stages return whatever streamed
      through them — mask downstream (e.g. keep only the loss term of
      stage ``n_stages - 1``).
    """
    n_micro = x_micro.shape[0]
    stage = lax.axis_index(pp_axis)
    shift = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def tick(carry, t):
        state, outputs, aux_acc = carry
        # stage 0 ingests microbatch t (clamped re-reads past M are never
        # written to outputs, so they carry no gradient)
        inject = lax.dynamic_index_in_dim(
            x_micro, jnp.clip(t, 0, n_micro - 1), 0, keepdims=False)
        x_in = jnp.where(stage == 0, inject, state)
        if with_aux:
            y, aux = stage_fn(stage_params, x_in)
            # stage s processes microbatch t - s at tick t; ticks outside
            # [s, s + M) stream zeros/garbage — exclude their aux
            valid = jnp.logical_and(t >= stage, t - stage < n_micro)
            aux_acc = aux_acc + jnp.where(valid, aux, 0.0)
        else:
            y = stage_fn(stage_params, x_in)
        # microbatch m exits the last stage at tick m + S - 1
        out_idx = t - (n_stages - 1)
        idx = jnp.clip(out_idx, 0, n_micro - 1)
        cur = lax.dynamic_index_in_dim(outputs, idx, 0, keepdims=False)
        write = jnp.logical_and(stage == n_stages - 1, out_idx >= 0)
        outputs = lax.dynamic_update_index_in_dim(
            outputs, jnp.where(write, y, cur), idx, 0)
        state = lax.ppermute(y, pp_axis, shift)
        return (state, outputs, aux_acc), None

    init = (jnp.zeros_like(x_micro[0]), jnp.zeros_like(x_micro),
            jnp.float32(0.0))
    (_, outputs, aux_sum), _ = lax.scan(
        tick, init, jnp.arange(n_micro + n_stages - 1))
    if with_aux:
        return outputs, aux_sum
    return outputs


def circular_layer_permutation(n_layers: int, n_stages: int,
                               n_loops: int) -> np.ndarray:
    """Layer-axis permutation that turns the natural ``[n_layers]`` stack
    into the circular-pipeline storage layout.

    Circular pipelining splits the stack into ``n_stages * n_loops``
    chunks placed round-robin: chunk ``c`` (layers ``c*Lc .. (c+1)*Lc``)
    lives on stage ``c % n_stages`` and runs on that stage's loop
    ``c // n_stages``.  JAX shards a leading axis contiguously, so the
    storage order must put each stage's ``n_loops`` chunks next to each
    other: global slot ``(s, r, l)`` holds original layer
    ``(r*n_stages + s)*Lc + l``.  Apply with ``jnp.take(leaf, perm,
    axis=0)`` (and the argsort inverse to go back to the natural order,
    e.g. for checkpoint export).
    """
    if n_layers % (n_stages * n_loops):
        raise ValueError(f"n_layers ({n_layers}) must divide by "
                         f"n_stages*n_loops ({n_stages}*{n_loops})")
    lc = n_layers // (n_stages * n_loops)
    perm = np.empty((n_layers,), np.int64)
    g = 0
    for s in range(n_stages):
        for r in range(n_loops):
            c = r * n_stages + s
            for l in range(lc):
                perm[g] = c * lc + l
                g += 1
    return perm


def gpipe_circular(stage_fn: Callable, chunk_params, x_micro: jax.Array,
                   pp_axis: str, n_stages: int, n_loops: int,
                   with_aux: bool = False):
    """Circular (interleaved) pipeline over ``pp_axis``.

    Each stage holds ``n_loops`` parameter chunks (round-robin layer
    placement — see :func:`circular_layer_permutation`) and every
    microbatch rides the ring ``n_loops`` times, visiting chunks in layer
    order.  The schedule is loop-major: stage ``s`` runs (microbatch
    ``m``, loop ``r``) at tick ``r*M + m + s``, so the total tick count
    is ``n_loops*M + S - 1`` and the bubble fraction drops from GPipe's
    ``(S-1)/(M+S-1)`` to ``(S-1)/(n_loops*M + S-1)`` — the standard
    interleaving refinement, for the price of ``n_loops``x more permute
    hops per microbatch (each hop still a single nearest-neighbor
    ppermute of one microbatch activation).

    Requires ``M >= n_stages`` (the loop-major schedule stalls
    otherwise) — activations returning to stage 0 for their next loop
    wait in a FIFO of depth ``M - n_stages``.

    Args:
      stage_fn: ``(chunk_params_r, x) -> y`` (or ``(y, aux)`` with
        ``with_aux``) — runs ONE chunk (``n_layers/(S*n_loops)``
        layers); receives the ``r``-th slice of ``chunk_params``.
      chunk_params: per-shard pytree whose leaves lead with
        ``[n_loops, ...]`` — this stage's chunks in loop order.
      x_micro / pp_axis / n_stages / with_aux: as in :func:`gpipe`.

    Returns as :func:`gpipe` (outputs of the LAST chunk on the last
    stage; garbage elsewhere — mask downstream).
    """
    n_micro = x_micro.shape[0]
    if n_micro < n_stages:
        raise ValueError(
            f"circular pipeline needs n_micro ({n_micro}) >= n_stages "
            f"({n_stages}) — the loop-major schedule stalls otherwise")
    if n_loops == 1:
        squeeze = jax.tree.map(lambda a: a[0], chunk_params)
        return gpipe(stage_fn, squeeze, x_micro, pp_axis, n_stages,
                     with_aux=with_aux)
    stage = lax.axis_index(pp_axis)
    shift = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    depth = n_micro - n_stages  # FIFO delay for loop re-entry at stage 0

    def tick(carry, t):
        state, fifo, outputs, aux_acc = carry
        # stage s processes (microbatch m, loop r) at tick t = r*M + m + s
        rel = t - stage
        m = jnp.clip(rel % n_micro, 0, n_micro - 1)
        r = jnp.clip(rel // n_micro, 0, n_loops - 1)
        active = jnp.logical_and(rel >= 0, (rel // n_micro) < n_loops)
        inject = lax.dynamic_index_in_dim(x_micro, m, 0, keepdims=False)
        if depth > 0:
            feed, fifo = fifo[0], jnp.concatenate(
                [fifo[1:], state[None]], axis=0)
        else:
            feed = state
        x0 = jnp.where(rel // n_micro == 0, inject, feed)
        x_in = jnp.where(stage == 0, x0, state)
        params_r = jax.tree.map(
            lambda a: lax.dynamic_index_in_dim(a, r, 0, keepdims=False),
            chunk_params)
        if with_aux:
            y, aux = stage_fn(params_r, x_in)
            aux_acc = aux_acc + jnp.where(active, aux, 0.0)
        else:
            y = stage_fn(params_r, x_in)
        write = jnp.logical_and(
            jnp.logical_and(stage == n_stages - 1, active),
            r == n_loops - 1)
        cur = lax.dynamic_index_in_dim(outputs, m, 0, keepdims=False)
        outputs = lax.dynamic_update_index_in_dim(
            outputs, jnp.where(write, y, cur), m, 0)
        state = lax.ppermute(y, pp_axis, shift)
        return (state, fifo, outputs, aux_acc), None

    fifo0 = jnp.zeros((max(depth, 1),) + x_micro.shape[1:], x_micro.dtype)
    init = (jnp.zeros_like(x_micro[0]), fifo0, jnp.zeros_like(x_micro),
            jnp.float32(0.0))
    (_, _, outputs, aux_sum), _ = lax.scan(
        tick, init, jnp.arange(n_loops * n_micro + n_stages - 1))
    if with_aux:
        return outputs, aux_sum
    return outputs
