"""Ulysses (all-to-all) sequence parallelism.

The second sequence-parallel flavor beside ring attention
(``parallel/ring_attention.py``): instead of rotating K/V blocks around
the ring, one ``all_to_all`` re-shards the activations from
sequence-sharded to HEAD-sharded, every device runs ordinary full (or
Pallas flash) attention over the COMPLETE sequence for its subset of
heads, and a second ``all_to_all`` re-shards back (the DeepSpeed-Ulysses
communication pattern).

Trade-off vs ring attention, both first-class here:

* Ulysses moves each activation twice per attention (2 all-to-alls of
  the [B, T_local, H, D] block) regardless of sequence length; ring
  moves K/V ``n-1`` times but overlaps every hop with block compute.
* Ulysses caps the sp degree at the KV-head count (GQA: ``n_kv_heads %
  sp == 0`` required); ring has no head constraint.
* Ulysses runs one dense attention per device (best MXU shape, trivially
  composes with the flash kernel); ring's blockwise online-softmax merge
  adds VPU work.

Under ``jax.grad`` the transpose of an ``all_to_all`` is the reverse
``all_to_all`` — the backward falls out of autodiff, like every other
collective in this framework.
"""

from __future__ import annotations

from typing import Optional

import jax
from jax import lax

from bluefog_tpu.parallel.ring_attention import full_attention

__all__ = ["ulysses_attention"]


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      axis_name: str, causal: bool = True,
                      scale: Optional[float] = None,
                      impl: str = "xla",
                      block_size: int = 512) -> jax.Array:
    """All-to-all sequence-parallel attention.

    Must run inside ``shard_map`` with ``axis_name`` bound.  q:
    ``[B, T_local, H, D]``, k/v: ``[B, T_local, H_kv, D]`` — the local
    sequence shard with ALL heads (rotary already applied at global
    positions by the caller).  Returns ``[B, T_local, H, D]``.

    ``H`` and ``H_kv`` must divide by the axis size.
    """
    n = lax.axis_size(axis_name)
    h, n_kv = q.shape[2], k.shape[2]
    if h % n or n_kv % n:
        raise ValueError(
            f"ulysses attention shards heads over the sp axis: n_heads "
            f"({h}) and n_kv_heads ({n_kv}) must divide by its size ({n})")

    def seq_to_heads(x):  # [B, T/n, H, D] -> [B, T, H/n, D]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    qg, kg, vg = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    if impl == "flash":
        from bluefog_tpu.parallel.pallas_attention import flash_attention

        t = qg.shape[1]
        out = flash_attention(qg, kg, vg, causal=causal, scale=scale,
                              block_q=min(block_size, t),
                              block_k=min(block_size, t))
    else:
        out = full_attention(qg, kg, vg, causal=causal, scale=scale)
    # [B, T, H/n, D] -> [B, T/n, H, D]
    return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)
