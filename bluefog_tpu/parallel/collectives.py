"""Shard-level collective kernels — the TPU data plane.

This module replaces the reference's entire C++ communication stack
(MPIController, bluefog/common/mpi_controller.cc, and NCCLController,
bluefog/common/nccl_controller.cc) with XLA collectives.  Each function here
operates on a **per-device shard** under an active mesh axis, i.e. it must be
called inside ``shard_map`` (or any SPMD context where ``axis_name`` is
bound).  The eager, BlueFog-compatible wrappers live in
``bluefog_tpu.context``.

Design notes
------------
* ``neighbor_allreduce`` (reference mpi_controller.cc:419-745) lowers to one
  ``lax.ppermute`` per *shift class* of the topology (see
  ``bluefog_tpu.topology.spec``) followed by a weighted combine.  For
  exponential-2 graphs that is log2(n) permutes; for the dynamic one-peer
  schedule it is exactly one — the property behind BlueFog's O(1) per-step
  communication claim (reference README.rst:51-60).
* The weighted combine is accumulated in float32 even for bf16/fp16 payloads,
  matching the reference which reduces in framework ops after the allgather
  (reference torch/mpi_ops.cc:99-164).
* There is no negotiation phase and no fusion buffer: SPMD traces make
  readiness static, and XLA schedules/fuses the collectives (reference
  operations.cc:853-1115 and tensor_queue.h:75-124 have no equivalent here —
  by design).
"""

from __future__ import annotations

# This module legitimately constructs weight tables from scratch — the
# analysis lint's weight-matrix-bypass rule treats it as an authority
# (everywhere else, tables must come from the shared helpers here).
_WEIGHT_AUTHORITY = True

from functools import partial
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bluefog_tpu.topology.spec import (DynamicTopology, Topology,
                                       self_weights_of as _self_weights_of)

CommSpec = Union[Topology, DynamicTopology]

__all__ = [
    "allreduce",
    "broadcast",
    "allgather",
    "allgatherv",
    "neighbor_allreduce",
    "neighbor_allreduce_buckets",
    "neighbor_allgather",
    "edge_structure",
    "class_recv_weights",
    "self_weight_vector",
    "neighbor_allgather_padded",
    "in_neighbor_lists",
    "pair_gossip",
    "push_sum_structure",
    "push_sum_mix",
    "hierarchical_neighbor_allreduce",
    "machine_groups",
    "validate_machine_decomposition",
    "mix_compress_exchange",
    "mix_wire_bytes",
    "mix_mirror_slots",
]


def _accum_dtype(dtype) -> jnp.dtype:
    """Combine in f32 for low-precision floats; keep f64/f32/ints as f32+."""
    dtype = jnp.dtype(dtype)
    if dtype in (jnp.dtype(jnp.bfloat16), jnp.dtype(jnp.float16)):
        return jnp.dtype(jnp.float32)
    if jnp.issubdtype(dtype, jnp.integer) or dtype == jnp.dtype(bool):
        return jnp.dtype(jnp.float32)
    return dtype


_structure_cache: dict = {}


def edge_structure(spec: DynamicTopology) -> DynamicTopology:
    """The spec with all edge weights replaced by 1.0 — the compile-time
    skeleton.  A DECLARED edge transfers even when its weight is 0.0
    (matching the reference, which sends the scaled-by-zero payload,
    mpi_controller.cc:594-600, rather than skipping the send).

    Memoized on ``(size, edges)``: a weight schedule over one edge
    structure builds a fresh spec every step, but the skeleton (and its
    cached shift decomposition) is shared across all of them."""
    key = (spec.size, spec.edges)
    structure = _structure_cache.get(key)
    if structure is None:
        structure = DynamicTopology.from_edges(
            spec.size, {e: 1.0 for e in spec.edges})
        _structure_cache[key] = structure
    return structure


def class_recv_weights(spec: CommSpec) -> jnp.ndarray:
    """[n_classes, n] weight rows: row c, entry d = the weight rank d
    applies to what it receives through shift class c (0 where no edge).
    Class order matches ``edge_structure(spec).shift_classes``.  Built in
    float64 so f64 payloads (x64 mode) combine with exact weights; JAX
    downcasts to f32 automatically when x64 is off.

    For DynamicTopology the rows come straight from the edge-weight map
    over the memoized skeleton's classes — the per-step spec itself is
    never decomposed (eager hot path)."""
    if isinstance(spec, Topology):
        rows = [cls.recv_weights for cls in spec.shift_classes]
        if not rows:
            return jnp.zeros((0, spec.size), jnp.float32)
        return jnp.asarray(np.asarray(rows, np.float64))
    structure = edge_structure(spec)
    ew = dict(zip(spec.edges, spec.edge_weight_values))
    rows = np.zeros((len(structure.shift_classes), spec.size), np.float64)
    for c, cls in enumerate(structure.shift_classes):
        for (src, dst) in cls.perm:
            rows[c, dst] = ew.get((src, dst), 0.0)
    return jnp.asarray(rows)


def self_weight_vector(spec: CommSpec) -> jnp.ndarray:
    """[n] per-rank self weights as a traced-operand vector (float64 for
    the same exactness reason as ``class_recv_weights``)."""
    return jnp.asarray(np.asarray(_self_weights_of(spec), np.float64))


def allreduce(x: jax.Array, axis_name: str, average: bool = True) -> jax.Array:
    """Global (all-ranks) sum or average.  Reference: mpi_controller.cc:169,
    nccl_controller.cc:443; average is applied framework-side like
    torch/mpi_ops.cc's allreduce callback."""
    acc = _accum_dtype(x.dtype)
    total = lax.psum(x.astype(acc), axis_name)
    if average:
        total = total / lax.psum(1, axis_name)
    return total.astype(x.dtype)


def broadcast(x: jax.Array, root_rank: int, axis_name: str) -> jax.Array:
    """Every rank receives root's value.  Reference: mpi_controller.cc:193.

    Lowering choice (measured reasoning, not an oversight): the masked
    psum's ring-allreduce wire cost is ~2|x| per link, CONSTANT in n —
    while a ppermute doubling tree costs log2(n) sequential |x| hops
    (7|x| at n=128) and an all_gather of the root slice materializes
    n|x| per device.  The adds-of-zeros are VPU-negligible next to the
    ICI transfer, so masked psum is within 2x of the |x| broadcast lower
    bound at every scale and beats both alternatives from n=8 up.
    """
    idx = lax.axis_index(axis_name)
    masked = jnp.where(idx == root_rank, x, jnp.zeros_like(x))
    # psum of the single nonzero contribution == root's value, exactly.
    return lax.psum(masked, axis_name)


def allgather(x: jax.Array, axis_name: str) -> jax.Array:
    """Concatenate all ranks' shards along axis 0.
    Reference: mpi_controller.cc:136 (allgatherv).  SPMD restriction: equal
    shapes per rank (the reference NCCL path has the same restriction,
    nccl_controller.cc:396)."""
    return lax.all_gather(x, axis_name, axis=0, tiled=True)


def _permute_bf16_wire(x: jax.Array, axis_name: str, perm) -> jax.Array:
    """ppermute ``x`` rounded to bfloat16 on the wire, received as f32.

    The bf16 payload rides as a u16 BITCAST: XLA may legally hoist a
    ``convert`` across a collective-permute (verified on XLA:CPU — the
    rewrite puts the full f32 payload back on the wire), but it cannot
    see through a bitcast, so the 2-byte wire format survives
    optimization."""
    h = lax.bitcast_convert_type(x.astype(jnp.bfloat16), jnp.uint16)
    r = lax.bitcast_convert_type(
        lax.ppermute(h, axis_name, perm), jnp.bfloat16)
    return r


def _wire_quantize_int8(x: jax.Array, key: Optional[jax.Array] = None):
    """Per-tensor absmax int8 quantization for the ppermute payload:
    4x (f32) / 2x (bf16) fewer bytes on the ICI/DCN wire.

    ``key=None`` rounds to nearest — deterministic but BIASED: in an
    iterated averaging process every round pushes each entry the same
    direction, so the per-round snaps can accumulate into a consensus
    error floor that grows with rank count.  With a PRNG ``key`` the
    fractional part rounds STOCHASTICALLY (floor(y + u), u ~ U[0,1)):
    E[q] == y exactly, so quantization noise enters the mixing recursion
    zero-mean and averages out instead of compounding — the n=128
    simulation in benchmarks/wire_quant_consensus.py measures the two
    floors side by side."""
    x32 = x.astype(jnp.float32)
    scale = jnp.max(jnp.abs(x32)) / 127.0
    safe = jnp.where(scale == 0.0, 1.0, scale)
    y = x32 / safe
    if key is None:
        q = jnp.round(y)
    else:
        u = jax.random.uniform(key, x32.shape, jnp.float32)
        q = jnp.floor(y + u)
    q = jnp.clip(q, -127, 127).astype(jnp.int8)
    return q, scale


def neighbor_allreduce(
    x: jax.Array,
    spec: CommSpec,
    axis_name: str,
    compress: Optional[str] = None,
    class_weights: Optional[jax.Array] = None,
    self_weights: Optional[jax.Array] = None,
    wire_key: Optional[jax.Array] = None,
) -> jax.Array:
    """Weighted neighbor averaging — THE BlueFog primitive.

    out[i] = self_weight[i] * x[i] + sum_{(j,i) in E} w[j,i] * x[j]

    Reference: semantics at torch/mpi_ops.py:545-560 + combine in
    torch/mpi_ops.cc:99-164; wire path mpi_controller.cc:419-745.
    One ``lax.ppermute`` per shift class; weights gathered per-rank via
    ``lax.axis_index``.

    ``compress="int8"`` quantizes the ppermuted payload (per-tensor absmax
    int8 + one f32 scale per neighbor) — the wire-level counterpart of the
    reference's gradient compressor (reference compressor/Compressor.py),
    made TPU-native by riding the collective itself.  The self term stays
    full precision; max relative error per received tensor is
    ~0.4% of its absmax.  ``compress="bf16"`` instead rounds the wire
    payload to bfloat16 (2x fewer f32 bytes, ~3 decimal digits kept).

    ``class_weights`` ([n_classes, n], ``class_recv_weights`` layout) and
    ``self_weights`` ([n]) optionally supply the combine weights as TRACED
    OPERANDS; ``spec`` then only contributes the edge structure, so one
    compiled program serves every weight schedule over that structure
    (eager retrace-hazard fix — same design as windows.py's put/update).

    ``wire_key`` (int8 only) switches the wire quantizer to UNBIASED
    stochastic rounding: pass a PRNG key (vary it per step, e.g.
    ``jax.random.fold_in(base, step)``); it is folded with the rank
    index so every rank draws independent rounding noise.  See
    ``_wire_quantize_int8`` for why round-to-nearest can build a
    consensus floor in iterated averaging.
    """
    if compress not in (None, "int8", "bf16"):
        raise ValueError(f"unknown compress mode {compress!r}")
    if wire_key is not None and compress != "int8":
        raise ValueError("wire_key= requires compress='int8'")
    acc_dtype = _accum_dtype(x.dtype)
    idx = lax.axis_index(axis_name)
    if wire_key is not None:
        # independent rounding noise per rank
        wire_key = jax.random.fold_in(wire_key, idx)
    if self_weights is None:
        self_w = jnp.asarray(_self_weights_of(spec), dtype=acc_dtype)[idx]
    else:
        self_w = self_weights.astype(acc_dtype)[idx]

    def recv_w(c, cls):
        if class_weights is None:
            return jnp.asarray(cls.recv_weights, dtype=acc_dtype)[idx]
        return class_weights[c].astype(acc_dtype)[idx]

    # In-degree-1 edge sets whose classes are pairwise disjoint (every
    # src and dst appears once across ALL classes — e.g. each round of
    # the one-peer dynamic or torus schedules) fuse into ONE
    # collective-permute with mixed shifts: one ICI launch instead of
    # one per wraparound class, and the per-rank weight collapses to a
    # single vector.  Static multi-in-degree graphs (exp2, ring with
    # both directions) keep the per-class path below.
    classes = spec.shift_classes
    if len(classes) > 1:
        all_pairs = [p for cls in classes for p in cls.perm]
        srcs = [s for s, _ in all_pairs]
        dsts = [d for _, d in all_pairs]
        if len(set(srcs)) == len(srcs) and len(set(dsts)) == len(dsts):
            merged = tuple(sorted(all_pairs))
            if class_weights is None:
                w_fused = jnp.asarray(
                    np.sum([cls.recv_weights for cls in classes], axis=0),
                    dtype=acc_dtype)[idx]
            else:
                masks = np.zeros((len(classes), spec.size))
                for c, cls in enumerate(classes):
                    for _, d in cls.perm:
                        masks[c, d] = 1.0
                w_fused = (class_weights.astype(acc_dtype)
                           * jnp.asarray(masks, acc_dtype)).sum(0)[idx]
            if compress == "int8":
                q, scale = _wire_quantize_int8(x, wire_key)
                rcv = (lax.ppermute(q, axis_name, merged)
                       .astype(jnp.float32)
                       * lax.ppermute(scale, axis_name, merged))
            elif compress == "bf16" and x.dtype != jnp.bfloat16:
                rcv = _permute_bf16_wire(x, axis_name, merged)
            else:
                rcv = lax.ppermute(x, axis_name, merged)
            acc = x.astype(acc_dtype) * self_w + rcv.astype(acc_dtype) * w_fused
            return acc.astype(x.dtype)

    received, weights = [], [self_w]
    if compress == "int8":
        q, scale = _wire_quantize_int8(x, wire_key)
        for c, cls in enumerate(spec.shift_classes):
            rq = lax.ppermute(q, axis_name, cls.perm)
            rs = lax.ppermute(scale, axis_name, cls.perm)
            received.append(rq.astype(jnp.float32) * rs)
            weights.append(recv_w(c, cls))
    elif compress == "bf16" and x.dtype != jnp.bfloat16:
        # Wire-only round-trip: halves f32 ICI bytes (~3 decimal digits
        # kept); the self term stays full precision.  No-op for bf16
        # payloads (handled by the uncompressed branch below).
        for c, cls in enumerate(spec.shift_classes):
            received.append(_permute_bf16_wire(x, axis_name, cls.perm))
            weights.append(recv_w(c, cls))
    else:
        for c, cls in enumerate(spec.shift_classes):
            received.append(lax.ppermute(x, axis_name, cls.perm))
            weights.append(recv_w(c, cls))
    # The weighted combine is a plain multiply-add chain; XLA fuses it
    # into one HBM pass.  A hand-written Pallas kernel for this was
    # benchmarked on v5e (round 2) at 1.5-2.3x SLOWER than the XLA fusion
    # (0.86 ms vs 1.97 ms for 100 MB f32, k=3) and deleted — the
    # reference needs cuda_kernels.cu only because torch does not fuse.
    acc = x.astype(acc_dtype) * self_w
    for r, w in zip(received, weights[1:]):
        acc = acc + r.astype(acc_dtype) * w
    return acc.astype(x.dtype)


def neighbor_allreduce_buckets(
    buffers: Sequence[jax.Array],
    spec: CommSpec,
    axis_name: str,
    compress: Optional[str] = None,
    wire_key: Optional[jax.Array] = None,
    hierarchical_local_size: Optional[int] = None,
    class_weights: Optional[jax.Array] = None,
    self_weights: Optional[jax.Array] = None,
) -> list:
    """One weighted neighbor combine per bucket buffer — the data plane
    of the jitted overlap engine (``build_train_step(overlap=
    "bucketed")``).

    Each bucket is an INDEPENDENT collective over the same topology: on
    an async backend every bucket lowers to its own
    ``collective-permute-start``/``-done`` pair, so XLA's latency-hiding
    scheduler can run bucket *i*'s transfer concurrently with whatever
    arithmetic bucket *i+1* (or the surrounding step) has ready — the
    TPU-native equivalent of the reference's background-thread overlap
    (reference optimizers.py hooks + operations.cc tensor fusion), with
    the schedule decided by the compiler instead of a host thread.

    ``wire_key`` (with ``compress="int8"``) is folded with the BUCKET
    index so every bucket draws independent stochastic-rounding noise;
    ``hierarchical_local_size`` routes buckets through the machine-level
    combine instead (``spec`` is then the MACHINE schedule, compression
    applies to the DCN leg only).  ``class_weights``/``self_weights``
    supply the combine weights as TRACED OPERANDS shared by every
    bucket — the resilience layer's topology-healing delivery, same
    contract as ``neighbor_allreduce`` (machine-level tables under
    ``hierarchical_local_size``).  Numerics per element are identical to the per-leaf
    ``neighbor_allreduce`` (the weighted combine distributes over
    concatenation) except for int8's per-TENSOR absmax scale, which under
    bucketing is per-BUCKET.
    """
    outs = []
    for i, buf in enumerate(buffers):
        key = (jax.random.fold_in(wire_key, i)
               if wire_key is not None else None)
        if hierarchical_local_size is not None:
            outs.append(hierarchical_neighbor_allreduce(
                buf, spec, hierarchical_local_size, axis_name,
                compress=compress, wire_key=key,
                class_weights=class_weights, self_weights=self_weights))
            continue
        outs.append(neighbor_allreduce(
            buf, spec, axis_name, compress=compress, wire_key=key,
            class_weights=class_weights, self_weights=self_weights))
    return outs


def neighbor_allgather(
    x: jax.Array,
    spec: CommSpec,
    axis_name: str,
) -> jax.Array:
    """Gather in-neighbor values into a dense per-source buffer.

    Returns shape ``[size, *x.shape]``: slot ``j`` holds rank j's value if
    (j -> me) is an edge, zeros otherwise.  The eager layer slices this into
    the reference's ragged concat-along-dim0 layout ordered by source rank
    (reference torch/mpi_ops.py:440-476; wire mpi_controller.cc:282-361).
    Dense slots keep shapes static under SPMD, which the reference cannot do
    (per-rank in-degree varies) — callers index by the topology's neighbor
    lists.
    """
    idx = lax.axis_index(axis_name)
    out = jnp.zeros((spec.size,) + x.shape, dtype=x.dtype)
    for cls in spec.shift_classes:
        received = lax.ppermute(x, axis_name, cls.perm)
        mask = jnp.asarray(
            [1.0 if w != 0.0 else 0.0 for w in cls.recv_weights],
            dtype=jnp.float32,
        )[idx]
        src = (idx - cls.shift) % spec.size
        slot = jnp.where(mask > 0, received, jnp.zeros_like(received))
        out = lax.dynamic_update_index_in_dim(out, slot, src, 0)
    return out


def allgatherv(
    x: jax.Array,
    sizes: Sequence[int],
    axis_name: str,
) -> jax.Array:
    """Variable-size allgather (reference allgatherv,
    mpi_controller.cc:136-168 — gathers per-rank counts, computes
    displacements, then ``MPI_Allgatherv``).

    SPMD requires static shapes, so rank r's payload arrives padded to
    ``max(sizes)`` rows along dim 0 (``x`` is the per-shard padded buffer);
    ``sizes`` is the trace-time list of true per-rank row counts.  The
    output is the exact ragged concatenation ``[sum(sizes), ...]`` — the
    pad rows are dropped on device by one static row-gather (the
    displacement computation, done at trace time instead of runtime).
    """
    sizes = [int(s) for s in sizes]
    pad = x.shape[0]
    if any(s > pad for s in sizes):
        raise ValueError(f"sizes {sizes} exceed the padded row count {pad}")
    gathered = lax.all_gather(x, axis_name, axis=0, tiled=True)
    rows = np.concatenate(
        [np.arange(s, dtype=np.int32) + r * pad
         for r, s in enumerate(sizes)]) if sizes else np.zeros(0, np.int32)
    return jnp.take(gathered, jnp.asarray(rows), axis=0)


def in_neighbor_lists(spec: CommSpec) -> list:
    """Sorted in-neighbor lists per rank, derived from the shift classes
    (edges with nonzero recv weight).  Host-side, trace-time."""
    lists: list = [[] for _ in range(spec.size)]
    for cls in spec.shift_classes:
        for dst in range(spec.size):
            if cls.recv_weights[dst] != 0.0:
                lists[dst].append((dst - cls.shift) % spec.size)
    for l in lists:
        l.sort()
    return lists


def neighbor_allgather_padded(
    x: jax.Array,
    spec: CommSpec,
    axis_name: str,
) -> jax.Array:
    """In-degree-sized neighbor gather: shape ``[max_in_degree, *x.shape]``
    per shard, slot ``k`` holding the value of the rank's k-th smallest
    in-neighbor (zeros beyond the rank's own in-degree).

    This is the scalable replacement for the dense ``[size, ...]`` buffer:
    per-shard memory is O(in_degree * |x|) — the reference likewise
    allocates in-degree-sized output (mpi_controller.cc:282-361).  Slot
    positions vary per rank, so each shift class writes through a per-rank
    slot table (a trace-time constant indexed by ``lax.axis_index``); for
    graphs whose in-degree is uniform (every standard topology), the result
    reshaped to ``[in_degree * d0, ...]`` IS the reference's
    concat-by-source-rank layout (torch/mpi_ops.py:440-476) with no host
    finalization at all.
    """
    n = spec.size
    lists = in_neighbor_lists(spec)
    d_max = max((len(l) for l in lists), default=0)
    if d_max == 0:
        return jnp.zeros((0,) + x.shape, x.dtype)
    idx = lax.axis_index(axis_name)
    out = jnp.zeros((d_max,) + x.shape, x.dtype)
    for cls in spec.shift_classes:
        received = lax.ppermute(x, axis_name, cls.perm)
        slots = []
        for dst in range(n):
            if cls.recv_weights[dst] != 0.0:
                slots.append(lists[dst].index((dst - cls.shift) % n))
            else:
                slots.append(-1)
        slot = jnp.asarray(slots, jnp.int32)[idx]
        has_edge = slot >= 0
        safe = jnp.maximum(slot, 0)
        current = lax.dynamic_index_in_dim(out, safe, 0, keepdims=True)
        update = jnp.where(has_edge, received[None], current)
        out = lax.dynamic_update_index_in_dim(out, update, safe, 0)
    return out


def pair_gossip(
    x: jax.Array,
    target_ranks: Sequence[int],
    axis_name: str,
    self_weight: Optional[float] = None,
    pair_weight: Optional[float] = None,
) -> jax.Array:
    """Randomized two-node averaging: out = self_weight*x + pair_weight*x_t.

    ``target_ranks[i]`` is rank i's pair; the mapping should be an involution
    (i's target's target is i), mirroring the reference's requirement that
    both sides call simultaneously (torch/mpi_ops.py:883-907,
    mpi_controller.cc:747 MPI_Sendrecv).
    """
    if self_weight is None:
        self_weight = 0.5
    if pair_weight is None:
        pair_weight = 0.5
    n = len(target_ranks)
    # Exchange: each rank i sends to target_ranks[i].
    perm = [(i, int(t)) for i, t in enumerate(target_ranks) if int(t) != i]
    acc_dtype = _accum_dtype(x.dtype)
    received = lax.ppermute(x, axis_name, perm)
    out = self_weight * x.astype(acc_dtype) + pair_weight * received.astype(acc_dtype)
    # Ranks paired with themselves keep their value.
    idx = lax.axis_index(axis_name)
    is_self = jnp.asarray([int(t) == i for i, t in enumerate(target_ranks)])[idx]
    out = jnp.where(is_self, x.astype(acc_dtype), out)
    return out.astype(x.dtype)


def push_sum_structure(spec: CommSpec):
    """(out_degrees, filtered perms): only edges with nonzero combine
    weight count as push-sum out-edges (a 0.0-weight edge in a
    DynamicTopology is declared but carries nothing).  Shared by the
    on-device mix (:func:`push_sum_mix`) and the host-side fleet
    gossip (``bluefog_tpu.observe.fleet``), so both walk the SAME
    column-stochastic structure — a healed spec (zeroed dead edges)
    excises the dead rank from either path identically."""
    deg = np.zeros(spec.size, dtype=np.int64)
    perms = []
    for cls in spec.shift_classes:
        pairs = tuple((src, dst) for src, dst in cls.perm
                      if cls.recv_weights[dst] != 0.0)
        if not pairs:
            continue
        perms.append(pairs)
        for src, _ in pairs:
            deg[src] += 1
    return deg, perms


def push_sum_mix(tree, ps_weight: jax.Array, spec: CommSpec,
                 axis_name: str):
    """One push-sum round: column-stochastic mixing of the extended payload.

    Every rank j scales its payload (each leaf of ``tree`` and the scalar
    ``ps_weight``) by ``a_j = 1 / (out_degree_j + 1)`` and pushes it along
    every out-edge; receivers sum what arrives plus their own scaled
    payload.  Columns of the implied mixing matrix sum to 1, which
    preserves ``sum_i ps_weight_i == n`` — the associated-P invariant the
    reference asserts (reference test/torch_win_ops_test.py:780-863; wire
    path mpi_controller.cc:1665-1701, optimizers.py:1026-1177).

    NOTE: only the topology's edge STRUCTURE is used; combine weights are
    replaced by the uniform column-stochastic ``1/(out_degree+1)`` scales,
    exactly like the reference's push-sum optimizer (optimizers.py:
    1032-1035) — arbitrary weights are generally not column-stochastic and
    would break the invariant.  Zero-weight edges do not count.

    Mixing is performed in the accumulation dtype (f32 for low-precision
    payloads) and RETURNED in it — push-sum state should stay
    high-precision across rounds; callers cast once after de-biasing.

    Returns ``(mixed_tree, mixed_ps)`` — still biased; de-bias with
    ``z = x / ps`` (reference optimizers.py:1151-1155).
    """
    deg, perms = push_sum_structure(spec)
    idx = lax.axis_index(axis_name)
    a = jnp.asarray(1.0 / (deg + 1.0), jnp.float32)[idx]

    def mix_leaf(x):
        acc_dtype = _accum_dtype(x.dtype)
        scaled = x.astype(acc_dtype) * a
        acc = scaled
        for perm in perms:
            # ppermute delivers zeros to ranks with no in-edge in this class
            acc = acc + lax.ppermute(scaled, axis_name, perm)
        return acc

    mixed = jax.tree.map(mix_leaf, tree)
    mixed_ps = mix_leaf(ps_weight)
    return mixed, mixed_ps


def machine_groups(size: int, local_size: int) -> list:
    """Partition ranks [0, size) into machines of ``local_size`` ranks."""
    local_size = int(local_size)
    if local_size < 1:
        raise ValueError(f"local_size must be >= 1, got {local_size}")
    if size % local_size != 0:
        raise ValueError(
            f"rank count {size} is not divisible by local_size {local_size}")
    return [
        list(range(m * local_size, (m + 1) * local_size))
        for m in range(size // local_size)
    ]


def validate_machine_decomposition(n_ranks: int, local_size: int,
                                   machine_specs: Sequence[CommSpec] = ()
                                   ) -> list:
    """Shared validation for the two-level machine decomposition: the
    rank count must tile into machines of ``local_size``, and every
    machine-level schedule spec must be sized to the MACHINE count (not
    the rank count).  Returns the intra-machine rank groups (the
    ``axis_index_groups`` of the ICI reduce).

    This is the single source of truth for both the training exchange
    (:func:`hierarchical_neighbor_allreduce`, ``build_train_step``) and
    the metrics plane (``observe.fleet.aggregate_hierarchical``)."""
    groups = machine_groups(n_ranks, local_size)
    m = len(groups)
    for s in machine_specs:
        if s.size != m:
            raise ValueError(
                f"machine schedule of size {s.size} does not match "
                f"{m} machines ({n_ranks} ranks / local_size "
                f"{int(local_size)})")
    return groups


def hierarchical_neighbor_allreduce(
    x: jax.Array,
    machine_spec: CommSpec,
    local_size: int,
    axis_name: str,
    compress: Optional[str] = None,
    class_weights: Optional[jax.Array] = None,
    self_weights: Optional[jax.Array] = None,
    wire_key: Optional[jax.Array] = None,
) -> jax.Array:
    """Machine-level neighbor averaging: ``W_dcn ⊗ exact-local-mean``.

    Reference semantics (mpi_controller.cc:656-725, nccl_controller.cc:800-
    860): (1) intra-machine allreduce-average forms a "super node", (2) the
    machine means are neighbor-averaged over the machine topology, (3) the
    result is shared intra-machine.  On TPU step (1) is ONE grouped ``psum``
    (over the intra slice of the rank axis — ICI-local), step (2) is a
    ppermute where every local rank talks to its counterpart on the neighbor
    machine (so no separate broadcast step (3) is needed: all local ranks
    already hold the machine mean).  Per-machine DCN cost per round drops
    from ``deg(rank) * full-width`` sends to one machine-mean exchange.

    ``compress`` ("int8"/"bf16") and ``wire_key`` (stochastic rounding, see
    :func:`neighbor_allreduce`) apply to the DCN leg ONLY: the machine means
    crossing machines are quantized; the intra-machine reduce always runs
    full precision — ICI bandwidth is nearly free, and keeping the exact
    local mean means quantization noise enters the mixing recursion once
    per round, not twice.

    ``class_weights`` ([n_machine_classes, n_machines]) and
    ``self_weights`` ([n_machines]) supply the INTER-MACHINE combine
    weights as traced operands — the healing/elastic delivery path, at
    machine granularity (the machine is the failure domain).

    With ``local_size == 1`` the decomposition is exact flat neighbor
    averaging: the singleton-group psum is the identity, the counterpart
    expansion reproduces the rank-level permutes, and the arithmetic
    (including the int8 per-rank fold of ``wire_key``) mirrors
    :func:`neighbor_allreduce` bitwise.
    """
    if compress not in (None, "int8", "bf16"):
        raise ValueError(f"unknown compress mode {compress!r}")
    if wire_key is not None and compress != "int8":
        raise ValueError("wire_key= requires compress='int8'")
    n_total = machine_spec.size * local_size
    groups = validate_machine_decomposition(n_total, local_size,
                                            (machine_spec,))
    acc_dtype = _accum_dtype(x.dtype)
    # ICI leg: ONE exact grouped reduce, always full precision.
    local_mean = lax.psum(x.astype(acc_dtype), axis_name,
                          axis_index_groups=groups)
    local_mean = local_mean / local_size

    idx = lax.axis_index(axis_name)
    machine_id = idx // local_size
    if wire_key is not None:
        # independent rounding noise per rank
        wire_key = jax.random.fold_in(wire_key, idx)
    if self_weights is None:
        self_w = jnp.asarray(_self_weights_of(machine_spec),
                             dtype=acc_dtype)[machine_id]
    else:
        self_w = self_weights.astype(acc_dtype)[machine_id]

    # DCN leg: the machine mean goes on the wire in the PAYLOAD dtype
    # (exact round-trip at local_size == 1; halves DCN bytes for bf16
    # params) or compressed; the self term keeps the full-precision mean.
    wire = local_mean.astype(x.dtype)

    def expand(perm):
        # Machine edge (ms, md) expands to rank pairs (ms*L+j, md*L+j).
        return [(ms * local_size + j, md * local_size + j)
                for (ms, md) in perm for j in range(local_size)]

    def recv_w(c, cls):
        if class_weights is None:
            return jnp.asarray(cls.recv_weights, dtype=acc_dtype)[machine_id]
        return class_weights[c].astype(acc_dtype)[machine_id]

    # Mirror the flat path's class fusion: in-degree-1 machine schedules
    # with pairwise-disjoint classes (one-peer dynamic rounds — the
    # schedules the hierarchical compiler emits) collapse to ONE
    # collective-permute on the DCN, so the whole round is exactly one
    # grouped all-reduce + one permute.
    classes = machine_spec.shift_classes
    if len(classes) > 1:
        all_pairs = [p for cls in classes for p in cls.perm]
        srcs = [s for s, _ in all_pairs]
        dsts = [d for _, d in all_pairs]
        if len(set(srcs)) == len(srcs) and len(set(dsts)) == len(dsts):
            merged = tuple(expand(sorted(all_pairs)))
            if class_weights is None:
                w_fused = jnp.asarray(
                    np.sum([cls.recv_weights for cls in classes], axis=0),
                    dtype=acc_dtype)[machine_id]
            else:
                masks = np.zeros((len(classes), machine_spec.size))
                for c, cls in enumerate(classes):
                    for _, d in cls.perm:
                        masks[c, d] = 1.0
                w_fused = (class_weights.astype(acc_dtype)
                           * jnp.asarray(masks, acc_dtype)).sum(0)[machine_id]
            if compress == "int8":
                q, scale = _wire_quantize_int8(wire, wire_key)
                rcv = (lax.ppermute(q, axis_name, merged)
                       .astype(jnp.float32)
                       * lax.ppermute(scale, axis_name, merged))
            elif compress == "bf16" and x.dtype != jnp.bfloat16:
                rcv = _permute_bf16_wire(wire, axis_name, merged)
            else:
                rcv = lax.ppermute(wire, axis_name, merged)
            acc = local_mean * self_w + rcv.astype(acc_dtype) * w_fused
            return acc.astype(x.dtype)

    received, weights = [], []
    if compress == "int8":
        q, scale = _wire_quantize_int8(wire, wire_key)
        for c, cls in enumerate(classes):
            pairs = expand(cls.perm)
            rq = lax.ppermute(q, axis_name, pairs)
            rs = lax.ppermute(scale, axis_name, pairs)
            received.append(rq.astype(jnp.float32) * rs)
            weights.append(recv_w(c, cls))
    elif compress == "bf16" and x.dtype != jnp.bfloat16:
        for c, cls in enumerate(classes):
            received.append(
                _permute_bf16_wire(wire, axis_name, expand(cls.perm)))
            weights.append(recv_w(c, cls))
    else:
        for c, cls in enumerate(classes):
            received.append(lax.ppermute(wire, axis_name, expand(cls.perm)))
            weights.append(recv_w(c, cls))
    acc = local_mean * self_w
    for r, w in zip(received, weights):
        acc = acc + r.astype(acc_dtype) * w
    return acc.astype(x.dtype)


# ------------------------------------------------------------------ #
# error-feedback compressed mixing: sparse deltas on the wire
# ------------------------------------------------------------------ #
def mix_wire_bytes(numel: int, k: int, values: str = "int8") -> int:
    """Host-side byte count of one compressed-mixing wire buffer — the
    single uint8 payload :func:`mix_compress_exchange` permutes per
    bucket per class: ``k`` quantized values (1 byte each under int8,
    4 under ``values="none"``), the packed keep-mask (8 entries/byte),
    and — int8 only — the 4-byte f32 absmax scale.  This is the number
    the collectives contract (``predicted_collectives`` /
    ``verify_collective_contract``) charges per permute, so the cost
    model and the lowering can never disagree about the sparse wire."""
    numel, k = int(numel), int(k)
    mask_bytes = (numel + 7) // 8
    if values in ("int8", "int8_sr"):
        return k + mask_bytes + 4
    return 4 * k + mask_bytes


def mix_mirror_slots(spec: CommSpec) -> int:
    """Number of receiver-side mirror rows one round of ``spec`` needs:
    1 when the round's shift classes fuse into a single permute (every
    src and dst unique across ALL classes — each rank then has at most
    one in-edge, the class-fusion rule of :func:`neighbor_allreduce`),
    else one per class (a rank may receive from several senders and
    must track each sender's cumulative deltas separately).  Host-side,
    trace-time — the mixing-state allocator and the exchange must agree
    on this layout."""
    classes = spec.shift_classes
    if len(classes) <= 1:
        return max(len(classes), 1)
    all_pairs = [p for cls in classes for p in cls.perm]
    srcs = [s for s, _ in all_pairs]
    dsts = [d for _, d in all_pairs]
    if len(set(srcs)) == len(srcs) and len(set(dsts)) == len(dsts):
        return 1
    return len(classes)


def _mix_encode_wire(target: jax.Array, k: int, k_live, values: str,
                     key: Optional[jax.Array]):
    """(wire uint8 [mix_wire_bytes], dense_delta f32 [n]): top-k select
    the delta, quantize the kept values, pack everything into ONE flat
    byte buffer, and decode it back — the sender's own dense delta is
    recomputed FROM THE WIRE BYTES so it is bitwise what every receiver
    will decode (the ref/mirror consistency invariant)."""
    from bluefog_tpu.compressor import topk_mask_encode

    n = target.shape[0]
    mask, vals = topk_mask_encode(target, k, k_live)
    packed = jnp.packbits(mask)
    if values in ("int8", "int8_sr"):
        q, scale = _wire_quantize_int8(vals, key)
        wire = jnp.concatenate([
            lax.bitcast_convert_type(q, jnp.uint8),
            packed,
            lax.bitcast_convert_type(scale, jnp.uint8),
        ])
    else:
        wire = jnp.concatenate([
            lax.bitcast_convert_type(vals.astype(jnp.float32),
                                     jnp.uint8).reshape(-1),
            packed,
        ])
    return wire, _mix_decode_wire(wire, n, k, values)


def _mix_decode_wire(wire: jax.Array, n: int, k: int,
                     values: str) -> jax.Array:
    """Dense f32 [n] delta from one wire buffer.  A ppermute delivers
    all-zero bytes to ranks with no in-edge in the class; the zero mask
    then decodes to an exactly-zero delta, so receivers never need an
    explicit has-in-edge gate."""
    from bluefog_tpu.compressor import topk_mask_decode

    mask_bytes = (n + 7) // 8
    if values in ("int8", "int8_sr"):
        q = lax.bitcast_convert_type(wire[:k], jnp.int8)
        packed = wire[k:k + mask_bytes]
        scale = lax.bitcast_convert_type(
            wire[k + mask_bytes:k + mask_bytes + 4], jnp.float32)
        vals = q.astype(jnp.float32) * scale
    else:
        vals = lax.bitcast_convert_type(
            wire[:4 * k].reshape(k, 4), jnp.float32)
        packed = wire[4 * k:4 * k + mask_bytes]
    mask = jnp.unpackbits(packed, count=n).astype(bool)
    return topk_mask_decode(mask, vals)


def mix_compress_exchange(
    x: jax.Array,
    spec: CommSpec,
    axis_name: str,
    *,
    ref_row: jax.Array,
    mirrors: jax.Array,
    err: jax.Array,
    ratio: jax.Array,
    k: int,
    values: str = "int8",
    error_feedback: bool = True,
    class_weights: Optional[jax.Array] = None,
    self_weights: Optional[jax.Array] = None,
    wire_key: Optional[jax.Array] = None,
    hierarchical_local_size: Optional[int] = None,
):
    """ONE round of error-feedback compressed neighbor averaging.

    The wire carries ``compress(x - ref + e)`` instead of ``x``: each
    rank keeps a reference copy ``ref`` of what it has cumulatively
    told this round's receivers (per round, because a rotating schedule
    pairs different partners per round) and an error accumulator ``e``;
    the payload is the top-k-by-magnitude sparsification of the delta
    (packed keep-mask + int8-quantized values, see
    :func:`mix_wire_bytes`), the residual accumulates into ``e``, and
    every receiver reconstructs the sender's full state as
    ``mirror + delta`` — ``mirror`` being its own cumulative record of
    that sender's deltas, bitwise equal to the sender's ``ref`` by
    construction (both integrate the identical decoded byte stream from
    the same starting point).  The combine is then the ordinary
    weighted average of full-precision reconstructions, so the mixing
    recursion stays contractive; what compression costs is absorbed by
    the error feedback instead of compounding (the ratio sweep in
    benchmarks/wire_quant_consensus.py measures the floor, EF on vs
    off).

    Args (all state flat f32, allocated by the train-step builder):

    * ``ref_row`` — ``[n]``: this ROUND's cumulative sent deltas.
    * ``mirrors`` — ``[mix_mirror_slots(spec), n]``: cumulative
      received deltas, one row per in-edge slot of this round.
    * ``err`` — ``[n]``: the error-feedback accumulator (shared across
      rounds; pass and ignore under ``error_feedback=False``).
    * ``ratio`` — traced f32 scalar: the LIVE compression ratio.  The
      static ``k`` (from the build-time ratio) fixes every shape and
      the physical wire bytes; ``ratio`` masks the active prefix
      (``k_live = clip(floor(ratio * n), 1, k)``), so the control
      plane tightens sparsity online with zero recompiles.
    * ``k`` — static per-bucket kept count
      (``compressor._resolve_k``).
    * ``values`` — ``"int8"`` (absmax per bucket, round-to-nearest),
      ``"int8_sr"`` (stochastic rounding via ``wire_key``), or
      ``"none"`` (f32 values on the wire).
    * ``hierarchical_local_size`` — compress the DCN leg only: ``x``
      is first reduced to the exact intra-machine mean (ICI psum, full
      precision) and ref/mirror/err live at MACHINE-mean granularity;
      ``spec``/weights are machine-level, counterpart-expanded like
      :func:`hierarchical_neighbor_allreduce`.

    Returns ``(out, new_ref_row, new_mirrors, new_err)`` with ``out``
    in ``x``'s shape/dtype and the state advanced — the caller owns the
    slot bookkeeping across rounds.  A rank (or machine) with no
    out-edge this round leaves ``ref``/``err`` untouched; a rank with
    no in-edge receives zero bytes and leaves its mirror untouched.
    """
    if values not in ("int8", "int8_sr", "none"):
        raise ValueError(f"unknown mix values mode {values!r}")
    if wire_key is not None and values != "int8_sr":
        raise ValueError("wire_key= requires values='int8_sr'")
    if values == "int8_sr" and wire_key is None:
        raise ValueError("values='int8_sr' needs a wire_key")
    shape, dtype = x.shape, x.dtype
    xf = x.reshape(-1)
    nb = xf.size
    idx = lax.axis_index(axis_name)
    if hierarchical_local_size is not None:
        L = int(hierarchical_local_size)
        n_total = spec.size * L
        groups = validate_machine_decomposition(n_total, L, (spec,))
        base = lax.psum(xf.astype(jnp.float32), axis_name,
                        axis_index_groups=groups) / L
        unit = idx // L

        def expand(perm):
            return [(ms * L + j, md * L + j)
                    for (ms, md) in perm for j in range(L)]
    else:
        base = xf.astype(jnp.float32)
        unit = idx
        expand = list
    if wire_key is not None:
        wire_key = jax.random.fold_in(wire_key, idx)

    classes_all = spec.shift_classes
    if not classes_all:
        if self_weights is None:
            sw = jnp.asarray(_self_weights_of(spec), jnp.float32)[unit]
        else:
            sw = self_weights.astype(jnp.float32)[unit]
        return ((base * sw).astype(dtype).reshape(shape), ref_row,
                mirrors, err)

    # sender side: encode the delta once per round (the same wire goes
    # to every out-edge), fold the residual into e, advance ref — but
    # only for ranks/machines that actually have an out-edge this round
    target = base - ref_row + err
    k_live = jnp.clip(jnp.floor(ratio * nb).astype(jnp.int32), 1, k)
    wire, d_own = _mix_encode_wire(target, k, k_live, values, wire_key)
    classes = spec.shift_classes
    has_out_tbl = np.zeros(spec.size, bool)
    for cls in classes:
        for (s, _) in cls.perm:
            has_out_tbl[s] = True
    has_out = jnp.asarray(has_out_tbl)[unit]
    new_ref = jnp.where(has_out, ref_row + d_own, ref_row)
    if error_feedback:
        new_err = jnp.where(has_out, target - d_own, err)
    else:
        new_err = err

    if self_weights is None:
        self_w = jnp.asarray(_self_weights_of(spec),
                             dtype=jnp.float32)[unit]
    else:
        self_w = self_weights.astype(jnp.float32)[unit]

    # receiver side: mirror the class-fusion rule of the uncompressed
    # exchange — in-degree-1 disjoint rounds move ONE permute of the
    # one wire buffer and need ONE mirror row; multi-class rounds
    # permute the same wire per class and integrate per-slot
    fused = len(classes) > 1 and mix_mirror_slots(spec) == 1
    acc = base * self_w
    if fused or len(classes) == 1:
        if fused:
            all_pairs = sorted(p for cls in classes for p in cls.perm)
            perm = tuple(expand(all_pairs))
            if class_weights is None:
                w = jnp.asarray(
                    np.sum([cls.recv_weights for cls in classes],
                           axis=0), jnp.float32)[unit]
            else:
                masks = np.zeros((len(classes), spec.size))
                for c, cls in enumerate(classes):
                    for _, d in cls.perm:
                        masks[c, d] = 1.0
                w = (class_weights.astype(jnp.float32)
                     * jnp.asarray(masks, jnp.float32)).sum(0)[unit]
        else:
            perm = tuple(expand(classes[0].perm))
            if class_weights is None:
                w = jnp.asarray(classes[0].recv_weights,
                                jnp.float32)[unit]
            else:
                w = class_weights[0].astype(jnp.float32)[unit]
        rd = _mix_decode_wire(lax.ppermute(wire, axis_name, perm),
                              nb, k, values)
        new_mirrors = mirrors.at[0].add(rd)
        acc = acc + new_mirrors[0] * w
    else:
        new_mirrors = mirrors
        for c, cls in enumerate(classes):
            rd = _mix_decode_wire(
                lax.ppermute(wire, axis_name, tuple(expand(cls.perm))),
                nb, k, values)
            new_mirrors = new_mirrors.at[c].add(rd)
            if class_weights is None:
                w = jnp.asarray(cls.recv_weights, jnp.float32)[unit]
            else:
                w = class_weights[c].astype(jnp.float32)[unit]
            acc = acc + new_mirrors[c] * w
    return (acc.astype(dtype).reshape(shape), new_ref, new_mirrors,
            new_err)
