"""Fused weighted neighbor-combine Pallas kernel.

SURVEY.md §7.9(a): the reference's only CUDA kernel scales a buffer by a
destination weight before sending (reference
bluefog/common/cuda/cuda_kernels.cu ``ScaleBufferCudaImpl``).  The TPU
equivalent of that memory-bound step is the post-ppermute combine

    out = w_0 * x + w_1 * r_1 + ... + w_k * r_k

which this kernel performs in a single VMEM pass over all k+1 operands:
one read of each input tile, one write of the output tile, accumulation in
f32 regardless of payload dtype.

Measured reality (see ``bench_combine`` and docs/performance.md): XLA
already fuses the equivalent ``jnp`` multiply-add chain into one HBM pass,
so this kernel is a parity alternative, not a win — it exists to keep a
hand-tuned escape hatch for combine variants XLA cannot fuse (and as the
documented counterpart of the reference's CUDA kernel).  The collective
layer uses the XLA path by default; set ``BLUEFOG_FUSED_COMBINE=pallas``
to route :func:`bluefog_tpu.parallel.collectives.neighbor_allreduce`'s
static-weight combine through this kernel.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["fused_weighted_combine", "bench_combine"]


def _kernel(w_ref, *refs):
    *in_refs, o_ref = refs
    acc = in_refs[0][...].astype(jnp.float32) * w_ref[0]
    for i, r in enumerate(in_refs[1:], start=1):
        acc = acc + r[...].astype(jnp.float32) * w_ref[i]
    o_ref[...] = acc.astype(o_ref.dtype)


def fused_weighted_combine(
    x: jax.Array,
    received: Sequence[jax.Array],
    weights: jax.Array,
    block_rows: int = 1024,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """``weights[0] * x + sum_i weights[1+i] * received[i]`` in one pass.

    ``weights`` is a traced f32 vector of length ``1 + len(received)`` (so
    one compiled kernel serves every rank's weight values).  Inputs of any
    shape/dtype; accumulation in f32 (the reference reduces in fp32 torch
    ops, torch/mpi_ops.cc:119-155).  Differentiable: the op is linear, so
    the VJP is exact (pallas_call itself has no autodiff rule).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _combine_vjp(x, tuple(received), jnp.asarray(weights, jnp.float32),
                        block_rows, interpret)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _combine_vjp(x, received, weights, block_rows, interpret):
    return _combine_impl(x, received, weights, block_rows, interpret)


def _combine_fwd(x, received, weights, block_rows, interpret):
    out = _combine_impl(x, received, weights, block_rows, interpret)
    return out, (x, received, weights)


def _combine_bwd(block_rows, interpret, res, g):
    x, received, weights = res
    g32 = g.astype(jnp.float32)
    dx = (g32 * weights[0]).astype(x.dtype)
    drs = tuple((g32 * weights[1 + i]).astype(r.dtype)
                for i, r in enumerate(received))
    dw = jnp.stack(
        [jnp.vdot(g32, a.astype(jnp.float32)) for a in (x, *received)])
    return dx, drs, dw


_combine_vjp.defvjp(_combine_fwd, _combine_bwd)


def _combine_impl(x, received, weights, block_rows, interpret):
    ins = [x, *received]
    orig_shape, orig_dtype = x.shape, x.dtype
    n = x.size
    # collapse to 2D [rows, 128]-friendly layout; pad the tail block inside
    # pallas (elementwise: lane garbage never crosses lanes)
    lane = 128
    rows = -(-n // lane)
    if rows * lane == n:  # exact reshape, no copy
        flat = [jnp.ravel(a).reshape(rows, lane) for a in ins]
    else:  # ragged tail: pad (one extra copy; combine stays correct)
        flat = [jnp.pad(jnp.ravel(a), (0, rows * lane - n)).reshape(rows, lane)
                for a in ins]
    block_rows = min(block_rows, rows)
    grid = (-(-rows // block_rows),)
    spec = pl.BlockSpec((block_rows, lane), lambda i: (i, 0))
    out = pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)] + [spec] * len(ins),
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((rows, lane), orig_dtype),
        interpret=interpret,
    )(weights, *flat)
    return out.reshape(-1)[:n].reshape(orig_shape)


def bench_combine(size: int = 25_000_000, k: int = 3, dtype=jnp.float32,
                  iters: int = 20):
    """Micro-benchmark: pallas fused combine vs the XLA-fused jnp chain.
    Returns (pallas_ms, xla_ms)."""
    import time

    import numpy as np

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(size), dtype)
    rs = [jnp.asarray(rng.randn(size), dtype) for _ in range(k)]
    w = jnp.asarray(rng.rand(k + 1), jnp.float32)

    @jax.jit
    def pallas_fn(x, rs, w):
        return fused_weighted_combine(x, rs, w)

    @jax.jit
    def xla_fn(x, rs, w):
        acc = x.astype(jnp.float32) * w[0]
        for i, r in enumerate(rs):
            acc = acc + r.astype(jnp.float32) * w[1 + i]
        return acc.astype(x.dtype)

    from bluefog_tpu.benchutil import device_fetch, fetch_overhead

    def timeit(fn):
        device_fetch(fn(x, rs, w)[0])  # compile + warm
        rtt = fetch_overhead()
        t0 = time.perf_counter()
        out = None
        for _ in range(iters):
            out = fn(x, rs, w)
        device_fetch(out[0])
        return max(time.perf_counter() - t0 - rtt, 1e-9) / iters * 1e3

    return timeit(pallas_fn), timeit(xla_fn)
