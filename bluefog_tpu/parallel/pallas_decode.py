"""Pallas TPU fused decode-attention step (GQA, int8-cache aware).

Round-5 closure of the verdict's decode-floor item: the round-4 per-layer
bisection attributed ~50 us/layer at 200M/B=32 to "batched-tiny-dot MXU
latency + small-op overheads" — a diagnosis, not a refutation.  This
kernel is the experiment: ONE ``pallas_call`` per layer replaces the
XLA chain (quantize -> two einsums -> softmax -> scale folds) that the
cached-attention step otherwise lowers to, with

* GQA batched dots: each grid row owns one (batch, kv-head) pair; its
  ``rep`` query heads attend as a single [rep, S] score block, so the
  cache streams at its native kv-head count (never widened);
* in-kernel int8 cache dequant: the cache blocks convert to f32 INSIDE
  the kernel, and both per-vector scales commute to the cheap side —
  the key scale multiplies the [rep, block_s] score columns (not the
  [block_s, D] key block), the value scale folds into the
  probabilities;
* probabilities kept in float (never re-quantized): the w8a8 path's
  per-step probability re-quantization was VPU work linear in cache
  length and cost it the long-context crown
  (benchmarks/decode_200m_v5e1_r04.json long_context note); here the
  value contraction runs f32 x f32 against the converted block, so the
  long-context behavior matches the weight-only mode by construction;
* online softmax over S blocks (the flash recurrence, pallas_attention
  ``_kernel``), so the score matrix never exceeds [rep, block_s] and
  the same kernel serves 128-long and 128k-long caches.

The decode step remains HBM-bound in theory; whether the fused kernel
beats XLA's lowering at small models / large batch is a MEASUREMENT
(examples/decode_benchmark.py --decode-attn pallas) — the kernel ships
either way, with its numbers, like pallas_conv did in round 3.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["decode_attention", "decode_attention_int8"]

_NEG_INF = -1e30


def _fit_block(t: int, want: int) -> int:
    want = min(want, t)
    for b in range(want, 0, -1):
        if t % b == 0:
            return b
    return 1


def _decode_kernel(idx_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref,
                   m_ref, l_ref, acc_ref, *, scale: float, quantized: bool,
                   n_kv: int):
    """Grid = (B, S blocks).  One batch element's [KV * rep, D] query
    tile is resident; its KV heads process as a STATIC in-kernel loop
    (one program per batch element instead of per (batch, kv) pair —
    per-program overhead amortizes over the kv heads, measured ~2x
    end-to-end at B=32/KV=4 vs the (B*KV,) grid).  K/V stream as
    [KV, block_s, D] tiles (int8 when quantized — converted in-kernel,
    scales applied on the score/probability side where they are
    O(rep * block_s), not O(block_s * D))."""
    sj = pl.program_id(1)
    n_s = pl.num_programs(1)
    q_all = q_ref[0].astype(jnp.float32)      # [KV * rep, D]
    heads, d = q_all.shape
    rep = heads // n_kv
    block_s = k_ref.shape[2]

    @pl.when(sj == 0)
    def _():
        m_ref[:] = jnp.full(m_ref.shape, _NEG_INF, jnp.float32)
        l_ref[:] = jnp.zeros(l_ref.shape, jnp.float32)
        acc_ref[:] = jnp.zeros(acc_ref.shape, jnp.float32)

    # Per-kv-head dots in a STATIC loop.  (A block-diagonal packing
    # that fuses the kv heads into two big dots — [heads, KV*D] @
    # [KV*D, bs] and [heads, KV*bs] @ [KV*bs, D] — was built and
    # measured on the chip: EQUAL at B=32/S=384, 2.3x SLOWER at S=2304,
    # because its in-kernel K transposes and [heads, KV*bs] operand
    # builds scale with S while the tiny-dot latency they save does
    # not.  The loop keeps every operand in its native layout:
    # tpu.matmul absorbs the [rep, D] x [block_s, D]^T contraction
    # without an explicit transpose.)
    for kv in range(n_kv):
        q = q_all[kv * rep:(kv + 1) * rep]    # [rep, D]
        k_blk = k_ref[0, kv].astype(jnp.float32)   # [block_s, D]
        v_blk = v_ref[0, kv].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [rep, block_s]
        if quantized:
            # key scale is constant along the contracted head_dim:
            # apply to the score columns ([0, kv] basic indexing keeps
            # the loads 2D — fancier indexing lowers to >2D gathers
            # Mosaic refuses; scales carry a trailing singleton so
            # their blocks stay TPU-tileable)
            s = s * ks_ref[0, kv][:, 0][None, :]
        # the single decode query sits at global position idx: keys at
        # j <= idx are valid (j == idx was just written), the cache
        # tail beyond is unwritten zeros and must be masked out
        pos = sj * block_s + jax.lax.broadcasted_iota(
            jnp.int32, (rep, block_s), 1)
        s = jnp.where(pos <= idx_ref[0], s, _NEG_INF)

        sl = slice(kv * rep, (kv + 1) * rep)
        m, l, acc = m_ref[sl], l_ref[sl], acc_ref[sl]
        blk_m = jnp.max(s, axis=-1)
        new_m = jnp.maximum(m, blk_m)
        p = jnp.exp(s - new_m[:, None])
        p = jnp.where(s <= _NEG_INF / 2, 0.0, p)
        corr = jnp.exp(m - new_m)
        m_ref[sl] = new_m
        l_ref[sl] = l * corr + jnp.sum(p, axis=-1)
        if quantized:
            # value scale varies along the contracted position axis:
            # fold into the probabilities (kept float — NEVER
            # re-quantized, the round-4 w8a8 long-context regression);
            # the softmax denominator above uses the UNSCALED p, so
            # this only rescales the values
            p = p * vs_ref[0, kv][:, 0][None, :]
        acc_ref[sl] = acc * corr[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(sj == n_s - 1)
    def _():
        safe_l = jnp.maximum(l_ref[:], 1e-30)
        o_ref[0] = (acc_ref[:] / safe_l[:, None]).astype(o_ref.dtype)


def _auto_interpret(interpret: Optional[bool]) -> bool:
    if interpret is not None:
        return interpret
    return jax.default_backend() != "tpu"


def _decode_impl(q, k_all, v_all, ks_all, vs_all, idx, *, block_s,
                 interpret):
    """q: [B, 1, n_q, D]; k_all/v_all: KV-HEAD-MAJOR [B, KV, S, D]
    (int8 when quantized); ks_all/vs_all: [B, KV, S] f32 scales or None.
    Returns [B, 1, n_q, D] in q's dtype."""
    b, t, n_q, d = q.shape
    assert t == 1, "the fused decode kernel serves single-token steps"
    n_kv, s_len = k_all.shape[1], k_all.shape[2]
    rep = n_q // n_kv
    quantized = ks_all is not None
    block_s = _fit_block(s_len, block_s)
    if block_s < 8 and s_len >= 8:
        # no viable tiling (e.g. a prime cache length > the wanted
        # block): a 1-position block would run one grid step per cache
        # position — refuse loudly instead of being silently 100x slow
        raise ValueError(
            f"cache length {s_len} has no block divisor in [8, "
            f"{min(512, s_len)}]; pad max_len to a multiple of 8 or "
            "use decode_attn='xla'")

    q3 = q.reshape(b, n_q, d)  # kv-major head order matches the cache
    idx1 = jnp.reshape(jnp.asarray(idx, jnp.int32), (1,))

    kv_spec = pl.BlockSpec((1, n_kv, block_s, d),
                           lambda bk, sj: (bk, 0, sj, 0))
    # trailing singleton keeps the scale block TPU-tileable (last dim
    # equals the array dim; second-to-last is the 8-aligned block_s)
    scale_spec = pl.BlockSpec((1, n_kv, block_s, 1),
                              lambda bk, sj: (bk, 0, sj, 0))
    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),
        pl.BlockSpec((1, n_q, d), lambda bk, sj: (bk, 0, 0)),
        kv_spec, kv_spec,
    ]
    args = [idx1, q3, k_all, v_all]
    if quantized:
        in_specs += [scale_spec, scale_spec]
        args += [ks_all[..., None], vs_all[..., None]]
    else:
        # scales unused; pass the idx scalar twice as cheap placeholders
        in_specs += [pl.BlockSpec(memory_space=pltpu.SMEM),
                     pl.BlockSpec(memory_space=pltpu.SMEM)]
        args += [idx1, idx1]

    out = pl.pallas_call(
        functools.partial(_decode_kernel, scale=1.0 / d ** 0.5,
                          quantized=quantized, n_kv=n_kv),
        grid=(b, s_len // block_s),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, n_q, d), lambda bk, sj: (bk, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, n_q, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((n_q,), jnp.float32),
            pltpu.VMEM((n_q,), jnp.float32),
            pltpu.VMEM((n_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(*args)
    return out.reshape(b, 1, n_q, d)


def decode_attention(q, k_all, v_all, idx, *, block_s: int = 512,
                     interpret: Optional[bool] = None):
    """Fused GQA decode-attention step over a full-precision cache.

    q: [B, 1, n_q, D]; k_all/v_all: [B, KV, S, D] (cache layout/dtype);
    idx: scalar current position.  Drop-in for the decode-step case of
    ``models.llama._cached_attention`` (reference has no counterpart —
    decode itself is a new capability, docs/parity.md)."""
    return _decode_impl(q, k_all, v_all, None, None, idx, block_s=block_s,
                        interpret=_auto_interpret(interpret))


def decode_attention_int8(q, kq_all, ks_all, vq_all, vs_all, idx, *,
                          block_s: int = 512,
                          interpret: Optional[bool] = None):
    """Fused GQA decode-attention step over the int8 K/V cache with
    in-kernel dequant and float probabilities.

    kq_all/vq_all: int8 [B, KV, S, D]; ks_all/vs_all: f32 [B, KV, S]
    per-vector scales (the ``kv_quant='int8'`` cache layout,
    models/llama.py).  Replaces the decode-step case of both
    ``_cached_attention_int8`` (whose probability re-quantization cost
    it the long-context crown) and the dequant-then-attend path."""
    return _decode_impl(q, kq_all, vq_all, ks_all, vs_all, idx,
                        block_s=block_s,
                        interpret=_auto_interpret(interpret))
