"""TPU parallelism layer: collective kernels, meshes, sequence parallelism."""

from bluefog_tpu.parallel import collectives  # noqa: F401
